"""TPC-DS-like tables and query plans (TpcdsLikeSpark.scala analogue:
integration_tests/src/main/scala/.../tpcds/TpcdsLikeSpark.scala defines the
full table schemas + hand-written DataFrame queries; this module generates
the subset of tables the -like queries read and defines each query as a
function data_dir -> plan).

Queries: the classic reporting shape (q3/q42/q52/q55: fact x date_dim x
item, filtered group-by revenue) plus a q72-like (catalog_sales x
inventory x warehouse x item x date_dim with an inter-fact inequality — the
multi-way join headline of BASELINE config #3)."""
from __future__ import annotations

import functools
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions import aggregates as A
from spark_rapids_tpu.expressions import arithmetic as ar
from spark_rapids_tpu.expressions import predicates as P
from spark_rapids_tpu.expressions.base import Alias, BoundReference, Literal
from spark_rapids_tpu.io import ParquetSource
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.plan import nodes as pn

CATEGORIES = np.array(["Books", "Electronics", "Home", "Jewelry", "Men",
                       "Music", "Shoes", "Sports", "Children", "Women"],
                      dtype=object)


# ---------------------------------------------------------------------------
# datagen


def gen_date_dim(sf: float, seed: int = 31) -> pa.Table:
    # one row per day 1998-2002, d_date_sk dense from 2450815 (dsdgen's
    # julian base is arbitrary; dense sks keep joins realistic)
    days = np.arange(np.datetime64("1998-01-01"),
                     np.datetime64("2003-01-01"))
    n = len(days)
    years = days.astype("datetime64[Y]").astype(int) + 1970
    months = days.astype("datetime64[M]").astype(int) % 12 + 1
    week_seq = (days - np.datetime64("1998-01-01")).astype(int) // 7
    # TPC-DS d_dow: 0=Sunday .. 6=Saturday; numpy weekday: 0=Monday
    dow = (days.astype("datetime64[D]").view("int64") + 4) % 7
    day_names = np.array(["Sunday", "Monday", "Tuesday", "Wednesday",
                          "Thursday", "Friday", "Saturday"], dtype=object)
    dom = (days - days.astype("datetime64[M]")).astype(int) + 1
    month_seq = (years - 1998) * 12 + (months - 1)
    return pa.table({
        "d_date_sk": np.arange(2450815, 2450815 + n, dtype=np.int64),
        "d_date": days,
        "d_year": years.astype(np.int32),
        "d_moy": months.astype(np.int32),
        "d_dom": dom.astype(np.int32),
        "d_dow": dow.astype(np.int32),
        "d_day_name": day_names[dow],
        "d_week_seq": week_seq.astype(np.int32),
        "d_month_seq": month_seq.astype(np.int32),
        "d_qoy": ((months - 1) // 3 + 1).astype(np.int32),
        "d_quarter_name": np.array(
            [f"{y}Q{q}" for y, q in
             zip(years, (months - 1) // 3 + 1)], dtype=object),
    })


def gen_item(sf: float, seed: int = 32) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(18_000 * sf), 50)
    brand_id = rng.integers(1, 1000, n).astype(np.int32)
    cat_id = rng.integers(0, 10, n)
    return pa.table({
        "i_item_sk": np.arange(1, n + 1, dtype=np.int64),
        "i_brand_id": brand_id,
        "i_brand": np.array([f"brand#{b}" for b in brand_id],
                            dtype=object),
        "i_category_id": cat_id.astype(np.int32),
        "i_category": CATEGORIES[cat_id],
        "i_class_id": rng.integers(1, 9, n).astype(np.int32),
        "i_manufact_id": rng.integers(1, 1000, n).astype(np.int32),
        "i_manager_id": rng.integers(1, 100, n).astype(np.int32),
        "i_item_id": np.array([f"AAAAAAAA{i:08d}" for i in range(1, n + 1)],
                              dtype=object),
        "i_current_price": np.round(0.5 + rng.random(n) * 2.0, 2),
        "i_wholesale_cost": np.round(0.2 + rng.random(n) * 1.5, 2),
        "i_manufact": np.array(
            [f"manufact{m % 200}" for m in rng.integers(1, 1000, n)],
            dtype=object),
        "i_class": np.array(
            [f"class{c}" for c in rng.integers(1, 9, n)], dtype=object),
        "i_item_desc": np.array([f"item description {i % 997}"
                                 for i in range(n)], dtype=object),
        "i_product_name": np.array([f"product{i}" for i in range(1, n + 1)],
                                   dtype=object),
        "i_color": np.array(
            ["red", "blue", "green", "yellow", "white", "black",
             "orange", "purple", "beige", "slate"],
            dtype=object)[rng.integers(0, 10, n)],
        "i_size": np.array(
            ["small", "medium", "large", "extra large", "petite",
             "economy"], dtype=object)[rng.integers(0, 6, n)],
        "i_units": np.array(
            ["Each", "Dozen", "Case", "Pallet", "Gross", "Ounce"],
            dtype=object)[rng.integers(0, 6, n)],
    })


def _date_sks(rng, n):
    return rng.integers(2450815, 2450815 + 5 * 365, n).astype(np.int64)


@functools.lru_cache(maxsize=2)  # returns generators re-sample the same fact table
def gen_store_sales(sf: float, seed: int = 33) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(2_880_000 * sf), 200)
    n_item = max(int(18_000 * sf), 50)
    return pa.table({
        "ss_sold_date_sk": _date_sks(rng, n),
        "ss_sold_time_sk": rng.integers(0, 86_400, n).astype(np.int64),
        "ss_item_sk": rng.integers(1, n_item + 1, n).astype(np.int64),
        "ss_customer_sk": rng.integers(1, max(int(100_000 * sf), 20), n
                                       ).astype(np.int64),
        "ss_cdemo_sk": rng.integers(1, max(int(1_000 * sf), 20) + 1, n
                                    ).astype(np.int64),
        # ~2% nulls: dsdgen fact FKs are nullable, and q44 aggregates
        # exactly the ss_hdemo_sk IS NULL slice
        "ss_hdemo_sk": pa.array(rng.integers(1, 7201, n).astype(np.int64),
                                mask=rng.random(n) < 0.02),
        "ss_promo_sk": rng.integers(1, max(int(300 * sf), 10) + 1, n
                                    ).astype(np.int64),
        # ~2% nulls: q76 aggregates exactly the IS NULL slice
        "ss_store_sk": pa.array(
            rng.integers(1, max(int(12 * sf), 2) + 1, n).astype(np.int64),
            mask=rng.random(n) < 0.02),
        "ss_ticket_number": rng.integers(1, max(n // 3, 2), n
                                         ).astype(np.int64),
        "ss_addr_sk": rng.integers(1, max(int(50_000 * sf), 15) + 1, n
                                   ).astype(np.int64),
        "ss_quantity": rng.integers(1, 101, n).astype(np.int32),
        "ss_sales_price": np.round(rng.random(n) * 200, 2),
        "ss_net_paid": np.round(rng.random(n) * 250, 2),
        "ss_ext_tax": np.round(rng.random(n) * 20, 2),
        "ss_wholesale_cost": np.round(rng.random(n) * 100, 2),
        "ss_list_price": np.round(rng.random(n) * 250, 2),
        "ss_coupon_amt": np.round(rng.random(n) * 50, 2),
        "ss_ext_list_price": np.round(rng.random(n) * 25_000, 2),
        "ss_ext_wholesale_cost": np.round(rng.random(n) * 10_000, 2),
        "ss_ext_discount_amt": np.round(rng.random(n) * 4_000, 2),
        "ss_ext_sales_price": np.round(rng.random(n) * 20_000, 2),
        "ss_net_profit": np.round(rng.random(n) * 4_000 - 2_000, 2),
    })


@functools.lru_cache(maxsize=2)  # returns sample it
def gen_catalog_sales(sf: float, seed: int = 34) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(1_440_000 * sf), 150)
    n_item = max(int(18_000 * sf), 50)
    n_cust = max(int(100_000 * sf), 20)
    n_addr = max(int(50_000 * sf), 15)
    n_wh = max(int(5 * sf), 2)
    return pa.table({
        "cs_sold_date_sk": _date_sks(rng, n),
        "cs_ship_date_sk": _date_sks(rng, n) + rng.integers(1, 30, n),
        "cs_item_sk": rng.integers(1, n_item + 1, n).astype(np.int64),
        "cs_bill_customer_sk": rng.integers(1, n_cust + 1, n
                                            ).astype(np.int64),
        "cs_bill_addr_sk": rng.integers(1, n_addr + 1, n
                                        ).astype(np.int64),
        "cs_order_number": rng.integers(1, max(n // 3, 2), n
                                        ).astype(np.int64),
        "cs_warehouse_sk": rng.integers(1, n_wh + 1, n).astype(np.int64),
        "cs_sold_time_sk": rng.integers(0, 86_400, n).astype(np.int64),
        "cs_quantity": rng.integers(1, 101, n).astype(np.int32),
        "cs_sales_price": np.round(rng.random(n) * 200, 2),
        "cs_ext_discount_amt": np.round(rng.random(n) * 4_000, 2),
        "cs_net_profit": np.round(rng.random(n) * 4_000 - 2_000, 2),
        "cs_ext_sales_price": np.round(rng.random(n) * 20_000, 2),
        "cs_bill_cdemo_sk": rng.integers(
            1, max(int(1_000 * sf), 20) + 1, n).astype(np.int64),
        "cs_bill_hdemo_sk": rng.integers(1, 7201, n).astype(np.int64),
        "cs_promo_sk": rng.integers(
            1, max(int(300 * sf), 10) + 1, n).astype(np.int64),
        "cs_ship_customer_sk": rng.integers(1, n_cust + 1, n
                                            ).astype(np.int64),
        # ~2% nulls: q76 aggregates exactly the IS NULL slice
        "cs_ship_addr_sk": pa.array(
            rng.integers(1, n_addr + 1, n).astype(np.int64),
            mask=rng.random(n) < 0.02),
        "cs_call_center_sk": rng.integers(1, 7, n).astype(np.int64),
        "cs_ship_mode_sk": rng.integers(1, 21, n).astype(np.int64),
        "cs_catalog_page_sk": rng.integers(
            1, max(int(100 * sf), 10) + 1, n).astype(np.int64),
        "cs_net_paid": np.round(rng.random(n) * 300, 2),
        "cs_ext_ship_cost": np.round(rng.random(n) * 100, 2),
        "cs_ext_wholesale_cost": np.round(rng.random(n) * 100, 2),
        "cs_ext_list_price": np.round(rng.random(n) * 250, 2),
        "cs_list_price": np.round(0.5 + rng.random(n) * 200, 2),
        "cs_wholesale_cost": np.round(0.2 + rng.random(n) * 80, 2),
        "cs_coupon_amt": np.round(rng.random(n) * 50, 2),
    })


def gen_inventory(sf: float, seed: int = 35) -> pa.Table:
    rng = np.random.default_rng(seed)
    n_item = max(int(18_000 * sf), 50)
    n_wh = max(int(5 * sf), 2)
    # weekly snapshots: every item x warehouse x ~26 weeks
    weeks = 26
    n = n_item * n_wh * weeks
    item = np.tile(np.arange(1, n_item + 1, dtype=np.int64), n_wh * weeks)
    wh = np.repeat(np.arange(1, n_wh + 1, dtype=np.int64), n_item * weeks)
    week_start = rng.integers(2450815, 2450815 + 5 * 365 - 7,
                              weeks)
    date_sk = np.tile(np.repeat(week_start, n_item), n_wh)
    return pa.table({
        "inv_date_sk": date_sk.astype(np.int64),
        "inv_item_sk": item,
        "inv_warehouse_sk": wh,
        "inv_quantity_on_hand": rng.integers(0, 120, n).astype(np.int32),
    })


def gen_warehouse(sf: float, seed: int = 36) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(5 * sf), 2)
    states = np.array(["CA", "TX", "NY", "WA", "GA"], dtype=object)
    return pa.table({
        "w_warehouse_sk": np.arange(1, n + 1, dtype=np.int64),
        "w_warehouse_name": np.array([f"Warehouse {i}"
                                      for i in range(1, n + 1)],
                                     dtype=object),
        "w_state": states[rng.integers(0, 5, n)],
        "w_warehouse_sq_ft": rng.integers(50_000, 1_000_000, n
                                          ).astype(np.int32),
        "w_city": np.array(["Midway", "Fairview", "Oakdale"],
                           dtype=object)[rng.integers(0, 3, n)],
        "w_county": np.array(["Williamson County", "Bronx County"],
                             dtype=object)[rng.integers(0, 2, n)],
        "w_country": np.array(["United States"] * n, dtype=object),
    })


def gen_web_site(sf: float, seed: int = 52) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = 30
    return pa.table({
        "web_site_sk": np.arange(1, n + 1, dtype=np.int64),
        "web_site_id": np.array([f"AAAAAAAA{i:04d}"
                                 for i in range(1, n + 1)], dtype=object),
        "web_name": np.array([f"site_{i % 10}" for i in range(n)],
                             dtype=object),
        "web_company_name": np.array(["pri", "able", "ought", "eing"],
                                     dtype=object)[rng.integers(0, 4, n)],
    })


def gen_ship_mode(sf: float, seed: int = 53) -> pa.Table:
    n = 20
    types = np.array(["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR",
                      "TWO DAY"], dtype=object)
    carriers = np.array(["UPS", "FEDEX", "AIRBORNE", "USPS", "DHL",
                         "TBS", "ZHOU", "LATVIAN", "MSC", "ORIENTAL",
                         "BARIAN", "BOXBUNDLES", "ALLIANCE", "HARMSTORF",
                         "PRIVATECARRIER", "DIAMOND", "RUPEKSA",
                         "GERMA", "GREAT EASTERN", "VALUE"], dtype=object)
    return pa.table({
        "sm_ship_mode_sk": np.arange(1, n + 1, dtype=np.int64),
        "sm_type": types[np.arange(n) % 5],
        "sm_carrier": carriers[:n],
        "sm_code": np.array(["AIR", "SURFACE", "SEA", "LIBRARY"],
                            dtype=object)[np.arange(n) % 4],
    })


def gen_call_center(sf: float, seed: int = 54) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = 6
    return pa.table({
        "cc_call_center_sk": np.arange(1, n + 1, dtype=np.int64),
        "cc_call_center_id": np.array(
            [f"AAAAAAAA{i:04d}" for i in range(1, n + 1)], dtype=object),
        "cc_name": np.array([f"call center {i}"
                             for i in range(1, n + 1)], dtype=object),
        "cc_county": np.array(["Williamson County", "Franklin Parish",
                               "Bronx County"], dtype=object)[
            rng.integers(0, 3, n)],
        "cc_manager": np.array([f"Manager {i}" for i in range(1, n + 1)],
                               dtype=object),
    })


def gen_income_band(sf: float, seed: int = 55) -> pa.Table:
    n = 20
    lo = np.arange(n, dtype=np.int32) * 10_000
    return pa.table({
        "ib_income_band_sk": np.arange(1, n + 1, dtype=np.int64),
        "ib_lower_bound": lo,
        "ib_upper_bound": lo + 10_000,
    })


def gen_catalog_page(sf: float, seed: int = 56) -> pa.Table:
    n = max(int(100 * sf), 10)
    return pa.table({
        "cp_catalog_page_sk": np.arange(1, n + 1, dtype=np.int64),
        "cp_catalog_page_id": np.array(
            [f"AAAAAAAA{i:04d}" for i in range(1, n + 1)], dtype=object),
    })


def gen_store_returns(sf: float, seed: int = 48) -> pa.Table:
    """~8% of store_sales rows return; key columns are SAMPLED from the
    sales table so multi-key joins (q21's ticket+item+customer) hit."""
    rng = np.random.default_rng(seed)
    sales = gen_store_sales(sf)
    n_s = sales.num_rows
    n = max(n_s // 12, 30)
    idx = rng.choice(n_s, n, replace=False)
    item = sales["ss_item_sk"].to_numpy()[idx]
    cust = sales["ss_customer_sk"].to_numpy()[idx]
    ticket = sales["ss_ticket_number"].to_numpy()[idx]
    sold = sales["ss_sold_date_sk"].to_numpy()[idx]
    # not sampled from sales: ss_store_sk is nullable there
    store_sk = rng.integers(1, max(int(12 * sf), 2) + 1, n
                            ).astype(np.int64)
    cdemo = sales["ss_cdemo_sk"].to_numpy()[idx]
    # not sampled from sales: ss_hdemo_sk is nullable there
    hdemo = rng.integers(1, 7201, n).astype(np.int64)
    return pa.table({
        "sr_item_sk": item,
        "sr_customer_sk": cust,
        "sr_ticket_number": ticket,
        "sr_returned_date_sk": sold + rng.integers(1, 90, n),
        "sr_return_quantity": rng.integers(1, 20, n).astype(np.int32),
        "sr_return_amt": np.round(rng.random(n) * 150, 2),
        "sr_net_loss": np.round(rng.random(n) * 80, 2),
        "sr_reason_sk": rng.integers(1, 36, n).astype(np.int64),
        "sr_store_sk": store_sk,
        "sr_cdemo_sk": cdemo,
        "sr_hdemo_sk": hdemo,
        "sr_fee": np.round(rng.random(n) * 100, 2),
        "sr_refunded_cash": np.round(rng.random(n) * 100, 2),
        "sr_reversed_charge": np.round(rng.random(n) * 50, 2),
        "sr_store_credit": np.round(rng.random(n) * 50, 2),
        "sr_return_ship_cost": np.round(rng.random(n) * 30, 2),
        "sr_return_amt_inc_tax": np.round(rng.random(n) * 160, 2),
        "sr_return_tax": np.round(rng.random(n) * 12, 2),
    })


def gen_web_page(sf: float, seed: int = 49) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(60 * sf), 5)
    return pa.table({
        "wp_web_page_sk": np.arange(1, n + 1, dtype=np.int64),
        "wp_char_count": rng.integers(4000, 7001, n).astype(np.int32),
    })


def gen_customer_demographics(sf: float, seed: int = 37) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(1_000 * sf), 20)
    return pa.table({
        "cd_demo_sk": np.arange(1, n + 1, dtype=np.int64),
        "cd_gender": np.array(["M", "F"], dtype=object)[
            rng.integers(0, 2, n)],
        "cd_marital_status": np.array(["M", "S", "D", "W", "U"],
                                      dtype=object)[rng.integers(0, 5, n)],
        "cd_education_status": np.array(
            ["Primary", "Secondary", "College", "2 yr Degree",
             "4 yr Degree", "Advanced Degree", "Unknown"],
            dtype=object)[rng.integers(0, 7, n)],
        "cd_purchase_estimate": (rng.integers(1, 21, n) * 500
                                 ).astype(np.int32),
        "cd_credit_rating": np.array(
            ["Low Risk", "Good", "High Risk", "Unknown"],
            dtype=object)[rng.integers(0, 4, n)],
        "cd_dep_count": rng.integers(0, 7, n).astype(np.int32),
        "cd_dep_employed_count": rng.integers(0, 7, n).astype(np.int32),
        "cd_dep_college_count": rng.integers(0, 7, n).astype(np.int32),
    })


def gen_promotion(sf: float, seed: int = 38) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(300 * sf), 10)
    return pa.table({
        "p_promo_sk": np.arange(1, n + 1, dtype=np.int64),
        "p_channel_email": np.array(["Y", "N"], dtype=object)[
            rng.integers(0, 2, n)],
        "p_channel_event": np.array(["Y", "N"], dtype=object)[
            rng.integers(0, 2, n)],
        "p_channel_dmail": np.array(["Y", "N"], dtype=object)[
            rng.integers(0, 2, n)],
        "p_channel_tv": np.array(["Y", "N"], dtype=object)[
            rng.integers(0, 2, n)],
    })


def gen_household_demographics(sf: float, seed: int = 39) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = 7200  # fixed-size dim in TPC-DS
    pots = np.array([">10000", "5001-10000", "1001-5000", "unknown"],
                    dtype=object)
    return pa.table({
        "hd_demo_sk": np.arange(1, n + 1, dtype=np.int64),
        "hd_dep_count": rng.integers(0, 10, n).astype(np.int32),
        "hd_vehicle_count": rng.integers(0, 6, n).astype(np.int32),
        "hd_buy_potential": pots[rng.integers(0, 4, n)],
        "hd_income_band_sk": rng.integers(1, 21, n).astype(np.int64),
    })


def gen_time_dim(sf: float, seed: int = 40) -> pa.Table:
    secs = np.arange(86_400, dtype=np.int64)
    hours = secs // 3600
    meal = np.where(
        (hours >= 6) & (hours <= 9), "breakfast",
        np.where((hours >= 11) & (hours <= 13), "lunch",
                 np.where((hours >= 17) & (hours <= 20), "dinner", "")))
    return pa.table({
        "t_time_sk": secs,
        "t_time": secs.astype(np.int32),
        "t_hour": hours.astype(np.int32),
        "t_minute": (secs // 60 % 60).astype(np.int32),
        "t_meal_time": meal.astype(object),
        "t_am_pm": np.where(hours < 12, "AM", "PM").astype(object),
    })


def gen_store(sf: float, seed: int = 41) -> pa.Table:
    n = max(int(12 * sf), 2)
    rng = np.random.default_rng(seed)
    cities = np.array(["Midway", "Fairview", "Oakdale", "Riverside"],
                      dtype=object)
    counties = np.array(["Williamson County", "Franklin Parish",
                         "Bronx County", "Orange County"], dtype=object)
    states = np.array(["TN", "TX", "OH", "CA"], dtype=object)
    stypes = np.array(["Ave", "St", "Blvd"], dtype=object)
    return pa.table({
        "s_store_sk": np.arange(1, n + 1, dtype=np.int64),
        "s_store_id": np.array([f"AAAAAAAA{i:04d}" for i in range(1, n + 1)],
                               dtype=object),
        "s_store_name": np.array([f"ese{i}" for i in range(1, n + 1)],
                                 dtype=object),
        "s_gmt_offset": np.where(rng.random(n) < 0.7, -5.0, -6.0),
        "s_city": cities[rng.integers(0, 4, n)],
        "s_county": counties[rng.integers(0, 4, n)],
        "s_state": states[rng.integers(0, 4, n)],
        # drawn from the address pool so q24's s_zip = ca_zip join hits
        "s_zip": _CA_ZIP_POOL[rng.integers(0, len(_CA_ZIP_POOL), n)],
        "s_street_number": np.array([str(i * 10) for i in range(1, n + 1)],
                                    dtype=object),
        "s_street_name": np.array([f"Main {i}" for i in range(1, n + 1)],
                                  dtype=object),
        "s_street_type": stypes[rng.integers(0, 3, n)],
        "s_suite_number": np.array([f"Suite {i}" for i in range(1, n + 1)],
                                   dtype=object),
        "s_number_employees": rng.integers(200, 300, n).astype(np.int32),
        "s_company_id": rng.integers(1, 3, n).astype(np.int32),
        "s_company_name": np.array(["Unknown", "ought"], dtype=object)[
            rng.integers(0, 2, n)],
        "s_market_id": rng.integers(1, 11, n).astype(np.int32),
        "s_floor_space": rng.integers(5_000_000, 10_000_000, n
                                      ).astype(np.int32),
    })




def gen_reason(sf: float, seed: int = 50) -> pa.Table:
    n = 35
    return pa.table({
        "r_reason_sk": np.arange(1, n + 1, dtype=np.int64),
        "r_reason_desc": np.array([f"reason {i}" for i in range(1, n + 1)],
                                  dtype=object),
    })


def gen_catalog_returns(sf: float, seed: int = 51) -> pa.Table:
    """~8% of catalog_sales return; keys sampled so (order, item) joins
    hit (q40)."""
    rng = np.random.default_rng(seed)
    sales = gen_catalog_sales(sf)
    n_s = sales.num_rows
    n = max(n_s // 12, 20)
    idx = rng.choice(n_s, n, replace=False)
    return pa.table({
        "cr_item_sk": sales["cs_item_sk"].to_numpy()[idx],
        "cr_order_number": sales["cs_order_number"].to_numpy()[idx],
        "cr_refunded_cash": np.round(rng.random(n) * 100, 2),
        "cr_returned_date_sk": (sales["cs_sold_date_sk"].to_numpy()[idx]
                                + rng.integers(1, 90, n)),
        "cr_returning_customer_sk":
            sales["cs_bill_customer_sk"].to_numpy()[idx],
        "cr_refunded_customer_sk":
            sales["cs_bill_customer_sk"].to_numpy()[idx],
        "cr_returning_addr_sk": sales["cs_bill_addr_sk"].to_numpy()[idx],
        "cr_call_center_sk": sales["cs_call_center_sk"].to_numpy()[idx],
        "cr_catalog_page_sk":
            sales["cs_catalog_page_sk"].to_numpy()[idx],
        "cr_return_quantity": rng.integers(1, 20, n).astype(np.int32),
        "cr_return_amount": np.round(rng.random(n) * 150, 2),
        "cr_return_amt_inc_tax": np.round(rng.random(n) * 160, 2),
        "cr_net_loss": np.round(rng.random(n) * 80, 2),
        "cr_fee": np.round(rng.random(n) * 100, 2),
        "cr_reason_sk": rng.integers(1, 36, n).astype(np.int64),
    })


def gen_customer(sf: float, seed: int = 42) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(100_000 * sf), 20)
    n_demo = max(int(1_000 * sf), 10)
    n_addr = max(int(50_000 * sf), 15)
    firsts = np.array(["James", "Mary", "John", "Ana", "Wei", "Olu",
                       "Kei", "Lena"], dtype=object)
    lasts = np.array(["Smith", "Garcia", "Chen", "Okafor", "Sato",
                      "Novak"], dtype=object)
    sals = np.array(["Mr.", "Ms.", "Dr.", "Sir"], dtype=object)
    return pa.table({
        "c_customer_sk": np.arange(1, n + 1, dtype=np.int64),
        "c_customer_id": np.array(
            [f"AAAAAAAA{i:08d}" for i in range(1, n + 1)], dtype=object),
        "c_current_cdemo_sk": rng.integers(1, n_demo + 1, n
                                           ).astype(np.int64),
        "c_current_hdemo_sk": rng.integers(1, 7201, n).astype(np.int64),
        "c_current_addr_sk": rng.integers(1, n_addr + 1, n
                                          ).astype(np.int64),
        "c_first_name": firsts[rng.integers(0, len(firsts), n)],
        "c_last_name": lasts[rng.integers(0, len(lasts), n)],
        "c_salutation": sals[rng.integers(0, 4, n)],
        "c_preferred_cust_flag": np.array(["Y", "N"], dtype=object)[
            rng.integers(0, 2, n)],
        "c_birth_country": np.array(
            ["UNITED STATES", "CANADA", "MEXICO", "JAPAN", "GERMANY"],
            dtype=object)[rng.integers(0, 5, n)],
        "c_birth_year": rng.integers(1930, 1993, n).astype(np.int32),
        "c_birth_month": rng.integers(1, 13, n).astype(np.int32),
        "c_birth_day": rng.integers(1, 29, n).astype(np.int32),
        "c_login": np.array([f"login{i}" for i in range(1, n + 1)],
                            dtype=object),
        "c_email_address": np.array(
            [f"c{i}@example.com" for i in range(1, n + 1)], dtype=object),
        "c_first_sales_date_sk": rng.integers(
            2450815, 2450815 + 5 * 365, n).astype(np.int64),
        "c_first_shipto_date_sk": rng.integers(
            2450815, 2450815 + 5 * 365, n).astype(np.int64),
    })


_CA_STATES = np.array(["KY", "GA", "NM", "MT", "OR", "IN", "WI", "MO",
                       "WV", "CA", "TX", "NY"], dtype=object)
_CA_ZIP_POOL = np.array(
    ["85669", "86197", "88274", "83405", "86475", "85392", "85460",
     "80348", "81792", "10001", "94103", "73301", "30301", "98101",
     "60601", "33101"], dtype=object)


def gen_customer_address(sf: float, seed: int = 44) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(50_000 * sf), 15)
    countries = np.array(["United States", "Canada", "Mexico"],
                         dtype=object)
    cities = np.array(["Midway", "Fairview", "Oakdale", "Riverside",
                       "Pleasant Hill"], dtype=object)
    return pa.table({
        "ca_address_sk": np.arange(1, n + 1, dtype=np.int64),
        "ca_country": countries[rng.integers(0, 3, n)],
        "ca_state": _CA_STATES[rng.integers(0, 12, n)],
        "ca_city": cities[rng.integers(0, 5, n)],
        "ca_zip": _CA_ZIP_POOL[rng.integers(0, len(_CA_ZIP_POOL), n)],
        "ca_gmt_offset": np.where(rng.random(n) < 0.6, -5.0, -7.0),
        "ca_county": np.array(
            ["Williamson County", "Franklin Parish", "Bronx County",
             "Orange County", "Walker County", "Ziebach County"],
            dtype=object)[rng.integers(0, 6, n)],
        "ca_street_number": np.array([str(i) for i in
                                      rng.integers(1, 1000, n)],
                                     dtype=object),
        "ca_street_name": np.array(
            [f"street {i % 40}" for i in rng.integers(0, 1000, n)],
            dtype=object),
        "ca_street_type": np.array(["Ave", "St", "Blvd", "Ct"],
                                   dtype=object)[rng.integers(0, 4, n)],
        "ca_suite_number": np.array(
            [f"Suite {i % 90}" for i in rng.integers(0, 1000, n)],
            dtype=object),
        "ca_location_type": np.array(["apartment", "condo",
                                      "single family"], dtype=object)[
            rng.integers(0, 3, n)],
    })


@functools.lru_cache(maxsize=2)  # returns generators re-sample it
def gen_web_sales(sf: float, seed: int = 46) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(700_000 * sf), 200)
    n_cust = max(int(100_000 * sf), 20)
    n_item = max(int(18_000 * sf), 50)
    n_addr = max(int(50_000 * sf), 15)
    n_wp = max(int(60 * sf), 5)
    n_wh = max(int(5 * sf), 2)
    return pa.table({
        "ws_sold_date_sk": rng.integers(2450815, 2450815 + 5 * 365, n
                                        ).astype(np.int64),
        "ws_sold_time_sk": rng.integers(0, 86_400, n).astype(np.int64),
        "ws_bill_customer_sk": rng.integers(1, n_cust + 1, n
                                            ).astype(np.int64),
        "ws_bill_addr_sk": rng.integers(1, n_addr + 1, n
                                        ).astype(np.int64),
        "ws_item_sk": rng.integers(1, n_item + 1, n).astype(np.int64),
        "ws_order_number": rng.integers(1, max(n // 3, 2), n
                                        ).astype(np.int64),
        "ws_quantity": rng.integers(1, 101, n).astype(np.int32),
        "ws_warehouse_sk": rng.integers(1, n_wh + 1, n).astype(np.int64),
        "ws_web_page_sk": rng.integers(1, n_wp + 1, n).astype(np.int64),
        "ws_ship_hdemo_sk": rng.integers(1, 7201, n).astype(np.int64),
        "ws_sales_price": np.round(rng.random(n) * 200, 2),
        "ws_net_paid": np.round(rng.random(n) * 300, 2),
        "ws_ext_list_price": np.round(rng.random(n) * 250, 2),
        "ws_ext_wholesale_cost": np.round(rng.random(n) * 100, 2),
        "ws_ext_discount_amt": np.round(rng.random(n) * 40, 2),
        "ws_ext_sales_price": np.round(rng.random(n) * 200, 2),
        "ws_net_profit": np.round(rng.random(n) * 300 - 150, 2),
        "ws_web_site_sk": rng.integers(1, 31, n).astype(np.int64),
        "ws_ship_date_sk": (rng.integers(2450815, 2450815 + 5 * 365, n) +
                            rng.integers(1, 30, n)).astype(np.int64),
        "ws_ship_addr_sk": rng.integers(1, n_addr + 1, n
                                        ).astype(np.int64),
        # ~2% nulls: q76 aggregates exactly the IS NULL slice
        "ws_ship_customer_sk": pa.array(
            rng.integers(1, n_cust + 1, n).astype(np.int64),
            mask=rng.random(n) < 0.02),
        "ws_ship_mode_sk": rng.integers(1, 21, n).astype(np.int64),
        "ws_ext_ship_cost": np.round(rng.random(n) * 100, 2),
        "ws_wholesale_cost": np.round(0.2 + rng.random(n) * 80, 2),
        "ws_list_price": np.round(0.5 + rng.random(n) * 200, 2),
        "ws_promo_sk": rng.integers(
            1, max(int(300 * sf), 10) + 1, n).astype(np.int64),
        "ws_coupon_amt": np.round(rng.random(n) * 50, 2),
    })


def gen_web_returns(sf: float, seed: int = 48) -> pa.Table:
    """~10% of web_sales return; keys sampled from the sales so the
    (order, item) two-key left join hits."""
    rng = np.random.default_rng(seed)
    sales = gen_web_sales(sf)
    n_s = sales.num_rows
    n = max(n_s // 10, 20)
    idx = rng.choice(n_s, n, replace=False)
    return pa.table({
        "wr_order_number": sales["ws_order_number"].to_numpy()[idx],
        "wr_item_sk": sales["ws_item_sk"].to_numpy()[idx],
        "wr_refunded_cash": np.round(rng.random(n) * 100, 2),
        "wr_returned_date_sk": (sales["ws_sold_date_sk"].to_numpy()[idx]
                                + rng.integers(1, 90, n)),
        "wr_returning_customer_sk":
            sales["ws_bill_customer_sk"].to_numpy()[idx],
        "wr_refunded_customer_sk":
            sales["ws_bill_customer_sk"].to_numpy()[idx],
        "wr_returning_addr_sk": sales["ws_bill_addr_sk"].to_numpy()[idx],
        "wr_refunded_addr_sk": sales["ws_bill_addr_sk"].to_numpy()[idx],
        "wr_refunded_cdemo_sk": rng.integers(
            1, max(int(1_000 * sf), 20) + 1, n).astype(np.int64),
        "wr_returning_cdemo_sk": rng.integers(
            1, max(int(1_000 * sf), 20) + 1, n).astype(np.int64),
        "wr_web_page_sk": sales["ws_web_page_sk"].to_numpy()[idx],
        "wr_reason_sk": rng.integers(1, 36, n).astype(np.int64),
        "wr_return_quantity": rng.integers(1, 20, n).astype(np.int32),
        "wr_return_amt": np.round(rng.random(n) * 150, 2),
        "wr_net_loss": np.round(rng.random(n) * 80, 2),
        "wr_fee": np.round(rng.random(n) * 100, 2),
    })


GENERATORS = {
    "date_dim": gen_date_dim,
    "item": gen_item,
    "store_sales": gen_store_sales,
    "catalog_sales": gen_catalog_sales,
    "inventory": gen_inventory,
    "warehouse": gen_warehouse,
    "customer_demographics": gen_customer_demographics,
    "promotion": gen_promotion,
    "household_demographics": gen_household_demographics,
    "time_dim": gen_time_dim,
    "store": gen_store,
    "store_returns": gen_store_returns,
    "web_page": gen_web_page,
    "reason": gen_reason,
    "catalog_returns": gen_catalog_returns,
    "customer": gen_customer,
    "customer_address": gen_customer_address,
    "web_sales": gen_web_sales,
    "web_returns": gen_web_returns,
    "web_site": gen_web_site,
    "ship_mode": gen_ship_mode,
    "call_center": gen_call_center,
    "income_band": gen_income_band,
    "catalog_page": gen_catalog_page,
}


def write_tables(data_dir: str, sf: float, tables=None,
                 files_per_table: int = 4) -> None:
    os.makedirs(data_dir, exist_ok=True)
    for name in tables or GENERATORS:
        table = GENERATORS[name](sf)
        tdir = os.path.join(data_dir, name)
        os.makedirs(tdir, exist_ok=True)
        per = -(-table.num_rows // files_per_table)
        for i in range(files_per_table):
            chunk = table.slice(i * per, per)
            if chunk.num_rows:
                pq.write_table(chunk,
                               os.path.join(tdir,
                                            f"part-{i:03d}.parquet"))


# ---------------------------------------------------------------------------
# queries


def ref(i, t):
    return BoundReference(i, t)


def _scan(data_dir: str, table: str, columns):
    return pn.ScanNode(ParquetSource(os.path.join(data_dir, table),
                                     columns=columns))


def _report_query(data_dir: str, item_filter, group_ordinal_names,
                  date_filter_moy=11, date_filter_year=None):
    """The q3/q42/q52/q55 family: date_dim x store_sales x item,
    filtered on month (and maybe year) + an item attribute, grouped on
    (d_year, item attrs), sum(ss_ext_sales_price) descending."""
    dd_cond = P.EqualTo(ref(1, dt.INT32),
                        Literal(date_filter_moy, dt.INT32))
    if date_filter_year is not None:
        dd_cond = P.And(dd_cond,
                        P.EqualTo(ref(2, dt.INT32),
                                  Literal(date_filter_year, dt.INT32)))
    date_dim = pn.FilterNode(
        dd_cond, _scan(data_dir, "date_dim",
                       ["d_date_sk", "d_moy", "d_year"]))
    sales = _scan(data_dir, "store_sales",
                  ["ss_sold_date_sk", "ss_item_sk",
                   "ss_ext_sales_price"])
    item_cols, item_pred, group_item_ordinals = item_filter
    item = pn.FilterNode(item_pred, _scan(data_dir, "item", item_cols))
    # [d_date_sk 0, d_moy 1, d_year 2, ss_sold_date_sk 3, ss_item_sk 4,
    #  ss_ext_sales_price 5]
    ds = pn.JoinNode("inner", date_dim, sales, [0], [0])
    # + item cols at 6..
    dsi = pn.JoinNode("inner", ds, item, [4], [0])
    group_refs = [ref(2, dt.INT32)] + \
        [ref(6 + o, t) for o, t in group_item_ordinals]
    proj = pn.ProjectNode(
        [Alias(e, n) for e, n in zip(group_refs, group_ordinal_names)] +
        [Alias(ref(5, dt.FLOAT64), "price")], dsi)
    k = len(group_refs)
    agg = pn.AggregateNode(
        [ref(i, e.dtype) for i, e in enumerate(group_refs)],
        [pn.AggCall(A.Sum(ref(k, dt.FLOAT64)), "sum_agg")],
        proj, grouping_names=group_ordinal_names)
    sort = pn.SortNode(
        [SortKeySpec.spark_default(k, ascending=False)] +
        [SortKeySpec.spark_default(i) for i in range(k)], agg)
    return pn.LimitNode(100, sort)


def q3(data_dir: str) -> pn.PlanNode:
    """Brand revenue for one manufacturer in November
    (TpcdsLikeSpark.scala q3)."""
    item_filter = (["i_item_sk", "i_brand_id", "i_brand",
                    "i_manufact_id"],
                   P.EqualTo(ref(3, dt.INT32), Literal(128, dt.INT32)),
                   [(1, dt.INT32), (2, dt.STRING)])
    return _report_query(data_dir, item_filter,
                         ["d_year", "brand_id", "brand"])


def q42(data_dir: str) -> pn.PlanNode:
    """Category revenue for one manager-year (q42)."""
    item_filter = (["i_item_sk", "i_category_id", "i_category",
                    "i_manager_id"],
                   P.EqualTo(ref(3, dt.INT32), Literal(1, dt.INT32)),
                   [(1, dt.INT32), (2, dt.STRING)])
    return _report_query(data_dir, item_filter,
                         ["d_year", "i_category_id", "i_category"],
                         date_filter_year=2000)


def q52(data_dir: str) -> pn.PlanNode:
    """Brand revenue for one manager-year (q52)."""
    item_filter = (["i_item_sk", "i_brand_id", "i_brand",
                    "i_manager_id"],
                   P.EqualTo(ref(3, dt.INT32), Literal(1, dt.INT32)),
                   [(1, dt.INT32), (2, dt.STRING)])
    return _report_query(data_dir, item_filter,
                         ["d_year", "brand_id", "brand"],
                         date_filter_year=2000)


def q55(data_dir: str) -> pn.PlanNode:
    """Brand revenue, manager 28, one month (q55)."""
    item_filter = (["i_item_sk", "i_brand_id", "i_brand",
                    "i_manager_id"],
                   P.EqualTo(ref(3, dt.INT32), Literal(28, dt.INT32)),
                   [(1, dt.INT32), (2, dt.STRING)])
    return _report_query(data_dir, item_filter,
                         ["d_year", "brand_id", "brand"],
                         date_filter_year=1999)


def q72(data_dir: str) -> pn.PlanNode:
    """q72-like: catalog_sales x inventory (same item, on-hand below
    ordered quantity) x warehouse x item x date_dim — the infamous
    expansion join, simplified to the tables generated here."""
    cs = _scan(data_dir, "catalog_sales",
               ["cs_sold_date_sk", "cs_item_sk", "cs_quantity"])
    inv = _scan(data_dir, "inventory",
                ["inv_date_sk", "inv_item_sk", "inv_warehouse_sk",
                 "inv_quantity_on_hand"])
    # join on item; keep only rows where on-hand < ordered (the q72
    # shortage condition) — an equi-join with an inter-fact residual
    # [cs 0-2, inv 3-6]
    short = pn.JoinNode(
        "inner", cs, inv, [1], [1],
        condition=P.LessThan(ref(6, dt.INT32), ref(2, dt.INT32)))
    wh = _scan(data_dir, "warehouse",
               ["w_warehouse_sk", "w_warehouse_name"])
    # + [w_warehouse_sk 7, w_warehouse_name 8]
    sw = pn.JoinNode("inner", short, wh, [5], [0])
    item = _scan(data_dir, "item", ["i_item_sk", "i_item_desc"])
    # + [i_item_sk 9, i_item_desc 10]
    swi = pn.JoinNode("inner", sw, item, [1], [0])
    dd = _scan(data_dir, "date_dim", ["d_date_sk", "d_week_seq"])
    # + [d_date_sk 11, d_week_seq 12]
    swid = pn.JoinNode("inner", swi, dd, [0], [0])
    agg = pn.AggregateNode(
        [ref(10, dt.STRING), ref(8, dt.STRING), ref(12, dt.INT32)],
        [pn.AggCall(A.Count(), "no_promo")],
        swid, grouping_names=["i_item_desc", "w_warehouse_name",
                              "d_week_seq"])
    sort = pn.SortNode([SortKeySpec.spark_default(3, ascending=False),
                        SortKeySpec.spark_default(0),
                        SortKeySpec.spark_default(1),
                        SortKeySpec.spark_default(2)], agg)
    return pn.LimitNode(100, sort)


def q7(data_dir: str) -> pn.PlanNode:
    """Promotional-item averages per item for one demographic slice
    (TpcdsLikeSpark q7): 5-way join + multi-average group-by."""
    ss = _scan(data_dir, "store_sales",
               ["ss_sold_date_sk", "ss_item_sk", "ss_cdemo_sk",
                "ss_promo_sk", "ss_quantity", "ss_list_price",
                "ss_coupon_amt", "ss_sales_price"])
    cd = pn.FilterNode(
        P.And(P.EqualTo(ref(1, dt.STRING), Literal("M")),
              P.And(P.EqualTo(ref(2, dt.STRING), Literal("S")),
                    P.EqualTo(ref(3, dt.STRING), Literal("College")))),
        _scan(data_dir, "customer_demographics",
              ["cd_demo_sk", "cd_gender", "cd_marital_status",
               "cd_education_status"]))
    # + [cd 8..11]
    s1 = pn.JoinNode("inner", ss, cd, [2], [0])
    dd = pn.FilterNode(
        P.EqualTo(ref(1, dt.INT32), Literal(2000, dt.INT32)),
        _scan(data_dir, "date_dim", ["d_date_sk", "d_year"]))
    # + [d_date_sk 12, d_year 13]
    s2 = pn.JoinNode("inner", s1, dd, [0], [0])
    promo = pn.FilterNode(
        P.Or(P.EqualTo(ref(1, dt.STRING), Literal("N")),
             P.EqualTo(ref(2, dt.STRING), Literal("N"))),
        _scan(data_dir, "promotion",
              ["p_promo_sk", "p_channel_email", "p_channel_event"]))
    # + [p_promo_sk 14, p_channel_email 15, p_channel_event 16]
    s3 = pn.JoinNode("inner", s2, promo, [3], [0])
    item = _scan(data_dir, "item", ["i_item_sk", "i_item_desc"])
    # + [i_item_sk 17, i_item_desc 18]
    s4 = pn.JoinNode("inner", s3, item, [1], [0])
    from spark_rapids_tpu.expressions.cast import Cast

    agg = pn.AggregateNode(
        [ref(18, dt.STRING)],
        [pn.AggCall(A.Average(Cast(ref(4, dt.INT32), dt.FLOAT64)),
                    "agg1"),
         pn.AggCall(A.Average(ref(5, dt.FLOAT64)), "agg2"),
         pn.AggCall(A.Average(ref(6, dt.FLOAT64)), "agg3"),
         pn.AggCall(A.Average(ref(7, dt.FLOAT64)), "agg4")],
        s4, grouping_names=["i_item_desc"])
    sort = pn.SortNode([SortKeySpec.spark_default(0)], agg)
    return pn.LimitNode(100, sort)


def q96(data_dir: str) -> pn.PlanNode:
    """Count of evening purchases by large households at one store
    (TpcdsLikeSpark q96): pure 4-way join + count."""
    ss = _scan(data_dir, "store_sales",
               ["ss_sold_time_sk", "ss_hdemo_sk", "ss_store_sk"])
    hd = pn.FilterNode(
        P.EqualTo(ref(1, dt.INT32), Literal(7, dt.INT32)),
        _scan(data_dir, "household_demographics",
              ["hd_demo_sk", "hd_dep_count"]))
    td = pn.FilterNode(
        P.And(P.EqualTo(ref(1, dt.INT32), Literal(20, dt.INT32)),
              P.GreaterThanOrEqual(ref(2, dt.INT32),
                                   Literal(30, dt.INT32))),
        _scan(data_dir, "time_dim", ["t_time_sk", "t_hour", "t_minute"]))
    store = pn.FilterNode(
        P.EqualTo(ref(1, dt.STRING), Literal("ese1")),
        _scan(data_dir, "store", ["s_store_sk", "s_store_name"]))
    s1 = pn.JoinNode("inner", ss, hd, [1], [0])
    s2 = pn.JoinNode("inner", s1, td, [0], [0])
    s3 = pn.JoinNode("inner", s2, store, [2], [0])
    return pn.AggregateNode([], [pn.AggCall(A.Count(), "cnt")], s3)


def q98(data_dir: str) -> pn.PlanNode:
    """Revenue share within item class (TpcdsLikeSpark q98): the
    windowed-aggregate shape — per-item revenue plus a partitioned
    window SUM over the class for the ratio."""
    ss = _scan(data_dir, "store_sales",
               ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    dd = pn.FilterNode(
        P.EqualTo(ref(2, dt.INT32), Literal(1999, dt.INT32)),
        _scan(data_dir, "date_dim",
              ["d_date_sk", "d_moy", "d_year"]))
    item = pn.FilterNode(
        P.In(ref(2, dt.STRING),
             [Literal("Sports"), Literal("Books"), Literal("Home")]),
        _scan(data_dir, "item",
              ["i_item_sk", "i_class_id", "i_category",
               "i_item_desc"]))
    s1 = pn.JoinNode("inner", ss, dd, [0], [0])
    # + item at 6..9
    s2 = pn.JoinNode("inner", s1, item, [1], [0])
    per_item = pn.AggregateNode(
        [ref(9, dt.STRING), ref(7, dt.INT32), ref(8, dt.STRING)],
        [pn.AggCall(A.Sum(ref(2, dt.FLOAT64)), "itemrevenue")],
        s2, grouping_names=["i_item_desc", "i_class_id", "i_category"])
    # windowed class total: partition by class, unbounded frame sum
    win = pn.WindowNode(
        [1], [],
        [pn.WindowCall(A.Sum(ref(3, dt.FLOAT64)), "classrevenue",
                       pn.WindowFrame(None, None))],
        per_item)
    share = pn.ProjectNode(
        [Alias(ref(0, dt.STRING), "i_item_desc"),
         Alias(ref(2, dt.STRING), "i_category"),
         Alias(ref(3, dt.FLOAT64), "itemrevenue"),
         Alias(ar.Multiply(
             Literal(100.0),
             ar.Divide(ref(3, dt.FLOAT64), ref(4, dt.FLOAT64))),
             "revenueratio")], win)
    sort = pn.SortNode([SortKeySpec.spark_default(1),
                        SortKeySpec.spark_default(3),
                        SortKeySpec.spark_default(0)], share)
    return pn.LimitNode(100, sort)


QUERIES = {"tpcds_q3": q3, "tpcds_q7": q7, "tpcds_q42": q42,
           "tpcds_q52": q52, "tpcds_q55": q55, "tpcds_q72": q72,
           "tpcds_q96": q96, "tpcds_q98": q98}

# ---------------------------------------------------------------------------
# SQL-text queries (TpcdsLikeSpark.scala embeds the public TPC-DS SQL; here
# the same spec queries run through the engine's own SQL front end).
# Literals are adapted to the generated data's ranges: dates 1998-2002
# (d_month_seq 0-59 from 1998-01), item prices 0.5-2.5, coupon amounts
# 0-50, store names "ese<i>"; q13/q48 hoist the equi-join conjuncts every
# OR branch repeats (semantics-preserving factoring the Spark optimizer
# performs); q50's backtick aliases and q90's decimal casts use portable
# spellings.
# ---------------------------------------------------------------------------


def _session(data_dir: str):
    from spark_rapids_tpu.api import Session

    s = Session()
    for t in GENERATORS:
        s.register_parquet(t, os.path.join(data_dir, t))
    return s


def _sql_query(final_sql: str):
    def factory(data_dir: str) -> pn.PlanNode:
        return _session(data_dir).sql(final_sql)._plan

    return factory


TPCDS_SQL = {
    "q6": """
SELECT a.ca_state state, count(*) cnt
FROM customer_address a, customer c, store_sales s, date_dim d, item i,
  (SELECT i_category cat, avg(i_current_price) * 1.2 AS thresh
   FROM item GROUP BY i_category) avgp
WHERE a.ca_address_sk = c.c_current_addr_sk
AND c.c_customer_sk = s.ss_customer_sk
AND s.ss_sold_date_sk = d.d_date_sk
AND s.ss_item_sk = i.i_item_sk
AND d.d_month_seq = (SELECT min(d_month_seq) FROM date_dim
                     WHERE d_year = 2001 AND d_moy = 1)
AND avgp.cat = i.i_category
AND i.i_current_price > avgp.thresh
GROUP BY a.ca_state HAVING count(*) >= 10
ORDER BY cnt, state LIMIT 100
""",
    "q9": """
SELECT CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20) > 409
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20) END bucket1,
       CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40) > 512
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40) END bucket2,
       CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60) > 622
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60)
            ELSE (SELECT avg(ss_net_paid) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60) END bucket3
FROM reason WHERE r_reason_sk = 1
""",
    "q13": """
SELECT avg(ss_quantity), avg(ss_ext_sales_price),
       avg(ss_ext_wholesale_cost), sum(ss_ext_wholesale_cost)
FROM store_sales, store, customer_demographics,
     household_demographics, customer_address, date_dim
WHERE s_store_sk = ss_store_sk
AND ss_sold_date_sk = d_date_sk AND d_year = 2001
AND ss_hdemo_sk = hd_demo_sk
AND cd_demo_sk = ss_cdemo_sk
AND ss_addr_sk = ca_address_sk
AND ((cd_marital_status = 'M' AND cd_education_status = 'Advanced Degree'
      AND ss_sales_price BETWEEN 100.0 AND 150.0 AND hd_dep_count = 3)
  OR (cd_marital_status = 'S' AND cd_education_status = 'College'
      AND ss_sales_price BETWEEN 50.0 AND 100.0 AND hd_dep_count = 1)
  OR (cd_marital_status = 'W' AND cd_education_status = '2 yr Degree'
      AND ss_sales_price BETWEEN 150.0 AND 200.0 AND hd_dep_count = 1))
AND ((ca_country = 'United States' AND ca_state IN ('TX', 'OR', 'KY')
      AND ss_net_profit BETWEEN 100 AND 200)
  OR (ca_country = 'United States' AND ca_state IN ('OR', 'NM', 'KY')
      AND ss_net_profit BETWEEN 150 AND 300)
  OR (ca_country = 'United States' AND ca_state IN ('CA', 'TX', 'MO')
      AND ss_net_profit BETWEEN 50 AND 250))
""",
    "q15": """
SELECT ca_zip, sum(cs_sales_price) AS total
FROM catalog_sales, customer, customer_address, date_dim
WHERE cs_bill_customer_sk = c_customer_sk
AND c_current_addr_sk = ca_address_sk
AND (substring(ca_zip, 1, 5) IN ('85669', '86197', '88274', '83405',
                                 '86475', '85392', '85460', '80348',
                                 '81792')
     OR ca_state IN ('CA', 'WI', 'GA')
     OR cs_sales_price > 180)
AND cs_sold_date_sk = d_date_sk
AND d_qoy = 2 AND d_year = 2001
GROUP BY ca_zip ORDER BY ca_zip LIMIT 100
""",
    "q19": """
SELECT i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item, customer, customer_address, store
WHERE d_date_sk = ss_sold_date_sk
AND ss_item_sk = i_item_sk
AND i_manager_id = 8
AND d_moy = 11 AND d_year = 1998
AND ss_customer_sk = c_customer_sk
AND c_current_addr_sk = ca_address_sk
AND substring(ca_zip, 1, 5) <> substring(s_zip, 1, 5)
AND ss_store_sk = s_store_sk
GROUP BY i_brand, i_brand_id, i_manufact_id, i_manufact
ORDER BY ext_price DESC, brand, brand_id, i_manufact_id, i_manufact
LIMIT 100
""",
    "q25": """
SELECT i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) AS store_sales_profit,
       sum(sr_net_loss) AS store_returns_loss,
       sum(cs_net_profit) AS catalog_sales_profit
FROM store_sales, store_returns, catalog_sales, date_dim d1,
     date_dim d2, date_dim d3, store, item
WHERE d1.d_moy = 4 AND d1.d_year = 2001
AND d1.d_date_sk = ss_sold_date_sk
AND i_item_sk = ss_item_sk
AND s_store_sk = ss_store_sk
AND ss_customer_sk = sr_customer_sk
AND ss_item_sk = sr_item_sk
AND ss_ticket_number = sr_ticket_number
AND sr_returned_date_sk = d2.d_date_sk
AND d2.d_moy BETWEEN 4 AND 10 AND d2.d_year = 2001
AND sr_customer_sk = cs_bill_customer_sk
AND sr_item_sk = cs_item_sk
AND cs_sold_date_sk = d3.d_date_sk
AND d3.d_moy BETWEEN 4 AND 10 AND d3.d_year = 2001
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id, i_item_desc, s_store_id, s_store_name
LIMIT 100
""",
    "q28": """
SELECT * FROM
(SELECT avg(ss_list_price) B1_LP, count(ss_list_price) B1_CNT,
        count(DISTINCT ss_list_price) B1_CNTD
 FROM store_sales WHERE ss_quantity BETWEEN 0 AND 5
 AND (ss_list_price BETWEEN 8 AND 18
      OR ss_coupon_amt BETWEEN 10 AND 20
      OR ss_wholesale_cost BETWEEN 57 AND 77)) B1 CROSS JOIN
(SELECT avg(ss_list_price) B2_LP, count(ss_list_price) B2_CNT,
        count(DISTINCT ss_list_price) B2_CNTD
 FROM store_sales WHERE ss_quantity BETWEEN 6 AND 10
 AND (ss_list_price BETWEEN 90 AND 100
      OR ss_coupon_amt BETWEEN 20 AND 30
      OR ss_wholesale_cost BETWEEN 31 AND 51)) B2 CROSS JOIN
(SELECT avg(ss_list_price) B3_LP, count(ss_list_price) B3_CNT,
        count(DISTINCT ss_list_price) B3_CNTD
 FROM store_sales WHERE ss_quantity BETWEEN 11 AND 15
 AND (ss_list_price BETWEEN 142 AND 152
      OR ss_coupon_amt BETWEEN 30 AND 40
      OR ss_wholesale_cost BETWEEN 79 AND 99)) B3
LIMIT 100
""",
    "q33": """
WITH ss AS (
  SELECT i_manufact_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category IN ('Electronics'))
  AND ss_item_sk = i_item_sk
  AND ss_sold_date_sk = d_date_sk
  AND d_year = 1998 AND d_moy = 5
  AND ss_addr_sk = ca_address_sk AND ca_gmt_offset = -5.0
  GROUP BY i_manufact_id),
cs AS (
  SELECT i_manufact_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category IN ('Electronics'))
  AND cs_item_sk = i_item_sk
  AND cs_sold_date_sk = d_date_sk
  AND d_year = 1998 AND d_moy = 5
  AND cs_bill_addr_sk = ca_address_sk AND ca_gmt_offset = -5.0
  GROUP BY i_manufact_id),
ws AS (
  SELECT i_manufact_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category IN ('Electronics'))
  AND ws_item_sk = i_item_sk
  AND ws_sold_date_sk = d_date_sk
  AND d_year = 1998 AND d_moy = 5
  AND ws_bill_addr_sk = ca_address_sk AND ca_gmt_offset = -5.0
  GROUP BY i_manufact_id)
SELECT i_manufact_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss UNION ALL
      SELECT * FROM cs UNION ALL
      SELECT * FROM ws) tmp1
GROUP BY i_manufact_id
ORDER BY total_sales, i_manufact_id
LIMIT 100
""",
    "q37": """
SELECT i_item_id, i_item_desc, i_current_price
FROM item, inventory, date_dim, catalog_sales
WHERE i_current_price BETWEEN 1.0 AND 1.8
AND inv_item_sk = i_item_sk
AND d_date_sk = inv_date_sk
AND d_date BETWEEN cast('2000-02-01' AS date)
              AND (cast('2000-02-01' AS date) + INTERVAL '60' day)
AND i_manufact_id IN (677, 940, 694, 808)
AND inv_quantity_on_hand BETWEEN 100 AND 500
AND cs_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id LIMIT 100
""",
    "q40": """
SELECT w_state, i_item_id,
  sum(CASE WHEN (d_date < cast('2000-03-11' AS date))
      THEN cs_sales_price - coalesce(cr_refunded_cash, 0)
      ELSE 0 END) AS sales_before,
  sum(CASE WHEN (d_date >= cast('2000-03-11' AS date))
      THEN cs_sales_price - coalesce(cr_refunded_cash, 0)
      ELSE 0 END) AS sales_after
FROM catalog_sales LEFT OUTER JOIN catalog_returns ON
  (cs_order_number = cr_order_number AND cs_item_sk = cr_item_sk),
  warehouse, item, date_dim
WHERE i_current_price BETWEEN 0.99 AND 1.49
AND i_item_sk = cs_item_sk
AND cs_warehouse_sk = w_warehouse_sk
AND cs_sold_date_sk = d_date_sk
AND d_date BETWEEN (cast('2000-03-11' AS date) - INTERVAL '30' day)
              AND (cast('2000-03-11' AS date) + INTERVAL '30' day)
GROUP BY w_state, i_item_id
ORDER BY w_state, i_item_id
LIMIT 100
""",
    "q43": """
SELECT s_store_name, s_store_id,
  sum(CASE WHEN (d_day_name = 'Sunday') THEN ss_sales_price
      ELSE null END) sun_sales,
  sum(CASE WHEN (d_day_name = 'Monday') THEN ss_sales_price
      ELSE null END) mon_sales,
  sum(CASE WHEN (d_day_name = 'Tuesday') THEN ss_sales_price
      ELSE null END) tue_sales,
  sum(CASE WHEN (d_day_name = 'Wednesday') THEN ss_sales_price
      ELSE null END) wed_sales,
  sum(CASE WHEN (d_day_name = 'Thursday') THEN ss_sales_price
      ELSE null END) thu_sales,
  sum(CASE WHEN (d_day_name = 'Friday') THEN ss_sales_price
      ELSE null END) fri_sales,
  sum(CASE WHEN (d_day_name = 'Saturday') THEN ss_sales_price
      ELSE null END) sat_sales
FROM date_dim, store_sales, store
WHERE d_date_sk = ss_sold_date_sk
AND s_store_sk = ss_store_sk
AND s_gmt_offset = -5.0
AND d_year = 2000
GROUP BY s_store_name, s_store_id
ORDER BY s_store_name, s_store_id, sun_sales, mon_sales, tue_sales,
         wed_sales, thu_sales, fri_sales, sat_sales
LIMIT 100
""",
    "q46": """
SELECT c_last_name, c_first_name, ca_city, bought_city,
       ss_ticket_number, amt, profit
FROM (SELECT ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      FROM store_sales, date_dim, store, household_demographics,
           customer_address
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
      AND store_sales.ss_store_sk = store.s_store_sk
      AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
      AND store_sales.ss_addr_sk = customer_address.ca_address_sk
      AND (household_demographics.hd_dep_count = 4 OR
           household_demographics.hd_vehicle_count = 3)
      AND date_dim.d_dow IN (6, 0)
      AND date_dim.d_year IN (1999, 2000, 2001)
      AND store.s_city IN ('Fairview', 'Midway')
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk,
               ca_city) dn, customer, customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
AND customer.c_current_addr_sk = current_addr.ca_address_sk
AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, c_first_name, ca_city, bought_city,
         ss_ticket_number
LIMIT 100
""",
    "q48": """
SELECT sum(ss_quantity) AS q
FROM store_sales, store, customer_demographics, customer_address,
     date_dim
WHERE s_store_sk = ss_store_sk
AND ss_sold_date_sk = d_date_sk AND d_year = 2000
AND cd_demo_sk = ss_cdemo_sk
AND ss_addr_sk = ca_address_sk
AND ((cd_marital_status = 'M' AND cd_education_status = '4 yr Degree'
      AND ss_sales_price BETWEEN 100.0 AND 150.0)
  OR (cd_marital_status = 'D' AND cd_education_status = '2 yr Degree'
      AND ss_sales_price BETWEEN 50.0 AND 100.0)
  OR (cd_marital_status = 'S' AND cd_education_status = 'College'
      AND ss_sales_price BETWEEN 150.0 AND 200.0))
AND ((ca_country = 'United States' AND ca_state IN ('CA', 'OR', 'TX')
      AND ss_net_profit BETWEEN 0 AND 2000)
  OR (ca_country = 'United States' AND ca_state IN ('OR', 'NM', 'KY')
      AND ss_net_profit BETWEEN 150 AND 3000)
  OR (ca_country = 'United States' AND ca_state IN ('GA', 'TX', 'MO')
      AND ss_net_profit BETWEEN 50 AND 25000))
""",
    "q50": """
SELECT s_store_name, s_company_id, s_street_number, s_street_name,
       s_street_type, s_suite_number, s_city, s_county, s_state, s_zip,
  sum(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk <= 30)
      THEN 1 ELSE 0 END) AS d30,
  sum(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk > 30) AND
           (sr_returned_date_sk - ss_sold_date_sk <= 60)
      THEN 1 ELSE 0 END) AS d31_60,
  sum(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk > 60) AND
           (sr_returned_date_sk - ss_sold_date_sk <= 90)
      THEN 1 ELSE 0 END) AS d61_90,
  sum(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk > 90)
      THEN 1 ELSE 0 END) AS d_over_90
FROM store_sales, store_returns, store, date_dim d1, date_dim d2
WHERE d2.d_year = 2001 AND d2.d_moy = 8
AND ss_ticket_number = sr_ticket_number
AND ss_item_sk = sr_item_sk
AND ss_sold_date_sk = d1.d_date_sk
AND sr_returned_date_sk = d2.d_date_sk
AND ss_customer_sk = sr_customer_sk
AND ss_store_sk = s_store_sk
GROUP BY s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state,
         s_zip
ORDER BY s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state,
         s_zip
LIMIT 100
""",
    "q59": """
WITH wss AS (
  SELECT d_week_seq, ss_store_sk,
    sum(CASE WHEN (d_day_name = 'Sunday') THEN ss_sales_price
        ELSE null END) sun_sales,
    sum(CASE WHEN (d_day_name = 'Monday') THEN ss_sales_price
        ELSE null END) mon_sales,
    sum(CASE WHEN (d_day_name = 'Friday') THEN ss_sales_price
        ELSE null END) fri_sales,
    sum(CASE WHEN (d_day_name = 'Saturday') THEN ss_sales_price
        ELSE null END) sat_sales
  FROM store_sales, date_dim
  WHERE d_date_sk = ss_sold_date_sk
  GROUP BY d_week_seq, ss_store_sk)
SELECT s_store_name1, s_store_id1, d_week_seq1,
       sun_sales1 / sun_sales2, mon_sales1 / mon_sales2,
       fri_sales1 / fri_sales2, sat_sales1 / sat_sales2
FROM
(SELECT s_store_name s_store_name1, wss.d_week_seq d_week_seq1,
        s_store_id s_store_id1, sun_sales sun_sales1,
        mon_sales mon_sales1, fri_sales fri_sales1,
        sat_sales sat_sales1
 FROM wss, store, date_dim d
 WHERE d.d_week_seq = wss.d_week_seq AND ss_store_sk = s_store_sk
 AND d_month_seq BETWEEN 24 AND 35) y,
(SELECT s_store_name s_store_name2, wss.d_week_seq d_week_seq2,
        s_store_id s_store_id2, sun_sales sun_sales2,
        mon_sales mon_sales2, fri_sales fri_sales2,
        sat_sales sat_sales2
 FROM wss, store, date_dim d
 WHERE d.d_week_seq = wss.d_week_seq AND ss_store_sk = s_store_sk
 AND d_month_seq BETWEEN 36 AND 47) x
WHERE s_store_id1 = s_store_id2
AND d_week_seq1 = d_week_seq2 - 52
ORDER BY s_store_name1, s_store_id1, d_week_seq1
LIMIT 100
""",
    "q65": """
SELECT s_store_name, i_item_desc, sc.revenue, i_current_price,
       i_wholesale_cost, i_brand
FROM store, item,
  (SELECT ss_store_sk, avg(revenue) AS ave
   FROM (SELECT ss_store_sk, ss_item_sk,
                sum(ss_sales_price) AS revenue
         FROM store_sales, date_dim
         WHERE ss_sold_date_sk = d_date_sk
         AND d_month_seq BETWEEN 24 AND 35
         GROUP BY ss_store_sk, ss_item_sk) sa
   GROUP BY ss_store_sk) sb,
  (SELECT ss_store_sk, ss_item_sk, sum(ss_sales_price) AS revenue
   FROM store_sales, date_dim
   WHERE ss_sold_date_sk = d_date_sk
   AND d_month_seq BETWEEN 24 AND 35
   GROUP BY ss_store_sk, ss_item_sk) sc
WHERE sb.ss_store_sk = sc.ss_store_sk
AND sc.revenue <= 0.1 * sb.ave
AND s_store_sk = sc.ss_store_sk
AND i_item_sk = sc.ss_item_sk
ORDER BY s_store_name, i_item_desc, sc.revenue
LIMIT 100
""",
    "q68": """
SELECT c_last_name, c_first_name, ca_city, bought_city,
       ss_ticket_number, extended_price, extended_tax, list_price
FROM (SELECT ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_ext_sales_price) extended_price,
             sum(ss_ext_list_price) list_price,
             sum(ss_ext_tax) extended_tax
      FROM store_sales, date_dim, store, household_demographics,
           customer_address
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
      AND store_sales.ss_store_sk = store.s_store_sk
      AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
      AND store_sales.ss_addr_sk = customer_address.ca_address_sk
      AND date_dim.d_dom BETWEEN 1 AND 2
      AND (household_demographics.hd_dep_count = 4 OR
           household_demographics.hd_vehicle_count = 3)
      AND date_dim.d_year IN (1999, 2000, 2001)
      AND store.s_city IN ('Midway', 'Fairview')
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk,
               ca_city) dn, customer, customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
AND customer.c_current_addr_sk = current_addr.ca_address_sk
AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, ss_ticket_number
LIMIT 100
""",
    "q73": """
SELECT c_last_name, c_first_name, c_salutation,
       c_preferred_cust_flag, ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
      AND store_sales.ss_store_sk = store.s_store_sk
      AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
      AND date_dim.d_dom BETWEEN 1 AND 2
      AND (household_demographics.hd_buy_potential = '>10000' OR
           household_demographics.hd_buy_potential = 'unknown')
      AND household_demographics.hd_vehicle_count > 0
      AND CASE WHEN household_demographics.hd_vehicle_count > 0
          THEN household_demographics.hd_dep_count /
               household_demographics.hd_vehicle_count
          ELSE null END > 1
      AND date_dim.d_year IN (1999, 2000, 2001)
      AND store.s_county IN ('Williamson County', 'Franklin Parish',
                             'Bronx County', 'Orange County')
      GROUP BY ss_ticket_number, ss_customer_sk) dj, customer
WHERE ss_customer_sk = c_customer_sk
AND cnt BETWEEN 1 AND 5
ORDER BY cnt DESC, c_last_name ASC, ss_ticket_number
LIMIT 1000
""",
    "q79": """
SELECT c_last_name, c_first_name,
       substring(s_city, 1, 30) AS city30, ss_ticket_number, amt,
       profit
FROM (SELECT ss_ticket_number, ss_customer_sk, store.s_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      FROM store_sales, date_dim, store, household_demographics
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
      AND store_sales.ss_store_sk = store.s_store_sk
      AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
      AND (household_demographics.hd_dep_count = 6 OR
           household_demographics.hd_vehicle_count > 2)
      AND date_dim.d_dow = 1
      AND date_dim.d_year IN (1999, 2000, 2001)
      AND store.s_number_employees BETWEEN 200 AND 295
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk,
               store.s_city) ms, customer
WHERE ss_customer_sk = c_customer_sk
ORDER BY c_last_name, c_first_name, city30, profit, ss_ticket_number
LIMIT 100
""",
    "q82": """
SELECT i_item_id, i_item_desc, i_current_price
FROM item, inventory, date_dim, store_sales
WHERE i_current_price BETWEEN 1.0 AND 1.8
AND inv_item_sk = i_item_sk
AND d_date_sk = inv_date_sk
AND d_date BETWEEN cast('2000-05-25' AS date)
              AND (cast('2000-05-25' AS date) + INTERVAL '60' day)
AND i_manufact_id IN (129, 270, 821, 423)
AND inv_quantity_on_hand BETWEEN 100 AND 500
AND ss_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id LIMIT 100
""",
    "q88": """
SELECT * FROM
(SELECT count(*) h8_30_to_9 FROM store_sales, household_demographics,
       time_dim, store
 WHERE ss_sold_time_sk = time_dim.t_time_sk
 AND ss_hdemo_sk = household_demographics.hd_demo_sk
 AND ss_store_sk = s_store_sk
 AND time_dim.t_hour = 8 AND time_dim.t_minute >= 30
 AND ((household_demographics.hd_dep_count = 4 AND
       household_demographics.hd_vehicle_count <= 6) OR
      (household_demographics.hd_dep_count = 2 AND
       household_demographics.hd_vehicle_count <= 4) OR
      (household_demographics.hd_dep_count = 0 AND
       household_demographics.hd_vehicle_count <= 2))
 AND store.s_store_name = 'ese1') s1 CROSS JOIN
(SELECT count(*) h9_to_9_30 FROM store_sales, household_demographics,
       time_dim, store
 WHERE ss_sold_time_sk = time_dim.t_time_sk
 AND ss_hdemo_sk = household_demographics.hd_demo_sk
 AND ss_store_sk = s_store_sk
 AND time_dim.t_hour = 9 AND time_dim.t_minute < 30
 AND ((household_demographics.hd_dep_count = 4 AND
       household_demographics.hd_vehicle_count <= 6) OR
      (household_demographics.hd_dep_count = 2 AND
       household_demographics.hd_vehicle_count <= 4) OR
      (household_demographics.hd_dep_count = 0 AND
       household_demographics.hd_vehicle_count <= 2))
 AND store.s_store_name = 'ese1') s2 CROSS JOIN
(SELECT count(*) h9_30_to_10 FROM store_sales,
       household_demographics, time_dim, store
 WHERE ss_sold_time_sk = time_dim.t_time_sk
 AND ss_hdemo_sk = household_demographics.hd_demo_sk
 AND ss_store_sk = s_store_sk
 AND time_dim.t_hour = 9 AND time_dim.t_minute >= 30
 AND ((household_demographics.hd_dep_count = 4 AND
       household_demographics.hd_vehicle_count <= 6) OR
      (household_demographics.hd_dep_count = 2 AND
       household_demographics.hd_vehicle_count <= 4) OR
      (household_demographics.hd_dep_count = 0 AND
       household_demographics.hd_vehicle_count <= 2))
 AND store.s_store_name = 'ese1') s3
""",
    "q90": """
SELECT cast(amc AS double) / cast(pmc AS double) am_pm_ratio
FROM (SELECT count(*) amc FROM web_sales, household_demographics,
            time_dim, web_page
      WHERE ws_sold_time_sk = time_dim.t_time_sk
      AND ws_ship_hdemo_sk = household_demographics.hd_demo_sk
      AND ws_web_page_sk = web_page.wp_web_page_sk
      AND time_dim.t_hour BETWEEN 8 AND 9
      AND household_demographics.hd_dep_count = 6
      AND web_page.wp_char_count BETWEEN 5000 AND 5200) at CROSS JOIN
     (SELECT count(*) pmc FROM web_sales, household_demographics,
            time_dim, web_page
      WHERE ws_sold_time_sk = time_dim.t_time_sk
      AND ws_ship_hdemo_sk = household_demographics.hd_demo_sk
      AND ws_web_page_sk = web_page.wp_web_page_sk
      AND time_dim.t_hour BETWEEN 19 AND 20
      AND household_demographics.hd_dep_count = 6
      AND web_page.wp_char_count BETWEEN 5000 AND 5200) pt
ORDER BY am_pm_ratio
LIMIT 100
""",
    "q93": """
SELECT ss_customer_sk, sum(act_sales) sumsales
FROM (SELECT ss_item_sk, ss_ticket_number, ss_customer_sk,
             CASE WHEN sr_return_quantity IS NOT NULL
             THEN (ss_quantity - sr_return_quantity) * ss_sales_price
             ELSE (ss_quantity * ss_sales_price) END act_sales
      FROM store_sales LEFT OUTER JOIN store_returns
        ON (sr_item_sk = ss_item_sk AND
            sr_ticket_number = ss_ticket_number), reason
      WHERE sr_reason_sk = r_reason_sk
      AND r_reason_desc = 'reason 28') t
GROUP BY ss_customer_sk
ORDER BY sumsales, ss_customer_sk
LIMIT 100
""",
    "q97": """
WITH ssci AS (
  SELECT ss_customer_sk customer_sk, ss_item_sk item_sk
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk
  AND d_month_seq BETWEEN 24 AND 35
  GROUP BY ss_customer_sk, ss_item_sk),
csci AS (
  SELECT cs_bill_customer_sk customer_sk, cs_item_sk item_sk
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk
  AND d_month_seq BETWEEN 24 AND 35
  GROUP BY cs_bill_customer_sk, cs_item_sk)
SELECT sum(CASE WHEN ssci.customer_sk IS NOT NULL
                AND csci.customer_sk IS NULL
           THEN 1 ELSE 0 END) store_only,
       sum(CASE WHEN ssci.customer_sk IS NULL
                AND csci.customer_sk IS NOT NULL
           THEN 1 ELSE 0 END) catalog_only,
       sum(CASE WHEN ssci.customer_sk IS NOT NULL
                AND csci.customer_sk IS NOT NULL
           THEN 1 ELSE 0 END) store_and_catalog
FROM ssci FULL OUTER JOIN csci
  ON (ssci.customer_sk = csci.customer_sk
      AND ssci.item_sk = csci.item_sk)
LIMIT 100
""",
}

for _name, _sql in TPCDS_SQL.items():
    QUERIES[f"tpcds_{_name}"] = _sql_query(_sql)
TPCDS_SQL["q1"] = """
WITH customer_total_return AS
  (SELECT sr_customer_sk AS ctr_customer_sk,
          ss_store_sk AS ctr_store_sk,
          sum(sr_return_amt) AS ctr_total_return
   FROM store_returns, store_sales, date_dim
   WHERE sr_ticket_number = ss_ticket_number
   AND sr_item_sk = ss_item_sk
   AND sr_returned_date_sk = d_date_sk AND d_year = 2000
   GROUP BY sr_customer_sk, ss_store_sk),
store_avg AS
  (SELECT ctr_store_sk AS avg_store_sk,
          avg(ctr_total_return) * 1.2 AS thresh
   FROM customer_total_return GROUP BY ctr_store_sk)
SELECT c_customer_id
FROM customer_total_return ctr1, store_avg, store, customer
WHERE ctr1.ctr_store_sk = store_avg.avg_store_sk
AND ctr1.ctr_total_return > store_avg.thresh
AND s_store_sk = ctr1.ctr_store_sk
AND s_state = 'TN'
AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id LIMIT 100
"""

TPCDS_SQL["q12"] = """
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
  sum(ws_ext_sales_price) AS itemrevenue,
  sum(ws_ext_sales_price) * 100 / sum(sum(ws_ext_sales_price)) OVER
    (PARTITION BY i_class) AS revenueratio
FROM web_sales, item, date_dim
WHERE ws_item_sk = i_item_sk
AND i_category IN ('Sports', 'Books', 'Home')
AND ws_sold_date_sk = d_date_sk
AND d_date BETWEEN cast('1999-02-22' AS date)
              AND (cast('1999-02-22' AS date) + INTERVAL '30' day)
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
LIMIT 100
"""

TPCDS_SQL["q20"] = """
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
  sum(cs_ext_sales_price) AS itemrevenue,
  sum(cs_ext_sales_price) * 100 / sum(sum(cs_ext_sales_price)) OVER
    (PARTITION BY i_class) AS revenueratio
FROM catalog_sales, item, date_dim
WHERE cs_item_sk = i_item_sk
AND i_category IN ('Sports', 'Books', 'Home')
AND cs_sold_date_sk = d_date_sk
AND d_date BETWEEN cast('1999-02-22' AS date)
              AND (cast('1999-02-22' AS date) + INTERVAL '30' day)
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
LIMIT 100
"""

TPCDS_SQL["q21"] = """
SELECT * FROM (
  SELECT w_warehouse_name, i_item_id,
    sum(CASE WHEN d_date < cast('2000-03-11' AS date)
        THEN inv_quantity_on_hand ELSE 0 END) AS inv_before,
    sum(CASE WHEN d_date >= cast('2000-03-11' AS date)
        THEN inv_quantity_on_hand ELSE 0 END) AS inv_after
  FROM inventory, warehouse, item, date_dim
  WHERE i_current_price BETWEEN 0.99 AND 1.49
  AND i_item_sk = inv_item_sk
  AND inv_warehouse_sk = w_warehouse_sk
  AND inv_date_sk = d_date_sk
  AND d_date BETWEEN (cast('2000-03-11' AS date) - INTERVAL '30' day)
                AND (cast('2000-03-11' AS date) + INTERVAL '30' day)
  GROUP BY w_warehouse_name, i_item_id) x
WHERE (CASE WHEN inv_before > 0 THEN inv_after / inv_before
       ELSE null END) BETWEEN 2.0 / 3.0 AND 3.0 / 2.0
ORDER BY w_warehouse_name, i_item_id
LIMIT 100
"""

TPCDS_SQL["q29"] = """
SELECT i_item_id, i_item_desc, s_store_id, s_store_name,
  sum(ss_quantity) AS store_sales_quantity,
  sum(sr_return_quantity) AS store_returns_quantity,
  sum(cs_quantity) AS catalog_sales_quantity
FROM store_sales, store_returns, catalog_sales, date_dim d1,
     date_dim d2, date_dim d3, store, item
WHERE d1.d_moy = 4 AND d1.d_year = 1999
AND d1.d_date_sk = ss_sold_date_sk
AND i_item_sk = ss_item_sk
AND s_store_sk = ss_store_sk
AND ss_customer_sk = sr_customer_sk
AND ss_item_sk = sr_item_sk
AND ss_ticket_number = sr_ticket_number
AND sr_returned_date_sk = d2.d_date_sk
AND d2.d_moy BETWEEN 4 AND 7 AND d2.d_year = 1999
AND sr_customer_sk = cs_bill_customer_sk
AND sr_item_sk = cs_item_sk
AND cs_sold_date_sk = d3.d_date_sk
AND d3.d_year IN (1999, 2000, 2001)
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id, i_item_desc, s_store_id, s_store_name
LIMIT 100
"""

# q32/q92: the spec's correlated per-item scalar subquery decorrelates
# into a grouped-average join (the rewrite Spark's optimizer performs)
TPCDS_SQL["q32"] = """
SELECT sum(cs_ext_discount_amt) AS excess_discount_amount
FROM catalog_sales, item, date_dim,
  (SELECT cs_item_sk AS t_item_sk,
          1.3 * avg(cs_ext_discount_amt) AS thresh
   FROM catalog_sales, date_dim
   WHERE d_date BETWEEN cast('2000-01-27' AS date)
                   AND (cast('2000-01-27' AS date) + INTERVAL '90' day)
   AND d_date_sk = cs_sold_date_sk
   GROUP BY cs_item_sk) t
WHERE i_manufact_id = 977
AND i_item_sk = cs_item_sk
AND t.t_item_sk = cs_item_sk
AND d_date BETWEEN cast('2000-01-27' AS date)
              AND (cast('2000-01-27' AS date) + INTERVAL '90' day)
AND d_date_sk = cs_sold_date_sk
AND cs_ext_discount_amt > t.thresh
LIMIT 100
"""

TPCDS_SQL["q34"] = """
SELECT c_last_name, c_first_name, c_salutation,
       c_preferred_cust_flag, ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
      AND store_sales.ss_store_sk = store.s_store_sk
      AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
      AND (date_dim.d_dom BETWEEN 1 AND 3 OR
           date_dim.d_dom BETWEEN 25 AND 28)
      AND (household_demographics.hd_buy_potential = '>10000' OR
           household_demographics.hd_buy_potential = 'unknown')
      AND household_demographics.hd_vehicle_count > 0
      AND (CASE WHEN household_demographics.hd_vehicle_count > 0
           THEN household_demographics.hd_dep_count /
                household_demographics.hd_vehicle_count
           ELSE null END) > 1.2
      AND date_dim.d_year IN (1999, 2000, 2001)
      AND store.s_county IN ('Williamson County')
      GROUP BY ss_ticket_number, ss_customer_sk) dn, customer
WHERE ss_customer_sk = c_customer_sk
AND cnt BETWEEN 2 AND 20
ORDER BY c_last_name, c_first_name, c_salutation,
         c_preferred_cust_flag DESC, ss_ticket_number
LIMIT 1000
"""

# q39: the spec's simple-CASE (case mean when 0 ...) spelled searched
TPCDS_SQL["q39"] = """
WITH inv AS
  (SELECT w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy,
          stdev, mean,
          CASE WHEN mean = 0 THEN null ELSE stdev / mean END cov
   FROM (SELECT w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy,
                stddev_samp(inv_quantity_on_hand) stdev,
                avg(inv_quantity_on_hand) mean
         FROM inventory, item, warehouse, date_dim
         WHERE inv_item_sk = i_item_sk
         AND inv_warehouse_sk = w_warehouse_sk
         AND inv_date_sk = d_date_sk
         AND d_year = 2001
         GROUP BY w_warehouse_name, w_warehouse_sk, i_item_sk,
                  d_moy) foo
   WHERE CASE WHEN mean = 0 THEN 0 ELSE stdev / mean END > 1)
SELECT inv1.w_warehouse_sk AS w1, inv1.i_item_sk AS i1,
       inv1.d_moy AS moy1, inv1.mean AS mean1, inv1.cov AS cov1,
       inv2.w_warehouse_sk AS w2, inv2.i_item_sk AS i2,
       inv2.d_moy AS moy2, inv2.mean AS mean2, inv2.cov AS cov2
FROM inv inv1, inv inv2
WHERE inv1.i_item_sk = inv2.i_item_sk
AND inv1.w_warehouse_sk = inv2.w_warehouse_sk
AND inv1.d_moy = 1 AND inv2.d_moy = 2
ORDER BY w1, i1, moy1, mean1, cov1, moy2, mean2, cov2
"""

# q53/q89: brand-literal pools adapted to the generated category/class
# values (brands are random; the plan shape — OR'd pools + windowed
# average deviation — is what the query exercises)
TPCDS_SQL["q53"] = """
SELECT * FROM
  (SELECT i_manufact_id, sum(ss_sales_price) sum_sales,
          avg(sum(ss_sales_price)) OVER
            (PARTITION BY i_manufact_id) avg_quarterly_sales
   FROM item, store_sales, date_dim, store
   WHERE ss_item_sk = i_item_sk AND
   ss_sold_date_sk = d_date_sk AND
   ss_store_sk = s_store_sk AND
   d_month_seq IN (24, 25, 26, 27, 28, 29, 30, 31, 32, 33, 34, 35) AND
   ((i_category IN ('Books', 'Children', 'Electronics') AND
     i_class IN ('class1', 'class2', 'class3', 'class4'))
    OR (i_category IN ('Women', 'Music', 'Men') AND
        i_class IN ('class5', 'class6', 'class7', 'class8')))
   GROUP BY i_manufact_id, d_qoy) tmp1
WHERE CASE WHEN avg_quarterly_sales > 0
      THEN abs(sum_sales - avg_quarterly_sales) / avg_quarterly_sales
      ELSE null END > 0.1
ORDER BY avg_quarterly_sales, sum_sales, i_manufact_id
LIMIT 100
"""

TPCDS_SQL["q60"] = """
WITH ss AS (
  SELECT i_item_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category IN ('Music'))
  AND ss_item_sk = i_item_sk
  AND ss_sold_date_sk = d_date_sk
  AND d_year = 1998 AND d_moy = 9
  AND ss_addr_sk = ca_address_sk AND ca_gmt_offset = -5.0
  GROUP BY i_item_id),
cs AS (
  SELECT i_item_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category IN ('Music'))
  AND cs_item_sk = i_item_sk
  AND cs_sold_date_sk = d_date_sk
  AND d_year = 1998 AND d_moy = 9
  AND cs_bill_addr_sk = ca_address_sk AND ca_gmt_offset = -5.0
  GROUP BY i_item_id),
ws AS (
  SELECT i_item_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category IN ('Music'))
  AND ws_item_sk = i_item_sk
  AND ws_sold_date_sk = d_date_sk
  AND d_year = 1998 AND d_moy = 9
  AND ws_bill_addr_sk = ca_address_sk AND ca_gmt_offset = -5.0
  GROUP BY i_item_id)
SELECT i_item_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss UNION ALL
      SELECT * FROM cs UNION ALL
      SELECT * FROM ws) tmp1
GROUP BY i_item_id
ORDER BY i_item_id, total_sales
LIMIT 100
"""

TPCDS_SQL["q71"] = """
SELECT i_brand_id brand_id, i_brand brand, t_hour, t_minute,
       sum(ext_price) ext_price
FROM item,
  (SELECT ws_ext_sales_price AS ext_price,
          ws_sold_date_sk AS sold_date_sk,
          ws_item_sk AS sold_item_sk,
          ws_sold_time_sk AS time_sk
   FROM web_sales, date_dim
   WHERE d_date_sk = ws_sold_date_sk AND d_moy = 11 AND d_year = 1999
   UNION ALL
   SELECT cs_ext_sales_price AS ext_price,
          cs_sold_date_sk AS sold_date_sk,
          cs_item_sk AS sold_item_sk,
          cs_sold_time_sk AS time_sk
   FROM catalog_sales, date_dim
   WHERE d_date_sk = cs_sold_date_sk AND d_moy = 11 AND d_year = 1999
   UNION ALL
   SELECT ss_ext_sales_price AS ext_price,
          ss_sold_date_sk AS sold_date_sk,
          ss_item_sk AS sold_item_sk,
          ss_sold_time_sk AS time_sk
   FROM store_sales, date_dim
   WHERE d_date_sk = ss_sold_date_sk AND d_moy = 11 AND d_year = 1999
  ) tmp, time_dim
WHERE sold_item_sk = i_item_sk
AND i_manager_id = 1
AND time_sk = t_time_sk
AND (t_meal_time = 'breakfast' OR t_meal_time = 'dinner')
GROUP BY i_brand, i_brand_id, t_hour, t_minute
ORDER BY ext_price DESC, i_brand_id, t_hour, t_minute
LIMIT 1000
"""

TPCDS_SQL["q89"] = """
SELECT * FROM (
  SELECT i_category, i_class, i_brand, s_store_name, s_store_id,
         d_moy, sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price)) OVER
           (PARTITION BY i_category, i_brand, s_store_name, s_store_id)
         avg_monthly_sales
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk AND
  ss_sold_date_sk = d_date_sk AND
  ss_store_sk = s_store_sk AND
  d_year IN (1999) AND
  ((i_category IN ('Books', 'Electronics', 'Sports') AND
    i_class IN ('class1', 'class2', 'class3'))
   OR (i_category IN ('Men', 'Jewelry', 'Women') AND
       i_class IN ('class4', 'class5', 'class6')))
  GROUP BY i_category, i_class, i_brand, s_store_name, s_store_id,
           d_moy) tmp1
WHERE CASE WHEN avg_monthly_sales <> 0
      THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
      ELSE null END > 0.1
ORDER BY sum_sales - avg_monthly_sales, s_store_name, s_store_id,
         i_category, i_class, i_brand, d_moy
LIMIT 100
"""

TPCDS_SQL["q92"] = """
SELECT sum(ws_ext_discount_amt) AS excess_discount_amount
FROM web_sales, item, date_dim,
  (SELECT ws_item_sk AS t_item_sk,
          1.3 * avg(ws_ext_discount_amt) AS thresh
   FROM web_sales, date_dim
   WHERE d_date BETWEEN cast('2000-01-27' AS date)
                   AND (cast('2000-01-27' AS date) + INTERVAL '90' day)
   AND d_date_sk = ws_sold_date_sk
   GROUP BY ws_item_sk) t
WHERE i_manufact_id = 350
AND i_item_sk = ws_item_sk
AND t.t_item_sk = ws_item_sk
AND d_date BETWEEN cast('2000-01-27' AS date)
              AND (cast('2000-01-27' AS date) + INTERVAL '90' day)
AND d_date_sk = ws_sold_date_sk
AND ws_ext_discount_amt > t.thresh
ORDER BY excess_discount_amount
LIMIT 100
"""

# ---------------------------------------------------------------------------
# round-3 breadth batch A: set operations (INTERSECT/EXCEPT), ROLLUP +
# grouping(), cross-joined single-row aggregates, simple CASE. Spelling
# adaptations (semantics-preserving, noted per query): set-op cores are
# flat (no parenthesized SELECTs), and expression equi-joins pre-project
# their key (substr'd zips in q8, the week_seq offset in q2) because the
# planner joins on columns — the rewrite Spark's optimizer performs with
# ProjectExec before the join.

TPCDS_SQL["q2"] = """
WITH wscs AS (
  SELECT ws_sold_date_sk AS sold_date_sk,
         ws_ext_sales_price AS sales_price FROM web_sales
  UNION ALL
  SELECT cs_sold_date_sk AS sold_date_sk,
         cs_ext_sales_price AS sales_price FROM catalog_sales),
wswscs AS (
  SELECT d_week_seq,
    sum(CASE WHEN d_day_name = 'Sunday' THEN sales_price ELSE null END)
      AS sun_sales,
    sum(CASE WHEN d_day_name = 'Monday' THEN sales_price ELSE null END)
      AS mon_sales,
    sum(CASE WHEN d_day_name = 'Tuesday' THEN sales_price ELSE null END)
      AS tue_sales,
    sum(CASE WHEN d_day_name = 'Wednesday' THEN sales_price ELSE null
        END) AS wed_sales,
    sum(CASE WHEN d_day_name = 'Thursday' THEN sales_price ELSE null
        END) AS thu_sales,
    sum(CASE WHEN d_day_name = 'Friday' THEN sales_price ELSE null END)
      AS fri_sales,
    sum(CASE WHEN d_day_name = 'Saturday' THEN sales_price ELSE null
        END) AS sat_sales
  FROM wscs, date_dim WHERE d_date_sk = sold_date_sk
  GROUP BY d_week_seq)
SELECT d_week_seq1, round(sun_sales1 / sun_sales2, 2) AS r_sun,
  round(mon_sales1 / mon_sales2, 2) AS r_mon,
  round(tue_sales1 / tue_sales2, 2) AS r_tue,
  round(wed_sales1 / wed_sales2, 2) AS r_wed,
  round(thu_sales1 / thu_sales2, 2) AS r_thu,
  round(fri_sales1 / fri_sales2, 2) AS r_fri,
  round(sat_sales1 / sat_sales2, 2) AS r_sat
FROM
  (SELECT wswscs.d_week_seq AS d_week_seq1, sun_sales AS sun_sales1,
     mon_sales AS mon_sales1, tue_sales AS tue_sales1,
     wed_sales AS wed_sales1, thu_sales AS thu_sales1,
     fri_sales AS fri_sales1, sat_sales AS sat_sales1
   FROM wswscs, date_dim
   WHERE date_dim.d_week_seq = wswscs.d_week_seq AND d_year = 2001) y,
  (SELECT wswscs.d_week_seq - 53 AS d_week_seq2, sun_sales AS sun_sales2,
     mon_sales AS mon_sales2, tue_sales AS tue_sales2,
     wed_sales AS wed_sales2, thu_sales AS thu_sales2,
     fri_sales AS fri_sales2, sat_sales AS sat_sales2
   FROM wswscs, date_dim
   WHERE date_dim.d_week_seq = wswscs.d_week_seq AND d_year = 2002) z
WHERE d_week_seq1 = d_week_seq2
ORDER BY d_week_seq1
"""

TPCDS_SQL["q8"] = """
SELECT s_store_name, sum(ss_net_profit) AS total
FROM store_sales, date_dim,
  (SELECT s_store_sk, s_store_name, substr(s_zip, 1, 2) AS s_zip2
   FROM store) s,
  (SELECT substr(ca_zip5, 1, 2) AS ca_zip2 FROM
    (SELECT substr(ca_zip, 1, 5) AS ca_zip5 FROM customer_address
     WHERE substr(ca_zip, 1, 5) IN ('85669', '86197', '88274', '83405',
       '86475', '85392', '85460', '80348', '81792')
     INTERSECT
     SELECT substr(ca_zip, 1, 5) AS ca_zip5
     FROM customer_address, customer
     WHERE ca_address_sk = c_current_addr_sk
       AND c_preferred_cust_flag = 'Y'
     GROUP BY substr(ca_zip, 1, 5) HAVING count(*) > 10) A2) v1
WHERE ss_store_sk = s_store_sk AND ss_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 1998 AND s_zip2 = ca_zip2
GROUP BY s_store_name ORDER BY s_store_name LIMIT 100
"""

TPCDS_SQL["q27"] = """
SELECT i_item_id, s_state, grouping(s_state) AS g_state,
  avg(ss_quantity) AS agg1, avg(ss_list_price) AS agg2,
  avg(ss_coupon_amt) AS agg3, avg(ss_sales_price) AS agg4
FROM store_sales, customer_demographics, date_dim, store, item
WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
  AND ss_store_sk = s_store_sk AND ss_cdemo_sk = cd_demo_sk
  AND cd_gender = 'M' AND cd_marital_status = 'S'
  AND cd_education_status = 'College' AND d_year = 2002
  AND s_state = 'TN'
GROUP BY ROLLUP(i_item_id, s_state)
ORDER BY i_item_id, s_state LIMIT 100
"""

TPCDS_SQL["q36"] = """
SELECT sum(ss_net_profit) / sum(ss_ext_sales_price) AS gross_margin,
  i_category, i_class,
  grouping(i_category) + grouping(i_class) AS lochierarchy,
  rank() OVER (
    PARTITION BY grouping(i_category) + grouping(i_class),
      CASE WHEN grouping(i_class) = 0 THEN i_category END
    ORDER BY sum(ss_net_profit) / sum(ss_ext_sales_price) ASC)
    AS rank_within_parent
FROM store_sales, date_dim d1, item, store
WHERE d1.d_year = 2001 AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
  AND s_state IN ('TN', 'TX', 'OH', 'CA')
GROUP BY ROLLUP(i_category, i_class)
ORDER BY lochierarchy DESC,
  CASE WHEN lochierarchy = 0 THEN i_category END,
  rank_within_parent LIMIT 100
"""

TPCDS_SQL["q38"] = """
SELECT count(*) AS num_hot FROM (
  SELECT DISTINCT c_last_name, c_first_name, d_date
  FROM store_sales, date_dim, customer
  WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
    AND store_sales.ss_customer_sk = customer.c_customer_sk
    AND d_month_seq BETWEEN 36 AND 47
  INTERSECT
  SELECT DISTINCT c_last_name, c_first_name, d_date
  FROM catalog_sales, date_dim, customer
  WHERE catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
    AND catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
    AND d_month_seq BETWEEN 36 AND 47
  INTERSECT
  SELECT DISTINCT c_last_name, c_first_name, d_date
  FROM web_sales, date_dim, customer
  WHERE web_sales.ws_sold_date_sk = date_dim.d_date_sk
    AND web_sales.ws_bill_customer_sk = customer.c_customer_sk
    AND d_month_seq BETWEEN 36 AND 47) hot_cust
LIMIT 100
"""

TPCDS_SQL["q58"] = """
WITH ss_items AS (
  SELECT i_item_id AS item_id, sum(ss_ext_sales_price) AS ss_item_rev
  FROM store_sales, item, date_dim
  WHERE ss_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq = (SELECT d_week_seq FROM date_dim
                                       WHERE d_date = '2000-01-03'))
    AND ss_sold_date_sk = d_date_sk
  GROUP BY i_item_id),
cs_items AS (
  SELECT i_item_id AS item_id, sum(cs_ext_sales_price) AS cs_item_rev
  FROM catalog_sales, item, date_dim
  WHERE cs_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq = (SELECT d_week_seq FROM date_dim
                                       WHERE d_date = '2000-01-03'))
    AND cs_sold_date_sk = d_date_sk
  GROUP BY i_item_id),
ws_items AS (
  SELECT i_item_id AS item_id, sum(ws_ext_sales_price) AS ws_item_rev
  FROM web_sales, item, date_dim
  WHERE ws_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq = (SELECT d_week_seq FROM date_dim
                                       WHERE d_date = '2000-01-03'))
    AND ws_sold_date_sk = d_date_sk
  GROUP BY i_item_id)
SELECT ss_items.item_id, ss_item_rev,
  ss_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100
    AS ss_dev,
  cs_item_rev,
  cs_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100
    AS cs_dev,
  ws_item_rev,
  ws_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100
    AS ws_dev,
  (ss_item_rev + cs_item_rev + ws_item_rev) / 3 AS average
FROM ss_items, cs_items, ws_items
WHERE ss_items.item_id = cs_items.item_id
  AND ss_items.item_id = ws_items.item_id
  AND ss_item_rev BETWEEN 0.9 * cs_item_rev AND 1.1 * cs_item_rev
  AND ss_item_rev BETWEEN 0.9 * ws_item_rev AND 1.1 * ws_item_rev
  AND cs_item_rev BETWEEN 0.9 * ss_item_rev AND 1.1 * ss_item_rev
  AND cs_item_rev BETWEEN 0.9 * ws_item_rev AND 1.1 * ws_item_rev
  AND ws_item_rev BETWEEN 0.9 * ss_item_rev AND 1.1 * ss_item_rev
  AND ws_item_rev BETWEEN 0.9 * cs_item_rev AND 1.1 * cs_item_rev
ORDER BY item_id, ss_item_rev LIMIT 100
"""

TPCDS_SQL["q61"] = """
SELECT promotions, total, promotions / total * 100 AS pct
FROM
  (SELECT sum(ss_ext_sales_price) AS promotions
   FROM store_sales, store, promotion, date_dim, customer,
     customer_address, item
   WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
     AND ss_promo_sk = p_promo_sk AND ss_customer_sk = c_customer_sk
     AND ca_address_sk = c_current_addr_sk AND ss_item_sk = i_item_sk
     AND ca_gmt_offset = -5.0 AND i_category = 'Jewelry'
     AND (p_channel_dmail = 'Y' OR p_channel_email = 'Y'
          OR p_channel_tv = 'Y')
     AND s_gmt_offset = -5.0 AND d_year = 1998 AND d_moy = 11)
   promotional_sales,
  (SELECT sum(ss_ext_sales_price) AS total
   FROM store_sales, store, date_dim, customer, customer_address, item
   WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
     AND ss_customer_sk = c_customer_sk
     AND ca_address_sk = c_current_addr_sk AND ss_item_sk = i_item_sk
     AND ca_gmt_offset = -5.0 AND i_category = 'Jewelry'
     AND s_gmt_offset = -5.0 AND d_year = 1998 AND d_moy = 11)
   all_sales
ORDER BY promotions, total LIMIT 100
"""

TPCDS_SQL["q63"] = """
SELECT * FROM
  (SELECT i_manager_id, sum(ss_sales_price) AS sum_sales,
     avg(sum(ss_sales_price)) OVER (PARTITION BY i_manager_id)
       AS avg_monthly_sales
   FROM item, store_sales, date_dim, store
   WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
     AND ss_store_sk = s_store_sk
     AND d_month_seq IN (36, 37, 38, 39, 40, 41, 42, 43, 44, 45, 46, 47)
     AND (i_category IN ('Books', 'Children', 'Electronics')
            AND i_class IN ('class1', 'class2', 'class3')
          OR i_category IN ('Women', 'Music', 'Men')
            AND i_class IN ('class4', 'class5', 'class6'))
   GROUP BY i_manager_id, d_moy) tmp1
WHERE CASE WHEN avg_monthly_sales > 0
           THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           ELSE null END > 0.1
ORDER BY i_manager_id, avg_monthly_sales, sum_sales LIMIT 100
"""

TPCDS_SQL["q70"] = """
SELECT sum(ss_net_profit) AS total_sum, s_state, s_county,
  grouping(s_state) + grouping(s_county) AS lochierarchy,
  rank() OVER (
    PARTITION BY grouping(s_state) + grouping(s_county),
      CASE WHEN grouping(s_county) = 0 THEN s_state END
    ORDER BY sum(ss_net_profit) DESC) AS rank_within_parent
FROM store_sales, date_dim d1, store
WHERE d1.d_month_seq BETWEEN 36 AND 47
  AND d1.d_date_sk = ss_sold_date_sk AND s_store_sk = ss_store_sk
  AND s_state IN (SELECT s_state FROM
    (SELECT s_state, rank() OVER (PARTITION BY s_state
       ORDER BY sum(ss_net_profit) DESC) AS ranking
     FROM store_sales, store, date_dim
     WHERE d_month_seq BETWEEN 36 AND 47
       AND d_date_sk = ss_sold_date_sk AND s_store_sk = ss_store_sk
     GROUP BY s_state, s_county) tmp1
    WHERE ranking <= 5)
GROUP BY ROLLUP(s_state, s_county)
ORDER BY lochierarchy DESC,
  CASE WHEN lochierarchy = 0 THEN s_state END,
  rank_within_parent LIMIT 100
"""

TPCDS_SQL["q87"] = """
SELECT count(*) AS num_cool FROM (
  SELECT DISTINCT c_last_name, c_first_name, d_date
  FROM store_sales, date_dim, customer
  WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
    AND store_sales.ss_customer_sk = customer.c_customer_sk
    AND d_month_seq BETWEEN 36 AND 47
  EXCEPT
  SELECT DISTINCT c_last_name, c_first_name, d_date
  FROM catalog_sales, date_dim, customer
  WHERE catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
    AND catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
    AND d_month_seq BETWEEN 36 AND 47
  EXCEPT
  SELECT DISTINCT c_last_name, c_first_name, d_date
  FROM web_sales, date_dim, customer
  WHERE web_sales.ws_sold_date_sk = date_dim.d_date_sk
    AND web_sales.ws_bill_customer_sk = customer.c_customer_sk
    AND d_month_seq BETWEEN 36 AND 47) cool_cust
"""

# ---------------------------------------------------------------------------
# round-3 breadth batch B: correlated [NOT] EXISTS, year-over-year CTE
# self-joins, deep ROLLUPs, HAVING-level scalar subqueries. Adaptations:
# "OR EXISTS"/OR'd IN-subqueries become IN over a UNION ALL of the two
# channels (q10/q35 — same rows, Spark plans an ExistenceJoin);
# correlated scalar subqueries are hand-decorrelated through a grouped
# CTE + join (q30/q81, the q1 precedent); q41's correlated count(*) > 0
# is spelled as IN; q45's OR'd item subquery is spelled over i_item_sk
# (ids are unique per sk in this datagen).

TPCDS_SQL["q4"] = """
WITH year_total AS (
  SELECT c_customer_id AS customer_id, c_first_name AS customer_first_name,
    c_last_name AS customer_last_name,
    c_preferred_cust_flag AS customer_preferred_cust_flag,
    c_birth_country AS customer_birth_country,
    c_login AS customer_login, c_email_address AS customer_email_address,
    d_year AS dyear,
    sum(((ss_ext_list_price - ss_ext_wholesale_cost - ss_ext_discount_amt)
         + ss_ext_sales_price) / 2) AS year_total, 's' AS sale_type
  FROM customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk AND ss_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name,
    c_preferred_cust_flag, c_birth_country, c_login, c_email_address,
    d_year
  UNION ALL
  SELECT c_customer_id AS customer_id, c_first_name AS customer_first_name,
    c_last_name AS customer_last_name,
    c_preferred_cust_flag AS customer_preferred_cust_flag,
    c_birth_country AS customer_birth_country,
    c_login AS customer_login, c_email_address AS customer_email_address,
    d_year AS dyear,
    sum(((cs_ext_list_price - cs_ext_wholesale_cost - cs_ext_discount_amt)
         + cs_ext_sales_price) / 2) AS year_total, 'c' AS sale_type
  FROM customer, catalog_sales, date_dim
  WHERE c_customer_sk = cs_bill_customer_sk
    AND cs_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name,
    c_preferred_cust_flag, c_birth_country, c_login, c_email_address,
    d_year
  UNION ALL
  SELECT c_customer_id AS customer_id, c_first_name AS customer_first_name,
    c_last_name AS customer_last_name,
    c_preferred_cust_flag AS customer_preferred_cust_flag,
    c_birth_country AS customer_birth_country,
    c_login AS customer_login, c_email_address AS customer_email_address,
    d_year AS dyear,
    sum(((ws_ext_list_price - ws_ext_wholesale_cost - ws_ext_discount_amt)
         + ws_ext_sales_price) / 2) AS year_total, 'w' AS sale_type
  FROM customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk
    AND ws_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name,
    c_preferred_cust_flag, c_birth_country, c_login, c_email_address,
    d_year)
SELECT t_s_secyear.customer_id, t_s_secyear.customer_first_name,
  t_s_secyear.customer_last_name, t_s_secyear.customer_email_address
FROM year_total t_s_firstyear, year_total t_s_secyear,
  year_total t_c_firstyear, year_total t_c_secyear,
  year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_c_secyear.customer_id
  AND t_s_firstyear.customer_id = t_c_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.sale_type = 's' AND t_c_firstyear.sale_type = 'c'
  AND t_w_firstyear.sale_type = 'w' AND t_s_secyear.sale_type = 's'
  AND t_c_secyear.sale_type = 'c' AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.dyear = 2001 AND t_s_secyear.dyear = 2001 + 1
  AND t_c_firstyear.dyear = 2001 AND t_c_secyear.dyear = 2001 + 1
  AND t_w_firstyear.dyear = 2001 AND t_w_secyear.dyear = 2001 + 1
  AND t_s_firstyear.year_total > 0 AND t_c_firstyear.year_total > 0
  AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_c_firstyear.year_total > 0
           THEN t_c_secyear.year_total / t_c_firstyear.year_total
           ELSE null END
      > CASE WHEN t_s_firstyear.year_total > 0
             THEN t_s_secyear.year_total / t_s_firstyear.year_total
             ELSE null END
  AND CASE WHEN t_c_firstyear.year_total > 0
           THEN t_c_secyear.year_total / t_c_firstyear.year_total
           ELSE null END
      > CASE WHEN t_w_firstyear.year_total > 0
             THEN t_w_secyear.year_total / t_w_firstyear.year_total
             ELSE null END
ORDER BY t_s_secyear.customer_id, t_s_secyear.customer_first_name,
  t_s_secyear.customer_last_name, t_s_secyear.customer_email_address
LIMIT 100
"""

TPCDS_SQL["q10"] = """
SELECT cd_gender, cd_marital_status, cd_education_status,
  count(*) AS cnt1, cd_purchase_estimate, count(*) AS cnt2,
  cd_credit_rating, count(*) AS cnt3, cd_dep_count, count(*) AS cnt4,
  cd_dep_employed_count, count(*) AS cnt5, cd_dep_college_count,
  count(*) AS cnt6
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND ca_county IN ('Williamson County', 'Franklin Parish',
                    'Bronx County')
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT * FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk AND d_year = 2002
                AND d_moy BETWEEN 1 AND 4)
  AND c.c_customer_sk IN
    (SELECT ws_bill_customer_sk FROM web_sales, date_dim
     WHERE ws_sold_date_sk = d_date_sk AND d_year = 2002
       AND d_moy BETWEEN 1 AND 4
     UNION ALL
     SELECT cs_ship_customer_sk FROM catalog_sales, date_dim
     WHERE cs_sold_date_sk = d_date_sk AND d_year = 2002
       AND d_moy BETWEEN 1 AND 4)
GROUP BY cd_gender, cd_marital_status, cd_education_status,
  cd_purchase_estimate, cd_credit_rating, cd_dep_count,
  cd_dep_employed_count, cd_dep_college_count
ORDER BY cd_gender, cd_marital_status, cd_education_status,
  cd_purchase_estimate, cd_credit_rating, cd_dep_count,
  cd_dep_employed_count, cd_dep_college_count
LIMIT 100
"""

TPCDS_SQL["q11"] = """
WITH year_total AS (
  SELECT c_customer_id AS customer_id, c_first_name AS customer_first_name,
    c_last_name AS customer_last_name,
    c_preferred_cust_flag AS customer_preferred_cust_flag,
    c_birth_country AS customer_birth_country,
    c_login AS customer_login, c_email_address AS customer_email_address,
    d_year AS dyear,
    sum(ss_ext_list_price - ss_ext_discount_amt) AS year_total,
    's' AS sale_type
  FROM customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk AND ss_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name,
    c_preferred_cust_flag, c_birth_country, c_login, c_email_address,
    d_year
  UNION ALL
  SELECT c_customer_id AS customer_id, c_first_name AS customer_first_name,
    c_last_name AS customer_last_name,
    c_preferred_cust_flag AS customer_preferred_cust_flag,
    c_birth_country AS customer_birth_country,
    c_login AS customer_login, c_email_address AS customer_email_address,
    d_year AS dyear,
    sum(ws_ext_list_price - ws_ext_discount_amt) AS year_total,
    'w' AS sale_type
  FROM customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk
    AND ws_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name,
    c_preferred_cust_flag, c_birth_country, c_login, c_email_address,
    d_year)
SELECT t_s_secyear.customer_id, t_s_secyear.customer_first_name,
  t_s_secyear.customer_last_name,
  t_s_secyear.customer_preferred_cust_flag
FROM year_total t_s_firstyear, year_total t_s_secyear,
  year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.sale_type = 's' AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's' AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.dyear = 2001 AND t_s_secyear.dyear = 2001 + 1
  AND t_w_firstyear.dyear = 2001 AND t_w_secyear.dyear = 2001 + 1
  AND t_s_firstyear.year_total > 0 AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_w_firstyear.year_total > 0
           THEN t_w_secyear.year_total / t_w_firstyear.year_total
           ELSE 0.0 END
      > CASE WHEN t_s_firstyear.year_total > 0
             THEN t_s_secyear.year_total / t_s_firstyear.year_total
             ELSE 0.0 END
ORDER BY t_s_secyear.customer_id, t_s_secyear.customer_first_name,
  t_s_secyear.customer_last_name,
  t_s_secyear.customer_preferred_cust_flag
LIMIT 100
"""

TPCDS_SQL["q17"] = """
SELECT i_item_id, i_item_desc, s_state,
  count(ss_quantity) AS store_sales_quantitycount,
  avg(ss_quantity) AS store_sales_quantityave,
  stddev_samp(ss_quantity) AS store_sales_quantitystdev,
  count(sr_return_quantity) AS store_returns_quantitycount,
  avg(sr_return_quantity) AS store_returns_quantityave,
  stddev_samp(sr_return_quantity) AS store_returns_quantitystdev,
  count(cs_quantity) AS catalog_sales_quantitycount,
  avg(cs_quantity) AS catalog_sales_quantityave,
  stddev_samp(cs_quantity) AS catalog_sales_quantitystdev
FROM store_sales, store_returns, catalog_sales, date_dim d1,
  date_dim d2, date_dim d3, store, item
WHERE d1.d_quarter_name = '2001Q1' AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_quarter_name IN ('2001Q1', '2001Q2', '2001Q3')
  AND sr_customer_sk = cs_bill_customer_sk AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_quarter_name IN ('2001Q1', '2001Q2', '2001Q3')
GROUP BY i_item_id, i_item_desc, s_state
ORDER BY i_item_id, i_item_desc, s_state LIMIT 100
"""

TPCDS_SQL["q18"] = """
SELECT i_item_id, ca_country, ca_state, ca_county,
  avg(cast(cs_quantity AS double)) AS agg1,
  avg(cast(cs_list_price AS double)) AS agg2,
  avg(cast(cs_coupon_amt AS double)) AS agg3,
  avg(cast(cs_sales_price AS double)) AS agg4,
  avg(cast(cs_net_profit AS double)) AS agg5,
  avg(cast(c_birth_year AS double)) AS agg6,
  avg(cast(cd1.cd_dep_count AS double)) AS agg7
FROM catalog_sales, customer_demographics cd1,
  customer_demographics cd2, customer, customer_address, date_dim, item
WHERE cs_sold_date_sk = d_date_sk AND cs_item_sk = i_item_sk
  AND cs_bill_cdemo_sk = cd1.cd_demo_sk
  AND cs_bill_customer_sk = c_customer_sk
  AND cd1.cd_gender = 'F' AND cd1.cd_education_status = 'Unknown'
  AND c_current_cdemo_sk = cd2.cd_demo_sk
  AND c_current_addr_sk = ca_address_sk
  AND c_birth_month IN (1, 6, 8, 9, 12, 2) AND d_year = 1998
  AND ca_state IN ('KY', 'GA', 'NM', 'MT', 'OR', 'IN', 'WI')
GROUP BY ROLLUP(i_item_id, ca_country, ca_state, ca_county)
ORDER BY ca_country, ca_state, ca_county, i_item_id LIMIT 100
"""

TPCDS_SQL["q22"] = """
SELECT i_product_name, i_brand, i_class, i_category,
  avg(inv_quantity_on_hand) AS qoh
FROM inventory, date_dim, item
WHERE inv_date_sk = d_date_sk AND inv_item_sk = i_item_sk
  AND d_month_seq BETWEEN 36 AND 47
GROUP BY ROLLUP(i_product_name, i_brand, i_class, i_category)
ORDER BY qoh, i_product_name, i_brand, i_class, i_category LIMIT 100
"""

TPCDS_SQL["q26"] = """
SELECT i_item_id, avg(cs_quantity) AS agg1, avg(cs_list_price) AS agg2,
  avg(cs_coupon_amt) AS agg3, avg(cs_sales_price) AS agg4
FROM catalog_sales, customer_demographics, date_dim, item, promotion
WHERE cs_sold_date_sk = d_date_sk AND cs_item_sk = i_item_sk
  AND cs_bill_cdemo_sk = cd_demo_sk AND cs_promo_sk = p_promo_sk
  AND cd_gender = 'M' AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id ORDER BY i_item_id LIMIT 100
"""

TPCDS_SQL["q30"] = """
WITH customer_total_return AS (
  SELECT wr_returning_customer_sk AS ctr_customer_sk,
    ca_state AS ctr_state, sum(wr_return_amt) AS ctr_total_return
  FROM web_returns, date_dim, customer_address
  WHERE wr_returned_date_sk = d_date_sk AND d_year = 2002
    AND wr_returning_addr_sk = ca_address_sk
  GROUP BY wr_returning_customer_sk, ca_state),
state_avg AS (
  SELECT ctr_state AS avg_state, avg(ctr_total_return) * 1.2 AS thresh
  FROM customer_total_return GROUP BY ctr_state)
SELECT c_customer_id, c_salutation, c_first_name, c_last_name,
  c_preferred_cust_flag, c_birth_day, c_birth_month, c_birth_year,
  c_birth_country, c_login, c_email_address, ctr_total_return
FROM customer_total_return ctr1, state_avg, customer, customer_address
WHERE ctr1.ctr_state = state_avg.avg_state
  AND ctr1.ctr_total_return > state_avg.thresh
  AND ca_state = 'GA' AND ca_address_sk = c_current_addr_sk
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id, c_salutation, c_first_name, c_last_name,
  c_preferred_cust_flag, c_birth_day, c_birth_month, c_birth_year,
  c_birth_country, c_login, c_email_address, ctr_total_return
LIMIT 100
"""

TPCDS_SQL["q35"] = """
SELECT ca_state, cd_gender, cd_marital_status, cd_dep_count,
  count(*) AS cnt1, avg(cd_dep_count) AS a1, max(cd_dep_count) AS m1,
  sum(cd_dep_count) AS s1, cd_dep_employed_count, count(*) AS cnt2,
  avg(cd_dep_employed_count) AS a2, max(cd_dep_employed_count) AS m2,
  sum(cd_dep_employed_count) AS s2, cd_dep_college_count,
  count(*) AS cnt3, avg(cd_dep_college_count) AS a3,
  max(cd_dep_college_count) AS m3, sum(cd_dep_college_count) AS s3
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT * FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk AND d_year = 2002
                AND d_qoy < 4)
  AND c.c_customer_sk IN
    (SELECT ws_bill_customer_sk FROM web_sales, date_dim
     WHERE ws_sold_date_sk = d_date_sk AND d_year = 2002 AND d_qoy < 4
     UNION ALL
     SELECT cs_ship_customer_sk FROM catalog_sales, date_dim
     WHERE cs_sold_date_sk = d_date_sk AND d_year = 2002 AND d_qoy < 4)
GROUP BY ca_state, cd_gender, cd_marital_status, cd_dep_count,
  cd_dep_employed_count, cd_dep_college_count
ORDER BY ca_state, cd_gender, cd_marital_status, cd_dep_count,
  cd_dep_employed_count, cd_dep_college_count
LIMIT 100
"""

TPCDS_SQL["q41"] = """
SELECT DISTINCT i_product_name
FROM item i1
WHERE i_manufact_id BETWEEN 200 AND 800
  AND i_manufact IN
    (SELECT i_manufact FROM item
     WHERE (i_category = 'Women' AND i_color IN ('red', 'blue')
            AND i_units IN ('Each', 'Dozen')
            AND i_size IN ('small', 'petite'))
        OR (i_category = 'Men' AND i_color IN ('green', 'black')
            AND i_units IN ('Case', 'Gross')
            AND i_size IN ('large', 'economy')))
ORDER BY i_product_name LIMIT 100
"""

TPCDS_SQL["q44"] = """
SELECT asceding.rnk, i1.i_product_name AS best_performing,
  i2.i_product_name AS worst_performing
FROM
  (SELECT * FROM
    (SELECT item_sk, rank() OVER (ORDER BY rank_col ASC) AS rnk FROM
      (SELECT ss_item_sk AS item_sk, avg(ss_net_profit) AS rank_col
       FROM store_sales ss1 WHERE ss_store_sk = 1 GROUP BY ss_item_sk
       HAVING avg(ss_net_profit) > 0.9 *
         (SELECT avg(ss_net_profit) AS rank_col FROM store_sales
          WHERE ss_store_sk = 1 AND ss_hdemo_sk IS NULL
          GROUP BY ss_store_sk)) V1) V11
   WHERE rnk < 11) asceding,
  (SELECT * FROM
    (SELECT item_sk, rank() OVER (ORDER BY rank_col DESC) AS rnk FROM
      (SELECT ss_item_sk AS item_sk, avg(ss_net_profit) AS rank_col
       FROM store_sales ss1 WHERE ss_store_sk = 1 GROUP BY ss_item_sk
       HAVING avg(ss_net_profit) > 0.9 *
         (SELECT avg(ss_net_profit) AS rank_col FROM store_sales
          WHERE ss_store_sk = 1 AND ss_hdemo_sk IS NULL
          GROUP BY ss_store_sk)) V2) V21
   WHERE rnk < 11) descending, item i1, item i2
WHERE asceding.rnk = descending.rnk
  AND i1.i_item_sk = asceding.item_sk
  AND i2.i_item_sk = descending.item_sk
ORDER BY asceding.rnk LIMIT 100
"""

TPCDS_SQL["q45"] = """
SELECT ca_zip, ca_city, sum(ws_sales_price) AS total
FROM web_sales, customer, customer_address, date_dim, item
WHERE ws_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk AND ws_item_sk = i_item_sk
  AND ws_sold_date_sk = d_date_sk
  AND (substr(ca_zip, 1, 5) IN ('85669', '86197', '88274', '83405',
         '86475', '85392', '85460', '80348', '81792')
       OR i_item_sk IN (2, 3, 5, 7, 11, 13, 17, 19, 23, 29))
  AND d_qoy = 2 AND d_year = 2001
GROUP BY ca_zip, ca_city ORDER BY ca_zip, ca_city LIMIT 100
"""

TPCDS_SQL["q47"] = """
WITH v1 AS (
  SELECT i_category, i_brand, s_store_name, s_company_name, d_year,
    d_moy, sum(ss_sales_price) AS sum_sales,
    avg(sum(ss_sales_price)) OVER (PARTITION BY i_category, i_brand,
      s_store_name, s_company_name, d_year) AS avg_monthly_sales,
    rank() OVER (PARTITION BY i_category, i_brand, s_store_name,
      s_company_name ORDER BY d_year, d_moy) AS rn
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND (d_year = 2000 OR (d_year = 1999 AND d_moy = 12)
         OR (d_year = 2001 AND d_moy = 1))
  GROUP BY i_category, i_brand, s_store_name, s_company_name, d_year,
    d_moy),
v2 AS (
  SELECT v1.i_category, v1.i_brand, v1.s_store_name, v1.s_company_name,
    v1.d_year, v1.d_moy, v1.avg_monthly_sales, v1.sum_sales,
    v1_lag.sum_sales AS psum, v1_lead.sum_sales AS nsum
  FROM v1, v1 v1_lag, v1 v1_lead
  WHERE v1.i_category = v1_lag.i_category
    AND v1.i_brand = v1_lag.i_brand
    AND v1.s_store_name = v1_lag.s_store_name
    AND v1.s_company_name = v1_lag.s_company_name
    AND v1.i_category = v1_lead.i_category
    AND v1.i_brand = v1_lead.i_brand
    AND v1.s_store_name = v1_lead.s_store_name
    AND v1.s_company_name = v1_lead.s_company_name
    AND v1.rn = v1_lag.rn + 1 AND v1.rn = v1_lead.rn - 1)
SELECT * FROM v2
WHERE d_year = 2000 AND avg_monthly_sales > 0
  AND CASE WHEN avg_monthly_sales > 0
           THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           ELSE null END > 0.1
ORDER BY sum_sales - avg_monthly_sales, s_store_name LIMIT 100
"""

TPCDS_SQL["q56"] = """
WITH ss AS (
  SELECT i_item_id, sum(ss_ext_sales_price) AS total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('slate', 'blue', 'red'))
    AND ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 2 AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5.0
  GROUP BY i_item_id),
cs AS (
  SELECT i_item_id, sum(cs_ext_sales_price) AS total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('slate', 'blue', 'red'))
    AND cs_item_sk = i_item_sk AND cs_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 2 AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5.0
  GROUP BY i_item_id),
ws AS (
  SELECT i_item_id, sum(ws_ext_sales_price) AS total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('slate', 'blue', 'red'))
    AND ws_item_sk = i_item_sk AND ws_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 2 AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5.0
  GROUP BY i_item_id)
SELECT i_item_id, sum(total_sales) AS total_sales
FROM (SELECT * FROM ss UNION ALL SELECT * FROM cs
      UNION ALL SELECT * FROM ws) tmp1
GROUP BY i_item_id ORDER BY total_sales, i_item_id LIMIT 100
"""

TPCDS_SQL["q57"] = """
WITH v1 AS (
  SELECT i_category, i_brand, cc_name, d_year, d_moy,
    sum(cs_sales_price) AS sum_sales,
    avg(sum(cs_sales_price)) OVER (PARTITION BY i_category, i_brand,
      cc_name, d_year) AS avg_monthly_sales,
    rank() OVER (PARTITION BY i_category, i_brand, cc_name
      ORDER BY d_year, d_moy) AS rn
  FROM item, catalog_sales, date_dim, call_center
  WHERE cs_item_sk = i_item_sk AND cs_sold_date_sk = d_date_sk
    AND cc_call_center_sk = cs_call_center_sk
    AND (d_year = 2000 OR (d_year = 1999 AND d_moy = 12)
         OR (d_year = 2001 AND d_moy = 1))
  GROUP BY i_category, i_brand, cc_name, d_year, d_moy),
v2 AS (
  SELECT v1.i_category, v1.i_brand, v1.cc_name, v1.d_year, v1.d_moy,
    v1.avg_monthly_sales, v1.sum_sales, v1_lag.sum_sales AS psum,
    v1_lead.sum_sales AS nsum
  FROM v1, v1 v1_lag, v1 v1_lead
  WHERE v1.i_category = v1_lag.i_category
    AND v1.i_brand = v1_lag.i_brand AND v1.cc_name = v1_lag.cc_name
    AND v1.i_category = v1_lead.i_category
    AND v1.i_brand = v1_lead.i_brand AND v1.cc_name = v1_lead.cc_name
    AND v1.rn = v1_lag.rn + 1 AND v1.rn = v1_lead.rn - 1)
SELECT * FROM v2
WHERE d_year = 2000 AND avg_monthly_sales > 0
  AND CASE WHEN avg_monthly_sales > 0
           THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           ELSE null END > 0.1
ORDER BY sum_sales - avg_monthly_sales, cc_name LIMIT 100
"""

TPCDS_SQL["q67"] = """
SELECT * FROM
  (SELECT i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
     d_moy, s_store_id, sumsales,
     rank() OVER (PARTITION BY i_category
       ORDER BY sumsales DESC) AS rk
   FROM
    (SELECT i_category, i_class, i_brand, i_product_name, d_year,
       d_qoy, d_moy, s_store_id,
       sum(coalesce(ss_sales_price * ss_quantity, 0)) AS sumsales
     FROM store_sales, date_dim, store, item
     WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
       AND ss_store_sk = s_store_sk AND d_month_seq BETWEEN 36 AND 47
     GROUP BY ROLLUP(i_category, i_class, i_brand, i_product_name,
       d_year, d_qoy, d_moy, s_store_id)) dw1) dw2
WHERE rk <= 100
ORDER BY i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
  d_moy, s_store_id, sumsales, rk
LIMIT 100
"""

TPCDS_SQL["q69"] = """
SELECT cd_gender, cd_marital_status, cd_education_status,
  count(*) AS cnt1, cd_purchase_estimate, count(*) AS cnt2,
  cd_credit_rating, count(*) AS cnt3
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND ca_state IN ('KY', 'GA', 'NM')
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT * FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk AND d_year = 2001
                AND d_moy BETWEEN 4 AND 6)
  AND NOT EXISTS (SELECT * FROM web_sales, date_dim
                  WHERE c.c_customer_sk = ws_bill_customer_sk
                    AND ws_sold_date_sk = d_date_sk AND d_year = 2001
                    AND d_moy BETWEEN 4 AND 6)
  AND NOT EXISTS (SELECT * FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_ship_customer_sk
                    AND cs_sold_date_sk = d_date_sk AND d_year = 2001
                    AND d_moy BETWEEN 4 AND 6)
GROUP BY cd_gender, cd_marital_status, cd_education_status,
  cd_purchase_estimate, cd_credit_rating
ORDER BY cd_gender, cd_marital_status, cd_education_status,
  cd_purchase_estimate, cd_credit_rating
LIMIT 100
"""

TPCDS_SQL["q74"] = """
WITH year_total AS (
  SELECT c_customer_id AS customer_id,
    c_first_name AS customer_first_name,
    c_last_name AS customer_last_name, d_year AS dyear,
    sum(ss_net_paid) AS year_total, 's' AS sale_type
  FROM customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk AND ss_sold_date_sk = d_date_sk
    AND d_year IN (2001, 2002)
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year
  UNION ALL
  SELECT c_customer_id AS customer_id,
    c_first_name AS customer_first_name,
    c_last_name AS customer_last_name, d_year AS dyear,
    sum(ws_net_paid) AS year_total, 'w' AS sale_type
  FROM customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk
    AND ws_sold_date_sk = d_date_sk AND d_year IN (2001, 2002)
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year)
SELECT t_s_secyear.customer_id, t_s_secyear.customer_first_name,
  t_s_secyear.customer_last_name
FROM year_total t_s_firstyear, year_total t_s_secyear,
  year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.sale_type = 's' AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's' AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.dyear = 2001 AND t_s_secyear.dyear = 2002
  AND t_w_firstyear.dyear = 2001 AND t_w_secyear.dyear = 2002
  AND t_s_firstyear.year_total > 0 AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_w_firstyear.year_total > 0
           THEN t_w_secyear.year_total / t_w_firstyear.year_total
           ELSE null END
      > CASE WHEN t_s_firstyear.year_total > 0
             THEN t_s_secyear.year_total / t_s_firstyear.year_total
             ELSE null END
ORDER BY t_s_secyear.customer_id, t_s_secyear.customer_first_name,
  t_s_secyear.customer_last_name
LIMIT 100
"""

TPCDS_SQL["q81"] = """
WITH customer_total_return AS (
  SELECT cr_returning_customer_sk AS ctr_customer_sk,
    ca_state AS ctr_state, sum(cr_return_amt_inc_tax) AS ctr_total_return
  FROM catalog_returns, date_dim, customer_address
  WHERE cr_returned_date_sk = d_date_sk AND d_year = 2000
    AND cr_returning_addr_sk = ca_address_sk
  GROUP BY cr_returning_customer_sk, ca_state),
state_avg AS (
  SELECT ctr_state AS avg_state, avg(ctr_total_return) * 1.2 AS thresh
  FROM customer_total_return GROUP BY ctr_state)
SELECT c_customer_id, c_salutation, c_first_name, c_last_name,
  ca_street_number, ca_street_name, ca_street_type, ca_suite_number,
  ca_city, ca_county, ca_state, ca_zip, ca_country, ca_gmt_offset,
  ca_location_type, ctr_total_return
FROM customer_total_return ctr1, state_avg, customer, customer_address
WHERE ctr1.ctr_state = state_avg.avg_state
  AND ctr1.ctr_total_return > state_avg.thresh
  AND ca_state = 'GA' AND ca_address_sk = c_current_addr_sk
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id, c_salutation, c_first_name, c_last_name,
  ca_street_number, ca_street_name, ca_street_type, ca_suite_number,
  ca_city, ca_county, ca_state, ca_zip, ca_country, ca_gmt_offset,
  ca_location_type, ctr_total_return
LIMIT 100
"""

# ---------------------------------------------------------------------------
# round-3 breadth batch C: ship/return chains over the new dimension
# tables (web_site, ship_mode, call_center, income_band), NULL-FK
# slices (q76), channel unions with literal tags, LEFT OUTER returns
# joins. Adaptations: q16/q94's correlated "<>" EXISTS is spelled as IN
# over a HAVING count(DISTINCT warehouse) > 1 group (same order set);
# q95 keeps the spec's ws_wh self-join CTE verbatim.

TPCDS_SQL["q16"] = """
SELECT count(DISTINCT cs_order_number) AS order_count,
  sum(cs_ext_ship_cost) AS total_shipping_cost,
  sum(cs_net_profit) AS total_net_profit
FROM catalog_sales cs1, date_dim, customer_address, call_center
WHERE d_date BETWEEN cast('2002-02-01' AS date)
                 AND (cast('2002-02-01' AS date) + interval '60' day)
  AND cs1.cs_ship_date_sk = d_date_sk
  AND cs1.cs_ship_addr_sk = ca_address_sk AND ca_state = 'GA'
  AND cs1.cs_call_center_sk = cc_call_center_sk
  AND cc_county = 'Williamson County'
  AND cs1.cs_order_number IN
    (SELECT cs_order_number FROM catalog_sales
     GROUP BY cs_order_number
     HAVING count(DISTINCT cs_warehouse_sk) > 1)
  AND NOT EXISTS (SELECT * FROM catalog_returns cr1
                  WHERE cs1.cs_order_number = cr1.cr_order_number)
ORDER BY count(DISTINCT cs_order_number) LIMIT 100
"""

TPCDS_SQL["q31"] = """
WITH ss AS (
  SELECT ca_county, d_qoy, d_year, sum(ss_ext_sales_price) AS store_sales
  FROM store_sales, date_dim, customer_address
  WHERE ss_sold_date_sk = d_date_sk AND ss_addr_sk = ca_address_sk
  GROUP BY ca_county, d_qoy, d_year),
ws AS (
  SELECT ca_county, d_qoy, d_year, sum(ws_ext_sales_price) AS web_sales
  FROM web_sales, date_dim, customer_address
  WHERE ws_sold_date_sk = d_date_sk AND ws_bill_addr_sk = ca_address_sk
  GROUP BY ca_county, d_qoy, d_year)
SELECT ss1.ca_county, ss1.d_year,
  ws2.web_sales / ws1.web_sales AS web_q1_q2_increase,
  ss2.store_sales / ss1.store_sales AS store_q1_q2_increase,
  ws3.web_sales / ws2.web_sales AS web_q2_q3_increase,
  ss3.store_sales / ss2.store_sales AS store_q2_q3_increase
FROM ss ss1, ss ss2, ss ss3, ws ws1, ws ws2, ws ws3
WHERE ss1.d_qoy = 1 AND ss1.d_year = 2000
  AND ss1.ca_county = ss2.ca_county
  AND ss2.d_qoy = 2 AND ss2.d_year = 2000
  AND ss2.ca_county = ss3.ca_county
  AND ss3.d_qoy = 3 AND ss3.d_year = 2000
  AND ss1.ca_county = ws1.ca_county
  AND ws1.d_qoy = 1 AND ws1.d_year = 2000
  AND ws1.ca_county = ws2.ca_county
  AND ws2.d_qoy = 2 AND ws2.d_year = 2000
  AND ws1.ca_county = ws3.ca_county
  AND ws3.d_qoy = 3 AND ws3.d_year = 2000
  AND CASE WHEN ws1.web_sales > 0
           THEN ws2.web_sales / ws1.web_sales ELSE null END
      > CASE WHEN ss1.store_sales > 0
             THEN ss2.store_sales / ss1.store_sales ELSE null END
  AND CASE WHEN ws2.web_sales > 0
           THEN ws3.web_sales / ws2.web_sales ELSE null END
      > CASE WHEN ss2.store_sales > 0
             THEN ss3.store_sales / ss2.store_sales ELSE null END
ORDER BY ss1.ca_county
"""

TPCDS_SQL["q49"] = """
SELECT 'web' AS channel, item, return_ratio, return_rank, currency_rank
FROM
 (SELECT item, return_ratio, currency_ratio,
    rank() OVER (ORDER BY return_ratio) AS return_rank,
    rank() OVER (ORDER BY currency_ratio) AS currency_rank
  FROM
   (SELECT ws_item_sk AS item,
      cast(sum(coalesce(wr_return_quantity, 0)) AS double) /
        cast(sum(coalesce(ws_quantity, 0)) AS double) AS return_ratio,
      cast(sum(coalesce(wr_return_amt, 0)) AS double) /
        cast(sum(coalesce(ws_net_paid, 0)) AS double) AS currency_ratio
    FROM web_sales ws LEFT OUTER JOIN web_returns wr
      ON (ws.ws_order_number = wr.wr_order_number
          AND ws.ws_item_sk = wr.wr_item_sk), date_dim
    WHERE wr_return_amt > 10 AND ws_net_profit > 1
      AND ws_net_paid > 0 AND ws_quantity > 25
      AND ws_sold_date_sk = d_date_sk AND d_year = 2001 AND d_moy = 12
    GROUP BY ws_item_sk) in_web) w
WHERE return_rank <= 10 OR currency_rank <= 10
UNION
SELECT 'catalog' AS channel, item, return_ratio, return_rank,
  currency_rank
FROM
 (SELECT item, return_ratio, currency_ratio,
    rank() OVER (ORDER BY return_ratio) AS return_rank,
    rank() OVER (ORDER BY currency_ratio) AS currency_rank
  FROM
   (SELECT cs_item_sk AS item,
      cast(sum(coalesce(cr_return_quantity, 0)) AS double) /
        cast(sum(coalesce(cs_quantity, 0)) AS double) AS return_ratio,
      cast(sum(coalesce(cr_return_amount, 0)) AS double) /
        cast(sum(coalesce(cs_net_paid, 0)) AS double) AS currency_ratio
    FROM catalog_sales cs LEFT OUTER JOIN catalog_returns cr
      ON (cs.cs_order_number = cr.cr_order_number
          AND cs.cs_item_sk = cr.cr_item_sk), date_dim
    WHERE cr_return_amount > 10 AND cs_net_profit > 1
      AND cs_net_paid > 0 AND cs_quantity > 25
      AND cs_sold_date_sk = d_date_sk AND d_year = 2001 AND d_moy = 12
    GROUP BY cs_item_sk) in_cat) c
WHERE return_rank <= 10 OR currency_rank <= 10
UNION
SELECT 'store' AS channel, item, return_ratio, return_rank,
  currency_rank
FROM
 (SELECT item, return_ratio, currency_ratio,
    rank() OVER (ORDER BY return_ratio) AS return_rank,
    rank() OVER (ORDER BY currency_ratio) AS currency_rank
  FROM
   (SELECT ss_item_sk AS item,
      cast(sum(coalesce(sr_return_quantity, 0)) AS double) /
        cast(sum(coalesce(ss_quantity, 0)) AS double) AS return_ratio,
      cast(sum(coalesce(sr_return_amt, 0)) AS double) /
        cast(sum(coalesce(ss_net_paid, 0)) AS double) AS currency_ratio
    FROM store_sales ss LEFT OUTER JOIN store_returns sr
      ON (ss.ss_ticket_number = sr.sr_ticket_number
          AND ss.ss_item_sk = sr.sr_item_sk), date_dim
    WHERE sr_return_amt > 10 AND ss_net_profit > 1
      AND ss_net_paid > 0 AND ss_quantity > 25
      AND ss_sold_date_sk = d_date_sk AND d_year = 2001 AND d_moy = 12
    GROUP BY ss_item_sk) in_store) s
WHERE return_rank <= 10 OR currency_rank <= 10
ORDER BY 1, 4, 5, item LIMIT 100
"""

TPCDS_SQL["q62"] = """
SELECT substr(w_warehouse_name, 1, 20) AS wname, sm_type, web_name,
  sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk <= 30
      THEN 1 ELSE 0 END) AS d30,
  sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 30
       AND ws_ship_date_sk - ws_sold_date_sk <= 60
      THEN 1 ELSE 0 END) AS d60,
  sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 60
       AND ws_ship_date_sk - ws_sold_date_sk <= 90
      THEN 1 ELSE 0 END) AS d90,
  sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 90
       AND ws_ship_date_sk - ws_sold_date_sk <= 120
      THEN 1 ELSE 0 END) AS d120,
  sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 120
      THEN 1 ELSE 0 END) AS dmore
FROM web_sales, warehouse, ship_mode, web_site, date_dim
WHERE d_month_seq BETWEEN 36 AND 47 AND ws_ship_date_sk = d_date_sk
  AND ws_warehouse_sk = w_warehouse_sk
  AND ws_ship_mode_sk = sm_ship_mode_sk
  AND ws_web_site_sk = web_site_sk
GROUP BY substr(w_warehouse_name, 1, 20), sm_type, web_name
ORDER BY wname, sm_type, web_name LIMIT 100
"""

TPCDS_SQL["q75"] = """
WITH all_sales AS (
  SELECT d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
    sum(sales_cnt) AS sales_cnt, sum(sales_amt) AS sales_amt
  FROM (
    SELECT d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
      cs_quantity - coalesce(cr_return_quantity, 0) AS sales_cnt,
      cs_ext_sales_price - coalesce(cr_return_amount, 0.0) AS sales_amt
    FROM catalog_sales JOIN item ON i_item_sk = cs_item_sk
      JOIN date_dim ON d_date_sk = cs_sold_date_sk
      LEFT JOIN catalog_returns
        ON (cs_order_number = cr_order_number
            AND cs_item_sk = cr_item_sk)
    WHERE i_category = 'Books'
    UNION
    SELECT d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
      ss_quantity - coalesce(sr_return_quantity, 0) AS sales_cnt,
      ss_ext_sales_price - coalesce(sr_return_amt, 0.0) AS sales_amt
    FROM store_sales JOIN item ON i_item_sk = ss_item_sk
      JOIN date_dim ON d_date_sk = ss_sold_date_sk
      LEFT JOIN store_returns
        ON (ss_ticket_number = sr_ticket_number
            AND ss_item_sk = sr_item_sk)
    WHERE i_category = 'Books'
    UNION
    SELECT d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
      ws_quantity - coalesce(wr_return_quantity, 0) AS sales_cnt,
      ws_ext_sales_price - coalesce(wr_return_amt, 0.0) AS sales_amt
    FROM web_sales JOIN item ON i_item_sk = ws_item_sk
      JOIN date_dim ON d_date_sk = ws_sold_date_sk
      LEFT JOIN web_returns
        ON (ws_order_number = wr_order_number
            AND ws_item_sk = wr_item_sk)
    WHERE i_category = 'Books') sales_detail
  GROUP BY d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id)
SELECT prev_yr.d_year AS prev_year, curr_yr.d_year AS year,
  curr_yr.i_brand_id, curr_yr.i_class_id, curr_yr.i_category_id,
  curr_yr.i_manufact_id, prev_yr.sales_cnt AS prev_yr_cnt,
  curr_yr.sales_cnt AS curr_yr_cnt,
  curr_yr.sales_cnt - prev_yr.sales_cnt AS sales_cnt_diff,
  curr_yr.sales_amt - prev_yr.sales_amt AS sales_amt_diff
FROM all_sales curr_yr, all_sales prev_yr
WHERE curr_yr.i_brand_id = prev_yr.i_brand_id
  AND curr_yr.i_class_id = prev_yr.i_class_id
  AND curr_yr.i_category_id = prev_yr.i_category_id
  AND curr_yr.i_manufact_id = prev_yr.i_manufact_id
  AND curr_yr.d_year = 2002 AND prev_yr.d_year = 2001
  AND cast(curr_yr.sales_cnt AS double) /
      cast(prev_yr.sales_cnt AS double) < 0.9
ORDER BY sales_cnt_diff, sales_amt_diff LIMIT 100
"""

TPCDS_SQL["q76"] = """
SELECT channel, col_name, d_year, d_qoy, i_category,
  count(*) AS sales_cnt, sum(ext_sales_price) AS sales_amt FROM (
  SELECT 'store' AS channel, 'ss_store_sk' AS col_name, d_year, d_qoy,
    i_category, ss_ext_sales_price AS ext_sales_price
  FROM store_sales, item, date_dim
  WHERE ss_store_sk IS NULL AND ss_sold_date_sk = d_date_sk
    AND ss_item_sk = i_item_sk
  UNION ALL
  SELECT 'web' AS channel, 'ws_ship_customer_sk' AS col_name, d_year,
    d_qoy, i_category, ws_ext_sales_price AS ext_sales_price
  FROM web_sales, item, date_dim
  WHERE ws_ship_customer_sk IS NULL AND ws_sold_date_sk = d_date_sk
    AND ws_item_sk = i_item_sk
  UNION ALL
  SELECT 'catalog' AS channel, 'cs_ship_addr_sk' AS col_name, d_year,
    d_qoy, i_category, cs_ext_sales_price AS ext_sales_price
  FROM catalog_sales, item, date_dim
  WHERE cs_ship_addr_sk IS NULL AND cs_sold_date_sk = d_date_sk
    AND cs_item_sk = i_item_sk) foo
GROUP BY channel, col_name, d_year, d_qoy, i_category
ORDER BY channel, col_name, d_year, d_qoy, i_category LIMIT 100
"""

TPCDS_SQL["q84"] = """
SELECT c_customer_id AS customer_id,
  coalesce(c_last_name, '') || ', ' || coalesce(c_first_name, '')
    AS customername
FROM customer, customer_address, customer_demographics,
  household_demographics, income_band, store_returns
WHERE ca_city = 'Fairview' AND c_current_addr_sk = ca_address_sk
  AND ib_lower_bound >= 30000 AND ib_upper_bound <= 50000
  AND ib_income_band_sk = hd_income_band_sk
  AND cd_demo_sk = c_current_cdemo_sk
  AND hd_demo_sk = c_current_hdemo_sk AND sr_cdemo_sk = cd_demo_sk
ORDER BY c_customer_id LIMIT 100
"""

TPCDS_SQL["q85"] = """
SELECT substr(r_reason_desc, 1, 20) AS rdesc, avg(ws_quantity) AS aq,
  avg(wr_refunded_cash) AS arc, avg(wr_fee) AS af
FROM web_sales, web_returns, web_page, customer_demographics cd1,
  customer_demographics cd2, customer_address, date_dim, reason
WHERE ws_web_page_sk = wp_web_page_sk AND ws_item_sk = wr_item_sk
  AND ws_order_number = wr_order_number AND ws_sold_date_sk = d_date_sk
  AND d_year = 2000 AND cd1.cd_demo_sk = wr_refunded_cdemo_sk
  AND cd2.cd_demo_sk = wr_returning_cdemo_sk
  AND ca_address_sk = wr_refunded_addr_sk AND r_reason_sk = wr_reason_sk
  AND ((cd1.cd_marital_status = 'M'
        AND cd1.cd_marital_status = cd2.cd_marital_status
        AND cd1.cd_education_status = 'Advanced Degree'
        AND cd1.cd_education_status = cd2.cd_education_status
        AND ws_sales_price BETWEEN 100.0 AND 150.0)
    OR (cd1.cd_marital_status = 'S'
        AND cd1.cd_marital_status = cd2.cd_marital_status
        AND cd1.cd_education_status = 'College'
        AND cd1.cd_education_status = cd2.cd_education_status
        AND ws_sales_price BETWEEN 50.0 AND 100.0)
    OR (cd1.cd_marital_status = 'W'
        AND cd1.cd_marital_status = cd2.cd_marital_status
        AND cd1.cd_education_status = '2 yr Degree'
        AND cd1.cd_education_status = cd2.cd_education_status
        AND ws_sales_price BETWEEN 150.0 AND 200.0))
  AND ((ca_country = 'United States'
        AND ca_state IN ('IN', 'OH', 'NM')
        AND ws_net_profit BETWEEN 100 AND 200)
    OR (ca_country = 'United States'
        AND ca_state IN ('WI', 'CA', 'TX')
        AND ws_net_profit BETWEEN 50 AND 120)
    OR (ca_country = 'United States'
        AND ca_state IN ('KY', 'GA', 'NY')
        AND ws_net_profit BETWEEN 0 AND 150))
GROUP BY r_reason_desc
ORDER BY rdesc, aq, arc, af LIMIT 100
"""

TPCDS_SQL["q91"] = """
SELECT cc_call_center_id AS call_center, cc_name AS call_center_name,
  cc_manager AS manager, sum(cr_net_loss) AS returns_loss
FROM call_center, catalog_returns, date_dim, customer,
  customer_address, customer_demographics, household_demographics
WHERE cr_call_center_sk = cc_call_center_sk
  AND cr_returned_date_sk = d_date_sk
  AND cr_returning_customer_sk = c_customer_sk
  AND cd_demo_sk = c_current_cdemo_sk
  AND hd_demo_sk = c_current_hdemo_sk
  AND ca_address_sk = c_current_addr_sk
  AND d_year = 1998 AND d_moy = 11
  AND ((cd_marital_status = 'M' AND cd_education_status = 'Unknown')
    OR (cd_marital_status = 'W'
        AND cd_education_status = 'Advanced Degree'))
  AND hd_buy_potential LIKE 'unknown%' AND ca_gmt_offset = -7.0
GROUP BY cc_call_center_id, cc_name, cc_manager, cd_marital_status,
  cd_education_status
ORDER BY returns_loss DESC
"""

TPCDS_SQL["q94"] = """
SELECT count(DISTINCT ws_order_number) AS order_count,
  sum(ws_ext_ship_cost) AS total_shipping_cost,
  sum(ws_net_profit) AS total_net_profit
FROM web_sales ws1, date_dim, customer_address, web_site
WHERE d_date BETWEEN cast('1999-02-01' AS date)
                 AND (cast('1999-02-01' AS date) + interval '60' day)
  AND ws1.ws_ship_date_sk = d_date_sk
  AND ws1.ws_ship_addr_sk = ca_address_sk AND ca_state = 'CA'
  AND ws1.ws_web_site_sk = web_site_sk AND web_company_name = 'pri'
  AND ws1.ws_order_number IN
    (SELECT ws_order_number FROM web_sales
     GROUP BY ws_order_number
     HAVING count(DISTINCT ws_warehouse_sk) > 1)
  AND NOT EXISTS (SELECT * FROM web_returns wr1
                  WHERE ws1.ws_order_number = wr1.wr_order_number)
ORDER BY count(DISTINCT ws_order_number) LIMIT 100
"""

TPCDS_SQL["q95"] = """
WITH ws_wh AS (
  SELECT ws1.ws_order_number, ws1.ws_warehouse_sk AS wh1,
    ws2.ws_warehouse_sk AS wh2
  FROM web_sales ws1, web_sales ws2
  WHERE ws1.ws_order_number = ws2.ws_order_number
    AND ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
SELECT count(DISTINCT ws_order_number) AS order_count,
  sum(ws_ext_ship_cost) AS total_shipping_cost,
  sum(ws_net_profit) AS total_net_profit
FROM web_sales ws1, date_dim, customer_address, web_site
WHERE d_date BETWEEN cast('1999-02-01' AS date)
                 AND (cast('1999-02-01' AS date) + interval '60' day)
  AND ws1.ws_ship_date_sk = d_date_sk
  AND ws1.ws_ship_addr_sk = ca_address_sk AND ca_state = 'CA'
  AND ws1.ws_web_site_sk = web_site_sk AND web_company_name = 'pri'
  AND ws1.ws_order_number IN (SELECT ws_order_number FROM ws_wh)
  AND ws1.ws_order_number IN
    (SELECT wr_order_number FROM web_returns, ws_wh
     WHERE wr_order_number = ws_wh.ws_order_number)
ORDER BY count(DISTINCT ws_order_number) LIMIT 100
"""

TPCDS_SQL["q99"] = """
SELECT substr(w_warehouse_name, 1, 20) AS wname, sm_type, cc_name,
  sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk <= 30
      THEN 1 ELSE 0 END) AS d30,
  sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 30
       AND cs_ship_date_sk - cs_sold_date_sk <= 60
      THEN 1 ELSE 0 END) AS d60,
  sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 60
       AND cs_ship_date_sk - cs_sold_date_sk <= 90
      THEN 1 ELSE 0 END) AS d90,
  sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 90
       AND cs_ship_date_sk - cs_sold_date_sk <= 120
      THEN 1 ELSE 0 END) AS d120,
  sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 120
      THEN 1 ELSE 0 END) AS dmore
FROM catalog_sales, warehouse, ship_mode, call_center, date_dim
WHERE d_month_seq BETWEEN 36 AND 47 AND cs_ship_date_sk = d_date_sk
  AND cs_warehouse_sk = w_warehouse_sk
  AND cs_ship_mode_sk = sm_ship_mode_sk
  AND cs_call_center_sk = cc_call_center_sk
GROUP BY substr(w_warehouse_name, 1, 20), sm_type, cc_name
ORDER BY wname, sm_type, cc_name LIMIT 100
"""

# ---------------------------------------------------------------------------
# round-3 breadth batch D: channel-union ROLLUPs over sales+returns
# (q5/q77/q80), multi-channel INTERSECT item sets (q14), best-customer
# CTE chains with scalar-sub thresholds (q23/q24), cumulative-window
# FULL OUTER (q51), month-window scalar-sub bounds (q54), 24-way CASE
# pivots (q66), returns deviation (q83). Adaptations: HAVING count
# thresholds scaled to the -like datagen density (q23 cnt > 1 vs the
# spec's > 4 at SF100+); q66 uses cs_net_paid (no *_inc_tax column).

TPCDS_SQL["q5"] = """
WITH ssr AS (
  SELECT s_store_id, sum(sales_price) AS sales, sum(profit) AS profit,
    sum(return_amt) AS returns_, sum(net_loss) AS profit_loss
  FROM (
    SELECT ss_store_sk AS store_sk, ss_sold_date_sk AS date_sk,
      ss_ext_sales_price AS sales_price, ss_net_profit AS profit,
      cast(0 AS double) AS return_amt, cast(0 AS double) AS net_loss
    FROM store_sales
    UNION ALL
    SELECT sr_store_sk, sr_returned_date_sk, cast(0 AS double),
      cast(0 AS double), sr_return_amt, sr_net_loss
    FROM store_returns) salesreturns, date_dim, store
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS date)
                   AND (cast('2000-08-23' AS date) + interval '14' day)
    AND store_sk = s_store_sk
  GROUP BY s_store_id),
csr AS (
  SELECT cp_catalog_page_id, sum(sales_price) AS sales,
    sum(profit) AS profit, sum(return_amt) AS returns_,
    sum(net_loss) AS profit_loss
  FROM (
    SELECT cs_catalog_page_sk AS page_sk, cs_sold_date_sk AS date_sk,
      cs_ext_sales_price AS sales_price, cs_net_profit AS profit,
      cast(0 AS double) AS return_amt, cast(0 AS double) AS net_loss
    FROM catalog_sales
    UNION ALL
    SELECT cr_catalog_page_sk, cr_returned_date_sk, cast(0 AS double),
      cast(0 AS double), cr_return_amount, cr_net_loss
    FROM catalog_returns) salesreturns, date_dim, catalog_page
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS date)
                   AND (cast('2000-08-23' AS date) + interval '14' day)
    AND page_sk = cp_catalog_page_sk
  GROUP BY cp_catalog_page_id),
wsr AS (
  SELECT web_site_id, sum(sales_price) AS sales, sum(profit) AS profit,
    sum(return_amt) AS returns_, sum(net_loss) AS profit_loss
  FROM (
    SELECT ws_web_site_sk AS site_sk, ws_sold_date_sk AS date_sk,
      ws_ext_sales_price AS sales_price, ws_net_profit AS profit,
      cast(0 AS double) AS return_amt, cast(0 AS double) AS net_loss
    FROM web_sales
    UNION ALL
    SELECT ws.ws_web_site_sk, wr_returned_date_sk,
      cast(0 AS double), cast(0 AS double), wr_return_amt, wr_net_loss
    FROM web_returns wr LEFT OUTER JOIN web_sales ws
      ON (wr.wr_item_sk = ws.ws_item_sk
          AND wr.wr_order_number = ws.ws_order_number)) salesreturns,
    date_dim, web_site
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS date)
                   AND (cast('2000-08-23' AS date) + interval '14' day)
    AND site_sk = web_site_sk
  GROUP BY web_site_id)
SELECT channel, id, sum(sales) AS sales, sum(returns_) AS returns_,
  sum(profit) AS profit FROM (
  SELECT 'store channel' AS channel, 'store' || s_store_id AS id,
    sales, returns_, profit - profit_loss AS profit FROM ssr
  UNION ALL
  SELECT 'catalog channel' AS channel,
    'catalog_page' || cp_catalog_page_id AS id, sales, returns_,
    profit - profit_loss AS profit FROM csr
  UNION ALL
  SELECT 'web channel' AS channel, 'web_site' || web_site_id AS id,
    sales, returns_, profit - profit_loss AS profit FROM wsr) x
GROUP BY ROLLUP(channel, id)
ORDER BY channel, id LIMIT 100
"""

TPCDS_SQL["q14"] = """
WITH cross_items AS (
  SELECT i_item_sk AS ss_item_sk FROM item,
   (SELECT iss.i_brand_id AS brand_id, iss.i_class_id AS class_id,
      iss.i_category_id AS category_id
    FROM store_sales, item iss, date_dim d1
    WHERE ss_item_sk = iss.i_item_sk AND ss_sold_date_sk = d1.d_date_sk
      AND d1.d_year BETWEEN 1999 AND 2001
    INTERSECT
    SELECT ics.i_brand_id AS brand_id, ics.i_class_id AS class_id,
      ics.i_category_id AS category_id
    FROM catalog_sales, item ics, date_dim d2
    WHERE cs_item_sk = ics.i_item_sk AND cs_sold_date_sk = d2.d_date_sk
      AND d2.d_year BETWEEN 1999 AND 2001
    INTERSECT
    SELECT iws.i_brand_id AS brand_id, iws.i_class_id AS class_id,
      iws.i_category_id AS category_id
    FROM web_sales, item iws, date_dim d3
    WHERE ws_item_sk = iws.i_item_sk AND ws_sold_date_sk = d3.d_date_sk
      AND d3.d_year BETWEEN 1999 AND 2001) x
  WHERE i_brand_id = brand_id AND i_class_id = class_id
    AND i_category_id = category_id),
avg_sales AS (
  SELECT avg(quantity * list_price) AS average_sales FROM (
    SELECT ss_quantity AS quantity, ss_list_price AS list_price
    FROM store_sales, date_dim
    WHERE ss_sold_date_sk = d_date_sk AND d_year BETWEEN 1999 AND 2001
    UNION ALL
    SELECT cs_quantity AS quantity, cs_list_price AS list_price
    FROM catalog_sales, date_dim
    WHERE cs_sold_date_sk = d_date_sk AND d_year BETWEEN 1999 AND 2001
    UNION ALL
    SELECT ws_quantity AS quantity, ws_list_price AS list_price
    FROM web_sales, date_dim
    WHERE ws_sold_date_sk = d_date_sk
      AND d_year BETWEEN 1999 AND 2001) x)
SELECT channel, i_brand_id, i_class_id, i_category_id,
  sum(sales) AS sum_sales, sum(number_sales) AS sum_number_sales FROM (
  SELECT 'store' AS channel, i_brand_id, i_class_id, i_category_id,
    sum(ss_quantity * ss_list_price) AS sales,
    count(*) AS number_sales
  FROM store_sales, item, date_dim
  WHERE ss_item_sk IN (SELECT ss_item_sk FROM cross_items)
    AND ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 11
  GROUP BY i_brand_id, i_class_id, i_category_id
  HAVING sum(ss_quantity * ss_list_price) >
    (SELECT average_sales FROM avg_sales)
  UNION ALL
  SELECT 'catalog' AS channel, i_brand_id, i_class_id, i_category_id,
    sum(cs_quantity * cs_list_price) AS sales,
    count(*) AS number_sales
  FROM catalog_sales, item, date_dim
  WHERE cs_item_sk IN (SELECT ss_item_sk FROM cross_items)
    AND cs_item_sk = i_item_sk AND cs_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 11
  GROUP BY i_brand_id, i_class_id, i_category_id
  HAVING sum(cs_quantity * cs_list_price) >
    (SELECT average_sales FROM avg_sales)
  UNION ALL
  SELECT 'web' AS channel, i_brand_id, i_class_id, i_category_id,
    sum(ws_quantity * ws_list_price) AS sales,
    count(*) AS number_sales
  FROM web_sales, item, date_dim
  WHERE ws_item_sk IN (SELECT ss_item_sk FROM cross_items)
    AND ws_item_sk = i_item_sk AND ws_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 11
  GROUP BY i_brand_id, i_class_id, i_category_id
  HAVING sum(ws_quantity * ws_list_price) >
    (SELECT average_sales FROM avg_sales)) y
GROUP BY ROLLUP(channel, i_brand_id, i_class_id, i_category_id)
ORDER BY channel, i_brand_id, i_class_id, i_category_id LIMIT 100
"""

TPCDS_SQL["q23"] = """
WITH frequent_ss_items AS (
  SELECT substr(i_item_desc, 1, 30) AS itemdesc, i_item_sk AS item_sk,
    d_date AS solddate, count(*) AS cnt
  FROM store_sales, date_dim, item
  WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
    AND d_year IN (2000, 2001, 2002)
  GROUP BY substr(i_item_desc, 1, 30), i_item_sk, d_date
  HAVING count(*) > 1),
max_store_sales AS (
  SELECT max(csales) AS tpcds_cmax FROM
    (SELECT c_customer_sk, sum(ss_quantity * ss_sales_price) AS csales
     FROM store_sales, customer, date_dim
     WHERE ss_customer_sk = c_customer_sk AND ss_sold_date_sk = d_date_sk
       AND d_year IN (2000, 2001, 2002)
     GROUP BY c_customer_sk) t),
best_ss_customer AS (
  SELECT c_customer_sk, sum(ss_quantity * ss_sales_price) AS ssales
  FROM store_sales, customer
  WHERE ss_customer_sk = c_customer_sk
  GROUP BY c_customer_sk
  HAVING sum(ss_quantity * ss_sales_price) >
    0.5 * (SELECT tpcds_cmax FROM max_store_sales))
SELECT sum(sales) AS total FROM (
  SELECT cs_quantity * cs_list_price AS sales
  FROM catalog_sales, date_dim
  WHERE d_year = 2000 AND d_moy = 5 AND cs_sold_date_sk = d_date_sk
    AND cs_item_sk IN (SELECT item_sk FROM frequent_ss_items)
    AND cs_bill_customer_sk IN
      (SELECT c_customer_sk FROM best_ss_customer)
  UNION ALL
  SELECT ws_quantity * ws_list_price AS sales
  FROM web_sales, date_dim
  WHERE d_year = 2000 AND d_moy = 5 AND ws_sold_date_sk = d_date_sk
    AND ws_item_sk IN (SELECT item_sk FROM frequent_ss_items)
    AND ws_bill_customer_sk IN
      (SELECT c_customer_sk FROM best_ss_customer)) x
LIMIT 100
"""

TPCDS_SQL["q24"] = """
WITH ssales AS (
  SELECT c_last_name, c_first_name, s_store_name, ca_state, s_state,
    i_color, i_current_price, i_manager_id, i_units, i_size,
    sum(ss_net_paid) AS netpaid
  FROM store_sales, store_returns, store, item, customer,
    customer_address
  WHERE ss_ticket_number = sr_ticket_number AND ss_item_sk = sr_item_sk
    AND ss_customer_sk = c_customer_sk AND ss_item_sk = i_item_sk
    AND ss_store_sk = s_store_sk AND c_current_addr_sk = ca_address_sk
    AND c_birth_country <> upper(ca_country) AND s_zip = ca_zip
    AND s_market_id = 8
  GROUP BY c_last_name, c_first_name, s_store_name, ca_state, s_state,
    i_color, i_current_price, i_manager_id, i_units, i_size)
SELECT c_last_name, c_first_name, s_store_name, sum(netpaid) AS paid
FROM ssales WHERE i_color = 'red'
GROUP BY c_last_name, c_first_name, s_store_name
HAVING sum(netpaid) > (SELECT 0.05 * avg(netpaid) FROM ssales)
ORDER BY c_last_name, c_first_name, s_store_name
"""

TPCDS_SQL["q51"] = """
WITH web_v1 AS (
  SELECT ws_item_sk AS item_sk, d_date,
    sum(sum(ws_sales_price)) OVER (PARTITION BY ws_item_sk
      ORDER BY d_date
      ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS cume_sales
  FROM web_sales, date_dim
  WHERE ws_sold_date_sk = d_date_sk AND d_month_seq BETWEEN 36 AND 47
  GROUP BY ws_item_sk, d_date),
store_v1 AS (
  SELECT ss_item_sk AS item_sk, d_date,
    sum(sum(ss_sales_price)) OVER (PARTITION BY ss_item_sk
      ORDER BY d_date
      ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS cume_sales
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk AND d_month_seq BETWEEN 36 AND 47
  GROUP BY ss_item_sk, d_date)
SELECT * FROM (
  SELECT item_sk, d_date, web_sales, store_sales,
    max(web_sales) OVER (PARTITION BY item_sk ORDER BY d_date
      ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
      AS web_cumulative,
    max(store_sales) OVER (PARTITION BY item_sk ORDER BY d_date
      ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
      AS store_cumulative
  FROM (
    SELECT CASE WHEN web.item_sk IS NOT NULL THEN web.item_sk
                ELSE store.item_sk END AS item_sk,
      CASE WHEN web.d_date IS NOT NULL THEN web.d_date
           ELSE store.d_date END AS d_date,
      web.cume_sales AS web_sales, store.cume_sales AS store_sales
    FROM web_v1 web FULL OUTER JOIN store_v1 store
      ON (web.item_sk = store.item_sk
          AND web.d_date = store.d_date)) x) y
WHERE web_cumulative > store_cumulative
ORDER BY item_sk, d_date LIMIT 100
"""

TPCDS_SQL["q54"] = """
WITH my_customers AS (
  SELECT DISTINCT c_customer_sk, c_current_addr_sk
  FROM (SELECT cs_sold_date_sk AS sold_date_sk,
          cs_bill_customer_sk AS customer_sk, cs_item_sk AS item_sk
        FROM catalog_sales
        UNION ALL
        SELECT ws_sold_date_sk AS sold_date_sk,
          ws_bill_customer_sk AS customer_sk, ws_item_sk AS item_sk
        FROM web_sales) cs_or_ws_sales, item, date_dim, customer
  WHERE sold_date_sk = d_date_sk AND item_sk = i_item_sk
    AND i_category = 'Women' AND i_class = 'class1'
    AND c_customer_sk = cs_or_ws_sales.customer_sk
    AND d_moy = 12 AND d_year = 1998),
my_revenue AS (
  SELECT c_customer_sk, sum(ss_ext_sales_price) AS revenue
  FROM my_customers, store_sales, customer_address, store, date_dim
  WHERE c_current_addr_sk = ca_address_sk AND ca_county = s_county
    AND ca_state = s_state AND ss_customer_sk = c_customer_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_month_seq BETWEEN
      (SELECT DISTINCT d_month_seq + 1 FROM date_dim
       WHERE d_year = 1998 AND d_moy = 12)
      AND
      (SELECT DISTINCT d_month_seq + 3 FROM date_dim
       WHERE d_year = 1998 AND d_moy = 12)
  GROUP BY c_customer_sk),
segments AS (
  SELECT cast((revenue / 50) AS int) AS segment FROM my_revenue)
SELECT segment, count(*) AS num_customers, segment * 50 AS segment_base
FROM segments GROUP BY segment
ORDER BY segment, num_customers LIMIT 100
"""

TPCDS_SQL["q66"] = """
SELECT w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
  w_country, ship_carriers, year_, sum(jan_sales) AS jan_sales,
  sum(feb_sales) AS feb_sales, sum(mar_sales) AS mar_sales,
  sum(apr_sales) AS apr_sales, sum(may_sales) AS may_sales,
  sum(jun_sales) AS jun_sales, sum(jul_sales) AS jul_sales,
  sum(aug_sales) AS aug_sales, sum(sep_sales) AS sep_sales,
  sum(oct_sales) AS oct_sales, sum(nov_sales) AS nov_sales,
  sum(dec_sales) AS dec_sales, sum(jan_net) AS jan_net,
  sum(feb_net) AS feb_net, sum(mar_net) AS mar_net,
  sum(apr_net) AS apr_net, sum(may_net) AS may_net,
  sum(jun_net) AS jun_net, sum(jul_net) AS jul_net,
  sum(aug_net) AS aug_net, sum(sep_net) AS sep_net,
  sum(oct_net) AS oct_net, sum(nov_net) AS nov_net,
  sum(dec_net) AS dec_net
FROM (
  SELECT w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
    w_state, w_country, 'DHL,BARIAN' AS ship_carriers,
    d_year AS year_,
    sum(CASE WHEN d_moy = 1 THEN ws_ext_sales_price * ws_quantity
        ELSE 0 END) AS jan_sales,
    sum(CASE WHEN d_moy = 2 THEN ws_ext_sales_price * ws_quantity
        ELSE 0 END) AS feb_sales,
    sum(CASE WHEN d_moy = 3 THEN ws_ext_sales_price * ws_quantity
        ELSE 0 END) AS mar_sales,
    sum(CASE WHEN d_moy = 4 THEN ws_ext_sales_price * ws_quantity
        ELSE 0 END) AS apr_sales,
    sum(CASE WHEN d_moy = 5 THEN ws_ext_sales_price * ws_quantity
        ELSE 0 END) AS may_sales,
    sum(CASE WHEN d_moy = 6 THEN ws_ext_sales_price * ws_quantity
        ELSE 0 END) AS jun_sales,
    sum(CASE WHEN d_moy = 7 THEN ws_ext_sales_price * ws_quantity
        ELSE 0 END) AS jul_sales,
    sum(CASE WHEN d_moy = 8 THEN ws_ext_sales_price * ws_quantity
        ELSE 0 END) AS aug_sales,
    sum(CASE WHEN d_moy = 9 THEN ws_ext_sales_price * ws_quantity
        ELSE 0 END) AS sep_sales,
    sum(CASE WHEN d_moy = 10 THEN ws_ext_sales_price * ws_quantity
        ELSE 0 END) AS oct_sales,
    sum(CASE WHEN d_moy = 11 THEN ws_ext_sales_price * ws_quantity
        ELSE 0 END) AS nov_sales,
    sum(CASE WHEN d_moy = 12 THEN ws_ext_sales_price * ws_quantity
        ELSE 0 END) AS dec_sales,
    sum(CASE WHEN d_moy = 1 THEN ws_net_paid * ws_quantity
        ELSE 0 END) AS jan_net,
    sum(CASE WHEN d_moy = 2 THEN ws_net_paid * ws_quantity
        ELSE 0 END) AS feb_net,
    sum(CASE WHEN d_moy = 3 THEN ws_net_paid * ws_quantity
        ELSE 0 END) AS mar_net,
    sum(CASE WHEN d_moy = 4 THEN ws_net_paid * ws_quantity
        ELSE 0 END) AS apr_net,
    sum(CASE WHEN d_moy = 5 THEN ws_net_paid * ws_quantity
        ELSE 0 END) AS may_net,
    sum(CASE WHEN d_moy = 6 THEN ws_net_paid * ws_quantity
        ELSE 0 END) AS jun_net,
    sum(CASE WHEN d_moy = 7 THEN ws_net_paid * ws_quantity
        ELSE 0 END) AS jul_net,
    sum(CASE WHEN d_moy = 8 THEN ws_net_paid * ws_quantity
        ELSE 0 END) AS aug_net,
    sum(CASE WHEN d_moy = 9 THEN ws_net_paid * ws_quantity
        ELSE 0 END) AS sep_net,
    sum(CASE WHEN d_moy = 10 THEN ws_net_paid * ws_quantity
        ELSE 0 END) AS oct_net,
    sum(CASE WHEN d_moy = 11 THEN ws_net_paid * ws_quantity
        ELSE 0 END) AS nov_net,
    sum(CASE WHEN d_moy = 12 THEN ws_net_paid * ws_quantity
        ELSE 0 END) AS dec_net
  FROM web_sales, warehouse, date_dim, time_dim, ship_mode
  WHERE ws_warehouse_sk = w_warehouse_sk
    AND ws_sold_date_sk = d_date_sk AND ws_sold_time_sk = t_time_sk
    AND ws_ship_mode_sk = sm_ship_mode_sk AND d_year = 2001
    AND t_time BETWEEN 30838 AND 30838 + 28800
    AND sm_carrier IN ('DHL', 'BARIAN')
  GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
    w_state, w_country, d_year
  UNION ALL
  SELECT w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
    w_state, w_country, 'DHL,BARIAN' AS ship_carriers,
    d_year AS year_,
    sum(CASE WHEN d_moy = 1 THEN cs_sales_price * cs_quantity
        ELSE 0 END) AS jan_sales,
    sum(CASE WHEN d_moy = 2 THEN cs_sales_price * cs_quantity
        ELSE 0 END) AS feb_sales,
    sum(CASE WHEN d_moy = 3 THEN cs_sales_price * cs_quantity
        ELSE 0 END) AS mar_sales,
    sum(CASE WHEN d_moy = 4 THEN cs_sales_price * cs_quantity
        ELSE 0 END) AS apr_sales,
    sum(CASE WHEN d_moy = 5 THEN cs_sales_price * cs_quantity
        ELSE 0 END) AS may_sales,
    sum(CASE WHEN d_moy = 6 THEN cs_sales_price * cs_quantity
        ELSE 0 END) AS jun_sales,
    sum(CASE WHEN d_moy = 7 THEN cs_sales_price * cs_quantity
        ELSE 0 END) AS jul_sales,
    sum(CASE WHEN d_moy = 8 THEN cs_sales_price * cs_quantity
        ELSE 0 END) AS aug_sales,
    sum(CASE WHEN d_moy = 9 THEN cs_sales_price * cs_quantity
        ELSE 0 END) AS sep_sales,
    sum(CASE WHEN d_moy = 10 THEN cs_sales_price * cs_quantity
        ELSE 0 END) AS oct_sales,
    sum(CASE WHEN d_moy = 11 THEN cs_sales_price * cs_quantity
        ELSE 0 END) AS nov_sales,
    sum(CASE WHEN d_moy = 12 THEN cs_sales_price * cs_quantity
        ELSE 0 END) AS dec_sales,
    sum(CASE WHEN d_moy = 1 THEN cs_net_paid * cs_quantity
        ELSE 0 END) AS jan_net,
    sum(CASE WHEN d_moy = 2 THEN cs_net_paid * cs_quantity
        ELSE 0 END) AS feb_net,
    sum(CASE WHEN d_moy = 3 THEN cs_net_paid * cs_quantity
        ELSE 0 END) AS mar_net,
    sum(CASE WHEN d_moy = 4 THEN cs_net_paid * cs_quantity
        ELSE 0 END) AS apr_net,
    sum(CASE WHEN d_moy = 5 THEN cs_net_paid * cs_quantity
        ELSE 0 END) AS may_net,
    sum(CASE WHEN d_moy = 6 THEN cs_net_paid * cs_quantity
        ELSE 0 END) AS jun_net,
    sum(CASE WHEN d_moy = 7 THEN cs_net_paid * cs_quantity
        ELSE 0 END) AS jul_net,
    sum(CASE WHEN d_moy = 8 THEN cs_net_paid * cs_quantity
        ELSE 0 END) AS aug_net,
    sum(CASE WHEN d_moy = 9 THEN cs_net_paid * cs_quantity
        ELSE 0 END) AS sep_net,
    sum(CASE WHEN d_moy = 10 THEN cs_net_paid * cs_quantity
        ELSE 0 END) AS oct_net,
    sum(CASE WHEN d_moy = 11 THEN cs_net_paid * cs_quantity
        ELSE 0 END) AS nov_net,
    sum(CASE WHEN d_moy = 12 THEN cs_net_paid * cs_quantity
        ELSE 0 END) AS dec_net
  FROM catalog_sales, warehouse, date_dim, time_dim, ship_mode
  WHERE cs_warehouse_sk = w_warehouse_sk
    AND cs_sold_date_sk = d_date_sk AND cs_sold_time_sk = t_time_sk
    AND cs_ship_mode_sk = sm_ship_mode_sk AND d_year = 2001
    AND t_time BETWEEN 30838 AND 30838 + 28800
    AND sm_carrier IN ('DHL', 'BARIAN')
  GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
    w_state, w_country, d_year) x
GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
  w_state, w_country, ship_carriers, year_
ORDER BY w_warehouse_name LIMIT 100
"""

TPCDS_SQL["q77"] = """
WITH ss AS (
  SELECT s_store_sk, sum(ss_ext_sales_price) AS sales,
    sum(ss_net_profit) AS profit
  FROM store_sales, date_dim, store
  WHERE ss_sold_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS date)
                   AND (cast('2000-08-23' AS date) + interval '30' day)
    AND ss_store_sk = s_store_sk
  GROUP BY s_store_sk),
sr AS (
  SELECT s_store_sk, sum(sr_return_amt) AS returns_,
    sum(sr_net_loss) AS profit_loss
  FROM store_returns, date_dim, store
  WHERE sr_returned_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS date)
                   AND (cast('2000-08-23' AS date) + interval '30' day)
    AND sr_store_sk = s_store_sk
  GROUP BY s_store_sk),
cs AS (
  SELECT cs_call_center_sk, sum(cs_ext_sales_price) AS sales,
    sum(cs_net_profit) AS profit
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS date)
                   AND (cast('2000-08-23' AS date) + interval '30' day)
  GROUP BY cs_call_center_sk),
cr AS (
  SELECT sum(cr_return_amount) AS returns_,
    sum(cr_net_loss) AS profit_loss
  FROM catalog_returns, date_dim
  WHERE cr_returned_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS date)
                   AND (cast('2000-08-23' AS date)
                        + interval '30' day)),
ws AS (
  SELECT wp_web_page_sk, sum(ws_ext_sales_price) AS sales,
    sum(ws_net_profit) AS profit
  FROM web_sales, date_dim, web_page
  WHERE ws_sold_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS date)
                   AND (cast('2000-08-23' AS date) + interval '30' day)
    AND ws_web_page_sk = wp_web_page_sk
  GROUP BY wp_web_page_sk),
wr AS (
  SELECT wp_web_page_sk, sum(wr_return_amt) AS returns_,
    sum(wr_net_loss) AS profit_loss
  FROM web_returns, date_dim, web_page
  WHERE wr_returned_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS date)
                   AND (cast('2000-08-23' AS date) + interval '30' day)
    AND wr_web_page_sk = wp_web_page_sk
  GROUP BY wp_web_page_sk)
SELECT channel, id, sum(sales) AS sales, sum(returns_) AS returns_,
  sum(profit) AS profit FROM (
  SELECT 'store channel' AS channel, ss.s_store_sk AS id, sales,
    coalesce(returns_, 0.0) AS returns_,
    profit - coalesce(profit_loss, 0.0) AS profit
  FROM ss LEFT JOIN sr ON ss.s_store_sk = sr.s_store_sk
  UNION ALL
  SELECT 'catalog channel' AS channel, cs_call_center_sk AS id, sales,
    returns_, profit - profit_loss AS profit
  FROM cs CROSS JOIN cr
  UNION ALL
  SELECT 'web channel' AS channel, ws.wp_web_page_sk AS id, sales,
    coalesce(returns_, 0.0) AS returns_,
    profit - coalesce(profit_loss, 0.0) AS profit
  FROM ws LEFT JOIN wr ON ws.wp_web_page_sk = wr.wp_web_page_sk) x
GROUP BY ROLLUP(channel, id)
ORDER BY channel, id LIMIT 100
"""

TPCDS_SQL["q78"] = """
WITH ws AS (
  SELECT d_year AS ws_sold_year, ws_item_sk,
    ws_bill_customer_sk AS ws_customer_sk, sum(ws_quantity) AS ws_qty,
    sum(ws_wholesale_cost) AS ws_wc, sum(ws_sales_price) AS ws_sp
  FROM web_sales LEFT JOIN web_returns
    ON wr_order_number = ws_order_number AND ws_item_sk = wr_item_sk,
    date_dim
  WHERE wr_order_number IS NULL AND ws_sold_date_sk = d_date_sk
  GROUP BY d_year, ws_item_sk, ws_bill_customer_sk),
cs AS (
  SELECT d_year AS cs_sold_year, cs_item_sk,
    cs_bill_customer_sk AS cs_customer_sk, sum(cs_quantity) AS cs_qty,
    sum(cs_wholesale_cost) AS cs_wc, sum(cs_sales_price) AS cs_sp
  FROM catalog_sales LEFT JOIN catalog_returns
    ON cr_order_number = cs_order_number AND cs_item_sk = cr_item_sk,
    date_dim
  WHERE cr_order_number IS NULL AND cs_sold_date_sk = d_date_sk
  GROUP BY d_year, cs_item_sk, cs_bill_customer_sk),
ss AS (
  SELECT d_year AS ss_sold_year, ss_item_sk,
    ss_customer_sk, sum(ss_quantity) AS ss_qty,
    sum(ss_wholesale_cost) AS ss_wc, sum(ss_sales_price) AS ss_sp
  FROM store_sales LEFT JOIN store_returns
    ON sr_ticket_number = ss_ticket_number AND ss_item_sk = sr_item_sk,
    date_dim
  WHERE sr_ticket_number IS NULL AND ss_sold_date_sk = d_date_sk
  GROUP BY d_year, ss_item_sk, ss_customer_sk)
SELECT ss_item_sk,
  round(ss_qty / (coalesce(ws_qty, 0) + coalesce(cs_qty, 0)), 2)
    AS ratio,
  ss_qty AS store_qty, ss_wc AS store_wholesale_cost,
  ss_sp AS store_sales_price,
  coalesce(ws_qty, 0) + coalesce(cs_qty, 0) AS other_chan_qty,
  coalesce(ws_wc, 0) + coalesce(cs_wc, 0)
    AS other_chan_wholesale_cost,
  coalesce(ws_sp, 0) + coalesce(cs_sp, 0) AS other_chan_sales_price
FROM ss LEFT JOIN ws
  ON (ws_sold_year = ss_sold_year AND ws_item_sk = ss_item_sk
      AND ws_customer_sk = ss_customer_sk)
  LEFT JOIN cs
  ON (cs_sold_year = ss_sold_year AND cs_item_sk = ss_item_sk
      AND cs_customer_sk = ss_customer_sk)
WHERE (coalesce(ws_qty, 0) > 0 OR coalesce(cs_qty, 0) > 0)
  AND ss_sold_year = 2000
ORDER BY ss_item_sk, ss_qty DESC, ss_wc DESC, ss_sp DESC,
  other_chan_qty LIMIT 100
"""

TPCDS_SQL["q80"] = """
WITH ssr AS (
  SELECT s_store_id AS store_id, sum(ss_ext_sales_price) AS sales,
    sum(coalesce(sr_return_amt, 0.0)) AS returns_,
    sum(ss_net_profit - coalesce(sr_net_loss, 0.0)) AS profit
  FROM store_sales LEFT OUTER JOIN store_returns
    ON (ss_item_sk = sr_item_sk
        AND ss_ticket_number = sr_ticket_number),
    date_dim, store, item, promotion
  WHERE ss_sold_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS date)
                   AND (cast('2000-08-23' AS date) + interval '30' day)
    AND ss_store_sk = s_store_sk AND ss_item_sk = i_item_sk
    AND i_current_price > 1.0 AND ss_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY s_store_id),
csr AS (
  SELECT cp_catalog_page_id AS catalog_page_id,
    sum(cs_ext_sales_price) AS sales,
    sum(coalesce(cr_return_amount, 0.0)) AS returns_,
    sum(cs_net_profit - coalesce(cr_net_loss, 0.0)) AS profit
  FROM catalog_sales LEFT OUTER JOIN catalog_returns
    ON (cs_item_sk = cr_item_sk AND cs_order_number = cr_order_number),
    date_dim, catalog_page, item, promotion
  WHERE cs_sold_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS date)
                   AND (cast('2000-08-23' AS date) + interval '30' day)
    AND cs_catalog_page_sk = cp_catalog_page_sk
    AND cs_item_sk = i_item_sk AND i_current_price > 1.0
    AND cs_promo_sk = p_promo_sk AND p_channel_tv = 'N'
  GROUP BY cp_catalog_page_id),
wsr AS (
  SELECT web_site_id, sum(ws_ext_sales_price) AS sales,
    sum(coalesce(wr_return_amt, 0.0)) AS returns_,
    sum(ws_net_profit - coalesce(wr_net_loss, 0.0)) AS profit
  FROM web_sales LEFT OUTER JOIN web_returns
    ON (ws_item_sk = wr_item_sk AND ws_order_number = wr_order_number),
    date_dim, web_site, item, promotion
  WHERE ws_sold_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS date)
                   AND (cast('2000-08-23' AS date) + interval '30' day)
    AND ws_web_site_sk = web_site_sk AND ws_item_sk = i_item_sk
    AND i_current_price > 1.0 AND ws_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY web_site_id)
SELECT channel, id, sum(sales) AS sales, sum(returns_) AS returns_,
  sum(profit) AS profit FROM (
  SELECT 'store channel' AS channel, 'store' || store_id AS id,
    sales, returns_, profit FROM ssr
  UNION ALL
  SELECT 'catalog channel' AS channel,
    'catalog_page' || catalog_page_id AS id, sales, returns_, profit
  FROM csr
  UNION ALL
  SELECT 'web channel' AS channel, 'web_site' || web_site_id AS id,
    sales, returns_, profit FROM wsr) x
GROUP BY ROLLUP(channel, id)
ORDER BY channel, id LIMIT 100
"""

TPCDS_SQL["q83"] = """
WITH sr_items AS (
  SELECT i_item_id AS item_id, sum(sr_return_quantity) AS sr_item_qty
  FROM store_returns, item, date_dim
  WHERE sr_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim WHERE d_week_seq IN
      (SELECT d_week_seq FROM date_dim WHERE d_date IN
        (cast('2000-06-30' AS date), cast('2000-09-27' AS date),
         cast('2000-11-17' AS date))))
    AND sr_returned_date_sk = d_date_sk
  GROUP BY i_item_id),
cr_items AS (
  SELECT i_item_id AS item_id, sum(cr_return_quantity) AS cr_item_qty
  FROM catalog_returns, item, date_dim
  WHERE cr_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim WHERE d_week_seq IN
      (SELECT d_week_seq FROM date_dim WHERE d_date IN
        (cast('2000-06-30' AS date), cast('2000-09-27' AS date),
         cast('2000-11-17' AS date))))
    AND cr_returned_date_sk = d_date_sk
  GROUP BY i_item_id),
wr_items AS (
  SELECT i_item_id AS item_id, sum(wr_return_quantity) AS wr_item_qty
  FROM web_returns, item, date_dim
  WHERE wr_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim WHERE d_week_seq IN
      (SELECT d_week_seq FROM date_dim WHERE d_date IN
        (cast('2000-06-30' AS date), cast('2000-09-27' AS date),
         cast('2000-11-17' AS date))))
    AND wr_returned_date_sk = d_date_sk
  GROUP BY i_item_id)
SELECT sr_items.item_id, sr_item_qty,
  sr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 * 100
    AS sr_dev,
  cr_item_qty,
  cr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 * 100
    AS cr_dev,
  wr_item_qty,
  wr_item_qty / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 * 100
    AS wr_dev,
  (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 AS average
FROM sr_items, cr_items, wr_items
WHERE sr_items.item_id = cr_items.item_id
  AND sr_items.item_id = wr_items.item_id
ORDER BY sr_items.item_id, sr_item_qty LIMIT 100
"""

TPCDS_SQL["q86"] = """
SELECT sum(ws_net_paid) AS total_sum, i_category, i_class,
  grouping(i_category) + grouping(i_class) AS lochierarchy,
  rank() OVER (
    PARTITION BY grouping(i_category) + grouping(i_class),
      CASE WHEN grouping(i_class) = 0 THEN i_category END
    ORDER BY sum(ws_net_paid) DESC) AS rank_within_parent
FROM web_sales, date_dim d1, item
WHERE d1.d_month_seq BETWEEN 36 AND 47
  AND d1.d_date_sk = ws_sold_date_sk AND i_item_sk = ws_item_sk
GROUP BY ROLLUP(i_category, i_class)
ORDER BY lochierarchy DESC,
  CASE WHEN lochierarchy = 0 THEN i_category END,
  rank_within_parent LIMIT 100
"""

TPCDS_SQL["q64"] = """
WITH cs_ui AS (
  SELECT cs_item_sk, sum(cs_ext_list_price) AS sale,
    sum(cr_refunded_cash + cr_fee) AS refund
  FROM catalog_sales, catalog_returns
  WHERE cs_item_sk = cr_item_sk AND cs_order_number = cr_order_number
  GROUP BY cs_item_sk
  HAVING sum(cs_ext_list_price) > 2 * sum(cr_refunded_cash + cr_fee)),
cross_sales AS (
  SELECT i_product_name AS product_name, i_item_sk AS item_sk,
    s_store_name AS store_name, s_zip AS store_zip,
    ad1.ca_street_number AS b_street_number,
    ad1.ca_street_name AS b_street_name, ad1.ca_city AS b_city,
    ad1.ca_zip AS b_zip, ad2.ca_street_number AS c_street_number,
    ad2.ca_street_name AS c_street_name, ad2.ca_city AS c_city,
    ad2.ca_zip AS c_zip, d1.d_year AS syear, d2.d_year AS fsyear,
    d3.d_year AS s2year, count(*) AS cnt,
    sum(ss_wholesale_cost) AS s1, sum(ss_list_price) AS s2,
    sum(ss_coupon_amt) AS s3
  FROM store_sales, store_returns, cs_ui, date_dim d1, date_dim d2,
    date_dim d3, store, customer, customer_demographics cd1,
    customer_demographics cd2, promotion, household_demographics hd1,
    household_demographics hd2, customer_address ad1,
    customer_address ad2, income_band ib1, income_band ib2, item
  WHERE ss_store_sk = s_store_sk AND ss_sold_date_sk = d1.d_date_sk
    AND ss_customer_sk = c_customer_sk
    AND ss_cdemo_sk = cd1.cd_demo_sk AND ss_hdemo_sk = hd1.hd_demo_sk
    AND ss_addr_sk = ad1.ca_address_sk AND ss_item_sk = i_item_sk
    AND ss_item_sk = sr_item_sk AND ss_ticket_number = sr_ticket_number
    AND ss_item_sk = cs_ui.cs_item_sk
    AND c_current_cdemo_sk = cd2.cd_demo_sk
    AND c_current_hdemo_sk = hd2.hd_demo_sk
    AND c_current_addr_sk = ad2.ca_address_sk
    AND c_first_sales_date_sk = d2.d_date_sk
    AND c_first_shipto_date_sk = d3.d_date_sk
    AND ss_promo_sk = p_promo_sk
    AND hd1.hd_income_band_sk = ib1.ib_income_band_sk
    AND hd2.hd_income_band_sk = ib2.ib_income_band_sk
    AND cd1.cd_marital_status <> cd2.cd_marital_status
    AND i_color IN ('purple', 'red', 'blue', 'green', 'beige',
                    'slate')
    AND i_current_price BETWEEN 0.5 AND 2.0
    AND i_current_price BETWEEN 0.8 AND 2.5
  GROUP BY i_product_name, i_item_sk, s_store_name, s_zip,
    ad1.ca_street_number, ad1.ca_street_name, ad1.ca_city, ad1.ca_zip,
    ad2.ca_street_number, ad2.ca_street_name, ad2.ca_city, ad2.ca_zip,
    d1.d_year, d2.d_year, d3.d_year)
SELECT cs1.product_name, cs1.store_name, cs1.store_zip,
  cs1.b_street_number, cs1.b_street_name, cs1.b_city, cs1.b_zip,
  cs1.c_street_number, cs1.c_street_name, cs1.c_city, cs1.c_zip,
  cs1.syear, cs1.cnt, cs1.s1, cs1.s2, cs1.s3, cs2.s1 AS s1_2,
  cs2.s2 AS s2_2, cs2.s3 AS s3_2, cs2.syear AS syear_2,
  cs2.cnt AS cnt_2
FROM cross_sales cs1, cross_sales cs2
WHERE cs1.item_sk = cs2.item_sk AND cs1.syear = 1999
  AND cs2.syear = 1999 + 1 AND cs2.cnt <= cs1.cnt
  AND cs1.store_name = cs2.store_name
  AND cs1.store_zip = cs2.store_zip
ORDER BY cs1.product_name, cs1.store_name, cnt_2, cs1.s1, s1_2
"""

# re-iterate the dict: every TPCDS_SQL entry registers, so a query
# added anywhere above cannot silently skip oracle testing
for _name, _sql in TPCDS_SQL.items():
    QUERIES[f"tpcds_{_name}"] = _sql_query(_sql)

