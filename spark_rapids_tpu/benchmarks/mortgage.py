"""Mortgage-like ETL benchmark (integration_tests/.../mortgage/
MortgageSpark.scala analogue): the reference's second benchmark family —
a join-enrich-aggregate ETL over loan performance + acquisition tables.

Shapes kept faithful: a large "performance" fact table (loan_id,
monthly_reporting_period, current_actual_upb, delinquency status) joined
to an "acquisition" dimension (loan_id, orig_interest_rate, credit
score band), filtered, then delinquency aggregates per band."""
from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions import aggregates as A
from spark_rapids_tpu.expressions import predicates as P
from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Literal)
from spark_rapids_tpu.expressions.cast import Cast
from spark_rapids_tpu.expressions.conditional import If
from spark_rapids_tpu.io import ParquetSource
from spark_rapids_tpu.plan import nodes as pn

BANDS = np.array(["<600", "600-660", "660-720", "720-780", ">780"],
                 dtype=object)


def ref(i, t):
    return BoundReference(i, t)


def gen_tables(data_dir: str, sf: float, seed: int = 31,
               files_per_table: int = 4) -> None:
    rng = np.random.default_rng(seed)
    n_loans = max(int(100_000 * sf), 50)
    n_perf = n_loans * 12  # ~a year of monthly rows per loan
    acq = pa.table({
        "loan_id": np.arange(1, n_loans + 1, dtype=np.int64),
        "orig_interest_rate": np.round(rng.random(n_loans) * 5 + 2, 3),
        "credit_band": BANDS[rng.integers(0, len(BANDS), n_loans)],
    })
    perf = pa.table({
        "loan_id": rng.integers(1, n_loans + 1, n_perf).astype(np.int64),
        "period": rng.integers(0, 12, n_perf).astype(np.int32),
        "current_actual_upb": np.round(
            rng.random(n_perf) * 400_000 + 10_000, 2),
        "delinquency_status": rng.choice(
            np.arange(0, 6, dtype=np.int32), n_perf,
            p=[0.82, 0.08, 0.04, 0.03, 0.02, 0.01]),
    })
    for name, table in (("acquisition", acq), ("performance", perf)):
        tdir = os.path.join(data_dir, name)
        os.makedirs(tdir, exist_ok=True)
        per = -(-table.num_rows // files_per_table)
        for i in range(files_per_table):
            chunk = table.slice(i * per, per)
            if chunk.num_rows:
                pq.write_table(chunk, os.path.join(
                    tdir, f"part-{i:03d}.parquet"))


def etl(data_dir: str) -> pn.PlanNode:
    """delinquency summary per credit band:
    join perf->acq, filter upb, flag 90+-day delinquency, aggregate."""
    perf = pn.ScanNode(ParquetSource(
        os.path.join(data_dir, "performance")))
    acq = pn.ScanNode(ParquetSource(
        os.path.join(data_dir, "acquisition")))
    perf_f = pn.FilterNode(
        P.GreaterThan(ref(2, dt.FLOAT64), Literal(50_000.0)), perf)
    # perf ⋈ acq on loan_id -> [loan_id, period, upb, delinq,
    #                           loan_id2, rate, band]
    joined = pn.JoinNode("inner", perf_f, acq, [0], [0])
    severe = If(P.GreaterThanOrEqual(ref(3, dt.INT32),
                                     Literal(3, dt.INT32)),
                Literal(1, dt.INT32), Literal(0, dt.INT32))
    proj = pn.ProjectNode(
        [Alias(ref(6, dt.STRING), "band"),
         Alias(ref(2, dt.FLOAT64), "upb"),
         Alias(Cast(severe, dt.INT64), "severe"),
         Alias(ref(5, dt.FLOAT64), "rate")], joined)
    agg = pn.AggregateNode(
        [ref(0, dt.STRING)],
        [pn.AggCall(A.Count(), "loans"),
         pn.AggCall(A.Sum(ref(2, dt.INT64)), "severe_cnt"),
         pn.AggCall(A.Average(ref(1, dt.FLOAT64)), "avg_upb"),
         pn.AggCall(A.Average(ref(3, dt.FLOAT64)), "avg_rate")],
        proj, grouping_names=["band"])
    from spark_rapids_tpu.ops.sortkeys import SortKeySpec

    return pn.SortNode([SortKeySpec.spark_default(0)], agg)
