"""TPC-H-like query definitions as plan trees (TpchLikeSpark.scala
analogue: each query is a function from the data directory to a plan)."""
from __future__ import annotations

import os

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions import aggregates as A
from spark_rapids_tpu.expressions import arithmetic as ar
from spark_rapids_tpu.expressions import predicates as P
from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Literal)
from spark_rapids_tpu.expressions.cast import Cast
from spark_rapids_tpu.io import ParquetSource
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.plan import nodes as pn


def _date_days(s: str) -> int:
    return int((np.datetime64(s) - np.datetime64("1970-01-01")
                ).astype(int))


def _scan(data_dir: str, table: str, columns):
    return pn.ScanNode(ParquetSource(os.path.join(data_dir, table),
                                     columns=columns))


def ref(i, t):
    return BoundReference(i, t)


def q1(data_dir: str) -> pn.PlanNode:
    """Pricing summary report: scan-heavy groupby with many aggregates
    (the reference's headline scan+agg shape)."""
    scan = _scan(data_dir, "lineitem",
                 ["l_returnflag", "l_linestatus", "l_quantity",
                  "l_extendedprice", "l_discount", "l_tax", "l_shipdate"])
    filt = pn.FilterNode(
        P.LessThanOrEqual(ref(6, dt.DATE),
                          Literal(_date_days("1998-09-02"), dt.DATE)),
        scan)
    qty = ref(2, dt.FLOAT64)
    price = ref(3, dt.FLOAT64)
    disc = ref(4, dt.FLOAT64)
    tax = ref(5, dt.FLOAT64)
    disc_price = ar.Multiply(price, ar.Subtract(Literal(1.0), disc))
    charge = ar.Multiply(disc_price, ar.Add(Literal(1.0), tax))
    agg = pn.AggregateNode(
        [ref(0, dt.STRING), ref(1, dt.STRING)],
        [pn.AggCall(A.Sum(qty), "sum_qty"),
         pn.AggCall(A.Sum(price), "sum_base_price"),
         pn.AggCall(A.Sum(disc_price), "sum_disc_price"),
         pn.AggCall(A.Sum(charge), "sum_charge"),
         pn.AggCall(A.Average(qty), "avg_qty"),
         pn.AggCall(A.Average(price), "avg_price"),
         pn.AggCall(A.Average(disc), "avg_disc"),
         pn.AggCall(A.Count(), "count_order")],
        filt, grouping_names=["l_returnflag", "l_linestatus"])
    return pn.SortNode([SortKeySpec.spark_default(0),
                        SortKeySpec.spark_default(1)], agg)


def q6(data_dir: str) -> pn.PlanNode:
    """Forecasting revenue change: tight filter + global aggregate."""
    scan = _scan(data_dir, "lineitem",
                 ["l_extendedprice", "l_discount", "l_quantity",
                  "l_shipdate"])
    d = ref(3, dt.DATE)
    cond = P.And(
        P.And(P.GreaterThanOrEqual(d, Literal(_date_days("1994-01-01"),
                                              dt.DATE)),
              P.LessThan(d, Literal(_date_days("1995-01-01"), dt.DATE))),
        P.And(P.And(P.GreaterThanOrEqual(ref(1, dt.FLOAT64),
                                         Literal(0.05)),
                    P.LessThanOrEqual(ref(1, dt.FLOAT64),
                                      Literal(0.07))),
              P.LessThan(ref(2, dt.FLOAT64), Literal(24.0))))
    filt = pn.FilterNode(cond, scan)
    revenue = ar.Multiply(ref(0, dt.FLOAT64), ref(1, dt.FLOAT64))
    return pn.AggregateNode([], [pn.AggCall(A.Sum(revenue), "revenue")],
                            filt)


def q3(data_dir: str) -> pn.PlanNode:
    """Shipping priority: 3-way join + groupby + top-N (the multi-way
    join shape of BASELINE config #3)."""
    customer = _scan(data_dir, "customer", ["c_custkey", "c_mktsegment"])
    orders = _scan(data_dir, "orders",
                   ["o_orderkey", "o_custkey", "o_orderdate",
                    "o_shippriority"])
    lineitem = _scan(data_dir, "lineitem",
                     ["l_orderkey", "l_extendedprice", "l_discount",
                      "l_shipdate"])
    cust_f = pn.FilterNode(
        P.EqualTo(ref(1, dt.STRING), Literal("BUILDING")), customer)
    ord_f = pn.FilterNode(
        P.LessThan(ref(2, dt.DATE),
                   Literal(_date_days("1995-03-15"), dt.DATE)), orders)
    li_f = pn.FilterNode(
        P.GreaterThan(ref(3, dt.DATE),
                      Literal(_date_days("1995-03-15"), dt.DATE)),
        lineitem)
    # customer ⋈ orders on custkey
    co = pn.JoinNode("inner", cust_f, ord_f, [0], [1])
    # (c..., o...) ⋈ lineitem on orderkey;  co schema:
    # [c_custkey, c_mktsegment, o_orderkey, o_custkey, o_orderdate,
    #  o_shippriority]
    col = pn.JoinNode("inner", co, li_f, [2], [0])
    # col schema adds [l_orderkey, l_extendedprice, l_discount,
    # l_shipdate] at 6..9
    revenue = ar.Multiply(ref(7, dt.FLOAT64),
                          ar.Subtract(Literal(1.0), ref(8, dt.FLOAT64)))
    proj = pn.ProjectNode(
        [Alias(ref(6, dt.INT64), "l_orderkey"),
         Alias(ref(4, dt.DATE), "o_orderdate"),
         Alias(ref(5, dt.INT32), "o_shippriority"),
         Alias(revenue, "rev")], col)
    agg = pn.AggregateNode(
        [ref(0, dt.INT64), ref(1, dt.DATE), ref(2, dt.INT32)],
        [pn.AggCall(A.Sum(ref(3, dt.FLOAT64)), "revenue")],
        proj, grouping_names=["l_orderkey", "o_orderdate",
                              "o_shippriority"])
    sort = pn.SortNode([SortKeySpec.spark_default(3, ascending=False),
                        SortKeySpec.spark_default(1)], agg)
    return pn.LimitNode(10, sort)


def q4(data_dir: str) -> pn.PlanNode:
    """Order priority checking: date-window filter + EXISTS-subquery as a
    left-semi join + groupby count."""
    orders = _scan(data_dir, "orders",
                   ["o_orderkey", "o_orderdate", "o_orderpriority"])
    ord_f = pn.FilterNode(
        P.And(P.GreaterThanOrEqual(ref(1, dt.DATE),
                                   Literal(_date_days("1993-07-01"),
                                           dt.DATE)),
              P.LessThan(ref(1, dt.DATE),
                         Literal(_date_days("1993-10-01"), dt.DATE))),
        orders)
    lineitem = _scan(data_dir, "lineitem",
                     ["l_orderkey", "l_commitdate", "l_receiptdate"])
    li_f = pn.FilterNode(P.LessThan(ref(1, dt.DATE), ref(2, dt.DATE)),
                         lineitem)
    semi = pn.JoinNode("left_semi", ord_f, li_f, [0], [0])
    agg = pn.AggregateNode(
        [ref(2, dt.STRING)], [pn.AggCall(A.Count(), "order_count")],
        semi, grouping_names=["o_orderpriority"])
    return pn.SortNode([SortKeySpec.spark_default(0)], agg)


def q5(data_dir: str) -> pn.PlanNode:
    """Local supplier volume: 6-table join chain + groupby revenue
    (the TPC-DS q72 / TPCxBB q3 multi-way-join shape of BASELINE
    config #3)."""
    region = pn.FilterNode(
        P.EqualTo(ref(1, dt.STRING), Literal("ASIA")),
        _scan(data_dir, "region", ["r_regionkey", "r_name"]))
    nation = _scan(data_dir, "nation",
                   ["n_nationkey", "n_name", "n_regionkey"])
    # nation x region -> [n_nationkey, n_name, n_regionkey, r_regionkey,
    #                     r_name]
    nr = pn.JoinNode("inner", nation, region, [2], [0])
    supplier = _scan(data_dir, "supplier", ["s_suppkey", "s_nationkey"])
    # -> [s_suppkey, s_nationkey, n_nationkey, n_name, n_regionkey,
    #     r_regionkey, r_name]
    snr = pn.JoinNode("inner", supplier, nr, [1], [0])
    customer = _scan(data_dir, "customer", ["c_custkey", "c_nationkey"])
    orders = pn.FilterNode(
        P.And(P.GreaterThanOrEqual(ref(2, dt.DATE),
                                   Literal(_date_days("1994-01-01"),
                                           dt.DATE)),
              P.LessThan(ref(2, dt.DATE),
                         Literal(_date_days("1995-01-01"), dt.DATE))),
        _scan(data_dir, "orders",
              ["o_orderkey", "o_custkey", "o_orderdate"]))
    # -> [c_custkey, c_nationkey, o_orderkey, o_custkey, o_orderdate]
    co = pn.JoinNode("inner", customer, orders, [0], [1])
    lineitem = _scan(data_dir, "lineitem",
                     ["l_orderkey", "l_suppkey", "l_extendedprice",
                      "l_discount"])
    # -> co + [l_orderkey, l_suppkey, l_extendedprice, l_discount] @ 5..8
    col = pn.JoinNode("inner", co, lineitem, [2], [0])
    # l_suppkey = s_suppkey AND c_nationkey = s_nationkey (the "local
    # supplier" constraint); snr cols land at 9..15, n_name @ 12
    full = pn.JoinNode("inner", col, snr, [6, 1], [0, 1])
    revenue = ar.Multiply(ref(7, dt.FLOAT64),
                          ar.Subtract(Literal(1.0), ref(8, dt.FLOAT64)))
    proj = pn.ProjectNode([Alias(ref(12, dt.STRING), "n_name"),
                           Alias(revenue, "rev")], full)
    agg = pn.AggregateNode(
        [ref(0, dt.STRING)],
        [pn.AggCall(A.Sum(ref(1, dt.FLOAT64)), "revenue")],
        proj, grouping_names=["n_name"])
    return pn.SortNode([SortKeySpec.spark_default(1, ascending=False)],
                       agg)


def q10(data_dir: str) -> pn.PlanNode:
    """Returned item reporting: 4-table join, wide groupby, top 20."""
    customer = _scan(data_dir, "customer",
                     ["c_custkey", "c_nationkey", "c_acctbal", "c_name",
                      "c_phone"])
    orders = pn.FilterNode(
        P.And(P.GreaterThanOrEqual(ref(2, dt.DATE),
                                   Literal(_date_days("1993-10-01"),
                                           dt.DATE)),
              P.LessThan(ref(2, dt.DATE),
                         Literal(_date_days("1994-01-01"), dt.DATE))),
        _scan(data_dir, "orders",
              ["o_orderkey", "o_custkey", "o_orderdate"]))
    lineitem = pn.FilterNode(
        P.EqualTo(ref(3, dt.STRING), Literal("R")),
        _scan(data_dir, "lineitem",
              ["l_orderkey", "l_extendedprice", "l_discount",
               "l_returnflag"]))
    nation = _scan(data_dir, "nation", ["n_nationkey", "n_name"])
    # [c...0-4, o_orderkey 5, o_custkey 6, o_orderdate 7]
    co = pn.JoinNode("inner", customer, orders, [0], [1])
    # + [l_orderkey 8, l_extendedprice 9, l_discount 10, l_returnflag 11]
    col = pn.JoinNode("inner", co, lineitem, [5], [0])
    # + [n_nationkey 12, n_name 13]
    con = pn.JoinNode("inner", col, nation, [1], [0])
    revenue = ar.Multiply(ref(9, dt.FLOAT64),
                          ar.Subtract(Literal(1.0), ref(10, dt.FLOAT64)))
    proj = pn.ProjectNode(
        [Alias(ref(0, dt.INT64), "c_custkey"),
         Alias(ref(3, dt.STRING), "c_name"),
         Alias(ref(2, dt.FLOAT64), "c_acctbal"),
         Alias(ref(4, dt.STRING), "c_phone"),
         Alias(ref(13, dt.STRING), "n_name"),
         Alias(revenue, "rev")], con)
    agg = pn.AggregateNode(
        [ref(0, dt.INT64), ref(1, dt.STRING), ref(2, dt.FLOAT64),
         ref(3, dt.STRING), ref(4, dt.STRING)],
        [pn.AggCall(A.Sum(ref(5, dt.FLOAT64)), "revenue")],
        proj, grouping_names=["c_custkey", "c_name", "c_acctbal",
                              "c_phone", "n_name"])
    sort = pn.SortNode([SortKeySpec.spark_default(5, ascending=False)],
                       agg)
    return pn.LimitNode(20, sort)


def q12(data_dir: str) -> pn.PlanNode:
    """Shipping modes and order priority: join + conditional aggregation
    (CASE WHEN inside SUM)."""
    from spark_rapids_tpu.expressions.conditional import If
    from spark_rapids_tpu.expressions.predicates import In

    orders = _scan(data_dir, "orders",
                   ["o_orderkey", "o_orderpriority"])
    li = _scan(data_dir, "lineitem",
               ["l_orderkey", "l_shipdate", "l_commitdate",
                "l_receiptdate", "l_shipmode"])
    li_f = pn.FilterNode(
        P.And(P.And(In(ref(4, dt.STRING),
                       [Literal("MAIL"), Literal("SHIP")]),
                    P.LessThan(ref(2, dt.DATE), ref(3, dt.DATE))),
              P.And(P.LessThan(ref(1, dt.DATE), ref(2, dt.DATE)),
                    P.And(P.GreaterThanOrEqual(
                              ref(3, dt.DATE),
                              Literal(_date_days("1994-01-01"), dt.DATE)),
                          P.LessThan(
                              ref(3, dt.DATE),
                              Literal(_date_days("1995-01-01"),
                                      dt.DATE))))),
        li)
    # [o_orderkey 0, o_orderpriority 1, l_orderkey 2, ..., l_shipmode 6]
    j = pn.JoinNode("inner", orders, li_f, [0], [0])
    is_high = In(ref(1, dt.STRING),
                 [Literal("1-URGENT"), Literal("2-HIGH")])
    proj = pn.ProjectNode(
        [Alias(ref(6, dt.STRING), "l_shipmode"),
         Alias(If(is_high, Literal(1), Literal(0)), "high"),
         Alias(If(is_high, Literal(0), Literal(1)), "low")], j)
    agg = pn.AggregateNode(
        [ref(0, dt.STRING)],
        [pn.AggCall(A.Sum(ref(1, dt.INT64)), "high_line_count"),
         pn.AggCall(A.Sum(ref(2, dt.INT64)), "low_line_count")],
        proj, grouping_names=["l_shipmode"])
    return pn.SortNode([SortKeySpec.spark_default(0)], agg)


def q14(data_dir: str) -> pn.PlanNode:
    """Promotion effect: join + CASE WHEN ratio of global aggregates."""
    from spark_rapids_tpu.expressions.conditional import If
    from spark_rapids_tpu.expressions.strings import StartsWith

    li = pn.FilterNode(
        P.And(P.GreaterThanOrEqual(ref(3, dt.DATE),
                                   Literal(_date_days("1995-09-01"),
                                           dt.DATE)),
              P.LessThan(ref(3, dt.DATE),
                         Literal(_date_days("1995-10-01"), dt.DATE))),
        _scan(data_dir, "lineitem",
              ["l_partkey", "l_extendedprice", "l_discount",
               "l_shipdate"]))
    part = _scan(data_dir, "part", ["p_partkey", "p_type"])
    # + [p_partkey 4, p_type 5]
    j = pn.JoinNode("inner", li, part, [0], [0])
    rev = ar.Multiply(ref(1, dt.FLOAT64),
                      ar.Subtract(Literal(1.0), ref(2, dt.FLOAT64)))
    promo = If(StartsWith(ref(5, dt.STRING), "PROMO"), rev,
               Literal(0.0))
    proj = pn.ProjectNode([Alias(promo, "promo_rev"),
                           Alias(rev, "rev")], j)
    agg = pn.AggregateNode(
        [], [pn.AggCall(A.Sum(ref(0, dt.FLOAT64)), "sum_promo"),
             pn.AggCall(A.Sum(ref(1, dt.FLOAT64)), "sum_rev")], proj)
    ratio = ar.Multiply(Literal(100.0),
                        ar.Divide(ref(0, dt.FLOAT64),
                                  ref(1, dt.FLOAT64)))
    return pn.ProjectNode([Alias(ratio, "promo_revenue")], agg)


def q18(data_dir: str) -> pn.PlanNode:
    """Large volume customer: IN-subquery over a grouped HAVING filter
    realized as agg -> filter -> semi-join, then re-join + re-aggregate.
    (Threshold lowered from 300 to 100 for the synthetic -like data.)"""
    li_keys = _scan(data_dir, "lineitem", ["l_orderkey", "l_quantity"])
    big = pn.FilterNode(
        P.GreaterThan(ref(1, dt.FLOAT64), Literal(100.0)),
        pn.AggregateNode([ref(0, dt.INT64)],
                         [pn.AggCall(A.Sum(ref(1, dt.FLOAT64)), "sq")],
                         li_keys, grouping_names=["l_orderkey"]))
    orders = _scan(data_dir, "orders",
                   ["o_orderkey", "o_custkey", "o_totalprice",
                    "o_orderdate"])
    ord_big = pn.JoinNode("left_semi", orders, big, [0], [0])
    customer = _scan(data_dir, "customer", ["c_custkey", "c_name"])
    # [o... 0-3, c_custkey 4, c_name 5]
    oc = pn.JoinNode("inner", ord_big, customer, [1], [0])
    li = _scan(data_dir, "lineitem", ["l_orderkey", "l_quantity"])
    # + [l_orderkey 6, l_quantity 7]
    ocl = pn.JoinNode("inner", oc, li, [0], [0])
    agg = pn.AggregateNode(
        [ref(5, dt.STRING), ref(4, dt.INT64), ref(0, dt.INT64),
         ref(3, dt.DATE), ref(2, dt.FLOAT64)],
        [pn.AggCall(A.Sum(ref(7, dt.FLOAT64)), "sum_qty")],
        ocl, grouping_names=["c_name", "c_custkey", "o_orderkey",
                             "o_orderdate", "o_totalprice"])
    sort = pn.SortNode([SortKeySpec.spark_default(4, ascending=False),
                        SortKeySpec.spark_default(3)], agg)
    return pn.LimitNode(100, sort)


def q19(data_dir: str) -> pn.PlanNode:
    """Discounted revenue: equi-join on partkey with a 3-arm OR residual
    condition over both sides (brand/container/size/quantity bands)."""
    from spark_rapids_tpu.expressions.predicates import In
    from spark_rapids_tpu.expressions.strings import StartsWith

    li = pn.FilterNode(
        P.And(In(ref(4, dt.STRING),
                 [Literal("AIR"), Literal("REG AIR")]),
              P.EqualTo(ref(5, dt.STRING),
                        Literal("DELIVER IN PERSON"))),
        _scan(data_dir, "lineitem",
              ["l_partkey", "l_quantity", "l_extendedprice",
               "l_discount", "l_shipmode", "l_shipinstruct"]))
    part = _scan(data_dir, "part",
                 ["p_partkey", "p_brand", "p_size", "p_container"])
    qty = ref(1, dt.FLOAT64)
    # part columns land at 6..9 after the join
    brand = ref(7, dt.STRING)
    size = ref(8, dt.INT32)
    container = ref(9, dt.STRING)

    def arm(brand_lit, cont_prefix, qlo, qhi, smax):
        return P.And(
            P.And(P.EqualTo(brand, Literal(brand_lit)),
                  StartsWith(container, cont_prefix)),
            P.And(P.And(P.GreaterThanOrEqual(qty, Literal(float(qlo))),
                        P.LessThanOrEqual(qty, Literal(float(qhi)))),
                  P.LessThanOrEqual(size, Literal(smax, dt.INT32))))

    cond = P.Or(P.Or(arm("Brand#12", "SM", 1, 11, 5),
                     arm("Brand#23", "MED", 10, 20, 10)),
                arm("Brand#34", "LG", 20, 30, 15))
    j = pn.JoinNode("inner", li, part, [0], [0], condition=cond)
    rev = ar.Multiply(ref(2, dt.FLOAT64),
                      ar.Subtract(Literal(1.0), ref(3, dt.FLOAT64)))
    proj = pn.ProjectNode([Alias(rev, "rev")], j)
    return pn.AggregateNode(
        [], [pn.AggCall(A.Sum(ref(0, dt.FLOAT64)), "revenue")], proj)


def _lit_one(plan: pn.PlanNode, names) -> pn.PlanNode:
    """Append a constant key column — the decorrelation trick that turns
    a scalar subquery into an equi-join on lit(1)."""
    schema_types = plan.output_schema().types
    exprs = [ref(i, t) for i, t in enumerate(schema_types)]
    exprs.append(Literal(1, dt.INT64))
    return pn.ProjectNode(exprs, plan, names + ["one"])


def q7(data_dir: str) -> pn.PlanNode:
    """Volume shipping: 2-nation flow pairs with a year extract and an
    OR condition over the joined nations."""
    from spark_rapids_tpu.expressions.datetime import Year

    supplier = _scan(data_dir, "supplier", ["s_suppkey", "s_nationkey"])
    n1 = _scan(data_dir, "nation", ["n_nationkey", "n_name"])
    # supp x n1 -> [s_suppkey, s_nationkey, n1_key 2, supp_nation 3]
    sn = pn.JoinNode("inner", supplier, n1, [1], [0])
    customer = _scan(data_dir, "customer", ["c_custkey", "c_nationkey"])
    n2 = _scan(data_dir, "nation", ["n_nationkey", "n_name"])
    # cust x n2 -> [c_custkey, c_nationkey, n2_key 2, cust_nation 3]
    cn = pn.JoinNode("inner", customer, n2, [1], [0])
    li = pn.FilterNode(
        P.And(P.GreaterThanOrEqual(ref(4, dt.DATE),
                                   Literal(_date_days("1995-01-01"),
                                           dt.DATE)),
              P.LessThanOrEqual(ref(4, dt.DATE),
                                Literal(_date_days("1996-12-31"),
                                        dt.DATE))),
        _scan(data_dir, "lineitem",
              ["l_orderkey", "l_suppkey", "l_extendedprice",
               "l_discount", "l_shipdate"]))
    orders = _scan(data_dir, "orders", ["o_orderkey", "o_custkey"])
    # li x orders -> [l..0-4, o_orderkey 5, o_custkey 6]
    lo = pn.JoinNode("inner", li, orders, [0], [0])
    # x sn on l_suppkey -> + [s_suppkey 7, s_nationkey 8, nk 9,
    #                         supp_nation 10]
    los = pn.JoinNode("inner", lo, sn, [1], [0])
    # x cn on o_custkey -> + [c_custkey 11, c_nationkey 12, nk 13,
    #                         cust_nation 14]
    losc = pn.JoinNode("inner", los, cn, [6], [0])
    flow = P.Or(
        P.And(P.EqualTo(ref(10, dt.STRING), Literal("FRANCE")),
              P.EqualTo(ref(14, dt.STRING), Literal("GERMANY"))),
        P.And(P.EqualTo(ref(10, dt.STRING), Literal("GERMANY")),
              P.EqualTo(ref(14, dt.STRING), Literal("FRANCE"))))
    filt = pn.FilterNode(flow, losc)
    vol = ar.Multiply(ref(2, dt.FLOAT64),
                      ar.Subtract(Literal(1.0), ref(3, dt.FLOAT64)))
    proj = pn.ProjectNode(
        [Alias(ref(10, dt.STRING), "supp_nation"),
         Alias(ref(14, dt.STRING), "cust_nation"),
         Alias(Year(ref(4, dt.DATE)), "l_year"),
         Alias(vol, "volume")], filt)
    agg = pn.AggregateNode(
        [ref(0, dt.STRING), ref(1, dt.STRING), ref(2, dt.INT32)],
        [pn.AggCall(A.Sum(ref(3, dt.FLOAT64)), "revenue")],
        proj, grouping_names=["supp_nation", "cust_nation", "l_year"])
    return pn.SortNode([SortKeySpec.spark_default(0),
                        SortKeySpec.spark_default(1),
                        SortKeySpec.spark_default(2)], agg)


def q9(data_dir: str) -> pn.PlanNode:
    """Product type profit: 5-way join, profit expression, groupby
    nation x year."""
    from spark_rapids_tpu.expressions.datetime import Year
    from spark_rapids_tpu.expressions.strings import Contains

    part = pn.FilterNode(
        Contains(ref(1, dt.STRING), "BRASS"),
        _scan(data_dir, "part", ["p_partkey", "p_type"]))
    li = _scan(data_dir, "lineitem",
               ["l_orderkey", "l_partkey", "l_suppkey", "l_quantity",
                "l_extendedprice", "l_discount"])
    # li x part -> + [p_partkey 6, p_type 7]
    lp = pn.JoinNode("inner", li, part, [1], [0])
    supplier = _scan(data_dir, "supplier", ["s_suppkey", "s_nationkey"])
    # + [s_suppkey 8, s_nationkey 9]
    lps = pn.JoinNode("inner", lp, supplier, [2], [0])
    partsupp = _scan(data_dir, "partsupp",
                     ["ps_partkey", "ps_suppkey", "ps_supplycost"])
    # join on (partkey, suppkey) -> + [ps_partkey 10, ps_suppkey 11,
    #                                  ps_supplycost 12]
    lpsp = pn.JoinNode("inner", lps, partsupp, [1, 2], [0, 1])
    orders = _scan(data_dir, "orders", ["o_orderkey", "o_orderdate"])
    # + [o_orderkey 13, o_orderdate 14]
    lpspo = pn.JoinNode("inner", lpsp, orders, [0], [0])
    nation = _scan(data_dir, "nation", ["n_nationkey", "n_name"])
    # + [n_nationkey 15, n_name 16]
    full = pn.JoinNode("inner", lpspo, nation, [9], [0])
    profit = ar.Subtract(
        ar.Multiply(ref(4, dt.FLOAT64),
                    ar.Subtract(Literal(1.0), ref(5, dt.FLOAT64))),
        ar.Multiply(ref(12, dt.FLOAT64), ref(3, dt.FLOAT64)))
    proj = pn.ProjectNode(
        [Alias(ref(16, dt.STRING), "nation"),
         Alias(Year(ref(14, dt.DATE)), "o_year"),
         Alias(profit, "amount")], full)
    agg = pn.AggregateNode(
        [ref(0, dt.STRING), ref(1, dt.INT32)],
        [pn.AggCall(A.Sum(ref(2, dt.FLOAT64)), "sum_profit")],
        proj, grouping_names=["nation", "o_year"])
    return pn.SortNode([SortKeySpec.spark_default(0),
                        SortKeySpec.spark_default(1, ascending=False)],
                       agg)


def q13(data_dir: str) -> pn.PlanNode:
    """Customer distribution: LEFT join + two-level aggregation
    (count-of-counts)."""
    customer = _scan(data_dir, "customer", ["c_custkey"])
    orders = pn.FilterNode(
        P.Not(P.In(ref(2, dt.STRING),
                   [Literal("1-URGENT")])),
        _scan(data_dir, "orders",
              ["o_orderkey", "o_custkey", "o_orderpriority"]))
    # LEFT join keeps order-less customers -> [c_custkey,
    #  o_orderkey 1, o_custkey 2, o_orderpriority 3]
    co = pn.JoinNode("left", customer, orders, [0], [1])
    counts = pn.AggregateNode(
        [ref(0, dt.INT64)],
        [pn.AggCall(A.Count(ref(1, dt.INT64)), "c_count")],
        co, grouping_names=["c_custkey"])
    dist = pn.AggregateNode(
        [ref(1, dt.INT64)], [pn.AggCall(A.Count(), "custdist")],
        counts, grouping_names=["c_count"])
    return pn.SortNode([SortKeySpec.spark_default(1, ascending=False),
                        SortKeySpec.spark_default(0, ascending=False)],
                       dist)


def q11(data_dir: str) -> pn.PlanNode:
    """Important stock: partsupp value per part vs a global-threshold
    scalar subquery, decorrelated into an equi-join on lit(1)."""
    partsupp = _scan(data_dir, "partsupp",
                     ["ps_partkey", "ps_suppkey", "ps_availqty",
                      "ps_supplycost"])
    supplier = _scan(data_dir, "supplier", ["s_suppkey", "s_nationkey"])
    nation = pn.FilterNode(
        P.EqualTo(ref(1, dt.STRING), Literal("GERMANY")),
        _scan(data_dir, "nation", ["n_nationkey", "n_name"]))
    sn = pn.JoinNode("inner", supplier, nation, [1], [0])
    # ps x sn on suppkey -> value rows; [ps..0-3, s_suppkey 4,
    #  s_nationkey 5, n_nationkey 6, n_name 7]
    psn = pn.JoinNode("inner", partsupp, sn, [1], [0])
    value = ar.Multiply(ref(3, dt.FLOAT64),
                        Cast(ref(2, dt.INT32), dt.FLOAT64))
    vals = pn.ProjectNode([Alias(ref(0, dt.INT64), "ps_partkey"),
                           Alias(value, "value")], psn)
    per_part = pn.AggregateNode(
        [ref(0, dt.INT64)],
        [pn.AggCall(A.Sum(ref(1, dt.FLOAT64)), "value")],
        vals, grouping_names=["ps_partkey"])
    total = pn.AggregateNode(
        [], [pn.AggCall(A.Sum(ref(1, dt.FLOAT64)), "total")], vals)
    thresh = pn.ProjectNode(
        [Alias(ar.Multiply(ref(0, dt.FLOAT64), Literal(0.0001)),
               "threshold"), Alias(Literal(1, dt.INT64), "one")], total)
    keyed = _lit_one(per_part, ["ps_partkey", "value"])
    # join per-part values against the single threshold row
    j = pn.JoinNode("inner", keyed, thresh, [2], [1])
    filt = pn.FilterNode(P.GreaterThan(ref(1, dt.FLOAT64),
                                       ref(3, dt.FLOAT64)), j)
    proj = pn.ProjectNode([Alias(ref(0, dt.INT64), "ps_partkey"),
                           Alias(ref(1, dt.FLOAT64), "value")], filt)
    return pn.SortNode([SortKeySpec.spark_default(1, ascending=False)],
                       proj)


def q16(data_dir: str) -> pn.PlanNode:
    """Parts/supplier relationship: anti join + count distinct."""
    from spark_rapids_tpu.expressions.strings import StartsWith

    part = pn.FilterNode(
        P.And(P.Not(P.EqualTo(ref(1, dt.STRING), Literal("Brand#45"))),
              P.And(P.Not(StartsWith(ref(2, dt.STRING), "MEDIUM")),
                    P.In(ref(3, dt.INT32),
                         [Literal(k, dt.INT32)
                          for k in (49, 14, 23, 45, 19, 3, 36, 9)]))),
        _scan(data_dir, "part",
              ["p_partkey", "p_brand", "p_type", "p_size"]))
    supplier_bad = pn.FilterNode(
        P.LessThan(ref(1, dt.FLOAT64), Literal(-500.0)),
        _scan(data_dir, "supplier", ["s_suppkey", "s_acctbal"]))
    partsupp = _scan(data_dir, "partsupp",
                     ["ps_partkey", "ps_suppkey"])
    # exclude "bad" suppliers (the NOT IN subquery)
    ps_ok = pn.JoinNode("left_anti", partsupp, supplier_bad, [1], [0])
    # x part -> + [p_partkey 2, p_brand 3, p_type 4, p_size 5]
    pp = pn.JoinNode("inner", ps_ok, part, [0], [0])
    agg = pn.AggregateNode(
        [ref(3, dt.STRING), ref(4, dt.STRING), ref(5, dt.INT32)],
        [pn.AggCall(A.Count(ref(1, dt.INT64), distinct=True),
                    "supplier_cnt")],
        pp, grouping_names=["p_brand", "p_type", "p_size"])
    return pn.SortNode([SortKeySpec.spark_default(3, ascending=False),
                        SortKeySpec.spark_default(0),
                        SortKeySpec.spark_default(1),
                        SortKeySpec.spark_default(2)], agg)


def q17(data_dir: str) -> pn.PlanNode:
    """Small-quantity-order revenue: per-part average joined back
    (correlated scalar subquery, decorrelated)."""
    from spark_rapids_tpu.expressions.strings import StartsWith

    part = pn.FilterNode(
        P.And(P.EqualTo(ref(1, dt.STRING), Literal("Brand#23")),
              StartsWith(ref(2, dt.STRING), "MED")),
        _scan(data_dir, "part", ["p_partkey", "p_brand", "p_container"]))
    li = _scan(data_dir, "lineitem",
               ["l_partkey", "l_quantity", "l_extendedprice"])
    per_part_avg = pn.AggregateNode(
        [ref(0, dt.INT64)],
        [pn.AggCall(A.Average(ref(1, dt.FLOAT64)), "avg_qty")],
        li, grouping_names=["l_partkey"])
    # li x part -> [l..0-2, p_partkey 3, p_brand 4, p_container 5]
    lp = pn.JoinNode("inner", li, part, [0], [0])
    # + [l_partkey(avg) 6, avg_qty 7]
    lpa = pn.JoinNode("inner", lp, per_part_avg, [0], [0])
    filt = pn.FilterNode(
        P.LessThan(ref(1, dt.FLOAT64),
                   ar.Multiply(Literal(0.2), ref(7, dt.FLOAT64))), lpa)
    agg = pn.AggregateNode(
        [], [pn.AggCall(A.Sum(ref(2, dt.FLOAT64)), "sum_rev")], filt)
    return pn.ProjectNode(
        [Alias(ar.Divide(ref(0, dt.FLOAT64), Literal(7.0)),
               "avg_yearly")], agg)


def q22(data_dir: str) -> pn.PlanNode:
    """Global sales opportunity: phone-prefix filter, above-average
    balance (decorrelated), anti join against orders."""
    from spark_rapids_tpu.expressions.strings import Substring

    cust = _scan(data_dir, "customer",
                 ["c_custkey", "c_acctbal", "c_phone"])
    with_cc = pn.ProjectNode(
        [ref(0, dt.INT64), ref(1, dt.FLOAT64),
         Substring(ref(2, dt.STRING), 1, 2)],
        cust, ["c_custkey", "c_acctbal", "cntrycode"])
    sel = pn.FilterNode(
        P.In(ref(2, dt.STRING),
             [Literal(c) for c in ("13", "31", "23", "29", "30")]),
        with_cc)
    pos = pn.FilterNode(P.GreaterThan(ref(1, dt.FLOAT64),
                                      Literal(0.0)), sel)
    avg_bal = pn.AggregateNode(
        [], [pn.AggCall(A.Average(ref(1, dt.FLOAT64)), "avg_bal")], pos)
    avg_keyed = _lit_one(avg_bal, ["avg_bal"])
    sel_keyed = _lit_one(sel, ["c_custkey", "c_acctbal", "cntrycode"])
    # join the single avg row in, keep above-average customers
    j = pn.JoinNode("inner", sel_keyed, avg_keyed, [3], [1])
    rich = pn.FilterNode(P.GreaterThan(ref(1, dt.FLOAT64),
                                       ref(4, dt.FLOAT64)), j)
    orders = _scan(data_dir, "orders", ["o_custkey"])
    # customers with no orders
    no_orders = pn.JoinNode("left_anti", rich, orders, [0], [0])
    agg = pn.AggregateNode(
        [ref(2, dt.STRING)],
        [pn.AggCall(A.Count(), "numcust"),
         pn.AggCall(A.Sum(ref(1, dt.FLOAT64)), "totacctbal")],
        no_orders, grouping_names=["cntrycode"])
    return pn.SortNode([SortKeySpec.spark_default(0)], agg)


def q2(data_dir: str) -> pn.PlanNode:
    """Minimum cost supplier: per-part min supplycost within a region,
    joined back (correlated MIN subquery, decorrelated)."""
    from spark_rapids_tpu.expressions.strings import EndsWith

    part = pn.FilterNode(
        P.And(P.EqualTo(ref(2, dt.INT32), Literal(15, dt.INT32)),
              EndsWith(ref(1, dt.STRING), "BRASS")),
        _scan(data_dir, "part", ["p_partkey", "p_type", "p_size"]))
    region = pn.FilterNode(
        P.EqualTo(ref(1, dt.STRING), Literal("EUROPE")),
        _scan(data_dir, "region", ["r_regionkey", "r_name"]))
    nation = _scan(data_dir, "nation",
                   ["n_nationkey", "n_name", "n_regionkey"])
    nr = pn.JoinNode("inner", nation, region, [2], [0])
    supplier = _scan(data_dir, "supplier",
                     ["s_suppkey", "s_nationkey", "s_acctbal"])
    # [s..0-2, n_nationkey 3, n_name 4, n_regionkey 5, r_regionkey 6,
    #  r_name 7]
    snr = pn.JoinNode("inner", supplier, nr, [1], [0])
    partsupp = _scan(data_dir, "partsupp",
                     ["ps_partkey", "ps_suppkey", "ps_supplycost"])
    # ps x snr -> [ps..0-2, snr 3..10]
    ps_eu = pn.JoinNode("inner", partsupp, snr, [1], [0])
    # region-scoped min cost per part
    min_cost = pn.AggregateNode(
        [ref(0, dt.INT64)],
        [pn.AggCall(A.Min(ref(2, dt.FLOAT64)), "min_cost")],
        ps_eu, grouping_names=["ps_partkey"])
    # x part -> keep BRASS size-15 parts; [ps_eu 0..10, p_partkey 11,
    #  p_type 12, p_size 13]
    psp = pn.JoinNode("inner", ps_eu, part, [0], [0])
    # x min_cost on partkey -> + [mc_partkey 14, min_cost 15]
    pspm = pn.JoinNode("inner", psp, min_cost, [0], [0])
    best = pn.FilterNode(
        P.EqualTo(ref(2, dt.FLOAT64), ref(15, dt.FLOAT64)), pspm)
    proj = pn.ProjectNode(
        [Alias(ref(5, dt.FLOAT64), "s_acctbal"),
         Alias(ref(7, dt.STRING), "n_name"),
         Alias(ref(0, dt.INT64), "p_partkey"),
         Alias(ref(12, dt.STRING), "p_type"),
         Alias(ref(2, dt.FLOAT64), "ps_supplycost")], best)
    sort = pn.SortNode([SortKeySpec.spark_default(0, ascending=False),
                        SortKeySpec.spark_default(1),
                        SortKeySpec.spark_default(2)], proj)
    return pn.LimitNode(100, sort)


def q8(data_dir: str) -> pn.PlanNode:
    """National market share: nation's share of regional revenue by
    year (CASE-conditional ratio of aggregates)."""
    from spark_rapids_tpu.expressions.conditional import If
    from spark_rapids_tpu.expressions.datetime import Year

    part = pn.FilterNode(
        P.EqualTo(ref(1, dt.STRING),
                  Literal("ECONOMY ANODIZED STEEL")),
        _scan(data_dir, "part", ["p_partkey", "p_type"]))
    li = _scan(data_dir, "lineitem",
               ["l_orderkey", "l_partkey", "l_suppkey",
                "l_extendedprice", "l_discount"])
    # li x part -> + [p_partkey 5, p_type 6]
    lp = pn.JoinNode("inner", li, part, [1], [0])
    orders = pn.FilterNode(
        P.And(P.GreaterThanOrEqual(ref(2, dt.DATE),
                                   Literal(_date_days("1995-01-01"),
                                           dt.DATE)),
              P.LessThanOrEqual(ref(2, dt.DATE),
                                Literal(_date_days("1996-12-31"),
                                        dt.DATE))),
        _scan(data_dir, "orders",
              ["o_orderkey", "o_custkey", "o_orderdate"]))
    # + [o_orderkey 7, o_custkey 8, o_orderdate 9]
    lpo = pn.JoinNode("inner", lp, orders, [0], [0])
    customer = _scan(data_dir, "customer", ["c_custkey", "c_nationkey"])
    # + [c_custkey 10, c_nationkey 11]
    lpoc = pn.JoinNode("inner", lpo, customer, [8], [0])
    region = pn.FilterNode(
        P.EqualTo(ref(1, dt.STRING), Literal("AMERICA")),
        _scan(data_dir, "region", ["r_regionkey", "r_name"]))
    n1 = _scan(data_dir, "nation", ["n_nationkey", "n_regionkey"])
    n1r = pn.JoinNode("inner", n1, region, [1], [0])
    # customer nation must be in AMERICA; + [n_nationkey 12,
    #  n_regionkey 13, r_regionkey 14, r_name 15]
    lpocn = pn.JoinNode("inner", lpoc, n1r, [11], [0])
    n2 = _scan(data_dir, "nation", ["n_nationkey", "n_name"])
    supplier = _scan(data_dir, "supplier", ["s_suppkey", "s_nationkey"])
    # supplier -> its nation name
    sn = pn.JoinNode("inner", supplier, n2, [1], [0])
    # + [s_suppkey 16, s_nationkey 17, n_nationkey 18, supp_nation 19]
    full = pn.JoinNode("inner", lpocn, sn, [2], [0])
    vol = ar.Multiply(ref(3, dt.FLOAT64),
                      ar.Subtract(Literal(1.0), ref(4, dt.FLOAT64)))
    brazil_vol = If(P.EqualTo(ref(19, dt.STRING), Literal("BRAZIL")),
                    vol, Literal(0.0))
    proj = pn.ProjectNode(
        [Alias(Year(ref(9, dt.DATE)), "o_year"),
         Alias(vol, "volume"), Alias(brazil_vol, "brazil_volume")],
        full)
    agg = pn.AggregateNode(
        [ref(0, dt.INT32)],
        [pn.AggCall(A.Sum(ref(2, dt.FLOAT64)), "brazil"),
         pn.AggCall(A.Sum(ref(1, dt.FLOAT64)), "total")],
        proj, grouping_names=["o_year"])
    share = pn.ProjectNode(
        [Alias(ref(0, dt.INT32), "o_year"),
         Alias(ar.Divide(ref(1, dt.FLOAT64), ref(2, dt.FLOAT64)),
               "mkt_share")], agg)
    return pn.SortNode([SortKeySpec.spark_default(0)], share)


def q15(data_dir: str) -> pn.PlanNode:
    """Top supplier: per-supplier revenue equal to the global maximum
    (the revenue view + scalar MAX subquery, decorrelated)."""
    li = pn.FilterNode(
        P.And(P.GreaterThanOrEqual(ref(3, dt.DATE),
                                   Literal(_date_days("1996-01-01"),
                                           dt.DATE)),
              P.LessThan(ref(3, dt.DATE),
                         Literal(_date_days("1996-04-01"), dt.DATE))),
        _scan(data_dir, "lineitem",
              ["l_suppkey", "l_extendedprice", "l_discount",
               "l_shipdate"]))
    rev = ar.Multiply(ref(1, dt.FLOAT64),
                      ar.Subtract(Literal(1.0), ref(2, dt.FLOAT64)))
    proj = pn.ProjectNode([Alias(ref(0, dt.INT64), "supplier_no"),
                           Alias(rev, "rev")], li)
    revenue = pn.AggregateNode(
        [ref(0, dt.INT64)],
        [pn.AggCall(A.Sum(ref(1, dt.FLOAT64)), "total_revenue")],
        proj, grouping_names=["supplier_no"])
    max_rev = pn.AggregateNode(
        [], [pn.AggCall(A.Max(ref(1, dt.FLOAT64)), "max_rev")], revenue)
    max_keyed = _lit_one(max_rev, ["max_rev"])
    rev_keyed = _lit_one(revenue, ["supplier_no", "total_revenue"])
    j = pn.JoinNode("inner", rev_keyed, max_keyed, [2], [1])
    top = pn.FilterNode(P.EqualTo(ref(1, dt.FLOAT64),
                                  ref(3, dt.FLOAT64)), j)
    supplier = _scan(data_dir, "supplier", ["s_suppkey", "s_acctbal"])
    # + [s_suppkey 5, s_acctbal 6]
    js = pn.JoinNode("inner", top, supplier, [0], [0])
    proj2 = pn.ProjectNode(
        [Alias(ref(5, dt.INT64), "s_suppkey"),
         Alias(ref(1, dt.FLOAT64), "total_revenue")], js)
    return pn.SortNode([SortKeySpec.spark_default(0)], proj2)


def q20(data_dir: str) -> pn.PlanNode:
    """Potential part promotion: suppliers whose stock exceeds half a
    year's shipments of forest parts (nested IN subqueries as
    semi-joins + a decorrelated per-(part,supp) quantity sum)."""
    from spark_rapids_tpu.expressions.strings import StartsWith

    part = pn.FilterNode(
        StartsWith(ref(1, dt.STRING), "STANDARD"),
        _scan(data_dir, "part", ["p_partkey", "p_type"]))
    li = pn.FilterNode(
        P.And(P.GreaterThanOrEqual(ref(3, dt.DATE),
                                   Literal(_date_days("1994-01-01"),
                                           dt.DATE)),
              P.LessThan(ref(3, dt.DATE),
                         Literal(_date_days("1995-01-01"), dt.DATE))),
        _scan(data_dir, "lineitem",
              ["l_partkey", "l_suppkey", "l_quantity", "l_shipdate"]))
    shipped = pn.AggregateNode(
        [ref(0, dt.INT64), ref(1, dt.INT64)],
        [pn.AggCall(A.Sum(ref(2, dt.FLOAT64)), "qty")],
        li, grouping_names=["l_partkey", "l_suppkey"])
    partsupp = _scan(data_dir, "partsupp",
                     ["ps_partkey", "ps_suppkey", "ps_availqty"])
    # only forest parts
    ps_f = pn.JoinNode("left_semi", partsupp, part, [0], [0])
    # x shipped quantities on (part, supp) -> + [l_partkey 3,
    #  l_suppkey 4, qty 5]
    psq = pn.JoinNode("inner", ps_f, shipped, [0, 1], [0, 1])
    over = pn.FilterNode(
        P.GreaterThan(Cast(ref(2, dt.INT32), dt.FLOAT64),
                      ar.Multiply(Literal(0.5), ref(5, dt.FLOAT64))),
        psq)
    supplier = _scan(data_dir, "supplier",
                     ["s_suppkey", "s_nationkey"])
    nation = pn.FilterNode(
        P.EqualTo(ref(1, dt.STRING), Literal("CANADA")),
        _scan(data_dir, "nation", ["n_nationkey", "n_name"]))
    sn = pn.JoinNode("inner", supplier, nation, [1], [0])
    good = pn.JoinNode("left_semi", sn, over, [0], [1])
    proj = pn.ProjectNode([Alias(ref(0, dt.INT64), "s_suppkey")], good)
    return pn.SortNode([SortKeySpec.spark_default(0)], proj)


def q21(data_dir: str) -> pn.PlanNode:
    """Suppliers who kept orders waiting: the EXISTS/NOT-EXISTS pair
    decorrelated through per-order distinct-supplier counts (orders
    with multiple suppliers where ONLY this supplier delivered late)."""
    li = _scan(data_dir, "lineitem",
               ["l_orderkey", "l_suppkey", "l_commitdate",
                "l_receiptdate"])
    late = pn.FilterNode(P.GreaterThan(ref(3, dt.DATE),
                                       ref(2, dt.DATE)), li)
    # per order: how many distinct suppliers at all / delivered late
    supp_all = pn.AggregateNode(
        [ref(0, dt.INT64)],
        [pn.AggCall(A.Count(ref(1, dt.INT64), distinct=True), "n")],
        li, grouping_names=["l_orderkey"])
    supp_late = pn.AggregateNode(
        [ref(0, dt.INT64)],
        [pn.AggCall(A.Count(ref(1, dt.INT64), distinct=True), "n")],
        late, grouping_names=["l_orderkey"])
    multi = pn.FilterNode(P.GreaterThan(ref(1, dt.INT64),
                                        Literal(1, dt.INT64)), supp_all)
    solo_late = pn.FilterNode(P.EqualTo(ref(1, dt.INT64),
                                        Literal(1, dt.INT64)), supp_late)
    orders = pn.FilterNode(
        P.EqualTo(ref(1, dt.STRING), Literal("F")),
        _scan(data_dir, "orders", ["o_orderkey", "o_orderstatus"]))
    # failing orders with >1 supplier where exactly one was late
    o1 = pn.JoinNode("left_semi", orders, multi, [0], [0])
    o2 = pn.JoinNode("left_semi", o1, solo_late, [0], [0])
    # the waiting supplier = the late lineitem's supplier on those orders
    late_on = pn.JoinNode("left_semi", late, o2, [0], [0])
    supplier = _scan(data_dir, "supplier",
                     ["s_suppkey", "s_nationkey"])
    nation = pn.FilterNode(
        P.EqualTo(ref(1, dt.STRING), Literal("SAUDI ARABIA")),
        _scan(data_dir, "nation", ["n_nationkey", "n_name"]))
    sn = pn.JoinNode("inner", supplier, nation, [1], [0])
    # [late 0-3, s_suppkey 4, s_nationkey 5, n_nationkey 6, n_name 7]
    ls = pn.JoinNode("inner", late_on, sn, [1], [0])
    agg = pn.AggregateNode(
        [ref(4, dt.INT64)], [pn.AggCall(A.Count(), "numwait")],
        ls, grouping_names=["s_suppkey"])
    sort = pn.SortNode([SortKeySpec.spark_default(1, ascending=False),
                        SortKeySpec.spark_default(0)], agg)
    return pn.LimitNode(100, sort)


QUERIES = {"tpch_q1": q1, "tpch_q2": q2, "tpch_q3": q3, "tpch_q4": q4,
           "tpch_q5": q5, "tpch_q6": q6, "tpch_q7": q7, "tpch_q8": q8,
           "tpch_q9": q9, "tpch_q10": q10, "tpch_q11": q11,
           "tpch_q12": q12, "tpch_q13": q13, "tpch_q14": q14,
           "tpch_q15": q15, "tpch_q16": q16, "tpch_q17": q17,
           "tpch_q18": q18, "tpch_q19": q19, "tpch_q20": q20,
           "tpch_q21": q21, "tpch_q22": q22}
