"""TPC-H-like query definitions as plan trees (TpchLikeSpark.scala
analogue: each query is a function from the data directory to a plan)."""
from __future__ import annotations

import os

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions import aggregates as A
from spark_rapids_tpu.expressions import arithmetic as ar
from spark_rapids_tpu.expressions import predicates as P
from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Literal)
from spark_rapids_tpu.io import ParquetSource
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.plan import nodes as pn


def _date_days(s: str) -> int:
    return int((np.datetime64(s) - np.datetime64("1970-01-01")
                ).astype(int))


def _scan(data_dir: str, table: str, columns):
    return pn.ScanNode(ParquetSource(os.path.join(data_dir, table),
                                     columns=columns))


def ref(i, t):
    return BoundReference(i, t)


def q1(data_dir: str) -> pn.PlanNode:
    """Pricing summary report: scan-heavy groupby with many aggregates
    (the reference's headline scan+agg shape)."""
    scan = _scan(data_dir, "lineitem",
                 ["l_returnflag", "l_linestatus", "l_quantity",
                  "l_extendedprice", "l_discount", "l_tax", "l_shipdate"])
    filt = pn.FilterNode(
        P.LessThanOrEqual(ref(6, dt.DATE),
                          Literal(_date_days("1998-09-02"), dt.DATE)),
        scan)
    qty = ref(2, dt.FLOAT64)
    price = ref(3, dt.FLOAT64)
    disc = ref(4, dt.FLOAT64)
    tax = ref(5, dt.FLOAT64)
    disc_price = ar.Multiply(price, ar.Subtract(Literal(1.0), disc))
    charge = ar.Multiply(disc_price, ar.Add(Literal(1.0), tax))
    agg = pn.AggregateNode(
        [ref(0, dt.STRING), ref(1, dt.STRING)],
        [pn.AggCall(A.Sum(qty), "sum_qty"),
         pn.AggCall(A.Sum(price), "sum_base_price"),
         pn.AggCall(A.Sum(disc_price), "sum_disc_price"),
         pn.AggCall(A.Sum(charge), "sum_charge"),
         pn.AggCall(A.Average(qty), "avg_qty"),
         pn.AggCall(A.Average(price), "avg_price"),
         pn.AggCall(A.Average(disc), "avg_disc"),
         pn.AggCall(A.Count(), "count_order")],
        filt, grouping_names=["l_returnflag", "l_linestatus"])
    return pn.SortNode([SortKeySpec.spark_default(0),
                        SortKeySpec.spark_default(1)], agg)


def q6(data_dir: str) -> pn.PlanNode:
    """Forecasting revenue change: tight filter + global aggregate."""
    scan = _scan(data_dir, "lineitem",
                 ["l_extendedprice", "l_discount", "l_quantity",
                  "l_shipdate"])
    d = ref(3, dt.DATE)
    cond = P.And(
        P.And(P.GreaterThanOrEqual(d, Literal(_date_days("1994-01-01"),
                                              dt.DATE)),
              P.LessThan(d, Literal(_date_days("1995-01-01"), dt.DATE))),
        P.And(P.And(P.GreaterThanOrEqual(ref(1, dt.FLOAT64),
                                         Literal(0.05)),
                    P.LessThanOrEqual(ref(1, dt.FLOAT64),
                                      Literal(0.07))),
              P.LessThan(ref(2, dt.FLOAT64), Literal(24.0))))
    filt = pn.FilterNode(cond, scan)
    revenue = ar.Multiply(ref(0, dt.FLOAT64), ref(1, dt.FLOAT64))
    return pn.AggregateNode([], [pn.AggCall(A.Sum(revenue), "revenue")],
                            filt)


def q3(data_dir: str) -> pn.PlanNode:
    """Shipping priority: 3-way join + groupby + top-N (the multi-way
    join shape of BASELINE config #3)."""
    customer = _scan(data_dir, "customer", ["c_custkey", "c_mktsegment"])
    orders = _scan(data_dir, "orders",
                   ["o_orderkey", "o_custkey", "o_orderdate",
                    "o_shippriority"])
    lineitem = _scan(data_dir, "lineitem",
                     ["l_orderkey", "l_extendedprice", "l_discount",
                      "l_shipdate"])
    cust_f = pn.FilterNode(
        P.EqualTo(ref(1, dt.STRING), Literal("BUILDING")), customer)
    ord_f = pn.FilterNode(
        P.LessThan(ref(2, dt.DATE),
                   Literal(_date_days("1995-03-15"), dt.DATE)), orders)
    li_f = pn.FilterNode(
        P.GreaterThan(ref(3, dt.DATE),
                      Literal(_date_days("1995-03-15"), dt.DATE)),
        lineitem)
    # customer ⋈ orders on custkey
    co = pn.JoinNode("inner", cust_f, ord_f, [0], [1])
    # (c..., o...) ⋈ lineitem on orderkey;  co schema:
    # [c_custkey, c_mktsegment, o_orderkey, o_custkey, o_orderdate,
    #  o_shippriority]
    col = pn.JoinNode("inner", co, li_f, [2], [0])
    # col schema adds [l_orderkey, l_extendedprice, l_discount,
    # l_shipdate] at 6..9
    revenue = ar.Multiply(ref(7, dt.FLOAT64),
                          ar.Subtract(Literal(1.0), ref(8, dt.FLOAT64)))
    proj = pn.ProjectNode(
        [Alias(ref(6, dt.INT64), "l_orderkey"),
         Alias(ref(4, dt.DATE), "o_orderdate"),
         Alias(ref(5, dt.INT32), "o_shippriority"),
         Alias(revenue, "rev")], col)
    agg = pn.AggregateNode(
        [ref(0, dt.INT64), ref(1, dt.DATE), ref(2, dt.INT32)],
        [pn.AggCall(A.Sum(ref(3, dt.FLOAT64)), "revenue")],
        proj, grouping_names=["l_orderkey", "o_orderdate",
                              "o_shippriority"])
    sort = pn.SortNode([SortKeySpec.spark_default(3, ascending=False),
                        SortKeySpec.spark_default(1)], agg)
    return pn.LimitNode(10, sort)


def q4(data_dir: str) -> pn.PlanNode:
    """Order priority checking: date-window filter + EXISTS-subquery as a
    left-semi join + groupby count."""
    orders = _scan(data_dir, "orders",
                   ["o_orderkey", "o_orderdate", "o_orderpriority"])
    ord_f = pn.FilterNode(
        P.And(P.GreaterThanOrEqual(ref(1, dt.DATE),
                                   Literal(_date_days("1993-07-01"),
                                           dt.DATE)),
              P.LessThan(ref(1, dt.DATE),
                         Literal(_date_days("1993-10-01"), dt.DATE))),
        orders)
    lineitem = _scan(data_dir, "lineitem",
                     ["l_orderkey", "l_commitdate", "l_receiptdate"])
    li_f = pn.FilterNode(P.LessThan(ref(1, dt.DATE), ref(2, dt.DATE)),
                         lineitem)
    semi = pn.JoinNode("left_semi", ord_f, li_f, [0], [0])
    agg = pn.AggregateNode(
        [ref(2, dt.STRING)], [pn.AggCall(A.Count(), "order_count")],
        semi, grouping_names=["o_orderpriority"])
    return pn.SortNode([SortKeySpec.spark_default(0)], agg)


def q5(data_dir: str) -> pn.PlanNode:
    """Local supplier volume: 6-table join chain + groupby revenue
    (the TPC-DS q72 / TPCxBB q3 multi-way-join shape of BASELINE
    config #3)."""
    region = pn.FilterNode(
        P.EqualTo(ref(1, dt.STRING), Literal("ASIA")),
        _scan(data_dir, "region", ["r_regionkey", "r_name"]))
    nation = _scan(data_dir, "nation",
                   ["n_nationkey", "n_name", "n_regionkey"])
    # nation x region -> [n_nationkey, n_name, n_regionkey, r_regionkey,
    #                     r_name]
    nr = pn.JoinNode("inner", nation, region, [2], [0])
    supplier = _scan(data_dir, "supplier", ["s_suppkey", "s_nationkey"])
    # -> [s_suppkey, s_nationkey, n_nationkey, n_name, n_regionkey,
    #     r_regionkey, r_name]
    snr = pn.JoinNode("inner", supplier, nr, [1], [0])
    customer = _scan(data_dir, "customer", ["c_custkey", "c_nationkey"])
    orders = pn.FilterNode(
        P.And(P.GreaterThanOrEqual(ref(2, dt.DATE),
                                   Literal(_date_days("1994-01-01"),
                                           dt.DATE)),
              P.LessThan(ref(2, dt.DATE),
                         Literal(_date_days("1995-01-01"), dt.DATE))),
        _scan(data_dir, "orders",
              ["o_orderkey", "o_custkey", "o_orderdate"]))
    # -> [c_custkey, c_nationkey, o_orderkey, o_custkey, o_orderdate]
    co = pn.JoinNode("inner", customer, orders, [0], [1])
    lineitem = _scan(data_dir, "lineitem",
                     ["l_orderkey", "l_suppkey", "l_extendedprice",
                      "l_discount"])
    # -> co + [l_orderkey, l_suppkey, l_extendedprice, l_discount] @ 5..8
    col = pn.JoinNode("inner", co, lineitem, [2], [0])
    # l_suppkey = s_suppkey AND c_nationkey = s_nationkey (the "local
    # supplier" constraint); snr cols land at 9..15, n_name @ 12
    full = pn.JoinNode("inner", col, snr, [6, 1], [0, 1])
    revenue = ar.Multiply(ref(7, dt.FLOAT64),
                          ar.Subtract(Literal(1.0), ref(8, dt.FLOAT64)))
    proj = pn.ProjectNode([Alias(ref(12, dt.STRING), "n_name"),
                           Alias(revenue, "rev")], full)
    agg = pn.AggregateNode(
        [ref(0, dt.STRING)],
        [pn.AggCall(A.Sum(ref(1, dt.FLOAT64)), "revenue")],
        proj, grouping_names=["n_name"])
    return pn.SortNode([SortKeySpec.spark_default(1, ascending=False)],
                       agg)


def q10(data_dir: str) -> pn.PlanNode:
    """Returned item reporting: 4-table join, wide groupby, top 20."""
    customer = _scan(data_dir, "customer",
                     ["c_custkey", "c_nationkey", "c_acctbal", "c_name",
                      "c_phone"])
    orders = pn.FilterNode(
        P.And(P.GreaterThanOrEqual(ref(2, dt.DATE),
                                   Literal(_date_days("1993-10-01"),
                                           dt.DATE)),
              P.LessThan(ref(2, dt.DATE),
                         Literal(_date_days("1994-01-01"), dt.DATE))),
        _scan(data_dir, "orders",
              ["o_orderkey", "o_custkey", "o_orderdate"]))
    lineitem = pn.FilterNode(
        P.EqualTo(ref(3, dt.STRING), Literal("R")),
        _scan(data_dir, "lineitem",
              ["l_orderkey", "l_extendedprice", "l_discount",
               "l_returnflag"]))
    nation = _scan(data_dir, "nation", ["n_nationkey", "n_name"])
    # [c...0-4, o_orderkey 5, o_custkey 6, o_orderdate 7]
    co = pn.JoinNode("inner", customer, orders, [0], [1])
    # + [l_orderkey 8, l_extendedprice 9, l_discount 10, l_returnflag 11]
    col = pn.JoinNode("inner", co, lineitem, [5], [0])
    # + [n_nationkey 12, n_name 13]
    con = pn.JoinNode("inner", col, nation, [1], [0])
    revenue = ar.Multiply(ref(9, dt.FLOAT64),
                          ar.Subtract(Literal(1.0), ref(10, dt.FLOAT64)))
    proj = pn.ProjectNode(
        [Alias(ref(0, dt.INT64), "c_custkey"),
         Alias(ref(3, dt.STRING), "c_name"),
         Alias(ref(2, dt.FLOAT64), "c_acctbal"),
         Alias(ref(4, dt.STRING), "c_phone"),
         Alias(ref(13, dt.STRING), "n_name"),
         Alias(revenue, "rev")], con)
    agg = pn.AggregateNode(
        [ref(0, dt.INT64), ref(1, dt.STRING), ref(2, dt.FLOAT64),
         ref(3, dt.STRING), ref(4, dt.STRING)],
        [pn.AggCall(A.Sum(ref(5, dt.FLOAT64)), "revenue")],
        proj, grouping_names=["c_custkey", "c_name", "c_acctbal",
                              "c_phone", "n_name"])
    sort = pn.SortNode([SortKeySpec.spark_default(5, ascending=False)],
                       agg)
    return pn.LimitNode(20, sort)


def q12(data_dir: str) -> pn.PlanNode:
    """Shipping modes and order priority: join + conditional aggregation
    (CASE WHEN inside SUM)."""
    from spark_rapids_tpu.expressions.conditional import If
    from spark_rapids_tpu.expressions.predicates import In

    orders = _scan(data_dir, "orders",
                   ["o_orderkey", "o_orderpriority"])
    li = _scan(data_dir, "lineitem",
               ["l_orderkey", "l_shipdate", "l_commitdate",
                "l_receiptdate", "l_shipmode"])
    li_f = pn.FilterNode(
        P.And(P.And(In(ref(4, dt.STRING),
                       [Literal("MAIL"), Literal("SHIP")]),
                    P.LessThan(ref(2, dt.DATE), ref(3, dt.DATE))),
              P.And(P.LessThan(ref(1, dt.DATE), ref(2, dt.DATE)),
                    P.And(P.GreaterThanOrEqual(
                              ref(3, dt.DATE),
                              Literal(_date_days("1994-01-01"), dt.DATE)),
                          P.LessThan(
                              ref(3, dt.DATE),
                              Literal(_date_days("1995-01-01"),
                                      dt.DATE))))),
        li)
    # [o_orderkey 0, o_orderpriority 1, l_orderkey 2, ..., l_shipmode 6]
    j = pn.JoinNode("inner", orders, li_f, [0], [0])
    is_high = In(ref(1, dt.STRING),
                 [Literal("1-URGENT"), Literal("2-HIGH")])
    proj = pn.ProjectNode(
        [Alias(ref(6, dt.STRING), "l_shipmode"),
         Alias(If(is_high, Literal(1), Literal(0)), "high"),
         Alias(If(is_high, Literal(0), Literal(1)), "low")], j)
    agg = pn.AggregateNode(
        [ref(0, dt.STRING)],
        [pn.AggCall(A.Sum(ref(1, dt.INT64)), "high_line_count"),
         pn.AggCall(A.Sum(ref(2, dt.INT64)), "low_line_count")],
        proj, grouping_names=["l_shipmode"])
    return pn.SortNode([SortKeySpec.spark_default(0)], agg)


def q14(data_dir: str) -> pn.PlanNode:
    """Promotion effect: join + CASE WHEN ratio of global aggregates."""
    from spark_rapids_tpu.expressions.conditional import If
    from spark_rapids_tpu.expressions.strings import StartsWith

    li = pn.FilterNode(
        P.And(P.GreaterThanOrEqual(ref(3, dt.DATE),
                                   Literal(_date_days("1995-09-01"),
                                           dt.DATE)),
              P.LessThan(ref(3, dt.DATE),
                         Literal(_date_days("1995-10-01"), dt.DATE))),
        _scan(data_dir, "lineitem",
              ["l_partkey", "l_extendedprice", "l_discount",
               "l_shipdate"]))
    part = _scan(data_dir, "part", ["p_partkey", "p_type"])
    # + [p_partkey 4, p_type 5]
    j = pn.JoinNode("inner", li, part, [0], [0])
    rev = ar.Multiply(ref(1, dt.FLOAT64),
                      ar.Subtract(Literal(1.0), ref(2, dt.FLOAT64)))
    promo = If(StartsWith(ref(5, dt.STRING), "PROMO"), rev,
               Literal(0.0))
    proj = pn.ProjectNode([Alias(promo, "promo_rev"),
                           Alias(rev, "rev")], j)
    agg = pn.AggregateNode(
        [], [pn.AggCall(A.Sum(ref(0, dt.FLOAT64)), "sum_promo"),
             pn.AggCall(A.Sum(ref(1, dt.FLOAT64)), "sum_rev")], proj)
    ratio = ar.Multiply(Literal(100.0),
                        ar.Divide(ref(0, dt.FLOAT64),
                                  ref(1, dt.FLOAT64)))
    return pn.ProjectNode([Alias(ratio, "promo_revenue")], agg)


def q18(data_dir: str) -> pn.PlanNode:
    """Large volume customer: IN-subquery over a grouped HAVING filter
    realized as agg -> filter -> semi-join, then re-join + re-aggregate.
    (Threshold lowered from 300 to 100 for the synthetic -like data.)"""
    li_keys = _scan(data_dir, "lineitem", ["l_orderkey", "l_quantity"])
    big = pn.FilterNode(
        P.GreaterThan(ref(1, dt.FLOAT64), Literal(100.0)),
        pn.AggregateNode([ref(0, dt.INT64)],
                         [pn.AggCall(A.Sum(ref(1, dt.FLOAT64)), "sq")],
                         li_keys, grouping_names=["l_orderkey"]))
    orders = _scan(data_dir, "orders",
                   ["o_orderkey", "o_custkey", "o_totalprice",
                    "o_orderdate"])
    ord_big = pn.JoinNode("left_semi", orders, big, [0], [0])
    customer = _scan(data_dir, "customer", ["c_custkey", "c_name"])
    # [o... 0-3, c_custkey 4, c_name 5]
    oc = pn.JoinNode("inner", ord_big, customer, [1], [0])
    li = _scan(data_dir, "lineitem", ["l_orderkey", "l_quantity"])
    # + [l_orderkey 6, l_quantity 7]
    ocl = pn.JoinNode("inner", oc, li, [0], [0])
    agg = pn.AggregateNode(
        [ref(5, dt.STRING), ref(4, dt.INT64), ref(0, dt.INT64),
         ref(3, dt.DATE), ref(2, dt.FLOAT64)],
        [pn.AggCall(A.Sum(ref(7, dt.FLOAT64)), "sum_qty")],
        ocl, grouping_names=["c_name", "c_custkey", "o_orderkey",
                             "o_orderdate", "o_totalprice"])
    sort = pn.SortNode([SortKeySpec.spark_default(4, ascending=False),
                        SortKeySpec.spark_default(3)], agg)
    return pn.LimitNode(100, sort)


def q19(data_dir: str) -> pn.PlanNode:
    """Discounted revenue: equi-join on partkey with a 3-arm OR residual
    condition over both sides (brand/container/size/quantity bands)."""
    from spark_rapids_tpu.expressions.predicates import In
    from spark_rapids_tpu.expressions.strings import StartsWith

    li = pn.FilterNode(
        P.And(In(ref(4, dt.STRING),
                 [Literal("AIR"), Literal("REG AIR")]),
              P.EqualTo(ref(5, dt.STRING),
                        Literal("DELIVER IN PERSON"))),
        _scan(data_dir, "lineitem",
              ["l_partkey", "l_quantity", "l_extendedprice",
               "l_discount", "l_shipmode", "l_shipinstruct"]))
    part = _scan(data_dir, "part",
                 ["p_partkey", "p_brand", "p_size", "p_container"])
    qty = ref(1, dt.FLOAT64)
    # part columns land at 6..9 after the join
    brand = ref(7, dt.STRING)
    size = ref(8, dt.INT32)
    container = ref(9, dt.STRING)

    def arm(brand_lit, cont_prefix, qlo, qhi, smax):
        return P.And(
            P.And(P.EqualTo(brand, Literal(brand_lit)),
                  StartsWith(container, cont_prefix)),
            P.And(P.And(P.GreaterThanOrEqual(qty, Literal(float(qlo))),
                        P.LessThanOrEqual(qty, Literal(float(qhi)))),
                  P.LessThanOrEqual(size, Literal(smax, dt.INT32))))

    cond = P.Or(P.Or(arm("Brand#12", "SM", 1, 11, 5),
                     arm("Brand#23", "MED", 10, 20, 10)),
                arm("Brand#34", "LG", 20, 30, 15))
    j = pn.JoinNode("inner", li, part, [0], [0], condition=cond)
    rev = ar.Multiply(ref(2, dt.FLOAT64),
                      ar.Subtract(Literal(1.0), ref(3, dt.FLOAT64)))
    proj = pn.ProjectNode([Alias(rev, "rev")], j)
    return pn.AggregateNode(
        [], [pn.AggCall(A.Sum(ref(0, dt.FLOAT64)), "revenue")], proj)


QUERIES = {"tpch_q1": q1, "tpch_q3": q3, "tpch_q4": q4, "tpch_q5": q5,
           "tpch_q6": q6, "tpch_q10": q10, "tpch_q12": q12,
           "tpch_q14": q14, "tpch_q18": q18, "tpch_q19": q19}
