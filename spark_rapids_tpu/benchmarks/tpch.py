"""TPC-H-like query definitions as plan trees (TpchLikeSpark.scala
analogue: each query is a function from the data directory to a plan)."""
from __future__ import annotations

import os

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions import aggregates as A
from spark_rapids_tpu.expressions import arithmetic as ar
from spark_rapids_tpu.expressions import predicates as P
from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Literal)
from spark_rapids_tpu.io import ParquetSource
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.plan import nodes as pn


def _date_days(s: str) -> int:
    return int((np.datetime64(s) - np.datetime64("1970-01-01")
                ).astype(int))


def _scan(data_dir: str, table: str, columns):
    return pn.ScanNode(ParquetSource(os.path.join(data_dir, table),
                                     columns=columns))


def ref(i, t):
    return BoundReference(i, t)


def q1(data_dir: str) -> pn.PlanNode:
    """Pricing summary report: scan-heavy groupby with many aggregates
    (the reference's headline scan+agg shape)."""
    scan = _scan(data_dir, "lineitem",
                 ["l_returnflag", "l_linestatus", "l_quantity",
                  "l_extendedprice", "l_discount", "l_tax", "l_shipdate"])
    filt = pn.FilterNode(
        P.LessThanOrEqual(ref(6, dt.DATE),
                          Literal(_date_days("1998-09-02"), dt.DATE)),
        scan)
    qty = ref(2, dt.FLOAT64)
    price = ref(3, dt.FLOAT64)
    disc = ref(4, dt.FLOAT64)
    tax = ref(5, dt.FLOAT64)
    disc_price = ar.Multiply(price, ar.Subtract(Literal(1.0), disc))
    charge = ar.Multiply(disc_price, ar.Add(Literal(1.0), tax))
    agg = pn.AggregateNode(
        [ref(0, dt.STRING), ref(1, dt.STRING)],
        [pn.AggCall(A.Sum(qty), "sum_qty"),
         pn.AggCall(A.Sum(price), "sum_base_price"),
         pn.AggCall(A.Sum(disc_price), "sum_disc_price"),
         pn.AggCall(A.Sum(charge), "sum_charge"),
         pn.AggCall(A.Average(qty), "avg_qty"),
         pn.AggCall(A.Average(price), "avg_price"),
         pn.AggCall(A.Average(disc), "avg_disc"),
         pn.AggCall(A.Count(), "count_order")],
        filt, grouping_names=["l_returnflag", "l_linestatus"])
    return pn.SortNode([SortKeySpec.spark_default(0),
                        SortKeySpec.spark_default(1)], agg)


def q6(data_dir: str) -> pn.PlanNode:
    """Forecasting revenue change: tight filter + global aggregate."""
    scan = _scan(data_dir, "lineitem",
                 ["l_extendedprice", "l_discount", "l_quantity",
                  "l_shipdate"])
    d = ref(3, dt.DATE)
    cond = P.And(
        P.And(P.GreaterThanOrEqual(d, Literal(_date_days("1994-01-01"),
                                              dt.DATE)),
              P.LessThan(d, Literal(_date_days("1995-01-01"), dt.DATE))),
        P.And(P.And(P.GreaterThanOrEqual(ref(1, dt.FLOAT64),
                                         Literal(0.05)),
                    P.LessThanOrEqual(ref(1, dt.FLOAT64),
                                      Literal(0.07))),
              P.LessThan(ref(2, dt.FLOAT64), Literal(24.0))))
    filt = pn.FilterNode(cond, scan)
    revenue = ar.Multiply(ref(0, dt.FLOAT64), ref(1, dt.FLOAT64))
    return pn.AggregateNode([], [pn.AggCall(A.Sum(revenue), "revenue")],
                            filt)


def q3(data_dir: str) -> pn.PlanNode:
    """Shipping priority: 3-way join + groupby + top-N (the multi-way
    join shape of BASELINE config #3)."""
    customer = _scan(data_dir, "customer", ["c_custkey", "c_mktsegment"])
    orders = _scan(data_dir, "orders",
                   ["o_orderkey", "o_custkey", "o_orderdate",
                    "o_shippriority"])
    lineitem = _scan(data_dir, "lineitem",
                     ["l_orderkey", "l_extendedprice", "l_discount",
                      "l_shipdate"])
    cust_f = pn.FilterNode(
        P.EqualTo(ref(1, dt.STRING), Literal("BUILDING")), customer)
    ord_f = pn.FilterNode(
        P.LessThan(ref(2, dt.DATE),
                   Literal(_date_days("1995-03-15"), dt.DATE)), orders)
    li_f = pn.FilterNode(
        P.GreaterThan(ref(3, dt.DATE),
                      Literal(_date_days("1995-03-15"), dt.DATE)),
        lineitem)
    # customer ⋈ orders on custkey
    co = pn.JoinNode("inner", cust_f, ord_f, [0], [1])
    # (c..., o...) ⋈ lineitem on orderkey;  co schema:
    # [c_custkey, c_mktsegment, o_orderkey, o_custkey, o_orderdate,
    #  o_shippriority]
    col = pn.JoinNode("inner", co, li_f, [2], [0])
    # col schema adds [l_orderkey, l_extendedprice, l_discount,
    # l_shipdate] at 6..9
    revenue = ar.Multiply(ref(7, dt.FLOAT64),
                          ar.Subtract(Literal(1.0), ref(8, dt.FLOAT64)))
    proj = pn.ProjectNode(
        [Alias(ref(6, dt.INT64), "l_orderkey"),
         Alias(ref(4, dt.DATE), "o_orderdate"),
         Alias(ref(5, dt.INT32), "o_shippriority"),
         Alias(revenue, "rev")], col)
    agg = pn.AggregateNode(
        [ref(0, dt.INT64), ref(1, dt.DATE), ref(2, dt.INT32)],
        [pn.AggCall(A.Sum(ref(3, dt.FLOAT64)), "revenue")],
        proj, grouping_names=["l_orderkey", "o_orderdate",
                              "o_shippriority"])
    sort = pn.SortNode([SortKeySpec.spark_default(3, ascending=False),
                        SortKeySpec.spark_default(1)], agg)
    return pn.LimitNode(10, sort)


QUERIES = {"tpch_q1": q1, "tpch_q3": q3, "tpch_q6": q6}
