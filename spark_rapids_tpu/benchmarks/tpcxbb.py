"""TPCx-BB-like tables and query plans (TpcxbbLikeSpark.scala analogue).

The reference implements 30 "-like" queries over the BigBench retail
schema; the ones it can actually run exclude the UDTF/python/ML queries
(Q1/Q2/Q3/Q4/Q10 etc. throw UnsupportedOperationException,
TpcxbbLikeSpark.scala:808-832). This module covers the representative
SQL-only shapes on generated data:

- q5-like: clickstream x item categorical click counts per user, joined
  to customer demographics with CASE projections (the logistic-regression
  feature build, TpcxbbLikeSpark.scala:832-890)
- q9-like: store_sales x date_dim x customer_address x store x
  customer_demographics under 3-arm OR band predicates, global sum
  (TpcxbbLikeSpark.scala:1044-1119)
- q26-like: store_sales x item('Books') per-customer class-id count
  vector with HAVING (TpcxbbLikeSpark.scala:1968-2014)
"""
from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.benchmarks import tpcds
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions import aggregates as A
from spark_rapids_tpu.expressions import predicates as P
from spark_rapids_tpu.expressions.base import Alias, BoundReference, Literal
from spark_rapids_tpu.expressions.conditional import If
from spark_rapids_tpu.io import ParquetSource
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.plan import nodes as pn

EDUCATION = np.array(["Advanced Degree", "College", "4 yr Degree",
                      "2 yr Degree", "Secondary", "Primary", "Unknown"],
                     dtype=object)
MARITAL = np.array(["M", "S", "D", "W", "U"], dtype=object)
STATES = np.array(["KY", "GA", "NM", "MT", "OR", "IN", "WI", "MO", "WV",
                   "CA", "TX", "NY"], dtype=object)
COUNTRIES = np.array(["United States", "Canada", "Mexico"], dtype=object)


def gen_web_clickstreams(sf: float, seed: int = 41) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(5_000_000 * sf), 300)
    n_item = max(int(18_000 * sf), 50)
    n_cust = max(int(100_000 * sf), 20)
    user = rng.integers(1, n_cust + 1, n).astype(np.int64)
    user_null = rng.random(n) < 0.05  # anonymous clicks
    return pa.table({
        "wcs_user_sk": pa.array(
            [None if m else int(u) for u, m in zip(user, user_null)],
            type=pa.int64()),
        "wcs_item_sk": rng.integers(1, n_item + 1, n).astype(np.int64),
    })


def gen_customer(sf: float, seed: int = 42) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(100_000 * sf), 20)
    n_demo = max(int(1_000 * sf), 10)
    return pa.table({
        "c_customer_sk": np.arange(1, n + 1, dtype=np.int64),
        "c_current_cdemo_sk": rng.integers(1, n_demo + 1, n
                                           ).astype(np.int64),
    })


def gen_customer_demographics(sf: float, seed: int = 43) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(1_000 * sf), 10)
    return pa.table({
        "cd_demo_sk": np.arange(1, n + 1, dtype=np.int64),
        "cd_gender": np.array(["M", "F"], dtype=object)[
            rng.integers(0, 2, n)],
        "cd_education_status": EDUCATION[rng.integers(0, 7, n)],
        "cd_marital_status": MARITAL[rng.integers(0, 5, n)],
    })


def gen_customer_address(sf: float, seed: int = 44) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(50_000 * sf), 15)
    return pa.table({
        "ca_address_sk": np.arange(1, n + 1, dtype=np.int64),
        "ca_country": COUNTRIES[rng.integers(0, 3, n)],
        "ca_state": STATES[rng.integers(0, 12, n)],
    })


def gen_store(sf: float, seed: int = 45) -> pa.Table:
    n = max(int(12 * sf), 2)
    return pa.table({
        "s_store_sk": np.arange(1, n + 1, dtype=np.int64),
    })


GENERATORS = {
    "web_clickstreams": gen_web_clickstreams,
    "customer": gen_customer,
    "customer_demographics": gen_customer_demographics,
    "customer_address": gen_customer_address,
    "store": gen_store,
}


def write_tables(data_dir: str, sf: float, files_per_table: int = 4
                 ) -> None:
    """BigBench tables + the shared retail facts/dims from the TPC-DS-like
    generators (store_sales/item/date_dim)."""
    tpcds.write_tables(data_dir, sf,
                       tables=["store_sales", "item", "date_dim"],
                       files_per_table=files_per_table)
    os.makedirs(data_dir, exist_ok=True)
    for name, gen in GENERATORS.items():
        table = gen(sf)
        tdir = os.path.join(data_dir, name)
        os.makedirs(tdir, exist_ok=True)
        per = -(-table.num_rows // files_per_table)
        for i in range(files_per_table):
            chunk = table.slice(i * per, per)
            if chunk.num_rows:
                pq.write_table(chunk,
                               os.path.join(tdir,
                                            f"part-{i:03d}.parquet"))


def ref(i, t):
    return BoundReference(i, t)


def _scan(data_dir: str, table: str, columns):
    return pn.ScanNode(ParquetSource(os.path.join(data_dir, table),
                                     columns=columns))


def _count_if(cond):
    """count(CASE WHEN cond THEN 1 ELSE NULL END)"""
    return A.Count(If(cond, Literal(1, dt.INT64),
                      Literal(None, dt.INT64)))


def _sum_if(cond):
    """SUM(CASE WHEN cond THEN 1 ELSE 0 END)"""
    return A.Sum(If(cond, Literal(1, dt.INT64), Literal(0, dt.INT64)))


def q5(data_dir: str) -> pn.PlanNode:
    """Per-user clicks-per-category feature vector joined to
    demographics (TpcxbbLikeSpark.scala:832-890)."""
    clicks = pn.FilterNode(
        P.IsNotNull(ref(0, dt.INT64)),
        _scan(data_dir, "web_clickstreams",
              ["wcs_user_sk", "wcs_item_sk"]))
    item = _scan(data_dir, "item",
                 ["i_item_sk", "i_category", "i_category_id"])
    # [wcs_user_sk 0, wcs_item_sk 1, i_item_sk 2, i_category 3,
    #  i_category_id 4]
    ci = pn.JoinNode("inner", clicks, item, [1], [0])
    cat_id = ref(4, dt.INT32)
    aggs = [pn.AggCall(_sum_if(P.EqualTo(ref(3, dt.STRING),
                                         Literal("Books"))),
                       "clicks_in_category")]
    for k in range(1, 8):
        aggs.append(pn.AggCall(
            _sum_if(P.EqualTo(cat_id, Literal(k, dt.INT32))),
            f"clicks_in_{k}"))
    user_clicks = pn.AggregateNode([ref(0, dt.INT64)], aggs, ci,
                                   grouping_names=["wcs_user_sk"])
    customer = _scan(data_dir, "customer",
                     ["c_customer_sk", "c_current_cdemo_sk"])
    # user_clicks has 9 cols; + [c_customer_sk 9, c_current_cdemo_sk 10]
    uc = pn.JoinNode("inner", user_clicks, customer, [0], [0])
    demo = _scan(data_dir, "customer_demographics",
                 ["cd_demo_sk", "cd_gender", "cd_education_status"])
    # + [cd_demo_sk 11, cd_gender 12, cd_education_status 13]
    ucd = pn.JoinNode("inner", uc, demo, [10], [0])
    college = If(
        P.In(ref(13, dt.STRING),
             [Literal("Advanced Degree"), Literal("College"),
              Literal("4 yr Degree"), Literal("2 yr Degree")]),
        Literal(1, dt.INT64), Literal(0, dt.INT64))
    male = If(P.EqualTo(ref(12, dt.STRING), Literal("M")),
              Literal(1, dt.INT64), Literal(0, dt.INT64))
    outs = [Alias(ref(1, dt.INT64), "clicks_in_category"),
            Alias(college, "college_education"), Alias(male, "male")]
    for k in range(1, 8):
        outs.append(Alias(ref(1 + k, dt.INT64), f"clicks_in_{k}"))
    return pn.ProjectNode(outs, ucd)


def q9(data_dir: str) -> pn.PlanNode:
    """Banded OR-predicate multi-join global sum
    (TpcxbbLikeSpark.scala:1044-1119)."""
    ss = _scan(data_dir, "store_sales",
               ["ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
                "ss_store_sk", "ss_quantity", "ss_sales_price",
                "ss_net_profit"])
    dd = pn.FilterNode(
        P.EqualTo(ref(1, dt.INT32), Literal(2000, dt.INT32)),
        _scan(data_dir, "date_dim", ["d_date_sk", "d_year"]))
    # [ss 0-6, d_date_sk 7, d_year 8]
    s1 = pn.JoinNode("inner", ss, dd, [0], [0])
    # reuse customer_sk as the address key (the -like data keys addresses
    # by customer) — + [ca_address_sk 9, ca_country 10, ca_state 11]
    ca = _scan(data_dir, "customer_address",
               ["ca_address_sk", "ca_country", "ca_state"])
    s2 = pn.JoinNode("inner", s1, ca, [2], [0])
    store = _scan(data_dir, "store", ["s_store_sk"])
    # + [s_store_sk 12]
    s3 = pn.JoinNode("inner", s2, store, [3], [0])
    demo = _scan(data_dir, "customer_demographics",
                 ["cd_demo_sk", "cd_marital_status",
                  "cd_education_status"])
    # demo keyed by customer_sk % n_demo at generation; join through
    # customer_sk is the -like simplification; + [cd_demo_sk 13,
    # cd_marital_status 14, cd_education_status 15]
    s4 = pn.JoinNode("inner", s3, demo, [2], [0])
    price = ref(5, dt.FLOAT64)
    profit = ref(6, dt.FLOAT64)
    md = P.And(P.EqualTo(ref(14, dt.STRING), Literal("M")),
               P.EqualTo(ref(15, dt.STRING), Literal("4 yr Degree")))

    def band(e, lo, hi):
        return P.And(P.GreaterThanOrEqual(e, Literal(float(lo))),
                     P.LessThanOrEqual(e, Literal(float(hi))))

    arm_a = P.Or(P.Or(P.And(md, band(price, 100, 150)),
                      P.And(md, band(price, 50, 200))),
                 P.And(md, band(price, 150, 200)))
    us = P.EqualTo(ref(10, dt.STRING), Literal("United States"))

    def states(*ss):
        return P.In(ref(11, dt.STRING), [Literal(s) for s in ss])

    arm_b = P.Or(
        P.Or(P.And(P.And(us, states("KY", "GA", "NM")),
                   band(profit, 0, 2000)),
             P.And(P.And(us, states("MT", "OR", "IN")),
                   band(profit, 150, 3000))),
        P.And(P.And(us, states("WI", "MO", "WV")),
              band(profit, 50, 25000)))
    filt = pn.FilterNode(P.And(arm_a, arm_b), s4)
    return pn.AggregateNode(
        [], [pn.AggCall(A.Sum(ref(4, dt.INT32)), "sum_quantity")], filt)


def q26(data_dir: str) -> pn.PlanNode:
    """Per-customer class-id purchase-count vector with HAVING
    (TpcxbbLikeSpark.scala:1968-2014); class ids reduced to 8 to match
    the generated item table."""
    ss = pn.FilterNode(
        P.IsNotNull(ref(1, dt.INT64)),
        _scan(data_dir, "store_sales", ["ss_item_sk", "ss_customer_sk"]))
    item = pn.FilterNode(
        P.In(ref(1, dt.STRING), [Literal("Books")]),
        _scan(data_dir, "item",
              ["i_item_sk", "i_category", "i_class_id"]))
    # [ss_item_sk 0, ss_customer_sk 1, i_item_sk 2, i_category 3,
    #  i_class_id 4]
    j = pn.JoinNode("inner", ss, item, [0], [0])
    class_id = ref(4, dt.INT32)
    aggs = [pn.AggCall(_count_if(P.EqualTo(class_id,
                                           Literal(k, dt.INT32))),
                       f"id{k}") for k in range(1, 9)]
    aggs.append(pn.AggCall(A.Count(ref(0, dt.INT64)), "cnt"))
    agg = pn.AggregateNode([ref(1, dt.INT64)], aggs, j,
                           grouping_names=["cid"])
    having = pn.FilterNode(P.GreaterThan(ref(9, dt.INT64),
                                         Literal(5, dt.INT64)), agg)
    proj = pn.ProjectNode(
        [Alias(ref(0, dt.INT64), "cid")] +
        [Alias(ref(k, dt.INT64), f"id{k}") for k in range(1, 9)],
        having)
    return pn.SortNode([SortKeySpec.spark_default(0)], proj)


QUERIES = {"tpcxbb_q5": q5, "tpcxbb_q9": q9, "tpcxbb_q26": q26}
