"""TPCx-BB-like tables and queries (TpcxbbLikeSpark.scala analogue).

The reference implements 30 "-like" queries over the BigBench retail
schema; the ones it can actually run exclude the UDTF/python/ML queries
(Q1-4/8/10/18/19/27/29/30 throw UnsupportedOperationException,
TpcxbbLikeSpark.scala:808-832). This module covers ALL 19 runnable
queries on generated data: q5/q6/q9/q11/q26 as hand-built plan trees
(round 1-2), the other 14 as SQL text through the engine's own front
end (round 3), each oracle-verified in tests/test_benchmarks.py. The
north-star metric is this suite's geomean (BASELINE.md)."""
from __future__ import annotations

import functools
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.benchmarks import tpcds
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions import aggregates as A
from spark_rapids_tpu.expressions import arithmetic as ar
from spark_rapids_tpu.expressions import predicates as P
from spark_rapids_tpu.expressions.base import Alias, BoundReference, Literal
from spark_rapids_tpu.expressions.cast import Cast
from spark_rapids_tpu.expressions.conditional import If
from spark_rapids_tpu.io import ParquetSource
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.plan import nodes as pn

EDUCATION = np.array(["Advanced Degree", "College", "4 yr Degree",
                      "2 yr Degree", "Secondary", "Primary", "Unknown"],
                     dtype=object)
MARITAL = np.array(["M", "S", "D", "W", "U"], dtype=object)
STATES = np.array(["KY", "GA", "NM", "MT", "OR", "IN", "WI", "MO", "WV",
                   "CA", "TX", "NY"], dtype=object)
COUNTRIES = np.array(["United States", "Canada", "Mexico"], dtype=object)


DATE_SK_LO = 2450815          # date_dim's base (tpcds.gen_date_dim)
DATE_SK_HI = 2450815 + 5 * 365


def gen_web_clickstreams(sf: float, seed: int = 41) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(5_000_000 * sf), 300)
    n_item = max(int(18_000 * sf), 50)
    n_cust = max(int(100_000 * sf), 20)
    user = rng.integers(1, n_cust + 1, n).astype(np.int64)
    user_null = rng.random(n) < 0.05  # anonymous clicks
    sales = rng.integers(1, 1 << 30, n)
    sales_null = rng.random(n) < 0.9  # most clicks are views, not buys
    return pa.table({
        "wcs_user_sk": pa.array(
            [None if m else int(u) for u, m in zip(user, user_null)],
            type=pa.int64()),
        "wcs_item_sk": rng.integers(1, n_item + 1, n).astype(np.int64),
        "wcs_click_date_sk": rng.integers(DATE_SK_LO, DATE_SK_HI, n
                                          ).astype(np.int64),
        "wcs_sales_sk": pa.array(
            [None if m else int(s) for s, m in zip(sales, sales_null)],
            type=pa.int64()),
    })


def gen_customer_demographics(sf: float, seed: int = 43) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(1_000 * sf), 10)
    return pa.table({
        "cd_demo_sk": np.arange(1, n + 1, dtype=np.int64),
        "cd_gender": np.array(["M", "F"], dtype=object)[
            rng.integers(0, 2, n)],
        "cd_education_status": EDUCATION[rng.integers(0, 7, n)],
        "cd_marital_status": MARITAL[rng.integers(0, 5, n)],
    })


def gen_product_reviews(sf: float, seed: int = 47) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(60_000 * sf), 100)
    n_item = max(int(18_000 * sf), 50)
    item = rng.integers(1, n_item + 1, n).astype(np.int64)
    null = rng.random(n) < 0.03
    words = np.array(["great", "poor", "fine", "broken", "love", "meh"],
                     dtype=object)
    return pa.table({
        "pr_review_sk": np.arange(1, n + 1, dtype=np.int64),
        "pr_item_sk": pa.array(
            [None if m else int(i) for i, m in zip(item, null)],
            type=pa.int64()),
        "pr_review_rating": rng.integers(1, 6, n).astype(np.int32),
        "pr_review_content": np.array(
            [f"{words[i % 6]} product {i % 97}" for i in range(n)],
            dtype=object),
    })


def gen_item_marketprices(sf: float, seed: int = 49) -> pa.Table:
    rng = np.random.default_rng(seed)
    n_item = max(int(18_000 * sf), 50)
    per_item = 3  # few competitor price points per item
    n = n_item * per_item
    start = rng.integers(2450915, 2450815 + 4 * 365, n).astype(np.int64)
    return pa.table({
        "imp_sk": np.arange(1, n + 1, dtype=np.int64),
        "imp_item_sk": np.repeat(
            np.arange(1, n_item + 1, dtype=np.int64), per_item),
        "imp_competitor_price": np.round(0.3 + rng.random(n) * 2.5, 2),
        "imp_start_date": start,
        "imp_end_date": start + rng.integers(30, 120, n),
    })


GENERATORS = {
    "web_clickstreams": gen_web_clickstreams,
    "customer_demographics": gen_customer_demographics,
    "product_reviews": gen_product_reviews,
    "item_marketprices": gen_item_marketprices,
}

# BigBench shares the retail dims/facts with the TPC-DS-like generators
# (the reference's TpcxbbLikeSpark schema reuses them the same way)
TPCDS_TABLES = ["store_sales", "item", "date_dim", "store", "warehouse",
                "inventory", "promotion", "household_demographics",
                "time_dim", "store_returns", "web_page", "customer",
                "customer_address", "web_sales", "web_returns"]


def write_tables(data_dir: str, sf: float, files_per_table: int = 4
                 ) -> None:
    """BigBench tables + the shared retail facts/dims from the TPC-DS-like
    generators."""
    tpcds.write_tables(data_dir, sf, tables=TPCDS_TABLES,
                       files_per_table=files_per_table)
    os.makedirs(data_dir, exist_ok=True)
    for name, gen in GENERATORS.items():
        table = gen(sf)
        tdir = os.path.join(data_dir, name)
        os.makedirs(tdir, exist_ok=True)
        per = -(-table.num_rows // files_per_table)
        for i in range(files_per_table):
            chunk = table.slice(i * per, per)
            if chunk.num_rows:
                pq.write_table(chunk,
                               os.path.join(tdir,
                                            f"part-{i:03d}.parquet"))


def ref(i, t):
    return BoundReference(i, t)


def _scan(data_dir: str, table: str, columns):
    return pn.ScanNode(ParquetSource(os.path.join(data_dir, table),
                                     columns=columns))


def _count_if(cond):
    """count(CASE WHEN cond THEN 1 ELSE NULL END)"""
    return A.Count(If(cond, Literal(1, dt.INT64),
                      Literal(None, dt.INT64)))


def _sum_if(cond):
    """SUM(CASE WHEN cond THEN 1 ELSE 0 END)"""
    return A.Sum(If(cond, Literal(1, dt.INT64), Literal(0, dt.INT64)))


def q5(data_dir: str) -> pn.PlanNode:
    """Per-user clicks-per-category feature vector joined to
    demographics (TpcxbbLikeSpark.scala:832-890)."""
    clicks = pn.FilterNode(
        P.IsNotNull(ref(0, dt.INT64)),
        _scan(data_dir, "web_clickstreams",
              ["wcs_user_sk", "wcs_item_sk"]))
    item = _scan(data_dir, "item",
                 ["i_item_sk", "i_category", "i_category_id"])
    # [wcs_user_sk 0, wcs_item_sk 1, i_item_sk 2, i_category 3,
    #  i_category_id 4]
    ci = pn.JoinNode("inner", clicks, item, [1], [0])
    cat_id = ref(4, dt.INT32)
    aggs = [pn.AggCall(_sum_if(P.EqualTo(ref(3, dt.STRING),
                                         Literal("Books"))),
                       "clicks_in_category")]
    for k in range(1, 8):
        aggs.append(pn.AggCall(
            _sum_if(P.EqualTo(cat_id, Literal(k, dt.INT32))),
            f"clicks_in_{k}"))
    user_clicks = pn.AggregateNode([ref(0, dt.INT64)], aggs, ci,
                                   grouping_names=["wcs_user_sk"])
    customer = _scan(data_dir, "customer",
                     ["c_customer_sk", "c_current_cdemo_sk"])
    # user_clicks has 9 cols; + [c_customer_sk 9, c_current_cdemo_sk 10]
    uc = pn.JoinNode("inner", user_clicks, customer, [0], [0])
    demo = _scan(data_dir, "customer_demographics",
                 ["cd_demo_sk", "cd_gender", "cd_education_status"])
    # + [cd_demo_sk 11, cd_gender 12, cd_education_status 13]
    ucd = pn.JoinNode("inner", uc, demo, [10], [0])
    college = If(
        P.In(ref(13, dt.STRING),
             [Literal("Advanced Degree"), Literal("College"),
              Literal("4 yr Degree"), Literal("2 yr Degree")]),
        Literal(1, dt.INT64), Literal(0, dt.INT64))
    male = If(P.EqualTo(ref(12, dt.STRING), Literal("M")),
              Literal(1, dt.INT64), Literal(0, dt.INT64))
    outs = [Alias(ref(1, dt.INT64), "clicks_in_category"),
            Alias(college, "college_education"), Alias(male, "male")]
    for k in range(1, 8):
        outs.append(Alias(ref(1 + k, dt.INT64), f"clicks_in_{k}"))
    return pn.ProjectNode(outs, ucd)


def q9(data_dir: str) -> pn.PlanNode:
    """Banded OR-predicate multi-join global sum
    (TpcxbbLikeSpark.scala:1044-1119)."""
    ss = _scan(data_dir, "store_sales",
               ["ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
                "ss_store_sk", "ss_quantity", "ss_sales_price",
                "ss_net_profit"])
    dd = pn.FilterNode(
        P.EqualTo(ref(1, dt.INT32), Literal(2000, dt.INT32)),
        _scan(data_dir, "date_dim", ["d_date_sk", "d_year"]))
    # [ss 0-6, d_date_sk 7, d_year 8]
    s1 = pn.JoinNode("inner", ss, dd, [0], [0])
    # reuse customer_sk as the address key (the -like data keys addresses
    # by customer) — + [ca_address_sk 9, ca_country 10, ca_state 11]
    ca = _scan(data_dir, "customer_address",
               ["ca_address_sk", "ca_country", "ca_state"])
    s2 = pn.JoinNode("inner", s1, ca, [2], [0])
    store = _scan(data_dir, "store", ["s_store_sk"])
    # + [s_store_sk 12]
    s3 = pn.JoinNode("inner", s2, store, [3], [0])
    demo = _scan(data_dir, "customer_demographics",
                 ["cd_demo_sk", "cd_marital_status",
                  "cd_education_status"])
    # demo keyed by customer_sk % n_demo at generation; join through
    # customer_sk is the -like simplification; + [cd_demo_sk 13,
    # cd_marital_status 14, cd_education_status 15]
    s4 = pn.JoinNode("inner", s3, demo, [2], [0])
    price = ref(5, dt.FLOAT64)
    profit = ref(6, dt.FLOAT64)
    md = P.And(P.EqualTo(ref(14, dt.STRING), Literal("M")),
               P.EqualTo(ref(15, dt.STRING), Literal("4 yr Degree")))

    def band(e, lo, hi):
        return P.And(P.GreaterThanOrEqual(e, Literal(float(lo))),
                     P.LessThanOrEqual(e, Literal(float(hi))))

    arm_a = P.Or(P.Or(P.And(md, band(price, 100, 150)),
                      P.And(md, band(price, 50, 200))),
                 P.And(md, band(price, 150, 200)))
    us = P.EqualTo(ref(10, dt.STRING), Literal("United States"))

    def states(*ss):
        return P.In(ref(11, dt.STRING), [Literal(s) for s in ss])

    arm_b = P.Or(
        P.Or(P.And(P.And(us, states("KY", "GA", "NM")),
                   band(profit, 0, 2000)),
             P.And(P.And(us, states("MT", "OR", "IN")),
                   band(profit, 150, 3000))),
        P.And(P.And(us, states("WI", "MO", "WV")),
              band(profit, 50, 25000)))
    filt = pn.FilterNode(P.And(arm_a, arm_b), s4)
    return pn.AggregateNode(
        [], [pn.AggCall(A.Sum(ref(4, dt.INT32)), "sum_quantity")], filt)


def q26(data_dir: str) -> pn.PlanNode:
    """Per-customer class-id purchase-count vector with HAVING
    (TpcxbbLikeSpark.scala:1968-2014); class ids reduced to 8 to match
    the generated item table."""
    ss = pn.FilterNode(
        P.IsNotNull(ref(1, dt.INT64)),
        _scan(data_dir, "store_sales", ["ss_item_sk", "ss_customer_sk"]))
    item = pn.FilterNode(
        P.In(ref(1, dt.STRING), [Literal("Books")]),
        _scan(data_dir, "item",
              ["i_item_sk", "i_category", "i_class_id"]))
    # [ss_item_sk 0, ss_customer_sk 1, i_item_sk 2, i_category 3,
    #  i_class_id 4]
    j = pn.JoinNode("inner", ss, item, [0], [0])
    class_id = ref(4, dt.INT32)
    aggs = [pn.AggCall(_count_if(P.EqualTo(class_id,
                                           Literal(k, dt.INT32))),
                       f"id{k}") for k in range(1, 9)]
    aggs.append(pn.AggCall(A.Count(ref(0, dt.INT64)), "cnt"))
    agg = pn.AggregateNode([ref(1, dt.INT64)], aggs, j,
                           grouping_names=["cid"])
    having = pn.FilterNode(P.GreaterThan(ref(9, dt.INT64),
                                         Literal(5, dt.INT64)), agg)
    proj = pn.ProjectNode(
        [Alias(ref(0, dt.INT64), "cid")] +
        [Alias(ref(k, dt.INT64), f"id{k}") for k in range(1, 9)],
        having)
    return pn.SortNode([SortKeySpec.spark_default(0)], proj)


def _channel_year_totals(data_dir, scan, date_col, cust_col,
                         price_cols, cust_name):
    """The q6 per-channel view: conditional first/second-year totals per
    customer with HAVING first_year_total > 0
    (TpcxbbLikeSpark.scala:891-970)."""
    dd = pn.FilterNode(
        P.And(P.GreaterThanOrEqual(ref(1, dt.INT32),
                                   Literal(2000, dt.INT32)),
              P.LessThanOrEqual(ref(1, dt.INT32),
                                Literal(2001, dt.INT32))),
        _scan(data_dir, "date_dim", ["d_date_sk", "d_year"]))
    ncols = len(scan.output_schema().names)
    j = pn.JoinNode("inner", scan, dd, [date_col], [0])
    lp, wc, da, sp = price_cols
    half = ar.Divide(
        ar.Add(ar.Subtract(ar.Subtract(ref(lp, dt.FLOAT64),
                                       ref(wc, dt.FLOAT64)),
                           ref(da, dt.FLOAT64)),
               ref(sp, dt.FLOAT64)), Literal(2.0))
    is_y1 = P.EqualTo(ref(ncols + 1, dt.INT32), Literal(2000, dt.INT32))
    proj = pn.ProjectNode(
        [Alias(ref(cust_col, dt.INT64), cust_name),
         Alias(If(is_y1, half, Literal(0.0)), "y1"),
         Alias(If(P.Not(is_y1), half, Literal(0.0)), "y2")], j)
    agg = pn.AggregateNode(
        [ref(0, dt.INT64)],
        [pn.AggCall(A.Sum(ref(1, dt.FLOAT64)), "first_year_total"),
         pn.AggCall(A.Sum(ref(2, dt.FLOAT64)), "second_year_total")],
        proj, grouping_names=[cust_name])
    return pn.FilterNode(P.GreaterThan(ref(1, dt.FLOAT64),
                                       Literal(0.0)), agg)


def q6(data_dir: str) -> pn.PlanNode:
    """Store-to-web purchase-habit shift: per-channel year-over-year
    ratio comparison, top customers by web increase
    (TpcxbbLikeSpark.scala:891-970)."""
    store = _channel_year_totals(
        data_dir,
        _scan(data_dir, "store_sales",
              ["ss_sold_date_sk", "ss_customer_sk", "ss_ext_list_price",
               "ss_ext_wholesale_cost", "ss_ext_discount_amt",
               "ss_ext_sales_price"]),
        date_col=0, cust_col=1, price_cols=(2, 3, 4, 5),
        cust_name="customer_sk")
    web = _channel_year_totals(
        data_dir,
        _scan(data_dir, "web_sales",
              ["ws_sold_date_sk", "ws_bill_customer_sk",
               "ws_ext_list_price", "ws_ext_wholesale_cost",
               "ws_ext_discount_amt", "ws_ext_sales_price"]),
        date_col=0, cust_col=1, price_cols=(2, 3, 4, 5),
        cust_name="customer_sk")
    # web x store per customer -> ratio comparison
    # [w_cust 0, w_y1 1, w_y2 2, s_cust 3, s_y1 4, s_y2 5]
    j = pn.JoinNode("inner", web, store, [0], [0])
    web_ratio = ar.Divide(ref(2, dt.FLOAT64), ref(1, dt.FLOAT64))
    store_ratio = ar.Divide(ref(5, dt.FLOAT64), ref(4, dt.FLOAT64))
    shifted = pn.FilterNode(P.GreaterThan(web_ratio, store_ratio), j)
    proj = pn.ProjectNode(
        [Alias(web_ratio, "web_sales_increase_ratio"),
         Alias(ref(0, dt.INT64), "c_customer_sk")], shifted)
    sort = pn.SortNode([SortKeySpec.spark_default(0, ascending=False),
                        SortKeySpec.spark_default(1)], proj)
    return pn.LimitNode(100, sort)


def q11(data_dir: str) -> pn.PlanNode:
    """Review-sentiment vs revenue correlation
    (TpcxbbLikeSpark.scala:1126-1180): per-item review stats joined to
    per-item revenue, then Pearson corr computed from moment sums
    (n, Σx, Σy, Σxy, Σx², Σy²) — corr() itself is not a device
    aggregate, the same gap the reference has."""
    reviews = pn.FilterNode(
        P.IsNotNull(ref(0, dt.INT64)),
        _scan(data_dir, "product_reviews",
              ["pr_item_sk", "pr_review_rating"]))
    stats = pn.AggregateNode(
        [ref(0, dt.INT64)],
        [pn.AggCall(A.Count(), "r_count"),
         pn.AggCall(A.Average(Cast(ref(1, dt.INT32), dt.FLOAT64)),
                    "avg_rating")],
        reviews, grouping_names=["pr_item_sk"])
    dd = pn.FilterNode(
        P.EqualTo(ref(1, dt.INT32), Literal(2001, dt.INT32)),
        _scan(data_dir, "date_dim", ["d_date_sk", "d_year"]))
    ws = pn.FilterNode(
        P.IsNotNull(ref(1, dt.INT64)),
        _scan(data_dir, "web_sales",
              ["ws_sold_date_sk", "ws_item_sk", "ws_net_paid"]))
    ws_in = pn.JoinNode("left_semi", ws, dd, [0], [0])
    revenue = pn.AggregateNode(
        [ref(1, dt.INT64)],
        [pn.AggCall(A.Sum(ref(2, dt.FLOAT64)), "revenue")],
        ws_in, grouping_names=["ws_item_sk"])
    # [pr_item_sk 0, r_count 1, avg_rating 2, ws_item_sk 3, revenue 4]
    j = pn.JoinNode("inner", stats, revenue, [0], [0])
    x = Cast(ref(1, dt.INT64), dt.FLOAT64)   # reviews_count
    y = ref(2, dt.FLOAT64)                   # avg_rating
    moments = pn.ProjectNode(
        [Alias(x, "x"), Alias(y, "y"),
         Alias(ar.Multiply(x, y), "xy"),
         Alias(ar.Multiply(x, x), "xx"),
         Alias(ar.Multiply(y, y), "yy")], j)
    sums = pn.AggregateNode(
        [], [pn.AggCall(A.Count(), "n"),
             pn.AggCall(A.Sum(ref(0, dt.FLOAT64)), "sx"),
             pn.AggCall(A.Sum(ref(1, dt.FLOAT64)), "sy"),
             pn.AggCall(A.Sum(ref(2, dt.FLOAT64)), "sxy"),
             pn.AggCall(A.Sum(ref(3, dt.FLOAT64)), "sxx"),
             pn.AggCall(A.Sum(ref(4, dt.FLOAT64)), "syy")], moments)
    n = Cast(ref(0, dt.INT64), dt.FLOAT64)
    sx, sy = ref(1, dt.FLOAT64), ref(2, dt.FLOAT64)
    sxy, sxx, syy = (ref(3, dt.FLOAT64), ref(4, dt.FLOAT64),
                     ref(5, dt.FLOAT64))
    cov = ar.Subtract(ar.Multiply(n, sxy), ar.Multiply(sx, sy))
    vx = ar.Subtract(ar.Multiply(n, sxx), ar.Multiply(sx, sx))
    vy = ar.Subtract(ar.Multiply(n, syy), ar.Multiply(sy, sy))
    from spark_rapids_tpu.expressions.math import Sqrt

    corr = ar.Divide(cov, ar.Multiply(Sqrt(vx), Sqrt(vy)))
    return pn.ProjectNode([Alias(corr, "corr")], sums)


# ---------------------------------------------------------------------------
# SQL-text queries (the reference embeds these as Spark SQL,
# TpcxbbLikeSpark.scala; here they run through the engine's own SQL
# front end — sql/parser.py + sql/planner.py — over the same catalog).
# Literals are adapted to the generated data's ranges (dates 1998-2002,
# d_date_sk base 2450815); multi-statement queries stage temp views via
# Session.create_temp_view exactly where the reference CREATEs temp
# tables/views.
# ---------------------------------------------------------------------------


def _session(data_dir: str):
    from spark_rapids_tpu.api import Session

    s = Session()
    for t in list(GENERATORS) + TPCDS_TABLES:
        s.register_parquet(t, os.path.join(data_dir, t))
    return s


def _sql_query(final_sql: str, views=()):
    """Factory-factory: plan ``final_sql`` after staging ``views``
    (name, sql) temp views, reference CREATE TEMPORARY VIEW analogue."""

    def factory(data_dir: str) -> pn.PlanNode:
        s = _session(data_dir)
        for name, sql in views:
            s.create_temp_view(name, s.sql(sql))
        return s.sql(final_sql)._plan

    return factory


# Q7 (TpcxbbLikeSpark.scala:972-1038): states with >=10 customers buying
# items priced >=20% above their category average, in a given month.
q7 = _sql_query("""
SELECT ca_state, COUNT(*) AS cnt
FROM customer_address a, customer c, store_sales s,
  (SELECT k.i_item_sk FROM item k,
     (SELECT i_category, AVG(j.i_current_price) * 1.2 AS avg_price
      FROM item j GROUP BY j.i_category) avgCategoryPrice
   WHERE avgCategoryPrice.i_category = k.i_category
   AND k.i_current_price > avgCategoryPrice.avg_price) highPriceItems
WHERE a.ca_address_sk = c.c_current_addr_sk
AND c.c_customer_sk = s.ss_customer_sk
AND ca_state IS NOT NULL
AND ss_item_sk = highPriceItems.i_item_sk
AND s.ss_sold_date_sk IN
  (SELECT d_date_sk FROM date_dim WHERE d_year = 2001 AND d_moy = 7)
GROUP BY ca_state
HAVING cnt >= 10
ORDER BY cnt DESC, ca_state
LIMIT 10
""")


# Q12 (TpcxbbLikeSpark.scala:1184-1226): web views followed by in-store
# purchase of same-category items within 90 days.
q12 = _sql_query("""
SELECT DISTINCT wcs_user_sk
FROM
( SELECT wcs_user_sk, wcs_click_date_sk
  FROM web_clickstreams, item
  WHERE wcs_click_date_sk BETWEEN 2451300 AND (2451300 + 30)
  AND i_category IN ('Books', 'Electronics')
  AND wcs_item_sk = i_item_sk
  AND wcs_user_sk IS NOT NULL
  AND wcs_sales_sk IS NULL
) webInRange,
( SELECT ss_customer_sk, ss_sold_date_sk
  FROM store_sales, item
  WHERE ss_sold_date_sk BETWEEN 2451300 AND (2451300 + 90)
  AND i_category IN ('Books', 'Electronics')
  AND ss_item_sk = i_item_sk
  AND ss_customer_sk IS NOT NULL
) storeInRange
WHERE wcs_user_sk = ss_customer_sk
AND wcs_click_date_sk < ss_sold_date_sk
ORDER BY wcs_user_sk
""")


# Q13 (TpcxbbLikeSpark.scala:1226-1307): customers whose web-sales
# year-over-year growth beats their store-sales growth.
_Q13_VIEW = """
SELECT {cust} AS customer_sk,
    sum(CASE WHEN (d_year = 2001)     THEN {paid} ELSE 0 END)
        AS first_year_total,
    sum(CASE WHEN (d_year = 2001 + 1) THEN {paid} ELSE 0 END)
        AS second_year_total
FROM {tab} t
JOIN (SELECT d_date_sk, d_year FROM date_dim d
      WHERE d.d_year IN (2001, (2001 + 1))) dd
  ON (t.{date} = dd.d_date_sk)
GROUP BY {cust}
HAVING first_year_total > 0
"""
q13 = _sql_query("""
SELECT c_customer_sk, c_first_name, c_last_name,
      (store.second_year_total / store.first_year_total)
          AS storeSalesIncreaseRatio,
      (web.second_year_total / web.first_year_total)
          AS webSalesIncreaseRatio
FROM q13_temp_table1 store, q13_temp_table2 web, customer c
WHERE store.customer_sk = web.customer_sk
AND web.customer_sk = c_customer_sk
AND (web.second_year_total / web.first_year_total) >
    (store.second_year_total / store.first_year_total)
ORDER BY webSalesIncreaseRatio DESC, c_customer_sk, c_first_name,
         c_last_name
LIMIT 100
""", views=[
    ("q13_temp_table1", _Q13_VIEW.format(
        cust="ss_customer_sk", paid="ss_net_paid", tab="store_sales",
        date="ss_sold_date_sk")),
    ("q13_temp_table2", _Q13_VIEW.format(
        cust="ws_bill_customer_sk", paid="ws_net_paid", tab="web_sales",
        date="ws_sold_date_sk")),
])


# Q14 (TpcxbbLikeSpark.scala:1307-1336): morning/evening web sales ratio
# for high-content pages, customers with 5 dependents.
q14 = _sql_query("""
SELECT CASE WHEN pmc > 0 THEN amc / pmc ELSE -1.00 END AS am_pm_ratio
FROM (
  SELECT SUM(amc1) AS amc, SUM(pmc1) AS pmc
  FROM (
    SELECT
      CASE WHEN t_hour BETWEEN 7 AND 8 THEN COUNT(1) ELSE 0 END AS amc1,
      CASE WHEN t_hour BETWEEN 19 AND 20 THEN COUNT(1) ELSE 0 END AS pmc1
    FROM web_sales ws
    JOIN household_demographics hd
      ON (hd.hd_demo_sk = ws.ws_ship_hdemo_sk AND hd.hd_dep_count = 5)
    JOIN web_page wp
      ON (wp.wp_web_page_sk = ws.ws_web_page_sk
          AND wp.wp_char_count BETWEEN 5000 AND 6000)
    JOIN time_dim td
      ON (td.t_time_sk = ws.ws_sold_time_sk
          AND td.t_hour IN (7, 8, 19, 20))
    GROUP BY t_hour) cnt_am_pm
  ) sum_am_pm
""")


# Q15 (TpcxbbLikeSpark.scala:1336-1400): per-category sales-slope
# regression; categories with flat or declining store sales.
q15 = _sql_query("""
SELECT * FROM (
  SELECT cat,
    ((count(x) * SUM(xy) - SUM(x) * SUM(y)) /
     (count(x) * SUM(xx) - SUM(x) * SUM(x))) AS slope,
    (SUM(y) - ((count(x) * SUM(xy) - SUM(x) * SUM(y)) /
     (count(x) * SUM(xx) - SUM(x) * SUM(x))) * SUM(x)) / count(x)
        AS intercept
  FROM (
    SELECT i.i_category_id AS cat,
      s.ss_sold_date_sk AS x,
      SUM(s.ss_net_paid) AS y,
      s.ss_sold_date_sk * SUM(s.ss_net_paid) AS xy,
      s.ss_sold_date_sk * s.ss_sold_date_sk AS xx
    FROM store_sales s
    LEFT SEMI JOIN (
      SELECT d_date_sk FROM date_dim d
      WHERE d.d_date >= '2001-09-02' AND d.d_date <= '2002-09-02'
    ) dd ON (s.ss_sold_date_sk = dd.d_date_sk)
    INNER JOIN item i ON s.ss_item_sk = i.i_item_sk
    WHERE i.i_category_id IS NOT NULL
    AND s.ss_store_sk = 1
    GROUP BY i.i_category_id, s.ss_sold_date_sk
  ) temp
  GROUP BY cat
) regression
WHERE slope <= 0
ORDER BY cat
""")


# Q16 (TpcxbbLikeSpark.scala:1400-1442): sales impact 30 days around a
# price change, by warehouse state (unix_timestamp window re-expressed
# with datediff over the engine's DATE columns).
q16 = _sql_query("""
SELECT w_state, i_item_id,
  SUM(CASE WHEN datediff(d_date, '2001-03-16') < 0
      THEN ws_sales_price - COALESCE(wr_refunded_cash, 0)
      ELSE 0.0 END) AS sales_before,
  SUM(CASE WHEN datediff(d_date, '2001-03-16') >= 0
      THEN ws_sales_price - COALESCE(wr_refunded_cash, 0)
      ELSE 0.0 END) AS sales_after
FROM (
  SELECT * FROM web_sales ws
  LEFT OUTER JOIN web_returns wr
    ON (ws.ws_order_number = wr.wr_order_number
        AND ws.ws_item_sk = wr.wr_item_sk)
) a1
JOIN item i ON a1.ws_item_sk = i.i_item_sk
JOIN warehouse w ON a1.ws_warehouse_sk = w.w_warehouse_sk
JOIN date_dim d ON a1.ws_sold_date_sk = d.d_date_sk
AND datediff(d.d_date, '2001-03-16') >= -30
AND datediff(d.d_date, '2001-03-16') <= 30
GROUP BY w_state, i_item_id
ORDER BY w_state, i_item_id
LIMIT 100
""")


# Q17 (TpcxbbLikeSpark.scala:1442-1478): promotional sales ratio in a
# month/category/timezone slice.
q17 = _sql_query("""
SELECT sum(promotional) AS promotional, sum(total) AS total,
       CASE WHEN sum(total) > 0
            THEN 100 * sum(promotional) / sum(total)
            ELSE 0.0 END AS promo_percent
FROM (
  SELECT p_channel_email, p_channel_dmail, p_channel_tv,
    CASE WHEN (p_channel_dmail = 'Y' OR p_channel_email = 'Y'
               OR p_channel_tv = 'Y')
    THEN SUM(ss_ext_sales_price) ELSE 0 END AS promotional,
    SUM(ss_ext_sales_price) AS total
  FROM store_sales ss
  LEFT SEMI JOIN date_dim dd
    ON ss.ss_sold_date_sk = dd.d_date_sk AND dd.d_year = 2001
       AND dd.d_moy = 12
  LEFT SEMI JOIN item i
    ON ss.ss_item_sk = i.i_item_sk
       AND i.i_category IN ('Books', 'Music')
  LEFT SEMI JOIN store s
    ON ss.ss_store_sk = s.s_store_sk AND s.s_gmt_offset = -5.0
  LEFT SEMI JOIN (SELECT c.c_customer_sk FROM customer c
                  LEFT SEMI JOIN customer_address ca
                  ON c.c_current_addr_sk = ca.ca_address_sk
                     AND ca.ca_gmt_offset = -5.0) sub_c
    ON ss.ss_customer_sk = sub_c.c_customer_sk
  JOIN promotion p ON ss.ss_promo_sk = p.p_promo_sk
  GROUP BY p_channel_email, p_channel_dmail, p_channel_tv
  ) sum_promotional
ORDER BY promotional, total
LIMIT 100
""")


# Q20 (TpcxbbLikeSpark.scala:1503-1565): customer return-behavior
# segmentation vector.
q20 = _sql_query("""
SELECT
  ss_customer_sk AS user_sk,
  round(CASE WHEN ((returns_count IS NULL) OR (orders_count IS NULL)
        OR ((returns_count / orders_count) IS NULL)) THEN 0.0
        ELSE (returns_count / orders_count) END, 7) AS orderRatio,
  round(CASE WHEN ((returns_items IS NULL) OR (orders_items IS NULL)
        OR ((returns_items / orders_items) IS NULL)) THEN 0.0
        ELSE (returns_items / orders_items) END, 7) AS itemsRatio,
  round(CASE WHEN ((returns_money IS NULL) OR (orders_money IS NULL)
        OR ((returns_money / orders_money) IS NULL)) THEN 0.0
        ELSE (returns_money / orders_money) END, 7) AS monetaryRatio,
  round(CASE WHEN (returns_count IS NULL) THEN 0.0
        ELSE returns_count END, 0) AS frequency
FROM (
  SELECT ss_customer_sk,
    COUNT(DISTINCT ss_ticket_number) AS orders_count,
    COUNT(ss_item_sk) AS orders_items,
    SUM(ss_net_paid) AS orders_money
  FROM store_sales s GROUP BY ss_customer_sk
) orders
LEFT OUTER JOIN (
  SELECT sr_customer_sk,
    count(DISTINCT sr_ticket_number) AS returns_count,
    COUNT(sr_item_sk) AS returns_items,
    SUM(sr_return_amt) AS returns_money
  FROM store_returns GROUP BY sr_customer_sk
) returned ON ss_customer_sk = sr_customer_sk
ORDER BY user_sk
""")


# Q21 (TpcxbbLikeSpark.scala:1565-1653): items sold in a month, returned
# within 6 months, re-purchased on the web within the following years.
q21 = _sql_query("""
SELECT
  part_i.i_item_id AS i_item_id,
  part_i.i_item_desc AS i_item_desc,
  part_s.s_store_id AS s_store_id,
  part_s.s_store_name AS s_store_name,
  SUM(part_ss.ss_quantity) AS store_sales_quantity,
  SUM(part_sr.sr_return_quantity) AS store_returns_quantity,
  SUM(part_ws.ws_quantity) AS web_sales_quantity
FROM (
  SELECT sr_item_sk, sr_customer_sk, sr_ticket_number,
         sr_return_quantity
  FROM store_returns sr, date_dim d2
  WHERE d2.d_year = 2001
  AND d2.d_moy BETWEEN 1 AND 1 + 6
  AND sr.sr_returned_date_sk = d2.d_date_sk
) part_sr
INNER JOIN (
  SELECT ws_item_sk, ws_bill_customer_sk, ws_quantity
  FROM web_sales ws, date_dim d3
  WHERE d3.d_year BETWEEN 2001 AND 2001 + 1
  AND ws.ws_sold_date_sk = d3.d_date_sk
) part_ws ON (
  part_sr.sr_item_sk = part_ws.ws_item_sk
  AND part_sr.sr_customer_sk = part_ws.ws_bill_customer_sk
)
INNER JOIN (
  SELECT ss_item_sk, ss_store_sk, ss_customer_sk, ss_ticket_number,
         ss_quantity
  FROM store_sales ss, date_dim d1
  WHERE d1.d_year = 2001
  AND d1.d_moy = 1
  AND ss.ss_sold_date_sk = d1.d_date_sk
) part_ss ON (
  part_ss.ss_ticket_number = part_sr.sr_ticket_number
  AND part_ss.ss_item_sk = part_sr.sr_item_sk
  AND part_ss.ss_customer_sk = part_sr.sr_customer_sk
)
INNER JOIN store part_s ON (part_s.s_store_sk = part_ss.ss_store_sk)
INNER JOIN item part_i ON (part_i.i_item_sk = part_ss.ss_item_sk)
GROUP BY part_i.i_item_id, part_i.i_item_desc, part_s.s_store_id,
         part_s.s_store_name
ORDER BY part_i.i_item_id, part_i.i_item_desc, part_s.s_store_id,
         part_s.s_store_name
LIMIT 100
""")


# Q22 (TpcxbbLikeSpark.scala:1653-1708): inventory change 30 days around
# a price change, by warehouse.
q22 = _sql_query("""
SELECT w_warehouse_name, i_item_id,
  SUM(CASE WHEN datediff(d_date, '2001-05-08') < 0
      THEN inv_quantity_on_hand ELSE 0 END) AS inv_before,
  SUM(CASE WHEN datediff(d_date, '2001-05-08') >= 0
      THEN inv_quantity_on_hand ELSE 0 END) AS inv_after
FROM inventory inv, item i, warehouse w, date_dim d
WHERE i_current_price BETWEEN 0.98 AND 1.5
AND i_item_sk = inv_item_sk
AND inv_warehouse_sk = w_warehouse_sk
AND inv_date_sk = d_date_sk
AND datediff(d_date, '2001-05-08') >= -30
AND datediff(d_date, '2001-05-08') <= 30
GROUP BY w_warehouse_name, i_item_id
HAVING inv_before > 0
AND inv_after / inv_before >= 2.0 / 3.0
AND inv_after / inv_before <= 3.0 / 2.0
ORDER BY w_warehouse_name, i_item_id
LIMIT 100
""")


# Q23 (TpcxbbLikeSpark.scala:1708-1784): items with coefficient of
# variation >= 1.3 in consecutive months (stddev_samp / avg; the
# reference's decimal(15,5) casts stay double here — no decimal type).
q23 = _sql_query("""
SELECT
  inv1.inv_warehouse_sk, inv1.inv_item_sk, inv1.d_moy AS d_moy,
  inv1.cov AS cov, inv2.d_moy AS d_moy2, inv2.cov AS cov2
FROM q23_temp_table inv1
JOIN q23_temp_table inv2
  ON (inv1.inv_warehouse_sk = inv2.inv_warehouse_sk
      AND inv1.inv_item_sk = inv2.inv_item_sk
      AND inv1.d_moy = 1 AND inv2.d_moy = 1 + 1)
ORDER BY inv1.inv_warehouse_sk, inv1.inv_item_sk
""", views=[("q23_temp_table", """
SELECT inv_warehouse_sk, inv_item_sk, d_moy, (stdev / mean) AS cov
FROM (
  SELECT inv_warehouse_sk, inv_item_sk, d_moy,
    stddev_samp(inv_quantity_on_hand) AS stdev,
    avg(inv_quantity_on_hand) AS mean
  FROM inventory inv
  JOIN date_dim d
    ON (inv.inv_date_sk = d.d_date_sk AND d.d_year = 2001
        AND d_moy BETWEEN 1 AND (1 + 1))
  GROUP BY inv_warehouse_sk, inv_item_sk, d_moy
) q23_tmp_inv_part
WHERE mean > 0 AND stdev / mean >= 1.3
""")])


# Q24 (TpcxbbLikeSpark.scala:1784-1884): cross-price elasticity of
# demand for a given item (item sk adapted to the generated range).
q24 = _sql_query("""
SELECT ws_item_sk,
  avg((current_ss_quant + current_ws_quant - prev_ss_quant
       - prev_ws_quant) /
      ((prev_ss_quant + prev_ws_quant) * ws.price_change))
      AS cross_price_elasticity
FROM
  ( SELECT ws_item_sk, imp_sk, price_change,
      SUM(CASE WHEN ((ws_sold_date_sk >= c.imp_start_date)
          AND (ws_sold_date_sk < (c.imp_start_date
               + c.no_days_comp_price)))
          THEN ws_quantity ELSE 0 END) AS current_ws_quant,
      SUM(CASE WHEN ((ws_sold_date_sk >= (c.imp_start_date
               - c.no_days_comp_price))
          AND (ws_sold_date_sk < c.imp_start_date))
          THEN ws_quantity ELSE 0 END) AS prev_ws_quant
    FROM web_sales ws
    JOIN q24_temp_table c ON ws.ws_item_sk = c.i_item_sk
    GROUP BY ws_item_sk, imp_sk, price_change
  ) ws
JOIN
  ( SELECT ss_item_sk, imp_sk, price_change,
      SUM(CASE WHEN ((ss_sold_date_sk >= c.imp_start_date)
          AND (ss_sold_date_sk < (c.imp_start_date
               + c.no_days_comp_price)))
          THEN ss_quantity ELSE 0 END) AS current_ss_quant,
      SUM(CASE WHEN ((ss_sold_date_sk >= (c.imp_start_date
               - c.no_days_comp_price))
          AND (ss_sold_date_sk < c.imp_start_date))
          THEN ss_quantity ELSE 0 END) AS prev_ss_quant
    FROM store_sales ss
    JOIN q24_temp_table c ON c.i_item_sk = ss.ss_item_sk
    GROUP BY ss_item_sk, imp_sk, price_change
  ) ss
ON (ws.ws_item_sk = ss.ss_item_sk AND ws.imp_sk = ss.imp_sk)
GROUP BY ws.ws_item_sk
""", views=[("q24_temp_table", """
SELECT i_item_sk, imp_sk,
  (imp_competitor_price - i_current_price) / i_current_price
      AS price_change,
  imp_start_date,
  (imp_end_date - imp_start_date) AS no_days_comp_price
FROM item i, item_marketprices imp
WHERE i.i_item_sk = imp.imp_item_sk
AND i.i_item_sk = 7
ORDER BY i_item_sk, imp_sk, imp_start_date
""")])


# Q25 (TpcxbbLikeSpark.scala:1884-1968): RFM customer segmentation; the
# reference INSERTs store+web halves into one temp table — here the two
# SELECTs union (UnionNode) into the same staged view. Recency cutoff
# adapted to the generated date_sk range (last ~60 days of 2002).
_Q25_HALF = """
SELECT {cust} AS cid,
  count(DISTINCT {order_id}) AS frequency,
  max({date}) AS most_recent_date,
  SUM({paid}) AS amount
FROM {tab} t
JOIN date_dim d ON t.{date} = d.d_date_sk
WHERE d.d_date > '2002-01-02'
AND {cust} IS NOT NULL
GROUP BY {cust}
"""


def q25(data_dir: str) -> pn.PlanNode:
    s = _session(data_dir)
    halves = [s.sql(_Q25_HALF.format(
        cust="ss_customer_sk", order_id="ss_ticket_number",
        date="ss_sold_date_sk", paid="ss_net_paid", tab="store_sales")),
        s.sql(_Q25_HALF.format(
            cust="ws_bill_customer_sk", order_id="ws_order_number",
            date="ws_sold_date_sk", paid="ws_net_paid",
            tab="web_sales"))]
    s.create_temp_view("q25_temp_table",
                       pn.UnionNode([h._plan for h in halves]))
    return s.sql("""
SELECT cid AS cid,
  CASE WHEN 2452640 - max(most_recent_date) < 60 THEN 1.0
       ELSE 0.0 END AS recency,
  SUM(frequency) AS frequency,
  SUM(amount) AS totalspend
FROM q25_temp_table
GROUP BY cid
ORDER BY cid
""")._plan


# Q28 (TpcxbbLikeSpark.scala:2027-2082): 90/10 sentiment-classifier
# train/test split. The reference multi-INSERTs into two tables; the
# engine's analogue returns ONE result with a split tag column (union of
# both halves) — same rows, queryable shape.
_Q28_HALF = """
SELECT pr_review_sk, pr_review_rating AS pr_rating, pr_review_content,
       '{tag}' AS split
FROM product_reviews
WHERE pmod(pr_review_sk, 10) IN ({mods})
"""


def q28(data_dir: str) -> pn.PlanNode:
    s = _session(data_dir)
    train = s.sql(_Q28_HALF.format(tag="train",
                                   mods="1,2,3,4,5,6,7,8,9"))
    test = s.sql(_Q28_HALF.format(tag="test", mods="0"))
    return pn.UnionNode([train._plan, test._plan])


# All 19 runnable "-like" queries; the reference's own exclusions
# (Q1-4/8/10/18/19/27/29/30 need UDTF/python/UDF,
# TpcxbbLikeSpark.scala:808-832) are excluded here identically.
QUERIES = {"tpcxbb_q5": q5, "tpcxbb_q6": q6, "tpcxbb_q7": q7,
           "tpcxbb_q9": q9, "tpcxbb_q11": q11, "tpcxbb_q12": q12,
           "tpcxbb_q13": q13, "tpcxbb_q14": q14, "tpcxbb_q15": q15,
           "tpcxbb_q16": q16, "tpcxbb_q17": q17, "tpcxbb_q20": q20,
           "tpcxbb_q21": q21, "tpcxbb_q22": q22, "tpcxbb_q23": q23,
           "tpcxbb_q24": q24, "tpcxbb_q25": q25, "tpcxbb_q26": q26,
           "tpcxbb_q28": q28}
