"""TPCx-BB-like tables and query plans (TpcxbbLikeSpark.scala analogue).

The reference implements 30 "-like" queries over the BigBench retail
schema; the ones it can actually run exclude the UDTF/python/ML queries
(Q1/Q2/Q3/Q4/Q10 etc. throw UnsupportedOperationException,
TpcxbbLikeSpark.scala:808-832). This module covers the representative
SQL-only shapes on generated data:

- q5-like: clickstream x item categorical click counts per user, joined
  to customer demographics with CASE projections (the logistic-regression
  feature build, TpcxbbLikeSpark.scala:832-890)
- q9-like: store_sales x date_dim x customer_address x store x
  customer_demographics under 3-arm OR band predicates, global sum
  (TpcxbbLikeSpark.scala:1044-1119)
- q26-like: store_sales x item('Books') per-customer class-id count
  vector with HAVING (TpcxbbLikeSpark.scala:1968-2014)
"""
from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.benchmarks import tpcds
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions import aggregates as A
from spark_rapids_tpu.expressions import arithmetic as ar
from spark_rapids_tpu.expressions import predicates as P
from spark_rapids_tpu.expressions.base import Alias, BoundReference, Literal
from spark_rapids_tpu.expressions.cast import Cast
from spark_rapids_tpu.expressions.conditional import If
from spark_rapids_tpu.io import ParquetSource
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.plan import nodes as pn

EDUCATION = np.array(["Advanced Degree", "College", "4 yr Degree",
                      "2 yr Degree", "Secondary", "Primary", "Unknown"],
                     dtype=object)
MARITAL = np.array(["M", "S", "D", "W", "U"], dtype=object)
STATES = np.array(["KY", "GA", "NM", "MT", "OR", "IN", "WI", "MO", "WV",
                   "CA", "TX", "NY"], dtype=object)
COUNTRIES = np.array(["United States", "Canada", "Mexico"], dtype=object)


def gen_web_clickstreams(sf: float, seed: int = 41) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(5_000_000 * sf), 300)
    n_item = max(int(18_000 * sf), 50)
    n_cust = max(int(100_000 * sf), 20)
    user = rng.integers(1, n_cust + 1, n).astype(np.int64)
    user_null = rng.random(n) < 0.05  # anonymous clicks
    return pa.table({
        "wcs_user_sk": pa.array(
            [None if m else int(u) for u, m in zip(user, user_null)],
            type=pa.int64()),
        "wcs_item_sk": rng.integers(1, n_item + 1, n).astype(np.int64),
    })


def gen_customer(sf: float, seed: int = 42) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(100_000 * sf), 20)
    n_demo = max(int(1_000 * sf), 10)
    return pa.table({
        "c_customer_sk": np.arange(1, n + 1, dtype=np.int64),
        "c_current_cdemo_sk": rng.integers(1, n_demo + 1, n
                                           ).astype(np.int64),
    })


def gen_customer_demographics(sf: float, seed: int = 43) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(1_000 * sf), 10)
    return pa.table({
        "cd_demo_sk": np.arange(1, n + 1, dtype=np.int64),
        "cd_gender": np.array(["M", "F"], dtype=object)[
            rng.integers(0, 2, n)],
        "cd_education_status": EDUCATION[rng.integers(0, 7, n)],
        "cd_marital_status": MARITAL[rng.integers(0, 5, n)],
    })


def gen_customer_address(sf: float, seed: int = 44) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(50_000 * sf), 15)
    return pa.table({
        "ca_address_sk": np.arange(1, n + 1, dtype=np.int64),
        "ca_country": COUNTRIES[rng.integers(0, 3, n)],
        "ca_state": STATES[rng.integers(0, 12, n)],
    })


def gen_store(sf: float, seed: int = 45) -> pa.Table:
    n = max(int(12 * sf), 2)
    return pa.table({
        "s_store_sk": np.arange(1, n + 1, dtype=np.int64),
    })


def gen_web_sales(sf: float, seed: int = 46) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(700_000 * sf), 200)
    n_cust = max(int(100_000 * sf), 20)
    n_item = max(int(18_000 * sf), 50)
    return pa.table({
        "ws_sold_date_sk": rng.integers(2450815, 2450815 + 5 * 365, n
                                        ).astype(np.int64),
        "ws_bill_customer_sk": rng.integers(1, n_cust + 1, n
                                            ).astype(np.int64),
        "ws_item_sk": rng.integers(1, n_item + 1, n).astype(np.int64),
        "ws_net_paid": np.round(rng.random(n) * 300, 2),
        "ws_ext_list_price": np.round(rng.random(n) * 250, 2),
        "ws_ext_wholesale_cost": np.round(rng.random(n) * 100, 2),
        "ws_ext_discount_amt": np.round(rng.random(n) * 40, 2),
        "ws_ext_sales_price": np.round(rng.random(n) * 200, 2),
    })


def gen_product_reviews(sf: float, seed: int = 47) -> pa.Table:
    rng = np.random.default_rng(seed)
    n = max(int(60_000 * sf), 100)
    n_item = max(int(18_000 * sf), 50)
    item = rng.integers(1, n_item + 1, n).astype(np.int64)
    null = rng.random(n) < 0.03
    return pa.table({
        "pr_item_sk": pa.array(
            [None if m else int(i) for i, m in zip(item, null)],
            type=pa.int64()),
        "pr_review_rating": rng.integers(1, 6, n).astype(np.int32),
    })


GENERATORS = {
    "web_clickstreams": gen_web_clickstreams,
    "customer": gen_customer,
    "customer_demographics": gen_customer_demographics,
    "customer_address": gen_customer_address,
    "store": gen_store,
    "web_sales": gen_web_sales,
    "product_reviews": gen_product_reviews,
}


def write_tables(data_dir: str, sf: float, files_per_table: int = 4
                 ) -> None:
    """BigBench tables + the shared retail facts/dims from the TPC-DS-like
    generators (store_sales/item/date_dim)."""
    tpcds.write_tables(data_dir, sf,
                       tables=["store_sales", "item", "date_dim"],
                       files_per_table=files_per_table)
    os.makedirs(data_dir, exist_ok=True)
    for name, gen in GENERATORS.items():
        table = gen(sf)
        tdir = os.path.join(data_dir, name)
        os.makedirs(tdir, exist_ok=True)
        per = -(-table.num_rows // files_per_table)
        for i in range(files_per_table):
            chunk = table.slice(i * per, per)
            if chunk.num_rows:
                pq.write_table(chunk,
                               os.path.join(tdir,
                                            f"part-{i:03d}.parquet"))


def ref(i, t):
    return BoundReference(i, t)


def _scan(data_dir: str, table: str, columns):
    return pn.ScanNode(ParquetSource(os.path.join(data_dir, table),
                                     columns=columns))


def _count_if(cond):
    """count(CASE WHEN cond THEN 1 ELSE NULL END)"""
    return A.Count(If(cond, Literal(1, dt.INT64),
                      Literal(None, dt.INT64)))


def _sum_if(cond):
    """SUM(CASE WHEN cond THEN 1 ELSE 0 END)"""
    return A.Sum(If(cond, Literal(1, dt.INT64), Literal(0, dt.INT64)))


def q5(data_dir: str) -> pn.PlanNode:
    """Per-user clicks-per-category feature vector joined to
    demographics (TpcxbbLikeSpark.scala:832-890)."""
    clicks = pn.FilterNode(
        P.IsNotNull(ref(0, dt.INT64)),
        _scan(data_dir, "web_clickstreams",
              ["wcs_user_sk", "wcs_item_sk"]))
    item = _scan(data_dir, "item",
                 ["i_item_sk", "i_category", "i_category_id"])
    # [wcs_user_sk 0, wcs_item_sk 1, i_item_sk 2, i_category 3,
    #  i_category_id 4]
    ci = pn.JoinNode("inner", clicks, item, [1], [0])
    cat_id = ref(4, dt.INT32)
    aggs = [pn.AggCall(_sum_if(P.EqualTo(ref(3, dt.STRING),
                                         Literal("Books"))),
                       "clicks_in_category")]
    for k in range(1, 8):
        aggs.append(pn.AggCall(
            _sum_if(P.EqualTo(cat_id, Literal(k, dt.INT32))),
            f"clicks_in_{k}"))
    user_clicks = pn.AggregateNode([ref(0, dt.INT64)], aggs, ci,
                                   grouping_names=["wcs_user_sk"])
    customer = _scan(data_dir, "customer",
                     ["c_customer_sk", "c_current_cdemo_sk"])
    # user_clicks has 9 cols; + [c_customer_sk 9, c_current_cdemo_sk 10]
    uc = pn.JoinNode("inner", user_clicks, customer, [0], [0])
    demo = _scan(data_dir, "customer_demographics",
                 ["cd_demo_sk", "cd_gender", "cd_education_status"])
    # + [cd_demo_sk 11, cd_gender 12, cd_education_status 13]
    ucd = pn.JoinNode("inner", uc, demo, [10], [0])
    college = If(
        P.In(ref(13, dt.STRING),
             [Literal("Advanced Degree"), Literal("College"),
              Literal("4 yr Degree"), Literal("2 yr Degree")]),
        Literal(1, dt.INT64), Literal(0, dt.INT64))
    male = If(P.EqualTo(ref(12, dt.STRING), Literal("M")),
              Literal(1, dt.INT64), Literal(0, dt.INT64))
    outs = [Alias(ref(1, dt.INT64), "clicks_in_category"),
            Alias(college, "college_education"), Alias(male, "male")]
    for k in range(1, 8):
        outs.append(Alias(ref(1 + k, dt.INT64), f"clicks_in_{k}"))
    return pn.ProjectNode(outs, ucd)


def q9(data_dir: str) -> pn.PlanNode:
    """Banded OR-predicate multi-join global sum
    (TpcxbbLikeSpark.scala:1044-1119)."""
    ss = _scan(data_dir, "store_sales",
               ["ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
                "ss_store_sk", "ss_quantity", "ss_sales_price",
                "ss_net_profit"])
    dd = pn.FilterNode(
        P.EqualTo(ref(1, dt.INT32), Literal(2000, dt.INT32)),
        _scan(data_dir, "date_dim", ["d_date_sk", "d_year"]))
    # [ss 0-6, d_date_sk 7, d_year 8]
    s1 = pn.JoinNode("inner", ss, dd, [0], [0])
    # reuse customer_sk as the address key (the -like data keys addresses
    # by customer) — + [ca_address_sk 9, ca_country 10, ca_state 11]
    ca = _scan(data_dir, "customer_address",
               ["ca_address_sk", "ca_country", "ca_state"])
    s2 = pn.JoinNode("inner", s1, ca, [2], [0])
    store = _scan(data_dir, "store", ["s_store_sk"])
    # + [s_store_sk 12]
    s3 = pn.JoinNode("inner", s2, store, [3], [0])
    demo = _scan(data_dir, "customer_demographics",
                 ["cd_demo_sk", "cd_marital_status",
                  "cd_education_status"])
    # demo keyed by customer_sk % n_demo at generation; join through
    # customer_sk is the -like simplification; + [cd_demo_sk 13,
    # cd_marital_status 14, cd_education_status 15]
    s4 = pn.JoinNode("inner", s3, demo, [2], [0])
    price = ref(5, dt.FLOAT64)
    profit = ref(6, dt.FLOAT64)
    md = P.And(P.EqualTo(ref(14, dt.STRING), Literal("M")),
               P.EqualTo(ref(15, dt.STRING), Literal("4 yr Degree")))

    def band(e, lo, hi):
        return P.And(P.GreaterThanOrEqual(e, Literal(float(lo))),
                     P.LessThanOrEqual(e, Literal(float(hi))))

    arm_a = P.Or(P.Or(P.And(md, band(price, 100, 150)),
                      P.And(md, band(price, 50, 200))),
                 P.And(md, band(price, 150, 200)))
    us = P.EqualTo(ref(10, dt.STRING), Literal("United States"))

    def states(*ss):
        return P.In(ref(11, dt.STRING), [Literal(s) for s in ss])

    arm_b = P.Or(
        P.Or(P.And(P.And(us, states("KY", "GA", "NM")),
                   band(profit, 0, 2000)),
             P.And(P.And(us, states("MT", "OR", "IN")),
                   band(profit, 150, 3000))),
        P.And(P.And(us, states("WI", "MO", "WV")),
              band(profit, 50, 25000)))
    filt = pn.FilterNode(P.And(arm_a, arm_b), s4)
    return pn.AggregateNode(
        [], [pn.AggCall(A.Sum(ref(4, dt.INT32)), "sum_quantity")], filt)


def q26(data_dir: str) -> pn.PlanNode:
    """Per-customer class-id purchase-count vector with HAVING
    (TpcxbbLikeSpark.scala:1968-2014); class ids reduced to 8 to match
    the generated item table."""
    ss = pn.FilterNode(
        P.IsNotNull(ref(1, dt.INT64)),
        _scan(data_dir, "store_sales", ["ss_item_sk", "ss_customer_sk"]))
    item = pn.FilterNode(
        P.In(ref(1, dt.STRING), [Literal("Books")]),
        _scan(data_dir, "item",
              ["i_item_sk", "i_category", "i_class_id"]))
    # [ss_item_sk 0, ss_customer_sk 1, i_item_sk 2, i_category 3,
    #  i_class_id 4]
    j = pn.JoinNode("inner", ss, item, [0], [0])
    class_id = ref(4, dt.INT32)
    aggs = [pn.AggCall(_count_if(P.EqualTo(class_id,
                                           Literal(k, dt.INT32))),
                       f"id{k}") for k in range(1, 9)]
    aggs.append(pn.AggCall(A.Count(ref(0, dt.INT64)), "cnt"))
    agg = pn.AggregateNode([ref(1, dt.INT64)], aggs, j,
                           grouping_names=["cid"])
    having = pn.FilterNode(P.GreaterThan(ref(9, dt.INT64),
                                         Literal(5, dt.INT64)), agg)
    proj = pn.ProjectNode(
        [Alias(ref(0, dt.INT64), "cid")] +
        [Alias(ref(k, dt.INT64), f"id{k}") for k in range(1, 9)],
        having)
    return pn.SortNode([SortKeySpec.spark_default(0)], proj)


def _channel_year_totals(data_dir, scan, date_col, cust_col,
                         price_cols, cust_name):
    """The q6 per-channel view: conditional first/second-year totals per
    customer with HAVING first_year_total > 0
    (TpcxbbLikeSpark.scala:891-970)."""
    dd = pn.FilterNode(
        P.And(P.GreaterThanOrEqual(ref(1, dt.INT32),
                                   Literal(2000, dt.INT32)),
              P.LessThanOrEqual(ref(1, dt.INT32),
                                Literal(2001, dt.INT32))),
        _scan(data_dir, "date_dim", ["d_date_sk", "d_year"]))
    ncols = len(scan.output_schema().names)
    j = pn.JoinNode("inner", scan, dd, [date_col], [0])
    lp, wc, da, sp = price_cols
    half = ar.Divide(
        ar.Add(ar.Subtract(ar.Subtract(ref(lp, dt.FLOAT64),
                                       ref(wc, dt.FLOAT64)),
                           ref(da, dt.FLOAT64)),
               ref(sp, dt.FLOAT64)), Literal(2.0))
    is_y1 = P.EqualTo(ref(ncols + 1, dt.INT32), Literal(2000, dt.INT32))
    proj = pn.ProjectNode(
        [Alias(ref(cust_col, dt.INT64), cust_name),
         Alias(If(is_y1, half, Literal(0.0)), "y1"),
         Alias(If(P.Not(is_y1), half, Literal(0.0)), "y2")], j)
    agg = pn.AggregateNode(
        [ref(0, dt.INT64)],
        [pn.AggCall(A.Sum(ref(1, dt.FLOAT64)), "first_year_total"),
         pn.AggCall(A.Sum(ref(2, dt.FLOAT64)), "second_year_total")],
        proj, grouping_names=[cust_name])
    return pn.FilterNode(P.GreaterThan(ref(1, dt.FLOAT64),
                                       Literal(0.0)), agg)


def q6(data_dir: str) -> pn.PlanNode:
    """Store-to-web purchase-habit shift: per-channel year-over-year
    ratio comparison, top customers by web increase
    (TpcxbbLikeSpark.scala:891-970)."""
    store = _channel_year_totals(
        data_dir,
        _scan(data_dir, "store_sales",
              ["ss_sold_date_sk", "ss_customer_sk", "ss_ext_list_price",
               "ss_ext_wholesale_cost", "ss_ext_discount_amt",
               "ss_ext_sales_price"]),
        date_col=0, cust_col=1, price_cols=(2, 3, 4, 5),
        cust_name="customer_sk")
    web = _channel_year_totals(
        data_dir,
        _scan(data_dir, "web_sales",
              ["ws_sold_date_sk", "ws_bill_customer_sk",
               "ws_ext_list_price", "ws_ext_wholesale_cost",
               "ws_ext_discount_amt", "ws_ext_sales_price"]),
        date_col=0, cust_col=1, price_cols=(2, 3, 4, 5),
        cust_name="customer_sk")
    # web x store per customer -> ratio comparison
    # [w_cust 0, w_y1 1, w_y2 2, s_cust 3, s_y1 4, s_y2 5]
    j = pn.JoinNode("inner", web, store, [0], [0])
    web_ratio = ar.Divide(ref(2, dt.FLOAT64), ref(1, dt.FLOAT64))
    store_ratio = ar.Divide(ref(5, dt.FLOAT64), ref(4, dt.FLOAT64))
    shifted = pn.FilterNode(P.GreaterThan(web_ratio, store_ratio), j)
    proj = pn.ProjectNode(
        [Alias(web_ratio, "web_sales_increase_ratio"),
         Alias(ref(0, dt.INT64), "c_customer_sk")], shifted)
    sort = pn.SortNode([SortKeySpec.spark_default(0, ascending=False),
                        SortKeySpec.spark_default(1)], proj)
    return pn.LimitNode(100, sort)


def q11(data_dir: str) -> pn.PlanNode:
    """Review-sentiment vs revenue correlation
    (TpcxbbLikeSpark.scala:1126-1180): per-item review stats joined to
    per-item revenue, then Pearson corr computed from moment sums
    (n, Σx, Σy, Σxy, Σx², Σy²) — corr() itself is not a device
    aggregate, the same gap the reference has."""
    reviews = pn.FilterNode(
        P.IsNotNull(ref(0, dt.INT64)),
        _scan(data_dir, "product_reviews",
              ["pr_item_sk", "pr_review_rating"]))
    stats = pn.AggregateNode(
        [ref(0, dt.INT64)],
        [pn.AggCall(A.Count(), "r_count"),
         pn.AggCall(A.Average(Cast(ref(1, dt.INT32), dt.FLOAT64)),
                    "avg_rating")],
        reviews, grouping_names=["pr_item_sk"])
    dd = pn.FilterNode(
        P.EqualTo(ref(1, dt.INT32), Literal(2001, dt.INT32)),
        _scan(data_dir, "date_dim", ["d_date_sk", "d_year"]))
    ws = pn.FilterNode(
        P.IsNotNull(ref(1, dt.INT64)),
        _scan(data_dir, "web_sales",
              ["ws_sold_date_sk", "ws_item_sk", "ws_net_paid"]))
    ws_in = pn.JoinNode("left_semi", ws, dd, [0], [0])
    revenue = pn.AggregateNode(
        [ref(1, dt.INT64)],
        [pn.AggCall(A.Sum(ref(2, dt.FLOAT64)), "revenue")],
        ws_in, grouping_names=["ws_item_sk"])
    # [pr_item_sk 0, r_count 1, avg_rating 2, ws_item_sk 3, revenue 4]
    j = pn.JoinNode("inner", stats, revenue, [0], [0])
    x = Cast(ref(1, dt.INT64), dt.FLOAT64)   # reviews_count
    y = ref(2, dt.FLOAT64)                   # avg_rating
    moments = pn.ProjectNode(
        [Alias(x, "x"), Alias(y, "y"),
         Alias(ar.Multiply(x, y), "xy"),
         Alias(ar.Multiply(x, x), "xx"),
         Alias(ar.Multiply(y, y), "yy")], j)
    sums = pn.AggregateNode(
        [], [pn.AggCall(A.Count(), "n"),
             pn.AggCall(A.Sum(ref(0, dt.FLOAT64)), "sx"),
             pn.AggCall(A.Sum(ref(1, dt.FLOAT64)), "sy"),
             pn.AggCall(A.Sum(ref(2, dt.FLOAT64)), "sxy"),
             pn.AggCall(A.Sum(ref(3, dt.FLOAT64)), "sxx"),
             pn.AggCall(A.Sum(ref(4, dt.FLOAT64)), "syy")], moments)
    n = Cast(ref(0, dt.INT64), dt.FLOAT64)
    sx, sy = ref(1, dt.FLOAT64), ref(2, dt.FLOAT64)
    sxy, sxx, syy = (ref(3, dt.FLOAT64), ref(4, dt.FLOAT64),
                     ref(5, dt.FLOAT64))
    cov = ar.Subtract(ar.Multiply(n, sxy), ar.Multiply(sx, sy))
    vx = ar.Subtract(ar.Multiply(n, sxx), ar.Multiply(sx, sx))
    vy = ar.Subtract(ar.Multiply(n, syy), ar.Multiply(sy, sy))
    from spark_rapids_tpu.expressions.math import Sqrt

    corr = ar.Divide(cov, ar.Multiply(Sqrt(vx), Sqrt(vy)))
    return pn.ProjectNode([Alias(corr, "corr")], sums)


QUERIES = {"tpcxbb_q5": q5, "tpcxbb_q6": q6, "tpcxbb_q9": q9,
           "tpcxbb_q11": q11, "tpcxbb_q26": q26}
