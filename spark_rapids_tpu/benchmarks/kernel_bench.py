"""Native-kernel microbench: each Pallas kernel vs the jnp (or host)
implementation it replaces, op by op (KERNEL_r01 record).

Four ops, matching the three gated kernel kinds plus the fused-chain
compaction the sort kernel also serves:

- ``compact``     partition_order + takes  vs  stable argsort(~mask) + takes
- ``join_probe``  device hash-table probe  vs  two searchsorted passes
- ``lexsort``     LSD radix lexsort        vs  jnp.lexsort over key arrays
- ``string_contains``  char-table kernel   vs  the host dictionary map

Every op asserts bit-equality between the two paths before timing —
``scripts/kernel_check.py`` turns that into the CI fence (equality on
any backend; the >=2x ratio only on a real TPU, where the kernels are
compiled rather than interpreted).

    python -m spark_rapids_tpu.benchmarks.kernel_bench --rows 2000000
"""
from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _time(fn, iterations: int, warmup: int = 1) -> float:
    import jax

    for _ in range(warmup):
        jax.block_until_ready(fn())
    best = float("inf")
    for _ in range(iterations):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        best = min(best, time.perf_counter() - t0)
    return best


def bench_compact(rows: int, iterations: int, seed: int = 3) -> dict:
    """Fused-chain row compaction: permutation-from-liveness + payload
    gathers. The baseline is what execs/fused.run_steps does with the
    gate off; the kernel path is what it does with the gate on."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_tpu.native.kernels import sort as nsort

    r = np.random.default_rng(seed)
    mask = jnp.asarray(r.random(rows) > 0.5)
    pays = [jnp.asarray(r.integers(0, 10**9, rows)) for _ in range(3)]

    @jax.jit
    def base(m, ps):
        order = jnp.argsort(~m, stable=True)
        return [jnp.take(p, order) for p in ps]

    @jax.jit
    def kern(m, ps):
        order = nsort.partition_order(m)
        return [jnp.take(p, order) for p in ps]

    b = jax.device_get(base(mask, pays))
    k = jax.device_get(kern(mask, pays))
    equal = all(np.array_equal(x, y) for x, y in zip(b, k))
    base_s = _time(lambda: base(mask, pays), iterations)
    kern_s = _time(lambda: kern(mask, pays), iterations)
    return {"n": rows, "jnp_s": round(base_s, 4),
            "kernel_s": round(kern_s, 4),
            "ratio": round(base_s / kern_s, 3), "equal": bool(equal)}


def bench_join_probe(build_rows: int, probe_rows: int, iterations: int,
                     seed: int = 5) -> dict:
    """Probe side of the hash join, build table amortized (the
    build-once/probe-many contract of ops/join.prepare_build)."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_tpu.native.kernels import join as njoin

    r = np.random.default_rng(seed)
    h_b = jnp.sort(jnp.asarray(
        r.integers(-2**62, 2**62, build_rows)))
    h_p = jnp.asarray(np.concatenate([
        r.choice(np.asarray(jax.device_get(h_b)), probe_rows // 2),
        r.integers(-2**62, 2**62, probe_rows - probe_rows // 2)]))
    n_valid = jnp.asarray(build_rows)
    table = jax.block_until_ready(njoin.build_table(
        h_b, n_valid, njoin.table_bits_for(build_rows)))

    @jax.jit
    def base(sh, hp):
        lo = jnp.searchsorted(sh, hp, side="left")
        hi = jnp.searchsorted(sh, hp, side="right")
        return lo, hi - lo

    @jax.jit
    def kern(t, hp):
        return njoin.probe(t, hp)

    bl, bc = jax.device_get(base(h_b, h_p))
    kl, kc = jax.device_get(kern(table, h_p))
    equal = np.array_equal(bl, kl) and np.array_equal(bc, kc)
    base_s = _time(lambda: base(h_b, h_p), iterations)
    kern_s = _time(lambda: kern(table, h_p), iterations)
    return {"n": probe_rows, "jnp_s": round(base_s, 4),
            "kernel_s": round(kern_s, 4),
            "ratio": round(base_s / kern_s, 3), "equal": bool(equal)}


def bench_lexsort(rows: int, iterations: int, seed: int = 7) -> dict:
    """Permutation-producing lexsort over a composite radixable key
    (null-rank + int64 + int32), the ops/sortkeys routing pair."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.native.kernels import sort as nsort
    from spark_rapids_tpu.ops import sortkeys
    from spark_rapids_tpu.ops.sortkeys import SortKeySpec

    r = np.random.default_rng(seed)
    k1 = jnp.asarray(r.integers(-10**12, 10**12, rows))
    v1 = jnp.asarray(r.random(rows) > 0.1)
    k2 = jnp.asarray(r.integers(0, 100, rows).astype(np.int32))
    cols = [(k1, v1), (k2, None)]
    dtypes = [dt.INT64, dt.INT32]
    specs = [SortKeySpec(0, ascending=False, nulls_first=False),
             SortKeySpec(1)]
    num_rows = jnp.asarray(rows)

    @jax.jit
    def base(c0, c0v, c1, n):
        keys = sortkeys.order_key_arrays(
            [(c0, c0v), (c1, None)], dtypes, specs, n)
        return jnp.lexsort(list(reversed(keys)))

    @jax.jit
    def kern(c0, c0v, c1, n):
        return nsort.lexsort_order(
            [(c0, c0v), (c1, None)], dtypes, specs, n)

    b = np.asarray(jax.device_get(base(k1, v1, k2, num_rows)))
    k = np.asarray(jax.device_get(kern(k1, v1, k2, num_rows)))
    equal = np.array_equal(b, k)
    base_s = _time(lambda: base(k1, v1, k2, num_rows), iterations)
    kern_s = _time(lambda: kern(k1, v1, k2, num_rows), iterations)
    return {"n": rows, "jnp_s": round(base_s, 4),
            "kernel_s": round(kern_s, 4),
            "ratio": round(base_s / kern_s, 3), "equal": bool(equal)}


def bench_string_contains(dict_entries: int, iterations: int,
                          seed: int = 11) -> dict:
    """contains() over the dictionary: device char-table kernel vs the
    host per-entry python map (the expressions/strings fallback)."""
    import jax

    from spark_rapids_tpu.native.kernels import strings as nks

    r = np.random.default_rng(seed)
    alpha = np.array(list("abcdefgh"))
    dic = np.array(
        ["".join(r.choice(alpha, r.integers(2, 24)))
         for _ in range(dict_entries)], dtype=object)
    dic = np.unique(dic.astype(str)).astype(object)
    needle = "cde"
    chars, lens, ascii_only = nks.encode_dictionary(dic)

    def host():
        return np.array([needle in s for s in dic])

    def kern():
        return nks._match_table(chars, lens, "contains",
                                needle.encode("utf-8"))

    equal = np.array_equal(host(), np.asarray(jax.device_get(kern())))
    t0 = time.perf_counter()
    for _ in range(iterations):
        host()
    host_s = (time.perf_counter() - t0) / iterations
    kern_s = _time(kern, iterations)
    return {"n": int(len(dic)), "jnp_s": round(host_s, 4),
            "kernel_s": round(kern_s, 4),
            "ratio": round(host_s / kern_s, 3), "equal": bool(equal)}


def run(rows: int = 2_000_000, iterations: int = 3) -> dict:
    import jax

    import spark_rapids_tpu  # noqa: F401  (x64 on)
    from spark_rapids_tpu.native import kernels as nk

    ops = {
        "compact": bench_compact(rows, iterations),
        "join_probe": bench_join_probe(
            max(rows // 8, 1024), rows, iterations),
        "lexsort": bench_lexsort(max(rows // 4, 1024), iterations),
        "string_contains": bench_string_contains(20_000, iterations),
    }
    return {
        "metric": "native_kernel_vs_jnp",
        "backend": jax.default_backend(),
        "interpret": nk.interpret_mode(),
        "ops": ops,
        "all_equal": all(o["equal"] for o in ops.values()),
        "max_ratio": max(o["ratio"] for o in ops.values()),
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=2_000_000)
    ap.add_argument("--iterations", type=int, default=3)
    args = ap.parse_args(argv)
    print(json.dumps(run(args.rows, args.iterations)))


if __name__ == "__main__":
    main()
