"""BenchmarkRunner CLI (the reference's BenchmarkRunner.scala + BenchUtils:
run a named query N times, capture env/plan/timings as JSON, optionally
verify TPU results against the CPU oracle — docs/benchmarks.md:26-190).

    python -m spark_rapids_tpu.benchmarks.runner \
        --benchmark tpch_q1 --sf 0.01 --iterations 3 --compare \
        --data-dir /tmp/tpch --output q1.json
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Optional

# dispatch telemetry must wrap jax.jit BEFORE the compute modules
# import (module-level @jit decorators capture the binding) — hence
# this pre-parse ahead of the framework imports below
if "--dispatch-telemetry" in sys.argv:  # pragma: no cover - CLI path
    from spark_rapids_tpu.utils import dispatch as _dispatch

    _dispatch.install()

from spark_rapids_tpu.benchmarks import (datagen, mortgage, tpcds, tpch,
                                         tpcxbb)
from spark_rapids_tpu.config import RapidsConf

ALL_BENCHMARKS = dict(tpch.QUERIES)
ALL_BENCHMARKS.update(tpcds.QUERIES)
ALL_BENCHMARKS.update(tpcxbb.QUERIES)
ALL_BENCHMARKS["mortgage_etl"] = mortgage.etl


class BenchmarkRunner:
    def __init__(self, data_dir: str, sf: float,
                 conf: Optional[RapidsConf] = None, skew: float = 0.0):
        self.data_dir = data_dir
        self.sf = sf
        self.conf = conf or RapidsConf()
        # hot-key fraction for the skewed generator (tpch lineitem
        # only); 0.0 keeps the uniform data AND the uniform marker name
        self.skew = skew

    def ensure_data(self, benchmark: str = "tpch") -> None:
        if benchmark.startswith("mortgage"):
            family = "mortgage"
        elif benchmark.startswith("tpcds"):
            family = "tpcds"
        elif benchmark.startswith("tpcxbb"):
            family = "tpcxbb"
        else:
            family = "tpch"
        suffix = f"-skew-{self.skew}" if self.skew else ""
        marker = os.path.join(self.data_dir,
                              f".{family}-sf-{self.sf}{suffix}")
        if os.path.exists(marker):
            return
        os.makedirs(self.data_dir, exist_ok=True)
        # a dir holds exactly one scale factor per family: drop stale
        # markers so a later run at the old sf regenerates instead of
        # silently reading this sf's tables under the old label
        for stale in glob.glob(
                os.path.join(self.data_dir, f".{family}-sf-*")):
            os.remove(stale)
        if family == "mortgage":
            mortgage.gen_tables(self.data_dir, self.sf)
        elif family == "tpcds":
            tpcds.write_tables(self.data_dir, self.sf)
        elif family == "tpcxbb":
            tpcxbb.write_tables(self.data_dir, self.sf)
        else:
            datagen.write_tables(self.data_dir, self.sf,
                                 skew=self.skew)
        with open(marker, "w") as f:
            f.write("ok")

    @staticmethod
    def _env() -> dict:
        import jax

        import spark_rapids_tpu
        from spark_rapids_tpu.utils import dispatch as _disp

        # measured, not assumed: the per-dispatch floor distinguishes a
        # local in-process backend (~0) from a remote tunnel attachment
        # (~105 ms), so a recorded number can be interpreted without
        # knowing which box produced it
        try:
            rtt = round(_disp.measure_rtt(), 6)
        except Exception:
            rtt = None
        return {
            "framework_version": getattr(spark_rapids_tpu, "__version__",
                                         "dev"),
            "jax_version": jax.__version__,
            "backend": jax.devices()[0].platform,
            "device_count": len(jax.devices()),
            "device_kind": jax.devices()[0].device_kind,
            "rtt_probe_s": rtt,
        }

    def run(self, benchmark: str, iterations: int = 3,
            compare: bool = False, warmup: int = 1) -> dict:
        from spark_rapids_tpu.execs.base import collect
        from spark_rapids_tpu.plan.overrides import apply_overrides

        self.ensure_data(benchmark)
        plan_fn = ALL_BENCHMARKS[benchmark]
        result: dict = {
            "benchmark": benchmark,
            "scale_factor": self.sf,
            "env": self._env(),
            "iterations": [],
        }
        from spark_rapids_tpu.memory import fault_injection as _fi
        from spark_rapids_tpu.memory import retry as _retry
        from spark_rapids_tpu.memory.catalog import get_catalog
        from spark_rapids_tpu.utils import dispatch as disp

        from spark_rapids_tpu.parallel import spmd

        from spark_rapids_tpu.parallel import mesh as pmesh

        telemetry = disp.installed()
        df = None
        pre_stage = None
        pre_prog = None
        # fallback telemetry covers the WHOLE run (planning records the
        # reasons, and planning happens inside the iteration loop)
        run_pre_fb = spmd.fallback_snapshot()
        # mesh-construction fallbacks (device clamp, dropped model axis)
        # and ICI-vs-DCN seam decisions over the same window
        run_pre_mesh_fb = pmesh.mesh_fallback_snapshot()
        run_pre_seam = spmd.seam_snapshot()
        # AQE replan events over the whole run (counters live in
        # execs.adaptive; the dispatch module passes through so the
        # telemetry consumers snapshot from one place)
        run_pre_replan = disp.replan_snapshot()
        # scan-pipeline activity over the run (io/scanpipe counters:
        # bytes read vs pruned, decode/h2d seconds, overlap fraction)
        run_pre_scan = disp.scan_snapshot()
        # run-relative snapshots: totals, per-site map, catalog spill
        # counters and injector counts all report DELTAS over this run
        # — a second benchmark in the same process must not inherit the
        # first one's OOM activity in its report
        run_pre_retry = _retry.snapshot()
        run_pre_sites = _retry.stats()["per_site"]
        from spark_rapids_tpu.service.streaming import stats as _sstats

        run_pre_stream = _sstats.snapshot()
        from spark_rapids_tpu.runtime import recovery as _recovery

        run_pre_recovery = _recovery.snapshot()
        cat = get_catalog()
        pre_spill_dev = cat.spilled_device_bytes
        pre_spill_host = cat.spilled_host_bytes
        pre_inj = _fi.get_injector().stats()
        for i in range(warmup + iterations):
            plan = plan_fn(self.data_dir)  # fresh plan: no cached blocks
            exec_ = apply_overrides(plan, self.conf)
            pre = disp.snapshot() if telemetry else None
            pre_stage = disp.stage_snapshot() if telemetry else None
            pre_prog = disp.stage_programs_snapshot() if telemetry \
                else None
            pre_retry = _retry.snapshot()
            t0 = time.perf_counter()
            df = collect(exec_)
            elapsed = time.perf_counter() - t0
            if i >= warmup:
                it_rec = {"time_sec": elapsed,
                          "oom_retry": _retry.delta(pre_retry)}
                if telemetry:
                    it_rec["dispatch"] = disp.delta(pre)
                result["iterations"].append(it_rec)
        # OOM-resilience accounting across the whole run: the retry
        # ladder's per-site counters plus the spill catalog's tier
        # traffic — nonzero numbers here are the proof an over-budget
        # or fault-injected run actually exercised the machinery
        run_retry = _retry.delta(run_pre_retry)
        run_retry["per_site"] = _retry.site_delta(run_pre_sites)
        inj = _fi.get_injector().stats()
        result["memory"] = {
            "oom_retry": run_retry,
            "spilled_device_bytes": cat.spilled_device_bytes -
            pre_spill_dev,
            "spilled_host_bytes": cat.spilled_host_bytes -
            pre_spill_host,
            "device_budget": cat.device_budget,
            "fault_injection": {
                "armed": inj["armed"],
                "calls": inj["calls"] - pre_inj["calls"],
                "injections": inj["injections"] - pre_inj["injections"],
            },
        }
        # streaming ingestion activity during the run (zeros for pure
        # batch benchmarks; a dashboard-replay harness that appends
        # micro-batches between iterations shows its folds here)
        result["streaming"] = _sstats.delta(run_pre_stream)
        # lineage fault recovery during the run (zeros on a healthy
        # cluster; a chaos run shows its re-run maps and respawns here)
        result["recovery"] = _recovery.delta(run_pre_recovery)
        # every AQE replan this run made (skew splits/salting, strategy
        # switches, re-bucketing), with counts — zeros/empty when the
        # static plan ran unchanged
        result["replan_events"] = disp.replan_delta(run_pre_replan)
        # ingest telemetry: how much the scan layer read, what pruning
        # saved, and how much of the read+pack hid behind compute
        result["io_scan"] = disp.scan_delta(run_pre_scan)
        if telemetry and result["iterations"]:
            # the BASELINE.md-promised split: dispatch_count x RTT vs
            # time actually spent computing on the device
            from spark_rapids_tpu.plan.optimizer import cut_stages
            from spark_rapids_tpu.utils import progcache

            rtt = disp.measure_rtt()
            last = result["iterations"][-1]
            count = last["dispatch"]["dispatch_count"]
            result["dispatch_telemetry"] = {
                "executable_count": disp.executable_count(),
                "dispatch_count": count,
                "dispatch_rtt_s": round(rtt, 4),
                "est_dispatch_overhead_s": round(count * rtt, 3),
                "est_on_device_s": round(
                    max(last["time_sec"] - count * rtt, 0.0), 3),
                # measured per-stage round trips of the LAST iteration,
                # next to the plan's static per-stage estimate — the
                # split that shows WHERE the dispatch budget sits
                "per_stage": disp.stage_delta(pre_stage),
                # which PROGRAMS each stage launched (round-7: names
                # the six dispatches a bare "stage0: 6" hides)
                "per_stage_programs": disp.stage_program_delta(pre_prog),
                "stages": [
                    {"stage": s["stage"],
                     "ops": "+".join(s["ops"]),
                     "est_dispatches": s["est_dispatches"],
                     "mesh_internal": s["mesh_internal"]}
                    for s in cut_stages(exec_)],
                # every mesh-requested shuffle that stayed on the
                # host/TCP path this run, with the gate's reason
                "shuffle_fallbacks": spmd.fallback_delta(run_pre_fb),
                # mesh construction that downgraded the conf's request
                # (device clamp, dropped model axis) — the silent-clamp
                # fix: a too-big rapids.tpu.mesh.devices shows up here
                "mesh_fallbacks": pmesh.mesh_fallback_delta(
                    run_pre_mesh_fb),
                # which seam (intra-host ICI vs cross-host DCN) carried
                # each shuffle decision this run
                "seam_decisions": spmd.seam_delta(run_pre_seam),
                "replan_events": disp.replan_delta(run_pre_replan),
                "compile_cache": progcache.stats(),
            }
            # MEASURED on-device time (round-5): one extra serialized
            # pass where every jit call blocks and records its own
            # device seconds (per-kernel attribution), cross-checkable
            # against the wall-based estimate above. Task threads MUST
            # be 1: overlapped partitions would each time the other's
            # kernels. try/finally so a failing pass can't leave
            # blocking-mode timing enabled for later measurements.
            from spark_rapids_tpu import config as cfg

            serial_conf = self.conf.with_overrides(
                {cfg.TASK_THREADS.key: 1})
            disp.enable_device_timing()
            try:
                plan = plan_fn(self.data_dir)
                exec_m = apply_overrides(plan, serial_conf)
                t0 = time.perf_counter()
                collect(exec_m, conf=serial_conf)
                wall_m = time.perf_counter() - t0
            finally:
                kt = disp.disable_device_timing()
            per_kernel = {
                k: {"calls": c, "device_s": round(s, 4)}
                for k, (c, s) in sorted(
                    (i for i in kt.items() if i[0] != "__total__"),
                    key=lambda i: i[1][1], reverse=True)[:12]}
            # same seconds split per (stage, program): "stage0 spends
            # 2.1s in chain@a1b2" rather than a global program total
            per_stage_device = {
                label: {p: {"calls": c, "device_s": round(s, 4)}
                        for p, (c, s) in sorted(
                            progs.items(), key=lambda i: i[1][1],
                            reverse=True)[:8]}
                for label, progs in disp.stage_device_times().items()}
            result["device_timing"] = {
                "mode": "serialized",
                "wall_s": round(wall_m, 3),
                "on_device_s": round(kt["__total__"][1], 4),
                "timed_jit_calls": kt["__total__"][0],
                "per_kernel": per_kernel,
                "per_stage_programs_device_s": per_stage_device,
            }
        result["query_plan"] = exec_.tree_string()
        result["metrics"] = {
            name: {"rows": m.num_output_rows,
                   "batches": m.num_output_batches,
                   "op_time_ms": m.op_time_ns / 1e6}
            for name, m in exec_.all_metrics().items()}
        times = [it["time_sec"] for it in result["iterations"]]
        result["min_time_sec"] = min(times)
        result["rows_returned"] = len(df)
        if compare:
            result["compare"] = self.compare_results(benchmark, df)
        return result

    def compare_results(self, benchmark: str, tpu_df) -> dict:
        """BenchUtils.compareResults: run the CPU oracle and diff."""
        from spark_rapids_tpu.cpu.engine import execute_cpu

        plan = ALL_BENCHMARKS[benchmark](self.data_dir)
        t0 = time.perf_counter()
        cpu_df = execute_cpu(plan).to_pandas()
        cpu_time = time.perf_counter() - t0
        ok, reason = _frames_match(cpu_df, tpu_df)
        return {"matches_cpu": ok, "cpu_time_sec": cpu_time,
                "detail": reason}


def _frames_match(cpu_df, tpu_df) -> "tuple[bool, str]":
    try:
        from tests.compare import assert_frames_equal
    except ImportError:  # installed without tests/: structural check only
        ok = len(cpu_df) == len(tpu_df)
        return ok, "" if ok else "row count mismatch"
    try:
        assert_frames_equal(cpu_df, tpu_df, approx_float=1e-6)
        return True, ""
    except AssertionError as e:
        return False, str(e)[:500]


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--benchmark", required=True,
                   choices=sorted(ALL_BENCHMARKS))
    p.add_argument("--sf", type=float, default=0.01)
    p.add_argument("--iterations", type=int, default=3)
    p.add_argument("--warmup", type=int, default=1)
    p.add_argument("--compare", action="store_true")
    p.add_argument("--dispatch-telemetry", action="store_true",
                   help="count jit/eager/transfer dispatches per "
                        "iteration and report the dispatch-RTT vs "
                        "on-device split (install happens at module "
                        "import, before the compute modules load)")
    p.add_argument("--skew", type=float, default=0.0,
                   help="hot-key fraction for the skewed tpch "
                        "generator (0.5 = one orderkey carries half "
                        "of lineitem); 0 keeps uniform data")
    p.add_argument("--data-dir", default="/tmp/rapids_tpu_tpch")
    p.add_argument("--output", default=None)
    args = p.parse_args(argv)
    if args.dispatch_telemetry:
        from spark_rapids_tpu.utils import dispatch as disp

        if not disp.installed():
            # too late: the compute modules already imported with the
            # real jax.jit (module-level @jit decorators captured it).
            # The flag only works as a literal CLI token, which the
            # import-time pre-parse above matched before the imports.
            p.error("--dispatch-telemetry must appear verbatim in "
                    "sys.argv before module import (no abbreviations; "
                    "for programmatic use call "
                    "spark_rapids_tpu.utils.dispatch.install() before "
                    "importing the runner)")
    runner = BenchmarkRunner(args.data_dir, args.sf, skew=args.skew)
    result = runner.run(args.benchmark, iterations=args.iterations,
                        compare=args.compare, warmup=args.warmup)
    text = json.dumps(result, indent=2)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    print(text)


if __name__ == "__main__":
    main()
