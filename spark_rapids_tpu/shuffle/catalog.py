"""Per-executor shuffle buffer catalog (ShuffleBufferCatalog analogue,
sql-plugin §2.8: ShuffleBlockId -> buffer ids -> TableMeta).

Blocks written by map tasks are registered as spillable batches at
shuffle-output priority (spills FIRST — SpillPriorities.scala:32-60); the
serving path acquires through the spill catalog, transparently unspilling
(RapidsShuffleServer acquires "possibly unspilling")."""
from __future__ import annotations

import threading
from spark_rapids_tpu.utils import lockorder
from typing import Dict, List, Optional

from spark_rapids_tpu.columnar import compression, serde
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.memory import priorities
from spark_rapids_tpu.memory.catalog import BufferCatalog
from spark_rapids_tpu.memory.spillable import SpillableBatch
from spark_rapids_tpu.shuffle.meta import BlockId, ShuffleTableMeta


class ShuffleBufferCatalog:
    def __init__(self, buffer_catalog: BufferCatalog,
                 codec: str = "lz4"):
        self.buffer_catalog = buffer_catalog
        self.codec = codec
        self._lock = lockorder.make_lock("shuffle.catalog.state")
        self._blocks: Dict[BlockId, SpillableBatch] = {}
        self._metas: Dict[BlockId, ShuffleTableMeta] = {}

    def register(self, block: BlockId, batch: ColumnarBatch
                 ) -> ShuffleTableMeta:
        """Cache one map-output sub-batch (RapidsCachingWriter.write,
        RapidsShuffleInternalManager.scala:90-155)."""
        n = batch.realized_num_rows()
        dtypes = tuple(c.dtype.name for c in batch.columns)
        if n == 0:
            # degenerate (rows-only / empty) batch: meta only, no buffer
            meta = ShuffleTableMeta(block, 0, 0, dtypes)
            with self._lock:
                self._metas[block] = meta
            return meta
        sb = SpillableBatch(batch,
                            priorities.OUTPUT_FOR_SHUFFLE_PRIORITY,
                            catalog=self.buffer_catalog)
        payload_len = self._payload_len_estimate(batch)
        meta = ShuffleTableMeta(block, n, payload_len, dtypes)
        with self._lock:
            self._blocks[block] = sb
            self._metas[block] = meta
        return meta

    @staticmethod
    def _payload_len_estimate(batch: ColumnarBatch) -> int:
        # upper bound before compression; the actual wire chunking uses
        # the real payload length from serialize()
        return batch.device_memory_size() + 4096

    def meta(self, block: BlockId) -> Optional[ShuffleTableMeta]:
        with self._lock:
            return self._metas.get(block)

    def metas_for(self, shuffle_id: int, partition: int
                  ) -> List[ShuffleTableMeta]:
        with self._lock:
            return [m for b, m in sorted(self._metas.items())
                    if b.shuffle_id == shuffle_id
                    and b.partition == partition]

    def has_block(self, block: BlockId) -> bool:
        with self._lock:
            return block in self._metas

    def acquire_batch(self, block: BlockId):
        """Zero-copy local read (RapidsCachingReader local-hit path).
        Returns an ``acquired()`` context manager, or None for degenerate
        blocks."""
        with self._lock:
            sb = self._blocks.get(block)
        if sb is None:
            return None
        return sb.acquired()

    def serialize_payload(self, block: BlockId) -> bytes:
        """Wire payload for remote fetch: acquire (unspill if needed) ->
        host serialize -> compression envelope."""
        with self._lock:
            sb = self._blocks.get(block)
        if sb is None:
            raise KeyError(f"block {block} not in shuffle catalog")
        with sb.acquired() as batch:
            hb = serde.to_host_batch(batch)
        return compression.wrap(serde.serialize_host_batch(hb),
                                self.codec)

    def deserialize_payload(self, payload: bytes) -> ColumnarBatch:
        hb = serde.deserialize_host_batch(compression.unwrap(payload))
        return serde.to_device_batch(hb)

    def unregister_shuffle(self, shuffle_id: int) -> int:
        """Drop all blocks of a shuffle (unregisterShuffle on shuffle
        cleanup); returns blocks removed."""
        with self._lock:
            victims = [b for b in self._metas
                       if b.shuffle_id == shuffle_id]
            handles = [self._blocks.pop(b) for b in victims
                       if b in self._blocks]
            for b in victims:
                del self._metas[b]
        for h in handles:
            h.close()
        return len(victims)

    def __len__(self) -> int:
        with self._lock:
            return len(self._metas)
