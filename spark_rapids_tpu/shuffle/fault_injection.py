"""Deterministic transport/worker fault injection.

The sibling of ``memory/fault_injection.py`` for the distributed
runtime: where that module fires synthetic device OOM at exact guarded
calls, this one fires transport and process faults at exact protocol
ordinals, so the whole lineage-recovery ladder (docs/fault-tolerance.md;
RapidsShuffleIterator.scala:242-300's fetch-failure escalation) runs
deterministically on CPU CI:

- ``drop_at_request=N``: the Nth transport round trip fails with a
  retryable TransportError after dropping the socket — exercises the
  connection-level reconnect + exponential backoff
  (shuffle/tcp.py ``_roundtrip_retrying``) WITHOUT costing a stage.
- ``truncate_at_request=N``: the Nth chunk request's payload comes back
  short. The short-chunk check sits ABOVE the connection retry loop
  (transport.py ``_fetch_payload``), so this deterministically escalates
  to ``ShuffleFetchFailedError`` and a stage retry.
- ``kill_before_task=N``: SIGKILL the target worker right before the
  Nth task submission. The submit fails over locally; the worker's
  EARLIER registered outputs then fail reduce-side fetches — the
  worker-death half of recovery (invalidate, respawn, re-run).
- ``kill_host_at_stage=N``: SIGKILL one live worker HOST at the start
  of the Nth shuffle map stage — the host-granularity fault for the
  elastic-membership ladder (runtime/cluster.py): the host's earlier
  registered outputs fail reduce-side fetches, the slot respawns as
  ``{slot}~{gen}``, and exactly the lost maps re-run.
- ``partition_dcn_at_request=N``: the DCN seam partitions starting at
  the Nth cross-host round trip — each affected request fails like a
  downed inter-host link (socket dropped, retryable). With
  ``consecutive`` past the transport retry budget this escalates into
  a fetch failure and a stage retry; each distinct partition event
  bumps the ``dcn_partitions`` recovery counter.
- ``crash_at_fold=N``: SIGKILL the CURRENT process at the start of the
  Nth standing-query fold — after the delta's WAL append is durable,
  before the running state swaps. The unclean-death half of the PR 19
  streaming durability contract: restart recovery must rebuild from
  checkpoint + WAL replay, bit-exact.
- ``torn_checkpoint_at=N``: the Nth streaming checkpoint commit writes
  only the FIRST HALF of its bytes under the final file name, skipping
  the atomic rename — a crash that beat the rename. Recovery must
  reject it on CRC and fall back to an older checkpoint or the WAL.
- ``truncate_wal_at=N``: the Nth WAL record append persists only half
  its frame — a process dying mid-write. Replay must tolerate (and
  truncate) the torn tail; corruption MID-log stays loud.
- ``probability`` + ``seed``: seeded random connection drops for chaos
  sweeps; ``consecutive=K`` makes each firing point fail K events in a
  row (K past the transport retry budget escalates a drop into a fetch
  failure; a huge K with ``truncate_at_request=1`` shorts EVERY chunk —
  the maxStageRetries-exhaustion fence), ``max_injections`` caps the
  total so a chaos run terminates.

Only the arming process injects (workers never arm), so counts are
driver-deterministic. Armed from config
(``rapids.tpu.shuffle.faultInjection.*``) by ``runtime.initialize`` or
directly by tests/scripts (scripts/dist_chaos_check.py).
"""
from __future__ import annotations

import random
from typing import Optional

from spark_rapids_tpu.utils import lockorder


class _Trigger:
    """Fire at the Nth eligible event, then ``consecutive - 1`` more in
    a row (the memory injector's at_call + burst semantics)."""

    __slots__ = ("at", "consecutive", "count", "burst")

    def __init__(self, at: int, consecutive: int):
        self.at = max(int(at), 0)
        self.consecutive = max(int(consecutive), 1)
        self.count = 0
        self.burst = 0

    def fire(self) -> bool:
        self.count += 1
        if self.burst > 0:
            self.burst -= 1
            return True
        if self.at and self.count == self.at:
            self.burst = self.consecutive - 1
            return True
        return False


class ShuffleFaultInjector:
    """Thread-safe injection point shared by every transport client and
    worker handle in the process."""

    def __init__(self):
        self._lock = lockorder.make_lock("shuffle.faultInjection")
        self.disarm()

    def disarm(self) -> None:
        with self._lock:
            self._armed = False
            self._drop = _Trigger(0, 1)
            self._truncate = _Trigger(0, 1)
            self._kill = _Trigger(0, 1)
            self._kill_host = _Trigger(0, 1)
            self._dcn = _Trigger(0, 1)
            self._crash_fold = _Trigger(0, 1)
            self._torn_ckpt = _Trigger(0, 1)
            self._trunc_wal = _Trigger(0, 1)
            self._probability = 0.0
            self._rng: Optional[random.Random] = None
            self._max_injections = 0
            self._drops = 0
            self._truncations = 0
            self._kills = 0
            self._host_kills = 0
            self._dcn_drops = 0
            self._dcn_partitions = 0
            self._fold_crashes = 0
            self._torn_checkpoints = 0
            self._wal_truncations = 0

    def arm(self, drop_at_request: int = 0, truncate_at_request: int = 0,
            kill_before_task: int = 0, probability: float = 0.0,
            seed: int = 0, consecutive: int = 1,
            max_injections: int = 0, kill_host_at_stage: int = 0,
            partition_dcn_at_request: int = 0, crash_at_fold: int = 0,
            torn_checkpoint_at: int = 0,
            truncate_wal_at: int = 0) -> None:
        """Arm (resetting all counters). Ordinals count eligible events
        from 1; 0 disables that fault kind (probability may still drop
        connections)."""
        with self._lock:
            self._armed = True
            self._drop = _Trigger(drop_at_request, consecutive)
            self._truncate = _Trigger(truncate_at_request, consecutive)
            self._kill = _Trigger(kill_before_task, 1)
            self._kill_host = _Trigger(kill_host_at_stage, 1)
            self._dcn = _Trigger(partition_dcn_at_request, consecutive)
            self._crash_fold = _Trigger(crash_at_fold, 1)
            self._torn_ckpt = _Trigger(torn_checkpoint_at, consecutive)
            self._trunc_wal = _Trigger(truncate_wal_at, consecutive)
            self._probability = float(probability)
            self._rng = random.Random(seed) if probability > 0 else None
            self._max_injections = max(int(max_injections), 0)
            self._drops = 0
            self._truncations = 0
            self._kills = 0
            self._host_kills = 0
            self._dcn_drops = 0
            self._dcn_partitions = 0
            self._fold_crashes = 0
            self._torn_checkpoints = 0
            self._wal_truncations = 0

    @property
    def armed(self) -> bool:
        return self._armed

    def _capped(self) -> bool:
        return self._max_injections and \
            (self._drops + self._truncations + self._kills +
             self._host_kills + self._dcn_drops + self._fold_crashes +
             self._torn_checkpoints + self._wal_truncations) >= \
            self._max_injections

    def should_drop(self) -> bool:
        """Count one transport round trip; True = the caller must drop
        its socket and fail the request with a retryable error."""
        if not self._armed:
            return False
        with self._lock:
            fire = self._drop.fire()
            if not fire and self._rng is not None and \
                    self._rng.random() < self._probability:
                fire = True
                self._drop.burst = self._drop.consecutive - 1
            if not fire or self._capped():
                return False
            self._drops += 1
            return True

    def maybe_truncate(self, payload: bytes) -> bytes:
        """Count one chunk request carrying data; when firing, return a
        short payload (half the frame) for the client's length check to
        reject."""
        if not self._armed or len(payload) < 2:
            return payload
        with self._lock:
            if not self._truncate.fire() or self._capped():
                return payload
            self._truncations += 1
        return payload[:len(payload) // 2]

    def should_kill_task(self) -> bool:
        """Count one worker task submission; True = SIGKILL the target
        worker before submitting (the caller owns the process handle)."""
        if not self._armed:
            return False
        with self._lock:
            if not self._kill.fire() or self._capped():
                return False
            self._kills += 1
            return True

    def should_kill_host_at_stage(self) -> bool:
        """Count one shuffle map-stage start (driver-side); True = the
        runtime must SIGKILL one live worker HOST before running the
        stage (ClusterRuntime.kill_one_host owns the handles). Recovery
        then discovers the death through reduce-side fetch failures —
        the same signal a real host loss produces."""
        if not self._armed:
            return False
        with self._lock:
            if not self._kill_host.fire() or self._capped():
                return False
            self._host_kills += 1
            return True

    def should_partition_dcn(self) -> bool:
        """Count one cross-host transport round trip; True = the DCN
        seam is partitioned for this request (the caller drops its
        socket and fails with a retryable TransportError). The FIRST
        request of each partition event bumps the ``dcn_partitions``
        recovery counter; the burst that follows models the link
        staying down."""
        if not self._armed:
            return False
        with self._lock:
            if not self._dcn.fire() or self._capped():
                return False
            self._dcn_drops += 1
            initial = self._dcn.count == self._dcn.at
            if initial:
                self._dcn_partitions += 1
        if initial:
            from spark_rapids_tpu.runtime import recovery

            recovery.bump("dcn_partitions")
        return True

    def should_crash_at_fold(self) -> bool:
        """Count one standing-query fold start; True = the caller
        SIGKILLs its OWN process (standing.py owns the call) — the
        durability layer's unclean-death fault."""
        if not self._armed:
            return False
        with self._lock:
            if not self._crash_fold.fire() or self._capped():
                return False
            self._fold_crashes += 1
            return True

    def should_tear_checkpoint(self) -> bool:
        """Count one streaming checkpoint commit; True = the store
        writes half the blob under the final name with no rename (a
        crash that beat the atomic commit)."""
        if not self._armed:
            return False
        with self._lock:
            if not self._torn_ckpt.fire() or self._capped():
                return False
            self._torn_checkpoints += 1
            return True

    def should_truncate_wal(self) -> bool:
        """Count one WAL record append; True = only half the record's
        frame reaches the log (a process dying mid-write — the torn
        tail replay must tolerate)."""
        if not self._armed:
            return False
        with self._lock:
            if not self._trunc_wal.fire() or self._capped():
                return False
            self._wal_truncations += 1
            return True

    def stats(self) -> dict:
        with self._lock:
            return {"armed": self._armed,
                    "requests": self._drop.count,
                    "chunk_requests": self._truncate.count,
                    "tasks": self._kill.count,
                    "stages": self._kill_host.count,
                    "folds": self._crash_fold.count,
                    "checkpoint_commits": self._torn_ckpt.count,
                    "wal_appends": self._trunc_wal.count,
                    "drops": self._drops,
                    "truncations": self._truncations,
                    "kills": self._kills,
                    "host_kills": self._host_kills,
                    "dcn_drops": self._dcn_drops,
                    "dcn_partitions": self._dcn_partitions,
                    "fold_crashes": self._fold_crashes,
                    "torn_checkpoints": self._torn_checkpoints,
                    "wal_truncations": self._wal_truncations}


_injector = ShuffleFaultInjector()


def get_injector() -> ShuffleFaultInjector:
    return _injector


def arm_from_conf(conf) -> bool:
    """Arm/disarm the global injector from
    ``rapids.tpu.shuffle.faultInjection.*``; returns True when armed."""
    from spark_rapids_tpu import config as cfg

    if not conf.get(cfg.SHUFFLE_FI_ENABLED):
        _injector.disarm()
        return False
    _injector.arm(
        drop_at_request=conf.get(cfg.SHUFFLE_FI_DROP_AT),
        truncate_at_request=conf.get(cfg.SHUFFLE_FI_TRUNCATE_AT),
        kill_before_task=conf.get(cfg.SHUFFLE_FI_KILL_BEFORE_TASK),
        probability=conf.get(cfg.SHUFFLE_FI_PROBABILITY),
        seed=conf.get(cfg.SHUFFLE_FI_SEED),
        consecutive=conf.get(cfg.SHUFFLE_FI_CONSECUTIVE),
        max_injections=conf.get(cfg.SHUFFLE_FI_MAX),
        kill_host_at_stage=conf.get(cfg.SHUFFLE_FI_KILL_HOST_AT_STAGE),
        partition_dcn_at_request=conf.get(
            cfg.SHUFFLE_FI_PARTITION_DCN_AT),
        crash_at_fold=conf.get(cfg.SHUFFLE_FI_CRASH_AT_FOLD),
        torn_checkpoint_at=conf.get(cfg.SHUFFLE_FI_TORN_CHECKPOINT_AT),
        truncate_wal_at=conf.get(cfg.SHUFFLE_FI_TRUNCATE_WAL_AT))
    return True
