"""TCP cross-process shuffle transport.

The reference's accelerated shuffle runs over UCX — endpoint bootstrap on
a TCP management port, tag-addressed transfers, a single progress thread
per endpoint (shuffle-plugin/.../ucx/UCX.scala:70-266,
UCXShuffleTransport.scala:47-105). TPU pods get the same-slice bulk path
"for free" as in-program ICI collectives (parallel/shuffle.py), so the
socket transport's job here is the reference's OTHER path: cross-host /
DCN block service with Spark-compatible failure semantics.

This module is a real-socket implementation of the transport-agnostic
protocol in shuffle/transport.py — the SAME ``ShuffleServer`` handlers
and the SAME ``ShuffleClient`` windowed-chunk/inflight-throttle logic run
over it, so everything the mocked-transport tests established about the
protocol holds across processes:

- framing: 4-byte big-endian length + JSON control message; chunk
  responses carry raw payload bytes after the JSON header,
- server: accept thread + per-connection reader threads that submit into
  ONE progress-queue endpoint (the UCX single-progress-thread model,
  UCX.scala:80-97) — handlers never run concurrently,
- client: one socket per connection object, request/response serialized
  under a lock; socket errors and timeouts surface as TransportError so
  the task iterator converts them to fetch-failures → stage retry
  (RapidsShuffleIterator.scala:242-300).
"""
from __future__ import annotations

import json
import random
import socket
import struct
import threading
from spark_rapids_tpu.utils import lockorder
import time
from typing import Dict, List, Optional

from spark_rapids_tpu.shuffle.meta import BlockId, ShuffleTableMeta
from spark_rapids_tpu.shuffle.transport import (Connection, ShuffleServer,
                                                TransportError, _Endpoint)

_LEN = struct.Struct(">I")
_MAX_FRAME = 256 << 20

# Process-wide transport retry policy (rapids.tpu.shuffle.retry.*):
# connections are created per-peer deep inside the transport registry,
# so the session pushes the knobs here once (configure_retry_from_conf,
# called from runtime.initialize alongside the fault injector) instead
# of threading a conf through every connect().
_retry_policy = {"max_reconnects": 3, "jitter_ms": 10}


def configure_retry(max_reconnects: Optional[int] = None,
                    jitter_ms: Optional[int] = None) -> None:
    """Set the process-wide transport retry policy; None leaves a field
    unchanged. Existing connections keep the policy they were built
    with (one socket, in-flight requests)."""
    if max_reconnects is not None:
        _retry_policy["max_reconnects"] = max(int(max_reconnects), 0)
    if jitter_ms is not None:
        _retry_policy["jitter_ms"] = max(int(jitter_ms), 0)


def configure_retry_from_conf(conf) -> None:
    """Push ``rapids.tpu.shuffle.retry.{maxReconnects,jitterMs}`` into
    the process-wide policy."""
    from spark_rapids_tpu import config as cfg

    configure_retry(
        max_reconnects=conf.get(cfg.SHUFFLE_RETRY_MAX_RECONNECTS),
        jitter_ms=conf.get(cfg.SHUFFLE_RETRY_JITTER_MS))


class Hangup(Exception):
    """Raised from a fault hook to kill the connection without replying —
    the injected-connection-drop primitive for failure tests."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("peer closed")
        buf.extend(part)
    return bytes(buf)


def _send_frame(sock: socket.socket, header: dict,
                payload: bytes = b"") -> None:
    body = json.dumps(header).encode()
    sock.sendall(_LEN.pack(len(body)) + body +
                 _LEN.pack(len(payload)) + payload)


def _recv_frame(sock: socket.socket):
    (hlen,) = _LEN.unpack(_recv_exact(sock, 4))
    if hlen > _MAX_FRAME:
        raise ConnectionError(f"oversized header {hlen}")
    header = json.loads(_recv_exact(sock, hlen))
    (plen,) = _LEN.unpack(_recv_exact(sock, 4))
    if plen > _MAX_FRAME:
        raise ConnectionError(f"oversized payload {plen}")
    payload = _recv_exact(sock, plen) if plen else b""
    return header, payload


def _block_to_wire(b: BlockId) -> list:
    return [b.shuffle_id, b.map_id, b.partition]


def _block_from_wire(w) -> BlockId:
    return BlockId(int(w[0]), int(w[1]), int(w[2]))


class TcpShuffleServer:
    """Serves one executor's catalog over a listening socket.

    The bootstrap role of the reference's TCP management port: peers
    connect to ``(host, port)`` learned from the map-status topology
    string (RapidsShuffleInternalManager.scala:171-183)."""

    def __init__(self, server: ShuffleServer, host: str = "127.0.0.1",
                 port: int = 0):
        self.server = server
        self._ep = _Endpoint(server)
        self._listener = socket.create_server((host, port))
        self.host, self.port = self._listener.getsockname()[:2]
        self._conns: List[socket.socket] = []
        self._lock = lockorder.make_lock("shuffle.tcp.server")
        self._closed = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name=f"tcp-shuffle-{server.executor_id}", daemon=True)
        self._accept_thread.start()

    @property
    def address(self):
        return (self.host, self.port)

    def _accept_loop(self):
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            with self._lock:
                self._conns.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket):
        try:
            while True:
                header, _ = _recv_frame(conn)
                op = header["op"]
                try:
                    if op == "metadata":
                        blocks = [_block_from_wire(w)
                                  for w in header["blocks"]]
                        metas = self._ep.submit("metadata",
                                                blocks).result()
                        _send_frame(conn, {
                            "ok": True,
                            "metas": [m.to_json() for m in metas]})
                    elif op == "chunk":
                        data = self._ep.submit(
                            "chunk", _block_from_wire(header["block"]),
                            int(header["offset"]),
                            int(header["length"])).result()
                        _send_frame(conn, {"ok": True}, bytes(data))
                    elif op == "release":
                        self._ep.submit(
                            "release",
                            _block_from_wire(header["block"])).result()
                        _send_frame(conn, {"ok": True})
                    else:
                        _send_frame(conn, {"ok": False,
                                           "error": f"bad op {op}"})
                except Hangup:
                    # fault injection: drop the connection mid-protocol
                    break
                except Exception as e:  # noqa: BLE001 - wire errors back
                    _send_frame(conn, {"ok": False, "error": str(e)})
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self):
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            for c in self._conns:
                try:
                    c.close()
                except OSError:
                    pass
            self._conns.clear()
        self._ep.shutdown()


class TcpConnection(Connection):
    """Client endpoint for one peer server; request/response pairs are
    serialized under a lock (one socket, in-order protocol).

    Transient transport faults (a slow peer's timeout, a dropped
    connection) retry with bounded exponential backoff — the failing
    round trip already dropped the socket, so each retry is also the
    one reconnect. Only after the retry budget (or the caller's
    timeout window) is exhausted does the error surface as a fetch
    failure and cost a whole stage re-run
    (RapidsShuffleIterator.scala:242-300 keeps that escalation)."""

    #: bounded transient-fault retries per request (first backoff
    #: _RETRY_BASE_S, doubling; total added wait stays well under any
    #: sane request timeout). The process-wide default comes from the
    #: retry policy (rapids.tpu.shuffle.retry.maxReconnects); this
    #: class attribute is the policy's own fallback.
    MAX_TRANSIENT_RETRIES = 3
    _RETRY_BASE_S = 0.05

    def __init__(self, host: str, port: int,
                 connect_timeout: float = 10.0,
                 max_transient_retries: Optional[int] = None):
        self._addr = (host, port)
        self._sock: Optional[socket.socket] = None
        self._lock = lockorder.make_lock("shuffle.tcp.client")
        self._connect_timeout = connect_timeout
        self._max_retries = _retry_policy["max_reconnects"] \
            if max_transient_retries is None else max_transient_retries
        self._jitter_s = _retry_policy["jitter_ms"] / 1e3

    def _ensure(self, timeout: float) -> socket.socket:
        if self._sock is None:
            try:
                self._sock = socket.create_connection(
                    self._addr, timeout=self._connect_timeout)
            except OSError as e:
                raise TransportError(
                    f"connect to {self._addr} failed: {e}")
        self._sock.settimeout(timeout)
        return self._sock

    def _roundtrip(self, header: dict, timeout: float):
        from spark_rapids_tpu.shuffle import fault_injection

        with self._lock:
            injector = fault_injection.get_injector()
            if injector.should_partition_dcn():
                self._drop()
                raise TransportError(
                    f"transport to {self._addr} failed: injected DCN "
                    f"partition (inter-host link down)")
            if injector.should_drop():
                self._drop()
                raise TransportError(
                    f"transport to {self._addr} failed: injected "
                    f"connection drop")
            sock = self._ensure(timeout)
            try:
                _send_frame(sock, header)
                resp, payload = _recv_frame(sock)
            except (ConnectionError, OSError, socket.timeout) as e:
                self._drop()
                raise TransportError(
                    f"transport to {self._addr} failed: {e}")
        if not resp.get("ok"):
            # peer answered with a semantic error: retrying would just
            # re-ask the same question
            raise TransportError(resp.get("error", "unknown peer error"),
                                 retryable=False)
        return resp, payload

    def _roundtrip_retrying(self, header: dict, timeout: float):
        """``_roundtrip`` with bounded exponential backoff on transient
        TransportError. The total wall time (tries + sleeps) is capped
        at the caller's ``timeout`` — a hiccuping peer costs backoff,
        never more than the budget the caller already signed up for.
        Each sleep carries uniform jitter (shuffle.retry.jitterMs) so
        the fan-in after a DCN blip — every surviving host re-knocking
        on the same peer — de-synchronizes instead of stampeding."""
        deadline = time.monotonic() + timeout
        backoff = self._RETRY_BASE_S
        attempt = 0
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TransportError(
                    f"transport to {self._addr} timed out after "
                    f"{attempt} attempts within {timeout}s")
            try:
                return self._roundtrip(header, remaining)
            except TransportError as e:
                attempt += 1
                remaining = deadline - time.monotonic()
                if not getattr(e, "retryable", True) or \
                        attempt > self._max_retries or \
                        remaining <= backoff:
                    raise
                # the failed roundtrip dropped the socket; the sleep
                # then _ensure() is the backoff + reconnect
                sleep = backoff
                if self._jitter_s:
                    sleep += random.uniform(0.0, self._jitter_s)
                time.sleep(min(sleep, remaining))
                backoff *= 2

    def _drop(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # -- Connection API ----------------------------------------------------

    def request_metadata(self, blocks: List[BlockId], timeout: float = 30.0
                         ) -> List[ShuffleTableMeta]:
        resp, _ = self._roundtrip_retrying(
            {"op": "metadata",
             "blocks": [_block_to_wire(b) for b in blocks]}, timeout)
        return [ShuffleTableMeta.from_json(m) for m in resp["metas"]]

    def request_chunk(self, block: BlockId, offset: int, length: int,
                      timeout: float = 30.0) -> bytes:
        from spark_rapids_tpu.shuffle import fault_injection

        _, payload = self._roundtrip_retrying(
            {"op": "chunk", "block": _block_to_wire(block),
             "offset": offset, "length": length}, timeout)
        # injected truncation sits ABOVE the retry loop on purpose: the
        # client's short-chunk check then escalates straight to a fetch
        # failure, the same path a mid-transfer peer crash takes
        return fault_injection.get_injector().maybe_truncate(payload)

    def release(self, block: BlockId) -> None:
        try:
            self._roundtrip({"op": "release",
                             "block": _block_to_wire(block)}, 30.0)
        except TransportError:
            pass  # best-effort: server GC also drops payload caches

    def close(self):
        with self._lock:
            self._drop()


class TcpTransport:
    """Endpoint registry over real sockets (UCXShuffleTransport's role:
    management-port bootstrap + per-peer endpoint table)."""

    def __init__(self):
        self._servers: Dict[str, TcpShuffleServer] = {}
        self._addrs: Dict[str, tuple] = {}
        self._lock = lockorder.make_lock("shuffle.tcp.registry")

    def register(self, server: ShuffleServer, host: str = "127.0.0.1",
                 port: int = 0) -> TcpShuffleServer:
        ts = TcpShuffleServer(server, host, port)
        with self._lock:
            self._servers[server.executor_id] = ts
            self._addrs[server.executor_id] = ts.address
        return ts

    def register_remote(self, executor_id: str, host: str,
                        port: int) -> None:
        """Record a peer served by ANOTHER process (the map-status
        topology info)."""
        with self._lock:
            self._addrs[executor_id] = (host, port)

    def connect(self, peer_executor_id: str) -> TcpConnection:
        with self._lock:
            addr = self._addrs.get(peer_executor_id)
        if addr is None:
            raise TransportError(f"no endpoint for {peer_executor_id}")
        return TcpConnection(*addr)

    def shutdown(self):
        with self._lock:
            for s in self._servers.values():
                s.close()
            self._servers.clear()
            self._addrs.clear()
