"""In-process multi-executor shuffle runtime.

Ties the pieces together the way a Spark cluster does for the reference:
each executor owns a spill BufferCatalog + ShuffleBufferCatalog + server
endpoint; a map-output tracker records which executor holds each map
task's output (the MapStatus registration,
RapidsShuffleInternalManager.scala:164-191); reduce-side reads go through
ShuffleIterator (local hits + transport fetches). This is the control
plane a real multi-host deployment keeps, with LocalTransport swapped for
a DCN-backed transport."""
from __future__ import annotations

import threading
from spark_rapids_tpu.utils import lockorder
from typing import Dict, Iterator, List, Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.memory.catalog import BufferCatalog
from spark_rapids_tpu.shuffle.catalog import ShuffleBufferCatalog
from spark_rapids_tpu.shuffle.iterator import ShuffleIterator
from spark_rapids_tpu.shuffle.meta import BlockId
from spark_rapids_tpu.shuffle.transport import (DEFAULT_BOUNCE_SIZE,
                                                DEFAULT_MAX_INFLIGHT,
                                                LocalTransport,
                                                ShuffleClient,
                                                ShuffleServer)


class Executor:
    def __init__(self, executor_id: str,
                 device_budget: Optional[int] = None,
                 host_budget: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 codec: str = "lz4"):
        self.executor_id = executor_id
        self.buffer_catalog = BufferCatalog(device_budget=device_budget,
                                            host_budget=host_budget,
                                            spill_dir=spill_dir)
        self.shuffle_catalog = ShuffleBufferCatalog(self.buffer_catalog,
                                                    codec=codec)
        self.server = ShuffleServer(executor_id, self.shuffle_catalog)


class LocalCluster:
    """N executors + transport + map-output tracker.

    ``transport="local"`` serves peers through in-process endpoints (the
    mocked-transport testing mode, SURVEY §4); ``transport="tcp"`` binds
    every executor's server to a real listening socket
    (shuffle/tcp.py) — the same client/protocol stack then runs over the
    wire, and executors served by OTHER PROCESSES can join via
    ``register_remote_executor`` (the reference's UCX transport wired
    into its shuffle manager, RapidsShuffleInternalManager.scala:200-305,
    with the TCP management-port bootstrap of UCX.scala:70-155)."""

    def __init__(self, n_executors: int,
                 device_budget: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 codec: str = "lz4",
                 bounce_size: int = DEFAULT_BOUNCE_SIZE,
                 max_inflight: int = DEFAULT_MAX_INFLIGHT,
                 transport: str = "local"):
        if transport == "tcp":
            from spark_rapids_tpu.shuffle.tcp import TcpTransport

            self.transport = TcpTransport()
        else:
            self.transport = LocalTransport()
        self.executors: List[Executor] = []
        self.bounce_size = bounce_size
        self.max_inflight = max_inflight
        for i in range(n_executors):
            ex = Executor(
                f"exec-{i}", device_budget=device_budget,
                spill_dir=None if spill_dir is None
                else f"{spill_dir}/exec-{i}",
                codec=codec)
            self.executors.append(ex)
            self.transport.register(ex.server)
        # shuffle_id -> map_id -> executor_id (MapOutputTracker)
        self._map_outputs: Dict[int, Dict[int, str]] = {}
        self._lock = lockorder.make_lock("shuffle.cluster.state")
        self._clients: Dict[tuple, ShuffleClient] = {}

    def executor(self, i: int) -> Executor:
        return self.executors[i]

    # -- map side ---------------------------------------------------------

    def write_map_output(self, shuffle_id: int, map_id: int,
                         executor_index: int,
                         partition_batches: Dict[int, ColumnarBatch]
                         ) -> None:
        """One map task's partitioned output lands in its executor's cache
        (RapidsCachingWriter.write + MapStatus registration)."""
        ex = self.executors[executor_index]
        for partition, batch in partition_batches.items():
            ex.shuffle_catalog.register(
                BlockId(shuffle_id, map_id, partition), batch)
        with self._lock:
            # MapStatus: executor + this map's {partition: byte size}.
            # Reads trust THIS record — a tracked block the owner lost is
            # a fetch failure, never a silent skip. Sizes feed AQE's
            # coalesced reads (Spark's MapStatus carries them the same
            # way, GpuShuffleExchangeExec.scala:95-101 map stats future).
            self._map_outputs.setdefault(shuffle_id, {})[map_id] = (
                ex.executor_id,
                {p: b.device_memory_size()
                 for p, b in partition_batches.items()})

    # -- cross-process peers (tcp transport only) -------------------------

    def register_remote_executor(self, executor_id: str, host: str,
                                 port: int) -> None:
        """Record a peer executor served by another OS process (its
        address is the map-status topology info the reference encodes in
        BlockManagerId, RapidsShuffleInternalManager.scala:171-183)."""
        self.transport.register_remote(executor_id, host, port)

    def register_remote_map_output(self, shuffle_id: int, map_id: int,
                                   executor_id: str,
                                   partitions) -> None:
        """MapStatus entry for a map task whose output lives on a remote
        (cross-process) executor. ``partitions``: {partition: bytes}
        (a bare iterable of ids is accepted with unknown sizes)."""
        if not isinstance(partitions, dict):
            partitions = {int(p): 0 for p in partitions}
        else:
            partitions = {int(p): int(s) for p, s in partitions.items()}
        with self._lock:
            self._map_outputs.setdefault(shuffle_id, {})[map_id] = (
                executor_id, partitions)

    # -- reduce side ------------------------------------------------------

    def _client(self, from_executor: str, to_executor: str
                ) -> ShuffleClient:
        key = (from_executor, to_executor)
        with self._lock:
            c = self._clients.get(key)
            if c is None:
                c = ShuffleClient(self.transport.connect(to_executor),
                                  bounce_size=self.bounce_size,
                                  max_inflight=self.max_inflight)
                self._clients[key] = c
            return c

    def evict_client(self, from_executor: str, to_executor: str) -> None:
        """Drop a cached peer client after a fetch error: the broken
        socket must not outlive the failure, or every later fetch to a
        RESTARTED peer (new port, re-registered address) keeps failing
        on the stale connection for the rest of the process lifetime."""
        with self._lock:
            c = self._clients.pop((from_executor, to_executor), None)
        if c is not None:
            close = getattr(c.conn, "close", None)
            if close is not None:
                try:
                    close()
                except OSError:
                    pass

    def read_partition(self, shuffle_id: int, partition: int,
                       reader_executor_index: int
                       ) -> Iterator[ColumnarBatch]:
        """All batches of one reduce partition, read from the reader
        executor's perspective."""
        with self._lock:
            maps = dict(self._map_outputs.get(shuffle_id, {}))
        reader = self.executors[reader_executor_index]
        locations = {}
        for map_id, (executor_id, partitions) in maps.items():
            if partition in partitions:
                locations[BlockId(shuffle_id, map_id, partition)] = \
                    executor_id
        it = ShuffleIterator(
            reader.shuffle_catalog, reader.executor_id, locations,
            lambda peer: self._client(reader.executor_id, peer),
            on_fetch_error=lambda peer: self.evict_client(
                reader.executor_id, peer))
        self.last_iterator = it  # for metric assertions in tests
        return iter(it)

    def unregister_shuffle(self, shuffle_id: int) -> None:
        for ex in self.executors:
            ex.shuffle_catalog.unregister_shuffle(shuffle_id)
        with self._lock:
            self._map_outputs.pop(shuffle_id, None)

    # -- failure recovery (SURVEY §5.3: Spark lineage/task-retry model) --

    def lose_executor(self, executor_index: int) -> None:
        """Simulate executor loss: its cached shuffle blocks are gone
        (the catalog empties) but the tracker still references it until
        invalidation — exactly the state that produces fetch failures."""
        ex = self.executors[executor_index]
        with ex.shuffle_catalog._lock:
            shuffles = {b.shuffle_id
                        for b in ex.shuffle_catalog._metas}
        for sid in shuffles:
            ex.shuffle_catalog.unregister_shuffle(sid)

    def invalidate_map_output(self, shuffle_id: int,
                              executor_id: str) -> List[int]:
        """Drop tracker entries pointing at a failed executor; returns
        the map ids that must re-run (Spark's fetch-failure handling
        unregisters the executor's outputs and reschedules those tasks)."""
        with self._lock:
            maps = self._map_outputs.get(shuffle_id, {})
            lost = [mid for mid, (eid, _) in maps.items()
                    if eid == executor_id]
            for mid in lost:
                del maps[mid]
        return lost

    def shutdown(self):
        self.transport.shutdown()
