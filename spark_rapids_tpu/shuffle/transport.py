"""Transport-agnostic shuffle client/server protocol.

Mirrors the reference's layering (RapidsShuffleTransport.scala:38-280):

- control plane: ``MetadataRequest`` -> exact per-block ``TableMeta``s
  (payload sizes realized by serializing, like JCudfSerialization sizes in
  the reference's metadata response),
- data plane: tag-addressed windowed chunk transfers sized to bounce
  buffers, client-driven, throttled by inflight bytes
  (BufferReceiveState / WindowedBlockIterator analogues),
- a single progress thread per server endpoint (UCX.scala:70-155 runs all
  UCX work on one progress thread for lock-freedom; LocalTransport does
  the same with a request queue),
- fault-injection hooks so error paths are testable without a cluster
  (the RapidsShuffleClientSuite mocked-transport strategy, SURVEY.md §4).

The bulk path between same-slice chips does NOT go through here — that is
the fused mesh all_to_all (parallel/shuffle.py). This transport is the
DCN/host path and the protocol reference for a future multi-host backend.
"""
from __future__ import annotations

import queue
import threading
from spark_rapids_tpu.utils import lockorder
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Tuple

from spark_rapids_tpu.shuffle.catalog import ShuffleBufferCatalog
from spark_rapids_tpu.shuffle.meta import BlockId, ShuffleTableMeta
from spark_rapids_tpu.utils.tracing import TraceRange

DEFAULT_BOUNCE_SIZE = 4 << 20       # bounce-buffer length (4 MiB)
DEFAULT_MAX_INFLIGHT = 1 << 30      # inflight receive bytes throttle


class TransportError(RuntimeError):
    """A shuffle transport request failed. ``retryable`` separates
    transient transport faults (socket drop, timeout — safe to retry:
    metadata/chunk reads are idempotent) from peer-reported semantic
    errors (unknown block, server exception) where a retry would just
    repeat the same answer."""

    def __init__(self, msg: str = "", retryable: bool = True):
        super().__init__(msg)
        self.retryable = retryable


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class ShuffleServer:
    """Serves one executor's shuffle catalog (RapidsShuffleServer:671).

    Payloads are serialized at metadata time (realizing exact wire sizes
    for the response) and cached until the client releases the block, so
    windowed chunk requests never re-serialize."""

    def __init__(self, executor_id: str, catalog: ShuffleBufferCatalog):
        self.executor_id = executor_id
        self.catalog = catalog
        self._payloads: Dict[BlockId, bytes] = {}
        self._lock = lockorder.make_lock("shuffle.transport.store")
        # fault-injection hooks (tests): raise/mutate per request
        self.on_metadata: Optional[Callable] = None
        self.on_chunk: Optional[Callable] = None

    def handle_metadata(self, blocks: List[BlockId]
                        ) -> List[ShuffleTableMeta]:
        if self.on_metadata is not None:
            self.on_metadata(blocks)
        out = []
        for b in blocks:
            meta = self.catalog.meta(b)
            if meta is None:
                raise TransportError(
                    f"{self.executor_id}: block {b} not found")
            if meta.num_rows > 0:
                with self._lock:
                    payload = self._payloads.get(b)
                if payload is None:
                    payload = self.catalog.serialize_payload(b)
                    with self._lock:
                        self._payloads[b] = payload
                meta = ShuffleTableMeta(meta.block, meta.num_rows,
                                        len(payload), meta.dtype_names)
            out.append(meta)
        return out

    def handle_chunk(self, block: BlockId, offset: int,
                     length: int) -> bytes:
        if self.on_chunk is not None:
            self.on_chunk(block, offset, length)
        with self._lock:
            payload = self._payloads.get(block)
        if payload is None:
            # metadata not requested first, or already released
            payload = self.catalog.serialize_payload(block)
            with self._lock:
                self._payloads[block] = payload
        if offset >= len(payload):
            raise TransportError(
                f"chunk out of range: {block} @{offset}")
        return payload[offset:offset + length]

    def handle_release(self, block: BlockId) -> None:
        with self._lock:
            self._payloads.pop(block, None)


# ---------------------------------------------------------------------------
# In-process transport (the UCX impl analogue)
# ---------------------------------------------------------------------------


class _Request:
    __slots__ = ("kind", "args", "future")

    def __init__(self, kind: str, args: tuple):
        self.kind = kind
        self.args = args
        self.future: Future = Future()


class _Endpoint:
    """One executor's server endpoint: a request queue drained by a single
    progress thread (the UCX progress-thread model, UCX.scala:80-97)."""

    def __init__(self, server: ShuffleServer):
        self.server = server
        self.q: "queue.Queue[Optional[_Request]]" = queue.Queue()
        self.thread = threading.Thread(
            target=self._progress, name=f"shuffle-{server.executor_id}",
            daemon=True)
        self.thread.start()

    def _progress(self):
        while True:
            req = self.q.get()
            if req is None:
                return
            try:
                if req.kind == "metadata":
                    req.future.set_result(
                        self.server.handle_metadata(*req.args))
                elif req.kind == "chunk":
                    req.future.set_result(
                        self.server.handle_chunk(*req.args))
                elif req.kind == "release":
                    self.server.handle_release(*req.args)
                    req.future.set_result(None)
                else:  # pragma: no cover
                    raise TransportError(f"bad request {req.kind}")
            except BaseException as e:
                req.future.set_exception(e)

    def submit(self, kind: str, *args) -> Future:
        r = _Request(kind, args)
        self.q.put(r)
        return r.future

    def shutdown(self):
        self.q.put(None)


class Connection:
    """Client view of a peer (RapidsShuffleTransport connection traits)."""

    def request_metadata(self, blocks: List[BlockId], timeout: float
                         ) -> List[ShuffleTableMeta]:
        raise NotImplementedError

    def request_chunk(self, block: BlockId, offset: int, length: int,
                      timeout: float) -> bytes:
        raise NotImplementedError

    def release(self, block: BlockId) -> None:
        raise NotImplementedError


class LocalConnection(Connection):
    def __init__(self, endpoint: _Endpoint):
        self._ep = endpoint

    def request_metadata(self, blocks, timeout=30.0):
        return self._ep.submit("metadata", blocks).result(timeout)

    def request_chunk(self, block, offset, length, timeout=30.0):
        return self._ep.submit("chunk", block, offset, length
                               ).result(timeout)

    def release(self, block):
        self._ep.submit("release", block)


class LocalTransport:
    """In-process executor registry: the management-port/endpoint-map role
    of UCXShuffleTransport (TCP bootstrap + endpoint table)."""

    def __init__(self):
        self._endpoints: Dict[str, _Endpoint] = {}
        self._lock = lockorder.make_lock("shuffle.transport.endpoints")

    def register(self, server: ShuffleServer) -> None:
        with self._lock:
            self._endpoints[server.executor_id] = _Endpoint(server)

    def connect(self, peer_executor_id: str) -> Connection:
        with self._lock:
            ep = self._endpoints.get(peer_executor_id)
        if ep is None:
            raise TransportError(f"no endpoint for {peer_executor_id}")
        return LocalConnection(ep)

    def shutdown(self):
        with self._lock:
            for ep in self._endpoints.values():
                ep.shutdown()
            self._endpoints.clear()


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class _InflightThrottle:
    """Blocks fetches while inflight receive bytes exceed the budget
    (RapidsConf maxReceiveInflightBytes, RapidsConf.scala:603-685)."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._inflight = 0
        self._cv = lockorder.make_condition("shuffle.transport.throttle")
        self.peak = 0  # observability

    def acquire(self, n: int) -> None:
        with self._cv:
            while self._inflight > 0 and \
                    self._inflight + n > self.max_bytes:
                self._cv.wait()
            self._inflight += n
            self.peak = max(self.peak, self._inflight)

    def release(self, n: int) -> None:
        with self._cv:
            self._inflight -= n
            self._cv.notify_all()


class BounceBufferPool:
    """Fixed-count pool of receive windows carved from ONE root buffer
    by an address-space sub-allocator (BounceBufferManager +
    AddressSpaceAllocator analogues: the reference registers a single
    allocation with UCX and sub-allocates bounce buffers from it). A
    window must be borrowed for every in-flight chunk, so chunk
    concurrency is bounded like the registered bounce buffers."""

    def __init__(self, count: int, size: int):
        from spark_rapids_tpu.memory.address_space import \
            AddressSpaceAllocator

        self.size = size
        self._root = bytearray(count * size)
        self._alloc = AddressSpaceAllocator(count * size)
        self._sem = threading.Semaphore(count)

    def borrow(self):
        self._sem.acquire()
        off = self._alloc.allocate(self.size)
        assert off is not None  # semaphore bounds outstanding windows
        return _BounceWindow(self, off)

    def give_back(self, window: "_BounceWindow") -> None:
        self._alloc.free(window.offset)
        self._sem.release()


class _BounceWindow:
    """A borrowed slice of the pool's root buffer."""

    __slots__ = ("offset", "view")

    def __init__(self, pool: BounceBufferPool, offset: int):
        self.offset = offset
        self.view = memoryview(pool._root)[offset:offset + pool.size]

    def __getitem__(self, s):
        return self.view[s]

    def __setitem__(self, s, value):
        self.view[s] = value


class ShuffleClient:
    """Fetches remote blocks: metadata exchange then windowed chunk
    transfers (the doFetch flow, RapidsShuffleClient.scala:480-610)."""

    def __init__(self, connection: Connection,
                 bounce_size: int = DEFAULT_BOUNCE_SIZE,
                 max_inflight: int = DEFAULT_MAX_INFLIGHT,
                 bounce_count: int = 8):
        self.conn = connection
        self.bounce_size = bounce_size
        self.throttle = _InflightThrottle(max_inflight)
        self.pool = BounceBufferPool(bounce_count, bounce_size)

    def fetch(self, blocks: List[BlockId], timeout: float = 30.0
              ) -> List[Tuple[ShuffleTableMeta, Optional[bytes]]]:
        """Returns (meta, payload|None) per block; None payload for
        degenerate rows-only blocks."""
        with TraceRange("ShuffleClient.metadata"):
            metas = self.conn.request_metadata(blocks, timeout)
        out: List[Tuple[ShuffleTableMeta, Optional[bytes]]] = []
        for meta in metas:
            if meta.num_rows == 0 or meta.payload_len == 0:
                out.append((meta, None))
                continue
            payload = self._fetch_payload(meta, timeout)
            out.append((meta, payload))
            self.conn.release(meta.block)
        return out

    def _fetch_payload(self, meta: ShuffleTableMeta,
                       timeout: float) -> bytes:
        """Windowed transfer of one block (BufferReceiveState windows,
        RapidsShuffleClient.scala:108-343)."""
        buf = bytearray(meta.payload_len)
        offset = 0
        while offset < meta.payload_len:
            length = min(self.bounce_size, meta.payload_len - offset)
            self.throttle.acquire(length)
            window = self.pool.borrow()
            try:
                with TraceRange("ShuffleClient.chunk"):
                    chunk = self.conn.request_chunk(
                        meta.block, offset, length, timeout)
                if len(chunk) != length:
                    raise TransportError(
                        f"short chunk for {meta.block}: "
                        f"{len(chunk)} != {length}")
                window[:length] = chunk          # recv into bounce buffer
                buf[offset:offset + length] = window[:length]
            finally:
                self.pool.give_back(window)
                self.throttle.release(length)
            offset += length
        return bytes(buf)
