"""Shuffle subsystem: device-resident partition cache + peer transport.

The reference's accelerated shuffle (SURVEY.md §2.8) is a GPU-side block
cache (RapidsCachingWriter/Reader) over a spillable buffer catalog, plus a
transport that moves blocks between executors with a metadata exchange
followed by tag-addressed windowed bulk transfers over UCX.

The TPU build keeps that architecture for the host/DCN path — metadata
exchange, windowed transfers with an inflight throttle, spillable shuffle
catalog, map-output tracking, fetch-failure semantics — while the
same-slice bulk path is the fused mesh ``all_to_all`` program in
parallel/shuffle.py (ICI replaces RDMA; XLA replaces the progress thread).
"""
from spark_rapids_tpu.shuffle.catalog import ShuffleBufferCatalog
from spark_rapids_tpu.shuffle.cluster import LocalCluster
from spark_rapids_tpu.shuffle.iterator import (ShuffleFetchFailedError,
                                               ShuffleIterator)
from spark_rapids_tpu.shuffle.meta import BlockId, ShuffleTableMeta
from spark_rapids_tpu.shuffle.transport import (LocalTransport,
                                                ShuffleClient,
                                                ShuffleServer)

__all__ = ["ShuffleBufferCatalog", "LocalCluster", "ShuffleIterator",
           "ShuffleFetchFailedError", "BlockId", "ShuffleTableMeta",
           "LocalTransport", "ShuffleClient", "ShuffleServer"]
