"""Shuffle block identity and table metadata.

The reference describes serialized tables with FlatBuffers ``TableMeta``
(format/TableMeta.java:59, built by MetaUtils.scala:144) keyed by Spark
ShuffleBlockIds. Here the metadata is a plain dataclass (it crosses the
wire as JSON inside the metadata response — the control plane is tiny
compared to payloads, exactly why the reference splits metadata from bulk
transfer)."""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True, order=True)
class BlockId:
    """(shuffle, map task, reduce partition) — ShuffleBlockId analogue."""

    shuffle_id: int
    map_id: int
    partition: int

    def __str__(self) -> str:
        return f"shuffle_{self.shuffle_id}_{self.map_id}_{self.partition}"


@dataclasses.dataclass(frozen=True)
class ShuffleTableMeta:
    """Describes one cached shuffle block (TableMeta analogue).

    ``payload_len`` is the enveloped wire size the receiver must budget
    for (the inflight throttle counts these bytes); ``num_rows`` lets
    degenerate rows-only batches skip the bulk transfer entirely
    (MetaUtils.scala:144 degenerate-batch path)."""

    block: BlockId
    num_rows: int
    payload_len: int
    dtype_names: Tuple[str, ...]

    def to_json(self) -> dict:
        return {"shuffle_id": self.block.shuffle_id,
                "map_id": self.block.map_id,
                "partition": self.block.partition,
                "num_rows": self.num_rows,
                "payload_len": self.payload_len,
                "dtypes": list(self.dtype_names)}

    @staticmethod
    def from_json(d: dict) -> "ShuffleTableMeta":
        return ShuffleTableMeta(
            BlockId(d["shuffle_id"], d["map_id"], d["partition"]),
            d["num_rows"], d["payload_len"], tuple(d["dtypes"]))
