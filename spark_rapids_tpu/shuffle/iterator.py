"""Task-facing shuffle read iterator (RapidsShuffleIterator:363 +
RapidsCachingReader.scala:59-166).

Given the blocks a reduce task needs, partitions them into local catalog
hits (zero-copy device reads, possibly unspilled) and per-peer remote
fetches; transport errors surface as ``ShuffleFetchFailedError`` naming
EVERY failed block — the reference converts these into Spark
fetch-failures so the stage retries
(RapidsShuffleIterator.scala:242-300)."""
from __future__ import annotations

from typing import Callable, Dict, Iterator, List, Optional, Sequence, Union

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.shuffle.catalog import ShuffleBufferCatalog
from spark_rapids_tpu.shuffle.meta import BlockId
from spark_rapids_tpu.shuffle.transport import ShuffleClient, TransportError


class ShuffleFetchFailedError(RuntimeError):
    """A reduce read lost block(s) to a failed peer or a corrupt frame.

    ``blocks`` is the FULL list that failed with this peer (recovery
    invalidates exactly the lost maps; logging names every missing
    block), ``block`` its first entry for single-block call sites, and
    ``batches_yielded`` how many batches the iterator had already
    produced — the stage-retry barrier uses it to confirm no partial
    progress leaks past a restart."""

    def __init__(self, blocks: Union[BlockId, Sequence[BlockId]],
                 executor_id: str, cause, batches_yielded: int = 0):
        blocks = [blocks] if isinstance(blocks, BlockId) else list(blocks)
        assert blocks, "a fetch failure names at least one block"
        named = ", ".join(str(b) for b in blocks)
        super().__init__(
            f"fetch failed for {len(blocks)} block(s) [{named}] from "
            f"executor {executor_id} after {batches_yielded} yielded "
            f"batch(es): {cause}")
        self.block = blocks[0]
        self.blocks = blocks
        self.executor_id = executor_id
        self.cause = cause
        self.batches_yielded = batches_yielded


class ShuffleIterator:
    """Yields the batches of one reduce partition.

    ``block_locations`` maps each wanted block to the executor that holds
    it (the MapStatus/MapOutputTracker answer); ``client_for`` lazily
    opens a transport client per peer; ``on_fetch_error`` (optional) is
    told the peer whose fetch failed BEFORE the fetch failure raises, so
    the owner of a per-peer client cache can evict the broken connection
    (a restarted peer is then reachable on the next attempt instead of
    failing on a stale socket forever)."""

    def __init__(self, local_catalog: ShuffleBufferCatalog,
                 local_executor_id: str,
                 block_locations: Dict[BlockId, str],
                 client_for: Callable[[str], ShuffleClient],
                 on_fetch_error: Optional[Callable[[str], None]] = None):
        self.local_catalog = local_catalog
        self.local_executor_id = local_executor_id
        self.block_locations = block_locations
        self.client_for = client_for
        self.on_fetch_error = on_fetch_error
        self.local_blocks_read = 0
        self.remote_blocks_read = 0
        self.remote_bytes_read = 0
        self.batches_yielded = 0

    def seam_stats(self) -> Dict[str, int]:
        """This read's traffic split by seam class (the multi-host
        topology vocabulary, parallel/mesh.HostTopology): local catalog
        hits never left the host ("ici" side of the seam), remote
        fetches crossed the DCN over transport."""
        return {"ici_local_blocks": self.local_blocks_read,
                "dcn_remote_blocks": self.remote_blocks_read,
                "dcn_remote_bytes": self.remote_bytes_read}

    def _failed(self, blocks, executor: str, cause
                ) -> ShuffleFetchFailedError:
        if self.on_fetch_error is not None and \
                executor != self.local_executor_id:
            self.on_fetch_error(executor)
        return ShuffleFetchFailedError(blocks, executor, cause,
                                       self.batches_yielded)

    def __iter__(self) -> Iterator[ColumnarBatch]:
        local: List[BlockId] = []
        by_peer: Dict[str, List[BlockId]] = {}
        for block, executor in sorted(self.block_locations.items()):
            if executor == self.local_executor_id:
                local.append(block)
            else:
                by_peer.setdefault(executor, []).append(block)
        # local hits first (RapidsCachingReader serves catalog hits
        # before starting transport fetches)
        for block in local:
            meta = self.local_catalog.meta(block)
            if meta is None:
                # the tracked-block-lost-by-owner contract
                # (shuffle/cluster.py write_map_output): a block the
                # tracker promised is a fetch failure, never a skip
                raise self._failed([block], self.local_executor_id,
                                   "missing local block")
            self.local_blocks_read += 1
            if meta.num_rows == 0:
                continue
            ctx = self.local_catalog.acquire_batch(block)
            with ctx as batch:
                self.batches_yielded += 1
                yield batch
        for executor, blocks in sorted(by_peer.items()):
            client = self.client_for(executor)
            try:
                results = client.fetch(blocks)
            except (TransportError, TimeoutError, KeyError) as e:
                raise self._failed(blocks, executor, e)
            for meta, payload in results:
                self.remote_blocks_read += 1
                if payload is None:
                    continue
                self.remote_bytes_read += len(payload)
                try:
                    batch = self.local_catalog.deserialize_payload(payload)
                except ValueError as e:  # checksum/corruption
                    raise self._failed([meta.block], executor, e)
                self.batches_yielded += 1
                yield batch
