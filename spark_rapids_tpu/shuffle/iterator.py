"""Task-facing shuffle read iterator (RapidsShuffleIterator:363 +
RapidsCachingReader.scala:59-166).

Given the blocks a reduce task needs, partitions them into local catalog
hits (zero-copy device reads, possibly unspilled) and per-peer remote
fetches; transport errors surface as ``ShuffleFetchFailedError`` naming
the failed block — the reference converts these into Spark fetch-failures
so the stage retries (RapidsShuffleIterator.scala:242-300)."""
from __future__ import annotations

from typing import Callable, Dict, Iterator, List, Optional, Tuple

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.shuffle.catalog import ShuffleBufferCatalog
from spark_rapids_tpu.shuffle.meta import BlockId
from spark_rapids_tpu.shuffle.transport import ShuffleClient, TransportError


class ShuffleFetchFailedError(RuntimeError):
    def __init__(self, block: BlockId, executor_id: str, cause):
        super().__init__(
            f"fetch failed for {block} from executor {executor_id}: "
            f"{cause}")
        self.block = block
        self.executor_id = executor_id
        self.cause = cause


class ShuffleIterator:
    """Yields the batches of one reduce partition.

    ``block_locations`` maps each wanted block to the executor that holds
    it (the MapStatus/MapOutputTracker answer); ``client_for`` lazily
    opens a transport client per peer."""

    def __init__(self, local_catalog: ShuffleBufferCatalog,
                 local_executor_id: str,
                 block_locations: Dict[BlockId, str],
                 client_for: Callable[[str], ShuffleClient]):
        self.local_catalog = local_catalog
        self.local_executor_id = local_executor_id
        self.block_locations = block_locations
        self.client_for = client_for
        self.local_blocks_read = 0
        self.remote_blocks_read = 0
        self.remote_bytes_read = 0

    def __iter__(self) -> Iterator[ColumnarBatch]:
        local: List[BlockId] = []
        by_peer: Dict[str, List[BlockId]] = {}
        for block, executor in sorted(self.block_locations.items()):
            if executor == self.local_executor_id:
                local.append(block)
            else:
                by_peer.setdefault(executor, []).append(block)
        # local hits first (RapidsCachingReader serves catalog hits
        # before starting transport fetches)
        for block in local:
            meta = self.local_catalog.meta(block)
            if meta is None:
                raise ShuffleFetchFailedError(
                    block, self.local_executor_id, "missing local block")
            self.local_blocks_read += 1
            if meta.num_rows == 0:
                continue
            ctx = self.local_catalog.acquire_batch(block)
            with ctx as batch:
                yield batch
        for executor, blocks in sorted(by_peer.items()):
            client = self.client_for(executor)
            try:
                results = client.fetch(blocks)
            except (TransportError, TimeoutError, KeyError) as e:
                raise ShuffleFetchFailedError(blocks[0], executor, e)
            for meta, payload in results:
                self.remote_blocks_read += 1
                if payload is None:
                    continue
                self.remote_bytes_read += len(payload)
                try:
                    yield self.local_catalog.deserialize_payload(payload)
                except ValueError as e:  # checksum/corruption
                    raise ShuffleFetchFailedError(meta.block, executor, e)
