"""Standalone shuffle-server process: one executor's catalog over TCP.

The reference's shuffle peers are separate executor JVMs, each serving
its cached blocks through the UCX transport
(RapidsShuffleInternalManager.scala:249-269, UCX.scala:70-155). This
module is the process entry point for the TPU build's equivalent: spawn
``python -m spark_rapids_tpu.shuffle.remote_worker`` with a JSON config
on stdin and it

1. builds an executor (BufferCatalog + ShuffleBufferCatalog),
2. registers the configured deterministic blocks (a map task's output),
3. serves them over a real listening socket (shuffle/tcp.py),
4. prints ``READY <host> <port>`` on stdout,
5. exits when stdin closes (parent-death binding, like Spark executor
   processes dying with their worker).

Config JSON::

    {"executor_id": "exec-remote",
     "blocks": [[shuffle_id, map_id, partition, lo, n], ...],
     "hangup_after_chunks": -1}   # >=0: raise Hangup after N chunk reqs

Blocks hold ``int64 arange(lo, lo+n)`` with every ``v % 7 == 3`` row
null — the same deterministic recipe the in-process shuffle tests use,
so both processes can compute the expected result independently.
"""
from __future__ import annotations

import json
import sys


def make_block_batch(lo: int, n: int):
    """Deterministic batch: int64 arange(lo, lo+n), v%7==3 -> null."""
    import numpy as np

    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.column import Column

    vals = np.arange(lo, lo + n, dtype=np.int64)
    valid = (vals % 7) != 3
    return ColumnarBatch(
        [Column.from_numpy(vals, dtype=dt.INT64, validity=valid)], n)


def run_task_loop(ex, ts) -> None:
    """Task-server mode: the worker EXECUTES map tasks shipped as pickled
    closures (the cluster runtime's remote executors — Spark's
    serialized-lineage model), registers the partitioned output in its
    own catalog, and serves it through the already-listening TCP server.
    Nested shuffle reads in the closure fetch from peer executors via
    this process's own transport client (ExecutorContext)."""
    import base64
    import pickle
    import traceback

    from spark_rapids_tpu.runtime.cluster import (ExecutorContext,
                                                  run_map_partitions,
                                                  set_executor_context)
    from spark_rapids_tpu.shuffle.meta import BlockId
    from spark_rapids_tpu.shuffle.tcp import TcpTransport

    transport = TcpTransport()
    set_executor_context(ExecutorContext(ex, transport))
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        cmd = json.loads(line)
        if cmd.get("cmd") == "exit":
            break
        try:
            assert cmd.get("cmd") == "run_map", cmd
            payload = pickle.loads(
                base64.b64decode(cmd["payload_b64"]))
            for eid, addr in payload["addresses"].items():
                if eid != ex.executor_id:
                    transport.register_remote(eid, *addr)
            subtree = payload["subtree"]
            if payload.get("mode") == "sample":
                # range-bounds sampling pass: run the subtree, return a
                # host row sample (the driver aggregates into bounds)
                from spark_rapids_tpu.runtime.cluster import \
                    sample_rows_host

                sample = sample_rows_host(
                    subtree.execute(payload["map_id"]),
                    subtree.schema, payload["sample_rows"])
                print(json.dumps({
                    "ok": True, "map_id": payload["map_id"],
                    "sample_b64": base64.b64encode(
                        pickle.dumps(sample)).decode()}), flush=True)
                continue
            parts = run_map_partitions(
                subtree.execute(payload["map_id"]),
                payload["partitioning"], payload["types"],
                payload["num_out"])
            for p, batch in parts.items():
                ex.shuffle_catalog.register(
                    BlockId(payload["shuffle_id"], payload["map_id"], p),
                    batch)
            print(json.dumps({"ok": True,
                              "map_id": payload["map_id"],
                              # MapStatus sizes ride back with the ids
                              # (AQE coalesced reads need them)
                              "partitions": {
                                  str(p): b.device_memory_size()
                                  for p, b in parts.items()}}),
                  flush=True)
        except Exception:
            print(json.dumps({"ok": False,
                              "error": traceback.format_exc()}),
                  flush=True)


def main() -> None:
    import os

    import spark_rapids_tpu  # noqa: F401

    # the axon sitecustomize forces jax_platforms at interpreter start,
    # so spawn-time env vars alone don't stick (same workaround as
    # tests/conftest.py); shipped mesh subtrees additionally need the
    # session's mesh width in virtual CPU devices
    import jax

    # FIRST pin the CPU backend (before any device probe): workers must
    # never compute on — or even initialize — the shared attached TPU
    jax.config.update("jax_platforms", "cpu")
    mesh_n = int(os.environ.get("SRT_WORKER_MESH_DEVICES", "0") or 0)
    if mesh_n >= 2:
        from spark_rapids_tpu.parallel.mesh import force_cpu_mesh

        force_cpu_mesh(mesh_n)
    from spark_rapids_tpu.shuffle.cluster import Executor
    from spark_rapids_tpu.shuffle.meta import BlockId
    from spark_rapids_tpu.shuffle.tcp import Hangup, TcpShuffleServer

    config = json.loads(sys.stdin.readline())
    ex = Executor(config.get("executor_id", "exec-remote"))
    if config.get("mode") == "task":
        ts = TcpShuffleServer(ex.server)
        print(f"READY {ts.host} {ts.port}", flush=True)
        run_task_loop(ex, ts)
        ts.close()
        return
    for sid, mid, part, lo, n in config.get("blocks", []):
        ex.shuffle_catalog.register(BlockId(sid, mid, part),
                                    make_block_batch(lo, n))

    hangup_after = int(config.get("hangup_after_chunks", -1))
    if hangup_after >= 0:
        state = {"served": 0}

        def chunk_hook(block, offset, length):
            if state["served"] >= hangup_after:
                raise Hangup()
            state["served"] += 1

        ex.server.on_chunk = chunk_hook

    ts = TcpShuffleServer(ex.server)
    print(f"READY {ts.host} {ts.port}", flush=True)

    # serve until the parent closes our stdin (or kills us)
    sys.stdin.read()
    ts.close()


if __name__ == "__main__":
    main()
