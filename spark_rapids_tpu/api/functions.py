"""pyspark.sql.functions analogue over the Column DSL."""
from __future__ import annotations

from typing import Callable, Optional

from spark_rapids_tpu.api.column import Column, _to_col, col, lit, when  # noqa: F401
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions import aggregates as A
from spark_rapids_tpu.expressions import arithmetic as ar
from spark_rapids_tpu.expressions import bitwise as bw
from spark_rapids_tpu.expressions import conditional as cond
from spark_rapids_tpu.expressions import datetime as dte
from spark_rapids_tpu.expressions import math as mth
from spark_rapids_tpu.expressions import strings as st
from spark_rapids_tpu.expressions.base import Expression


class AggColumn(Column):
    """An aggregate call (sum/min/.../count) awaiting GroupedData.agg."""

    def __init__(self, make: Callable, name: Optional[str] = None):
        self.make = make          # schema -> AggregateFunction
        super().__init__(self._err, name)

    @staticmethod
    def _err(schema) -> Expression:
        raise TypeError("aggregate functions are only valid in "
                        "group_by(...).agg(...) or DataFrame.agg(...)")

    def alias(self, name: str) -> "AggColumn":
        return AggColumn(self.make, name)

    name = alias


def _unary(klass) -> Callable[[object], Column]:
    def f(c) -> Column:
        cc = _to_col(c)
        return Column(lambda s: klass(cc.resolve(s)))
    return f


def _agg(klass) -> Callable[[object], AggColumn]:
    def f(c) -> AggColumn:
        cc = _to_col(c) if not isinstance(c, str) else col(c)
        return AggColumn(lambda s: klass(cc.resolve(s)))
    return f


# aggregates ---------------------------------------------------------------

sum = _agg(A.Sum)          # noqa: A001  (pyspark parity)
min = _agg(A.Min)          # noqa: A001
max = _agg(A.Max)          # noqa: A001
avg = _agg(A.Average)
mean = avg
first = _agg(A.First)
last = _agg(A.Last)


def count(c="*") -> AggColumn:
    if isinstance(c, str) and c == "*":
        return AggColumn(lambda s: A.Count(None))
    cc = col(c) if isinstance(c, str) else _to_col(c)
    return AggColumn(lambda s: A.Count(cc.resolve(s)))


# scalar functions ---------------------------------------------------------

abs = _unary(ar.Abs)       # noqa: A001
sqrt = _unary(mth.Sqrt)
exp = _unary(mth.Exp)
log = _unary(mth.Log)
log2 = _unary(mth.Log2)
log10 = _unary(mth.Log10)
sin = _unary(mth.Sin)
cos = _unary(mth.Cos)
tan = _unary(mth.Tan)
floor = _unary(mth.Floor)
ceil = _unary(mth.Ceil)
signum = _unary(ar.Signum)

upper = _unary(st.Upper)
lower = _unary(st.Lower)
length = _unary(st.Length)
trim = _unary(st.StringTrim)
ltrim = _unary(st.StringTrimLeft)
rtrim = _unary(st.StringTrimRight)
initcap = _unary(st.InitCap)
reverse = _unary(st.Reverse)

year = _unary(dte.Year)
month = _unary(dte.Month)
dayofmonth = _unary(dte.DayOfMonth)
dayofweek = _unary(dte.DayOfWeek)
dayofyear = _unary(dte.DayOfYear)
quarter = _unary(dte.Quarter)
hour = _unary(dte.Hour)
minute = _unary(dte.Minute)
second = _unary(dte.Second)
last_day = _unary(dte.LastDay)


def _binary_fn(klass) -> Callable[[object, object], Column]:
    def f(a, b) -> Column:
        ca, cb = _to_col(a), _to_col(b)
        return Column(lambda s: klass(ca.resolve(s), cb.resolve(s)))
    return f


shiftleft = _binary_fn(bw.ShiftLeft)
shiftright = _binary_fn(bw.ShiftRight)
shiftrightunsigned = _binary_fn(bw.ShiftRightUnsigned)
bitwise_and = _binary_fn(bw.BitwiseAnd)
bitwise_or = _binary_fn(bw.BitwiseOr)
bitwise_xor = _binary_fn(bw.BitwiseXor)
bitwise_not = _unary(bw.BitwiseNot)


def concat(*cols) -> Column:
    cs = [_to_col(c) for c in cols]
    return Column(lambda s: st.ConcatStrings(
        [c.resolve(s) for c in cs]))


def coalesce(*cols) -> Column:
    cs = [_to_col(c) for c in cols]
    return Column(lambda s: cond.Coalesce([c.resolve(s) for c in cs]))


def date_add(c, days: int) -> Column:
    cc = _to_col(c)
    return Column(lambda s: dte.DateAdd(cc.resolve(s),
                                        _to_col(days).resolve(s)))


def date_sub(c, days: int) -> Column:
    cc = _to_col(c)
    return Column(lambda s: dte.DateSub(cc.resolve(s),
                                        _to_col(days).resolve(s)))


def datediff(end, start) -> Column:
    e, st_ = _to_col(end), _to_col(start)
    return Column(lambda s: dte.DateDiff(e.resolve(s), st_.resolve(s)))


def udf(fn, return_type) -> Callable[..., Column]:
    """Wrap a Python scalar function (the reference's udf registration);
    the planner traces it into native expressions where possible
    (SURVEY.md §2.11), else it runs row-wise on the CPU engine."""
    from spark_rapids_tpu.udf import PythonUdf

    typ = dt.by_name(return_type) if isinstance(return_type, str) \
        else return_type

    def make(*cols) -> Column:
        cs = [col(c) if isinstance(c, str) else _to_col(c) for c in cols]
        return Column(lambda s: PythonUdf(
            fn, [c.resolve(s) for c in cs], typ))
    return make
