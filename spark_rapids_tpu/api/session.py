"""Session: the SparkSession-shaped entry point."""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from spark_rapids_tpu.api.dataframe import DataFrame
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.plan import nodes as pn
from spark_rapids_tpu.utils import lockorder


class Session:
    """Holds the config snapshot and builds root DataFrames. The
    reference's SQLPlugin injects itself into a SparkSession; here the
    Session IS the host (standalone framework), and acceleration gates
    ride the same rapids.tpu.* keys."""

    def __init__(self, conf: Optional[Dict] = None,
                 initialize_runtime: bool = False):
        self.conf = conf if isinstance(conf, RapidsConf) else \
            RapidsConf(conf)
        if initialize_runtime:
            # executor-init analogue: device acquisition, HBM budget,
            # global spill catalog + semaphore (runtime/device.py).
            # The runtime is PROCESS-GLOBAL (one chip, one catalog):
            # initializing a second Session replaces it, so refuse while
            # another Session still owns it — stop() that one first.
            from spark_rapids_tpu import runtime

            current = runtime.get_env()
            if current is not None and \
                    getattr(current, "_owner", None) is not None:
                raise RuntimeError(
                    "another Session owns the runtime; call its "
                    ".stop() before initializing a new one")
            self.runtime = runtime.initialize(self.conf)
            self.runtime._owner = self
        else:
            self.runtime = None
        self._catalog: Dict = {}
        #: table name -> registration version; replacing a temp view
        #: bumps it (a SNAPSHOT EVENT for the semantic cache)
        self._catalog_versions: Dict[str, int] = {}
        self._service = None
        import threading

        self._service_init_lock = lockorder.make_lock("api.session.serviceInit")

    @property
    def service(self):
        """Lazily-started concurrent query service (service/) — the
        multi-tenant front door. ``df.collect_async()`` and
        ``sql_async()`` submit through it."""
        with self._service_init_lock:
            if self._service is None:
                if getattr(self, "_service_stopped", False):
                    # stop() tore the service (and runtime) down —
                    # lazily resurrecting a fresh worker pool against
                    # it would "succeed" into a dead engine and leak
                    # threads
                    raise RuntimeError(
                        "Session is stopped; create a new Session")
                from spark_rapids_tpu.service import QueryService

                self._service = QueryService(self.conf, session=self)
            return self._service

    def sql_async(self, query: str, tenant: str = "default",
                  priority: int = 0, deadline=None):
        """Parse + plan + submit to the query service; returns a
        QueryHandle (poll/result/cancel) instead of blocking."""
        return self.service.submit(self.sql(query), tenant=tenant,
                                   priority=priority, deadline=deadline)

    def stop(self) -> None:
        """Release the process-global runtime this Session initialized
        (SparkSession.stop analogue) and shut down the query service.
        No-op for sessions that did not initialize them."""
        with self._service_init_lock:
            self._service_stopped = True
            service, self._service = self._service, None
        if service is not None:
            service.shutdown()
        if self.runtime is None:
            return
        from spark_rapids_tpu import runtime

        if runtime.get_env() is self.runtime:
            runtime.shutdown()
        self.runtime = None

    # -- readers ----------------------------------------------------------

    @property
    def read(self) -> "DataFrameReader":
        return DataFrameReader(self)

    def create_dataframe(self, data, schema: Optional[Schema] = None
                         ) -> DataFrame:
        """From a pandas DataFrame or a dict of columns."""
        import pandas as pd

        if isinstance(data, pd.DataFrame):
            cols = {}
            validity = {}
            for name in data.columns:
                s = data[name]
                if s.dtype == object or str(s.dtype) == "string":
                    cols[name] = np.array(
                        [None if v is None or (isinstance(v, float) and
                                               np.isnan(v)) else v
                         for v in s], dtype=object)
                else:
                    isna = s.isna().to_numpy(dtype=bool)
                    cols[name] = s.fillna(0).to_numpy()
                    if isna.any():
                        validity[name] = ~isna
            src = pn.InMemorySource(cols, schema=schema,
                                    validity=validity)
        else:
            src = pn.InMemorySource(dict(data), schema=schema)
        return DataFrame(pn.ScanNode(src), self)

    createDataFrame = create_dataframe

    def range(self, start: int, end: Optional[int] = None,
              step: int = 1) -> DataFrame:
        if end is None:
            start, end = 0, start
        return DataFrame(pn.RangeNode(start, end, step), self)

    # -- SQL entry point ---------------------------------------------------

    def create_temp_view(self, name: str, df_or_source) -> int:
        """Register a DataFrame / DataSource / plan under ``name`` for
        Session.sql (createOrReplaceTempView analogue). REPLACING a
        registered view is a SNAPSHOT EVENT: the displaced target's
        sources get their cache snapshot version bumped, so results the
        semantic cache computed from the old view are unreachable (the
        version participates in every cache key) — a silent replace
        must never serve yesterday's dashboard. Returns the table's new
        registration version."""
        target = df_or_source
        if isinstance(target, DataFrame):
            target = target._plan
        prev = self._catalog.get(name)
        if prev is not None and prev is not target:
            from spark_rapids_tpu.service.cache import snapshots

            snapshots.bump_plan(prev)
        self._catalog[name] = target
        version = self._catalog_versions.get(name, 0) + 1
        self._catalog_versions[name] = version
        return version

    createOrReplaceTempView = create_temp_view

    def table_version(self, name: str) -> int:
        """Registration version of ``name`` (0 = never registered)."""
        return self._catalog_versions.get(name, 0)

    def bump_table_version(self, name: str) -> int:
        """Explicitly invalidate cached results over ``name`` (the
        in-place-mutation escape hatch: data changed UNDER the same
        registered source object, which no key can see on its own)."""
        from spark_rapids_tpu.service.cache import snapshots

        target = self._catalog.get(name)
        if target is not None:
            snapshots.bump_plan(target)
        version = self._catalog_versions.get(name, 0) + 1
        self._catalog_versions[name] = version
        return version

    # -- streaming tables (service/streaming) ------------------------------

    def create_streaming_table(self, name: str, schema: Schema):
        """Create an appendable streaming table, register it as a temp
        view (batch queries over it see all rows appended so far), and
        return the StreamTableSource. Feed it with ``append_batch``;
        register continuous aggregations over it with
        ``service.register_standing``."""
        from spark_rapids_tpu import config as cfg
        from spark_rapids_tpu.service.streaming.source import \
            StreamTableSource

        src = StreamTableSource(name, schema)
        if str(self.conf.get(cfg.STREAMING_CHECKPOINT_DIR)
               or "").strip():
            # durability (PR 19): replay the table's WAL and route
            # future appends through it — BEFORE the view registers,
            # so batch queries see recovered rows from the first scan.
            # The knob check keeps the lazy `service` property lazy for
            # non-durable sessions.
            self.service.streaming.attach_source(src)
        self.create_temp_view(name, src)
        return src

    def streaming_table(self, name: str):
        """The registered StreamTableSource behind ``name``."""
        from spark_rapids_tpu.plan.incremental import \
            is_streaming_source

        target = self._catalog.get(name)
        if isinstance(target, pn.ScanNode):
            target = target.source
        if target is None or not is_streaming_source(target):
            raise KeyError(f"{name!r} is not a registered streaming "
                           "table")
        return target

    def append_batch(self, table, data, validity=None) -> int:
        """Append one micro-batch (dict of columns or pandas frame) to
        a streaming table — by name or source — routing through the
        query service so standing queries fold it synchronously;
        returns the rows landed."""
        return self.service.ingest(table, data, validity)

    def register_parquet(self, name: str, path, columns=None) -> None:
        """Catalog a parquet directory as a SQL table."""
        from spark_rapids_tpu.io import ParquetSource

        self.create_temp_view(name, ParquetSource(path, columns=columns))

    def sql(self, query: str) -> DataFrame:
        """Parse + plan a SELECT over the catalog; returns a lazy
        DataFrame like any other (the whole override/oracle machinery
        downstream is shared). Unsupported SQL raises SqlError."""
        from spark_rapids_tpu.sql import parse, plan_statement

        return DataFrame(plan_statement(parse(query), self._catalog),
                         self)


class DataFrameReader:
    def __init__(self, session: Session):
        self.session = session

    def parquet(self, *paths, columns=None) -> DataFrame:
        from spark_rapids_tpu.io import ParquetSource

        src = ParquetSource(list(paths) if len(paths) > 1 else paths[0],
                            columns=columns, conf=self.session.conf)
        return DataFrame(pn.ScanNode(src), self.session)

    def orc(self, *paths, columns=None) -> DataFrame:
        from spark_rapids_tpu.io import OrcSource

        src = OrcSource(list(paths) if len(paths) > 1 else paths[0],
                        columns=columns, conf=self.session.conf)
        return DataFrame(pn.ScanNode(src), self.session)

    def csv(self, *paths, schema: Optional[Schema] = None,
            header: bool = True, delimiter: str = ",") -> DataFrame:
        from spark_rapids_tpu.io import CsvSource

        src = CsvSource(list(paths) if len(paths) > 1 else paths[0],
                        schema=schema, header=header,
                        delimiter=delimiter, conf=self.session.conf)
        return DataFrame(pn.ScanNode(src), self.session)
