"""Lazy DataFrame over the engine-neutral plan tree."""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

from spark_rapids_tpu.api.column import Column, _to_col, col
from spark_rapids_tpu.api.functions import AggColumn
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Expression)
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.plan import nodes as pn

ColumnOrName = Union[Column, str]


def _as_col(c: ColumnOrName) -> Column:
    return col(c) if isinstance(c, str) else c


class DataFrame:
    def __init__(self, plan: pn.PlanNode, session):
        self._plan = plan
        self.session = session

    # -- metadata ---------------------------------------------------------

    @property
    def schema(self) -> Schema:
        return self._plan.output_schema()

    @property
    def columns(self) -> List[str]:
        return list(self.schema.names)

    @property
    def dtypes(self):
        s = self.schema
        return [(n, t.name) for n, t in zip(s.names, s.types)]

    def __repr__(self) -> str:  # pragma: no cover
        return f"DataFrame[{', '.join(f'{n}: {t}' for n, t in self.dtypes)}]"

    # -- transformations --------------------------------------------------

    def _df(self, plan: pn.PlanNode) -> "DataFrame":
        return DataFrame(plan, self.session)

    def select(self, *cols: ColumnOrName) -> "DataFrame":
        schema = self.schema
        exprs: List[Expression] = []
        names: List[str] = []
        for i, c in enumerate(cols):
            cc = _as_col(c)
            e = cc.resolve(schema)
            names.append(cc.out_name(f"col{i}"))
            exprs.append(e.children[0] if isinstance(e, Alias) else e)
        return self._df(pn.ProjectNode(exprs, self._plan, names))

    def filter(self, condition: Column) -> "DataFrame":
        return self._df(pn.FilterNode(
            condition.resolve(self.schema), self._plan))

    where = filter

    def with_column(self, name: str, c: Column) -> "DataFrame":
        schema = self.schema
        exprs = [BoundReference(i, t)
                 for i, t in enumerate(schema.types)]
        names = list(schema.names)
        new = c.resolve(schema)
        if name in names:
            exprs[names.index(name)] = new
        else:
            exprs.append(new)
            names.append(name)
        return self._df(pn.ProjectNode(exprs, self._plan, names))

    withColumn = with_column

    def drop(self, *names: str) -> "DataFrame":
        keep = [n for n in self.columns if n not in names]
        return self.select(*keep)

    def create_or_replace_temp_view(self, name: str) -> None:
        """Register this DataFrame in the session catalog for
        Session.sql (Spark's createOrReplaceTempView)."""
        self.session.create_temp_view(name, self)

    createOrReplaceTempView = create_or_replace_temp_view

    def explode(self, *cols: ColumnOrName, value_name: str = "col",
                pos: bool = False, pos_name: str = "pos") -> "DataFrame":
        """explode/posexplode of a per-row array created from ``cols``
        (the GenerateExec surface — the reference supports exactly
        explode(array(...)), GpuGenerateExec.scala). Every original
        column is kept; each input row emits len(cols) rows."""
        schema = self.schema
        exprs = []
        for c in cols:
            e = _as_col(c).resolve(schema)
            exprs.append(e.children[0] if isinstance(e, Alias) else e)
        return self._df(pn.GenerateNode(
            exprs, self._plan, list(range(len(schema.names))),
            value_name=value_name, include_pos=pos, pos_name=pos_name))

    def group_by(self, *cols: ColumnOrName) -> "GroupedData":
        return GroupedData(self, [_as_col(c) for c in cols],
                           [c if isinstance(c, str) else c.out_name(None)
                            for c in cols])

    groupBy = group_by

    def agg(self, *aggs: AggColumn) -> "DataFrame":
        return GroupedData(self, [], []).agg(*aggs)

    def join(self, other: "DataFrame", on=None, how: str = "inner"
             ) -> "DataFrame":
        how = {"leftsemi": "left_semi", "left_semi": "left_semi",
               "leftanti": "left_anti", "left_anti": "left_anti",
               "leftouter": "left", "rightouter": "right",
               "outer": "full", "fullouter": "full",
               "full_outer": "full"}.get(how, how)
        if how == "cross" or on is None:
            return self._df(pn.JoinNode("cross", self._plan, other._plan,
                                        [], []))
        ls, rs = self.schema, other.schema
        if isinstance(on, str):
            on = [on]
        lk, rk = [], []
        for o in on:
            if isinstance(o, tuple):
                lname, rname = o
            else:
                lname = rname = o
            lk.append(ls.index_of(lname))
            rk.append(rs.index_of(rname))
        return self._df(pn.JoinNode(how, self._plan, other._plan, lk, rk))

    def order_by(self, *cols: ColumnOrName,
                 ascending: Union[bool, Sequence[bool]] = True
                 ) -> "DataFrame":
        schema = self.schema
        if isinstance(ascending, bool):
            asc = [ascending] * len(cols)
        else:
            asc = list(ascending)
        specs = []
        for c, a in zip(cols, asc):
            e = _as_col(c).resolve(schema)
            if not isinstance(e, BoundReference):
                raise ValueError(
                    "order_by requires plain columns; project computed "
                    "keys first (with_column)")
            specs.append(SortKeySpec.spark_default(e.ordinal,
                                                   ascending=a))
        return self._df(pn.SortNode(specs, self._plan))

    sort = order_by
    orderBy = order_by

    def limit(self, n: int) -> "DataFrame":
        return self._df(pn.LimitNode(n, self._plan))

    def union(self, other: "DataFrame") -> "DataFrame":
        return self._df(pn.UnionNode([self._plan, other._plan]))

    unionAll = union

    def distinct(self) -> "DataFrame":
        schema = self.schema
        grouping = [BoundReference(i, t)
                    for i, t in enumerate(schema.types)]
        return self._df(pn.AggregateNode(
            grouping, [], self._plan,
            grouping_names=list(schema.names)))

    def map_in_pandas(self, fn, schema: Schema) -> "DataFrame":
        from spark_rapids_tpu.execs.python_exec import MapInPandasNode

        return self._df(MapInPandasNode(fn, schema, self._plan))

    mapInPandas = map_in_pandas

    def cache(self) -> "DataFrame":
        """Persist results as spillable device batches (HBM while it
        fits, host/disk under pressure — unlike the reference, which
        routes .cache() through the host-side Spark cache)."""
        from spark_rapids_tpu.execs.cache import CacheNode

        if isinstance(self._plan, CacheNode):
            return self
        return self._df(CacheNode(self._plan))

    persist = cache

    def unpersist(self) -> "DataFrame":
        from spark_rapids_tpu.execs.cache import CacheNode

        if isinstance(self._plan, CacheNode):
            self._plan.holder.unpersist()
        return self

    def with_column_renamed(self, old: str, new: str) -> "DataFrame":
        names = [new if n == old else n for n in self.columns]
        return self.to_df(*names)

    withColumnRenamed = with_column_renamed

    def to_df(self, *names: str) -> "DataFrame":
        schema = self.schema
        assert len(names) == len(schema)
        exprs = [BoundReference(i, t)
                 for i, t in enumerate(schema.types)]
        return self._df(pn.ProjectNode(exprs, self._plan,
                                       names=list(names)))

    toDF = to_df

    def fillna(self, value, subset: Optional[Sequence[str]] = None
               ) -> "DataFrame":
        """Replace NULLs with ``value`` in type-compatible columns
        (pyspark DataFrameNaFunctions.fill)."""
        from spark_rapids_tpu.columnar import dtypes as dt
        from spark_rapids_tpu.expressions.conditional import Coalesce
        from spark_rapids_tpu.expressions.base import Literal

        schema = self.schema
        exprs: List[Expression] = []
        for i, (name, typ) in enumerate(zip(schema.names,
                                            schema.types)):
            e: Expression = BoundReference(i, typ)
            applies = subset is None or name in subset
            compat = (
                (isinstance(value, bool) and typ is dt.BOOLEAN) or
                (isinstance(value, (int, float)) and
                 not isinstance(value, bool) and typ.is_numeric) or
                (isinstance(value, str) and typ is dt.STRING))
            if applies and compat:
                e = Coalesce([e, Literal(
                    typ.np_dtype.type(value).item()
                    if typ.is_numeric and not isinstance(value, bool)
                    else value, typ)])
            exprs.append(e)
        return self._df(pn.ProjectNode(exprs, self._plan,
                                       names=list(schema.names)))

    def dropna(self, how: str = "any",
               subset: Optional[Sequence[str]] = None) -> "DataFrame":
        """Drop rows with NULLs (pyspark DataFrameNaFunctions.drop)."""
        from spark_rapids_tpu.expressions import predicates as pr

        schema = self.schema
        cols = [i for i, n in enumerate(schema.names)
                if subset is None or n in subset]
        if not cols:
            return self
        terms = [pr.IsNotNull(BoundReference(i, schema.types[i]))
                 for i in cols]
        cond = terms[0]
        for t in terms[1:]:
            cond = pr.And(cond, t) if how == "any" else pr.Or(cond, t)
        return self._df(pn.FilterNode(cond, self._plan))

    def sample(self, fraction: float, seed: int = 0) -> "DataFrame":
        """Bernoulli row sample via the counter-based rand stream
        (nondeterministic vs Spark's sampler, so it rides the same
        incompatibleOps gate as rand())."""
        from spark_rapids_tpu.expressions import predicates as pr
        from spark_rapids_tpu.expressions.base import Literal
        from spark_rapids_tpu.expressions.nondeterministic import Rand

        return self._df(pn.FilterNode(
            pr.LessThan(Rand(seed), Literal(float(fraction))),
            self._plan))

    def describe(self, *cols: str):
        """count/mean/min/max summary of numeric columns (collected)."""
        from spark_rapids_tpu.api import functions as F

        schema = self.schema
        targets = [n for n, t in zip(schema.names, schema.types)
                   if t.is_numeric and (not cols or n in cols)]
        aggs = []
        for n in targets:
            aggs += [F.count(col(n)).alias(f"count({n})"),
                     F.avg(col(n)).alias(f"mean({n})"),
                     F.min(col(n)).alias(f"min({n})"),
                     F.max(col(n)).alias(f"max({n})")]
        return self.agg(*aggs).collect()

    def coalesce(self, num_partitions: int) -> "DataFrame":
        """Shrink partition count without a shuffle."""
        return self._df(pn.CoalescePartitionsNode(num_partitions,
                                                  self._plan))

    def repartition(self, num_partitions: int,
                    *cols: ColumnOrName) -> "DataFrame":
        schema = self.schema
        if cols:
            ordinals = []
            for c in cols:
                e = _as_col(c).resolve(schema)
                assert isinstance(e, BoundReference), \
                    "repartition keys must be plain columns"
                ordinals.append(e.ordinal)
            part = ("hash", ordinals)
        else:
            part = ("round_robin",)
        return self._df(pn.ShuffleExchangeNode(part, num_partitions,
                                               self._plan))

    # -- actions ----------------------------------------------------------

    def _exec(self):
        from spark_rapids_tpu.plan.overrides import apply_overrides

        self._last_exec = apply_overrides(self._plan, self.session.conf)
        return self._last_exec

    def collect(self):
        from spark_rapids_tpu.execs.base import collect

        return collect(self._exec(), conf=self.session.conf)

    def collect_async(self, tenant: str = "default", priority: int = 0,
                      deadline=None):
        """Submit through the session's QueryService (service/):
        returns a QueryHandle immediately; ``handle.result()`` blocks.
        Many collect_async() calls run concurrently under admission
        control + fair stage scheduling instead of serializing."""
        return self.session.service.submit(
            self, tenant=tenant, priority=priority, deadline=deadline)

    collectAsync = collect_async

    def last_metrics(self) -> dict:
        """Per-operator metrics of the most recent collect() — the SQL-UI
        SQLMetrics view (GpuExec.scala:90-96): rows/batches/self-time."""
        exec_ = getattr(self, "_last_exec", None)
        if exec_ is None:
            return {}
        return {name: {"rows": m.num_output_rows,
                       "batches": m.num_output_batches,
                       "op_time_ms": round(m.op_time_ns / 1e6, 3)}
                for name, m in exec_.all_metrics().items()}

    to_pandas = collect
    toPandas = collect

    def count(self) -> int:
        from spark_rapids_tpu.expressions import aggregates as A

        plan = pn.AggregateNode(
            [], [pn.AggCall(A.Count(None), "count")], self._plan)
        from spark_rapids_tpu.execs.base import collect
        from spark_rapids_tpu.plan.overrides import apply_overrides

        df = collect(apply_overrides(plan, self.session.conf),
                     conf=self.session.conf)
        return int(df["count"].iloc[0])

    def show(self, n: int = 20) -> None:  # pragma: no cover - console
        print(self.limit(n).collect().to_string(index=False))

    def explain(self) -> str:
        """Tag/convert report (spark.rapids.sql.explain analogue)."""
        from spark_rapids_tpu.plan.overrides import explain

        return explain(self._plan, self.session.conf)

    @property
    def write(self) -> "DataFrameWriter":
        return DataFrameWriter(self)


class GroupedData:
    def __init__(self, df: DataFrame, keys: List[Column],
                 key_names: List[Optional[str]]):
        self.df = df
        self.keys = keys
        self.key_names = key_names

    def agg(self, *aggs: AggColumn) -> DataFrame:
        schema = self.df.schema
        grouping = []
        gnames = []
        for i, (k, nm) in enumerate(zip(self.keys, self.key_names)):
            e = k.resolve(schema)
            grouping.append(e.children[0] if isinstance(e, Alias) else e)
            gnames.append(nm or k.out_name(f"key{i}"))
        calls = []
        for i, a in enumerate(aggs):
            assert isinstance(a, AggColumn), \
                "group_by().agg takes aggregate functions"
            calls.append(pn.AggCall(a.make(schema),
                                    a.out_name(f"agg{i}")))
        return self.df._df(pn.AggregateNode(
            grouping, calls, self.df._plan, grouping_names=gnames))

    def count(self) -> DataFrame:
        from spark_rapids_tpu.api import functions as F

        return self.agg(F.count("*").alias("count"))

    def apply_in_pandas(self, fn, schema: Schema) -> DataFrame:
        """groupBy(keys).applyInPandas: ``fn`` maps each group's pandas
        frame to a frame with ``schema``."""
        from spark_rapids_tpu.execs.python_exec import \
            GroupedMapInPandasNode

        return self.df._df(GroupedMapInPandasNode(
            self._key_ordinals(), fn, schema, self.df._plan))

    applyInPandas = apply_in_pandas

    def cogroup(self, other: "GroupedData") -> "CoGroupedData":
        return CoGroupedData(self, other)

    def _key_ordinals(self) -> List[int]:
        schema = self.df.schema
        out = []
        for k in self.keys:
            e = k.resolve(schema)
            assert isinstance(e, BoundReference), \
                "grouped/cogrouped pandas keys must be plain columns"
            out.append(e.ordinal)
        return out


class CoGroupedData:
    def __init__(self, left: "GroupedData", right: "GroupedData"):
        assert len(left.keys) == len(right.keys)
        self.left = left
        self.right = right

    def apply_in_pandas(self, fn, schema: Schema) -> DataFrame:
        from spark_rapids_tpu.execs.python_exec import \
            CoGroupedMapInPandasNode

        return self.left.df._df(CoGroupedMapInPandasNode(
            self.left.df._plan, self.right.df._plan,
            self.left._key_ordinals(), self.right._key_ordinals(),
            fn, schema))

    applyInPandas = apply_in_pandas

    def _shortcut(self, fn_name: str, *cols: str) -> DataFrame:
        from spark_rapids_tpu.api import functions as F

        fn = getattr(F, fn_name)
        targets = cols or [n for n, t in zip(self.df.schema.names,
                                             self.df.schema.types)
                           if t.is_numeric]
        return self.agg(*[fn(col(c)).alias(f"{fn_name}({c})")
                          for c in targets])

    def sum(self, *cols: str) -> DataFrame:
        return self._shortcut("sum", *cols)

    def min(self, *cols: str) -> DataFrame:
        return self._shortcut("min", *cols)

    def max(self, *cols: str) -> DataFrame:
        return self._shortcut("max", *cols)

    def avg(self, *cols: str) -> DataFrame:
        return self._shortcut("avg", *cols)

    mean = avg


class DataFrameWriter:
    def __init__(self, df: DataFrame):
        self.df = df
        self._mode = "overwrite"
        self._partition_by: List[str] = []

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = {"overwrite": "overwrite",
                      "error": "error",
                      "errorifexists": "error"}[m]
        return self

    def partition_by(self, *cols: str) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    partitionBy = partition_by

    def _write(self, path: str, fmt: str):
        from spark_rapids_tpu.execs.base import collect
        from spark_rapids_tpu.io.write import WriteFilesNode
        from spark_rapids_tpu.plan.overrides import apply_overrides

        node = WriteFilesNode(self.df._plan, path, format=fmt,
                              partition_by=self._partition_by,
                              mode=self._mode)
        return collect(apply_overrides(node, self.df.session.conf))

    def parquet(self, path: str):
        return self._write(path, "parquet")

    def orc(self, path: str):
        return self._write(path, "orc")
