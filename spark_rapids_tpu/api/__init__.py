"""User-facing DataFrame API.

The reference accelerates Spark's DataFrame/SQL API transparently; this
standalone framework exposes an equivalent front end so a Spark user
finds the familiar surface: a Session with readers, a Column expression
DSL (``col``/``lit``/functions), and a lazy DataFrame whose operations
build the engine-neutral plan tree. ``collect()`` plans through
TpuOverrides (accelerated with reasoned fallback); ``explain()`` shows
the same tag/reason output Spark users get from
``spark.rapids.sql.explain``.

    from spark_rapids_tpu.api import Session, col, lit, functions as F

    s = Session()
    df = s.read.parquet("/data/lineitem")
    out = (df.filter(col("l_shipdate") <= lit(10000))
             .group_by("l_returnflag")
             .agg(F.sum(col("l_quantity")).alias("qty"))
             .order_by("l_returnflag"))
    print(out.explain())
    pdf = out.collect()
"""
from spark_rapids_tpu.api.column import Column, col, lit, when
from spark_rapids_tpu.api import functions
from spark_rapids_tpu.api.dataframe import DataFrame, GroupedData
from spark_rapids_tpu.api.session import Session

__all__ = ["Session", "DataFrame", "GroupedData", "Column", "col",
           "lit", "when", "functions"]
