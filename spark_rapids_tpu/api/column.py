"""Column DSL: unresolved expression builders.

A ``Column`` wraps ``resolve(schema) -> Expression``: names bind to
ordinals only when the parent DataFrame applies the operation (Spark's
analysis phase). Operators mirror pyspark.sql.Column.
"""
from __future__ import annotations

from typing import Callable, Optional

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.expressions import arithmetic as ar
from spark_rapids_tpu.expressions import conditional as cond
from spark_rapids_tpu.expressions import predicates as pr
from spark_rapids_tpu.expressions import strings as st
from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Expression, Literal)
from spark_rapids_tpu.expressions.cast import Cast


class Column:
    def __init__(self, resolve: Callable[[Schema], Expression],
                 name: Optional[str] = None):
        self._resolve = resolve
        self._name = name

    def resolve(self, schema: Schema) -> Expression:
        e = self._resolve(schema)
        return e

    def named(self, schema: Schema, fallback: str) -> Expression:
        e = self.resolve(schema)
        name = self._name or fallback
        if isinstance(e, Alias):
            return e
        return Alias(e, name)

    def out_name(self, fallback: str) -> str:
        return self._name or fallback

    # -- naming -----------------------------------------------------------

    def alias(self, name: str) -> "Column":
        return Column(self._resolve, name)

    name = alias

    # -- operators --------------------------------------------------------

    def _bin(self, other, klass, flip=False) -> "Column":
        o = _to_col(other)

        def rf(schema: Schema) -> Expression:
            l, r = self.resolve(schema), o.resolve(schema)
            if flip:
                l, r = r, l
            return klass(l, r)
        return Column(rf)

    def __add__(self, o):
        return self._bin(o, ar.Add)

    def __radd__(self, o):
        return self._bin(o, ar.Add, flip=True)

    def __sub__(self, o):
        return self._bin(o, ar.Subtract)

    def __rsub__(self, o):
        return self._bin(o, ar.Subtract, flip=True)

    def __mul__(self, o):
        return self._bin(o, ar.Multiply)

    def __rmul__(self, o):
        return self._bin(o, ar.Multiply, flip=True)

    def __truediv__(self, o):
        return self._bin(o, ar.Divide)

    def __rtruediv__(self, o):
        return self._bin(o, ar.Divide, flip=True)

    def __mod__(self, o):
        return self._bin(o, ar.Remainder)

    def __neg__(self):
        return Column(lambda s: ar.UnaryMinus(self.resolve(s)))

    def __eq__(self, o):  # type: ignore[override]
        return self._bin(o, pr.EqualTo)

    def __ne__(self, o):  # type: ignore[override]
        c = self._bin(o, pr.EqualTo)
        return Column(lambda s: pr.Not(c.resolve(s)))

    def __lt__(self, o):
        return self._bin(o, pr.LessThan)

    def __le__(self, o):
        return self._bin(o, pr.LessThanOrEqual)

    def __gt__(self, o):
        return self._bin(o, pr.GreaterThan)

    def __ge__(self, o):
        return self._bin(o, pr.GreaterThanOrEqual)

    def __and__(self, o):
        return self._bin(o, pr.And)

    def __or__(self, o):
        return self._bin(o, pr.Or)

    def __invert__(self):
        return Column(lambda s: pr.Not(self.resolve(s)))

    def __hash__(self):
        return id(self)

    def __bool__(self):
        raise TypeError(
            "Column is not a boolean; use & | ~ for combinators")

    # -- methods ----------------------------------------------------------

    def is_null(self) -> "Column":
        return Column(lambda s: pr.IsNull(self.resolve(s)))

    isNull = is_null

    def is_not_null(self) -> "Column":
        return Column(lambda s: pr.IsNotNull(self.resolve(s)))

    isNotNull = is_not_null

    def isin(self, *values) -> "Column":
        vals = list(values[0]) if len(values) == 1 and \
            isinstance(values[0], (list, tuple, set)) else list(values)
        return Column(lambda s: pr.In(self.resolve(s),
                                      sorted(vals, key=repr)))

    def between(self, lo, hi) -> "Column":
        return (self >= lo) & (self <= hi)

    def cast(self, to) -> "Column":
        typ = dt.by_name(to) if isinstance(to, str) else to
        return Column(lambda s: Cast(self.resolve(s), typ),
                      self._name)

    astype = cast

    def startswith(self, prefix: str) -> "Column":
        return Column(lambda s: st.StartsWith(self.resolve(s), prefix))

    def endswith(self, suffix: str) -> "Column":
        return Column(lambda s: st.EndsWith(self.resolve(s), suffix))

    def contains(self, needle: str) -> "Column":
        return Column(lambda s: st.Contains(self.resolve(s), needle))

    def like(self, pattern: str) -> "Column":
        return Column(lambda s: st.Like(self.resolve(s), pattern))

    def substr(self, pos: int, length: Optional[int] = None) -> "Column":
        return Column(lambda s: st.Substring(self.resolve(s), pos,
                                             length))

    def when(self, condition: "Column", value) -> "Column":
        raise TypeError("use functions.when(cond, val) to start a CASE")

    def otherwise(self, value) -> "Column":
        raise TypeError("otherwise() only applies to when() chains")


class WhenColumn(Column):
    """CASE WHEN builder (functions.when)."""

    def __init__(self, branches):
        self._branches = branches
        super().__init__(self._build, None)

    def _build(self, schema: Schema) -> Expression:
        return cond.CaseWhen(
            [(c.resolve(schema), _to_col(v).resolve(schema))
             for c, v in self._branches], None)

    def when(self, condition: Column, value) -> "WhenColumn":
        return WhenColumn(self._branches + [(condition, value)])

    def otherwise(self, value) -> Column:
        branches = self._branches

        def rf(schema: Schema) -> Expression:
            return cond.CaseWhen(
                [(c.resolve(schema), _to_col(v).resolve(schema))
                 for c, v in branches],
                _to_col(value).resolve(schema))
        return Column(rf)


def col(name: str) -> Column:
    def rf(schema: Schema) -> Expression:
        i = schema.index_of(name)
        return BoundReference(i, schema.types[i])
    return Column(rf, name)


column = col


def lit(value) -> Column:
    return Column(lambda s: Literal(value))


def when(condition: Column, value) -> WhenColumn:
    return WhenColumn([(condition, value)])


def _to_col(v) -> Column:
    if isinstance(v, Column):
        return v
    return lit(v)
