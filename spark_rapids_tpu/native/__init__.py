"""ctypes binding to the native host runtime (native/src/srt_native.cpp).

The reference's host data plane is native (cuDF JNI buffers, nvcomp LZ4,
UCX); here the equivalents are a small C++ library for the host-side hot
loops — LZ4 block codec, validity bitmap packing, CRC32C — built lazily
with g++ on first import. Every entry point has a pure-Python fallback so
the engine still runs (slower) where no compiler exists; `available()`
reports which path is live.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from spark_rapids_tpu.utils import lockorder
from typing import Optional

import numpy as np

_LIB_NAME = "libsrt_native.so"
_lock = lockorder.make_lock("native.init")
_lib: Optional[ctypes.CDLL] = None
_tried = False

_U8P = ctypes.POINTER(ctypes.c_uint8)


def _repo_native_dir() -> Optional[str]:
    here = os.path.dirname(os.path.abspath(__file__))
    cand = os.path.normpath(os.path.join(here, "..", "..", "native"))
    return cand if os.path.isdir(cand) else None


def _try_build() -> Optional[str]:
    nd = _repo_native_dir()
    if nd is None:
        return None
    src = os.path.join(nd, "src", "srt_native.cpp")
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       _LIB_NAME)
    if not os.path.exists(src):
        return None
    if os.path.exists(out) and \
            os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    try:
        subprocess.run(
            ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-o", out,
             src], check=True, capture_output=True, timeout=120)
        return out
    except Exception:
        return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            _LIB_NAME)
        if not os.path.exists(path):
            path = _try_build()
        if path is None or not os.path.exists(path):
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.srt_lz4_max_compressed.restype = ctypes.c_long
        lib.srt_lz4_max_compressed.argtypes = [ctypes.c_long]
        lib.srt_lz4_compress.restype = ctypes.c_long
        lib.srt_lz4_compress.argtypes = [_U8P, ctypes.c_long, _U8P,
                                         ctypes.c_long]
        lib.srt_lz4_decompress.restype = ctypes.c_long
        lib.srt_lz4_decompress.argtypes = [_U8P, ctypes.c_long, _U8P,
                                           ctypes.c_long]
        lib.srt_pack_bits.restype = ctypes.c_long
        lib.srt_pack_bits.argtypes = [_U8P, ctypes.c_long, _U8P]
        lib.srt_unpack_bits.restype = ctypes.c_long
        lib.srt_unpack_bits.argtypes = [_U8P, ctypes.c_long, _U8P]
        lib.srt_crc32c.restype = ctypes.c_uint32
        lib.srt_crc32c.argtypes = [_U8P, ctypes.c_long, ctypes.c_uint32]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _as_u8(buf) -> np.ndarray:
    return np.frombuffer(buf, dtype=np.uint8)


def _ptr(arr: np.ndarray):
    return arr.ctypes.data_as(_U8P)


# ---------------------------------------------------------------------------
# LZ4 block codec
# ---------------------------------------------------------------------------


def lz4_compress(data: bytes) -> bytes:
    lib = _load()
    src = _as_u8(data)
    n = len(src)
    if lib is not None:
        cap = lib.srt_lz4_max_compressed(n)
        dst = np.empty(cap, dtype=np.uint8)
        written = lib.srt_lz4_compress(_ptr(src), n, _ptr(dst), cap)
        if written < 0:
            raise RuntimeError("lz4 compress overflow")
        return dst[:written].tobytes()
    return _py_lz4_compress(bytes(data))


def lz4_decompress(data: bytes, raw_len: int) -> bytes:
    lib = _load()
    src = _as_u8(data)
    if lib is not None:
        dst = np.empty(raw_len, dtype=np.uint8)
        got = lib.srt_lz4_decompress(_ptr(src), len(src), _ptr(dst),
                                     raw_len)
        if got != raw_len:
            raise ValueError(
                f"lz4 decompress: expected {raw_len} bytes, got {got}")
        return dst.tobytes()
    return _py_lz4_decompress(bytes(data), raw_len)


def _py_lz4_compress(data: bytes) -> bytes:
    """Literal-only LZ4 stream (valid format, no compression) — fallback
    writer when the native library is unavailable."""
    out = bytearray()
    n = len(data)
    llen = n
    if llen >= 15:
        out.append(15 << 4)
        rest = llen - 15
        while rest >= 255:
            out.append(255)
            rest -= 255
        out.append(rest)
    else:
        out.append(llen << 4)
    out += data
    return bytes(out)


def _py_lz4_decompress(src: bytes, raw_len: int) -> bytes:
    """Pure-Python LZ4 block decompressor — also the cross-check oracle
    for the native compressor in tests."""
    out = bytearray()
    i, n = 0, len(src)
    while i < n:
        token = src[i]
        i += 1
        llen = token >> 4
        if llen == 15:
            while True:
                b = src[i]
                i += 1
                llen += b
                if b != 255:
                    break
        out += src[i:i + llen]
        i += llen
        if i >= n:
            break
        off = src[i] | (src[i + 1] << 8)
        i += 2
        if off == 0 or off > len(out):
            raise ValueError("bad lz4 offset")
        mlen = (token & 15) + 4
        if (token & 15) == 15:
            while True:
                b = src[i]
                i += 1
                mlen += b
                if b != 255:
                    break
        start = len(out) - off
        for k in range(mlen):  # overlap-safe byte copy
            out.append(out[start + k])
    if len(out) != raw_len:
        raise ValueError(
            f"lz4 decompress: expected {raw_len}, got {len(out)}")
    return bytes(out)


# ---------------------------------------------------------------------------
# Validity bitmaps
# ---------------------------------------------------------------------------


def pack_bits(bools: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(bools, dtype=np.uint8)
    lib = _load()
    n = len(arr)
    if lib is not None:
        out = np.empty((n + 7) // 8, dtype=np.uint8)
        lib.srt_pack_bits(_ptr(arr), n, _ptr(out))
        return out.tobytes()
    return np.packbits(arr.astype(bool), bitorder="little").tobytes()


def unpack_bits(data: bytes, n: int) -> np.ndarray:
    lib = _load()
    src = _as_u8(data)
    if lib is not None:
        out = np.empty(n, dtype=np.uint8)
        lib.srt_unpack_bits(_ptr(src), n, _ptr(out))
        return out.astype(bool)
    return np.unpackbits(src, count=n, bitorder="little").astype(bool)


def crc32c(data: bytes, seed: int = 0) -> int:
    lib = _load()
    if lib is not None:
        src = _as_u8(data)
        return int(lib.srt_crc32c(_ptr(src), len(src), seed))
    # python fallback: table-driven CRC32C
    global _PY_CRC_TABLE
    if _PY_CRC_TABLE is None:
        tbl = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if (c & 1) else (c >> 1)
            tbl.append(c)
        _PY_CRC_TABLE = tbl
    c = seed ^ 0xFFFFFFFF
    for b in data:
        c = _PY_CRC_TABLE[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


_PY_CRC_TABLE = None
