"""Prefix-scan partition and binary-radix segmented sort kernels.

Two measured facts drive this module (BENCH_r08 + the kernel
microbench):

- The fused chain's end-of-program compaction is a stable
  ``argsort(~live)`` + per-column gathers — an O(n log n) sort network
  to answer an O(n) question ("where does each surviving row land?").
  :func:`partition_order` computes the identical permutation with one
  prefix scan + scatter: 3.6x the argsort at 2M rows even on the CPU
  interpret path, and the same shape of win anywhere a boolean key
  drives a sort (join match compaction, semi/anti keeps).

- ORDER BY permutations ride a variadic ``lax.sort`` whose payload
  carry cliffs at 6 lanes (ops/sort._CARRY_MAX_LANES: >20 min XLA
  compiles beyond it). :func:`lexsort_order` instead runs stable
  binary-radix passes over unsigned order keys — pass count scales
  with key *bit width*, never with payload count, so wide rows sort
  without the padding/carry blowup. Keys that cannot be radixed
  without a float bitcast (f64 is a software pair on TPU —
  ops/sortkeys module note) return None and the caller keeps the jnp
  path; the gate is a routing decision, not a semantics change.

Both kernels are stable and bit-exact against their jnp references
(differential fences in tests/test_kernels.py) and fully traceable, so
they ride inside fused-chain programs, the streaming fold, and
shard_map without changing any dispatch count.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.native import kernels as nk
from spark_rapids_tpu.ops import sortkeys


def partition_order(mask: jax.Array) -> jax.Array:
    """Stable permutation placing ``mask``-true rows first — bit-equal
    to ``jnp.argsort(~mask, stable=True)`` at O(n). The permutation is
    materialized once and every column gathers through it, preserving
    the chain compaction's count-oblivious contract."""
    n = mask.shape[0]

    def kernel(mask_ref, out_ref):
        lv = mask_ref[:]
        cs = jnp.cumsum(lv.astype(jnp.int32))
        n_true = cs[-1]
        iota = jax.lax.iota(jnp.int32, n)
        # true row i lands at its true-rank; false row i lands after
        # every true row, at its false-rank (i - trues-before-or-at-i)
        pos = jnp.where(lv, cs - 1, n_true + iota - cs)
        out_ref[pos] = iota

    return nk.pallas_call(
        kernel, out_shape=jax.ShapeDtypeStruct((n,), jnp.int32))(mask)


# -- radix lexsort ----------------------------------------------------------

# bit width of the unsigned order key per physical dtype; order_key_arrays
# only ever emits these (rank keys are int32 0/1 -> 1 bit)
_RADIX_BITS = {jnp.dtype(jnp.bool_): 1, jnp.dtype(jnp.int8): 8,
               jnp.dtype(jnp.int16): 16, jnp.dtype(jnp.int32): 32,
               jnp.dtype(jnp.int64): 64}


def _unsigned_key(k: jax.Array) -> Tuple[jax.Array, int]:
    """Order-isomorphic unsigned view of an integral key + its bit
    width. No float bitcasts (TPU f64 constraint): floats are the
    caller's fallback signal, never reach here."""
    d = jnp.dtype(k.dtype)
    bits = _RADIX_BITS[d]
    if d == jnp.dtype(jnp.bool_):
        return k.astype(jnp.uint32), 1
    if bits < 64:
        # widen then shift into non-negative range: order preserved
        return (k.astype(jnp.int64) + (1 << (bits - 1))).astype(
            jnp.uint64), bits
    # int64: flip the sign bit in the unsigned view
    return k.astype(jnp.uint64) ^ jnp.uint64(1 << 63), 64


def radix_order(keys: List[jax.Array],
                widths: Optional[List[int]] = None) -> jax.Array:
    """Stable ascending argsort of integral ``keys`` (most significant
    first) via LSD binary-radix inside one kernel. ``widths`` caps the
    per-key bit count when the caller knows the key's true range (rank
    keys are 1 bit); pass counts scale with total bits, not payloads."""
    n = keys[0].shape[0]
    ukeys, bit_list = [], []
    for i, k in enumerate(keys):
        u, b = _unsigned_key(k)
        if widths is not None and widths[i] is not None:
            b = min(b, widths[i])
        ukeys.append(u)
        bit_list.append(b)
    bits = tuple(bit_list)

    def kernel(*refs):
        out_ref = refs[-1]
        idx = jax.lax.iota(jnp.int32, n)
        # LSD: least-significant key first, low bit first; each pass is
        # a stable partition by the current bit of the key as seen
        # through the running permutation
        for kref, b in zip(reversed(refs[:-1]), reversed(bits)):
            kv = kref[:]
            for bit in range(b):
                cur = ((kv[idx] >> bit) & 1) == 0
                cs = jnp.cumsum(cur.astype(jnp.int32))
                nz = cs[-1]
                iota = jax.lax.iota(jnp.int32, n)
                pos = jnp.where(cur, cs - 1, nz + iota - cs)
                idx = jnp.zeros((n,), jnp.int32).at[pos].set(idx)
        out_ref[:] = idx

    return nk.pallas_call(
        kernel, out_shape=jax.ShapeDtypeStruct((n,), jnp.int32))(*ukeys)


def _radixable(dtypes: List[dt.DType], specs) -> bool:
    for spec in specs:
        if dtypes[spec.ordinal].is_floating:
            return False
    return True


def lexsort_order(cols, dtypes: List[dt.DType], specs,
                  num_rows, live_mask=None,
                  capacity_bits: Optional[int] = None
                  ) -> Optional[jax.Array]:
    """Kernel-backed replacement for ``sortkeys.lexsort_indices`` /
    the permutation inside ``sort_with_payloads``: the identical
    order-key arrays feed binary-radix passes instead of the variadic
    sort network. Returns None when a key needs a float bitcast (f64
    TPU constraint) — the caller falls back to the jnp path."""
    if not _radixable(dtypes, specs):
        return None
    keys = sortkeys.order_key_arrays(cols, dtypes, specs, num_rows,
                                     live_mask)
    widths: List[Optional[int]] = []
    for k in keys:
        d = jnp.dtype(k.dtype)
        if d not in _RADIX_BITS and not jnp.issubdtype(d, jnp.integer):
            return None  # float key array slipped through
        widths.append(None)
    # the leading pad/liveness rank key is 0/1 by construction
    widths[0] = 1
    return radix_order(keys, widths)
