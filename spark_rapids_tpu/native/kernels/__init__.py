"""Native Pallas kernel registry: gates, interpret-mode policy, and the
one sanctioned ``pallas_call`` entry point.

The reference accelerator's entire win lives in its native kernel layer
(cuDF's JNI surface); this package is the TPU analogue — hand-written
Pallas kernels for the ops where jit-of-jnp is the measured floor
(BENCH_r08's per-stage program attribution): the hash-join probe, row
compaction / segmented sort, and dictionary-string predicates. Three
rules hold the layer together:

1. **Gated, default-off.** Every kernel routes through ``enabled(kind)``
   reading the ``rapids.tpu.native.kernels.{enabled,join,sort,strings}``
   knobs (applied process-wide by ``runtime.device.initialize``, same
   contract as memory/retry). With the gate off, callers run the
   existing jnp implementations unchanged — the differential fences in
   tests/test_kernels.py assert bit-equality between the two.

2. **One interpret-mode decision.** Kernels never call
   ``pl.pallas_call`` directly; they call :func:`pallas_call` here,
   which sets ``interpret=True`` on any non-TPU backend. CPU CI
   therefore executes the *same kernel bodies* that compile for TPU —
   a compiled-only code path would be dead under tier-1. tpulint's
   TPU204 diagnostic fences this rule statically.

3. **Traceable by construction.** Every kernel is jit/shard_map
   composable (interpret mode lowers to XLA ops), so routing a kernel
   inside an existing fused-chain program changes zero dispatch counts
   — the q26 <= 5 dispatch fence holds with kernels on and off.
"""
from __future__ import annotations

from typing import Optional

from spark_rapids_tpu.utils import lockorder

_LOCK = lockorder.make_lock("native.kernels.config")

_DEFAULTS = {"enabled": False, "join": True, "sort": True,
             "strings": True}
_state = dict(_DEFAULTS)


def configure(enabled: Optional[bool] = None, join: Optional[bool] = None,
              sort: Optional[bool] = None,
              strings: Optional[bool] = None) -> None:
    """Set the process-wide kernel gates (None = leave unchanged)."""
    with _LOCK:
        for key, val in (("enabled", enabled), ("join", join),
                         ("sort", sort), ("strings", strings)):
            if val is not None:
                _state[key] = bool(val)


def configure_from_conf(conf) -> None:
    from spark_rapids_tpu import config as cfg

    configure(enabled=conf.get(cfg.NATIVE_KERNELS_ENABLED),
              join=conf.get(cfg.NATIVE_KERNELS_JOIN),
              sort=conf.get(cfg.NATIVE_KERNELS_SORT),
              strings=conf.get(cfg.NATIVE_KERNELS_STRINGS))


def reset_config() -> None:
    """Restore defaults (test teardown; runtime.device.shutdown)."""
    with _LOCK:
        _state.update(_DEFAULTS)


def enabled(kind: str) -> bool:
    """Is the ``kind`` kernel ('join' | 'sort' | 'strings') active?"""
    with _LOCK:
        return _state["enabled"] and _state[kind]


def cache_token() -> tuple:
    """Hashable gate state for program/jit cache keys: any compiled
    program whose trace read a gate must key on this, or a mid-process
    knob flip would serve the stale routing."""
    with _LOCK:
        return (_state["enabled"], _state["join"], _state["sort"],
                _state["strings"])


def interpret_mode() -> bool:
    """True when kernels must run through the Pallas interpreter: any
    backend that is not a real TPU (CPU CI, GPU). The decision is made
    once per process — backends don't change under a running query."""
    global _interpret
    if _interpret is None:
        try:
            import jax

            _interpret = jax.default_backend() != "tpu"
        except Exception:  # pragma: no cover - no backend at all
            _interpret = True
    return _interpret


_interpret: Optional[bool] = None


def pallas_call(kernel, *, out_shape, grid=None, **kwargs):
    """The one sanctioned ``pl.pallas_call`` wrapper: resolves the
    pallas module through the version shims and pins ``interpret`` to
    the process-wide policy. Direct ``pl.pallas_call`` sites elsewhere
    are a TPU204 lint error (they would silently dead-code the CPU CI
    leg or crash a TPU-compiled kernel on the CPU backend)."""
    from spark_rapids_tpu.shims import get_shims

    pl = get_shims().pallas()
    if pl is None:  # pragma: no cover - ancient jax
        raise RuntimeError("pallas unavailable in this jax version")
    if grid is not None:
        kwargs["grid"] = grid
    return pl.pallas_call(kernel, out_shape=out_shape,
                          interpret=interpret_mode(), **kwargs)
