"""Open-addressing hash-table build + probe kernel for equi-joins.

The jnp join probes pay one of two costs per stream batch (the
dense/hash dichotomy in ops/join.py + execs/fused._apply_join):

- dense mode: a prep-time inverse table over the key's value range —
  only exists below the span ceiling, single integral keys only;
- hash mode: ``searchsorted`` into the hash-sorted build — a ~17-step
  binary-search gather loop per probe, re-paid every batch.

This kernel replaces both with ONE device-resident bucketed table,
built once per build side and probed across every stream batch,
composite keys included (they are already folded into the 64-bit row
hash):

  build:  the hash-sorted build column (already produced by
          ``_prep_build_arrays`` / ``_probe_counts``) is viewed
          unsigned; its top ``table_bits`` bits are the bucket id, so
          bucket membership is a *contiguous slice* of the sorted
          array — the open-addressing displacement is exactly the
          bucket occupancy, no re-sort and no insertion loop. The
          table is a bucket-offset array ``part`` (one int32 per
          bucket, capacity 2x rows => load factor <= 0.5) plus the
          already-resident sorted hashes.
  probe:  one kernel: bucket id by shift, two offset gathers, then a
          short scan of the bucket (``max_seg`` iterations — the max
          bucket occupancy, measured at build, ~Poisson(0.5) tail for
          unique keys; equal-hash duplicates sit contiguously so the
          scan also yields the duplicate match count directly).

Exactness is inherited, not probabilistic: bucket slices are exact by
construction, equal hashes are contiguous, and the caller keeps the
same exact-key verification it applies to the searchsorted probe (the
leftmost-hash-match semantics are identical, so the differential
fence dense == hash == pallas holds bit-for-bit).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.native import kernels as nk


class ProbeTable(NamedTuple):
    """Device-resident probe state derived from the hash-sorted build.

    ``u_sorted``: the build hashes viewed unsigned with the sign bit
    flipped — order-isomorphic to the signed sort, so positions are
    SHARED with the hash-sorted build arrays and no second sort or
    rotation exists; ``part``: int32[2^table_bits + 1] bucket offsets
    into its valid region; ``max_seg``: max bucket occupancy = the
    probe scan bound; ``n_valid``: live build rows. Every field is a
    traceable array (the tuple is a clean pytree — ``table_bits`` is
    recovered from ``part``'s static shape), so the table builds inside
    whatever program prepares the build side, crosses jit boundaries
    freely, and costs zero extra dispatches."""

    u_sorted: jax.Array
    part: jax.Array
    max_seg: jax.Array
    n_valid: jax.Array

    @property
    def table_bits(self) -> int:
        return (self.part.shape[0] - 1).bit_length() - 1


def table_bits_for(capacity: int) -> int:
    """Bucket-count exponent for a build of ``capacity`` slots: 2x
    slots => load factor <= 0.5 with whole-array buckets."""
    bits = 1
    while (1 << bits) < 2 * max(capacity, 1):
        bits += 1
    return bits


def _unsigned(h: jax.Array) -> jax.Array:
    # order-isomorphic unsigned view of the int64 hash
    return h.astype(jnp.uint64) ^ jnp.uint64(1 << 63)


def unsigned_sorted(sh: jax.Array, n_valid: jax.Array) -> jax.Array:
    """The build hashes in the sign-flipped unsigned view (ascending,
    same positions as the signed sort); invalid slots park at u64 max
    (top bucket id is excluded from ``part``)."""
    iota = jnp.arange(sh.shape[0], dtype=jnp.int32)
    return jnp.where(iota < n_valid, _unsigned(sh),
                     jnp.uint64(0xFFFFFFFFFFFFFFFF))


def build_table(sh: jax.Array, n_valid, table_bits: int) -> ProbeTable:
    """Build the bucket-offset table from the hash-sorted build column
    ``sh`` (signed ascending, padding rows at int64 max past
    ``n_valid``). Pure jnp — it runs once, inside the same program
    that sorted the build."""
    cap = sh.shape[0]
    cap_t = 1 << table_bits
    n_valid = jnp.asarray(n_valid, jnp.int32)
    u_s = unsigned_sorted(sh, n_valid)
    iota = jnp.arange(cap, dtype=jnp.int32)
    home = jnp.where(iota < n_valid,
                     (u_s >> (64 - table_bits)).astype(jnp.int32), cap_t)
    # part[j] = #valid rows with bucket < j, via histogram + prefix sum
    # (invalid rows land in the sentinel bin past the table)
    hist = jnp.zeros((cap_t + 2,), jnp.int32).at[home + 1].add(1)
    part = jnp.cumsum(hist)[:cap_t + 1].astype(jnp.int32)
    max_seg = jnp.max(part[1:] - part[:-1])
    return ProbeTable(u_s, part, max_seg, n_valid)


def probe(table: ProbeTable, h_p: jax.Array):
    """Probe every stream hash against the device-resident table.

    Returns ``(lo, counts)`` — the exact contract of the searchsorted
    probe it replaces: ``lo`` is the first hash-match position in the
    hash-sorted build arrays (the unsigned view shares positions with
    the signed sort) and ``counts`` the match-run length (0 = no hash
    match)."""
    cap = table.u_sorted.shape[0]
    n = h_p.shape[0]
    shift = 64 - table.table_bits
    up = _unsigned(h_p)

    def kernel(u_ref, part_ref, up_ref, seg_ref, lo_ref, cnt_ref):
        upv = up_ref[:]
        hm = (upv >> shift).astype(jnp.int32)
        start = part_ref[hm]
        end = part_ref[hm + 1]

        def body(t, carry):
            off, cnt = carry
            idx = jnp.clip(start + t, 0, cap - 1)
            ut = u_ref[idx]
            in_seg = (start + t) < end
            off = off + ((ut < upv) & in_seg)
            cnt = cnt + ((ut == upv) & in_seg)
            return off, cnt

        zero = jnp.zeros((n,), jnp.int32)
        off, cnt = jax.lax.fori_loop(0, seg_ref[0], body, (zero, zero))
        lo_ref[:] = start + off
        cnt_ref[:] = cnt

    lo_u, counts = nk.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((n,), jnp.int32),
                   jax.ShapeDtypeStruct((n,), jnp.int32)))(
        table.u_sorted, table.part, up,
        jnp.reshape(table.max_seg, (1,)).astype(jnp.int32))
    return lo_u.astype(jnp.int32), counts
