"""Dictionary-string predicate and substring kernels over char tables.

The jnp string strategy (expressions/strings.py) factors every string
function into a per-dictionary-entry HOST transform (a Python loop over
unique values) plus a device gather by code. That keeps row-scale work
on device, but the host loop is O(cardinality) *Python* — on a 100k+
entry dictionary a single ``contains`` costs tens of milliseconds of
interpreter time per batch, serialized on the driver thread.

These kernels move the per-entry work onto the device: the dictionary
is encoded ONCE into a padded code+offset char table (uint8 chars +
per-entry byte lengths — never a per-row character matrix; the table
is O(cardinality * max_len), not O(rows)), and one Pallas kernel scans
it for every entry in parallel. The device-side gather by code is
unchanged.

Semantics guardrails (fall back to the host path, never approximate):

- byte-level windows are substring-exact for UTF-8 (a UTF-8 sequence
  never matches inside another code point), so contains / startswith /
  endswith / LIKE's ``%``-segments work on raw bytes for ANY input;
- LIKE ``_`` matches one *character*, and substring counts characters,
  so those routes require ASCII-only dictionary entries (checked at
  encode time);
- oversized tables (very long entries / huge dictionaries) fall back
  rather than build a pathological window tensor.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.native import kernels as nk

# window-tensor budget: n_entries * n_windows * needle_len bytes
_WINDOW_BUDGET = 64 << 20
# max padded entry length the kernels will scan
_MAX_ENTRY_LEN = 512


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def encode_dictionary(dic: np.ndarray
                      ) -> Optional[Tuple[np.ndarray, np.ndarray, bool]]:
    """(chars uint8[n, L], lens int32[n], ascii_only) — the padded char
    table for a dictionary, or None when an entry exceeds the scan
    ceiling. Matches the host transforms' ``str(entry)`` coercion."""
    n = len(dic)
    encoded = [str(s).encode("utf-8") for s in dic]
    maxlen = max((len(b) for b in encoded), default=0)
    if maxlen > _MAX_ENTRY_LEN:
        return None
    L = _pow2(max(maxlen, 1))
    chars = np.zeros((n, L), dtype=np.uint8)
    lens = np.zeros((n,), dtype=np.int32)
    for i, b in enumerate(encoded):
        chars[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
        lens[i] = len(b)
    ascii_only = maxlen == 0 or int(chars.max()) < 0x80
    return chars, lens, ascii_only


def _windows(ch: jax.Array, m: int) -> jax.Array:
    """(n, P, m) sliding byte windows of the char table, P = L - m + 1."""
    L = ch.shape[1]
    grid = (jnp.arange(L - m + 1, dtype=jnp.int32)[:, None] +
            jnp.arange(m, dtype=jnp.int32)[None, :])
    return jnp.take(ch, grid, axis=1)


def _match_table(chars: np.ndarray, lens: np.ndarray, kind: str,
                 needle: bytes) -> Optional[jax.Array]:
    """bool[n] per-entry predicate table, computed on device."""
    n, L = chars.shape
    m = len(needle)
    if m > L:
        # needle longer than any entry: nothing matches
        return jnp.zeros((n,), dtype=jnp.bool_)
    if m == 0:
        # '' is a prefix/suffix/substring of everything
        return jnp.ones((n,), dtype=jnp.bool_)
    if kind == "contains" and n * (L - m + 1) * m > _WINDOW_BUDGET:
        return None
    nd = jnp.asarray(np.frombuffer(needle, dtype=np.uint8))
    ch = jnp.asarray(chars)
    ln = jnp.asarray(lens)

    def kernel(ch_ref, ln_ref, nd_ref, out_ref):
        c = ch_ref[:]
        lv = ln_ref[:]
        ndv = nd_ref[:]
        if kind == "starts":
            hit = jnp.all(c[:, :m] == ndv[None, :], axis=1) & (lv >= m)
        elif kind == "ends":
            # per-entry window at len - m
            cols = (lv[:, None] - m +
                    jnp.arange(m, dtype=jnp.int32)[None, :])
            w = jnp.take_along_axis(c, jnp.clip(cols, 0, L - 1), axis=1)
            hit = jnp.all(w == ndv[None, :], axis=1) & (lv >= m)
        else:  # contains
            w = _windows(c, m) == ndv[None, None, :]
            p = jnp.arange(w.shape[1], dtype=jnp.int32)
            ok = jnp.all(w, axis=2) & (p[None, :] + m <= lv[:, None])
            hit = jnp.any(ok, axis=1)
        out_ref[:] = hit

    return nk.pallas_call(
        kernel, out_shape=jax.ShapeDtypeStruct((n,), jnp.bool_))(
        ch, ln, nd)


def _parse_like(pattern: str, escape: str
                ) -> Optional[List[Tuple[bool, List]]]:
    """LIKE pattern -> (anchored_start, anchored_end, segments), each
    segment a list of (byte, is_wildcard) tokens; None for patterns the
    kernel must not handle (non-ASCII with ``_`` is checked later)."""
    tokens: List = []  # byte int | None (= one-char wildcard) | "%"
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == escape and i + 1 < len(pattern):
            tokens.extend(pattern[i + 1].encode("utf-8"))
            i += 2
            continue
        if ch == "%":
            tokens.append("%")
        elif ch == "_":
            tokens.append(None)
        else:
            tokens.extend(ch.encode("utf-8"))
        i += 1
    segments: List[List] = [[]]
    for t in tokens:
        if t == "%":
            segments.append([])
        else:
            segments[-1].append(t)
    return segments


def _like_table(chars: np.ndarray, lens: np.ndarray, pattern: str,
                escape: str, ascii_only: bool) -> Optional[jax.Array]:
    """bool[n] LIKE table via greedy segment matching (greedy is exact
    for %-separated segments). ``_`` wildcards require an ASCII
    dictionary (byte == character)."""
    segments = _parse_like(pattern, escape)
    has_underscore = any(t is None for seg in segments for t in seg)
    if has_underscore and not ascii_only:
        return None
    n, L = chars.shape
    if any(len(seg) > L for seg in segments):
        # a segment longer than every entry can never match...
        # unless entries shorter than the pattern exist either way:
        # no entry can contain it
        return jnp.zeros((n,), dtype=jnp.bool_)
    # segment list semantics: pattern "a%b" -> ["a","b"]; leading %
    # yields an empty first segment, trailing % an empty last one
    win_cost = max((n * (L - len(s) + 1) * max(len(s), 1)
                    for s in segments), default=0)
    if win_cost > _WINDOW_BUDGET:
        return None

    seg_arrays = []
    for seg in segments:
        sb = np.array([0 if t is None else t for t in seg],
                      dtype=np.uint8)
        wild = np.array([t is None for t in seg], dtype=bool)
        seg_arrays.append((sb, wild))

    ch = jnp.asarray(chars)
    ln = jnp.asarray(lens)
    # anchoring comes from the token stream, not the raw text — a
    # trailing *escaped* % is a literal, not a wildcard
    raw = pattern
    toks = []
    i = 0
    while i < len(raw):
        if raw[i] == escape and i + 1 < len(raw):
            toks.append("lit")
            i += 2
            continue
        toks.append("%" if raw[i] == "%" else "lit")
        i += 1
    first_anchored = not (toks and toks[0] == "%")
    last_anchored = not (toks and toks[-1] == "%")

    nseg = len(seg_arrays)

    def kernel(ch_ref, ln_ref, *rest):
        out_ref = rest[-1]
        seg_refs = rest[:nseg]
        wild_refs = rest[nseg:2 * nseg]
        c = ch_ref[:]
        lv = ln_ref[:]
        ok = jnp.ones((n,), dtype=jnp.bool_)
        cur = jnp.zeros((n,), dtype=jnp.int32)
        for si, (sb, wild) in enumerate(seg_arrays):
            m = len(sb)
            sref = seg_refs[si]
            if m == 0:
                continue
            sv = sref[:]
            wv = wild_refs[si][:] != 0
            is_first = si == 0
            is_last = si == len(seg_arrays) - 1
            if is_first and first_anchored:
                w = (c[:, :m] == sv[None, :]) | wv[None, :]
                ok = ok & jnp.all(w, axis=1) & (lv >= m)
                cur = jnp.full((n,), m, dtype=jnp.int32)
            elif is_last and last_anchored:
                cols = (lv[:, None] - m +
                        jnp.arange(m, dtype=jnp.int32)[None, :])
                w = (jnp.take_along_axis(c, jnp.clip(cols, 0, L - 1),
                                         axis=1) == sv[None, :]) | \
                    wv[None, :]
                ok = ok & jnp.all(w, axis=1) & (lv - m >= cur)
                cur = lv
            else:
                w = (_windows(c, m) == sv[None, None, :]) | \
                    wv[None, None, :]
                p = jnp.arange(w.shape[1], dtype=jnp.int32)
                valid = (jnp.all(w, axis=2) &
                         (p[None, :] + m <= lv[:, None]) &
                         (p[None, :] >= cur[:, None]))
                found = jnp.any(valid, axis=1)
                first = jnp.argmax(valid, axis=1).astype(jnp.int32)
                ok = ok & found
                cur = first + m
        if len(segments) == 1 and first_anchored and last_anchored:
            # no % at all: exact-length match
            ok = ok & (lv == len(seg_arrays[0][0]))
        out_ref[:] = ok

    def _pad1(a):
        # zero-length operands are invalid; empty segments are
        # statically skipped in the kernel so the dummy is never read
        return jnp.asarray(a if len(a) else np.zeros((1,), a.dtype))

    args = ([ch, ln] + [_pad1(sb) for sb, _ in seg_arrays] +
            [_pad1(w.astype(np.uint8)) for _, w in seg_arrays])
    return nk.pallas_call(
        kernel, out_shape=jax.ShapeDtypeStruct((n,), jnp.bool_))(*args)


# -- eval-layer routing -----------------------------------------------------


def predicate_colv(v, kind: str, needle: str,
                   escape: Optional[str] = None):
    """Kernel route for a string predicate over a dictionary column:
    returns the gathered boolean ColV, or None to keep the host path
    (gate off, no dictionary, or outside the kernel's contract)."""
    if not nk.enabled("strings"):
        return None
    scol = getattr(v, "scol", None)
    if scol is None or len(scol.dictionary) == 0:
        return None
    enc = encode_dictionary(scol.dictionary)
    if enc is None:
        return None
    chars, lens, ascii_only = enc
    if kind == "like":
        table = _like_table(chars, lens, needle, escape or "\\",
                            ascii_only)
    else:
        table = _match_table(chars, lens, kind,
                             needle.encode("utf-8"))
    if table is None:
        return None
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.expressions.base import ColV

    data = jnp.take(table, v.data, mode="clip")
    return ColV(dt.BOOLEAN, data, v.validity)


def substring_colv(v, pos: int, length: Optional[int]):
    """Kernel route for substring(str, pos, len): the slice runs on
    device over the char table (ASCII dictionaries: byte == character),
    the host only decodes the already-sliced entries into the new
    dictionary. Returns ColV or None for the host path."""
    if not nk.enabled("strings"):
        return None
    scol = getattr(v, "scol", None)
    if scol is None or len(scol.dictionary) == 0:
        return None
    enc = encode_dictionary(scol.dictionary)
    if enc is None or not enc[2]:
        return None
    chars, lens = enc[0], enc[1]
    n, L = chars.shape
    ch = jnp.asarray(chars)
    ln = jnp.asarray(lens)

    def kernel(ch_ref, ln_ref, out_ref, olen_ref):
        c = ch_ref[:]
        lv = ln_ref[:]
        if pos > 0:
            start = jnp.full((n,), pos - 1, dtype=jnp.int32)
        elif pos < 0:
            start = lv + pos
        else:
            start = jnp.zeros((n,), dtype=jnp.int32)
        end = lv if length is None else start + length
        start_c = jnp.clip(start, 0, lv)
        end_c = jnp.clip(jnp.minimum(end, lv), 0, lv)
        out_len = jnp.maximum(end_c - start_c, 0)
        cols = start_c[:, None] + jnp.arange(L, dtype=jnp.int32)[None, :]
        sliced = jnp.take_along_axis(c, jnp.clip(cols, 0, L - 1), axis=1)
        keep = jnp.arange(L, dtype=jnp.int32)[None, :] < out_len[:, None]
        out_ref[:] = jnp.where(keep, sliced, 0).astype(jnp.uint8)
        olen_ref[:] = out_len

    out_chars, out_lens = nk.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((n, L), jnp.uint8),
                   jax.ShapeDtypeStruct((n,), jnp.int32)))(ch, ln)
    oc = np.asarray(jax.device_get(out_chars))
    ol = np.asarray(jax.device_get(out_lens))
    transformed = np.array(
        [oc[i, :ol[i]].tobytes().decode("utf-8") for i in range(n)],
        dtype=object)
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.column import StringColumn
    from spark_rapids_tpu.expressions.base import ColV

    new_dict, inv = np.unique(transformed.astype(str),
                              return_inverse=True)
    remap = jnp.asarray(inv.astype(np.int32))
    codes = jnp.take(remap, v.data, mode="clip")
    sc = StringColumn(codes, new_dict.astype(object), v.validity)
    return ColV(dt.STRING, codes, v.validity, sc)
