"""SQL front end: parse a SELECT statement into plan nodes.

The reference rides on Spark SQL for parsing/analysis and only replaces
physical planning; a STANDALONE framework needs its own entry point, so
this package provides the SQL surface the engine's node vocabulary can
express (the TPC-H/DS/xBB-like query shapes):

    SELECT [DISTINCT] exprs FROM t [JOIN u ON ...] [WHERE ...]
    [GROUP BY ...] [HAVING ...] [ORDER BY ...] [LIMIT n]

with arithmetic/comparison/boolean expressions, CASE WHEN, IN, BETWEEN,
LIKE, IS [NOT] NULL, casts, and the aggregate/scalar function names in
``planner._FUNCTIONS``. Tables resolve through the session catalog
(``Session.sql`` / ``create_temp_view``). Everything else raises
``SqlError`` — unsupported SQL fails loudly at parse/plan time, never
silently misplans.
"""
from spark_rapids_tpu.sql.parser import SqlError, parse  # noqa: F401
from spark_rapids_tpu.sql.planner import plan_statement  # noqa: F401
