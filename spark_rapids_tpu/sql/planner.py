"""AST -> plan-node planner with catalog-based name resolution.

The analysis layer Spark provides for the reference: resolve column
names against the FROM scope, split join conditions into equi-keys +
residual, stage aggregates (GROUP BY / HAVING / aggregate-of-expression
selects), then wrap DISTINCT / ORDER BY / LIMIT. Produces the same plan
nodes the DataFrame API builds, so everything downstream (override
tagging, CPU oracle, explain) is shared.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.expressions import aggregates as A
from spark_rapids_tpu.expressions import arithmetic as ar
from spark_rapids_tpu.expressions import conditional as cond
from spark_rapids_tpu.expressions import datetime as dte
from spark_rapids_tpu.expressions import math as mth
from spark_rapids_tpu.expressions import predicates as pr
from spark_rapids_tpu.expressions import strings as st
from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Expression, Literal)
from spark_rapids_tpu.expressions.cast import Cast
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.plan import nodes as pn
from spark_rapids_tpu.sql.parser import SqlError

_AGG_FNS = {"sum", "count", "avg", "min", "max", "first", "last",
            "stddev_samp", "stddev", "std", "stddev_pop",
            "var_samp", "variance", "var_pop"}

_CAST_TYPES = {
    "tinyint": dt.INT8, "smallint": dt.INT16,
    "int": dt.INT32, "integer": dt.INT32,
    "bigint": dt.INT64, "long": dt.INT64,
    "float": dt.FLOAT32, "real": dt.FLOAT32,
    "double": dt.FLOAT64,
    "string": dt.STRING, "varchar": dt.STRING,
    "date": dt.DATE, "timestamp": dt.TIMESTAMP,
    "boolean": dt.BOOLEAN,
}


def _date_days(s: str) -> int:
    try:
        return int((np.datetime64(s) -
                    np.datetime64("1970-01-01")).astype(int))
    except Exception:
        raise SqlError(f"bad DATE literal {s!r}")


def _ts_us(s: str) -> int:
    try:
        return int(np.datetime64(s, "us").astype(np.int64))
    except Exception:
        raise SqlError(f"bad TIMESTAMP literal {s!r}")


class _Scope:
    """Resolved FROM output: [(table_alias, column_name, dtype)]."""

    def __init__(self, entries: List[Tuple[Optional[str], str, dt.DType]]):
        self.entries = entries

    def resolve(self, tab: Optional[str], name: str) -> Tuple[int, dt.DType]:
        hits = [(i, t) for i, (a, n, t) in enumerate(self.entries)
                if n.lower() == name.lower() and
                (tab is None or (a or "").lower() == tab.lower())]
        if not hits:
            raise SqlError(f"column {tab + '.' if tab else ''}{name} "
                           "not found")
        if len(hits) > 1:
            raise SqlError(f"column {name} is ambiguous; qualify it")
        return hits[0]

    @property
    def width(self) -> int:
        return len(self.entries)


# ---------------------------------------------------------------------------
# expression planning
# ---------------------------------------------------------------------------


def _fn_scalar(name: str, args: List[Expression]) -> Expression:
    def need(n):
        if len(args) != n:
            raise SqlError(f"{name}() takes {n} arguments")

    if name == "abs":
        need(1)
        return ar.Abs(args[0])
    if name == "sqrt":
        need(1)
        return mth.Sqrt(args[0])
    if name in ("floor", "ceil"):
        need(1)
        return (mth.Floor if name == "floor" else mth.Ceil)(args[0])
    if name in ("year", "month", "quarter", "weekday", "dayofweek"):
        need(1)
        klass = {"year": dte.Year, "month": dte.Month,
                 "quarter": dte.Quarter, "weekday": dte.WeekDay,
                 "dayofweek": dte.DayOfWeek}[name]
        return klass(args[0])
    if name in ("day", "dayofmonth"):
        need(1)
        return dte.DayOfMonth(args[0])
    if name in ("upper", "lower", "trim", "ltrim", "rtrim", "reverse",
                "initcap"):
        need(1)
        klass = {"upper": st.Upper, "lower": st.Lower,
                 "trim": st.StringTrim, "ltrim": st.StringTrimLeft,
                 "rtrim": st.StringTrimRight, "reverse": st.Reverse,
                 "initcap": st.InitCap}[name]
        return klass(args[0])
    if name == "length":
        need(1)
        return st.Length(args[0])
    if name in ("substr", "substring"):
        if len(args) not in (2, 3):
            raise SqlError("substring(col, pos[, len])")
        pos = _want_int_lit(args[1], "substring position")
        ln = _want_int_lit(args[2], "substring length") \
            if len(args) == 3 else None
        return st.Substring(args[0], pos, ln)
    if name == "concat":
        return st.ConcatStrings(args)
    if name == "coalesce":
        return cond.Coalesce(args)
    if name == "nvl":
        need(2)
        return cond.Nvl(args[0], args[1])
    if name == "pow" or name == "power":
        need(2)
        return mth.Pow(args[0], args[1])
    if name == "round":
        if len(args) not in (1, 2):
            raise SqlError("round(col[, scale])")
        scale = _want_int_lit(args[1], "round scale") if len(args) == 2 \
            else 0
        return mth.Round(args[0], scale)
    if name == "pmod":
        need(2)
        return ar.Pmod(args[0], args[1])
    if name == "datediff":
        need(2)
        return dte.DateDiff(_as_date(args[0]), _as_date(args[1]))
    if name in ("unix_timestamp", "to_unix_timestamp"):
        # format argument accepted and ignored for date/timestamp inputs
        if not args:
            raise SqlError(f"{name}(col[, fmt])")
        return dte.UnixTimestamp(args[0])
    if name == "to_date":
        need(1)
        return _as_date(args[0])
    if name == "nullif":
        need(2)
        return cond.If(pr.EqualTo(args[0], args[1]),
                       Literal(None, args[0].dtype), args[0])
    if name in ("greatest", "least"):
        if len(args) < 2:
            raise SqlError(f"{name}() takes 2+ arguments")
        if any(a.dtype is dt.STRING for a in args):
            raise SqlError(f"{name}() over strings is unsupported")
        return (cond.Greatest if name == "greatest" else
                cond.Least)(args)
    if name in ("exp", "log", "log2", "log10", "sin", "cos", "tan"):
        need(1)
        klass = {"exp": mth.Exp, "log": mth.Log, "log2": mth.Log2,
                 "log10": mth.Log10, "sin": mth.Sin, "cos": mth.Cos,
                 "tan": mth.Tan}[name]
        return klass(args[0])
    raise SqlError(f"unknown function {name}()")


def _as_date(e: Expression) -> Expression:
    """Coerce to DATE: string literals parse eagerly, string columns cast."""
    if e.dtype is dt.DATE:
        return e
    if isinstance(e, Literal) and isinstance(e.value, str):
        return Literal(_date_days(e.value), dt.DATE)
    return Cast(e, dt.DATE)


def _want_int_lit(e: Expression, what: str) -> int:
    if isinstance(e, Literal) and isinstance(e.value, int):
        return e.value
    raise SqlError(f"{what} must be an integer literal")


def _cmp(op: str, lhs: Expression, rhs: Expression) -> Expression:
    # Spark coerces string literals compared against date/timestamp
    # columns; TPC query texts lean on it ("d_date > '2002-01-02'")
    def coerce(a, b):
        if a.dtype in (dt.DATE, dt.TIMESTAMP) and isinstance(b, Literal) \
                and isinstance(b.value, str):
            return Literal(_date_days(b.value) if a.dtype is dt.DATE
                           else _ts_us(b.value), a.dtype)
        return b

    rhs = coerce(lhs, rhs)
    lhs = coerce(rhs, lhs)
    if op == "=":
        return pr.EqualTo(lhs, rhs)
    if op in ("<>", "!="):
        return pr.Not(pr.EqualTo(lhs, rhs))
    return {"<": pr.LessThan, "<=": pr.LessThanOrEqual,
            ">": pr.GreaterThan, ">=": pr.GreaterThanOrEqual}[op](lhs, rhs)


class _ExprPlanner:
    """Plans value expressions against a scope; ``env`` maps canonical
    AST reprs to output ordinals (the post-aggregation namespace)."""

    def __init__(self, scope: _Scope,
                 env: Optional[Dict[str, Tuple[int, dt.DType]]] = None,
                 allow_aggs: bool = False):
        self.scope = scope
        self.env = env or {}
        self.allow_aggs = allow_aggs

    def plan(self, ast) -> Expression:
        key = repr(ast)
        if key in self.env:
            i, t = self.env[key]
            return BoundReference(i, t)
        kind = ast[0]
        if kind == "col":
            _, tab, name = ast
            i, t = self.scope.resolve(tab, name)
            return BoundReference(i, t)
        if kind == "lit":
            return self._literal(ast)
        if kind == "neg":
            e = self.plan(ast[1])
            if isinstance(e, Literal) and isinstance(e.value, (int, float)):
                return Literal(-e.value)
            return ar.UnaryMinus(e)
        if kind == "interval":
            raise SqlError("INTERVAL only valid in date +/- interval")
        if kind == "arith":
            _, op, l, r = ast
            # date +/- INTERVAL 'n' DAY
            if isinstance(r, tuple) and r[0] == "interval" and \
                    op in ("+", "-"):
                base = _as_date(self.plan(l))
                n = r[1] if op == "+" else -r[1]
                if isinstance(base, Literal):
                    return Literal(base.value + n, dt.DATE)
                return (dte.DateAdd if op == "+" else
                        dte.DateSub)(base, Literal(abs(n), dt.INT32))
            if isinstance(l, tuple) and l[0] == "interval" and op == "+":
                base = _as_date(self.plan(r))
                if isinstance(base, Literal):
                    return Literal(base.value + l[1], dt.DATE)
                return dte.DateAdd(base, Literal(l[1], dt.INT32))
            lhs, rhs = self.plan(l), self.plan(r)
            if isinstance(lhs, Literal) and isinstance(rhs, Literal) \
                    and lhs.value is not None and rhs.value is not None \
                    and isinstance(lhs.value, (int, float)) \
                    and isinstance(rhs.value, (int, float)) \
                    and op in ("+", "-", "*", "/"):
                # constant fold: IN-lists and join keys expect literals
                # ("d_year IN (2001, (2001 + 1))"), and scalar-only
                # subtrees must not reach the jit tracer ("2.0 / 3.0")
                if op == "/":
                    if rhs.value == 0:
                        return Literal(None, dt.FLOAT64)
                    return Literal(float(lhs.value) / float(rhs.value))
                v = {"+": lambda a, b: a + b, "-": lambda a, b: a - b,
                     "*": lambda a, b: a * b}[op](lhs.value, rhs.value)
                return Literal(v)
            klass = {"+": ar.Add, "-": ar.Subtract, "*": ar.Multiply,
                     "/": ar.Divide, "%": ar.Remainder}[op]
            return klass(lhs, rhs)
        if kind == "concat":
            lhs, rhs = self.plan(ast[1]), self.plan(ast[2])
            if isinstance(lhs, Literal) and isinstance(rhs, Literal) \
                    and isinstance(lhs.value, str) \
                    and isinstance(rhs.value, str):
                return Literal(lhs.value + rhs.value)
            # flatten chains into one ConcatStrings (a || b || c)
            parts = []
            for e in (lhs, rhs):
                parts.extend(e.children if isinstance(e, st.ConcatStrings)
                             else [e])
            return st.ConcatStrings(parts)
        if kind == "cmp":
            _, op, l, r = ast
            return _cmp(op, self.plan(l), self.plan(r))
        if kind == "and":
            return pr.And(self.plan(ast[1]), self.plan(ast[2]))
        if kind == "or":
            return pr.Or(self.plan(ast[1]), self.plan(ast[2]))
        if kind == "not":
            return pr.Not(self.plan(ast[1]))
        if kind == "isnull":
            e = self.plan(ast[1])
            return pr.IsNotNull(e) if ast[2] else pr.IsNull(e)
        if kind == "between":
            e = self.plan(ast[1])
            lo = self.plan(ast[2])
            hi = self.plan(ast[3])
            return pr.And(pr.GreaterThanOrEqual(e, lo),
                          pr.LessThanOrEqual(e, hi))
        if kind == "in":
            e = self.plan(ast[1])
            vals = [self.plan(v) for v in ast[2]]
            if not all(isinstance(v, Literal) for v in vals):
                raise SqlError("IN list must contain literals")
            return pr.In(e, vals)
        if kind == "like":
            e = self.plan(ast[1])
            pat = self.plan(ast[2])
            if not (isinstance(pat, Literal) and
                    isinstance(pat.value, str)):
                raise SqlError("LIKE pattern must be a string literal")
            return st.Like(e, pat.value)
        if kind == "case":
            _, whens, els = ast
            null_ast = ("lit", None, "null")
            pairs = [(self.plan(c),
                      None if v == null_ast else self.plan(v))
                     for c, v in whens]
            els_e = None if (els is None or els == null_ast) \
                else self.plan(els)
            # bare NULL branches type from the first typed branch
            # ("CASE WHEN m = 0 THEN null ELSE s/m END")
            typed = [v for _c, v in pairs if v is not None]
            if els_e is not None:
                typed.append(els_e)
            if not typed:
                raise SqlError("CASE with all-NULL branches is untyped")
            nt = typed[0].dtype
            pairs = [(c, Literal(None, nt) if v is None else v)
                     for c, v in pairs]
            if els_e is None:
                els_e = Literal(None, nt)
            return cond.CaseWhen(pairs, els_e)
        if kind == "cast":
            to = _CAST_TYPES.get(ast[2])
            if to is None:
                raise SqlError(f"unknown cast type {ast[2]!r}")
            e = self.plan(ast[1])
            # fold literal casts (scalar-only subtrees must not reach
            # the jit tracer — Cast evaluates scalars with float()/int())
            if isinstance(e, Literal) and isinstance(e.value, str):
                if to is dt.DATE:
                    return Literal(_date_days(e.value), dt.DATE)
                if to is dt.TIMESTAMP:
                    return Literal(_ts_us(e.value), dt.TIMESTAMP)
            if isinstance(e, Literal) and \
                    isinstance(e.value, (int, float, bool)):
                if to.is_floating:
                    return Literal(float(e.value), to)
                if to.is_integral:
                    return Literal(int(e.value), to)
            return Cast(e, to)
        if kind == "call":
            _, name, distinct, args = ast
            if name in _AGG_FNS:
                raise SqlError(
                    f"aggregate {name}() not allowed here")
            if distinct:
                raise SqlError("DISTINCT only applies to aggregates")
            return _fn_scalar(name, [self.plan(a) for a in args])
        if kind == "star":
            raise SqlError("* only allowed as a bare select item or "
                           "inside count(*)")
        raise SqlError(f"unsupported expression {kind!r}")

    def _literal(self, ast) -> Expression:
        _, v, k = ast
        if k == "date":
            return Literal(_date_days(v), dt.DATE)
        if k == "timestamp":
            return Literal(_ts_us(v), dt.TIMESTAMP)
        if k == "null":
            return Literal(None)
        return Literal(v)


def _plan_agg_call(ast, scope: _Scope,
                   env=None) -> A.AggregateFunction:
    _, name, distinct, args = ast
    ep = _ExprPlanner(scope, env)
    if name == "count":
        if args and args[0] != ("star",):
            arg = ep.plan(args[0])
            if isinstance(arg, Literal) and arg.value is not None \
                    and not distinct:
                return A.Count()  # count(1) == count(*)
            return A.Count(arg, distinct=distinct)
        if distinct:
            raise SqlError("count(DISTINCT *) is unsupported")
        return A.Count()
    if len(args) != 1:
        raise SqlError(f"{name}() takes one argument")
    arg = ep.plan(args[0])
    if name == "sum":
        return A.Sum(arg, distinct=distinct)
    if distinct:
        raise SqlError(f"{name}(DISTINCT) is unsupported")
    return {"avg": A.Average, "min": A.Min, "max": A.Max,
            "first": A.First, "last": A.Last,
            "stddev_samp": A.StddevSamp, "stddev": A.StddevSamp,
            "std": A.StddevSamp, "stddev_pop": A.StddevPop,
            "var_samp": A.VarianceSamp, "variance": A.VarianceSamp,
            "var_pop": A.VariancePop}[name](arg)


def _collect_agg_calls(ast, out: List):
    if not isinstance(ast, tuple):
        return
    if ast[0] == "winfn":
        # the OUTER call is a window function (evaluated after
        # grouping); only its arguments, partition and order keys may
        # reference group aggregates ("rank() over (order by sum(x))")
        _, call, partition, order, _frame = ast
        for a in call[3]:
            _collect_agg_calls(a, out)
        for p_ in partition:
            _collect_agg_calls(p_, out)
        for e_, _a, _n in order:
            _collect_agg_calls(e_, out)
        return
    if ast[0] == "call" and ast[1] in _AGG_FNS:
        if repr(ast) not in {repr(o) for o in out}:
            out.append(ast)
        return  # no nested aggregates
    for part in ast:
        if isinstance(part, tuple):
            _collect_agg_calls(part, out)
        elif isinstance(part, list):
            for p in part:
                if isinstance(p, tuple):
                    _collect_agg_calls(p, out)


# ---------------------------------------------------------------------------
# relation planning
# ---------------------------------------------------------------------------


def _split_join_condition(cond_ast, left_scope: _Scope,
                          right_scope: _Scope):
    """Split ON into equi-key ordinal pairs + residual conjuncts."""
    conjuncts = []

    def walk(a):
        if isinstance(a, tuple) and a[0] == "and":
            walk(a[1])
            walk(a[2])
        else:
            conjuncts.append(a)

    if cond_ast is not None:
        walk(cond_ast)
    lk, rk, residual = [], [], []
    for c in conjuncts:
        if isinstance(c, tuple) and c[0] == "cmp" and c[1] == "=" and \
                c[2][0] == "col" and c[3][0] == "col":
            sides = []
            for colast in (c[2], c[3]):
                _, tab, name = colast
                side = None
                try:
                    i, _t = left_scope.resolve(tab, name)
                    side = ("l", i)
                except SqlError:
                    pass
                try:
                    i, _t = right_scope.resolve(tab, name)
                    if side is not None:
                        side = None  # ambiguous across sides
                        break
                    side = ("r", i)
                except SqlError:
                    pass
                sides.append(side)
            if len(sides) == 2 and sides[0] and sides[1] and \
                    {sides[0][0], sides[1][0]} == {"l", "r"}:
                l = sides[0] if sides[0][0] == "l" else sides[1]
                r = sides[0] if sides[0][0] == "r" else sides[1]
                lk.append(l[1])
                rk.append(r[1])
                continue
        residual.append(c)
    residual_ast = None
    for c in residual:
        residual_ast = c if residual_ast is None else \
            ("and", residual_ast, c)
    return lk, rk, residual_ast


def _col_refs(ast, out: List):
    if not isinstance(ast, tuple):
        return
    if ast[0] == "col":
        out.append(ast)
        return
    for p in ast:
        if isinstance(p, tuple):
            _col_refs(p, out)
        elif isinstance(p, list):
            for x in p:
                _col_refs(x, out)


def _conjunct_side(c, lscope: _Scope, rscope: _Scope):
    """'l'/'r' when every column in ``c`` resolves on exactly that side;
    None when mixed/ambiguous."""
    refs: List = []
    _col_refs(c, refs)
    sides = set()
    for _, tab, name in refs:
        inl = inr = False
        try:
            lscope.resolve(tab, name)
            inl = True
        except SqlError:
            pass
        try:
            rscope.resolve(tab, name)
            inr = True
        except SqlError:
            pass
        if inl == inr:
            return None  # unresolvable or ambiguous
        sides.add("l" if inl else "r")
    if len(sides) == 1:
        return sides.pop()
    return None


def _plan_relation(rel, catalog) -> Tuple[pn.PlanNode, _Scope]:
    kind = rel[0]
    if kind == "table":
        _, name, alias = rel
        matches = [k for k in catalog if k.lower() == name.lower()]
        if not matches:
            raise SqlError(f"table {name!r} not found "
                           f"(known: {sorted(catalog)})")
        entry = catalog[matches[0]]
        node = entry if isinstance(entry, pn.PlanNode) else \
            pn.ScanNode(entry)
        schema = node.output_schema()
        scope = _Scope([(alias, n, t)
                        for n, t in zip(schema.names, schema.types)])
        return node, scope
    if kind == "subquery":
        _, sub, alias = rel
        node = plan_statement(sub, catalog)
        schema = node.output_schema()
        return node, _Scope([(alias, n, t)
                             for n, t in zip(schema.names,
                                             schema.types)])
    if kind == "join":
        _, jkind, lrel, rrel, on = rel
        if jkind == "cross" and on is not None:
            # Spark parses CROSS JOIN ... ON as an inner join; planning
            # it as cross would silently drop the condition
            jkind = "inner"
        lnode, lscope = _plan_relation(lrel, catalog)
        rnode, rscope = _plan_relation(rrel, catalog)
        lk, rk, residual = _split_join_condition(on, lscope, rscope)
        if jkind != "cross" and not lk:
            raise SqlError("join requires at least one equi-condition "
                           "(col = col across the two sides)")
        cond_expr = None
        joined_scope = _Scope(
            lscope.entries + rscope.entries
            if jkind not in ("left_semi", "left_anti")
            else lscope.entries)
        if residual is not None:
            if jkind in ("left_semi", "left_anti"):
                # one-sided ON conjuncts become pre-join filters (the
                # planning Spark does for "LEFT SEMI JOIN d ON k AND
                # d.x = lit": push the single-side predicate below the
                # join); cross-side non-equi residuals stay unsupported
                for c in _conjuncts(residual):
                    side = _conjunct_side(c, lscope, rscope)
                    if side == "r":
                        rnode = pn.FilterNode(
                            _ExprPlanner(rscope).plan(c), rnode)
                    elif side == "l" and jkind == "left_semi":
                        # valid for semi only: an anti join KEEPS left
                        # rows whose ON condition is false
                        lnode = pn.FilterNode(
                            _ExprPlanner(lscope).plan(c), lnode)
                    else:
                        raise SqlError(
                            "semi/anti joins support only equi or "
                            "single-side conditions")
            else:
                full_scope = _Scope(lscope.entries + rscope.entries)
                cond_expr = _ExprPlanner(full_scope).plan(residual)
        node = pn.JoinNode(jkind, lnode, rnode, lk, rk,
                           condition=cond_expr)
        return node, joined_scope
    raise SqlError(f"unsupported relation {kind!r}")


# ---------------------------------------------------------------------------
# statement planning
# ---------------------------------------------------------------------------


def _flatten_implicit(rel) -> List:
    if rel[0] == "join" and rel[1] == "implicit":
        return _flatten_implicit(rel[2]) + [rel[3]]
    return [rel]


def _conjuncts(ast) -> List:
    if ast is None:
        return []
    if isinstance(ast, tuple) and ast[0] == "and":
        return _conjuncts(ast[1]) + _conjuncts(ast[2])
    return [ast]


def _equi_pair(c, lscope: _Scope, rscope: _Scope):
    """ordinal pair when conjunct ``c`` is col=col across the scopes."""
    if not (isinstance(c, tuple) and c[0] == "cmp" and c[1] == "=" and
            c[2][0] == "col" and c[3][0] == "col"):
        return None

    def side(colast):
        _, tab, name = colast
        l = r = None
        try:
            l = lscope.resolve(tab, name)[0]
        except SqlError:
            pass
        try:
            r = rscope.resolve(tab, name)[0]
        except SqlError:
            pass
        if (l is None) == (r is None):
            return None  # missing or ambiguous across the scopes
        return ("l", l) if l is not None else ("r", r)

    a, b = side(c[2]), side(c[3])
    if a and b and {a[0], b[0]} == {"l", "r"}:
        l = a if a[0] == "l" else b
        r = a if a[0] == "r" else b
        return l[1], r[1]
    return None


def _resolves(scope_: _Scope, tab, name) -> bool:
    try:
        scope_.resolve(tab, name)
        return True
    except SqlError:
        return False


def _has_subquery(ast) -> bool:
    if not isinstance(ast, tuple):
        return False
    if ast[0] in ("in_sub", "scalar_sub", "exists", "select", "union"):
        return True
    for p in ast:
        if isinstance(p, tuple) and _has_subquery(p):
            return True
        if isinstance(p, list) and any(
                isinstance(x, tuple) and _has_subquery(x) for x in p):
            return True
    return False


def _is_single_row(node: pn.PlanNode) -> bool:
    """True when the plan provably yields AT MOST one row: a global
    aggregate (no grouping), possibly under projections, filters (the
    predicate-pushdown pass wraps pushed conjuncts around it; an empty
    side just gives an empty cross product), or LIMIT>=1."""
    while isinstance(node, (pn.ProjectNode, pn.FilterNode)) or \
            (isinstance(node, pn.LimitNode) and node.n >= 1):
        node = node.children[0]
    return isinstance(node, pn.AggregateNode) and not node.grouping


def _plan_implicit_joins(rels, where_ast, catalog):
    """Comma-FROM planning: hoist WHERE equi-conjuncts into inner-join
    keys, folding relations left-to-right (the analysis step Spark's
    optimizer performs for the classic TPC join syntax)."""
    planned = [_plan_relation(r, catalog) for r in rels]
    conjuncts = _conjuncts(where_ast)
    # push single-relation conjuncts below the joins (Spark's
    # PushDownPredicate): without this, self-joins of a filtered CTE
    # (TPC-DS q4/q11/q74: six instances of year_total) build the full
    # cross-product of every year and channel before filtering
    kept: List = []
    for c in conjuncts:
        refs: List = []
        _col_refs(c, refs)
        homes = []
        for i, (_n, s_i) in enumerate(planned):
            if refs and all(_resolves(s_i, tab, name)
                            for _, tab, name in refs):
                homes.append(i)
        if len(homes) == 1 and not _has_subquery(c):
            i = homes[0]
            n_i, s_i = planned[i]
            planned[i] = (pn.FilterNode(_ExprPlanner(s_i).plan(c), n_i),
                          s_i)
        else:
            kept.append(c)
    conjuncts = kept
    node, scope = planned[0]
    remaining = list(planned[1:])
    while remaining:
        progress = False
        for idx, (n2, s2) in enumerate(remaining):
            lk, rk, used = [], [], []
            for ci, c in enumerate(conjuncts):
                pair = _equi_pair(c, scope, s2)
                if pair:
                    lk.append(pair[0])
                    rk.append(pair[1])
                    used.append(ci)
            if lk:
                node = pn.JoinNode("inner", node, n2, lk, rk)
                scope = _Scope(scope.entries + s2.entries)
                for ci in reversed(used):
                    conjuncts.pop(ci)
                remaining.pop(idx)
                progress = True
                break
        if not progress:
            # provably single-row relations (global aggregates) may
            # cross-join without an equi link — the TPC-DS q61/q90
            # numerator/denominator shape. Anything else stays an
            # error: an unlinked multi-row table is almost always a
            # query bug, and the product would explode
            for idx, (n2, s2) in enumerate(remaining):
                if _is_single_row(n2):
                    node = pn.JoinNode("cross", node, n2, [], [])
                    scope = _Scope(scope.entries + s2.entries)
                    remaining.pop(idx)
                    progress = True
                    break
        if not progress:
            names = [r[0] for r in rels]
            raise SqlError(
                "comma-joined tables need WHERE equi-conditions "
                f"linking them (unlinked remain among {names})")
    residual = None
    for c in conjuncts:
        residual = c if residual is None else ("and", residual, c)
    if residual is not None:
        node = pn.FilterNode(_ExprPlanner(scope).plan(residual), node)
    return node, scope


def _subst_aliases(ast, alias_map, scope):
    """Replace unqualified column refs that match a SELECT alias (and do
    not resolve as real columns) with the aliased expression — Spark's
    HAVING/ORDER BY alias resolution ("HAVING cnt >= 10")."""
    if not isinstance(ast, tuple):
        return ast
    if ast[0] == "col" and ast[1] is None:
        name = ast[2].lower()
        if name in alias_map:
            try:
                scope.resolve(None, ast[2])
            except SqlError:
                return alias_map[name]
        return ast
    out = []
    for p in ast:
        if isinstance(p, tuple):
            out.append(_subst_aliases(p, alias_map, scope))
        elif isinstance(p, list):
            out.append([_subst_aliases(x, alias_map, scope)
                        if isinstance(x, tuple) else x for x in p])
        else:
            out.append(p)
    return tuple(out)


def _extract_in_subs(where_ast):
    """Pull top-level ``x IN (SELECT ...)`` and ``[NOT] EXISTS (...)``
    conjuncts out of WHERE; they become semi/anti joins (the rewrite
    Spark's optimizer performs — RewritePredicateSubquery)."""
    subs = []
    exists = []
    rest = None
    for c in _conjuncts(where_ast):
        if isinstance(c, tuple) and c[0] == "in_sub":
            subs.append((c[1], c[2], c[3]))
        elif isinstance(c, tuple) and c[0] == "exists":
            exists.append((c[1], False))
        elif isinstance(c, tuple) and c[0] == "not" and \
                isinstance(c[1], tuple) and c[1][0] == "exists":
            exists.append((c[1][1], True))
        else:
            rest = c if rest is None else ("and", rest, c)
    return rest, subs, exists


def _apply_exists(node, scope: _Scope, exists_subs, catalog):
    """Decorrelate [NOT] EXISTS into a left semi/anti join. The
    subquery's WHERE conjuncts that reference outer columns must be
    ``outer_col = inner_col`` equalities; they become the join keys,
    everything else stays inside the subquery (Spark's
    RewritePredicateSubquery + pullOutCorrelatedPredicates)."""
    for sub, negated in exists_subs:
        if sub[0] != "select":
            raise SqlError("EXISTS subquery cannot be a set operation")
        q = sub[1]
        if q["group"] or q["having"] is not None:
            raise SqlError("EXISTS over a grouped subquery is "
                           "unsupported")
        if q["limit"] is not None or q["order"]:
            # LIMIT changes EXISTS semantics (LIMIT 0 = always false);
            # refuse loudly rather than silently dropping it
            raise SqlError("EXISTS subquery cannot carry ORDER BY/LIMIT")
        # the inner FROM scope, planned without WHERE, classifies refs.
        # (These plan trees are discarded — plan_statement(keys_q)
        # re-plans the FROM; accepted planning-time cost to keep the
        # rewrite at the AST layer.) The subquery's own CTEs must be
        # visible to this classification pass, not just to the keys_q
        # re-plan (r3 advisor finding); planning them ONCE here and
        # handing sub_catalog to the keys_q plan avoids a second pass
        sub_catalog = _register_ctes(q.get("ctes"), catalog)
        inner_scope_entries: List[Tuple[Optional[str], str, dt.DType]] = []
        for r in _flatten_implicit(q["from"]):
            _n, s = _plan_relation(r, sub_catalog)
            inner_scope_entries.extend(s.entries)
        inner_scope = _Scope(inner_scope_entries)

        def is_correlated(c) -> bool:
            refs: List = []
            _col_refs(c, refs)
            return any(not _resolves(inner_scope, tab, name) and
                       _resolves(scope, tab, name)
                       for _, tab, name in refs)

        inner_where = None
        outer_keys: List[tuple] = []
        inner_keys: List[tuple] = []
        for c in _conjuncts(q["where"]):
            if not is_correlated(c):
                inner_where = c if inner_where is None \
                    else ("and", inner_where, c)
                continue
            ok = (isinstance(c, tuple) and c[0] == "cmp" and
                  c[1] == "=" and c[2][0] == "col" and c[3][0] == "col")
            if ok:
                sides = []
                for colast in (c[2], c[3]):
                    _, tab, name = colast
                    inner_ok = _resolves(inner_scope, tab, name)
                    sides.append("i" if inner_ok else "o")
                if set(sides) == {"i", "o"}:
                    outer_keys.append(c[2] if sides[0] == "o" else c[3])
                    inner_keys.append(c[2] if sides[0] == "i" else c[3])
                    continue
            raise SqlError(
                "EXISTS correlation must be outer_col = inner_col "
                f"equalities; cannot decorrelate {c!r}")
        if not outer_keys:
            raise SqlError("uncorrelated EXISTS is unsupported; use a "
                           "cross join against the aggregated subquery")
        keys_q = ("select", {
            "distinct": False,
            "sels": [(k, f"_exk{i}") for i, k in enumerate(inner_keys)],
            "from": q["from"], "where": inner_where, "group": [],
            "rollup": False, "having": None, "order": [],
            "limit": None, "ctes": [],  # already in sub_catalog
        })
        subnode = plan_statement(keys_q, sub_catalog)
        ords = []
        for k in outer_keys:
            e = _ExprPlanner(scope).plan(k)
            if not isinstance(e, BoundReference):
                raise SqlError("EXISTS outer key must be a plain column")
            ords.append(e.ordinal)
        node = pn.JoinNode("left_anti" if negated else "left_semi",
                           node, subnode, ords,
                           list(range(len(inner_keys))))
    return node


def _apply_in_subs(node, scope, subs, catalog):
    from spark_rapids_tpu.expressions import aggregates as A_

    for col_ast, sub, negated in subs:
        e = _ExprPlanner(scope).plan(col_ast)
        if not isinstance(e, BoundReference):
            raise SqlError("IN (subquery) needs a plain column on the "
                           "left")
        subnode = plan_statement(sub, catalog)
        sub_schema = subnode.output_schema()
        if len(sub_schema) != 1:
            raise SqlError("IN subquery must select exactly one column")
        if not negated:
            node = pn.JoinNode("left_semi", node, subnode,
                               [e.ordinal], [0])
            continue
        # NOT IN: null-aware anti join (Spark RewritePredicateSubquery).
        # SQL three-valued logic: a NULL probe never qualifies, and ANY
        # null in the subquery empties the whole result.
        node = pn.FilterNode(pr.IsNotNull(e), node)
        node = pn.JoinNode("left_anti", node, subnode, [e.ordinal], [0])
        width = len(node.output_schema())
        sub_ref = BoundReference(0, sub_schema.types[0])
        nullcnt = pn.AggregateNode(
            [], [pn.AggCall(A_.Count(), "_subnulls")],
            pn.FilterNode(pr.IsNull(sub_ref), subnode))
        node = pn.JoinNode("cross", node, nullcnt, [], [])
        node = pn.FilterNode(
            pr.EqualTo(BoundReference(width, dt.INT64), Literal(0)),
            node)
        out_schema = node.output_schema()
        node = pn.ProjectNode(
            [Alias(BoundReference(i, out_schema.types[i]),
                   out_schema.names[i]) for i in range(width)],
            node, names=list(out_schema.names)[:width])
    return node


def _replace_scalar_subs(ast, acc: List, prefix: str = "_ssq"):
    """Replace ('scalar_sub', q) nodes with generated column refs;
    ``acc`` collects (gen_name, subquery_ast)."""
    if not isinstance(ast, tuple):
        return ast
    if ast[0] == "scalar_sub":
        gen = f"{prefix}{len(acc)}"
        acc.append((gen, ast[1]))
        return ("col", None, gen)
    out = []
    for p in ast:
        if isinstance(p, tuple):
            out.append(_replace_scalar_subs(p, acc, prefix))
        elif isinstance(p, list):
            out.append([_replace_scalar_subs(x, acc, prefix)
                        if isinstance(x, tuple) else x for x in p])
        else:
            out.append(p)
    return tuple(out)


def _attach_scalar_subs(node, scope: _Scope, subs, catalog):
    """Cross-join 1-row scalar-subquery plans, extending the scope.
    (Aggregate scalar subqueries always produce exactly one row; a
    multi-row subquery here is a user error SQL rejects at runtime.)"""
    for gen, sub in subs:
        subnode = plan_statement(sub, catalog)
        ss = subnode.output_schema()
        if len(ss) != 1:
            raise SqlError("scalar subquery must select one column")
        node = pn.JoinNode("cross", node, subnode, [], [])
        scope = _Scope(scope.entries + [(None, gen, ss.types[0])])
    return node, scope


def _contains_col(ast, names: set) -> bool:
    refs: List = []
    _col_refs(ast, refs)
    return any(n.lower() in names for _, _t, n in refs)


def _collect_winfns(ast, out: List):
    if not isinstance(ast, tuple):
        return
    if ast[0] == "winfn":
        if repr(ast) not in {repr(o) for o in out}:
            out.append(ast)
        return  # windows over windows are unsupported
    for p in ast:
        if isinstance(p, tuple):
            _collect_winfns(p, out)
        elif isinstance(p, list):
            for x in p:
                if isinstance(x, tuple):
                    _collect_winfns(x, out)


def _plan_window(wast, node, scope: _Scope, env):
    """One ('winfn', call, partition, order, frame) -> WindowNode.
    Partition/order expressions that are not plain columns are
    materialized by a pre-projection (the planner-inserted project the
    reference gets from Catalyst before GpuWindowExec)."""
    _, call, partition, order, frame = wast
    planner = _ExprPlanner(scope, env)
    extra: List[Expression] = []
    base = scope.width

    def ordinal_of(e_ast) -> int:
        expr = planner.plan(e_ast)
        if isinstance(expr, BoundReference):
            return expr.ordinal
        extra.append(expr)
        return base + len(extra) - 1

    part_ords = [ordinal_of(p) for p in partition]
    specs = [SortKeySpec(ordinal_of(e), asc, nf)
             for e, asc, nf in order]

    fname = call[1]
    if fname in ("rank", "dense_rank", "row_number"):
        if call[3]:
            raise SqlError(f"{fname}() takes no arguments")
        if not specs:
            raise SqlError(f"{fname}() requires ORDER BY in OVER()")
        fn = fname
        wframe = pn.WindowFrame(None, 0)
    elif fname in ("lead", "lag"):
        args = call[3]
        if not args:
            raise SqlError(f"{fname}(col[, offset]) requires a column")
        fn = (fname, planner.plan(args[0]))
        wframe = pn.WindowFrame(None, 0)
    else:
        agg = _plan_agg_call(call, scope, env)
        fn = agg
        if frame is not None:
            wframe = pn.WindowFrame(frame[0], frame[1])
        elif specs:
            wframe = pn.WindowFrame(None, 0)   # running (SQL default)
        else:
            wframe = pn.WindowFrame(None, None)  # whole partition
    if extra:
        schema = node.output_schema()
        exprs = [Alias(BoundReference(i, t), schema.names[i])
                 for i, t in enumerate(schema.types)]
        names = list(schema.names)
        for j, e in enumerate(extra):
            exprs.append(Alias(e, f"_wk{j}"))
            names.append(f"_wk{j}")
        node = pn.ProjectNode(exprs, node, names)
        scope = _Scope(scope.entries +
                       [(None, f"_wk{j}", e.dtype)
                        for j, e in enumerate(extra)])
    gen = f"_win{len(env)}"
    wcall = pn.WindowCall(fn, gen, frame=wframe)
    node = pn.WindowNode(part_ords, specs, [wcall], node)
    out_schema = node.output_schema()
    new_ord = len(out_schema) - 1
    env = dict(env)
    env[repr(wast)] = (new_ord, out_schema.types[new_ord])
    scope = _Scope(scope.entries +
                   [(None, gen, out_schema.types[new_ord])])
    return node, scope, env


def _dedup(node: pn.PlanNode) -> pn.PlanNode:
    schema = node.output_schema()
    return pn.AggregateNode(
        [BoundReference(j, t) for j, t in enumerate(schema.types)],
        [], node, grouping_names=list(schema.names))


def _nullsafe_keys(node: pn.PlanNode) -> Tuple[pn.PlanNode, int]:
    """Append, per column, a NULL-coalesced copy and an is-null flag —
    joining on (coalesced, flag) pairs gives null-SAFE equality (SQL set
    ops treat NULLs as equal; Spark's <=> inside
    ReplaceIntersectWithSemiJoin / ReplaceExceptWithAntiJoin).

    NaN = NaN and -0.0 = 0.0 need NO planner-side normalization: every
    join key is canonicalized in the executor (ops/sortkeys.py
    ``canonicalize_floats`` feeds both the hash images and the
    exact-equality lanes), the engine-level analogue of Spark's
    NormalizeNaNAndZero — pinned by test_setops_nan_and_negzero_normalized."""
    schema = node.output_schema()
    width = len(schema)
    exprs: List[Expression] = [
        Alias(BoundReference(i, t), schema.names[i])
        for i, t in enumerate(schema.types)]
    names = list(schema.names)
    zeros = {dt.STRING: "", dt.BOOLEAN: False,
             dt.FLOAT32: 0.0, dt.FLOAT64: 0.0}
    for i, t in enumerate(schema.types):
        ref = BoundReference(i, t)
        exprs.append(Alias(cond.Coalesce([ref, Literal(zeros.get(t, 0),
                                                       t)]), f"_k{i}"))
        names.append(f"_k{i}")
        exprs.append(Alias(pr.IsNull(ref), f"_n{i}"))
        names.append(f"_n{i}")
    return pn.ProjectNode(exprs, node, names), width


def _plan_union(q, catalog) -> pn.PlanNode:
    """Set-op chain with SQL precedence (INTERSECT folded tighter by the
    parser): UNION [ALL] -> UnionNode (+ dedup for plain UNION);
    INTERSECT -> dedup + semi join; EXCEPT -> dedup + anti join (Spark's
    ReplaceIntersectWithSemiJoin / ReplaceExceptWithAntiJoin). The joins
    run on null-coalesced keys plus is-null flags so NULL rows compare
    EQUAL, matching the set-op <=> semantics."""
    nodes = [plan_statement(c, catalog) for c in q["cores"]]
    node = nodes[0]
    for i, rhs in enumerate(nodes[1:]):
        op = q["setops"][i]
        if op[0] == "union":
            node = pn.UnionNode([node, rhs])
            if not op[1]:
                node = _dedup(node)
        else:
            lhs_schema = node.output_schema()
            width = len(lhs_schema)
            rhs_schema = rhs.output_schema()
            if len(rhs_schema) != width:
                raise SqlError("set-op sides must have equal width")
            if list(lhs_schema.types) != list(rhs_schema.types):
                # no implicit set-op type coercion: misaligned key
                # dtypes would compare garbage lanes, so error loudly
                raise SqlError(
                    "set-op sides must have matching column types; got "
                    f"{[t.name for t in lhs_schema.types]} vs "
                    f"{[t.name for t in rhs_schema.types]}")
            lk, _w = _nullsafe_keys(_dedup(node))
            rk, _w = _nullsafe_keys(rhs)
            keys = list(range(width, 3 * width))
            joined = pn.JoinNode(
                "left_semi" if op[0] == "intersect" else "left_anti",
                lk, rk, keys, keys)
            schema = node.output_schema()
            node = pn.ProjectNode(
                [Alias(BoundReference(j, schema.types[j]),
                       schema.names[j]) for j in range(width)],
                joined, list(schema.names))
    if q["order"]:
        schema = node.output_schema()
        specs = []
        for e, asc, nulls_first in q["order"]:
            if e[0] == "lit" and isinstance(e[1], int):
                ordinal = e[1] - 1
            elif e[0] == "col" and e[1] is None and \
                    e[2] in schema.names:
                ordinal = schema.names.index(e[2])
            else:
                raise SqlError("UNION ORDER BY must use output names "
                               "or positions")
            specs.append(SortKeySpec(ordinal, asc, nulls_first))
        node = pn.SortNode(specs, node)
    if q["limit"] is not None:
        node = pn.LimitNode(q["limit"], node)
    return node


def _plan_rollup(q, node, scope: _Scope, agg_calls):
    """GROUP BY ROLLUP(g1..gn): n+1 grouping-set branches, each a
    normal AggregateNode over the shared child with dropped keys
    projected as typed NULLs, unioned (Spark's Expand+Aggregate plan
    produces the same rows; here each branch re-aggregates the child,
    which XLA dedups less but keeps every node a plain aggregate).
    ``grouping(col)`` resolves via per-branch 0/1 literal columns."""
    group = q["group"]
    n = len(group)
    grouping = [_ExprPlanner(scope).plan(g) for g in group]
    gnames = [g[2] if g[0] == "col" else f"_g{i}"
              for i, g in enumerate(group)]
    m = len(agg_calls)
    branches = []
    agg_types = None
    for k in range(n, -1, -1):
        calls = [pn.AggCall(_plan_agg_call(c, scope), f"_a{i}")
                 for i, c in enumerate(agg_calls)]
        agg_b = pn.AggregateNode(grouping[:k], calls, node,
                                 grouping_names=gnames[:k])
        schema_b = agg_b.output_schema()
        agg_types = list(schema_b.types)[k:]
        exprs: List[Expression] = []
        names: List[str] = []
        for i in range(n):
            e = BoundReference(i, grouping[i].dtype) if i < k \
                else Literal(None, grouping[i].dtype)
            exprs.append(Alias(e, gnames[i]))
            names.append(gnames[i])
        for j in range(m):
            exprs.append(Alias(BoundReference(k + j, agg_types[j]),
                               f"_a{j}"))
            names.append(f"_a{j}")
        for i in range(n):
            exprs.append(Alias(Literal(0 if i < k else 1, dt.INT32),
                               f"_grouping{i}"))
            names.append(f"_grouping{i}")
        branches.append(pn.ProjectNode(exprs, agg_b, names))
    node = pn.UnionNode(branches)
    env: Dict[str, Tuple[int, dt.DType]] = {}
    for i, g in enumerate(group):
        env[repr(g)] = (i, grouping[i].dtype)
        gcall = ("call", "grouping", False, [g])
        env[repr(gcall)] = (n + m + i, dt.INT32)
    for j, c in enumerate(agg_calls):
        env[repr(c)] = (n + j, agg_types[j])
    schema = node.output_schema()
    scope = _Scope([(None, nm, t)
                    for nm, t in zip(schema.names, schema.types)])
    return node, scope, env


def _register_ctes(ctes, catalog):
    """Plan each CTE once into a catalog copy (Spark's CTESubstitution);
    self-references across branches share the plan node, like temp
    views. Returns the original catalog untouched when there are none."""
    if not ctes:
        return catalog
    catalog = dict(catalog)
    for name, sub in ctes:
        catalog[name] = plan_statement(sub, catalog)
    return catalog


def plan_statement(ast, catalog) -> pn.PlanNode:
    q = ast[1]
    catalog = _register_ctes(q.get("ctes"), catalog)
    if ast[0] == "union":
        return _plan_union(q, catalog)
    assert ast[0] == "select"
    where_ast, in_subs, exists_subs = _extract_in_subs(q["where"])

    # uncorrelated scalar subqueries: each becomes a generated column
    # fed by a 1-row cross join (Spark's ScalarSubquery via subquery
    # broadcast). WHERE-referenced subs (and subs used INSIDE aggregate
    # arguments) attach before aggregation; SELECT/HAVING-level subs
    # attach AFTER it — the aggregate's output schema would drop them
    # (TPC-DS q32/q92 shape: sum(x) > (SELECT ...))
    ssq_post: List = []
    q = dict(q)
    q["sels"] = [(_replace_scalar_subs(e, ssq_post), a)
                 for e, a in q["sels"]]
    if q["having"] is not None:
        q["having"] = _replace_scalar_subs(q["having"], ssq_post)
    ssq_pre: List = []
    deferred_where = []
    if where_ast is not None:
        kept = None
        for c in _conjuncts(where_ast):
            before = len(ssq_pre)
            c2 = _replace_scalar_subs(c, ssq_pre, prefix="_ssqw")
            if len(ssq_pre) > before:
                deferred_where.append(c2)
            else:
                kept = c2 if kept is None else ("and", kept, c2)
        where_ast = kept
    # subs referenced inside aggregate ARGUMENTS evaluate pre-grouping
    agg_probe: List[tuple] = []
    for e, _a in q["sels"]:
        _collect_agg_calls(e, agg_probe)
    if q["having"] is not None:
        _collect_agg_calls(q["having"], agg_probe)
    in_agg_names = set()
    for call in agg_probe:
        refs: List = []
        _col_refs(call, refs)
        in_agg_names |= {n for _, _t, n in refs
                         if n.startswith("_ssq")}
    moved = [(g, s) for g, s in ssq_post if g in in_agg_names]
    ssq_post = [(g, s) for g, s in ssq_post if g not in in_agg_names]
    ssq_pre.extend(moved)
    rels = _flatten_implicit(q["from"])
    if len(rels) > 1:
        node, scope = _plan_implicit_joins(rels, where_ast, catalog)
    else:
        node, scope = _plan_relation(q["from"], catalog)
        if where_ast is not None:
            node = pn.FilterNode(_ExprPlanner(scope).plan(where_ast),
                                 node)
    node = _apply_in_subs(node, scope, in_subs, catalog)
    node = _apply_exists(node, scope, exists_subs, catalog)

    node, scope = _attach_scalar_subs(node, scope, ssq_pre, catalog)
    for c in deferred_where:
        node = pn.FilterNode(_ExprPlanner(scope).plan(c), node)

    # expand SELECT * / build select item list
    sels: List[Tuple[tuple, Optional[str]]] = []
    for e, alias in q["sels"]:
        if e == ("star",):
            for i, (tab, name, t) in enumerate(scope.entries):
                sels.append((("col", tab, name), name))
        else:
            sels.append((e, alias))

    alias_map = {a.lower(): e for e, a in sels if a}
    having_ast = _subst_aliases(q["having"], alias_map, scope) \
        if q["having"] is not None else None
    order_items = [(_subst_aliases(e, alias_map, scope), asc, nf)
                   for e, asc, nf in q["order"]]

    agg_calls: List[tuple] = []
    for e, _ in sels:
        _collect_agg_calls(e, agg_calls)
    if having_ast is not None:
        _collect_agg_calls(having_ast, agg_calls)
    for e, _asc, _nf in order_items:
        _collect_agg_calls(e, agg_calls)

    env: Dict[str, Tuple[int, dt.DType]] = {}
    if q.get("rollup") and q["group"]:
        node, scope, env = _plan_rollup(q, node, scope, agg_calls)
    elif q["group"] or agg_calls:
        grouping = [_ExprPlanner(scope).plan(g) for g in q["group"]]
        calls = [pn.AggCall(_plan_agg_call(c, scope), f"_a{i}")
                 for i, c in enumerate(agg_calls)]
        gnames = []
        for i, g in enumerate(q["group"]):
            gname = g[2] if g[0] == "col" else f"_g{i}"
            gnames.append(gname)
        node = pn.AggregateNode(grouping, calls, node,
                                grouping_names=gnames)
        # post-agg namespace: group ASTs then agg-call ASTs
        for i, g in enumerate(q["group"]):
            env[repr(g)] = (i, grouping[i].dtype)
        base = len(grouping)
        agg_schema = node.output_schema()
        for i, c in enumerate(agg_calls):
            env[repr(c)] = (base + i, agg_schema.types[base + i])
        scope = _Scope([(None, n, t)
                        for n, t in zip(agg_schema.names,
                                        agg_schema.types)])
        # group columns stay resolvable by name too

    # SELECT/HAVING-level scalar subqueries join here — after the
    # aggregate (whose schema would otherwise drop their columns), or
    # directly onto the base relation for aggregation-free queries
    node, scope = _attach_scalar_subs(node, scope, ssq_post, catalog)

    if having_ast is not None:
        node = pn.FilterNode(
            _ExprPlanner(scope, env).plan(having_ast), node)

    # window functions anywhere in the select list: each plans to a
    # WindowNode appending one column; env maps the winfn AST to that
    # column so the final projection (including expressions OVER window
    # results, e.g. ratios) resolves it like any other value
    winfns: List[tuple] = []
    for e, _a in sels:
        _collect_winfns(e, winfns)
    for wast in winfns:
        node, scope, env = _plan_window(wast, node, scope, env)

    # final projection. ORDER BY expressions that are not select items
    # ride as HIDDEN projection columns, sorted on, then projected away
    # (Spark's planner appends the same hidden sort attributes)
    out_exprs: List[Expression] = []
    out_names: List[str] = []
    for i, (e, alias) in enumerate(sels):
        expr = _ExprPlanner(scope, env).plan(e)
        name = alias or (e[2] if e[0] == "col" else f"col{i}")
        out_exprs.append(Alias(expr, name))
        out_names.append(name)

    sel_keys = {repr(e): i for i, (e, _a) in enumerate(sels)}
    specs = []
    hidden: List[Expression] = []
    for e, asc, nulls_first in order_items:
        if e[0] == "lit" and isinstance(e[1], int):
            ordinal = e[1] - 1  # ORDER BY position
            if not 0 <= ordinal < len(sels):
                raise SqlError(f"ORDER BY position {e[1]} out of range")
        elif repr(e) in sel_keys:
            ordinal = sel_keys[repr(e)]
        elif e[0] == "col" and e[1] is None and e[2] in out_names:
            ordinal = out_names.index(e[2])
        else:
            if q["distinct"]:
                raise SqlError("ORDER BY over a non-selected expression "
                               "cannot combine with DISTINCT")
            ordinal = len(sels) + len(hidden)
            hidden.append(_ExprPlanner(scope, env).plan(e))
        specs.append(SortKeySpec(ordinal, asc, nulls_first))

    if hidden:
        node = pn.ProjectNode(
            out_exprs + [Alias(h, f"_ord{j}")
                         for j, h in enumerate(hidden)],
            node, out_names + [f"_ord{j}"
                               for j in range(len(hidden))])
        node = pn.SortNode(specs, node)
        schema = node.output_schema()
        node = pn.ProjectNode(
            [Alias(BoundReference(i, schema.types[i]), out_names[i])
             for i in range(len(sels))], node, list(out_names))
        if q["limit"] is not None:
            node = pn.LimitNode(q["limit"], node)
        return node

    node = pn.ProjectNode(out_exprs, node, out_names)
    if q["distinct"]:
        schema = node.output_schema()
        node = pn.AggregateNode(
            [BoundReference(i, t) for i, t in enumerate(schema.types)],
            [], node, grouping_names=list(schema.names))
    if specs:
        node = pn.SortNode(specs, node)
    if q["limit"] is not None:
        node = pn.LimitNode(q["limit"], node)
    return node
