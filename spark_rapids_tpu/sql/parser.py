"""Recursive-descent SQL parser -> untyped AST.

Grammar (case-insensitive keywords):

    query     := SELECT [DISTINCT] sel (',' sel)* FROM relation
                 [WHERE expr] [GROUP BY expr (',' expr)*] [HAVING expr]
                 [ORDER BY order (',' order)*] [LIMIT int]
    sel       := expr [[AS] ident] | '*'
    relation  := table_or_sub ([INNER|LEFT [OUTER]|RIGHT [OUTER]|
                 FULL [OUTER]|LEFT SEMI|LEFT ANTI|CROSS] JOIN
                 table_or_sub [ON expr])*
    table_or_sub := ident [[AS] ident] | '(' query ')' [AS] ident
    order     := expr [ASC|DESC] [NULLS FIRST|NULLS LAST]
    expr      := OR-precedence expression grammar with NOT, comparison,
                 BETWEEN, IN (list | subquery-free), LIKE, IS [NOT] NULL,
                 additive/multiplicative arithmetic, unary -, literals,
                 CASE WHEN, CAST(e AS type), DATE 'lit', function calls,
                 [table.]column

AST nodes are plain tuples: ('select', {...}), ('col', tab, name),
('lit', value, kind), ('call', name, distinct, args), ('case', whens,
else_), ('cast', e, type), ('star',), binary ops ('and' 'or' 'not'
'cmp' 'arith' 'in' 'between' 'like' 'isnull').
"""
from __future__ import annotations

import re
from typing import List, Optional, Tuple


class SqlError(Exception):
    pass


_TOKEN_RE = re.compile(r"""
    \s+
  | --[^\n]*
  | (?P<num>\d+\.\d*([eE][+-]?\d+)?|\.\d+([eE][+-]?\d+)?|\d+([eE][+-]?\d+)?)
  | (?P<str>'(?:[^']|'')*')
  | (?P<ident>[A-Za-z_][A-Za-z_0-9]*)
  | (?P<op><=|>=|<>|!=|=|<|>|\(|\)|,|\*|/|%|\+|-|\.)
""", re.VERBOSE)

_KEYWORDS = {
    "select", "distinct", "from", "where", "group", "by", "having",
    "order", "limit", "as", "and", "or", "not", "in", "between", "like",
    "is", "null", "case", "when", "then", "else", "end", "cast", "join",
    "inner", "left", "right", "full", "outer", "semi", "anti", "cross",
    "on", "asc", "desc", "nulls", "first", "last", "date", "timestamp",
    "true", "false", "interval",
}


def _tokenize(sql: str) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            raise SqlError(f"cannot tokenize at: {sql[pos:pos+30]!r}")
        pos = m.end()
        if m.lastgroup is None:
            continue  # whitespace/comment
        text = m.group(m.lastgroup)
        kind = m.lastgroup
        if kind == "ident" and text.lower() in _KEYWORDS:
            out.append(("kw", text.lower()))
        else:
            out.append((kind, text))
    out.append(("eof", ""))
    return out


class _Parser:
    def __init__(self, sql: str):
        self.toks = _tokenize(sql)
        self.i = 0

    # -- token helpers -----------------------------------------------------

    def peek(self, k: int = 0):
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_kw(self, *kws: str) -> Optional[str]:
        kind, text = self.peek()
        if kind == "kw" and text in kws:
            self.i += 1
            return text
        return None

    def expect_kw(self, kw: str):
        if not self.accept_kw(kw):
            raise SqlError(f"expected {kw.upper()}, got "
                           f"{self.peek()[1]!r}")

    def accept_op(self, *ops: str) -> Optional[str]:
        kind, text = self.peek()
        if kind == "op" and text in ops:
            self.i += 1
            return text
        return None

    def expect_op(self, op: str):
        if not self.accept_op(op):
            raise SqlError(f"expected {op!r}, got {self.peek()[1]!r}")

    def expect_ident(self) -> str:
        kind, text = self.next()
        if kind != "ident":
            raise SqlError(f"expected identifier, got {text!r}")
        return text

    # -- query -------------------------------------------------------------

    def parse_query(self):
        self.expect_kw("select")
        distinct = bool(self.accept_kw("distinct"))
        sels = [self.parse_select_item()]
        while self.accept_op(","):
            sels.append(self.parse_select_item())
        self.expect_kw("from")
        rel = self.parse_relation()
        where = None
        if self.accept_kw("where"):
            where = self.parse_expr()
        group = []
        if self.accept_kw("group"):
            self.expect_kw("by")
            group = [self.parse_expr()]
            while self.accept_op(","):
                group.append(self.parse_expr())
        having = None
        if self.accept_kw("having"):
            having = self.parse_expr()
        order = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            order = [self.parse_order_item()]
            while self.accept_op(","):
                order.append(self.parse_order_item())
        limit = None
        if self.accept_kw("limit"):
            kind, text = self.next()
            if kind != "num" or not re.fullmatch(r"\d+", text):
                raise SqlError("LIMIT needs an integer")
            limit = int(text)
        return ("select", {"distinct": distinct, "sels": sels,
                           "from": rel, "where": where, "group": group,
                           "having": having, "order": order,
                           "limit": limit})

    def parse_select_item(self):
        if self.accept_op("*"):
            return (("star",), None)
        e = self.parse_expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.expect_ident()
        elif self.peek()[0] == "ident":
            alias = self.expect_ident()
        return (e, alias)

    def parse_order_item(self):
        e = self.parse_expr()
        asc = True
        if self.accept_kw("desc"):
            asc = False
        else:
            self.accept_kw("asc")
        nulls_first = asc  # Spark default: ASC->FIRST, DESC->LAST
        if self.accept_kw("nulls"):
            which = self.accept_kw("first", "last")
            if which is None:
                raise SqlError("NULLS must be followed by FIRST/LAST")
            nulls_first = which == "first"
        return (e, asc, nulls_first)

    # -- relations ---------------------------------------------------------

    def parse_relation(self):
        rel = self.parse_joined()
        # comma-separated FROM (the classic TPC syntax): implicit joins
        # whose conditions live in WHERE; the planner hoists them
        while self.accept_op(","):
            rel = ("join", "implicit", rel, self.parse_joined(), None)
        return rel

    def parse_joined(self):
        rel = self.parse_table_or_sub()
        while True:
            kind = None
            if self.accept_kw("cross"):
                kind = "cross"
            elif self.accept_kw("inner"):
                kind = "inner"
            elif self.accept_kw("left"):
                if self.accept_kw("semi"):
                    kind = "left_semi"
                elif self.accept_kw("anti"):
                    kind = "left_anti"
                else:
                    self.accept_kw("outer")
                    kind = "left"
            elif self.accept_kw("right"):
                self.accept_kw("outer")
                kind = "right"
            elif self.accept_kw("full"):
                self.accept_kw("outer")
                kind = "full"
            elif self.peek() == ("kw", "join"):
                kind = "inner"
            if kind is None:
                return rel
            self.expect_kw("join")
            right = self.parse_table_or_sub()
            cond = None
            if self.accept_kw("on"):
                cond = self.parse_expr()
            elif kind != "cross":
                raise SqlError(f"{kind.upper()} JOIN requires ON")
            rel = ("join", kind, rel, right, cond)

    def parse_table_or_sub(self):
        if self.accept_op("("):
            sub = self.parse_query()
            self.expect_op(")")
            self.accept_kw("as")
            alias = self.expect_ident()
            return ("subquery", sub, alias)
        name = self.expect_ident()
        alias = None
        if self.accept_kw("as"):
            alias = self.expect_ident()
        elif self.peek()[0] == "ident":
            alias = self.expect_ident()
        return ("table", name, alias or name)

    # -- expressions (precedence climbing) ---------------------------------

    def parse_expr(self):
        return self.parse_or()

    def parse_or(self):
        e = self.parse_and()
        while self.accept_kw("or"):
            e = ("or", e, self.parse_and())
        return e

    def parse_and(self):
        e = self.parse_not()
        while self.accept_kw("and"):
            e = ("and", e, self.parse_not())
        return e

    def parse_not(self):
        if self.accept_kw("not"):
            return ("not", self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self):
        e = self.parse_additive()
        negate = bool(self.accept_kw("not"))
        if self.accept_kw("between"):
            lo = self.parse_additive()
            self.expect_kw("and")
            hi = self.parse_additive()
            out = ("between", e, lo, hi)
            return ("not", out) if negate else out
        if self.accept_kw("in"):
            self.expect_op("(")
            if self.peek() == ("kw", "select"):
                sub = self.parse_query()
                self.expect_op(")")
                # negation carried in-node: NOT IN (subquery) is an
                # anti-join, not a boolean NOT (null semantics differ)
                return ("in_sub", e, sub, negate)
            vals = [self.parse_expr()]
            while self.accept_op(","):
                vals.append(self.parse_expr())
            self.expect_op(")")
            out = ("in", e, vals)
            return ("not", out) if negate else out
        if self.accept_kw("like"):
            pat = self.parse_additive()
            out = ("like", e, pat)
            return ("not", out) if negate else out
        if negate:
            raise SqlError("dangling NOT before a non-predicate")
        if self.accept_kw("is"):
            isnot = bool(self.accept_kw("not"))
            self.expect_kw("null")
            return ("isnull", e, isnot)
        op = self.accept_op("=", "<>", "!=", "<", "<=", ">", ">=")
        if op:
            rhs = self.parse_additive()
            return ("cmp", op, e, rhs)
        return e

    def parse_additive(self):
        e = self.parse_multiplicative()
        while True:
            op = self.accept_op("+", "-")
            if not op:
                return e
            e = ("arith", op, e, self.parse_multiplicative())

    def parse_multiplicative(self):
        e = self.parse_unary()
        while True:
            op = self.accept_op("*", "/", "%")
            if not op:
                return e
            e = ("arith", op, e, self.parse_unary())

    def parse_unary(self):
        if self.accept_op("-"):
            return ("neg", self.parse_unary())
        if self.accept_op("+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self):
        kind, text = self.peek()
        if kind == "op" and text == "(":
            self.next()
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if kind == "num":
            self.next()
            if re.fullmatch(r"\d+", text):
                return ("lit", int(text), "int")
            return ("lit", float(text), "float")
        if kind == "str":
            self.next()
            return ("lit", text[1:-1].replace("''", "'"), "str")
        if kind == "kw":
            if text in ("date", "timestamp"):
                # DATE 'yyyy-mm-dd' literal
                if self.peek(1)[0] == "str":
                    self.next()
                    _, s = self.next()
                    return ("lit", s[1:-1], text)
                # else: fall through (it may be a cast type name usage)
            if text == "null":
                self.next()
                return ("lit", None, "null")
            if text in ("true", "false"):
                self.next()
                return ("lit", text == "true", "bool")
            if text == "case":
                return self.parse_case()
            if text == "cast":
                self.next()
                self.expect_op("(")
                e = self.parse_expr()
                self.expect_kw("as")
                tkind, tname = self.next()
                if tkind not in ("ident", "kw"):
                    raise SqlError(f"bad cast type {tname!r}")
                self.expect_op(")")
                return ("cast", e, tname.lower())
        if kind == "ident":
            # function call or column reference
            if self.peek(1) == ("op", "("):
                name = self.expect_ident().lower()
                self.expect_op("(")
                distinct = bool(self.accept_kw("distinct"))
                args = []
                if self.accept_op("*"):
                    args.append(("star",))
                elif self.peek() != ("op", ")"):
                    args.append(self.parse_expr())
                    while self.accept_op(","):
                        args.append(self.parse_expr())
                self.expect_op(")")
                return ("call", name, distinct, args)
            tab_or_col = self.expect_ident()
            if self.accept_op("."):
                col = self.expect_ident()
                return ("col", tab_or_col, col)
            return ("col", None, tab_or_col)
        raise SqlError(f"unexpected token {text!r}")

    def parse_case(self):
        self.expect_kw("case")
        whens = []
        while self.accept_kw("when"):
            c = self.parse_expr()
            self.expect_kw("then")
            v = self.parse_expr()
            whens.append((c, v))
        els = None
        if self.accept_kw("else"):
            els = self.parse_expr()
        self.expect_kw("end")
        if not whens:
            raise SqlError("CASE requires at least one WHEN")
        return ("case", whens, els)


def parse(sql: str):
    p = _Parser(sql)
    q = p.parse_query()
    if p.peek()[0] != "eof":
        raise SqlError(f"trailing tokens at {p.peek()[1]!r}")
    return q
