"""Recursive-descent SQL parser -> untyped AST.

Grammar (case-insensitive keywords):

    query     := [WITH ctes] core ((UNION [ALL]|INTERSECT|EXCEPT) core)*
                 [ORDER BY order (',' order)*] [LIMIT int]
    core      := SELECT [DISTINCT] sel (',' sel)* FROM relation
                 [WHERE expr]
                 [GROUP BY (expr (',' expr)* | ROLLUP '(' exprs ')')]
                 [HAVING expr]
    sel       := expr [[AS] ident] | '*'
    relation  := table_or_sub ([INNER|LEFT [OUTER]|RIGHT [OUTER]|
                 FULL [OUTER]|LEFT SEMI|LEFT ANTI|CROSS] JOIN
                 table_or_sub [ON expr])*
    table_or_sub := ident [[AS] ident] | '(' query ')' [AS] ident
    order     := expr [ASC|DESC] [NULLS FIRST|NULLS LAST]
    expr      := OR-precedence expression grammar with NOT, comparison,
                 BETWEEN, IN (list | subquery), [NOT] EXISTS (subquery),
                 LIKE, IS [NOT] NULL, additive/multiplicative arithmetic,
                 '||' concatenation, unary -, literals, CASE (searched
                 and simple), CAST(e AS type), DATE 'lit', function
                 calls, [table.]column

AST nodes are plain tuples: ('select', {...}), ('col', tab, name),
('lit', value, kind), ('call', name, distinct, args), ('case', whens,
else_), ('cast', e, type), ('star',), binary ops ('and' 'or' 'not'
'cmp' 'arith' 'in' 'between' 'like' 'isnull').
"""
from __future__ import annotations

import re
from typing import List, Optional, Tuple


class SqlError(Exception):
    pass


_TOKEN_RE = re.compile(r"""
    \s+
  | --[^\n]*
  | (?P<num>\d+\.\d*([eE][+-]?\d+)?|\.\d+([eE][+-]?\d+)?|\d+([eE][+-]?\d+)?)
  | (?P<str>'(?:[^']|'')*')
  | (?P<ident>[A-Za-z_][A-Za-z_0-9]*)
  | (?P<op>\|\||<=|>=|<>|!=|=|<|>|\(|\)|,|\*|/|%|\+|-|\.)
""", re.VERBOSE)

_KEYWORDS = {
    "select", "distinct", "from", "where", "group", "by", "having",
    "order", "limit", "as", "and", "or", "not", "in", "between", "like",
    "is", "null", "case", "when", "then", "else", "end", "cast", "join",
    "inner", "left", "right", "full", "outer", "semi", "anti", "cross",
    "on", "asc", "desc", "nulls", "first", "last", "date", "timestamp",
    "true", "false", "interval", "with", "union", "all", "over",
    "partition", "rows", "unbounded", "preceding", "following",
    "current", "row", "exists", "intersect", "except", "rollup",
}


def _tokenize(sql: str) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            raise SqlError(f"cannot tokenize at: {sql[pos:pos+30]!r}")
        pos = m.end()
        if m.lastgroup is None:
            continue  # whitespace/comment
        text = m.group(m.lastgroup)
        kind = m.lastgroup
        if kind == "ident" and text.lower() in _KEYWORDS:
            out.append(("kw", text.lower()))
        else:
            out.append((kind, text))
    out.append(("eof", ""))
    return out


class _Parser:
    def __init__(self, sql: str):
        self.toks = _tokenize(sql)
        self.i = 0

    # -- token helpers -----------------------------------------------------

    def peek(self, k: int = 0):
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_kw(self, *kws: str) -> Optional[str]:
        kind, text = self.peek()
        if kind == "kw" and text in kws:
            self.i += 1
            return text
        return None

    def expect_kw(self, kw: str):
        if not self.accept_kw(kw):
            raise SqlError(f"expected {kw.upper()}, got "
                           f"{self.peek()[1]!r}")

    def accept_op(self, *ops: str) -> Optional[str]:
        kind, text = self.peek()
        if kind == "op" and text in ops:
            self.i += 1
            return text
        return None

    def expect_op(self, op: str):
        if not self.accept_op(op):
            raise SqlError(f"expected {op!r}, got {self.peek()[1]!r}")

    def expect_ident(self) -> str:
        kind, text = self.next()
        if kind != "ident":
            raise SqlError(f"expected identifier, got {text!r}")
        return text

    # -- query -------------------------------------------------------------

    def parse_query(self):
        """query := [WITH ctes] core (UNION [ALL] core)* [ORDER BY ...]
        [LIMIT n]. A plain SELECT keeps the legacy ('select', {...})
        shape; unions return ('union', {...})."""
        ctes = []
        if self.accept_kw("with"):
            while True:
                name = self.expect_ident()
                self.expect_kw("as")
                self.expect_op("(")
                sub = self.parse_query()
                self.expect_op(")")
                ctes.append((name, sub))
                if not self.accept_op(","):
                    break
        core = self.parse_select_core()
        cores = [core]
        setops = []  # ("union", all?) | ("intersect",) | ("except",)
        while True:
            if self.accept_kw("union"):
                setops.append(("union", bool(self.accept_kw("all"))))
            elif self.accept_kw("intersect"):
                if self.accept_kw("all"):
                    raise SqlError("INTERSECT ALL (multiset) unsupported")
                setops.append(("intersect",))
            elif self.accept_kw("except"):
                if self.accept_kw("all"):
                    raise SqlError("EXCEPT ALL (multiset) unsupported")
                setops.append(("except",))
            else:
                break
            cores.append(self.parse_select_core())
        order, limit = self.parse_order_limit()
        # INTERSECT binds tighter than UNION/EXCEPT (SQL standard;
        # Spark AstBuilder): fold runs of INTERSECT into nested set-op
        # nodes before the left-associative UNION/EXCEPT chain
        g_cores = [cores[0]]
        g_ops = []
        for op, c in zip(setops, cores[1:]):
            if op[0] == "intersect":
                prev = g_cores[-1]
                if prev[0] == "union" and prev[1].get("ichain"):
                    prev[1]["cores"].append(c)
                    prev[1]["setops"].append(op)
                else:
                    g_cores[-1] = ("union", {
                        "cores": [prev, c], "setops": [op],
                        "order": [], "limit": None, "ctes": [],
                        "ichain": True})
            else:
                g_ops.append(op)
                g_cores.append(c)
        if len(g_cores) == 1:
            out = g_cores[0]
            out[1]["order"] = order
            out[1]["limit"] = limit
            out[1]["ctes"] = ctes
            return out
        return ("union", {"cores": g_cores, "setops": g_ops,
                          "order": order, "limit": limit, "ctes": ctes})

    def parse_order_limit(self):
        order = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            order = [self.parse_order_item()]
            while self.accept_op(","):
                order.append(self.parse_order_item())
        limit = None
        if self.accept_kw("limit"):
            kind, text = self.next()
            if kind != "num" or not re.fullmatch(r"\d+", text):
                raise SqlError("LIMIT needs an integer")
            limit = int(text)
        return order, limit

    def parse_select_core(self):
        self.expect_kw("select")
        distinct = bool(self.accept_kw("distinct"))
        sels = [self.parse_select_item()]
        while self.accept_op(","):
            sels.append(self.parse_select_item())
        self.expect_kw("from")
        rel = self.parse_relation()
        where = None
        if self.accept_kw("where"):
            where = self.parse_expr()
        group = []
        rollup = False
        if self.accept_kw("group"):
            self.expect_kw("by")
            if self.accept_kw("rollup"):
                rollup = True
                self.expect_op("(")
                group = [self.parse_expr()]
                while self.accept_op(","):
                    group.append(self.parse_expr())
                self.expect_op(")")
            else:
                group = [self.parse_expr()]
                while self.accept_op(","):
                    group.append(self.parse_expr())
        having = None
        if self.accept_kw("having"):
            having = self.parse_expr()
        return ("select", {"distinct": distinct, "sels": sels,
                           "from": rel, "where": where, "group": group,
                           "rollup": rollup, "having": having,
                           "order": [], "limit": None, "ctes": []})

    def parse_select_item(self):
        if self.accept_op("*"):
            return (("star",), None)
        e = self.parse_expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.expect_ident()
        elif self.peek()[0] == "ident":
            alias = self.expect_ident()
        return (e, alias)

    def parse_order_item(self):
        e = self.parse_expr()
        asc = True
        if self.accept_kw("desc"):
            asc = False
        else:
            self.accept_kw("asc")
        nulls_first = asc  # Spark default: ASC->FIRST, DESC->LAST
        if self.accept_kw("nulls"):
            which = self.accept_kw("first", "last")
            if which is None:
                raise SqlError("NULLS must be followed by FIRST/LAST")
            nulls_first = which == "first"
        return (e, asc, nulls_first)

    # -- relations ---------------------------------------------------------

    def parse_relation(self):
        rel = self.parse_joined()
        # comma-separated FROM (the classic TPC syntax): implicit joins
        # whose conditions live in WHERE; the planner hoists them
        while self.accept_op(","):
            rel = ("join", "implicit", rel, self.parse_joined(), None)
        return rel

    def parse_joined(self):
        rel = self.parse_table_or_sub()
        while True:
            kind = None
            if self.accept_kw("cross"):
                kind = "cross"
            elif self.accept_kw("inner"):
                kind = "inner"
            elif self.accept_kw("left"):
                if self.accept_kw("semi"):
                    kind = "left_semi"
                elif self.accept_kw("anti"):
                    kind = "left_anti"
                else:
                    self.accept_kw("outer")
                    kind = "left"
            elif self.accept_kw("right"):
                self.accept_kw("outer")
                kind = "right"
            elif self.accept_kw("full"):
                self.accept_kw("outer")
                kind = "full"
            elif self.peek() == ("kw", "join"):
                kind = "inner"
            if kind is None:
                return rel
            self.expect_kw("join")
            right = self.parse_table_or_sub()
            cond = None
            if self.accept_kw("on"):
                cond = self.parse_expr()
            elif kind != "cross":
                raise SqlError(f"{kind.upper()} JOIN requires ON")
            rel = ("join", kind, rel, right, cond)

    def parse_table_or_sub(self):
        if self.accept_op("("):
            sub = self.parse_query()
            self.expect_op(")")
            self.accept_kw("as")
            alias = self.expect_ident()
            return ("subquery", sub, alias)
        name = self.expect_ident()
        alias = None
        if self.accept_kw("as"):
            alias = self.expect_ident()
        elif self.peek()[0] == "ident":
            alias = self.expect_ident()
        return ("table", name, alias or name)

    # -- expressions (precedence climbing) ---------------------------------

    def parse_expr(self):
        return self.parse_or()

    def parse_or(self):
        e = self.parse_and()
        while self.accept_kw("or"):
            e = ("or", e, self.parse_and())
        return e

    def parse_and(self):
        e = self.parse_not()
        while self.accept_kw("and"):
            e = ("and", e, self.parse_not())
        return e

    def parse_not(self):
        if self.accept_kw("not"):
            return ("not", self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self):
        e = self.parse_additive()
        negate = bool(self.accept_kw("not"))
        if self.accept_kw("between"):
            lo = self.parse_additive()
            self.expect_kw("and")
            hi = self.parse_additive()
            out = ("between", e, lo, hi)
            return ("not", out) if negate else out
        if self.accept_kw("in"):
            self.expect_op("(")
            if self.peek() == ("kw", "select"):
                sub = self.parse_query()
                self.expect_op(")")
                # negation carried in-node: NOT IN (subquery) is an
                # anti-join, not a boolean NOT (null semantics differ)
                return ("in_sub", e, sub, negate)
            vals = [self.parse_expr()]
            while self.accept_op(","):
                vals.append(self.parse_expr())
            self.expect_op(")")
            out = ("in", e, vals)
            return ("not", out) if negate else out
        if self.accept_kw("like"):
            pat = self.parse_additive()
            out = ("like", e, pat)
            return ("not", out) if negate else out
        if negate:
            raise SqlError("dangling NOT before a non-predicate")
        if self.accept_kw("is"):
            isnot = bool(self.accept_kw("not"))
            self.expect_kw("null")
            return ("isnull", e, isnot)
        op = self.accept_op("=", "<>", "!=", "<", "<=", ">", ">=")
        if op:
            rhs = self.parse_additive()
            return ("cmp", op, e, rhs)
        return e

    def parse_additive(self):
        e = self.parse_multiplicative()
        while True:
            op = self.accept_op("+", "-", "||")
            if not op:
                return e
            if op == "||":
                e = ("concat", e, self.parse_multiplicative())
            else:
                e = ("arith", op, e, self.parse_multiplicative())

    def parse_multiplicative(self):
        e = self.parse_unary()
        while True:
            op = self.accept_op("*", "/", "%")
            if not op:
                return e
            e = ("arith", op, e, self.parse_unary())

    def parse_unary(self):
        if self.accept_op("-"):
            return ("neg", self.parse_unary())
        if self.accept_op("+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self):
        kind, text = self.peek()
        if kind == "op" and text == "(":
            self.next()
            if self.peek() in (("kw", "select"), ("kw", "with")):
                sub = self.parse_query()
                self.expect_op(")")
                return ("scalar_sub", sub)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if kind == "num":
            self.next()
            if re.fullmatch(r"\d+", text):
                return ("lit", int(text), "int")
            return ("lit", float(text), "float")
        if kind == "str":
            self.next()
            return ("lit", text[1:-1].replace("''", "'"), "str")
        if kind == "kw":
            if text in ("date", "timestamp"):
                # DATE 'yyyy-mm-dd' literal
                if self.peek(1)[0] == "str":
                    self.next()
                    _, s = self.next()
                    return ("lit", s[1:-1], text)
                # else: fall through (it may be a cast type name usage)
            if text == "null":
                self.next()
                return ("lit", None, "null")
            if text == "interval":
                # INTERVAL 'n' DAY -> day-count marker consumed by +/-
                self.next()
                kind2, s = self.next()
                if kind2 == "str":
                    n = int(s[1:-1])
                elif kind2 == "num":
                    n = int(s)
                else:
                    raise SqlError("INTERVAL needs a number")
                unit = self.expect_ident().lower()
                mult = {"day": 1, "days": 1, "week": 7,
                        "weeks": 7}.get(unit)
                if mult is None:
                    raise SqlError(f"unsupported INTERVAL unit {unit!r}")
                return ("interval", n * mult)
            if text in ("true", "false"):
                self.next()
                return ("lit", text == "true", "bool")
            if text == "case":
                return self.parse_case()
            if text == "exists":
                self.next()
                self.expect_op("(")
                sub = self.parse_query()
                self.expect_op(")")
                return ("exists", sub)
            if text == "cast":
                self.next()
                self.expect_op("(")
                e = self.parse_expr()
                self.expect_kw("as")
                tkind, tname = self.next()
                if tkind not in ("ident", "kw"):
                    raise SqlError(f"bad cast type {tname!r}")
                self.expect_op(")")
                return ("cast", e, tname.lower())
        if kind == "ident":
            # function call or column reference
            if self.peek(1) == ("op", "("):
                name = self.expect_ident().lower()
                self.expect_op("(")
                distinct = bool(self.accept_kw("distinct"))
                args = []
                if self.accept_op("*"):
                    args.append(("star",))
                elif self.peek() != ("op", ")"):
                    args.append(self.parse_expr())
                    while self.accept_op(","):
                        args.append(self.parse_expr())
                self.expect_op(")")
                call = ("call", name, distinct, args)
                if self.accept_kw("over"):
                    return self.parse_over(call)
                return call
            tab_or_col = self.expect_ident()
            if self.accept_op("."):
                col = self.expect_ident()
                return ("col", tab_or_col, col)
            return ("col", None, tab_or_col)
        raise SqlError(f"unexpected token {text!r}")

    def parse_over(self, call):
        """OVER '(' [PARTITION BY exprs] [ORDER BY items]
        [ROWS BETWEEN a AND b] ')' -> ('winfn', call, partition,
        order, frame). Frame bounds: None=unbounded, 0=current row,
        +-n=offset rows; default frame is the SQL standard (whole
        partition without ORDER BY, running with it)."""
        self.expect_op("(")
        partition = []
        if self.accept_kw("partition"):
            self.expect_kw("by")
            partition = [self.parse_expr()]
            while self.accept_op(","):
                partition.append(self.parse_expr())
        order = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            order = [self.parse_order_item()]
            while self.accept_op(","):
                order.append(self.parse_order_item())
        frame = None
        if self.accept_kw("rows"):
            self.expect_kw("between")

            def bound(which):
                if self.accept_kw("unbounded"):
                    self.expect_kw("preceding" if which == "lo"
                                   else "following")
                    return None
                if self.accept_kw("current"):
                    self.expect_kw("row")
                    return 0
                kind, text = self.next()
                if kind != "num" or not re.fullmatch(r"\d+", text):
                    raise SqlError("ROWS bound needs an integer")
                n = int(text)
                if self.accept_kw("preceding"):
                    return -n
                self.expect_kw("following")
                return n

            lo = bound("lo")
            self.expect_kw("and")
            hi = bound("hi")
            frame = (lo, hi)
        self.expect_op(")")
        return ("winfn", call, partition, order, frame)

    def parse_case(self):
        """Searched CASE, plus simple CASE (``CASE e WHEN v THEN r``)
        desugared to ``CASE WHEN e = v THEN r`` (base AST shared)."""
        self.expect_kw("case")
        base = None
        if self.peek() != ("kw", "when"):
            base = self.parse_expr()
        whens = []
        while self.accept_kw("when"):
            c = self.parse_expr()
            if base is not None:
                c = ("cmp", "=", base, c)
            self.expect_kw("then")
            v = self.parse_expr()
            whens.append((c, v))
        els = None
        if self.accept_kw("else"):
            els = self.parse_expr()
        self.expect_kw("end")
        if not whens:
            raise SqlError("CASE requires at least one WHEN")
        return ("case", whens, els)


def parse(sql: str):
    p = _Parser(sql)
    q = p.parse_query()
    if p.peek()[0] != "eof":
        raise SqlError(f"trailing tokens at {p.peek()[1]!r}")
    return q
