from spark_rapids_tpu.columnar import dtypes  # noqa: F401
from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema  # noqa: F401
from spark_rapids_tpu.columnar.column import (  # noqa: F401
    Column,
    Scalar,
    StringColumn,
    unify_dictionaries,
)
