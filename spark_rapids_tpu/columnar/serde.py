"""Host batch representation + columnar wire format.

The TPU-native replacement for the pair of mechanisms the reference uses to
move batches off-device:

- ``TableMeta`` flatbuffers describing a serialized table (sql-plugin/src/
  main/java/.../format/TableMeta.java:59; built by MetaUtils.scala:144), and
- ``JCudfSerialization`` host write/read of columnar buffers
  (GpuColumnarBatchSerializer.scala:80-91,148).

One format serves three consumers — the host/disk spill tiers (§2.3), the
host-path shuffle serializer, and broadcast exchange — exactly like the
reference reuses TableMeta across spill and shuffle.

Layout of the serialized stream::

    MAGIC(4) | header_len(4, LE) | header(JSON, utf-8) | buffers...

The JSON header carries schema dtypes, row count, capacity, per-column
buffer sizes, validity presence and string dictionaries; buffers follow
contiguously in column order (data then validity per column). Buffers are
raw little-endian numpy bytes so the read side can ``np.frombuffer``
zero-copy off a memoryview.
"""
from __future__ import annotations

import dataclasses
import io
import json
import struct
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.columnar.column import Column, StringColumn

MAGIC = b"SRT0"


@dataclasses.dataclass
class HostColumn:
    """One column's host mirror (RapidsHostColumnVector.java analogue)."""

    dtype: dt.DType
    data: np.ndarray                       # (capacity,) kernel-dtype values
    validity: Optional[np.ndarray]         # (capacity,) bool, or None
    dictionary: Optional[np.ndarray] = None  # object[str] for STRING

    def nbytes(self) -> int:
        n = self.data.nbytes
        if self.validity is not None:
            n += self.validity.nbytes
        if self.dictionary is not None:
            n += sum(len(s.encode("utf-8")) + 4 for s in self.dictionary)
        return n


@dataclasses.dataclass
class HostBatch:
    """A ColumnarBatch materialized to host memory. ``num_rows`` is always a
    realized Python int here (host code needs real sizes)."""

    columns: List[HostColumn]
    num_rows: int

    @property
    def capacity(self) -> int:
        return len(self.columns[0].data) if self.columns else 0

    def nbytes(self) -> int:
        return sum(c.nbytes() for c in self.columns)


def to_host_batch(batch: ColumnarBatch) -> HostBatch:
    """Device→host copy (the D2H half of GpuColumnarBatchSerializer's write,
    GpuColumnarBatchSerializer.scala:80-91)."""
    n = batch.realized_num_rows()
    arrays = []
    for c in batch.columns:
        arrays.append(c.data)
        if c.validity is not None:
            arrays.append(c.validity)
    host = jax.device_get(arrays)  # one transfer round
    it = iter(host)
    cols: List[HostColumn] = []
    for c in batch.columns:
        data = np.asarray(next(it))
        validity = np.asarray(next(it)) if c.validity is not None else None
        dictionary = c.dictionary if isinstance(c, StringColumn) else None
        cols.append(HostColumn(c.dtype, data, validity, dictionary))
    return HostBatch(cols, n)


def to_device_batch(hb: HostBatch) -> ColumnarBatch:
    """Host→device upload (HostColumnarToGpu.scala:31 analogue)."""
    cols: List[Column] = []
    for hc in hb.columns:
        data = jnp.asarray(hc.data)
        validity = jnp.asarray(hc.validity) if hc.validity is not None \
            else None
        if hc.dtype is dt.STRING:
            cols.append(StringColumn(
                data,
                hc.dictionary if hc.dictionary is not None
                else np.array([], dtype=object),
                validity))
        else:
            cols.append(Column(hc.dtype, data, validity))
    return ColumnarBatch(cols, hb.num_rows)


def _np_wire(arr: np.ndarray) -> np.ndarray:
    """Ensure little-endian contiguous for raw-bytes wire format."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype.byteorder == ">":
        arr = arr.astype(arr.dtype.newbyteorder("<"))
    return arr


def serialize_host_batch(hb: HostBatch, out: Optional[io.RawIOBase] = None
                         ) -> Optional[bytes]:
    """Write the wire format; returns bytes if ``out`` is None."""
    from spark_rapids_tpu import native

    buffers: List[bytes] = []
    col_headers = []
    for hc in hb.columns:
        data = _np_wire(hc.data)
        hdr = {
            "dtype": hc.dtype.name,
            "np": data.dtype.str,
            "len": int(data.shape[0]),
            "has_validity": hc.validity is not None,
            # validity travels as an LSB-first bitmap (8x smaller; the
            # packed-validity layout cudf uses on the wire)
            "validity_packed": True,
        }
        buffers.append(data.tobytes())
        if hc.validity is not None:
            buffers.append(native.pack_bits(
                np.ascontiguousarray(hc.validity, dtype=np.uint8)))
        if hc.dictionary is not None:
            hdr["dictionary"] = [str(s) for s in hc.dictionary]
        col_headers.append(hdr)
    header = json.dumps({
        "num_rows": hb.num_rows,
        "columns": col_headers,
    }).encode("utf-8")
    stream = out or io.BytesIO()
    stream.write(MAGIC)
    stream.write(struct.pack("<I", len(header)))
    stream.write(header)
    for b in buffers:
        stream.write(b)
    if out is None:
        return stream.getvalue()
    return None


def deserialize_host_batch(data: bytes) -> HostBatch:
    mv = memoryview(data)
    if bytes(mv[:4]) != MAGIC:
        raise ValueError("bad magic in serialized batch")
    (hlen,) = struct.unpack("<I", mv[4:8])
    header = json.loads(bytes(mv[8:8 + hlen]).decode("utf-8"))
    off = 8 + hlen
    cols: List[HostColumn] = []
    for ch in header["columns"]:
        dtype = dt.by_name(ch["dtype"])
        np_dt = np.dtype(ch["np"])
        n = ch["len"]
        nbytes = np_dt.itemsize * n
        arr = np.frombuffer(mv[off:off + nbytes], dtype=np_dt)
        off += nbytes
        validity = None
        if ch["has_validity"]:
            if ch.get("validity_packed"):
                from spark_rapids_tpu import native

                nbits = (n + 7) // 8
                validity = native.unpack_bits(bytes(mv[off:off + nbits]),
                                              n)
                off += nbits
            else:
                validity = np.frombuffer(mv[off:off + n], dtype=np.bool_)
                off += n
        dictionary = None
        if "dictionary" in ch:
            dictionary = np.array(ch["dictionary"], dtype=object)
        cols.append(HostColumn(dtype, arr, validity, dictionary))
    return HostBatch(cols, header["num_rows"])


def schema_of(hb: HostBatch, names: Optional[Sequence[str]] = None) -> Schema:
    names = list(names) if names is not None \
        else [f"c{i}" for i in range(len(hb.columns))]
    return Schema(names, [c.dtype for c in hb.columns])
