"""Host <-> device columnar conversion.

Covers the reference's transition surface in one place:
- ``GpuRowToColumnarExec`` row->columnar converters (GpuRowToColumnarExec.scala:45-134)
- ``GpuColumnarToRowExec`` device->host row iteration (GpuColumnarToRowExec.scala:111)
- ``HostColumnarToGpu`` arrow/cached-batch upload (HostColumnarToGpu.scala:31)

Host decode rides pyarrow (the CPU half of the reference's scan path reads
and assembles host buffers before the device decode, GpuParquetScan.scala:228-265);
the upload is a single ``jnp.asarray`` per column into a bucketed buffer.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.columnar.column import Column, StringColumn
from spark_rapids_tpu.ops.buckets import bucket_capacity


def from_arrow_table(table, capacity: Optional[int] = None
                     ) -> Tuple[ColumnarBatch, Schema]:
    """Upload a pyarrow Table/RecordBatch to a device ColumnarBatch."""
    import pyarrow as pa

    if isinstance(table, pa.RecordBatch):
        table = pa.Table.from_batches([table])
    n = table.num_rows
    cap = capacity or bucket_capacity(n)
    names, types, cols = [], [], []
    for field, chunked in zip(table.schema, table.columns):
        dtype = dt.from_arrow(field.type)
        arr = chunked.combine_chunks() if chunked.num_chunks != 1 \
            else chunked.chunk(0)
        names.append(field.name)
        types.append(dtype)
        cols.append(_arrow_array_to_column(arr, dtype, cap))
    return ColumnarBatch(cols, n), Schema(names, types)


def _arrow_array_to_column(arr, dtype: dt.DType, cap: int) -> Column:
    import pyarrow as pa
    import pyarrow.compute as pc

    validity = None
    if arr.null_count:
        validity = np.asarray(pc.is_valid(arr))
    if dtype is dt.STRING:
        if pa.types.is_dictionary(arr.type):
            arr = pc.cast(arr, pa.string())
        pylist = arr.to_pylist()
        return StringColumn.from_strings(pylist, capacity=cap)
    if dtype is dt.TIMESTAMP:
        np_vals = np.asarray(pc.cast(arr, pa.int64()).fill_null(0))
    elif dtype is dt.DATE:
        np_vals = np.asarray(pc.cast(arr, pa.int32()).fill_null(0))
    else:
        np_vals = np.asarray(arr.fill_null(dt.null_sentinel(dtype))
                             if arr.null_count else arr)
    return Column.from_numpy(np_vals, dtype=dtype, validity=validity,
                             capacity=cap)


def to_arrow_table(batch: ColumnarBatch, schema: Schema):
    """Download a device batch into a pyarrow Table (write path)."""
    import pyarrow as pa

    n = batch.realized_num_rows()
    arrays = []
    for c, t in zip(batch.columns, schema.types):
        values, validity = c.to_numpy(n)
        pa_type = dt.to_arrow(t)
        if isinstance(c, StringColumn):
            arrays.append(pa.array(list(values), type=pa_type))
        else:
            mask = None if validity is None else ~validity
            arrays.append(pa.array(values, type=pa_type, mask=mask))
    return pa.table(dict(zip(schema.names, arrays)))


def from_pandas(df, capacity: Optional[int] = None
                ) -> Tuple[ColumnarBatch, Schema]:
    import pyarrow as pa

    return from_arrow_table(pa.Table.from_pandas(df, preserve_index=False),
                            capacity=capacity)


def rows_to_columnar(rows: Sequence[Sequence], schema: Schema,
                     capacity: Optional[int] = None) -> ColumnarBatch:
    """Row->columnar conversion (GpuRowToColumnarExec analogue). Per-column
    host builders then one upload each."""
    n = len(rows)
    cap = capacity or bucket_capacity(n)
    cols: List[Column] = []
    for j, t in enumerate(schema.types):
        vals = [r[j] for r in rows]
        if t is dt.STRING:
            cols.append(StringColumn.from_strings(vals, capacity=cap))
            continue
        validity = np.array([v is not None for v in vals], dtype=bool)
        filled = np.array(
            [v if v is not None else dt.null_sentinel(t) for v in vals],
            dtype=t.np_dtype)
        cols.append(Column.from_numpy(
            filled, dtype=t,
            validity=None if validity.all() else validity, capacity=cap))
    return ColumnarBatch(cols, n)


def columnar_to_rows(batch: ColumnarBatch) -> List[tuple]:
    """Device->host row materialization (GpuColumnarToRowExec analogue)."""
    n = batch.realized_num_rows()
    mats = []
    for c in batch.columns:
        values, validity = c.to_numpy(n)
        mats.append((values, validity))
    rows = []
    for i in range(n):
        row = []
        for values, validity in mats:
            if validity is not None and not validity[i]:
                row.append(None)
            else:
                v = values[i]
                row.append(v.item() if isinstance(v, np.generic) else v)
        rows.append(tuple(row))
    return rows
