"""Columnar batches: the unit of execution.

TPU-native analogue of Spark's ``ColumnarBatch`` carrying ``GpuColumnVector``s
(GpuColumnVector.java:252-276 from/to batch conversions). Key differences:

- ``num_rows`` may be a **device scalar** (0-d int32 array): kernels like
  filter and groupby produce data-dependent row counts; we leave the count
  on device until a consumer genuinely needs the Python int (coalescing
  decisions, shuffle sizing, host materialization). That keeps chains of
  jitted kernels free of host syncs — the TPU version of cuDF's
  "row count comes back with the table" behavior without blocking.
- all columns share one bucketed capacity >= num_rows.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.column import Column, StringColumn

RowCount = Union[int, jax.Array]


class Schema:
    """Ordered (name, DType) pairs. Plan attributes reference columns by
    ordinal after binding (GpuBoundReference analogue), names matter at the
    API/IO boundary."""

    __slots__ = ("names", "types")

    def __init__(self, names: Sequence[str], types: Sequence[dt.DType]):
        assert len(names) == len(types)
        self.names = list(names)
        self.types = list(types)

    def __len__(self) -> int:
        return len(self.names)

    def index_of(self, name: str) -> int:
        return self.names.index(name)

    def field(self, i: int):
        return self.names[i], self.types[i]

    def __repr__(self) -> str:  # pragma: no cover
        return "Schema(" + ", ".join(
            f"{n}:{t}" for n, t in zip(self.names, self.types)) + ")"


class ColumnarBatch:
    __slots__ = ("columns", "_num_rows", "origin")

    def __init__(self, columns: List[Column], num_rows: RowCount,
                 origin=None):
        self.columns = columns
        self._num_rows = num_rows
        #: (file_path, block_start, block_length) when this batch came
        #: straight from one file split (input_file_name support,
        #: GpuInputFileBlock.scala); transforms drop it — Spark's
        #: input_file_name is likewise only defined directly above scans
        self.origin = origin
        if columns:
            cap = columns[0].capacity
            assert all(c.capacity == cap for c in columns), \
                "all columns in a batch must share one capacity"

    # -- shape ------------------------------------------------------------

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    @property
    def capacity(self) -> int:
        return self.columns[0].capacity if self.columns else 0

    @property
    def num_rows(self) -> RowCount:
        """May be a device scalar; prefer this in jitted code."""
        return self._num_rows

    def num_rows_device(self) -> jax.Array:
        if isinstance(self._num_rows, int):
            return jnp.asarray(self._num_rows, dtype=jnp.int32)
        return self._num_rows

    def realized_num_rows(self) -> int:
        """Force the row count to the host (sync point — use sparingly,
        at batch boundaries only)."""
        if not isinstance(self._num_rows, int):
            self._num_rows = int(jax.device_get(self._num_rows))
        return self._num_rows

    @staticmethod
    def realize_counts(batches: "List[ColumnarBatch]") -> List[int]:
        """Realize MANY batches' lazy counts in ONE device_get — N
        separate syncs each pay the full tunnel RTT (~105 ms)."""
        lazy = [b for b in batches
                if not isinstance(b._num_rows, int)]
        if lazy:
            vals = jax.device_get([b._num_rows for b in lazy])
            for b, v in zip(lazy, vals):
                b._num_rows = int(v)
        return [b._num_rows for b in batches]

    def row_mask(self) -> jax.Array:
        """lane-mask of live rows: iota < num_rows."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < \
            self.num_rows_device()

    def device_memory_size(self) -> int:
        return sum(c.device_memory_size() for c in self.columns)

    # -- construction -----------------------------------------------------

    @staticmethod
    def empty(schema: Schema) -> "ColumnarBatch":
        cols: List[Column] = []
        from spark_rapids_tpu.ops.buckets import MIN_CAPACITY
        for t in schema.types:
            if t is dt.STRING:
                cols.append(StringColumn(
                    jnp.zeros(MIN_CAPACITY, dtype=jnp.int32),
                    np.array([], dtype=object)))
            else:
                cols.append(Column(
                    t, jnp.zeros(MIN_CAPACITY, dtype=t.kernel_dtype)))
        return ColumnarBatch(cols, 0)

    @staticmethod
    def rows_only(num_rows: int) -> "ColumnarBatch":
        """Degenerate batch: rows but no columns (the reference round-trips
        these through shuffle as metadata-only, MetaUtils.scala:144)."""
        return ColumnarBatch([], num_rows)

    def select(self, ordinals: Sequence[int]) -> "ColumnarBatch":
        return ColumnarBatch([self.columns[i] for i in ordinals],
                             self._num_rows)

    def with_columns(self, columns: List[Column]) -> "ColumnarBatch":
        return ColumnarBatch(columns, self._num_rows)

    def slice(self, start: int, length: int) -> "ColumnarBatch":
        """Zero-copy-ish row range view (SlicedGpuColumnVector analogue).
        Result is re-bucketed to the smallest capacity holding ``length``."""
        from spark_rapids_tpu.ops.buckets import bucket_capacity
        n = self.realized_num_rows()
        start = max(0, min(start, n))
        length = max(0, min(length, n - start))
        cap = bucket_capacity(length)
        cols = []
        for c in self.columns:
            grown = c.with_capacity(max(cap + start, c.capacity))
            data = jax.lax.dynamic_slice_in_dim(grown.data, start, cap)
            validity = None
            if grown.validity is not None:
                validity = jax.lax.dynamic_slice_in_dim(
                    grown.validity, start, cap)
            cols.append(c._like(data, validity))
        return ColumnarBatch(cols, length)

    # -- host materialization --------------------------------------------

    def to_pandas(self, schema: Optional[Schema] = None):
        import pandas as pd

        # ONE device->host transfer for the whole batch: every column's
        # data + validity and the (possibly lazy) row count ride a
        # single device_get — per-column fetches each pay the full
        # tunnel RTT (~105 ms on the axon backend)
        import jax

        fetched = jax.device_get((
            [c.data for c in self.columns],
            [c.validity for c in self.columns],
            None if isinstance(self._num_rows, int) else self._num_rows))
        datas, valids, n_dev = fetched
        if n_dev is not None:
            self._num_rows = int(n_dev)
        n = self._num_rows
        data = {}
        for i, c in enumerate(self.columns):
            name = schema.names[i] if schema else f"c{i}"
            values, validity = c._decode_host(datas[i], valids[i], n)
            if validity is not None and not isinstance(c, StringColumn):
                # preserve SQL NULLs: use pandas nullable / object via mask
                values = values.astype(object)
                values[~validity] = None
            if values.dtype == object:
                # explicit object Series: pandas 3's frame constructor
                # infers a string dtype from object arrays and coerces
                # None->NaN, losing SQL NULL-ness
                data[name] = pd.Series(values, dtype=object)
            else:
                data[name] = values
        df = pd.DataFrame(data)
        return df

    def __repr__(self) -> str:  # pragma: no cover
        nr = self._num_rows if isinstance(self._num_rows, int) else "<device>"
        return f"ColumnarBatch(cols={self.num_columns}, rows={nr}, cap={self.capacity})"
