"""Logical SQL type system and its mapping onto JAX array dtypes.

The reference supports (v0.3): bool, byte/short/int/long, float/double, date,
timestamp (UTC only) and string — no decimal/arrays/maps/structs
(reference: sql-plugin/.../GpuOverrides.scala:442-454). We mirror that type
matrix. Physical encodings are chosen for the TPU:

- DATE       -> int32 days since unix epoch (Spark's internal encoding)
- TIMESTAMP  -> int64 microseconds since epoch, UTC only (GpuOverrides.scala:341)
- STRING     -> dictionary encoding: int32 codes into a *sorted* host-side
  dictionary, so ordering/equality on codes equals ordering/equality on the
  strings (see columnar/column.py). cuDF's native string columns
  (offsets+bytes) have no XLA analogue; sorted-dictionary codes keep every
  relational kernel (sort/join/groupby/comparisons) purely numeric on device.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class DType:
    """A logical SQL data type.

    ``kernel_dtype`` is the physical jnp dtype used on device.
    """

    name: str
    kernel_dtype: Any  # np/jnp dtype
    byte_width: int
    is_numeric: bool = False
    is_floating: bool = False
    is_integral: bool = False

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return self.name

    def __reduce__(self):
        # types are compared by IDENTITY throughout (``typ is STRING``);
        # pickling by value would mint lookalike instances on the far
        # side of a remote-task boundary, so unpickle to the singleton
        return (by_name, (self.name,))

    @property
    def np_dtype(self):
        return np.dtype(self.kernel_dtype)


BOOLEAN = DType("boolean", jnp.bool_, 1)
INT8 = DType("tinyint", jnp.int8, 1, is_numeric=True, is_integral=True)
INT16 = DType("smallint", jnp.int16, 2, is_numeric=True, is_integral=True)
INT32 = DType("int", jnp.int32, 4, is_numeric=True, is_integral=True)
INT64 = DType("bigint", jnp.int64, 8, is_numeric=True, is_integral=True)
FLOAT32 = DType("float", jnp.float32, 4, is_numeric=True, is_floating=True)
FLOAT64 = DType("double", jnp.float64, 8, is_numeric=True, is_floating=True)
# Physical: int32 days since epoch.
DATE = DType("date", jnp.int32, 4)
# Physical: int64 microseconds since epoch (UTC).
TIMESTAMP = DType("timestamp", jnp.int64, 8)
# Physical: int32 dictionary codes (the dictionary itself lives host-side).
STRING = DType("string", jnp.int32, 4)

ALL_TYPES = [BOOLEAN, INT8, INT16, INT32, INT64, FLOAT32, FLOAT64, DATE,
             TIMESTAMP, STRING]
_BY_NAME = {t.name: t for t in ALL_TYPES}

INTEGRAL_TYPES = [INT8, INT16, INT32, INT64]
FRACTIONAL_TYPES = [FLOAT32, FLOAT64]
NUMERIC_TYPES = INTEGRAL_TYPES + FRACTIONAL_TYPES


def by_name(name: str) -> DType:
    return _BY_NAME[name]


def is_supported(dt: DType) -> bool:
    """Type-support gate, mirrors GpuOverrides.isSupportedType
    (reference GpuOverrides.scala:440-454)."""
    return dt in ALL_TYPES


_ARROW_MAP = {
    "bool": BOOLEAN,
    "int8": INT8,
    "int16": INT16,
    "int32": INT32,
    "int64": INT64,
    "float": FLOAT32,
    "float32": FLOAT32,
    "double": FLOAT64,
    "float64": FLOAT64,
    "date32[day]": DATE,
    "string": STRING,
    "large_string": STRING,
}


def from_arrow(arrow_type) -> DType:
    """Map a pyarrow DataType to a logical DType."""
    s = str(arrow_type)
    if s in _ARROW_MAP:
        return _ARROW_MAP[s]
    if s.startswith("timestamp"):
        return TIMESTAMP
    if s.startswith("dictionary"):
        return STRING
    raise TypeError(f"unsupported arrow type: {arrow_type}")


def to_arrow(dt: DType):
    import pyarrow as pa

    return {
        "boolean": pa.bool_(),
        "tinyint": pa.int8(),
        "smallint": pa.int16(),
        "int": pa.int32(),
        "bigint": pa.int64(),
        "float": pa.float32(),
        "double": pa.float64(),
        "date": pa.date32(),
        "timestamp": pa.timestamp("us", tz="UTC"),
        "string": pa.string(),
    }[dt.name]


def common_type(a: DType, b: DType) -> DType:
    """Numeric type promotion for binary expressions (Spark's findTightestCommonType
    subset for our supported matrix)."""
    if a is b:
        return a
    order = {INT8: 0, INT16: 1, INT32: 2, INT64: 3, FLOAT32: 4, FLOAT64: 5}
    if a in order and b in order:
        # int64 + float32 -> float64 to avoid precision loss (Spark behavior)
        if {a, b} == {INT64, FLOAT32}:
            return FLOAT64
        return a if order[a] >= order[b] else b
    raise TypeError(f"no common type for {a} and {b}")


def null_sentinel(dt: DType):
    """Value stored in data slots whose validity bit is false. Any value is
    semantically fine (kernels must consult validity); we pick ones that make
    min/max aggregations and sorts easy to mask."""
    if dt.is_floating:
        return np.nan
    if dt is BOOLEAN:
        return False
    return 0  # STRING null slots hold code 0 so gathers stay in-bounds
