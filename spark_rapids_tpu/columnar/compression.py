"""Pluggable table compression (TableCompressionCodec analogue,
TableCompressionCodec.scala:41,107; the reference's production codec is
nvcomp LZ4, NvcompLZ4CompressionCodec.scala:25).

Codecs wrap serialized-batch payloads for shuffle and disk spill in a
self-describing envelope::

    SRTC(4) | codec_id(1) | raw_len(8, LE) | crc32c(4, LE) | body

so readers never need out-of-band codec configuration (spill files and
shuffle blocks decode wherever they land), and corruption is caught by the
checksum before a bad buffer reaches a kernel.
"""
from __future__ import annotations

import struct
import zlib
from typing import Dict

from spark_rapids_tpu import native

ENVELOPE_MAGIC = b"SRTC"

_CODEC_IDS = {"none": 0, "lz4": 1, "zlib": 2}
_ID_CODECS = {v: k for k, v in _CODEC_IDS.items()}


class Codec:
    name = "none"

    def compress(self, data: bytes) -> bytes:
        return data

    def decompress(self, data: bytes, raw_len: int) -> bytes:
        return data


class Lz4Codec(Codec):
    """LZ4 block format via the native library (pure-Python fallback
    writes a literal-only stream, still valid LZ4)."""

    name = "lz4"

    def compress(self, data: bytes) -> bytes:
        return native.lz4_compress(data)

    def decompress(self, data: bytes, raw_len: int) -> bytes:
        return native.lz4_decompress(data, raw_len)


class ZlibCodec(Codec):
    name = "zlib"

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, level=1)

    def decompress(self, data: bytes, raw_len: int) -> bytes:
        out = zlib.decompress(data)
        if len(out) != raw_len:
            raise ValueError("zlib length mismatch")
        return out


_CODECS: Dict[str, Codec] = {
    "none": Codec(),
    "lz4": Lz4Codec(),
    "zlib": ZlibCodec(),
}


def get_codec(name: str) -> Codec:
    try:
        return _CODECS[name]
    except KeyError:
        raise ValueError(
            f"unknown compression codec {name!r}; "
            f"choose from {sorted(_CODECS)}") from None


def wrap(payload: bytes, codec_name: str) -> bytes:
    codec = get_codec(codec_name)
    body = codec.compress(payload)
    if codec_name != "none" and len(body) >= len(payload):
        codec_name, body = "none", payload  # incompressible: store raw
    crc = native.crc32c(body)
    return (ENVELOPE_MAGIC + struct.pack("<BQI", _CODEC_IDS[codec_name],
                                         len(payload), crc) + body)


def unwrap(data: bytes) -> bytes:
    mv = memoryview(data)
    if bytes(mv[:4]) != ENVELOPE_MAGIC:
        return data  # legacy/uncompressed stream
    if len(mv) < 17:
        raise ValueError(
            f"truncated compression envelope: {len(mv)} bytes, header "
            f"needs 17 (corrupted spill/shuffle payload)")
    codec_id, raw_len, crc = struct.unpack("<BQI", mv[4:17])
    body = bytes(mv[17:])
    if native.crc32c(body) != crc:
        raise ValueError("compression envelope checksum mismatch "
                         "(corrupted spill/shuffle payload)")
    codec = get_codec(_ID_CODECS[codec_id])
    return codec.decompress(body, raw_len)
