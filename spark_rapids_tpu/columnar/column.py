"""Device columns: JAX-array-backed columnar vectors with validity.

The TPU-native replacement for ``GpuColumnVector`` over cuDF columns
(sql-plugin/src/main/java/.../GpuColumnVector.java:39). Differences driven by
XLA:

- **Bucketed capacity**: ``data`` always has a power-of-two length >= the
  logical row count (see ops/buckets.py); the row count lives on the owning
  batch. cuDF columns are exact-sized; ours are padded so jitted kernels
  compile a bounded number of shape variants.
- **Validity**: a boolean mask array (True = valid) instead of a packed
  bitmask; XLA fuses mask math into the consuming kernels for free. ``None``
  means all-valid.
- **Strings**: cuDF has native offset+bytes string columns; XLA has no
  ragged type. ``StringColumn`` dictionary-encodes: int32 codes into a
  *sorted* host-side dictionary, making code order == lexicographic order,
  so every relational kernel (sort/join/groupby/compare) stays numeric and
  on-device. Cross-column string ops first unify dictionaries host-side.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.ops.buckets import bucket_capacity


class Scalar:
    """A typed scalar (GpuScalar analogue). ``value`` is a host Python value;
    None means a typed NULL."""

    __slots__ = ("dtype", "value")

    def __init__(self, dtype: dt.DType, value):
        self.dtype = dtype
        self.value = value

    @property
    def is_null(self) -> bool:
        return self.value is None

    def __repr__(self) -> str:  # pragma: no cover
        return f"Scalar({self.dtype}, {self.value})"


class Column:
    """A device column: ``data`` (capacity,) + optional validity mask.

    ``stats`` optionally holds host-known (min, max) value bounds (from
    file footer statistics or an upload-time pass). Kernels use them to
    pick narrow packed-key paths (ops/groupby); transforms drop them —
    they are never propagated through expressions."""

    __slots__ = ("dtype", "data", "validity", "stats")

    def __init__(self, dtype: dt.DType, data: jax.Array,
                 validity: Optional[jax.Array] = None,
                 stats=None):
        self.dtype = dtype
        self.data = data
        self.validity = validity
        self.stats = stats

    # -- construction -----------------------------------------------------

    @staticmethod
    def host_buffer(values: np.ndarray,
                    dtype: Optional[dt.DType] = None,
                    validity: Optional[np.ndarray] = None,
                    capacity: Optional[int] = None):
        """The host half of from_numpy: (np_buf, np_vmask|None, dtype).
        Callers with many columns batch the buffers into ONE device_put
        (per-column uploads each occupy a tunnel round trip)."""
        values = np.asarray(values)
        if dtype is None:
            dtype = _infer_dtype(values.dtype)
        n = len(values)
        cap = capacity or bucket_capacity(n)
        kd = dtype.np_dtype
        buf = np.zeros(cap, dtype=kd)
        buf[:n] = values.astype(kd, copy=False)
        vmask = None
        if validity is not None:
            vm = np.zeros(cap, dtype=bool)
            vm[:n] = validity
            # normalize null slots to the sentinel so padded garbage can't
            # leak through kernels that forget to mask (defense in depth)
            buf[:n][~np.asarray(validity, dtype=bool)] = dt.null_sentinel(dtype)
            vmask = vm
        return buf, vmask, dtype

    @staticmethod
    def from_numpy(values: np.ndarray, dtype: Optional[dt.DType] = None,
                   validity: Optional[np.ndarray] = None,
                   capacity: Optional[int] = None) -> "Column":
        buf, vmask, dtype = Column.host_buffer(values, dtype, validity,
                                               capacity)
        return Column(dtype, jnp.asarray(buf),
                      None if vmask is None else jnp.asarray(vmask))

    @staticmethod
    def all_null(dtype: dt.DType, capacity: int) -> "Column":
        data = jnp.zeros(capacity, dtype=dtype.kernel_dtype)
        if dtype is dt.STRING:
            import numpy as _np

            return StringColumn(data.astype(jnp.int32),
                                _np.array([], dtype=object),
                                jnp.zeros(capacity, dtype=bool))
        return Column(dtype, data, jnp.zeros(capacity, dtype=bool))

    @staticmethod
    def from_scalar(scalar: Scalar, capacity: int) -> "Column":
        if scalar.is_null:
            return Column.all_null(scalar.dtype, capacity)
        data = jnp.full(capacity, scalar.value,
                        dtype=scalar.dtype.kernel_dtype)
        return Column(scalar.dtype, data)

    # -- properties -------------------------------------------------------

    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    @property
    def has_nulls_possible(self) -> bool:
        return self.validity is not None

    def device_memory_size(self) -> int:
        """Bytes on device (GpuColumnVector.getTotalDeviceMemoryUsed
        analogue, GpuColumnVector.java:410)."""
        sz = self.capacity * self.dtype.byte_width
        if self.validity is not None:
            sz += self.capacity  # bool mask, 1B/lane
        return sz

    def validity_or_true(self) -> jax.Array:
        if self.validity is None:
            return jnp.ones(self.capacity, dtype=bool)
        return self.validity

    # -- basic transforms (host-orchestrated; heavy lifting in ops/) ------

    def gather(self, indices: jax.Array,
               in_bounds_mask: Optional[jax.Array] = None) -> "Column":
        """Row gather; rows where ``in_bounds_mask`` is False become null."""
        data = jnp.take(self.data, indices, mode="clip")
        validity = None
        if self.validity is not None:
            validity = jnp.take(self.validity, indices, mode="fill",
                                fill_value=False)
        if in_bounds_mask is not None:
            validity = in_bounds_mask if validity is None \
                else (validity & in_bounds_mask)
        return self._like(data, validity)

    def with_capacity(self, new_capacity: int) -> "Column":
        cap = self.capacity
        if new_capacity == cap:
            return self
        if new_capacity < cap:
            data = self.data[:new_capacity]
            validity = None if self.validity is None \
                else self.validity[:new_capacity]
        else:
            pad = new_capacity - cap
            data = jnp.concatenate(
                [self.data, jnp.zeros(pad, dtype=self.data.dtype)])
            validity = None
            if self.validity is not None:
                validity = jnp.concatenate(
                    [self.validity, jnp.zeros(pad, dtype=bool)])
        return self._like(data, validity)

    def _like(self, data, validity) -> "Column":
        """Rebuild preserving subclass payload (dictionary for strings)."""
        if isinstance(self, StringColumn):
            return StringColumn(data, self.dictionary, validity)
        return Column(self.dtype, data, validity)

    # -- host materialization --------------------------------------------

    def to_numpy(self, num_rows: int) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Returns (values, validity) trimmed to num_rows; validity None if
        all-valid. String columns return an object array of str/None."""
        data, validity = jax.device_get((self.data, self.validity))
        return self._decode_host(data, validity, num_rows)

    def _decode_host(self, data, validity, num_rows: int
                     ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Host-side tail of to_numpy over ALREADY-FETCHED arrays —
        batch.to_pandas prefetches every column in ONE device_get (each
        separate fetch pays the full tunnel RTT)."""
        data = np.asarray(data)[:num_rows]
        if validity is not None:
            validity = np.asarray(validity)[:num_rows]
            if bool(validity.all()):
                validity = None
        return data, validity

    def __repr__(self) -> str:  # pragma: no cover
        return (f"{type(self).__name__}({self.dtype}, cap={self.capacity}, "
                f"nulls={'?' if self.validity is not None else 'no'})")


class StringColumn(Column):
    """Dictionary-encoded string column.

    ``data`` holds int32 codes; ``dictionary`` is a host-side numpy object
    array of unique strings sorted ascending, so ``code_a < code_b`` iff
    ``str_a < str_b`` whenever two columns share a dictionary. This is the
    TPU stand-in for cuDF native string columns (SURVEY.md §7 "Strings").
    """

    # _dict_hashes: per-dictionary-entry content hashes, lazily filled by
    # ops.hashing.dict_hashes (without the slot the cache write silently
    # failed and every join/partition re-hashed the dictionary)
    __slots__ = ("dictionary", "_dict_hashes")

    def __init__(self, codes: jax.Array, dictionary: np.ndarray,
                 validity: Optional[jax.Array] = None):
        super().__init__(dt.STRING, codes, validity)
        self.dictionary = dictionary
        self._dict_hashes = None

    @staticmethod
    def host_codes(values: Sequence[Optional[str]],
                   capacity: Optional[int] = None):
        """Host half of from_strings: (codes_np, vmask_np|None,
        dictionary) for batched uploads."""
        n = len(values)
        cap = capacity or bucket_capacity(n)
        arr = np.asarray(values, dtype=object)
        null_mask = np.array([v is None for v in arr], dtype=bool)
        non_null = arr[~null_mask].astype(str) if (~null_mask).any() \
            else np.array([], dtype=str)
        dictionary, inv = (np.unique(non_null, return_inverse=True)
                           if len(non_null) else
                           (np.array([], dtype=object), np.array([], int)))
        codes = np.zeros(cap, dtype=np.int32)
        codes_valid = np.zeros(n, dtype=np.int32)
        codes_valid[~null_mask] = inv.astype(np.int32)
        codes[:n] = codes_valid
        vmask = None
        if null_mask.any():
            vmask = np.zeros(cap, dtype=bool)
            vmask[:n] = ~null_mask
        return codes, vmask, np.asarray(dictionary, dtype=object)

    @staticmethod
    def from_strings(values: Sequence[Optional[str]],
                     capacity: Optional[int] = None) -> "StringColumn":
        codes, vmask, dictionary = StringColumn.host_codes(values,
                                                           capacity)
        validity = None if vmask is None else jnp.asarray(vmask)
        return StringColumn(jnp.asarray(codes),
                            dictionary.astype(object), validity)

    def _decode_host(self, data, validity, num_rows: int):
        codes, validity = Column._decode_host(self, data, validity,
                                              num_rows)
        if len(self.dictionary):
            out = self.dictionary[np.clip(codes, 0, len(self.dictionary) - 1)]
        else:
            out = np.full(num_rows, None, dtype=object)
        out = np.asarray(out, dtype=object)
        if validity is not None:
            out[~validity] = None
        return out, validity

    def device_memory_size(self) -> int:
        # codes + validity only; dictionary lives host-side
        return super().device_memory_size()


def unify_dictionaries(cols: List[StringColumn]) -> List[StringColumn]:
    """Re-encode string columns onto one shared sorted dictionary.

    Needed before any cross-column string comparison/join/concat/groupby,
    analogous to how the reference re-serializes cuDF string columns for
    cross-batch ops. Host-side merge of (typically small) dictionaries; the
    per-row remap is a device gather.
    """
    if not cols:
        return cols
    merged = np.unique(np.concatenate([c.dictionary.astype(str)
                                       if len(c.dictionary) else
                                       np.array([], dtype=str)
                                       for c in cols]))
    merged_obj = merged.astype(object)
    out = []
    for c in cols:
        if len(c.dictionary) == len(merged) and (
                len(merged) == 0 or bool((c.dictionary == merged_obj).all())):
            out.append(StringColumn(c.data, merged_obj, c.validity))
            continue
        if len(c.dictionary):
            remap = np.searchsorted(merged, c.dictionary.astype(str))
        else:
            remap = np.array([0], dtype=np.int64)  # dummy, codes all masked
        remap_dev = jnp.asarray(remap.astype(np.int32))
        new_codes = jnp.take(remap_dev, c.data, mode="clip")
        out.append(StringColumn(new_codes, merged_obj, c.validity))
    return out


def _infer_dtype(np_dtype) -> dt.DType:
    np_dtype = np.dtype(np_dtype)
    mapping = {
        np.dtype(np.bool_): dt.BOOLEAN,
        np.dtype(np.int8): dt.INT8,
        np.dtype(np.int16): dt.INT16,
        np.dtype(np.int32): dt.INT32,
        np.dtype(np.int64): dt.INT64,
        np.dtype(np.float32): dt.FLOAT32,
        np.dtype(np.float64): dt.FLOAT64,
    }
    if np_dtype in mapping:
        return mapping[np_dtype]
    raise TypeError(f"cannot infer DType from numpy dtype {np_dtype}")
