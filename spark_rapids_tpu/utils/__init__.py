from spark_rapids_tpu.utils.arm import close_on_except, safe_close, with_resource  # noqa: F401
from spark_rapids_tpu.utils.tracing import TraceRange, trace_with_metrics  # noqa: F401
