"""Dispatch telemetry: how many device round trips a query costs.

Under the axon tunnel every dispatch pays ~105 ms fixed overhead
(BASELINE.md's measured cost model), so full-query wall clock divides
into ``dispatch_count x RTT`` plus true on-device time — the split the
reference's per-query methodology reports (docs/benchmarks.md:26-169)
and BASELINE.md promised. This module counts the three dispatch
sources:

- executions of framework-jitted programs (``jax.jit`` is wrapped
  BEFORE the framework modules import, so module-level ``@jit``
  decorators capture the counting binding),
- eager op-by-op primitive applications (host-orchestrated glue
  between jitted kernels — each one is its own tiny executable),
- explicit device->host transfers (``jax.device_get``).

``install()`` must run before importing any ``spark_rapids_tpu``
compute module; the benchmark runner does this when
``--dispatch-telemetry`` is passed. Zero overhead when not installed.
"""
from __future__ import annotations

import functools
import threading
from spark_rapids_tpu.utils import lockorder
import time

_installed = False
_jit_calls = 0
_eager_calls = 0
_transfers = 0
_compiled_fns: list = []

# -- per-stage attribution --------------------------------------------------
# The stage-cutting pass (plan/optimizer.cut_stages) labels every exec
# with its pipeline stage; base.timed() brackets each batch pull with
# enter_stage/exit_stage so every dispatch lands in the innermost
# active stage's bucket. Thread-local: concurrent task threads each
# carry their own stage.
_tls = threading.local()
_stage_counts: dict = {}
# {stage_label: {program_label: count}} — which PROGRAMS a stage's
# dispatches ran, not just how many (round-7: BENCH r05->r06 could say
# "stage0: 6" but not name the six, so a fusion regression and a
# legitimate chunked loop were indistinguishable from the JSON alone).
# jit launches label as the traced fn's qualname, eager primitives as
# "eager:<prim>", transfers as "device_get".
_stage_programs: dict = {}
_stage_lock = lockorder.make_lock("utils.dispatch.stage")


def enter_stage(label):
    """Set the current thread's stage; returns a token for exit_stage.
    Near-zero cost when telemetry is not installed or label is None."""
    if not _installed or label is None:
        return None
    prev = getattr(_tls, "stage", None)
    _tls.stage = label
    return (prev,)


def exit_stage(token) -> None:
    if token is not None:
        _tls.stage = token[0]


# -- per-query attribution --------------------------------------------------
# The query service brackets each stage slice with enter_query/exit_query
# so concurrent queries' dispatches split per query id in ServiceStats —
# same thread-local scheme as stages, orthogonal bucket.
#
# Coalesced dispatches (service/batching/microbatch): ONE physical
# launch serves K queries. The launch counts once globally and once in
# _tagged_total; each participant's _query_counts entry takes a 1/K
# share (per-query counts SUM to the physical launch count — counting
# 1 per participant would inflate the global picture K-fold) and its
# _query_coalesced entry records the participation itself.
_query_counts: dict = {}
_query_coalesced: dict = {}
_tagged_total = 0.0  # physical dispatches attributed to ANY query


def enter_query(query_id):
    """Tag this thread's dispatches with ``query_id``; returns a token
    for exit_query. No-op (None token) when telemetry isn't installed."""
    if not _installed or query_id is None:
        return None
    prev = getattr(_tls, "query", None)
    _tls.query = query_id
    return (prev,)


def exit_query(token) -> None:
    if token is not None:
        _tls.query = token[0]


def current_query():
    """The query id tagging this thread's dispatches, or None —
    run_partitions propagates it onto its pool threads the same way it
    propagates the catalog buffer-owner tag."""
    return getattr(_tls, "query", None)


def enter_coalesced(query_ids):
    """Mark this thread's NEXT dispatches as one physical launch
    serving every query in ``query_ids`` (the micro-batch leader wraps
    exactly the coalesced program call). Each launch then counts once
    globally and 1/K per participant, with the participation itself
    recorded in the coalesced counter. Returns a token for
    exit_coalesced; no-op (None) when telemetry isn't installed."""
    if not _installed or not query_ids:
        return None
    prev = getattr(_tls, "coalesced", None)
    _tls.coalesced = tuple(query_ids)
    return (prev,)


def exit_coalesced(token) -> None:
    if token is not None:
        _tls.coalesced = token[0]


def query_counts() -> dict:
    """{query_id: dispatch_count} accumulated so far (live queries).
    Counts are floats: a coalesced launch contributes a 1/K share to
    each of its K participants."""
    with _stage_lock:
        return dict(_query_counts)


def query_coalesced_counts() -> dict:
    """{query_id: coalesced launches participated in} (live queries)."""
    with _stage_lock:
        return dict(_query_coalesced)


def tagged_total() -> float:
    """Physical dispatches attributed to any query so far — by
    construction equal to the sum of per-query counts (the attribution
    invariant tests/test_batching.py fences)."""
    with _stage_lock:
        return _tagged_total


def pop_query_count(query_id) -> float:
    """Final dispatch count of a finished query, removed from the live
    map — a long-lived service must not accumulate one entry per query
    ever submitted."""
    with _stage_lock:
        return _query_counts.pop(query_id, 0)


def pop_query_coalesced(query_id) -> int:
    """Final coalesced-participation count of a finished query."""
    with _stage_lock:
        return _query_coalesced.pop(query_id, 0)


def _bump_stage(kind: str, program: str = None) -> None:
    global _tagged_total
    label = getattr(_tls, "stage", None) or "<unstaged>"
    qid = getattr(_tls, "query", None)
    group = getattr(_tls, "coalesced", None)
    with _stage_lock:
        d = _stage_counts.get(label)
        if d is None:
            d = _stage_counts[label] = {"jit": 0, "eager": 0, "get": 0}
        d[kind] += 1
        if program is not None:
            progs = _stage_programs.setdefault(label, {})
            progs[program] = progs.get(program, 0) + 1
        if group:
            share = 1.0 / len(group)
            for g in group:
                _query_counts[g] = _query_counts.get(g, 0) + share
                _query_coalesced[g] = _query_coalesced.get(g, 0) + 1
            _tagged_total += 1
        elif qid is not None:
            _query_counts[qid] = _query_counts.get(qid, 0) + 1
            _tagged_total += 1

# -- measured device timing (serialized mode) -------------------------------
# When enabled, every counted jit call BLOCKS until its result is ready
# and records (elapsed - RTT floor) as that kernel's measured device
# time, attributed per function name. This measures rather than infers
# on-device time (round-4 verdict: "is the chip actually busy" was
# inferred from dispatch counts). Serializing kills dispatch pipelining,
# so wall clock inflates — run it as a separate measurement pass, never
# during the timed iterations. Caveat: on relay backends where
# block_until_ready can return before remote execution completes the
# per-kernel split undercounts; the runner cross-checks the sum against
# the wall-based estimate and reports both.
_device_timing = False
_rtt_floor = 0.0
_kernel_times: dict = {}
# per-(stage, program) split of the same measured seconds: answers
# "which stage's launches of chain@a1b2 are the expensive ones" when
# one compiled program serves several pipeline stages
_stage_kernel_times: dict = {}


def install() -> None:
    """Wrap jax.jit / eager primitive application / device_get with
    counters. Idempotent; affects only this process."""
    global _installed
    if _installed:
        return
    import jax

    real_jit = jax.jit

    def counting_jit(fn=None, **kw):
        if fn is None:
            return lambda f: counting_jit(f, **kw)
        compiled = real_jit(fn, **kw)
        _compiled_fns.append(compiled)

        name = getattr(fn, "__qualname__", None) or \
            getattr(fn, "__name__", repr(fn))

        class _Counted:
            def __call__(self, *a, **k):
                global _jit_calls
                _jit_calls += 1
                _bump_stage("jit", name)
                if not _device_timing:
                    return compiled(*a, **k)
                t0 = time.perf_counter()
                out = compiled(*a, **k)
                jax.block_until_ready(out)
                dt = max(time.perf_counter() - t0 - _rtt_floor, 0.0)
                calls, secs = _kernel_times.get(name, (0, 0.0))
                _kernel_times[name] = (calls + 1, secs + dt)
                label = getattr(_tls, "stage", None) or "<unstaged>"
                with _stage_lock:
                    progs = _stage_kernel_times.setdefault(label, {})
                    c2, s2 = progs.get(name, (0, 0.0))
                    progs[name] = (c2 + 1, s2 + dt)
                return out

            def __getattr__(self, name_):
                return getattr(compiled, name_)

        w = _Counted()
        try:
            functools.update_wrapper(w, fn)
        except Exception:
            pass
        return w

    jax.jit = counting_jit

    try:
        from jax._src import dispatch as jdispatch

        real_apply = jdispatch.apply_primitive

        def counting_apply(prim, *a, **k):
            global _eager_calls
            _eager_calls += 1
            _bump_stage("eager", "eager:" + getattr(prim, "name", "?"))
            return real_apply(prim, *a, **k)

        jdispatch.apply_primitive = counting_apply
    except Exception:  # pragma: no cover - jax internals moved
        pass

    real_get = jax.device_get

    def counting_get(x):
        global _transfers
        _transfers += 1
        _bump_stage("get", "device_get")
        return real_get(x)

    jax.device_get = counting_get
    _installed = True


def installed() -> bool:
    return _installed


def snapshot() -> dict:
    return {"jit_calls": _jit_calls, "eager_op_calls": _eager_calls,
            "transfers": _transfers}


def delta(before: dict) -> dict:
    now = snapshot()
    d = {k: now[k] - before[k] for k in now}
    d["dispatch_count"] = sum(d.values())
    return d


def stage_snapshot() -> dict:
    """Per-stage {label: {jit, eager, get}} counts so far."""
    with _stage_lock:
        return {k: dict(v) for k, v in _stage_counts.items()}


def stage_delta(before: dict) -> dict:
    """Per-stage dispatch totals accumulated since ``before`` (a
    stage_snapshot), empty buckets dropped."""
    now = stage_snapshot()
    out = {}
    for label, counts in now.items():
        prev = before.get(label, {})
        n = sum(counts[k] - prev.get(k, 0) for k in counts)
        if n:
            out[label] = n
    return out


def stage_programs_snapshot() -> dict:
    """Per-stage {label: {program_label: count}} so far."""
    with _stage_lock:
        return {k: dict(v) for k, v in _stage_programs.items()}


def stage_program_delta(before: dict) -> dict:
    """Per-stage PROGRAM attribution accumulated since ``before`` (a
    stage_programs_snapshot): {stage: {program_label: launches}} with
    zero-delta programs dropped. The named complement of stage_delta —
    "stage0: 6" becomes "stage0: chain@a1b2 x4 + groupby x1 + get x1"."""
    now = stage_programs_snapshot()
    out = {}
    for label, progs in now.items():
        prev = before.get(label, {})
        d = {p: n - prev.get(p, 0) for p, n in progs.items()
             if n - prev.get(p, 0)}
        if d:
            out[label] = d
    return out


def replan_snapshot() -> dict:
    """AQE replan-event counts so far ({"rule: detail": n}) — thin
    passthrough so telemetry consumers snapshot dispatches and replans
    from one module (the counters live in execs.adaptive)."""
    from spark_rapids_tpu.execs import adaptive

    return adaptive.replan_snapshot()


def replan_delta(before: dict) -> dict:
    """Replan events recorded since ``before`` (a replan_snapshot)."""
    from spark_rapids_tpu.execs import adaptive

    return adaptive.replan_delta(before)


def scan_snapshot() -> dict:
    """Scan-pipeline telemetry counters so far — thin passthrough to
    io.scanpipe so telemetry consumers snapshot dispatches, replans and
    scans from one module."""
    from spark_rapids_tpu.io import scanpipe

    return scanpipe.snapshot()


def scan_delta(before: dict) -> dict:
    """The ``io.scan`` block accumulated since ``before`` (a
    scan_snapshot): bytes read/pruned, decode vs h2d seconds, measured
    scan–compute overlap fraction, per-format unprunable reasons."""
    from spark_rapids_tpu.io import scanpipe

    return scanpipe.delta(before)


def executable_count() -> int:
    """Distinct compiled executables across all jitted entry points
    (one jit fn compiles once per argument-shape signature)."""
    total = 0
    for f in _compiled_fns:
        try:
            total += f._cache_size()
        except Exception:
            total += 1
    return total


def enable_device_timing() -> None:
    """Start serialized per-kernel device-time measurement (requires
    install()). Measures the RTT floor once so each sample subtracts
    the fixed dispatch overhead."""
    global _device_timing, _rtt_floor, _kernel_times
    assert _installed, "dispatch.install() must run first"
    _rtt_floor = measure_rtt()
    _kernel_times = {}
    with _stage_lock:
        _stage_kernel_times.clear()
    _device_timing = True


def disable_device_timing() -> dict:
    """Stop measuring; returns {kernel_name: (calls, device_seconds)}
    plus the totals under the '__total__' key."""
    global _device_timing
    _device_timing = False
    out = dict(_kernel_times)
    total_calls = sum(c for c, _ in out.values())
    total_s = sum(s for _, s in out.values())
    out["__total__"] = (total_calls, total_s)
    return out


def stage_device_times() -> dict:
    """Measured device seconds split per (stage, program):
    {stage: {program: (calls, device_seconds)}}. Populated only while
    device timing is enabled; read it AFTER disable_device_timing."""
    with _stage_lock:
        return {label: dict(progs)
                for label, progs in _stage_kernel_times.items()}


def measure_rtt(samples: int = 5) -> float:
    """Median wall time of a trivial dispatch — the fixed per-dispatch
    overhead on this backend (~105 ms over the axon tunnel, ~0 local)."""
    import jax
    import jax.numpy as jnp

    x = jnp.arange(8)
    times = []
    for _ in range(samples + 1):
        t0 = time.perf_counter()
        jax.block_until_ready(x + 1)
        times.append(time.perf_counter() - t0)
    # MIN, not median: the fixed overhead is a floor; host scheduling
    # noise only ever inflates a sample
    return min(times[1:])  # drop the compile
