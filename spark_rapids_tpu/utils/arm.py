"""Resource lifetime idioms.

The reference threads every buffer through ``Arm.withResource`` /
``closeOnExcept`` try-finally helpers (sql-plugin/.../Arm.scala:23-75) and
``safeClose`` on collections (implicits.scala). Python has ``with``, but our
catalog-managed buffers and batches are ref-counted and often owned across
scopes, so we keep the same explicit idiom for anything exposing ``close()``.
"""
from __future__ import annotations

import contextlib
from typing import Callable, Iterable, TypeVar

T = TypeVar("T")
R = TypeVar("R")


def with_resource(resource: T, body: Callable[[T], R]) -> R:
    """Run ``body(resource)`` and always close the resource (Arm.scala:26)."""
    try:
        return body(resource)
    finally:
        _close(resource)


def close_on_except(resource: T, body: Callable[[T], R]) -> R:
    """Close the resource only if ``body`` raises (Arm.scala:55)."""
    try:
        return body(resource)
    except BaseException:
        _close(resource)
        raise


def safe_close(resources: Iterable) -> None:
    """Close every resource, raising the first error after closing all
    (RapidsPluginImplicits.safeClose analogue)."""
    first_err = None
    for r in resources:
        try:
            _close(r)
        except BaseException as e:  # noqa: BLE001
            if first_err is None:
                first_err = e
    if first_err is not None:
        raise first_err


def _close(resource) -> None:
    if resource is None:
        return
    closer = getattr(resource, "close", None)
    if closer is not None:
        closer()


@contextlib.contextmanager
def closing(resource: T):
    try:
        yield resource
    finally:
        _close(resource)
