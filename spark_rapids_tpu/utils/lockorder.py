"""Declared lock hierarchy + debug-mode runtime lock-order assertions.

The framework holds ~40 ``threading.Lock/RLock/Condition`` instances
across service/catalog/microbatcher/shuffle. A deadlock between any two
of them only reproduces under the exact interleaving that inverts their
acquisition order — runtime fences must get lucky. Instead the order is
DECLARED here once, every lock is created through :func:`make_lock` /
:func:`make_rlock` / :func:`make_condition` with its hierarchy name, and
two enforcement layers share the single source of truth:

- **statically**: ``spark_rapids_tpu/analysis/locks.py`` (tpulint
  TPU3xx) extracts nested ``with``-acquisitions across an
  intraprocedural call graph and checks every nesting edge against the
  ranks below;
- **at runtime**: when ``rapids.tpu.debug.lockOrder.enabled`` is set
  (env ``RAPIDS_TPU_DEBUG_LOCKORDER_ENABLED=1`` — read at lock-creation
  time, so it must be set before the framework imports; tests/conftest
  does this for every tier-1 run), each lock is wrapped in a tracking
  proxy that asserts, on every acquire, that no lock of EQUAL OR HIGHER
  rank is already held by the thread.

Rank semantics: a thread may acquire lock B while holding lock A iff
``rank(A) < rank(B)`` — lower ranks are the OUTER locks. Locks marked
*nestable* are per-instance locks whose distinct instances legitimately
nest (an exchange's materialize barrier runs its whole child subtree,
which may materialize inner exchanges); for those, same-name nesting is
allowed and the rank rule applies only against other names.

Disabled (the default), the factories return raw ``threading``
primitives — zero overhead in production.
"""
from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Tuple

#: The declared hierarchy: name -> rank. Lower rank = outer lock
#: (acquired first). Gaps left for future locks. Every make_lock /
#: make_rlock / make_condition name MUST appear here — tpulint TPU303
#: flags undeclared names statically and make_lock raises when tracking
#: is enabled.
LOCK_HIERARCHY: Dict[str, int] = {
    # -- query/service layer (outermost: these orchestrate everything) --
    "api.session.serviceInit": 10,
    "service.query": 20,              # QueryService RLock + done/work CVs
    # -- streaming ingestion (service/streaming): the manager registry
    # is taken under the service lock (stats) and holds the per-query
    # fold lock, which in turn runs whole exec subtrees (planBarrier,
    # >=30) and registers state in the catalog (100) ------------------
    "service.streaming.state": 24,
    "service.streaming.standing": 26, # per-standing-query fold lock
    # -- materialize-once stage barriers: held across whole child
    # subtree execution BY DESIGN (the lock is the stage boundary).
    # These four form the "planBarrier" GROUP (see GROUPS below): an
    # exchange's materialize runs its child subtree, which prepares
    # nested fused chains, which materialize THEIR broadcast builds —
    # a legitimate recursion over the (acyclic) plan DAG, so ordering
    # among group members is exempted rather than ranked. -------------
    "execs.cache.materialize": 30,
    "execs.adaptive.decide": 32,      # AQE replan decision barrier
    "exchange.shuffle.materialize": 34,
    "execs.fused.chainPrep": 36,
    "exchange.broadcast.materialize": 38,
    # -- runtime env swap: initialize/shutdown hold this across catalog
    # close, semaphore re-init, retry/fault-injection (re)configuration,
    # so it sits OUTSIDE the whole memory subsystem; get_env() takes it
    # briefly from inside stage barriers, so it sits inside those ------
    "runtime.device": 45,
    # -- cluster / distributed runtime ---------------------------------
    "runtime.cluster.recover": 50,
    "runtime.cluster.state": 52,
    "runtime.cluster.worker": 54,
    "runtime.cluster.clients": 56,
    "shuffle.cluster.state": 58,
    # -- python/UDF worker pools ---------------------------------------
    "execs.python.pool": 60,
    "udf.pyworker.pool": 62,
    # -- fused-chain build prep cache (global registry bookkeeping;
    # acquired UNDER chainPrep, never holds a barrier itself) ----------
    "execs.fused.prepCache": 70,
    # -- semantic cache registry (service/cache/manager): lookups run
    # under the service lock (20), publishes run inside fragment
    # materialize barriers (planBarrier, <=38), and eviction closes
    # spillable entries through the catalog (100) — so it sits between
    # the barriers and the memory subsystem --------------------------
    "service.cache.state": 76,
    # -- serving-layer batching ----------------------------------------
    "service.batching.microbatch": 80,
    "service.batching.buckets": 84,
    "expressions.fusedCache": 86,
    # -- io ------------------------------------------------------------
    "io.filesrc.splits": 90,
    # scan-cache registry (io/scanpipe): lookups/publishes hold this
    # while closing stale SpillableBatches through the catalog (100),
    # so it must sit OUTSIDE the memory subsystem ---------------------
    "io.scanpipe.cache": 91,
    # -- streaming table deltas: appends hold this while bumping the
    # snapshot counter (158); scans take it briefly to copy the delta
    # list before concatenating outside the lock ----------------------
    "service.streaming.source": 92,
    # -- streaming durability (service/streaming/durability): the WAL
    # lock is taken under the source lock (append persists the record
    # before the delta is visible); the checkpoint-store lock is taken
    # under the standing-query fold lock (26) and must stay OUTSIDE the
    # catalog (100) because loading a checkpoint registers state
    # buffers; the writer CV is the async-commit pending counter ------
    "service.streaming.wal": 94,
    "service.streaming.checkpoint": 96,
    "service.streaming.checkpointWriter": 98,
    # -- memory subsystem ----------------------------------------------
    "memory.catalog.state": 100,
    "memory.catalog.global": 102,
    "memory.catalog.spillWriter": 104,
    "memory.semaphore.instance": 106,
    "memory.semaphore": 108,
    "memory.addressSpace": 112,
    # -- shuffle transport ---------------------------------------------
    "shuffle.catalog.state": 116,
    "shuffle.tcp.registry": 118,  # shutdown closes servers under it
    "shuffle.tcp.server": 120,
    "shuffle.tcp.client": 124,
    "shuffle.transport.store": 132,
    "shuffle.transport.endpoints": 136,
    "shuffle.transport.throttle": 140,
    # -- leaf utility locks (never hold anything under these) ----------
    "execs.base.metrics": 150,
    "utils.progcache": 154,
    "service.cache.snapshots": 158,  # per-source version bump counter
    "memory.retry.policy": 160,
    "memory.retry.stats": 164,
    "memory.faultInjection": 168,
    "shuffle.faultInjection": 170,   # transport/worker fault injector
    "utils.dispatch.stage": 172,
    "execs.adaptive.replans": 174,   # replan-event + runtime-stat counters
    "parallel.spmd.fallbacks": 176,  # fallback/seam-decision counters
    "parallel.mesh.fallbacks": 177,  # mesh clamp/topology counters
    "io.scanpipe.stats": 179,        # scan-pipeline telemetry counters
    "runtime.recovery.stats": 178,   # process-global recovery counters
    "service.streaming.stats": 180,  # process-global fold counters
    "native.kernels.config": 182,    # pallas kernel gate state
    "native.init": 184,
    "shims.init": 188,
    "config.registry": 192,
}

#: Per-instance locks whose DISTINCT instances may nest (same name at
#: the same rank): materialize-once barriers recurse through child
#: subtrees that contain more of the same exec class, and a file
#: source's reentrant splits lock survives with_filters cloning.
NESTABLE = frozenset({
    "execs.cache.materialize",
    "exchange.shuffle.materialize",
    "exchange.broadcast.materialize",
    "io.filesrc.splits",
    "execs.base.metrics",
    "memory.catalog.state",       # one catalog instance per executor
    "shuffle.tcp.client",         # one client per peer connection
    "shuffle.transport.store",    # one store per executor server
    "runtime.cluster.worker",     # one handle per worker process
    "memory.addressSpace",
})

#: Mutual-exemption groups. Locks sharing a group skip the rank check
#: AGAINST EACH OTHER (in either direction): the planBarrier group's
#: members are per-plan-node stage barriers that recurse through an
#: acyclic plan DAG (exchange materialize -> child execution -> nested
#: chain prep -> inner broadcast materialize -> ...), so any pairwise
#: order can occur yet no cycle over lock INSTANCES is possible — the
#: DAG is always walked top-down. Ranks still order group members
#: against every lock outside the group.
GROUPS: Dict[str, str] = {
    "execs.cache.materialize": "planBarrier",
    "execs.adaptive.decide": "planBarrier",
    "exchange.shuffle.materialize": "planBarrier",
    "exchange.broadcast.materialize": "planBarrier",
    "execs.fused.chainPrep": "planBarrier",
}

_ENV_KEY = "RAPIDS_TPU_DEBUG_LOCKORDER_ENABLED"


def enabled() -> bool:
    """Whether lock-order tracking is on (the
    ``rapids.tpu.debug.lockOrder.enabled`` knob's env spelling, read
    directly so this module never imports config)."""
    return os.environ.get(_ENV_KEY, "").strip().lower() in (
        "1", "true", "yes", "on")


class LockOrderViolation(RuntimeError):
    """A lock was acquired while a lock of equal or higher rank was
    already held — an inversion of the declared hierarchy."""


_tls = threading.local()

_violations: List[dict] = []
_violations_lock = threading.Lock()
_raise_mode = False


def set_raise_mode(flag: bool) -> None:
    """raise on violation (unit tests) instead of recording (tier-1:
    conftest's sessionfinish hook reports recorded violations so one
    mis-nested acquire fails the run without corrupting unrelated
    tests mid-flight)."""
    global _raise_mode
    _raise_mode = bool(flag)


def violations() -> List[dict]:
    with _violations_lock:
        return list(_violations)


def reset_violations() -> None:
    with _violations_lock:
        _violations.clear()


def _held_stack() -> List["_TrackedLock"]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class _TrackedLock:
    """Proxy around a threading lock that maintains a per-thread stack
    of held locks and validates the declared hierarchy on acquire.
    Unknown attributes forward to the wrapped lock, so
    ``threading.Condition`` built over a tracked RLock still reaches
    ``_release_save``/``_acquire_restore`` (wait() then bypasses the
    tracker symmetrically: the stack is identical before and after)."""

    __slots__ = ("_inner", "name", "rank", "nestable", "group")

    def __init__(self, inner, name: str):
        rank = LOCK_HIERARCHY.get(name)
        if rank is None:
            raise LockOrderViolation(
                f"lock name {name!r} is not declared in "
                f"utils/lockorder.py LOCK_HIERARCHY")
        self._inner = inner
        self.name = name
        self.rank = rank
        self.nestable = name in NESTABLE
        self.group = GROUPS.get(name)

    def _check(self) -> None:
        held = _held_stack()
        worst: Optional[Tuple[str, int]] = None
        for h in held:
            if h is self:
                return  # reentrant re-acquire of an RLock: always fine
            if self.group is not None and h.group == self.group:
                continue  # same-group barriers: exempt (see GROUPS)
            if h.rank > self.rank or (
                    h.rank == self.rank and
                    not (self.nestable and h.name == self.name)):
                if worst is None or h.rank > worst[1]:
                    worst = (h.name, h.rank)
        if worst is None:
            return
        rec = {
            "acquiring": self.name, "acquiring_rank": self.rank,
            "held": worst[0], "held_rank": worst[1],
            "thread": threading.current_thread().name,
            "stack": "".join(traceback.format_stack(limit=8)[:-2]),
        }
        if _raise_mode:
            raise LockOrderViolation(
                f"acquiring {self.name!r} (rank {self.rank}) while "
                f"holding {worst[0]!r} (rank {worst[1]}) inverts the "
                f"declared hierarchy")
        with _violations_lock:
            # dedup by edge: one report per (held, acquiring) pair
            for v in _violations:
                if v["acquiring"] == self.name and v["held"] == worst[0]:
                    return
            _violations.append(rec)

    # -- lock protocol -----------------------------------------------
    def acquire(self, blocking=True, timeout=-1):
        self._check()
        got = self._inner.acquire(blocking, timeout)
        if got:
            _held_stack().append(self)
        return got

    def release(self):
        self._inner.release()
        st = _held_stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] is self:
                del st[i]
                break

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def __getattr__(self, name):
        return getattr(self._inner, name)


def make_lock(name: str):
    """A ``threading.Lock`` declared at hierarchy position ``name``
    (tracked proxy when lock-order debugging is enabled)."""
    if not enabled():
        return threading.Lock()
    return _TrackedLock(threading.Lock(), name)


def make_rlock(name: str):
    """A ``threading.RLock`` declared at hierarchy position ``name``."""
    if not enabled():
        return threading.RLock()
    return _TrackedLock(threading.RLock(), name)


def make_condition(name: str, lock=None):
    """A ``threading.Condition`` over ``lock`` (or a fresh declared
    RLock named ``name``). Waiting on a condition releases its OWN lock;
    holding any other lock across a ``wait`` is exactly the hazard the
    static pass (TPU302) flags."""
    if lock is None:
        lock = make_rlock(name)
    return threading.Condition(lock)
