"""Tracing and metrics fusion.

The reference wraps every operator and transport step in NVTX ranges and
fuses a range with a SQLMetric timer (`NvtxWithMetrics`,
sql-plugin/.../NvtxWithMetrics.scala:44). The TPU equivalents are
``jax.profiler.TraceAnnotation`` spans (visible in xprof/tensorboard traces)
fused with our operator metrics.
"""
from __future__ import annotations

import contextlib
import time
from typing import Optional

try:
    import jax.profiler as _jprof

    _HAVE_PROFILER = True
except Exception:  # pragma: no cover
    _HAVE_PROFILER = False


class Metric:
    """A single operator metric (SQLMetric analogue, GpuExec.scala:90-96)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def add(self, v) -> None:
        self.value += v

    def set(self, v) -> None:
        self.value = v

    def __repr__(self) -> str:  # pragma: no cover
        return f"Metric({self.name}={self.value})"


@contextlib.contextmanager
def TraceRange(name: str):
    """Named profiler span (NvtxRange analogue)."""
    if _HAVE_PROFILER:
        with _jprof.TraceAnnotation(name):
            yield
    else:  # pragma: no cover
        yield


@contextlib.contextmanager
def trace_with_metrics(name: str, metric: Optional[Metric] = None):
    """Profiler span + nanosecond timer accumulated into ``metric``
    (NvtxWithMetrics analogue)."""
    start = time.perf_counter_ns()
    try:
        with TraceRange(name):
            yield
    finally:
        if metric is not None:
            metric.add(time.perf_counter_ns() - start)
