"""Persistent compile cache: repeated plans over the same schema skip
both XLA compilation and warm-up dispatches.

Two layers cooperate:

- **in-process**: structurally identical fused programs share one
  jitted callable through the chain-key registry in
  ``expressions/compiler.py`` (``_FUSED_CACHE``, keyed by the same
  ``chain_key`` tuples whose CRC tags the program names). A fresh plan
  instance of a repeated query re-traces nothing.
- **cross-process**: JAX's persistent compilation cache (pointed at a
  platform-suffixed directory by the package ``__init__``) keeps the
  XLA *executables* across process restarts. The fused chain programs
  carry STABLE names (the ``fused_chain[...]@crc`` tag derives from
  the chain key, not object identity), which keeps their cache keys
  reproducible across runs — a cold process starts hot. ``install()``
  drops the only-cache-slow-compiles floor to zero: behind the
  remote-compile tunnel even a "fast" compile costs a round trip
  measured in seconds (BASELINE.md), so everything persists.

``bench.py`` installs this over the tracked ``.jax_cache`` seed; query
sessions opt in via ``rapids.tpu.sql.compileCacheDir``.
"""
from __future__ import annotations

import os
import threading
from spark_rapids_tpu.utils import lockorder

_installed_dir = None
_lock = lockorder.make_lock("utils.progcache")


def _platform_suffix() -> str:
    """THE per-platform cache-split rule (the package ``__init__``
    imports this at cache setup): CPU executables compiled in a
    TPU-attached process carry that platform's XLA target features and
    SIGSEGV a plain-CPU loader, so forced-CPU processes use their own
    directory. One definition — a drift between two sniffs would route
    a CPU process into the TPU cache."""
    first = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip()
    return "_cpu" if first == "cpu" else ""


def install(cache_dir=None) -> bool:
    """Enable aggressive persistent caching. With ``cache_dir`` None,
    adopts the directory the package ``__init__`` already configured;
    an explicit directory gets the same platform suffix treatment
    before taking over. Idempotent; first explicit call wins (jax
    holds one global cache) — a LATER call naming a different
    directory returns False so the caller knows its path was not
    honored."""
    global _installed_dir
    with _lock:
        if _installed_dir is not None:
            if cache_dir:
                sfx = _platform_suffix()
                want = cache_dir if not sfx or cache_dir.endswith(sfx) \
                    else cache_dir + sfx
                if os.path.abspath(want) != _installed_dir:
                    return False
            return True
        try:
            import jax

            if cache_dir:
                sfx = _platform_suffix()
                if sfx and not cache_dir.endswith(sfx):
                    cache_dir = cache_dir + sfx
                cache_dir = os.path.abspath(cache_dir)
                os.makedirs(cache_dir, exist_ok=True)
                jax.config.update("jax_compilation_cache_dir", cache_dir)
            else:
                cache_dir = jax.config.jax_compilation_cache_dir
                if not cache_dir:
                    return False
            # cache every executable: behind the remote-compile tunnel
            # even a "fast" compile costs a round trip measured in
            # seconds, so the usual only-cache-slow-compiles floor is
            # exactly backwards here
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
            try:
                jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes", -1)
            except Exception:
                pass  # older jax: option absent, default is fine
        except Exception:
            return False
        _installed_dir = cache_dir
        return True


def installed_dir():
    return _installed_dir


def stats() -> dict:
    """Program-registry effectiveness: in-process chain-key cache size
    and hit/miss counts (a miss = one trace + compile somewhere), the
    persistent directory when active, and the shape-bucket ledger —
    how many distinct (program, bucket-shape) executables the service
    path observed vs reused (service/batching: programs are keyed on
    BUCKETED operand shapes, so concurrent tenants land on the same
    executables by construction)."""
    from spark_rapids_tpu.expressions import compiler as _c

    out = dict(_c._FUSED_CACHE_STATS)
    out["programs"] = len(_c._FUSED_CACHE)
    out["persistent_dir"] = _installed_dir
    try:
        from spark_rapids_tpu.service.batching.buckets import \
            get_registry

        out["buckets"] = get_registry().stats()
    except Exception:  # pragma: no cover - service package unavailable
        pass
    return out
