"""Host-framework version shims (SURVEY.md §2.13).

The reference adapts to each Spark release through ServiceLoader-discovered
``SparkShimServiceProvider``s that probe the running version and hand back
a ``SparkShims`` implementation (ShimLoader.scala:26,
SparkShimServiceProvider.scala:25), overridable via
``spark.rapids.shims-provider-override`` (RapidsConf.scala:707). Our host
framework is jax, whose public surface also moves between releases
(``shard_map`` graduated from ``jax.experimental.shard_map`` to
``jax.shard_map``; backend-reset moved into ``jax.extend``). Same design:
providers declare the versions they serve, the loader probes the installed
jax exactly once, and everything version-sensitive in the package goes
through the resolved ``JaxShims``.
"""
from __future__ import annotations

import os
import threading
from spark_rapids_tpu.utils import lockorder
from typing import List, Optional, Tuple


def _parse_version(v: str) -> Tuple[int, ...]:
    parts = []
    for p in v.split("."):
        digits = ""
        for ch in p:
            if ch.isdigit():
                digits += ch
            else:
                break
        if digits == "":
            break
        parts.append(int(digits))
    return tuple(parts)


class JaxShims:
    """The version-varying API surface (SparkShims trait analogue,
    SparkShims.scala:62-141) — only entries this package actually calls."""

    def shard_map(self):
        """The shard_map transform."""
        raise NotImplementedError

    def clear_backends(self):
        """Reset backends so device-count flags re-apply."""
        raise NotImplementedError

    def pallas(self):
        """The pallas kernel module (None when unavailable)."""
        return None


class JaxShimServiceProvider:
    """SparkShimServiceProvider analogue: version probe + factory."""

    #: inclusive lower bound, exclusive upper bound (None = open)
    VERSION_RANGE: Tuple[Optional[str], Optional[str]] = (None, None)

    @classmethod
    def matches(cls, version: str) -> bool:
        lo, hi = cls.VERSION_RANGE
        v = _parse_version(version)
        if lo is not None and v < _parse_version(lo):
            return False
        if hi is not None and v >= _parse_version(hi):
            return False
        return True

    def build(self) -> JaxShims:
        raise NotImplementedError


def _kernel_safe_shard_map(sm):
    """Default ``check_rep=False`` while the native-kernel gate is on:
    interpret-mode ``pallas_call`` has no shard_map replication rule,
    so a kernel routed inside a mesh device step would fail to trace
    otherwise. Replication checking is a trace-time assertion, not a
    semantics change — the mesh differential fences
    (tests/test_spmd_shuffle.py, tests/test_kernels.py) still assert
    bit-equality against the single-device and oracle paths."""
    import functools

    @functools.wraps(sm)
    def wrapped(f, **kw):
        if "check_rep" not in kw:
            from spark_rapids_tpu.native import kernels as nk

            if nk.cache_token()[0]:
                kw["check_rep"] = False
        return sm(f, **kw)

    return wrapped


class _ModernJaxShims(JaxShims):
    """jax >= 0.6: public top-level shard_map, jax.extend backend API."""

    def shard_map(self):
        from jax import shard_map

        return _kernel_safe_shard_map(shard_map)

    def clear_backends(self):
        from jax.extend import backend

        backend.clear_backends()

    def pallas(self):
        try:
            from jax.experimental import pallas

            return pallas
        except ImportError:  # pragma: no cover - platform-dependent
            return None


class ModernJaxShimProvider(JaxShimServiceProvider):
    VERSION_RANGE = ("0.6", None)

    def build(self) -> JaxShims:
        return _ModernJaxShims()


class _LegacyJaxShims(_ModernJaxShims):
    """jax 0.4.x-0.5.x: shard_map lives in jax.experimental, backend
    reset is jax.clear_backends."""

    def shard_map(self):
        from jax.experimental.shard_map import shard_map  # type: ignore

        return _kernel_safe_shard_map(shard_map)

    def clear_backends(self):
        import jax

        # jax.clear_backends was removed mid-0.4.x (0.4.36); late 0.4.x
        # already carries the jax.extend.backend API
        if hasattr(jax, "clear_backends"):
            jax.clear_backends()  # type: ignore[attr-defined]
        else:
            from jax.extend import backend

            backend.clear_backends()


class LegacyJaxShimProvider(JaxShimServiceProvider):
    VERSION_RANGE = ("0.4", "0.6")

    def build(self) -> JaxShims:
        return _LegacyJaxShims()


#: discovery order — the ServiceLoader registry (ShimLoader.scala:26)
PROVIDERS: List[type] = [ModernJaxShimProvider, LegacyJaxShimProvider]

OVERRIDE_ENV = "RAPIDS_TPU_SHIMS_PROVIDER_OVERRIDE"

_lock = lockorder.make_lock("shims.init")
_shims: Optional[JaxShims] = None


def _resolve(version: str) -> JaxShims:
    override = os.environ.get(OVERRIDE_ENV)
    if override:
        # spark.rapids.shims-provider-override analogue: fully qualified
        # provider name trusted over the probe (RapidsConf.scala:707)
        import importlib

        mod, _, name = override.rpartition(".")
        klass = getattr(importlib.import_module(mod), name) if mod else \
            globals()[name]
        return klass().build()
    for p in PROVIDERS:
        if p.matches(version):
            return p().build()
    raise RuntimeError(
        f"Could not find a shim provider for jax {version}; supported "
        f"ranges: {[p.VERSION_RANGE for p in PROVIDERS]} (set "
        f"{OVERRIDE_ENV} to force one)")


def get_shims() -> JaxShims:
    """Probe once, cache forever (ShimLoader semantics)."""
    global _shims
    with _lock:
        if _shims is None:
            import jax

            _shims = _resolve(jax.__version__)
        return _shims
