"""Process-global fault-recovery counters.

Every rung of the lineage-recovery ladder (docs/fault-tolerance.md)
bumps a counter here: reduce-side fetch failures observed, map tasks
re-run from retained assignments, worker processes respawned, executor
slots blacklisted, stage retries spent, and in-program exchanges
degraded to the host/TCP path. Styled after memory/retry's and
service/streaming/stats' process totals so the benchmark runner can
bracket any run with ``snapshot()``/``delta()`` and emit a ``recovery``
block next to its ``memory``/``streaming`` blocks, and the service can
embed the same numbers in ServiceStats without holding a runtime
reference — a query that silently survived a worker death should be
visible in telemetry, never folklore.
"""
from __future__ import annotations

from typing import Dict

from spark_rapids_tpu.utils import lockorder

_lock = lockorder.make_lock("runtime.recovery.stats")

_KEYS = ("fetch_failures", "maps_rerun", "workers_respawned",
         "executors_blacklisted", "stage_retries", "spmd_degrades",
         # elastic-membership events (ClusterRuntime.add_host /
         # remove_host and the injected DCN seam partition): a host
         # joining or leaving mid-query is a recovery event here, not
         # an outage — counted in the same block the runner/service
         # already surface
         "hosts_added", "hosts_removed", "dcn_partitions",
         # streaming durability (PR 19): a standing query's state
         # restored from checkpoint + WAL replay after a restart or a
         # recoverable in-fold fault — the streaming tier's analogue
         # of maps_rerun
         "streaming_restores")

_counters: Dict[str, int] = {k: 0 for k in _KEYS}


def bump(key: str, n: int = 1) -> None:
    with _lock:
        _counters[key] += n


def snapshot() -> Dict[str, int]:
    with _lock:
        return dict(_counters)


def delta(before: Dict[str, int]) -> Dict[str, int]:
    now = snapshot()
    return {k: now[k] - before.get(k, 0) for k in _KEYS}


def reset() -> None:
    """Test isolation hook."""
    with _lock:
        for k in _KEYS:
            _counters[k] = 0
