"""Runtime bootstrap: device acquisition, memory sizing, global wiring.

The reference's executor-plugin init sequence (Plugin.scala:122-147 ->
GpuDeviceManager.initializeGpuAndMemory, SURVEY.md §3.1): acquire one
GPU, size the RMM pool from the alloc fraction/reserve math, install the
spill catalog + OOM handler, initialize the pinned pool and the task
semaphore — and exit the process on failure so the cluster manager
replaces the executor rather than hanging.

TPU-native sequence (``initialize(conf)``):
  1. TpuDeviceManager.acquire(): pick the chip (or host device), read its
     HBM size from the device API,
  2. budget = hbm * allocFraction - reserve (GpuDeviceManager.scala:
     159-258 sizing math) -> global BufferCatalog with host/disk tiers,
  3. TpuSemaphore(concurrentTpuTasks),
  4. GpuShuffleEnv analogue: shuffle codec selection.

``initialize`` is idempotent; ``shutdown`` tears down for tests.
"""
from spark_rapids_tpu.runtime.device import (RuntimeEnv, TpuDeviceManager,
                                             get_env, initialize,
                                             shutdown)

__all__ = ["initialize", "shutdown", "get_env", "RuntimeEnv",
           "TpuDeviceManager"]
