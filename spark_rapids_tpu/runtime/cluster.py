"""Cluster query execution: SQL shuffles over the multi-process runtime.

Round-4 top verdict item: in the reference, the shuffle transport lives
INSIDE the shuffle manager real queries use — map tasks write partitioned
batches into the executor's catalog (RapidsCachingWriter,
RapidsShuffleInternalManager.scala:90-155), MapStatus registration names
the owning executor (:164-191), and reduce tasks read local hits
zero-copy plus remote blocks through the transport
(RapidsCachingReader.scala:59-145). Here the same wiring becomes
planner-reachable: with ``rapids.tpu.cluster.enabled``, every hash/single
``ShuffleExchangeExec`` in the final plan is swapped for a
``ClusterShuffleExchangeExec`` whose

- MAP side assigns child partitions round-robin over executors — the
  in-process ones AND remote worker processes
  (``shuffle/remote_worker.py`` task mode) that receive a pickled task
  closure (the Spark serialized-lineage model), execute it, register the
  partitioned output in their own catalog, and serve it over TCP;
- REDUCE side reads through ``ShuffleIterator`` over the TCP transport
  (local catalog hits + per-peer socket fetches), with fetch failures
  driving the Spark retry model: invalidate the dead executor's map
  outputs, re-run those map tasks on survivors, re-read.

Remote tasks whose subtree contains ANOTHER cluster exchange get it
replaced by a ``ClusterShuffleReadExec`` stub before pickling — the
worker then fetches that stage's blocks from wherever they live instead
of recomputing the upstream stage (Spark's stage DAG in miniature).
"""
from __future__ import annotations

import base64
import itertools
import pickle
import threading
import time
from spark_rapids_tpu.runtime import recovery
from spark_rapids_tpu.utils import lockorder
from typing import Dict, Iterator, List, Optional, Tuple

from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.execs.exchange import (ShuffleExchangeExec,
                                             partition_batch)
from spark_rapids_tpu.shuffle.cluster import LocalCluster
from spark_rapids_tpu.shuffle.iterator import (ShuffleFetchFailedError,
                                               ShuffleIterator)
from spark_rapids_tpu.shuffle.meta import BlockId
from spark_rapids_tpu.shuffle.transport import ShuffleClient
from spark_rapids_tpu.utils.tracing import TraceRange


def run_map_partitions(batches, partitioning, types, num_out: int
                       ) -> Dict[int, ColumnarBatch]:
    """Partition a map task's output batches into per-reduce-partition
    batches — the write half shared by local tasks and remote workers."""
    from spark_rapids_tpu.ops import partition as part_ops
    from spark_rapids_tpu.ops.concat import concat_batches

    parts: Dict[int, ColumnarBatch] = {}
    for b in batches:
        if b.realized_num_rows() == 0:
            continue
        sorted_b, counts = partition_batch(b, partitioning, types,
                                           num_out)
        subs = part_ops.slice_partitions(sorted_b, counts)
        for p, sub in enumerate(subs):
            if sub is None:
                continue
            parts[p] = sub if p not in parts else \
                concat_batches([parts[p], sub])
    return parts


def sample_rows_host(batches, schema: Schema, k: int, seed: int = 0x5EED):
    """Uniform row sample of executed batches as HOST arrays (raw kernel
    values — dates stay day counts, strings decode to objects) plus the
    TOTAL row count — the map-side half of cluster range-bounds
    sampling (GpuRangePartitioner.scala:42-95's sampling job; the total
    lets the driver weight each map's contribution by its size)."""
    import numpy as np

    live = [b for b in batches if b.realized_num_rows() > 0]
    rng = np.random.default_rng(seed)
    per_batch = max(k // max(len(live), 1), 1)
    datas = {n: [] for n in schema.names}
    valids = {n: [] for n in schema.names}
    total = 0
    for b in live:
        n = b.realized_num_rows()
        total += n
        idx = np.arange(n) if n <= per_batch else \
            rng.choice(n, per_batch, replace=False)
        for name, col in zip(schema.names, b.columns):
            vals, valid = col.to_numpy(n)
            datas[name].append(np.asarray(vals)[idx])
            valids[name].append(
                np.asarray(valid)[idx] if valid is not None
                else np.ones(len(idx), dtype=bool))
    out_d = {n: (np.concatenate(v) if v else np.array([]))
             for n, v in datas.items()}
    out_v = {n: (np.concatenate(v) if v else np.array([], dtype=bool))
             for n, v in valids.items()}
    return out_d, out_v, total


def host_sample_to_batch(data: dict, validity: dict,
                         schema: Schema) -> ColumnarBatch:
    """Rebuild one device batch from host sample arrays (driver side)."""
    import numpy as np

    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.column import Column, StringColumn

    n = len(next(iter(data.values()))) if data else 0
    cols = []
    for name, t in zip(schema.names, schema.types):
        vals = np.asarray(data[name])
        valid = np.asarray(validity[name], dtype=bool)
        if t is dt.STRING:
            svals = [v if valid[i] else None
                     for i, v in enumerate(vals)]
            cols.append(StringColumn.from_strings(svals))
        else:
            cols.append(Column.from_numpy(
                vals, dtype=t,
                validity=None if valid.all() else valid))
    return ColumnarBatch(cols, n)


class ExecutorContext:
    """The process-local executor identity a ``ClusterShuffleReadExec``
    reads through: its catalog (local hits), its transport (peer
    fetches). The driver process sets one for executor 0; each worker
    process sets its own (remote_worker task mode)."""

    def __init__(self, executor, transport):
        self.executor = executor
        self.transport = transport
        self._clients: Dict[str, ShuffleClient] = {}
        self._lock = lockorder.make_lock("runtime.cluster.clients")

    def client_for(self, peer: str) -> ShuffleClient:
        with self._lock:
            c = self._clients.get(peer)
            if c is None:
                c = ShuffleClient(self.transport.connect(peer))
                self._clients[peer] = c
            return c

    def invalidate_client(self, peer: str) -> None:
        """Evict a cached peer client after a fetch error so the next
        attempt reconnects from the CURRENT address book — a respawned
        peer (new port) is unreachable through the stale socket."""
        with self._lock:
            c = self._clients.pop(peer, None)
        if c is not None:
            close = getattr(c.conn, "close", None)
            if close is not None:
                try:
                    close()
                except OSError:
                    pass


_CONTEXT: Optional[ExecutorContext] = None


def set_executor_context(ctx: Optional[ExecutorContext]) -> None:
    global _CONTEXT
    _CONTEXT = ctx


def executor_context() -> ExecutorContext:
    assert _CONTEXT is not None, \
        "no ExecutorContext in this process (cluster runtime not active)"
    return _CONTEXT


class ClusterShuffleReadExec(TpuExec):
    """Leaf exec serving one materialized cluster shuffle: a reduce
    task's view of the MapOutputTracker answer. Picklable — it carries
    only block locations + executor addresses; catalog and sockets come
    from the process's ExecutorContext (the reference's reader resolves
    its BlockManager the same way)."""

    def __init__(self, schema: Schema, shuffle_id: int, num_out: int,
                 num_maps: int,
                 map_outputs: Dict[int, Tuple[str, dict]],
                 addresses: Dict[str, Tuple[str, int]]):
        super().__init__([], schema)
        self.shuffle_id = shuffle_id
        self.num_out = num_out
        self.map_outputs = dict(map_outputs)
        self.addresses = dict(addresses)
        # an incomplete MapStatus set must NEVER become a stub: dropping
        # an in-recovery map from _locations would silently yield partial
        # data (Spark readers likewise demand every MapStatus up front)
        assert len(self.map_outputs) == num_maps, \
            (shuffle_id, sorted(self.map_outputs), num_maps)

    @property
    def num_partitions(self) -> int:
        return self.num_out

    def _locations(self, partition: int) -> Dict[BlockId, str]:
        locs: Dict[BlockId, str] = {}
        for map_id, (executor_id, partitions) in self.map_outputs.items():
            if partition in partitions:
                locs[BlockId(self.shuffle_id, map_id, partition)] = \
                    executor_id
        return locs

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            ctx = executor_context()
            for eid, addr in self.addresses.items():
                if eid != ctx.executor.executor_id:
                    ctx.transport.register_remote(eid, *addr)
            sit = ShuffleIterator(
                ctx.executor.shuffle_catalog,
                ctx.executor.executor_id, self._locations(partition),
                ctx.client_for, on_fetch_error=ctx.invalidate_client)
            empty = True
            for b in sit:
                if b.realized_num_rows() == 0:
                    continue
                empty = False
                yield b
            if empty:
                yield ColumnarBatch.empty(self.schema)
        return timed(self, it())


class ClusterShuffleExchangeExec(ShuffleExchangeExec):
    """ShuffleExchangeExec whose block store is the cluster runtime.

    ``wrap`` rebuilds from a planned single-process exchange; execution
    then follows the reference's write/read split instead of the
    per-process block dict."""

    def __init__(self, partitioning, num_out: int, child: TpuExec,
                 runtime: "ClusterRuntime", task_threads: int = 1,
                 batch_bytes: Optional[int] = None):
        super().__init__(partitioning, num_out, child,
                         task_threads=task_threads,
                         batch_bytes=batch_bytes)
        self.runtime = runtime
        self.shuffle_id: Optional[int] = None
        # set by ClusterRuntime.new_shuffle_id before map tasks run, so
        # make_read_stub can name the shuffle mid-materialization
        self._pending_sid: Optional[int] = None
        # reasons a map task was re-placed in-process instead of on its
        # assigned remote worker — surfaced in explain (tree_string) so
        # cluster-mode degradation is visible, never silent
        self.local_fallbacks: List[str] = []
        self._read_stub: Optional[ClusterShuffleReadExec] = None
        # the first reduce read of this exchange counts as one stage
        # boundary for the host-granularity fault injector
        self._reduce_stage_counted = False

    @classmethod
    def wrap(cls, ex: ShuffleExchangeExec, runtime: "ClusterRuntime"
             ) -> "ClusterShuffleExchangeExec":
        return cls(ex.partitioning, ex.num_out_partitions,
                   ex.children[0], runtime, task_threads=ex.task_threads,
                   batch_bytes=ex.collapse_bytes)

    def tree_string(self, indent: int = 0) -> str:
        label = "  " * indent + self.name
        if self.local_fallbacks:
            label += (f" [local fallback x{len(self.local_fallbacks)}:"
                      f" {self.local_fallbacks[0]}]")
        lines = [label]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    # -- map side ---------------------------------------------------------

    def _materialize(self) -> None:
        from spark_rapids_tpu.parallel import spmd
        from spark_rapids_tpu.shuffle import fault_injection

        with self._mat_lock:
            if self.shuffle_id is not None:
                return
            # this exchange's blocks cross the host boundary: the DCN
            # seam decision pairs with the ICI decisions the planner
            # records for host-local Mesh*Exec subtrees
            spmd.record_seam("exchange", spmd.SEAM_DCN,
                             "cluster exchange: map outputs cross the "
                             "host boundary over TCP")
            if fault_injection.get_injector().should_kill_host_at_stage():
                # host-granularity fault: SIGKILL a live worker at the
                # stage boundary. Recovery is NOT told — it discovers
                # the death through submit failures and reduce-side
                # fetch failures, the same signals a real host loss
                # produces.
                self.runtime.kill_one_host()
            sid = self.runtime.new_shuffle_id(self)
            child = self.children[0]
            if self.partitioning[0] == "range" and \
                    (len(self.partitioning) < 3 or
                     self.partitioning[2] is None):
                self._resolve_range_bounds(sid)
            with TraceRange("ClusterShuffleExchangeExec.map"):
                for map_id in range(child.num_partitions):
                    self.runtime.run_map_task(self, sid, map_id)
            self.shuffle_id = sid
            self._read_stub = self.make_read_stub()

    #: rows each map task contributes to the bounds sample
    SAMPLE_ROWS_PER_MAP = 4096

    def _resolve_range_bounds(self, sid: int) -> None:
        """Cluster range partitioning, the reference's two-job split
        (GpuRangePartitioner.scala:42-95): a SAMPLING pass runs the
        child on every executor and returns host key samples, the
        driver aggregates them into bounds, then the normal map phase
        ships tasks with bounds attached."""
        import numpy as np

        from spark_rapids_tpu.memory import priorities
        from spark_rapids_tpu.memory.spillable import SpillableBatch
        from spark_rapids_tpu.ops import partition as part_ops

        child = self.children[0]
        per_map = []  # (data, validity, total_rows)
        with TraceRange("ClusterShuffleExchangeExec.sampleBounds"):
            for map_id in range(child.num_partitions):
                per_map.append(self.runtime.run_sample_task(
                    self, sid, map_id, self.SAMPLE_ROWS_PER_MAP))
            total_rows = sum(t for _d, _v, t in per_map)
            if self.num_out_partitions > 1 and total_rows * max(
                    sum(t.byte_width for t in self.schema.types), 1) \
                    <= self.collapse_bytes:
                # adaptive collapse, cluster edition: a tiny staged
                # input takes ONE partition — no bounds, no range
                # kernel in any map task
                self.num_out_partitions = 1
                self.partitioning = ("single",)
                return
            # weight each map's contribution by its share of the total
            # rows: unweighted merging over-represents small maps and
            # skews the quantile bounds (Spark's RangePartitioner
            # weights per-partition samples the same way)
            merged_d: dict = {n: [] for n in self.schema.names}
            merged_v: dict = {n: [] for n in self.schema.names}
            rng = np.random.default_rng(0x5EED)
            budget = self.SAMPLE_ROWS_PER_MAP * max(len(per_map), 1)
            for d, v, t in per_map:
                have = len(next(iter(d.values()))) if d else 0
                if have == 0:
                    continue
                want = max(int(round(budget * t / max(total_rows, 1))),
                           1)
                idx = np.arange(have) if have <= want else \
                    rng.choice(have, want, replace=False)
                for n in self.schema.names:
                    merged_d[n].append(np.asarray(d[n])[idx])
                    merged_v[n].append(
                        np.asarray(v[n], dtype=bool)[idx])
            data = {n: np.concatenate(a) if a else np.array([])
                    for n, a in merged_d.items()}
            val = {n: np.concatenate(a) if a else np.array([], bool)
                   for n, a in merged_v.items()}
            batch = host_sample_to_batch(data, val, self.schema)
            staged = [SpillableBatch(
                batch, priorities.INPUT_FROM_SHUFFLE_PRIORITY)]
            specs = list(self.partitioning[1])
            types = list(self.schema.types)
            if len(specs) > 1:
                bounds = part_ops.sample_range_bounds_rows(
                    staged, specs, types, self.num_out_partitions)
            else:
                bounds = part_ops.sample_range_bounds_multi(
                    staged, specs, types, self.num_out_partitions)
            for sb in staged:
                sb.close()
        self.partitioning = ("range", specs, bounds)

    def run_map_locally(self, shuffle_id: int, map_id: int,
                        executor_index: int) -> None:
        """Execute one map task in THIS process, writing into the given
        local executor's catalog (RapidsCachingWriter.write)."""
        child = self.children[0]
        parts = run_map_partitions(
            child.execute(map_id), self.partitioning,
            list(self.schema.types), self.num_out_partitions)
        self.runtime.cluster.write_map_output(shuffle_id, map_id,
                                              executor_index, parts)

    def task_payload(self, shuffle_id: int, map_id: int) -> dict:
        """The pickled closure a remote worker executes: child subtree
        with nested cluster exchanges stubbed to reads, plus the
        partitioning spec and the peer address book."""
        return {
            "shuffle_id": shuffle_id,
            "map_id": map_id,
            "subtree": self.runtime.task_tree(self.children[0]),
            "partitioning": self.partitioning,
            "num_out": self.num_out_partitions,
            "types": list(self.schema.types),
            "addresses": self.runtime.addresses(),
        }

    def map_output_sizes(self) -> List[int]:
        """Per-reduce-partition bytes from the cluster tracker's
        MapStatus sizes (the in-process exchange reads its block dict;
        here blocks live in per-executor catalogs across processes) —
        feeds AQE's coalesced reads in cluster mode."""
        sid = self.shuffle_id if self.shuffle_id is not None \
            else self._pending_sid
        sizes = [0] * self.num_out_partitions
        for _mid, (_eid, partitions) in \
                self.runtime.map_outputs_snapshot(sid).items():
            for p, s in partitions.items():
                sizes[int(p)] += int(s)
        return sizes

    def make_read_stub(self) -> ClusterShuffleReadExec:
        sid = self.shuffle_id if self.shuffle_id is not None \
            else self._pending_sid
        assert sid is not None, \
            "make_read_stub before new_shuffle_id registered this exchange"
        maps = self.runtime.map_outputs_snapshot(sid)
        return ClusterShuffleReadExec(
            self.schema, sid, self.num_out_partitions,
            self.children[0].num_partitions, maps,
            self.runtime.addresses())

    # -- reduce side ------------------------------------------------------

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            from spark_rapids_tpu.memory import priorities
            from spark_rapids_tpu.memory.spillable import SpillableBatch
            from spark_rapids_tpu.shuffle import fault_injection

            self._materialize()
            # the reduce entry is a stage boundary too (the map stage
            # ended, the read stage begins) — and it is the boundary
            # where a host death costs the most: every map output is
            # registered, so killing here deterministically drives the
            # full fetch-failure -> recover -> re-run ladder. Counted
            # once per exchange, not per reduce partition.
            with self._mat_lock:
                first_reduce = not self._reduce_stage_counted
                self._reduce_stage_counted = True
            if first_reduce and fault_injection.get_injector() \
                    .should_kill_host_at_stage():
                self.runtime.kill_one_host()
            # stage-retry barrier: buffer the partition so a mid-stream
            # fetch failure can restart the read without duplicating
            # already-yielded batches (Spark re-runs the whole task).
            # Buffered batches are SPILLABLE — a large reduce partition
            # must not pin its full size in HBM while the read drains
            staged: List[SpillableBatch] = []
            budget = max(int(self.runtime.max_stage_retries), 0)
            backoff_s = max(int(self.runtime.retry_backoff_ms), 0) / 1e3
            attempt = 0
            while True:
                stub = self._read_stub
                try:
                    for b in stub.execute(partition):
                        staged.append(SpillableBatch(
                            b, priorities.INPUT_FROM_SHUFFLE_PRIORITY))
                    break
                except ShuffleFetchFailedError as e:
                    for sb in staged:
                        sb.close()
                    staged = []
                    recovery.bump("fetch_failures")
                    if attempt >= budget:
                        # budget exhausted: the ORIGINAL fetch failure
                        # surfaces, chained from its transport cause
                        raise e from (
                            e.cause
                            if isinstance(e.cause, BaseException)
                            else None)
                    if backoff_s:
                        time.sleep(backoff_s * (2 ** attempt))
                    attempt += 1
                    recovery.bump("stage_retries")
                    self.runtime.recover(e)
                    self._read_stub = self.make_read_stub()
            for sb in staged:
                with sb.acquired() as b:
                    yield b
                sb.close()
        return timed(self, it())


class RemoteTaskError(RuntimeError):
    """A task shipped to a remote worker RAN there and failed (the
    worker reported an error reply). Distinct from RuntimeError so the
    scheduler's local re-placement never triggers on driver-side
    failures that merely share the base class."""


class RemoteWorkerHandle:
    """Driver-side handle to one worker process (a separate OS process
    hosting an executor: catalog + TCP shuffle server + task loop).

    Replies are pumped by a daemon reader thread into a queue, which
    buys two liveness properties at once: ``run_map`` can bound its wait
    (``task_timeout`` — a hung worker used to be an infinite
    ``readline``), and ``close`` never deadlocks against a worker
    blocked mid-write on a reply larger than the pipe buffer (the
    thread keeps draining stdout while the driver waits for exit)."""

    def __init__(self, executor_id: str, proc, host: str, port: int,
                 task_timeout: Optional[float] = None):
        import queue

        self.executor_id = executor_id
        self.proc = proc
        self.host = host
        self.port = port
        #: seconds run_map waits for a reply before declaring the worker
        #: hung, killing it, and re-placing the task (None = forever)
        self.task_timeout = task_timeout
        self._lock = lockorder.make_lock("runtime.cluster.worker")
        self._replies: "queue.Queue[Optional[str]]" = queue.Queue()
        self._reader = threading.Thread(
            target=self._drain_stdout,
            name=f"worker-reader-{executor_id}", daemon=True)
        self._reader.start()

    def _drain_stdout(self) -> None:
        try:
            for line in self.proc.stdout:
                self._replies.put(line)
        except (ValueError, OSError):
            pass
        finally:
            self._replies.put(None)  # EOF sentinel: the worker is gone

    @classmethod
    def spawn(cls, executor_id: str, mesh_devices: int = 0,
              task_timeout: Optional[float] = None
              ) -> "RemoteWorkerHandle":
        import os
        import subprocess
        import sys

        env = dict(os.environ)
        # workers compute on CPU: they must not fight over the single
        # attached TPU (a real deployment gives each its own chip)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        if mesh_devices >= 2:
            # shipped mesh subtrees reconstruct their mesh from THIS
            # process's devices (parallel/mesh.reconstruct_mesh): give
            # the worker the session's mesh width in virtual devices —
            # ICI collectives inside the task, TCP shuffle between
            # executors (SURVEY §5.8 ICI+DCN composition)
            env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count"
                                f"={mesh_devices}")
            # the worker applies this explicitly at startup (the axon
            # sitecustomize overrides jax config at interpreter start,
            # so env flags alone don't stick — remote_worker.main)
            env["SRT_WORKER_MESH_DEVICES"] = str(mesh_devices)
        proc = subprocess.Popen(
            [sys.executable, "-m",
             "spark_rapids_tpu.shuffle.remote_worker"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env,
            text=True)
        proc.stdin.write(
            '{"executor_id": "%s", "mode": "task"}\n' % executor_id)
        proc.stdin.flush()
        # READY is read inline, BEFORE the reader thread exists (the
        # thread starts in __init__), so handshake and reply streams
        # never interleave
        line = proc.stdout.readline().split()
        assert line and line[0] == "READY", line
        return cls(executor_id, proc, line[1], int(line[2]),
                   task_timeout=task_timeout)

    def run_map(self, payload: dict,
                timeout: Optional[float] = None) -> dict:
        """Ship one map task; blocks until the worker reports or the
        liveness timeout expires. Raises ConnectionError on worker
        death or hang (the caller re-runs the task elsewhere)."""
        import json
        import queue

        from spark_rapids_tpu.shuffle import fault_injection

        if fault_injection.get_injector().should_kill_task():
            self.kill()  # injected worker death right before submit
        blob = base64.b64encode(pickle.dumps(payload)).decode()
        budget = self.task_timeout if timeout is None else timeout
        with self._lock:
            try:
                self.proc.stdin.write(
                    json.dumps({"cmd": "run_map", "payload_b64": blob}) +
                    "\n")
                self.proc.stdin.flush()
            except (BrokenPipeError, OSError, ValueError) as e:
                raise ConnectionError(
                    f"worker {self.executor_id} died at submit: {e}")
            try:
                line = self._replies.get(timeout=budget)
            except queue.Empty:
                # hung worker: kill it BEFORE re-placing the task, so a
                # late completion can never double-register its output
                self.kill()
                raise ConnectionError(
                    f"worker {self.executor_id} unresponsive after "
                    f"{budget}s (killed)") from None
        if line is None:
            raise ConnectionError(
                f"worker {self.executor_id} died")
        reply = json.loads(line)
        if not reply.get("ok"):
            raise RemoteTaskError(
                f"worker {self.executor_id} task failed: "
                f"{reply.get('error')}")
        return reply

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self):
        self.proc.kill()
        self.proc.wait()

    def close(self):
        # the reader thread keeps draining stdout, so a worker blocked
        # writing an oversized reply finishes the write and sees the
        # stdin EOF instead of deadlocking against our wait
        try:
            self.proc.stdin.close()
        except (BrokenPipeError, OSError, ValueError):
            pass
        try:
            self.proc.wait(timeout=5)
        except Exception:
            self.kill()  # always escalate: close() must end the process


class ClusterRuntime:
    """Driver-side cluster state: executors (in-process + worker
    processes), the MapOutputTracker, task assignments for retry, and
    the stage scheduler hooks the cluster exchange calls into."""

    def __init__(self, n_executors: int = 2, n_workers: int = 1,
                 spill_dir: Optional[str] = None,
                 mesh_devices: int = 0,
                 max_stage_retries: int = 3,
                 task_timeout_sec: Optional[float] = 120.0,
                 blacklist_after: int = 3,
                 respawn_workers: bool = True,
                 retry_backoff_ms: int = 50):
        self.cluster = LocalCluster(max(n_executors, 1), transport="tcp",
                                    spill_dir=spill_dir)
        self.mesh_devices = mesh_devices
        self.max_stage_retries = max_stage_retries
        self.task_timeout_sec = task_timeout_sec
        self.blacklist_after = blacklist_after
        self.respawn_workers = respawn_workers
        self.retry_backoff_ms = retry_backoff_ms
        self.workers: List[RemoteWorkerHandle] = []
        for i in range(n_workers):
            w = RemoteWorkerHandle.spawn(f"exec-worker-{i}",
                                         mesh_devices=mesh_devices,
                                         task_timeout=task_timeout_sec)
            self.workers.append(w)
            self.cluster.register_remote_executor(w.executor_id, w.host,
                                                  w.port)
        # consecutive-failure counts + blacklist, per worker SLOT (the
        # generation-free base id: every respawn of exec-worker-1 shares
        # exec-worker-1's record — blacklisting targets the flapping
        # host, not one incarnation of it)
        self._failures: Dict[str, int] = {}
        self.blacklisted: set = set()
        # slots retired by remove_host: never respawned, never targeted
        # — DISTINCT from blacklisting (a decommission is an operator /
        # autoscaler decision, not a fault record)
        self.decommissioned: set = set()
        # next fresh slot index for add_host (existing slots are 0..n-1)
        self._next_slot = n_workers
        # membership-change journal: (action, executor_id, reason)
        self.scale_events: List[dict] = []
        self._sid = itertools.count()
        self._lock = lockorder.make_lock("runtime.cluster.state")
        # serializes fetch-failure recovery against stub rebuilds: the
        # window between invalidating a dead executor's MapStatus and the
        # re-run registering its replacement must not be observable (a
        # snapshot taken inside it would silently drop that map's blocks)
        self._recover_lock = lockorder.make_rlock("runtime.cluster.recover")
        # shuffle_id -> exchange exec (for upstream stage re-runs)
        self.exchanges: Dict[int, ClusterShuffleExchangeExec] = {}
        # shuffle_id -> map_id -> executor_id assignment
        self.assignments: Dict[int, Dict[int, str]] = {}
        self._rr = itertools.count()
        # injectable task placement: fn(shuffle_id, map_id, targets) ->
        # executor_id (or None = fall back to round-robin). Tests and
        # alternative schedulers steer placement through this seam
        # instead of coupling to the round-robin counter internals.
        self.placement_hook = None

    # -- identity ---------------------------------------------------------

    def new_shuffle_id(self, exchange: ClusterShuffleExchangeExec) -> int:
        with self._lock:
            sid = next(self._sid)
            self.exchanges[sid] = exchange
            exchange._pending_sid = sid
            self.assignments[sid] = {}
            return sid

    def addresses(self) -> Dict[str, Tuple[str, int]]:
        out = dict(self.cluster.transport._addrs)
        for w in self.workers:
            out[w.executor_id] = (w.host, w.port)
        return out

    def executor_ids(self) -> List[str]:
        ids = [ex.executor_id for ex in self.cluster.executors]
        ids += [w.executor_id for w in self.workers
                if w.alive and
                self._slot(w.executor_id) not in self.blacklisted and
                self._slot(w.executor_id) not in self.decommissioned]
        return ids

    def live_worker_slots(self) -> List[str]:
        """Distinct worker slots with a live, targetable generation —
        the autoscaler's notion of current cluster size."""
        slots = []
        for w in self.workers:
            slot = self._slot(w.executor_id)
            if w.alive and slot not in self.blacklisted and \
                    slot not in self.decommissioned and \
                    slot not in slots:
                slots.append(slot)
        return slots

    # -- worker supervision (respawn + blacklist) --------------------------

    @staticmethod
    def _slot(executor_id: str) -> str:
        """Generation-free worker slot id: respawns of exec-worker-1 are
        exec-worker-1~1, exec-worker-1~2, ... and all map to the slot."""
        return executor_id.split("~", 1)[0]

    def _note_worker_failure(self, executor_id: str) -> None:
        """Count one liveness failure (submit-time death, task-timeout
        kill, fetch-failure blame) against the worker's slot; the Kth
        consecutive one blacklists it. In-process executors are never
        blacklisted — they are the driver's own catalogs."""
        slot = self._slot(executor_id)
        if not any(self._slot(w.executor_id) == slot
                   for w in self.workers):
            return
        newly = False
        with self._lock:
            n = self._failures.get(slot, 0) + 1
            self._failures[slot] = n
            if self.blacklist_after and n >= self.blacklist_after and \
                    slot not in self.blacklisted:
                self.blacklisted.add(slot)
                newly = True
        if newly:
            recovery.bump("executors_blacklisted")

    def _note_worker_success(self, executor_id: str) -> None:
        with self._lock:
            self._failures[self._slot(executor_id)] = 0

    def _respawn_dead_workers(self) -> None:
        """Supervision sweep: every dead, non-blacklisted worker slot
        with no live generation gets a fresh process (new id, same
        slot), registered with the driver's transport; peers learn the
        address through the address book every task payload and read
        stub carries (``addresses()``). Dead handles stay in
        ``self.workers`` — their ids must keep resolving for blame and
        for tests that index the original list."""
        if not self.respawn_workers:
            return
        for w in list(self.workers):
            if w.alive:
                continue
            slot = self._slot(w.executor_id)
            if slot in self.blacklisted or slot in self.decommissioned:
                continue
            if any(self._slot(o.executor_id) == slot and o.alive
                   for o in self.workers):
                continue
            gen = sum(1 for o in self.workers
                      if self._slot(o.executor_id) == slot)
            try:
                nw = RemoteWorkerHandle.spawn(
                    f"{slot}~{gen}", mesh_devices=self.mesh_devices,
                    task_timeout=self.task_timeout_sec)
            except (OSError, AssertionError, ValueError):
                # the replacement would not even start: that is another
                # strike against the slot
                self._note_worker_failure(slot)
                continue
            self.workers.append(nw)
            self.cluster.register_remote_executor(nw.executor_id,
                                                  nw.host, nw.port)
            recovery.bump("workers_respawned")

    # -- elastic membership (hosts join and leave as recovery events) -----

    def add_host(self, reason: str = "scale-up") -> str:
        """Join a NEW worker host to the running cluster: fresh slot,
        fresh process, registered with the driver's transport so the
        next task placement and every subsequent read stub's address
        book can target it. No stage pauses — the membership change
        rides the same seam recovery uses (serialized under the recover
        lock so a concurrent fetch-failure recovery never observes a
        half-registered host)."""
        with self._recover_lock:
            slot_idx = self._next_slot
            self._next_slot += 1
            eid = f"exec-worker-{slot_idx}"
            w = RemoteWorkerHandle.spawn(
                eid, mesh_devices=self.mesh_devices,
                task_timeout=self.task_timeout_sec)
            self.workers.append(w)
            self.cluster.register_remote_executor(w.executor_id, w.host,
                                                  w.port)
            self.scale_events.append(
                {"action": "add", "executor_id": eid, "reason": reason})
        recovery.bump("hosts_added")
        return eid

    def remove_host(self, executor_id: str,
                    reason: str = "scale-down") -> List[Tuple[int, int]]:
        """Decommission a worker host mid-query, driving the SAME
        lineage ladder a host death does: kill every live generation of
        the slot, invalidate its registered map outputs, and re-run
        exactly the lost maps on the survivors — so reduces that later
        rebuild their stubs read repaired trackers, never the dead
        host. The slot is retired (no respawn, no future placement) but
        NOT blacklisted: leaving on request is not a fault. Returns the
        (shuffle_id, map_id) pairs that re-ran."""
        slot = self._slot(executor_id)
        rerun: List[Tuple[int, int]] = []
        with self._recover_lock:
            self.decommissioned.add(slot)
            gens = [w for w in self.workers
                    if self._slot(w.executor_id) == slot]
            assert gens, f"remove_host: unknown worker slot {slot}"
            for w in gens:
                if w.alive:
                    w.kill()
            gen_ids = {w.executor_id for w in gens}
            with self._lock:
                sids = sorted(self.assignments)
            for sid in sids:
                exchange = self.exchanges.get(sid)
                if exchange is None:
                    continue
                for eid in gen_ids:
                    lost = self.cluster.invalidate_map_output(sid, eid)
                    for map_id in lost:
                        self.run_map_task(exchange, sid, map_id,
                                          exclude=gen_ids)
                        rerun.append((sid, map_id))
            if rerun:
                recovery.bump("maps_rerun", len(rerun))
            self.scale_events.append(
                {"action": "remove", "executor_id": executor_id,
                 "reason": reason, "maps_rerun": len(rerun)})
        recovery.bump("hosts_removed")
        return rerun

    def kill_one_host(self) -> Optional[str]:
        """SIGKILL one live, targetable worker host (the fault
        injector's host-granularity primitive), PREFERRING a host that
        owns registered map output — a load-bearing loss, so the
        deterministic CI kill exercises the recovery ladder instead of
        an idle bystander. Deliberately does NO bookkeeping: recovery
        must discover the death through fetch failures, exactly as
        with a real host loss."""
        owners = {self._slot(eid) for maps in self.assignments.values()
                  for eid in maps.values()}
        candidates = [
            w for w in self.workers
            if w.alive and self._slot(w.executor_id) not in
            self.blacklisted and self._slot(w.executor_id) not in
            self.decommissioned]
        preferred = [w for w in candidates
                     if self._slot(w.executor_id) in owners]
        for w in (preferred or candidates):
            w.kill()
            return w.executor_id
        return None

    # -- task scheduling --------------------------------------------------

    def _place(self, shuffle_id: int, map_id: int,
               targets: List[str]) -> str:
        """Pick the executor for one task: the placement hook decides
        when set (and names a live target); round-robin otherwise —
        the reference gets placement from Spark's scheduler."""
        if self.placement_hook is not None:
            chosen = self.placement_hook(shuffle_id, map_id,
                                         list(targets))
            if chosen is not None and chosen in targets:
                return chosen
        return targets[next(self._rr) % len(targets)]

    def run_map_task(self, exchange: ClusterShuffleExchangeExec,
                     shuffle_id: int, map_id: int,
                     exclude: Optional[set] = None) -> None:
        """Assign + execute one map task."""
        targets = [e for e in self.executor_ids()
                   if not exclude or e not in exclude]
        assert targets, "no live executors"
        target = self._place(shuffle_id, map_id, targets)
        worker = next((w for w in self.workers
                       if w.executor_id == target), None)
        if worker is not None:
            # build the payload OUTSIDE the placement try: task_tree()
            # materializes nested upstream stages driver-side, and a
            # failure there is a query failure, not a placement problem
            payload = exchange.task_payload(shuffle_id, map_id)
            try:
                reply = worker.run_map(payload)
                self.cluster.register_remote_map_output(
                    shuffle_id, map_id, worker.executor_id,
                    reply["partitions"])
                with self._lock:
                    self.assignments[shuffle_id][map_id] = \
                        worker.executor_id
                self._note_worker_success(target)
                return
            except (ConnectionError, BrokenPipeError, OSError) as e:
                # dead or hung worker at SUBMIT time: place locally
                # instead, and count the strike toward its blacklist
                exchange.local_fallbacks.append(
                    f"worker {target} dead at submit: {e}")
                self._note_worker_failure(target)
            except (pickle.PicklingError, TypeError, AttributeError) as e:
                # unpicklable task subtree (cached relations hold locks):
                # this task can only run in-process — local placement,
                # not a query failure
                exchange.local_fallbacks.append(
                    f"unpicklable task subtree: {type(e).__name__}: {e}")
            except RemoteTaskError as e:
                # the worker RAN the task and it failed remotely — e.g. a
                # nested ClusterShuffleReadExec in the shipped subtree hit
                # a fetch failure against a dead peer. Re-place locally
                # (the driver process can recover through its own
                # exchange objects) instead of failing the whole query.
                exchange.local_fallbacks.append(
                    f"remote task failed on {target}, re-placed locally: "
                    f"{e}")
        idx = self._local_index(target)
        exchange.run_map_locally(shuffle_id, map_id, idx)
        with self._lock:
            self.assignments[shuffle_id][map_id] = \
                self.cluster.executors[idx].executor_id

    def run_sample_task(self, exchange: "ClusterShuffleExchangeExec",
                        shuffle_id: int, map_id: int, k: int):
        """Bounds-sampling pass for one map partition: run it remotely
        when its placement slot is a worker, else locally; either way
        return host sample arrays (data, validity)."""
        targets = self.executor_ids()
        target = self._place(shuffle_id, map_id, targets)
        worker = next((w for w in self.workers
                       if w.executor_id == target), None)
        if worker is not None:
            payload = exchange.task_payload(shuffle_id, map_id)
            payload["mode"] = "sample"
            payload["sample_rows"] = k
            try:
                reply = worker.run_map(payload)
                return pickle.loads(
                    base64.b64decode(reply["sample_b64"]))
            except (ConnectionError, BrokenPipeError, OSError,
                    pickle.PicklingError, TypeError, AttributeError,
                    RemoteTaskError) as e:
                exchange.local_fallbacks.append(
                    f"sample task on {target} failed, ran locally: "
                    f"{type(e).__name__}")
        child = exchange.children[0]
        return sample_rows_host(child.execute(map_id), exchange.schema, k)

    def _local_index(self, target: str) -> int:
        for i, ex in enumerate(self.cluster.executors):
            if ex.executor_id == target:
                return i
        return 0  # a worker id that died — fall back to executor 0

    def task_tree(self, node: TpuExec) -> TpuExec:
        """Copy of a task subtree with nested cluster exchanges replaced
        by read stubs (materializing them first): the remote worker
        FETCHES upstream stages instead of recomputing them."""
        import copy

        from spark_rapids_tpu.execs.adaptive import \
            AdaptiveShuffleReaderExec

        if isinstance(node, ClusterShuffleExchangeExec):
            node._materialize()
            return node.make_read_stub()
        if isinstance(node, AdaptiveShuffleReaderExec):
            # resolve the group spec against the LIVE exchange before
            # its child becomes a read stub (stats need the tracker)
            node.groups
        clone = copy.copy(node)
        clone.children = [self.task_tree(c) for c in node.children]
        return clone

    # -- failure recovery (fetch-failure -> stage retry) ------------------

    def map_outputs_snapshot(self, shuffle_id: int
                             ) -> Dict[int, Tuple[str, dict]]:
        """Tracker snapshot for stub building, serialized against
        recovery so it can never observe a half-recovered shuffle."""
        with self._recover_lock:
            return dict(self.cluster._map_outputs.get(shuffle_id, {}))

    def recover(self, err: ShuffleFetchFailedError) -> None:
        """Spark's fetch-failure handling: unregister the dead executor's
        map outputs (for the failed shuffle), then re-run those map tasks
        on the survivors. Concurrent reduce tasks failing on the same
        dead peer serialize here; the second finds nothing left to
        invalidate and just rebuilds its stub from the repaired tracker."""
        dead = err.executor_id
        sid = err.block.shuffle_id
        with self._recover_lock:
            for w in self.workers:
                if w.executor_id == dead and w.alive:
                    w.kill()  # a peer that failed a fetch is not trusted
            self._note_worker_failure(dead)
            self._respawn_dead_workers()
            lost = self.cluster.invalidate_map_output(sid, dead)
            exchange = self.exchanges[sid]
            for map_id in lost:
                self.run_map_task(exchange, sid, map_id, exclude={dead})
            if lost:
                recovery.bump("maps_rerun", len(lost))

    def shutdown(self):
        for w in self.workers:
            w.close()
        self.cluster.shutdown()
        set_executor_context(None)


# -- planner hook ---------------------------------------------------------

_SESSION_RUNTIME: Optional[ClusterRuntime] = None
_RUNTIME_KEY: Optional[tuple] = None


def session_cluster(conf) -> Optional[ClusterRuntime]:
    """Process-cached cluster runtime (like session_mesh): spawning
    worker processes per query would defeat the executor model."""
    from spark_rapids_tpu import config as cfg

    if conf is None or not conf.get(cfg.CLUSTER_ENABLED):
        return None
    global _SESSION_RUNTIME, _RUNTIME_KEY
    mesh_devices = 0
    if conf.get(cfg.MESH_ENABLED):
        from spark_rapids_tpu.parallel.mesh import session_mesh

        m = session_mesh(conf)
        if m is not None:
            # total devices (data * model): workers must be able to
            # reconstruct the full 2-D slice a shipped subtree names
            mesh_devices = int(m.devices.size)
    key = (conf.get(cfg.CLUSTER_EXECUTORS),
           conf.get(cfg.CLUSTER_WORKERS), mesh_devices,
           conf.get(cfg.CLUSTER_MAX_STAGE_RETRIES),
           conf.get(cfg.CLUSTER_TASK_TIMEOUT_SEC),
           conf.get(cfg.CLUSTER_BLACKLIST_AFTER),
           conf.get(cfg.CLUSTER_RESPAWN_WORKERS),
           conf.get(cfg.CLUSTER_RETRY_BACKOFF_MS))
    if _SESSION_RUNTIME is None or _RUNTIME_KEY != key:
        if _SESSION_RUNTIME is not None:
            _SESSION_RUNTIME.shutdown()
        _SESSION_RUNTIME = ClusterRuntime(
            n_executors=key[0], n_workers=key[1],
            mesh_devices=mesh_devices,
            max_stage_retries=key[3], task_timeout_sec=key[4],
            blacklist_after=key[5], respawn_workers=key[6],
            retry_backoff_ms=key[7])
        _RUNTIME_KEY = key
        set_executor_context(ExecutorContext(
            _SESSION_RUNTIME.cluster.executors[0],
            _SESSION_RUNTIME.cluster.transport))
        import atexit

        atexit.register(shutdown_session_cluster)
    return _SESSION_RUNTIME


def active_cluster() -> Optional[ClusterRuntime]:
    """The live session cluster runtime, if one has been built — the
    autoscaler's handle onto the elastic-membership seam (it must never
    CREATE a cluster, only grow one the session already runs)."""
    return _SESSION_RUNTIME


def shutdown_session_cluster() -> None:
    global _SESSION_RUNTIME, _RUNTIME_KEY
    if _SESSION_RUNTIME is not None:
        _SESSION_RUNTIME.shutdown()
        _SESSION_RUNTIME = None
        _RUNTIME_KEY = None


def install_cluster_exchanges(exec_: TpuExec, runtime: ClusterRuntime,
                              _memo: Optional[dict] = None) -> TpuExec:
    """Post-planning pass: swap hash/single exchanges for cluster-backed
    ones (the reference swaps the shuffle manager underneath the same
    exec; here the exec itself is the seam). The rewrite is memoized by
    node identity so a shared exchange (CTE/ReuseExchange) stays ONE
    cluster exchange — every parent reads the same materialized shuffle
    instead of each re-shuffling the shared stage. Adaptive readers work
    ABOVE cluster exchanges: statistics come from ``map_output_sizes``
    (tracker MapStatus sizes) and paired join readers resolve through
    the readers' CURRENT children, so this rewrite flows straight
    through them (GpuOverrides.scala:1874-1887 role). Range exchanges
    run cluster-wide too: the driver aggregates per-map key samples,
    resolves bounds, then ships partition tasks with bounds attached
    (GpuRangePartitioner.scala:42-95's sample-then-partition split)."""
    if _memo is None:
        _memo = {}
    hit = _memo.get(id(exec_))
    if hit is not None:
        return hit[1]
    orig = exec_
    if isinstance(exec_, ShuffleExchangeExec) and \
            not isinstance(exec_, ClusterShuffleExchangeExec) and \
            exec_.partitioning[0] in ("hash", "single", "range"):
        exec_ = ClusterShuffleExchangeExec.wrap(exec_, runtime)
    exec_.children = [install_cluster_exchanges(c, runtime, _memo)
                      for c in exec_.children]
    # pin the original node in the memo value: id() reuse after GC is a
    # known landmine (see memory build-env-quirks)
    _memo[id(orig)] = (orig, exec_)
    return exec_
