"""Device manager + runtime environment singleton."""
from __future__ import annotations

import dataclasses
import threading
from spark_rapids_tpu.utils import lockorder
from typing import Optional

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.memory import semaphore as sem
from spark_rapids_tpu.memory.catalog import (BufferCatalog, get_catalog,
                                             reset_catalog)


class TpuDeviceManager:
    """GpuDeviceManager analogue (GpuDeviceManager.scala:31): owns the
    chosen device and the memory-budget math."""

    def __init__(self, device_ordinal: int = 0):
        self.device_ordinal = device_ordinal
        self._device = None

    @property
    def device(self):
        if self._device is None:
            import jax

            devices = jax.devices()
            if self.device_ordinal >= len(devices):
                raise RuntimeError(
                    f"device ordinal {self.device_ordinal} out of range "
                    f"({len(devices)} devices)")
            self._device = devices[self.device_ordinal]
        return self._device

    def hbm_bytes(self) -> Optional[int]:
        """Total device memory (Cuda.memGetInfo analogue). None when the
        backend doesn't report it (CPU host platform)."""
        try:
            stats = self.device.memory_stats()
        except Exception:
            return None
        if not stats:
            return None
        return stats.get("bytes_limit") or stats.get(
            "bytes_reservable_limit")

    def device_budget(self, conf: RapidsConf) -> Optional[int]:
        """allocFraction * hbm - reserve (GpuDeviceManager.scala:159-258
        pool sizing). None = unbounded (no HBM accounting available)."""
        total = self.hbm_bytes()
        if total is None:
            return None
        frac = conf.get(cfg.HBM_POOL_FRACTION)
        reserve = conf.get(cfg.HBM_RESERVE)
        budget = int(total * frac) - reserve
        if budget <= 0:
            raise RuntimeError(
                f"HBM budget non-positive: total={total} frac={frac} "
                f"reserve={reserve}")
        return budget


@dataclasses.dataclass
class RuntimeEnv:
    conf: RapidsConf
    device_manager: TpuDeviceManager
    catalog: BufferCatalog
    semaphore: "sem.TpuSemaphore"
    shuffle_codec: str

    @property
    def device(self):
        return self.device_manager.device


_env: Optional[RuntimeEnv] = None
_lock = lockorder.make_lock("runtime.device")


def initialize(conf: Optional[RapidsConf] = None,
               device_ordinal: int = 0) -> RuntimeEnv:
    """Executor-init analogue (RapidsExecutorPlugin.init,
    Plugin.scala:122-147). Idempotent: re-initializing with a new conf
    replaces the environment."""
    global _env
    conf = conf or RapidsConf()
    with _lock:
        dm = TpuDeviceManager(device_ordinal)
        _ = dm.device  # fail fast if the device is unavailable
        # an explicit configured budget wins over the HBM-derived one —
        # the artificially-small-budget mode the out-of-core fence uses
        budget = conf.get(cfg.DEVICE_BUDGET) or dm.device_budget(conf)
        catalog = BufferCatalog(
            device_budget=budget,
            host_budget=conf.get(cfg.HOST_SPILL_STORAGE_SIZE),
            spill_dir=conf.get(cfg.SPILL_DIR),
            disk_codec=conf.get(cfg.SHUFFLE_COMPRESSION_CODEC)
            if conf.get(cfg.SHUFFLE_COMPRESSION_CODEC) != "none"
            else "lz4",
            async_spill=conf.get(cfg.SPILL_ASYNC_WRITE))
        reset_catalog(catalog)
        semaphore = sem.initialize(conf.get(cfg.CONCURRENT_TPU_TASKS))
        from spark_rapids_tpu.memory import fault_injection, retry
        from spark_rapids_tpu.shuffle import \
            fault_injection as shuffle_fault_injection

        retry.configure_from_conf(conf)
        fault_injection.arm_from_conf(conf)
        shuffle_fault_injection.arm_from_conf(conf)
        from spark_rapids_tpu.shuffle import tcp as shuffle_tcp

        shuffle_tcp.configure_retry_from_conf(conf)
        from spark_rapids_tpu.native import kernels

        kernels.configure_from_conf(conf)
        _env = RuntimeEnv(conf, dm, catalog, semaphore,
                          conf.get(cfg.SHUFFLE_COMPRESSION_CODEC))
        return _env


def get_env() -> Optional[RuntimeEnv]:
    with _lock:
        return _env


def shutdown() -> None:
    """Test teardown: drop the environment and restore defaults."""
    global _env
    with _lock:
        old = _env
        _env = None
        if old is not None:
            old.catalog.close()  # drain + end the spill writer thread
        reset_catalog(BufferCatalog())
        sem.initialize(2)
        from spark_rapids_tpu.memory import fault_injection, retry
        from spark_rapids_tpu.shuffle import \
            fault_injection as shuffle_fault_injection

        retry.reset_config()
        fault_injection.get_injector().disarm()
        shuffle_fault_injection.get_injector().disarm()
        from spark_rapids_tpu.native import kernels

        kernels.reset_config()
