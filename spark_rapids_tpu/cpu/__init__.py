"""CPU engine: an independent pandas/numpy interpreter of the plan-node
vocabulary. Plays the role vanilla Spark plays in the reference — the
fallback target for nodes the planner can't put on TPU, and the golden
oracle for the CPU-vs-TPU comparison test harness
(SparkQueryCompareTestSuite.scala:153-161, integration_tests asserts.py)."""
from spark_rapids_tpu.cpu.engine import execute_cpu  # noqa: F401
