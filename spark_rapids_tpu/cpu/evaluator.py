"""Numpy expression evaluator with Spark SQL semantics.

Deliberately implemented WITHOUT jax so it is an independent oracle for the
device expression layer (the reference's oracle is vanilla Spark itself —
its CPU implementations of every expression; SURVEY.md §4). Columns are
(data ndarray, validity bool ndarray|None); strings are object arrays.
Dates are int32 days since epoch, timestamps int64 UTC microseconds —
the same logical encoding the device layer uses, so results compare 1:1.
"""
from __future__ import annotations

import datetime
import re as _re
from typing import List, Optional

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expressions import arithmetic as ar
from spark_rapids_tpu.expressions import bitwise as bw
from spark_rapids_tpu.expressions import conditional as cond
from spark_rapids_tpu.expressions import constraints as cns
from spark_rapids_tpu.expressions import datetime as dte
from spark_rapids_tpu.expressions import math as mth
from spark_rapids_tpu.expressions import nondeterministic as nd
from spark_rapids_tpu.expressions import predicates as pr
from spark_rapids_tpu.expressions import strings as st
from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Expression, Literal)
from spark_rapids_tpu.expressions.cast import (Cast, _format_one, _parse_one)


class CV:
    """A CPU column value: data + optional validity mask."""

    __slots__ = ("dtype", "data", "validity")

    def __init__(self, dtype: dt.DType, data: np.ndarray,
                 validity: Optional[np.ndarray] = None):
        self.dtype = dtype
        self.data = data
        self.validity = validity

    def valid_mask(self) -> np.ndarray:
        if self.validity is None:
            return np.ones(len(self.data), dtype=bool)
        return self.validity

    def __len__(self):
        return len(self.data)


def cv_null(dtype: dt.DType, n: int) -> CV:
    if dtype is dt.STRING:
        data = np.full(n, None, dtype=object)
    else:
        data = np.zeros(n, dtype=dtype.np_dtype)
    return CV(dtype, data, np.zeros(n, dtype=bool))


def cv_const(dtype: dt.DType, value, n: int) -> CV:
    if value is None:
        return cv_null(dtype, n)
    if dtype is dt.STRING:
        data = np.full(n, value, dtype=object)
    else:
        data = np.full(n, value, dtype=dtype.np_dtype)
    return CV(dtype, data, None)


def and_valid(*vs: Optional[np.ndarray]) -> Optional[np.ndarray]:
    out = None
    for v in vs:
        if v is None:
            continue
        out = v if out is None else (out & v)
    return out


class CpuEvalContext:
    def __init__(self, columns: List[CV], num_rows: int, origins=None):
        self.columns = columns
        self.num_rows = num_rows
        self.origins = origins  # [(origin, row_count)] above file scans


def eval_expr(e: Expression, ctx: CpuEvalContext) -> CV:
    """Evaluate to a full-length CV (literals broadcast)."""
    fn = _DISPATCH.get(type(e))
    if fn is None:
        # expressions may carry their own CPU evaluation (PythonUdf)
        if hasattr(e, "eval_cpu"):
            return e.eval_cpu(ctx)
        for klass, f in _DISPATCH.items():
            if isinstance(e, klass):
                fn = f
                break
    if fn is None:
        raise NotImplementedError(
            f"CPU evaluator: unsupported expression {type(e).__name__}")
    return fn(e, ctx)


# ---------------------------------------------------------------------------
# leaves

def _bound(e: BoundReference, ctx):
    return ctx.columns[e.ordinal]


def _literal(e: Literal, ctx):
    return cv_const(e.dtype, e.value, ctx.num_rows)


def _alias(e: Alias, ctx):
    return eval_expr(e.children[0], ctx)


# ---------------------------------------------------------------------------
# arithmetic (Java/Spark non-ANSI semantics: int ops wrap, x/0 -> null)

def _binary_num(e, ctx, op, out_dtype=None):
    l = eval_expr(e.children[0], ctx)
    r = eval_expr(e.children[1], ctx)
    odt = out_dtype or e.dtype
    with np.errstate(all="ignore"):
        data = op(l.data.astype(odt.np_dtype), r.data.astype(odt.np_dtype))
    return CV(odt, data.astype(odt.np_dtype),
              and_valid(l.validity, r.validity))


def _add(e, ctx):
    return _binary_num(e, ctx, np.add)


def _sub(e, ctx):
    return _binary_num(e, ctx, np.subtract)


def _mul(e, ctx):
    return _binary_num(e, ctx, np.multiply)


def _divide(e, ctx):
    l = eval_expr(e.children[0], ctx)
    r = eval_expr(e.children[1], ctx)
    rd = r.data.astype(np.float64)
    with np.errstate(all="ignore"):
        data = l.data.astype(np.float64) / np.where(rd == 0, 1.0, rd)
    validity = and_valid(l.validity, r.validity, rd != 0)
    return CV(dt.FLOAT64, data, validity)


def _int_div(e, ctx):
    l = eval_expr(e.children[0], ctx)
    r = eval_expr(e.children[1], ctx)
    ld = l.data.astype(np.int64)
    rd = r.data.astype(np.int64)
    safe = np.where(rd == 0, 1, rd)
    with np.errstate(all="ignore"):
        # Java integer division truncates toward zero
        q = (np.abs(ld) // np.abs(safe)) * (np.sign(ld) * np.sign(safe))
    validity = and_valid(l.validity, r.validity, rd != 0)
    return CV(dt.INT64, q.astype(np.int64), validity)


def _remainder(e, ctx):
    l = eval_expr(e.children[0], ctx)
    r = eval_expr(e.children[1], ctx)
    odt = e.dtype
    ld = l.data.astype(odt.np_dtype)
    rd = r.data.astype(odt.np_dtype)
    zero = (rd == 0)
    safe = np.where(zero, 1, rd)
    with np.errstate(all="ignore"):
        data = np.fmod(ld, safe)  # sign of dividend (Java %)
    return CV(odt, data.astype(odt.np_dtype),
              and_valid(l.validity, r.validity, ~zero))


def _pmod(e, ctx):
    l = eval_expr(e.children[0], ctx)
    r = eval_expr(e.children[1], ctx)
    odt = e.dtype
    ld = l.data.astype(odt.np_dtype)
    rd = r.data.astype(odt.np_dtype)
    zero = (rd == 0)
    safe = np.where(zero, 1, rd)
    with np.errstate(all="ignore"):
        m = np.fmod(ld, safe)
        # Spark: only NEGATIVE remainders are corrected; (r + n) wraps at
        # integer boundaries exactly like Java addition
        data = np.where(m < 0, np.fmod(m + safe, safe), m)
    return CV(odt, data.astype(odt.np_dtype),
              and_valid(l.validity, r.validity, ~zero))


def _bitwise_binary(op):
    def f(e, ctx):
        l = eval_expr(e.children[0], ctx)
        r = eval_expr(e.children[1], ctx)
        odt = e.dtype
        data = op(l.data.astype(odt.np_dtype), r.data.astype(odt.np_dtype))
        return CV(odt, data.astype(odt.np_dtype),
                  and_valid(l.validity, r.validity))
    return f


def _bitwise_not(e, ctx):
    v = eval_expr(e.children[0], ctx)
    return CV(e.dtype, np.invert(v.data.astype(e.dtype.np_dtype)),
              v.validity)


def _shift(op, unsigned=False):
    def f(e, ctx):
        l = eval_expr(e.children[0], ctx)
        r = eval_expr(e.children[1], ctx)
        odt = e.dtype
        width = 64 if odt is dt.INT64 else 32
        a = l.data.astype(odt.np_dtype)
        s = r.data.astype(np.int64) & (width - 1)  # Java shift mask
        if unsigned:
            ut = np.uint64 if odt is dt.INT64 else np.uint32
            data = (a.view(ut) >> s.astype(ut)).view(odt.np_dtype)
        else:
            data = op(a, s.astype(odt.np_dtype))
        return CV(odt, data.astype(odt.np_dtype),
                  and_valid(l.validity, r.validity))
    return f


def _unary_minus(e, ctx):
    v = eval_expr(e.children[0], ctx)
    with np.errstate(all="ignore"):
        return CV(e.dtype, (-v.data).astype(e.dtype.np_dtype), v.validity)


def _unary_pos(e, ctx):
    return eval_expr(e.children[0], ctx)


def _abs(e, ctx):
    v = eval_expr(e.children[0], ctx)
    with np.errstate(all="ignore"):
        return CV(e.dtype, np.abs(v.data).astype(e.dtype.np_dtype),
                  v.validity)


def _signum(e, ctx):
    v = eval_expr(e.children[0], ctx)
    return CV(dt.FLOAT64, np.sign(v.data.astype(np.float64)), v.validity)


# ---------------------------------------------------------------------------
# predicates

def _cmp(op):
    def run(e, ctx):
        l = eval_expr(e.children[0], ctx)
        r = eval_expr(e.children[1], ctx)
        if l.dtype is dt.STRING or r.dtype is dt.STRING:
            ld = l.data
            rd = r.data
            n = len(ld)
            out = np.zeros(n, dtype=bool)
            for i in range(n):
                a, b = ld[i], rd[i]
                if a is None or b is None:
                    continue
                out[i] = op(a, b)
            data = out
        else:
            ct = dt.common_type(l.dtype, r.dtype)
            with np.errstate(all="ignore"):
                data = op(l.data.astype(ct.np_dtype),
                          r.data.astype(ct.np_dtype))
        return CV(dt.BOOLEAN, np.asarray(data, dtype=bool),
                  and_valid(l.validity, r.validity))
    return run


def _eq_null_safe(e, ctx):
    l = eval_expr(e.children[0], ctx)
    r = eval_expr(e.children[1], ctx)
    lv, rv = l.valid_mask(), r.valid_mask()
    if l.dtype is dt.STRING:
        eq = np.array([a == b for a, b in zip(l.data, r.data)], dtype=bool)
    else:
        ct = dt.common_type(l.dtype, r.dtype)
        with np.errstate(all="ignore"):
            eq = l.data.astype(ct.np_dtype) == r.data.astype(ct.np_dtype)
    data = np.where(lv & rv, eq, ~lv & ~rv)
    return CV(dt.BOOLEAN, data, None)


def _and(e, ctx):
    l = eval_expr(e.children[0], ctx)
    r = eval_expr(e.children[1], ctx)
    lv, rv = l.valid_mask(), r.valid_mask()
    ld = l.data.astype(bool) & lv  # treat null as "not definitely true"
    rd = r.data.astype(bool) & rv
    false_l = lv & ~l.data.astype(bool)
    false_r = rv & ~r.data.astype(bool)
    data = ld & rd
    validity = (lv & rv) | false_l | false_r  # 3VL: false dominates null
    return CV(dt.BOOLEAN, data, validity)


def _or(e, ctx):
    l = eval_expr(e.children[0], ctx)
    r = eval_expr(e.children[1], ctx)
    lv, rv = l.valid_mask(), r.valid_mask()
    true_l = lv & l.data.astype(bool)
    true_r = rv & r.data.astype(bool)
    data = true_l | true_r
    validity = (lv & rv) | true_l | true_r  # 3VL: true dominates null
    return CV(dt.BOOLEAN, data, validity)


def _not(e, ctx):
    v = eval_expr(e.children[0], ctx)
    return CV(dt.BOOLEAN, ~v.data.astype(bool), v.validity)


def _is_null(e, ctx):
    v = eval_expr(e.children[0], ctx)
    return CV(dt.BOOLEAN, ~v.valid_mask(), None)


def _is_not_null(e, ctx):
    v = eval_expr(e.children[0], ctx)
    return CV(dt.BOOLEAN, v.valid_mask().copy(), None)


def _is_nan(e, ctx):
    v = eval_expr(e.children[0], ctx)
    data = np.isnan(v.data.astype(np.float64)) & v.valid_mask()
    return CV(dt.BOOLEAN, data, None)


def _in(e, ctx):
    v = eval_expr(e.children[0], ctx)
    non_null = [x for x in e.values if x is not None]
    has_null_item = any(x is None for x in e.values)
    if v.dtype is dt.STRING:
        data = np.array([x in non_null for x in v.data], dtype=bool)
    else:
        arr = (np.array(non_null, dtype=v.dtype.np_dtype) if non_null
               else np.array([], dtype=v.dtype.np_dtype))
        data = np.isin(v.data, arr)
    validity = v.valid_mask().copy()
    if has_null_item:
        validity &= data  # non-match with null in list -> unknown (3VL)
    return CV(dt.BOOLEAN, data,
              validity if (has_null_item or v.validity is not None) else None)


def _at_least_n(e, ctx):
    vs = [eval_expr(c, ctx) for c in e.children]
    cnt = np.zeros(ctx.num_rows, dtype=np.int64)
    for v in vs:
        ok = v.valid_mask().copy()
        if v.dtype.is_floating:
            ok &= ~np.isnan(v.data)
        cnt += ok
    return CV(dt.BOOLEAN, cnt >= e.n, None)


# ---------------------------------------------------------------------------
# conditional

def _if(e, ctx):
    p = eval_expr(e.children[0], ctx)
    t = eval_expr(e.children[1], ctx)
    o = eval_expr(e.children[2], ctx)
    take_then = p.data.astype(bool) & p.valid_mask()
    return _select(take_then, t, o, e.dtype)


def _select(mask: np.ndarray, a: CV, b: CV, odt: dt.DType) -> CV:
    if odt is dt.STRING:
        data = np.where(mask, a.data, b.data)
    else:
        data = np.where(mask, a.data.astype(odt.np_dtype),
                        b.data.astype(odt.np_dtype))
    validity = np.where(mask, a.valid_mask(), b.valid_mask())
    return CV(odt, data, validity)


def _case_when(e, ctx):
    odt = e.dtype
    if e.has_else:
        out = eval_expr(e.children[-1], ctx)
    else:
        out = cv_null(odt, ctx.num_rows)
    # fold right-to-left so earlier branches win (mirrors device eval)
    for i in reversed(range(e.n_branches)):
        p = eval_expr(e.children[2 * i], ctx)
        v = eval_expr(e.children[2 * i + 1], ctx)
        take = p.data.astype(bool) & p.valid_mask()
        out = _select(take, v, out, odt)
    return out


def _coalesce(e, ctx):
    out = eval_expr(e.children[0], ctx)
    odt = e.dtype
    for c in e.children[1:]:
        nxt = eval_expr(c, ctx)
        out = _select(out.valid_mask(), out, nxt, odt)
    return out


def _greatest_least(e, ctx, op):
    out = eval_expr(e.children[0], ctx)
    data, valid = out.data, out.valid_mask()
    for c in e.children[1:]:
        v = eval_expr(c, ctx)
        vv = v.valid_mask()
        with np.errstate(all="ignore"):
            combined = op(data, v.data)
        data = np.where(valid & vv, combined,
                        np.where(valid, data, v.data))
        valid = valid | vv
    return CV(e.dtype, data, valid)


def _nanvl(e, ctx):
    l = eval_expr(e.children[0], ctx)
    r = eval_expr(e.children[1], ctx)
    ld = l.data.astype(np.float64)
    # a unless a is a valid NaN; NULL left stays NULL (device NaNvl)
    take_l = ~np.isnan(ld) | ~l.valid_mask()
    return _select(take_l, l, r, e.dtype)


# ---------------------------------------------------------------------------
# math

_MATH_FNS = {
    mth.Sqrt: np.sqrt, mth.Cbrt: np.cbrt, mth.Exp: np.exp,
    mth.Expm1: np.expm1, mth.Log: np.log, mth.Log1p: np.log1p,
    mth.Log2: np.log2, mth.Log10: np.log10, mth.Sin: np.sin,
    mth.Cos: np.cos, mth.Tan: np.tan, mth.Asin: np.arcsin,
    mth.Acos: np.arccos, mth.Atan: np.arctan, mth.Sinh: np.sinh,
    mth.Cosh: np.cosh, mth.Tanh: np.tanh, mth.ToDegrees: np.degrees,
    mth.ToRadians: np.radians, mth.Rint: np.rint,
    mth.Asinh: np.arcsinh, mth.Acosh: np.arccosh, mth.Atanh: np.arctanh,
    mth.Cot: lambda x: 1.0 / np.tan(x),
}


def _logarithm(e, ctx):
    def fn(b, x):
        with np.errstate(all="ignore"):
            return (np.log(x.astype(np.float64)) /
                    np.log(b.astype(np.float64)))
    return _binary_num(e, ctx, fn, dt.FLOAT64)


def _java_regex_replacement(m, repl: str) -> str:
    """Expand a replacement string with JAVA Matcher.replaceAll semantics
    ($N = group reference taking the LONGEST valid group number,
    backslash escapes the next char, trailing lone backslash throws) —
    Python's re.sub uses \\N instead and would raise on Java escapes."""
    out = []
    i = 0
    n_groups = m.re.groups
    while i < len(repl):
        ch = repl[i]
        if ch == "\\":
            if i + 1 >= len(repl):
                raise ValueError(
                    "regexp_replace: trailing backslash in replacement")
            out.append(repl[i + 1])
            i += 2
        elif ch == "$" and i + 1 < len(repl) and repl[i + 1].isdigit():
            # greedy: extend the group number while it stays valid
            g = int(repl[i + 1])
            j = i + 2
            while j < len(repl) and repl[j].isdigit() and \
                    g * 10 + int(repl[j]) <= n_groups:
                g = g * 10 + int(repl[j])
                j += 1
            out.append(m.group(g) or "")
            i = j
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def _normalize_nan_zero(e, ctx):
    v = eval_expr(e.children[0], ctx)
    x = v.data + np.zeros((), dtype=v.data.dtype)  # -0.0 -> +0.0
    x = np.where(np.isnan(x), np.asarray(np.nan, dtype=x.dtype), x)
    return CV(e.dtype, x, v.validity)


def _unary_math(e, ctx):
    v = eval_expr(e.children[0], ctx)
    with np.errstate(all="ignore"):
        data = _MATH_FNS[type(e)](v.data.astype(np.float64))
    return CV(dt.FLOAT64, data, v.validity)


def _java_double_to_long(x: np.ndarray) -> np.ndarray:
    """Java (long) cast: NaN -> 0, saturate at Long.MIN/MAX."""
    hi = x >= 9.223372036854776e18   # 2^63
    lo = x <= -9.223372036854776e18
    nan = np.isnan(x)
    safe = np.where(hi | lo | nan, 0.0, x)
    with np.errstate(all="ignore"):
        out = safe.astype(np.int64)
    out = np.where(hi, np.iinfo(np.int64).max, out)
    out = np.where(lo, np.iinfo(np.int64).min, out)
    return np.where(nan, 0, out)


def _floor(e, ctx):
    v = eval_expr(e.children[0], ctx)
    data = _java_double_to_long(np.floor(v.data.astype(np.float64)))
    return CV(dt.INT64, data, v.validity)


def _ceil(e, ctx):
    v = eval_expr(e.children[0], ctx)
    data = _java_double_to_long(np.ceil(v.data.astype(np.float64)))
    return CV(dt.INT64, data, v.validity)


def _round(e, ctx):
    """Spark HALF_UP rounding (away from zero on .5)."""
    v = eval_expr(e.children[0], ctx)
    s = e.scale
    in_t = e.children[0].dtype
    if in_t.is_integral and s >= 0:
        return CV(e.dtype, v.data, v.validity)
    p = 10.0 ** s
    scaled = v.data.astype(np.float64) * p
    with np.errstate(all="ignore"):
        r = np.where(scaled >= 0, np.floor(scaled + 0.5),
                     np.ceil(scaled - 0.5)) / p
    if in_t.is_integral:
        r = _java_double_to_long(r).astype(in_t.np_dtype)
    return CV(e.dtype, r, v.validity)


def _pow(e, ctx):
    return _binary_num(e, ctx, np.power, dt.FLOAT64)


def _atan2(e, ctx):
    return _binary_num(e, ctx, np.arctan2, dt.FLOAT64)


# ---------------------------------------------------------------------------
# cast (reuses the scalar parse/format helpers from the device layer — they
# are host-side python already; the device layer's *vector* paths are jax)

def _cast(e: Cast, ctx):
    src = e.children[0].dtype
    v = eval_expr(e.children[0], ctx)
    to = e.to
    n = ctx.num_rows
    if src is to:
        return v
    valid = v.valid_mask()
    if src is dt.STRING:
        data = np.zeros(n, dtype=to.np_dtype) if to is not dt.STRING else \
            np.full(n, None, dtype=object)
        ok = np.zeros(n, dtype=bool)
        for i in range(n):
            if not valid[i] or v.data[i] is None:
                continue
            val, good = _parse_one(str(v.data[i]), to)
            if good:
                try:
                    data[i] = val
                    ok[i] = True
                except (OverflowError, ValueError):
                    pass
        return CV(to, data, ok)
    if to is dt.STRING:
        data = np.full(n, None, dtype=object)
        for i in range(n):
            if valid[i]:
                data[i] = _format_one(v.data[i], src)
        return CV(to, data, v.validity)
    if src is dt.BOOLEAN:
        return CV(to, v.data.astype(to.np_dtype), v.validity)
    if to is dt.BOOLEAN:
        return CV(to, v.data != 0, v.validity)
    if src is dt.DATE and to is dt.TIMESTAMP:
        return CV(to, v.data.astype(np.int64) * 86_400_000_000, v.validity)
    if src is dt.TIMESTAMP and to is dt.DATE:
        return CV(to, np.floor_divide(v.data, 86_400_000_000)
                  .astype(np.int32), v.validity)
    if src.is_floating and (to.is_integral or to in (dt.DATE, dt.TIMESTAMP)):
        info = np.iinfo(to.np_dtype)
        x = np.trunc(np.nan_to_num(v.data.astype(np.float64), nan=0.0))
        big = x >= float(info.max)
        small = x <= float(info.min)
        out = np.where(big, info.max,
                       np.where(small, info.min,
                                np.where(big | small, 0, x)
                                .astype(to.np_dtype)))
        return CV(to, out.astype(to.np_dtype), v.validity)
    with np.errstate(all="ignore"):
        return CV(to, v.data.astype(to.np_dtype), v.validity)


# ---------------------------------------------------------------------------
# datetime (dates = int32 days, timestamps = int64 micros UTC)

_EPOCH = datetime.date(1970, 1, 1)


def _days_to_np(days: np.ndarray) -> np.ndarray:
    return days.astype("datetime64[D]")


def _date_field(field):
    def run(e, ctx):
        v = eval_expr(e.children[0], ctx)
        d = _days_to_np(v.data)
        y = d.astype("datetime64[Y]").astype(np.int64) + 1970
        m = (d.astype("datetime64[M]").astype(np.int64) % 12) + 1
        day = (d - d.astype("datetime64[M]")).astype(np.int64) + 1
        vals = {"year": y, "month": m, "day": day}
        return CV(dt.INT32, vals[field].astype(np.int32), v.validity)
    return run


def _day_of_week(e, ctx):
    v = eval_expr(e.children[0], ctx)
    # Spark: 1 = Sunday ... 7 = Saturday; epoch (1970-01-01) was a Thursday
    dow = ((v.data.astype(np.int64) + 4) % 7 + 7) % 7 + 1
    return CV(dt.INT32, dow.astype(np.int32), v.validity)


def _week_day(e, ctx):
    v = eval_expr(e.children[0], ctx)
    # Spark WeekDay: 0 = Monday ... 6 = Sunday
    wd = ((v.data.astype(np.int64) + 3) % 7 + 7) % 7
    return CV(dt.INT32, wd.astype(np.int32), v.validity)


def _time_add(e, ctx):
    def fn(a, b):
        return a.astype(np.int64) + b.astype(np.int64)
    return _binary_num(e, ctx, fn, dt.TIMESTAMP)


def _day_of_year(e, ctx):
    v = eval_expr(e.children[0], ctx)
    d = _days_to_np(v.data)
    doy = (d - d.astype("datetime64[Y]")).astype(np.int64) + 1
    return CV(dt.INT32, doy.astype(np.int32), v.validity)


def _quarter(e, ctx):
    v = eval_expr(e.children[0], ctx)
    d = _days_to_np(v.data)
    m = (d.astype("datetime64[M]").astype(np.int64) % 12)
    return CV(dt.INT32, (m // 3 + 1).astype(np.int32), v.validity)


def _time_field(field):
    def run(e, ctx):
        v = eval_expr(e.children[0], ctx)
        us = v.data.astype(np.int64)
        sec = np.floor_divide(us, 1_000_000)
        vals = {
            "hour": np.floor_divide(sec, 3600) % 24,
            "minute": np.floor_divide(sec, 60) % 60,
            "second": sec % 60,
        }
        return CV(dt.INT32, vals[field].astype(np.int32), v.validity)
    return run


def _date_add(e, ctx):
    s = eval_expr(e.children[0], ctx)
    d = eval_expr(e.children[1], ctx)
    data = (s.data.astype(np.int64) + d.data.astype(np.int64))
    return CV(dt.DATE, data.astype(np.int32),
              and_valid(s.validity, d.validity))


def _date_sub(e, ctx):
    s = eval_expr(e.children[0], ctx)
    d = eval_expr(e.children[1], ctx)
    data = (s.data.astype(np.int64) - d.data.astype(np.int64))
    return CV(dt.DATE, data.astype(np.int32),
              and_valid(s.validity, d.validity))


def _date_diff(e, ctx):
    end = eval_expr(e.children[0], ctx)
    start = eval_expr(e.children[1], ctx)
    data = end.data.astype(np.int64) - start.data.astype(np.int64)
    return CV(dt.INT32, data.astype(np.int32),
              and_valid(end.validity, start.validity))


def _unix_timestamp(e, ctx):
    v = eval_expr(e.children[0], ctx)
    if v.dtype is dt.TIMESTAMP:
        data = np.floor_divide(v.data, 1_000_000)
    elif v.dtype is dt.DATE:
        data = v.data.astype(np.int64) * 86400
    else:
        raise NotImplementedError("unix_timestamp on strings: cast first")
    return CV(dt.INT64, data.astype(np.int64), v.validity)


def _from_unixtime(e, ctx):
    v = eval_expr(e.children[0], ctx)
    n = ctx.num_rows
    valid = v.valid_mask()
    data = np.full(n, None, dtype=object)
    for i in range(n):
        if valid[i]:
            x = datetime.datetime.fromtimestamp(
                int(v.data[i]), tz=datetime.timezone.utc)
            data[i] = x.strftime("%Y-%m-%d %H:%M:%S")
    return CV(dt.STRING, data, v.validity)


def _last_day(e, ctx):
    v = eval_expr(e.children[0], ctx)
    d = _days_to_np(v.data)
    nxt = d.astype("datetime64[M]") + np.timedelta64(1, "M")
    last = nxt.astype("datetime64[D]") - np.timedelta64(1, "D")
    return CV(dt.DATE, last.astype(np.int64).astype(np.int32), v.validity)


# ---------------------------------------------------------------------------
# strings (object-array python loops: oracle clarity over speed)

def _str_unary(fn):
    def run(e, ctx):
        v = eval_expr(e.children[0], ctx)
        valid = v.valid_mask()
        data = np.full(ctx.num_rows, None, dtype=object)
        for i in range(ctx.num_rows):
            if valid[i] and v.data[i] is not None:
                data[i] = fn(e, v.data[i])
        return CV(dt.STRING, data, v.validity)
    return run


def _length(e, ctx):
    v = eval_expr(e.children[0], ctx)
    valid = v.valid_mask()
    data = np.zeros(ctx.num_rows, dtype=np.int32)
    for i in range(ctx.num_rows):
        if valid[i] and v.data[i] is not None:
            data[i] = len(v.data[i])
    return CV(dt.INT32, data, v.validity)


def _substring(e, ctx):
    v = eval_expr(e.children[0], ctx)
    valid = v.valid_mask()
    data = np.full(ctx.num_rows, None, dtype=object)
    pos, ln = e.pos, e.length
    for i in range(ctx.num_rows):
        if not (valid[i] and v.data[i] is not None):
            continue
        s = v.data[i]
        # Spark substring: 1-based; 0 behaves like 1; negative from end
        if pos > 0:
            start = pos - 1
        elif pos == 0:
            start = 0
        else:
            start = max(len(s) + pos, 0)
        end = len(s) if ln is None else start + max(ln, 0)
        data[i] = s[start:end]
    return CV(dt.STRING, data, v.validity)


def _str_predicate(fn):
    def run(e, ctx):
        v = eval_expr(e.children[0], ctx)
        valid = v.valid_mask()
        data = np.zeros(ctx.num_rows, dtype=bool)
        for i in range(ctx.num_rows):
            if valid[i] and v.data[i] is not None:
                data[i] = fn(e, v.data[i])
        return CV(dt.BOOLEAN, data, v.validity)
    return run


def _like_to_regex(pattern: str, escape: str) -> str:
    import re

    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return "^" + "".join(out) + "$"


def _like(e, ctx):
    import re

    rx = re.compile(_like_to_regex(e.pattern, e.escape), flags=re.DOTALL)
    return _str_predicate(lambda _, s: rx.match(s) is not None)(e, ctx)


def _locate(e, ctx):
    v = eval_expr(e.children[0], ctx)
    valid = v.valid_mask()
    data = np.zeros(ctx.num_rows, dtype=np.int32)
    for i in range(ctx.num_rows):
        if valid[i] and v.data[i] is not None:
            if e.start < 1:
                data[i] = 0
            else:
                data[i] = v.data[i].find(e.needle, e.start - 1) + 1
    return CV(dt.INT32, data, v.validity)


def _concat(e, ctx):
    vs = [eval_expr(c, ctx) for c in e.children]
    validity = and_valid(*[v.validity for v in vs])
    data = np.full(ctx.num_rows, None, dtype=object)
    ok = np.ones(ctx.num_rows, dtype=bool) if validity is None else validity
    for i in range(ctx.num_rows):
        if ok[i]:
            data[i] = "".join(str(v.data[i]) for v in vs)
    return CV(dt.STRING, data, validity)


# ---------------------------------------------------------------------------

_DISPATCH = {
    BoundReference: _bound,
    Literal: _literal,
    Alias: _alias,
    ar.Add: _add,
    ar.Subtract: _sub,
    ar.Multiply: _mul,
    ar.Divide: _divide,
    ar.IntegralDivide: _int_div,
    ar.Remainder: _remainder,
    ar.Pmod: _pmod,
    bw.BitwiseAnd: _bitwise_binary(np.bitwise_and),
    bw.BitwiseOr: _bitwise_binary(np.bitwise_or),
    bw.BitwiseXor: _bitwise_binary(np.bitwise_xor),
    bw.BitwiseNot: _bitwise_not,
    bw.ShiftLeft: _shift(np.left_shift),
    bw.ShiftRight: _shift(np.right_shift),
    bw.ShiftRightUnsigned: _shift(None, unsigned=True),
    # the CPU oracle is one partition: pid 0, absolute positions
    nd.SparkPartitionID: lambda e, ctx: CV(
        dt.INT32, np.zeros(ctx.num_rows, dtype=np.int32)),
    nd.MonotonicallyIncreasingID: lambda e, ctx: CV(
        dt.INT64, np.arange(ctx.num_rows, dtype=np.int64)),
    nd.Rand: lambda e, ctx: CV(
        dt.FLOAT64, nd.rand_reference(e.seed, 0,
                                      np.arange(ctx.num_rows))),
    ar.UnaryMinus: _unary_minus,
    ar.UnaryPositive: _unary_pos,
    ar.Abs: _abs,
    ar.Signum: _signum,
    pr.EqualTo: _cmp(lambda a, b: a == b),
    pr.LessThan: _cmp(lambda a, b: a < b),
    pr.LessThanOrEqual: _cmp(lambda a, b: a <= b),
    pr.GreaterThan: _cmp(lambda a, b: a > b),
    pr.GreaterThanOrEqual: _cmp(lambda a, b: a >= b),
    pr.EqualNullSafe: _eq_null_safe,
    pr.And: _and,
    pr.Or: _or,
    pr.Not: _not,
    pr.IsNull: _is_null,
    pr.IsNotNull: _is_not_null,
    pr.IsNaN: _is_nan,
    pr.In: _in,
    pr.AtLeastNNonNulls: _at_least_n,
    cond.If: _if,
    cond.CaseWhen: _case_when,
    cond.Coalesce: _coalesce,
    cond.Greatest: lambda e, ctx: _greatest_least(e, ctx, np.maximum),
    cond.Least: lambda e, ctx: _greatest_least(e, ctx, np.fmin),
    cond.Nvl: _coalesce,
    cond.NaNvl: _nanvl,
    Cast: _cast,
    mth.Floor: _floor,
    mth.Round: _round,
    mth.Ceil: _ceil,
    mth.Pow: _pow,
    mth.Atan2: _atan2,
    mth.Logarithm: _logarithm,
    cns.NormalizeNaNAndZero: _normalize_nan_zero,
    cns.KnownFloatingPointNormalized:
        lambda e, ctx: eval_expr(e.children[0], ctx),
    dte.Year: _date_field("year"),
    dte.Month: _date_field("month"),
    dte.DayOfMonth: _date_field("day"),
    dte.DayOfWeek: _day_of_week,
    dte.WeekDay: _week_day,
    dte.TimeAdd: _time_add,
    dte.ToUnixTimestamp: _unix_timestamp,
    dte.DayOfYear: _day_of_year,
    dte.Quarter: _quarter,
    dte.Hour: _time_field("hour"),
    dte.Minute: _time_field("minute"),
    dte.Second: _time_field("second"),
    dte.DateAdd: _date_add,
    dte.DateSub: _date_sub,
    dte.DateDiff: _date_diff,
    dte.UnixTimestamp: _unix_timestamp,
    dte.FromUnixTime: _from_unixtime,
    dte.LastDay: _last_day,
    st.Upper: _str_unary(lambda e, s: s.upper()),
    st.Lower: _str_unary(lambda e, s: s.lower()),
    st.Length: _length,
    st.StringTrim: _str_unary(lambda e, s: s.strip()),
    st.StringTrimLeft: _str_unary(lambda e, s: s.lstrip()),
    st.StringTrimRight: _str_unary(lambda e, s: s.rstrip()),
    st.InitCap: _str_unary(
        lambda e, s: " ".join(w[:1].upper() + w[1:].lower()
                              for w in s.split(" "))),
    st.Reverse: _str_unary(lambda e, s: s[::-1]),
    st.Substring: _substring,
    st.StringReplace: _str_unary(
        lambda e, s: s.replace(e.search, e.replace)),
    st.SubstringIndex: _str_unary(lambda e, s: e.fn(s)),
    # the oracle runs the FULL regex (vanilla-Spark semantics); the TPU
    # path only accepts regex-free patterns, where the two coincide
    st.RegExpReplace: _str_unary(
        lambda e, s: _re.sub(
            e.pattern,
            lambda m: _java_regex_replacement(m, e.replacement), s)),
    st.StringRepeat: _str_unary(lambda e, s: s * max(e.times, 0)),
    st.StringLPad: _str_unary(
        lambda e, s: (e.pad * e.width + s)[-e.width:]
        if len(s) < e.width else s[:e.width]),
    st.StringRPad: _str_unary(
        lambda e, s: (s + e.pad * e.width)[:e.width]
        if len(s) < e.width else s[:e.width]),
    st.StartsWith: _str_predicate(lambda e, s: s.startswith(e.needle)),
    st.EndsWith: _str_predicate(lambda e, s: s.endswith(e.needle)),
    st.Contains: _str_predicate(lambda e, s: e.needle in s),
    st.Like: _like,
    st.StringLocate: _locate,
    st.ConcatStrings: _concat,
}

for k in _MATH_FNS:
    _DISPATCH[k] = _unary_math
