"""CPU plan interpreter: executes plan nodes over numpy/pandas frames.

Independent of the TPU exec layer (no jax): this is the "vanilla Spark" of
the framework — the engine the planner falls back to per-node and the oracle
the comparison harness checks TPU results against (SURVEY.md §4).
Materializes whole frames per node; batch streaming is a device-side concern.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import threading

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.cpu.evaluator import (CV, CpuEvalContext, cv_null,
                                            eval_expr)
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.plan import nodes as pn


class CpuFrame:
    """Schema + full-length CV columns."""

    def __init__(self, schema: Schema, cols: List[CV], num_rows: int):
        self.schema = schema
        self.cols = cols
        self.num_rows = num_rows
        #: [(origin, row_count)] runs straight above a file scan
        #: (input_file_name oracle support); transforms drop it
        self.origins = None

    def take(self, idx: np.ndarray,
             null_mask: Optional[np.ndarray] = None) -> "CpuFrame":
        """Gather rows; where null_mask is set the output row is all-null
        (outer-join padding)."""
        out = []
        safe = np.clip(idx, 0, max(self.num_rows - 1, 0))
        for c in self.cols:
            if self.num_rows == 0:
                out.append(cv_null(c.dtype, len(idx)))
                continue
            data = c.data[safe]
            valid = c.valid_mask()[safe]
            if null_mask is not None:
                valid = valid & ~null_mask
            out.append(CV(c.dtype, data, valid))
        return CpuFrame(self.schema, out, len(idx))

    def to_pandas(self):
        import pandas as pd

        data = {}
        for name, c in zip(self.schema.names, self.cols):
            valid = c.valid_mask()
            if c.dtype is dt.STRING:
                vals = [c.data[i] if valid[i] else None
                        for i in range(self.num_rows)]
                # explicit object Series: pandas 3's frame constructor
                # infers a string dtype from bare object arrays and
                # coerces None->NaN, turning SQL NULL strings into
                # float NaN (visible in ROLLUP null group keys)
                data[name] = pd.Series(vals, dtype=object)
            elif c.dtype is dt.BOOLEAN:
                data[name] = pd.array(
                    [bool(c.data[i]) if valid[i] else None
                     for i in range(self.num_rows)], dtype="boolean")
            elif c.dtype.is_integral or c.dtype in (dt.DATE, dt.TIMESTAMP):
                data[name] = pd.array(
                    [int(c.data[i]) if valid[i] else None
                     for i in range(self.num_rows)], dtype="Int64")
            else:
                # object dtype so SQL NULL (None) stays distinct from NaN
                vals = c.data.astype(np.float64).astype(object)
                vals[~valid] = None
                data[name] = pd.Series(vals, dtype=object)
        return pd.DataFrame(data)


_ORIGINS_STATE = threading.local()


def _plan_needs_origins(plan: pn.PlanNode) -> bool:
    """True when any expression in the tree is an input_file_* leaf —
    only then does the oracle scan need per-split origin tracking."""
    from spark_rapids_tpu.expressions.base import Expression
    from spark_rapids_tpu.expressions.nondeterministic import \
        _InputFileExpr

    def expr_has(e) -> bool:
        return bool(e.collect(lambda x: isinstance(x, _InputFileExpr)))

    for node in pn.walk(plan):
        for v in vars(node).values():
            if isinstance(v, Expression) and expr_has(v):
                return True
            if isinstance(v, (list, tuple)):
                for x in v:
                    if isinstance(x, Expression) and expr_has(x):
                        return True
                    if isinstance(x, (list, tuple)) and any(
                            isinstance(y, Expression) and expr_has(y)
                            for y in x):
                        return True
    return False


def execute_cpu(plan: pn.PlanNode) -> CpuFrame:
    root = not getattr(_ORIGINS_STATE, "active", False)
    if root:
        _ORIGINS_STATE.active = True
        _ORIGINS_STATE.needed = _plan_needs_origins(plan)
        # same gating the TPU planner applies: file identity exprs
        # forbid multi-file split packing
        pn.gate_split_packing(plan)
    try:
        fn = _NODES.get(type(plan))
        if fn is None:
            raise NotImplementedError(
                f"CPU engine: unsupported node {plan.name}")
        return fn(plan)
    finally:
        if root:
            _ORIGINS_STATE.active = False


# ---------------------------------------------------------------------------
# leaves


def _host_to_frame(schema: Schema, data, validity) -> CpuFrame:
    from spark_rapids_tpu.io.hoststrings import HostStrings

    cols = []
    n = None
    for name, typ in zip(schema.names, schema.types):
        raw = data[name]
        if isinstance(raw, HostStrings):
            # decode through the dictionary (vectorized take); nulls
            # are exactly the validity dict's falses (plus the empty-
            # dictionary all-null case) — no row-wise rescan needed
            v = validity.get(name)
            if v is not None:
                v = np.asarray(v, dtype=bool)
            if len(raw.dictionary) == 0 and len(raw):
                v = np.zeros(len(raw), dtype=bool)
            arr = raw.to_objects(v)
            cols.append(CV(typ, arr, v))
            n = len(arr)
            continue
        arr = np.asarray(raw)
        if typ is dt.STRING:
            arr = arr.astype(object)
            auto_null = np.array([x is not None for x in arr], dtype=bool)
        else:
            if arr.dtype.kind == "M":
                unit = np.datetime_data(arr.dtype)[0]
                arr = (arr.astype("datetime64[D]").astype(np.int32)
                       if typ is dt.DATE else
                       arr.astype("datetime64[us]").astype(np.int64))
            arr = arr.astype(typ.np_dtype)
            auto_null = None
        v = validity.get(name)
        if v is not None:
            v = np.asarray(v, dtype=bool)
        if auto_null is not None and not auto_null.all():
            v = auto_null if v is None else (v & auto_null)
        cols.append(CV(typ, arr, v))
        n = len(arr)
    return CpuFrame(schema, cols, n or 0)


def _concat_frames(schema: Schema, frames: List[CpuFrame]) -> CpuFrame:
    cols = []
    total = sum(f.num_rows for f in frames)
    for j, typ in enumerate(schema.types):
        np_t = object if typ is dt.STRING else typ.np_dtype
        data = np.concatenate([f.cols[j].data.astype(np_t)
                               for f in frames]) if total else \
            np.array([], dtype=np_t)
        valid = np.concatenate([f.cols[j].valid_mask() for f in frames]) \
            if total else np.array([], dtype=bool)
        cols.append(CV(typ, data, valid))
    return CpuFrame(schema, cols, total)


def _scan(node: pn.ScanNode) -> CpuFrame:
    schema = node.output_schema()
    src = node.source
    if not getattr(_ORIGINS_STATE, "needed", False) or \
            (src.split_origin(0) is None and src.num_splits() == 1):
        # common path: the multi-file thread-pool read
        data, validity = src.read_host()
        return _host_to_frame(schema, data, validity)
    # input_file_name in the plan: read split-by-split so per-row
    # origins exist (the oracle mirror of the device path's batch.origin)
    frames, origin_runs = [], []
    for s in range(src.num_splits()):
        data, validity = src.read_host_split(s)
        f = _host_to_frame(schema, data, validity)
        frames.append(f)
        origin_runs.append((src.split_origin(s), f.num_rows))
    out = _concat_frames(schema, frames)
    out.origins = origin_runs  # [(origin, row_count)] run-length
    return out


def _range(node: pn.RangeNode) -> CpuFrame:
    data = np.arange(node.start, node.end, node.step, dtype=np.int64)
    return CpuFrame(node.output_schema(),
                    [CV(dt.INT64, data, None)], len(data))


# ---------------------------------------------------------------------------
# row ops


def _project(node: pn.ProjectNode) -> CpuFrame:
    child = execute_cpu(node.children[0])
    ctx = CpuEvalContext(child.cols, child.num_rows,
                         origins=child.origins)
    cols = [eval_expr(e, ctx) for e in node.exprs]
    return CpuFrame(node.output_schema(), cols, child.num_rows)


def _filter(node: pn.FilterNode) -> CpuFrame:
    child = execute_cpu(node.children[0])
    ctx = CpuEvalContext(child.cols, child.num_rows,
                         origins=child.origins)
    cond = eval_expr(node.condition, ctx)
    keep = cond.data.astype(bool) & cond.valid_mask()
    idx = np.nonzero(keep)[0]
    out = child.take(idx)
    if child.origins is not None:
        # compact the origin runs through the same selection (a filter
        # keeps file provenance, matching the device path): map kept row
        # indices to run ids vectorized, then re-run-length encode
        bounds = np.cumsum([c for _, c in child.origins])
        run_of = np.searchsorted(bounds, idx, side="right")
        if len(run_of) == 0:
            out.origins = []
        else:
            # vectorized run-length re-encode of the kept rows' run ids
            starts = np.r_[0, np.flatnonzero(np.diff(run_of)) + 1]
            counts = np.diff(np.r_[starts, len(run_of)])
            out.origins = [(child.origins[int(run_of[s])][0], int(c))
                           for s, c in zip(starts, counts)]
    return out


def _limit(node: pn.LimitNode) -> CpuFrame:
    child = execute_cpu(node.children[0])
    n = min(node.n, child.num_rows)
    return child.take(np.arange(n))


def _union(node: pn.UnionNode) -> CpuFrame:
    frames = [execute_cpu(c) for c in node.children]
    schema = node.output_schema()
    cols = []
    total = sum(f.num_rows for f in frames)
    for j, typ in enumerate(schema.types):
        if typ is dt.STRING:
            data = np.concatenate([f.cols[j].data.astype(object)
                                   for f in frames]) if total else \
                np.array([], dtype=object)
        else:
            data = np.concatenate([f.cols[j].data.astype(typ.np_dtype)
                                   for f in frames]) if total else \
                np.array([], dtype=typ.np_dtype)
        valid = np.concatenate([f.cols[j].valid_mask() for f in frames]) \
            if total else np.array([], dtype=bool)
        cols.append(CV(typ, data, valid))
    return CpuFrame(schema, cols, total)


def _expand(node: pn.ExpandNode) -> CpuFrame:
    child = execute_cpu(node.children[0])
    ctx = CpuEvalContext(child.cols, child.num_rows,
                         origins=child.origins)
    per_proj = [[eval_expr(e, ctx) for e in p] for p in node.projections]
    schema = node.output_schema()
    nproj = len(per_proj)
    n = child.num_rows
    cols = []
    for j, typ in enumerate(schema.types):
        parts_d = [pp[j].data for pp in per_proj]
        parts_v = [pp[j].valid_mask() for pp in per_proj]
        if typ is dt.STRING:
            data = np.empty(n * nproj, dtype=object)
        else:
            data = np.empty(n * nproj, dtype=typ.np_dtype)
        valid = np.empty(n * nproj, dtype=bool)
        for k in range(nproj):
            data[k::nproj] = parts_d[k]
            valid[k::nproj] = parts_v[k]
        cols.append(CV(typ, data, valid))
    return CpuFrame(schema, cols, n * nproj)


def _generate(node: pn.GenerateNode) -> CpuFrame:
    """explode/posexplode of created-array slots: desugars to the same
    row-major interleave _expand performs, one projection per slot."""
    expand = pn.ExpandNode(node.expand_projections(), node.children[0],
                           list(node.output_schema().names))
    return _expand(expand)


# ---------------------------------------------------------------------------
# grouping machinery


def _group_key(c: CV, i: int):
    """Hashable per-row key with Spark grouping semantics: nulls group
    together, NaN==NaN, -0.0==0.0."""
    if not c.valid_mask()[i]:
        return None
    v = c.data[i]
    if c.dtype is dt.STRING:
        return v
    if c.dtype.is_floating:
        f = float(v)
        if f != f:
            return "__nan__"
        return f + 0.0  # -0.0 -> 0.0
    if c.dtype is dt.BOOLEAN:
        return bool(v)
    return int(v)


def _group_ids(cols: List[CV], n: int) -> Tuple[np.ndarray, int, np.ndarray]:
    """Returns (gid per row, n_groups, representative row per group)."""
    seen: Dict[tuple, int] = {}
    gid = np.empty(n, dtype=np.int64)
    reps: List[int] = []
    for i in range(n):
        key = tuple(_group_key(c, i) for c in cols)
        g = seen.get(key)
        if g is None:
            g = len(seen)
            seen[key] = g
            reps.append(i)
        gid[i] = g
    return gid, len(seen), np.array(reps, dtype=np.int64)


def _distinct_row_mask(cv: CV, gid: np.ndarray, n: int) -> np.ndarray:
    """Boolean mask keeping the first row of each (group, value) pair,
    with Spark value semantics (null==null, NaN==NaN, -0.0==0.0)."""
    seen = set()
    mask = np.zeros(n, dtype=bool)
    for i in range(n):
        key = (int(gid[i]), _group_key(cv, i))
        if key not in seen:
            seen.add(key)
            mask[i] = True
    return mask


def _agg_op(op: str, cv: Optional[CV], gid: np.ndarray, ng: int,
            n: int) -> CV:
    """One kernel-level aggregate op over groups (ops/groupby.AGG_OPS)."""
    if op == "count_star":
        data = np.bincount(gid, minlength=ng).astype(np.int64)
        return CV(dt.INT64, data, None)
    valid = cv.valid_mask()
    if op == "count":
        data = np.bincount(gid[valid], minlength=ng).astype(np.int64)
        return CV(dt.INT64, data, None)
    if op in ("sum", "sum_of_squares"):
        odt = dt.INT64 if (cv.dtype.is_integral or cv.dtype is dt.BOOLEAN) \
            else dt.FLOAT64
        acc = np.zeros(ng, dtype=odt.np_dtype)
        vals = cv.data.astype(odt.np_dtype)
        if op == "sum_of_squares":
            vals = vals * vals
        np.add.at(acc, gid[valid], vals[valid])
        has = np.zeros(ng, dtype=bool)
        has[gid[valid]] = True
        return CV(odt, acc, has)
    if op in ("m2", "rterm"):
        s = np.zeros(ng, dtype=np.float64)
        cnt = np.zeros(ng, dtype=np.int64)
        vals = cv.data.astype(np.float64)
        np.add.at(s, gid[valid], vals[valid])
        np.add.at(cnt, gid[valid], 1)
        nf = np.maximum(cnt, 1).astype(np.float64)
        has = cnt > 0
        if op == "rterm":
            return CV(dt.FLOAT64, (s * s) / nf, has)
        mean = s / nf
        m2 = np.zeros(ng, dtype=np.float64)
        dd = vals - mean[gid]
        np.add.at(m2, gid[valid], (dd * dd)[valid])
        return CV(dt.FLOAT64, np.maximum(m2, 0.0), has)
    if op in ("min", "max"):
        return _min_max(op, cv, gid, ng)
    if op in ("first", "last", "any_valid"):
        big = n + 1
        pos = np.full(ng, big if op != "last" else -1, dtype=np.int64)
        rows = np.arange(n)
        src = rows if op != "any_valid" else rows[valid]
        g = gid if op != "any_valid" else gid[valid]
        if op == "last":
            np.maximum.at(pos, g, src)
            chosen = pos
            ok = pos >= 0
        else:
            np.minimum.at(pos, g, src)
            chosen = np.where(pos < big, pos, 0)
            ok = pos < big
        data = cv.data[np.clip(chosen, 0, max(n - 1, 0))] if n else \
            np.zeros(ng, dtype=cv.data.dtype)
        v = valid[np.clip(chosen, 0, max(n - 1, 0))] & ok if n else \
            np.zeros(ng, dtype=bool)
        return CV(cv.dtype, data, v)
    raise NotImplementedError(f"agg op {op}")


def _min_max(op: str, cv: CV, gid: np.ndarray, ng: int) -> CV:
    valid = cv.valid_mask()
    n = len(cv.data)
    if cv.dtype is dt.STRING:
        filler = "" if op == "min" else None
        best: List = [None] * ng
        for i in range(n):
            if not valid[i] or cv.data[i] is None:
                continue
            g = gid[i]
            if best[g] is None or \
                    (cv.data[i] < best[g] if op == "min"
                     else cv.data[i] > best[g]):
                best[g] = cv.data[i]
        data = np.array(best, dtype=object)
        return CV(dt.STRING, data,
                  np.array([b is not None for b in best], dtype=bool))
    # numeric: rank rows by ascending Spark total order (NaN greatest),
    # then min/max over valid rows' ranks per group — no negation, so
    # int64 extremes stay exact.
    vals = cv.data
    isnan = np.isnan(vals.astype(np.float64)) if cv.dtype.is_floating \
        else np.zeros(n, dtype=bool)
    clean = np.where(isnan, 0, vals)
    order = np.lexsort((clean, isnan))
    rank_of = np.empty(n, dtype=np.int64)
    rank_of[order] = np.arange(n)
    if op == "min":
        pos = np.full(ng, n + 1, dtype=np.int64)
        np.minimum.at(pos, gid[valid], rank_of[valid])
        ok = pos < n + 1
    else:
        pos = np.full(ng, -1, dtype=np.int64)
        np.maximum.at(pos, gid[valid], rank_of[valid])
        ok = pos >= 0
    chosen = order[np.clip(np.where(ok, pos, 0), 0, max(n - 1, 0))] if n \
        else np.zeros(ng, dtype=np.int64)
    data = vals[chosen] if n else np.zeros(ng, dtype=vals.dtype)
    return CV(cv.dtype, data, ok)


def _aggregate(node: pn.AggregateNode) -> CpuFrame:
    from spark_rapids_tpu.expressions.base import BoundReference

    child = execute_cpu(node.children[0])
    n = child.num_rows
    ctx = CpuEvalContext(child.cols, n)
    key_cvs = [eval_expr(e, ctx) for e in node.grouping]

    ops_mode = "update" if node.mode in ("complete", "partial") else "merge"

    # input columns per agg: for update mode evaluate fn.input; for merge
    # mode partial columns follow grouping in the child schema.
    partial_cvs: List[CV] = []
    if n == 0 and not node.grouping:
        ng = 1
        gid = np.array([], dtype=np.int64)
        reps = np.array([0], dtype=np.int64)
        empty_global = True
    else:
        gid, ng, reps = _group_ids(key_cvs, n)
        if not node.grouping and ng == 0:
            ng, reps = 1, np.array([0], dtype=np.int64)
            empty_global = True
        else:
            empty_global = False

    pcol = len(node.grouping)  # merge mode: next partial ordinal to consume
    for call in node.aggs:
        fn = call.fn
        if ops_mode == "update":
            inp = eval_expr(fn.input, ctx) if fn.input is not None else None
            ops = fn.update_ops()
            if fn.distinct and inp is not None:
                # DISTINCT: keep one row per (group, value) pair before
                # aggregating (the TPU planner falls back for distinct, so
                # the oracle only sees complete mode here).
                sel = _distinct_row_mask(inp, gid, n)
                gid_d = gid[sel]
                inp_d = CV(inp.dtype, inp.data[sel], inp.valid_mask()[sel])
                for op in ops:
                    partial_cvs.append(
                        _agg_op(op, inp_d, gid_d, ng, int(sel.sum())))
                continue
            for op in ops:
                partial_cvs.append(_agg_op(op, inp, gid, ng, n))
        else:
            ops = fn.merge_ops()
            for op in ops:
                inp = child.cols[pcol]
                pcol += 1
                partial_cvs.append(_agg_op(op, inp, gid, ng, n))

    if empty_global:
        # global aggregate over empty input: one row of defaults
        # (aggregate.scala:488-501)
        out_partials = []
        for call in node.aggs:
            for ptype, pop in zip(call.fn.partial_types(),
                                  call.fn.update_ops()):
                if pop in ("count", "count_star"):
                    out_partials.append(
                        CV(dt.INT64, np.zeros(1, dtype=np.int64), None))
                else:
                    out_partials.append(cv_null(ptype, 1))
        partial_cvs = out_partials

    key_out = []
    for c in key_cvs:
        if n:
            key_out.append(CV(c.dtype, c.data[reps],
                              c.valid_mask()[reps]))
        else:
            key_out.append(cv_null(c.dtype, ng))

    if node.mode == "partial":
        return CpuFrame(node.output_schema(), key_out + partial_cvs, ng)

    # final/complete: evaluate each fn's result expression over partials
    ctx2 = CpuEvalContext(key_out + partial_cvs, ng)
    out_cols = list(key_out)
    base = len(key_out)
    for call in node.aggs:
        nparts = len(call.fn.partial_types())
        refs = [BoundReference(base + j, t)
                for j, t in enumerate(call.fn.partial_types())]
        final_expr = call.fn.evaluate(refs)
        out_cols.append(eval_expr(final_expr, ctx2))
        base += nparts
    return CpuFrame(node.output_schema(), out_cols, ng)


# ---------------------------------------------------------------------------
# sort


def _rank_arrays(c: CV, spec: SortKeySpec, n: int) -> List[np.ndarray]:
    """lexsort key levels for one ORDER BY term, least significant LAST
    (np.lexsort order). Levels: [value, nan_rank, null_rank] reversed."""
    valid = c.valid_mask()
    null_rank = np.where(valid, 1, 0) if spec.nulls_first else \
        np.where(valid, 0, 1)
    if c.dtype is dt.STRING:
        # factorize via sorted uniques -> order-isomorphic codes
        filled = np.array([x if x is not None else "" for x in c.data],
                          dtype=object)
        uniq, codes = np.unique(filled, return_inverse=True)
        vals = codes.astype(np.int64)
        nan_rank = np.zeros(n, dtype=np.int8)
    elif c.dtype.is_floating:
        f = c.data.astype(np.float64)
        isnan = np.isnan(f)
        nan_rank = isnan.astype(np.int8)  # NaN greatest
        vals = np.where(isnan, 0.0, f + 0.0)  # and -0.0 -> +0.0
    else:
        vals = c.data.astype(np.int64)
        nan_rank = np.zeros(n, dtype=np.int8)
    # canonicalize NULL slots: their stored data is garbage and must not
    # order rows within the null group (later sort terms decide)
    vals = np.where(valid, vals, vals.dtype.type(0))
    nan_rank = np.where(valid, nan_rank, np.int8(0))
    if not spec.ascending:
        # ints descend via bitwise NOT (= -x-1): exact and monotone even
        # at INT64_MIN, where plain negation wraps onto itself
        vals = -vals if c.dtype.is_floating else np.invert(vals)
        nan_rank = -nan_rank
    return [vals, nan_rank, null_rank]


def _sort_perm(frame: CpuFrame, specs: List[SortKeySpec]) -> np.ndarray:
    keys: List[np.ndarray] = [np.arange(frame.num_rows)]  # stable tiebreak
    for spec in reversed(specs):
        keys.extend(_rank_arrays(frame.cols[spec.ordinal], spec,
                                 frame.num_rows))
    return np.lexsort(keys)


def _sort(node: pn.SortNode) -> CpuFrame:
    child = execute_cpu(node.children[0])
    return child.take(_sort_perm(child, node.specs))


# ---------------------------------------------------------------------------
# join


def _join(node: pn.JoinNode) -> CpuFrame:
    left = execute_cpu(node.children[0])
    right = execute_cpu(node.children[1])
    nl, nr = left.num_rows, right.num_rows

    if node.kind == "cross":
        li = np.repeat(np.arange(nl), nr)
        ri = np.tile(np.arange(nr), nl)
    else:
        table: Dict[tuple, List[int]] = {}
        rkeys = [right.cols[k] for k in node.right_keys]
        for i in range(nr):
            key = tuple(_group_key(c, i) for c in rkeys)
            if None in key:
                continue  # null keys never match
            table.setdefault(key, []).append(i)
        lkeys = [left.cols[k] for k in node.left_keys]
        lis, ris = [], []
        for i in range(nl):
            key = tuple(_group_key(c, i) for c in lkeys)
            if None in key:
                continue
            for j in table.get(key, ()):
                lis.append(i)
                ris.append(j)
        li = np.array(lis, dtype=np.int64)
        ri = np.array(ris, dtype=np.int64)

    # residual condition filters candidate pairs (GpuHashJoin.scala:285-291)
    if node.condition is not None and len(li):
        lf = left.take(li)
        rf = right.take(ri)
        ctx = CpuEvalContext(lf.cols + rf.cols, len(li))
        c = eval_expr(node.condition, ctx)
        keep = c.data.astype(bool) & c.valid_mask()
        li, ri = li[keep], ri[keep]

    matched_l = np.zeros(nl, dtype=bool)
    matched_r = np.zeros(nr, dtype=bool)
    if len(li):
        matched_l[li] = True
        matched_r[ri] = True

    if node.kind == "left_semi":
        return left.take(np.nonzero(matched_l)[0])
    if node.kind == "left_anti":
        return left.take(np.nonzero(~matched_l)[0])

    pad_l = np.zeros(len(li), dtype=bool)
    if node.kind in ("left", "full"):
        extra = np.nonzero(~matched_l)[0]
        li = np.concatenate([li, extra])
        ri = np.concatenate([ri, np.zeros(len(extra), dtype=np.int64)])
        pad_l = np.concatenate([pad_l, np.ones(len(extra), dtype=bool)])
    pad_r = pad_l  # pad flags for the right side of l-outer rows
    if node.kind in ("right", "full"):
        extra = np.nonzero(~matched_r)[0]
        li = np.concatenate([li, np.zeros(len(extra), dtype=np.int64)])
        ri = np.concatenate([ri, extra])
        pad_left_rows = np.concatenate(
            [np.zeros(len(pad_r), dtype=bool),
             np.ones(len(extra), dtype=bool)])
        pad_r = np.concatenate([pad_r, np.zeros(len(extra), dtype=bool)])
    else:
        pad_left_rows = np.zeros(len(li), dtype=bool)

    lf = left.take(li, null_mask=pad_left_rows)
    rf = right.take(ri, null_mask=pad_r)
    return CpuFrame(node.output_schema(), lf.cols + rf.cols, len(li))


# ---------------------------------------------------------------------------
# window


def _window(node: pn.WindowNode) -> CpuFrame:
    from spark_rapids_tpu.expressions.aggregates import AggregateFunction

    child = execute_cpu(node.children[0])
    n = child.num_rows
    part_cols = [child.cols[i] for i in node.partition_ordinals]
    gid, ng, _ = _group_ids(part_cols, n)
    specs = node.order_specs
    # order rows by (partition, order keys) — stable
    keys: List[np.ndarray] = [np.arange(n)]
    for spec in reversed(specs):
        keys.extend(_rank_arrays(child.cols[spec.ordinal], spec, n))
    keys.append(gid)
    perm = np.lexsort(keys)

    out_cols = list(child.cols)
    schema = node.output_schema()

    # per-partition row lists in sorted order
    rows_by_part: List[List[int]] = [[] for _ in range(ng)]
    for r in perm:
        rows_by_part[gid[r]].append(r)

    # tie detection for rank/dense_rank: order-key equality
    def same_order_keys(a: int, b: int) -> bool:
        for spec in specs:
            c = child.cols[spec.ordinal]
            ka, kb = _group_key(c, a), _group_key(c, b)
            if ka != kb:
                return False
        return True

    for call_idx, call in enumerate(node.calls):
        typ = schema.types[len(child.cols) + call_idx]
        if typ is dt.STRING:
            data = np.full(n, None, dtype=object)
        else:
            data = np.zeros(n, dtype=typ.np_dtype)
        valid = np.ones(n, dtype=bool)

        order_ordinal = specs[0].ordinal if specs else -1
        for rows in rows_by_part:
            if isinstance(call.fn, AggregateFunction):
                _window_agg(call, child, rows, data, valid,
                            order_ordinal)
            elif call.fn == "row_number":
                for k, r in enumerate(rows):
                    data[r] = k + 1
            elif call.fn in ("rank", "dense_rank"):
                rank = 0
                dense = 0
                for k, r in enumerate(rows):
                    if k == 0 or not same_order_keys(rows[k - 1], r):
                        rank = k + 1
                        dense += 1
                    data[r] = rank if call.fn == "rank" else dense
            elif isinstance(call.fn, tuple) and call.fn[0] in ("lead",
                                                               "lag"):
                _window_shift(call, child, rows, data, valid)
            else:
                raise NotImplementedError(f"window fn {call.fn}")
        out_cols.append(CV(typ, data, valid))
    return CpuFrame(schema, out_cols, n)


def _window_agg(call: pn.WindowCall, child: CpuFrame, rows: List[int],
                data: np.ndarray, valid: np.ndarray,
                order_ordinal: int = -1) -> None:
    from spark_rapids_tpu.expressions.base import BoundReference

    fn = call.fn
    ctx = CpuEvalContext(child.cols, child.num_rows)
    inp = eval_expr(fn.input, ctx) if fn.input is not None else None
    lo, hi = call.frame.lower, call.frame.upper
    range_keys = None
    if call.frame.kind == "range":
        assert order_ordinal >= 0, "range frame requires an order spec"
        okey = child.cols[order_ordinal]
        kvalid = okey.valid_mask()
        range_keys = [(okey.data[r], bool(kvalid[r])) for r in rows]
    for k, r in enumerate(rows):
        if range_keys is not None:
            v, is_valid = range_keys[k]
            # UNBOUNDED sides are positional (include nulls / partition
            # end); value-bounded sides compare keys, with null rows
            # matching only other nulls (Spark RangeFrame semantics)
            def in_frame(j):
                kv, jv = range_keys[j]
                if not is_valid:
                    # null current row: a bounded upper clamps to the
                    # null run (nulls sort first, so the unbounded-
                    # preceding prefix up to the run's end IS the run);
                    # an unbounded upper reaches the partition end
                    return hi is None or not jv
                if not jv:  # null row vs valid current: only inside an
                    return lo is None  # unbounded-preceding region
                return (lo is None or kv >= v + lo) and \
                       (hi is None or kv <= v + hi)

            sel = [j for j in range(len(range_keys)) if in_frame(j)]
            frame_rows = np.array([rows[j] for j in sel], dtype=np.int64)
        else:
            s = 0 if lo is None else max(k + lo, 0)
            t = len(rows) if hi is None else min(k + hi + 1, len(rows))
            frame_rows = np.array(rows[s:t], dtype=np.int64)
        sub_gid = np.zeros(len(frame_rows), dtype=np.int64)
        if inp is not None:
            sub = CV(inp.dtype, inp.data[frame_rows],
                     inp.valid_mask()[frame_rows])
        else:
            sub = None
        partials = [_agg_op(op, sub, sub_gid, 1, len(frame_rows))
                    for op in fn.update_ops()]
        refs = [BoundReference(j, t2)
                for j, t2 in enumerate(fn.partial_types())]
        res = eval_expr(fn.evaluate(refs),
                        CpuEvalContext(partials, 1))
        data[r] = res.data[0]
        valid[r] = res.valid_mask()[0]


def _window_shift(call: pn.WindowCall, child: CpuFrame, rows: List[int],
                  data: np.ndarray, valid: np.ndarray) -> None:
    kind, expr = call.fn
    ctx = CpuEvalContext(child.cols, child.num_rows)
    inp = eval_expr(expr, ctx)
    off = call.offset if kind == "lead" else -call.offset
    for k, r in enumerate(rows):
        j = k + off
        if 0 <= j < len(rows):
            src = rows[j]
            data[r] = inp.data[src]
            valid[r] = inp.valid_mask()[src]
        elif call.default is not None:
            data[r] = call.default
        else:
            valid[r] = False


# ---------------------------------------------------------------------------

def _passthrough(node) -> CpuFrame:
    return execute_cpu(node.children[0])


_NODES = {
    pn.ScanNode: _scan,
    pn.RangeNode: _range,
    pn.ProjectNode: _project,
    pn.FilterNode: _filter,
    pn.LimitNode: _limit,
    pn.UnionNode: _union,
    pn.ExpandNode: _expand,
    pn.GenerateNode: _generate,
    pn.AggregateNode: _aggregate,
    pn.SortNode: _sort,
    pn.JoinNode: _join,
    pn.WindowNode: _window,
    pn.ShuffleExchangeNode: _passthrough,
    pn.CoalescePartitionsNode: _passthrough,
    pn.BroadcastExchangeNode: _passthrough,
}


def _write_files(node) -> CpuFrame:
    from spark_rapids_tpu.io.write import execute_write_cpu

    return execute_write_cpu(node)


def _register_io_nodes():
    from spark_rapids_tpu.execs.cache import CacheNode
    from spark_rapids_tpu.execs.python_exec import (
        AggregateInPandasNode, ArrowEvalPythonNode,
        CoGroupedMapInPandasNode, GroupedMapInPandasNode,
        MapInPandasNode, WindowInPandasNode,
        execute_agg_in_pandas_cpu, execute_arrow_eval_python_cpu,
        execute_cogrouped_map_cpu, execute_grouped_map_cpu,
        execute_map_in_pandas_cpu, execute_window_in_pandas_cpu)
    from spark_rapids_tpu.io.write import WriteFilesNode

    _NODES[WriteFilesNode] = _write_files
    _NODES[MapInPandasNode] = execute_map_in_pandas_cpu
    _NODES[GroupedMapInPandasNode] = execute_grouped_map_cpu
    _NODES[CoGroupedMapInPandasNode] = execute_cogrouped_map_cpu
    _NODES[WindowInPandasNode] = execute_window_in_pandas_cpu
    _NODES[ArrowEvalPythonNode] = execute_arrow_eval_python_cpu
    _NODES[AggregateInPandasNode] = execute_agg_in_pandas_cpu
    _NODES[CacheNode] = _passthrough  # the oracle recomputes


_register_io_nodes()
