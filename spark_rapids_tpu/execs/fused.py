"""Cross-exec fusion: one compiled program per pipeline segment.

The reference executes each physical operator as its own cuDF kernel
launch; kernel launches on a local GPU cost microseconds, so per-op
dispatch is free there. Behind a remote TPU attachment every dispatch is
a full round trip (~100 ms measured), so a scan->filter->join->aggregate
chain that is correct op-by-op is dispatch-bound end-to-end (the round-4
telemetry: TPCx-BB q9 = 131 dispatches x RTT IS the wall clock).

This module collapses a *pipeline segment* — a unary chain of

    FilterExec | ProjectExec | BroadcastHashJoinExec(probe side)

— into ONE jitted XLA program per input batch. The design is
count-oblivious: no step materializes a compacted result, so no step
needs the host to size an output buffer mid-chain:

- filters contribute a live-mask (rows stay in place, dead lanes ride
  along) — the same discipline ops/groupby.py uses for fused filters;
- broadcast join probes become a searchsorted against the build side's
  hash-sorted table, valid whenever the build's key hashes are UNIQUE
  (each probe row then has at most one candidate): the probe is a
  gather, matches fold into the live-mask (inner/semi/anti) or into the
  gathered columns' validity (left outer). Dimension tables joined on
  their key — the TPC fact->dim shape — are exactly this case. A build
  with duplicate key hashes falls back to the general expansion kernel
  (ops/join.py) via the preserved unfused subtree;
- a chain ending at a hash aggregate hands the live-mask directly to the
  groupby kernel (FusedAggregateExec), so the segment runs as chain
  program + shared groupby kernel: 2 dispatches per batch total;
- a standalone chain compacts once at the end of the program (stable
  argsort on the live-mask), its row count a lazy device scalar.

Reference parity anchors: the per-batch update pipeline shape of
aggregate.scala:420-478, GpuHashJoin.scala:302-318 (build once, stream
probe), and the 3-7x end-to-end bar of docs/FAQ.md:60-67 that motivates
attacking dispatch count rather than per-op time.
"""
from __future__ import annotations

import dataclasses
import threading
from spark_rapids_tpu.utils import lockorder
from functools import partial
from typing import Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.columnar.column import Column, StringColumn
from spark_rapids_tpu.execs import aggregate as agg_exec
from spark_rapids_tpu.execs import basic, joins
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.execs.exchange import BroadcastExchangeExec
from spark_rapids_tpu.expressions.base import (Alias, BoundReference, ColV,
                                               EvalContext, Expression,
                                               Literal, broadcast)
from spark_rapids_tpu.expressions.compiler import (
    _unwrap_alias, derive_stats, fused_cache_get_or_build)
from spark_rapids_tpu.native import kernels as nkr
from spark_rapids_tpu.ops import hashing, sortkeys
from spark_rapids_tpu.ops import join as join_ops
from spark_rapids_tpu.ops.join import _BUILD_NULL, _PROBE_NULL
from spark_rapids_tpu.utils.tracing import TraceRange

_MAXH = jnp.iinfo(jnp.int64).max

# dense-probe table ceiling: 4M i32 slots = 16 MB HBM per build. TPC
# dim surrogate keys are 1..|dim| so even sf 1000 date/time/store/
# household dims fit; above it the hash+searchsorted path stands.
_DENSE_SPAN_MAX = 1 << 22


# ---------------------------------------------------------------------------
# step descriptors (host-side, picklable)
# ---------------------------------------------------------------------------


class _AuxStringPred(Expression):
    """Trace-time stand-in for a string-vs-literal predicate inside a
    fused chain. Dictionaries are SORTED (code order == string order,
    columnar/column.py), so every comparison against a literal is a
    code-range test whose boundaries are that batch's dictionary
    searchsorted positions — delivered to the cached program as scalar
    OPERANDS (``ctx.aux``), never baked in as constants. This is what
    lets string filters (category = 'Books', marital_status = 'M', IN
    lists) ride INSIDE one fused program instead of breaking the chain
    into eager dictionary evaluation + a separate compaction pass.

    ``op``: 'eq_any' (EqualTo / IN — one [lo, hi) pair per literal),
    'lt' | 'le' (codes < bound), 'gt' | 'ge' (codes >= bound)."""

    def __init__(self, ref, op: str, literals: List[str],
                 base_slot: int = -1):
        super().__init__([ref])
        self.op = op
        self.literals = [str(v) for v in literals]
        self.base_slot = base_slot

    @property
    def dtype(self):
        return dt.BOOLEAN

    @property
    def device_only(self) -> bool:
        return True

    @property
    def deterministic(self) -> bool:
        return True

    def n_slots(self) -> int:
        return 2 * len(self.literals) if self.op == "eq_any" else 1

    def aux_values(self, dictionary) -> List[int]:
        """Per-batch dictionary positions for this predicate's slots."""
        d = dictionary.astype(str) if dictionary is not None and \
            len(dictionary) else np.array([], dtype=str)
        if self.op == "eq_any":
            out = []
            for lit in self.literals:
                out.append(int(np.searchsorted(d, lit, side="left")))
                out.append(int(np.searchsorted(d, lit, side="right")))
            return out
        lit = self.literals[0]
        side = "left" if self.op in ("lt", "ge") else "right"
        return [int(np.searchsorted(d, lit, side=side))]

    def eval(self, ctx):
        v = self.children[0].eval(ctx)
        v = broadcast(v, ctx)
        codes = v.data
        aux = ctx.aux
        b = self.base_slot
        if self.op == "eq_any":
            keep = jnp.zeros(codes.shape, dtype=bool)
            for i in range(len(self.literals)):
                keep = keep | ((codes >= aux[b + 2 * i]) &
                               (codes < aux[b + 2 * i + 1]))
        elif self.op in ("lt", "le"):
            keep = codes < aux[b]
        else:  # gt / ge
            keep = codes >= aux[b]
        return ColV(dt.BOOLEAN, keep, v.validity)


def _flip_cmp(op: str) -> str:
    return {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}[op]


def _as_string_pred(node) -> Optional[_AuxStringPred]:
    """The aux-operand replacement for ``node`` when it is a string-vs-
    literal predicate on a plain column reference; None otherwise."""
    from spark_rapids_tpu.expressions import predicates as pr

    _CMP = {pr.EqualTo: "eq_any", pr.LessThan: "lt",
            pr.LessThanOrEqual: "le", pr.GreaterThan: "gt",
            pr.GreaterThanOrEqual: "ge"}
    if isinstance(node, pr.In):
        ref = node.children[0]
        if isinstance(ref, BoundReference) and ref.dtype is dt.STRING \
                and node.values and all(
                    isinstance(v, str) for v in node.values):
            return _AuxStringPred(ref, "eq_any", list(node.values))
        return None
    op = _CMP.get(type(node))
    if op is None:
        return None
    a, b = node.children
    if isinstance(a, BoundReference) and a.dtype is dt.STRING and \
            isinstance(b, Literal) and isinstance(b.value, str):
        return _AuxStringPred(a, op, [b.value])
    if isinstance(b, BoundReference) and b.dtype is dt.STRING and \
            isinstance(a, Literal) and isinstance(a.value, str):
        return _AuxStringPred(
            b, op if op == "eq_any" else _flip_cmp(op), [a.value])
    return None


def chain_transform(e: Expression) -> Tuple[Expression,
                                            List[_AuxStringPred]]:
    """Rewrite string-literal predicates into aux-operand nodes; the
    result is chain-traceable iff it ends up device_only."""
    preds: List[_AuxStringPred] = []

    def fn(node):
        repl = _as_string_pred(node)
        if repl is not None:
            preds.append(repl)
            return repl
        return node

    return e.transform(fn), preds


def chain_traceable(e: Expression) -> bool:
    """Can this expression run inside a fused chain program (directly or
    after the string-predicate transform)?"""
    if not e.deterministic:
        return False
    if e.device_only:
        return True
    t, _ = chain_transform(e)
    return t.device_only


@dataclasses.dataclass
class FilterStep:
    condition: Expression
    aux_preds: List[_AuxStringPred] = dataclasses.field(
        default_factory=list)

    def key(self):
        k = self.condition.tree_key()
        return None if k is None else ("F", k)


@dataclasses.dataclass
class ProjectStep:
    exprs: List[Expression]
    aux_preds: List[_AuxStringPred] = dataclasses.field(
        default_factory=list)

    def key(self):
        ks = tuple(_unwrap_alias(e).tree_key() for e in self.exprs)
        return None if any(k is None for k in ks) else ("P", ks)


def make_filter_step(condition: Expression) -> FilterStep:
    t, preds = chain_transform(condition)
    return FilterStep(t, preds)


def make_project_step(exprs: Sequence[Expression]) -> ProjectStep:
    out, preds = [], []
    for e in exprs:
        t, p = chain_transform(e)
        out.append(t)
        preds.extend(p)
    return ProjectStep(out, preds)


@dataclasses.dataclass
class SortStep:
    """Terminal ORDER BY inside a chain program: one variadic
    ``lax.sort`` carries every column through the sort network, dead
    lanes (filtered rows, padding) sink to the end, and the live count
    comes out as a lazy device scalar — so a post-aggregate
    HAVING/project/sort tail runs as ONE compiled program instead of
    compaction + rebucket + a separate sort dispatch. Only the planner
    may append one, and only over a source that emits exactly one batch
    on one partition (a hash aggregate): a per-batch sort of a
    multi-batch stream would NOT be a global sort."""

    specs: tuple  # Tuple[SortKeySpec, ...] (frozen, hashable)

    def key(self):
        return ("S", tuple((s.ordinal, s.ascending, s.nulls_first)
                           for s in self.specs))


@dataclasses.dataclass
class JoinStep:
    kind: str                  # inner | left | left_semi | left_anti
    stream_keys: List[int]     # ordinals into the working columns
    build_keys: List[int]      # ordinals into the build schema
    build_index: int           # which prepared build feeds this step
    build_types: List[dt.DType]
    key_common: List[dt.DType]  # per-pair comparison type (mixed-type
    #                             keys cast to it on both sides)

    def key(self):
        return ("J", self.kind, tuple(self.stream_keys),
                tuple(self.build_keys), self.build_index,
                tuple(self.build_types), tuple(self.key_common))


# ---------------------------------------------------------------------------
# build-side preparation (once per query per broadcast)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class PreparedBuild:
    """Hash-sorted broadcast build table. ``ok`` False means duplicate
    matchable key hashes were found — the chain must fall back to the
    general join kernel for exact multi-match expansion.

    ``table`` (when set) is a dense inverse index over the build key's
    value range: ``table[key - dense_lo]`` = sorted build row, -1 =
    absent. Single integral keys whose span fits ``_DENSE_SPAN_MAX``
    (every TPC fact->dim surrogate key) probe with ONE gather instead
    of an int64 hash + searchsorted — the searchsorted lowers to a
    ~17-step binary-search loop whose per-step gather costs ~100 ms at
    multi-million-row probe widths on a v5e, which made the probe THE
    on-device cost of TPCx-BB q9 at sf 1."""

    ok: bool
    h_sorted: Optional[jax.Array] = None
    datas: Optional[tuple] = None
    vals: Optional[tuple] = None
    n_valid: Optional[jax.Array] = None   # device scalar
    ghosts: Optional[list] = None         # host wrap info per column
    table: Optional[jax.Array] = None     # dense inverse index
    dense_lo: int = 0
    #: native.kernels.join.ProbeTable — the device-resident bucket
    #: table (join kernel on), probed across every stream batch
    ptable: Optional[object] = None


def _hash_keys(key_cols: Sequence[ColV], types: Sequence[dt.DType],
               targets: Sequence[dt.DType], sentinel) -> jax.Array:
    """Traceable combined int64 hash of key columns, each cast to its
    pair's common comparison type first; rows where ANY key is null
    collapse to ``sentinel`` (disjoint sentinels per side keep SQL
    null-never-matches semantics — ops/join.py:38-56)."""
    vals = []
    any_null = None
    for c, t, tgt in zip(key_cols, types, targets):
        if tgt is dt.STRING:
            raise AssertionError("string join keys are not fusable")
        d = c.data if t is tgt else c.data.astype(tgt.kernel_dtype)
        v = hashing._numeric_to_int64(d, tgt)
        if c.validity is not None:
            nn = ~c.validity
            any_null = nn if any_null is None else (any_null | nn)
            v = jnp.where(c.validity, v, jnp.int64(hashing._NULL_HASH))
        vals.append(v)
    h = hashing._combine(tuple(vals))
    if any_null is not None:
        h = jnp.where(any_null, sentinel, h)
    return h


def _prep_build_arrays(datas, vals, num_rows, key_ords, types, hash_types,
                       key_range=False, dense_span=0, dense_lo=0,
                       kernel_table=False):
    """Traceable build-side preparation — the body of ``_prep_build``,
    shared verbatim by the chain engine's build-inlined program variant
    (the in-program build traces this INSIDE the consuming chain, so
    the standalone prep dispatch and its flag sync disappear).

    Sort the build by key hash; null-key and padding rows park at the
    +inf sentinel (they can never match). Returns the duplicate flag the
    host checks once per query, plus (when ``key_range``) the single
    key's valid-row (min, max) in its comparison type — fetched in the
    same sync as the dup flag so the host can build the dense probe
    table without another round trip. When the key's range is already
    HOST-known (footer/upload stats survived the build subtree),
    ``dense_span``/``dense_lo`` fold the dense inverse-table build into
    THIS program — no flag round trip feeds it and the separate
    _prep_dense_table dispatch disappears."""
    cols = [ColV(t, d, v) for t, d, v in zip(types, datas, vals)]
    h = _hash_keys([cols[o] for o in key_ords],
                   [types[o] for o in key_ords], hash_types, _BUILD_NULL)
    cap = h.shape[0]
    live = jnp.arange(cap, dtype=jnp.int32) < num_rows
    h_l = jnp.where(live & (h != _BUILD_NULL), h, _MAXH)
    order = jnp.argsort(h_l, stable=True)
    sh = jnp.take(h_l, order)
    sdatas = [jnp.take(d, order) for d in datas]
    svals = [None if v is None else jnp.take(v, order) for v in vals]
    if cap > 1:
        dup = jnp.any((sh[1:] == sh[:-1]) & (sh[:-1] != _MAXH))
    else:
        dup = jnp.zeros((), dtype=bool)
    n_valid = jnp.sum(sh != _MAXH).astype(jnp.int32)
    if key_range:
        o = key_ords[0]
        kd = cols[o].data.astype(hash_types[0].kernel_dtype).astype(
            jnp.int64)
        matchable = live & (h != _BUILD_NULL)
        kmin = jnp.min(jnp.where(matchable, kd, jnp.int64(2**62)))
        kmax = jnp.max(jnp.where(matchable, kd, jnp.int64(-2**62)))
    else:
        kmin = jnp.int64(0)
        kmax = jnp.int64(-1)
    if dense_span > 0:
        table = _dense_table_arrays(sdatas[key_ords[0]], n_valid,
                                    dense_lo, dense_span)
    else:
        table = jnp.zeros(0, dtype=jnp.int32)
    if kernel_table and dense_span <= 0:
        # join kernel: bucket-offset table over the hash-sorted build,
        # HBM-resident for every later probe batch (dense mode keeps
        # its one-gather inverse table — strictly cheaper when legal)
        from spark_rapids_tpu.native.kernels import join as njoin

        ptable = njoin.build_table(sh, n_valid,
                                   njoin.table_bits_for(cap))
    else:
        ptable = None
    return sh, sdatas, svals, dup, n_valid, kmin, kmax, table, ptable


@partial(jax.jit, static_argnames=("key_ords", "types", "hash_types",
                                   "key_range", "dense_span",
                                   "kernel_table"))
def _prep_build(datas, vals, num_rows, key_ords, types, hash_types,
                key_range=False, dense_span=0, dense_lo=0,
                kernel_table=False):
    """Standalone (host-path) build prep: one dispatch per build. The
    in-program-build default inlines _prep_build_arrays into the chain
    instead; this program remains for the knob-off / fallback path."""
    return _prep_build_arrays(datas, vals, num_rows, key_ords, types,
                              hash_types, key_range=key_range,
                              dense_span=dense_span, dense_lo=dense_lo,
                              kernel_table=kernel_table)


def _dense_table_arrays(keys_sorted, n_valid, lo, span):
    """Traceable core of the dense inverse index over the hash-sorted
    build: valid (live, non-null-key) rows occupy the sorted prefix
    [0, n_valid), so scatter their key positions once; absent values
    stay -1."""
    cap = keys_sorted.shape[0]
    iota = jnp.arange(cap, dtype=jnp.int32)
    pos = (keys_sorted.astype(jnp.int64) - lo).astype(jnp.int32)
    pos = jnp.where(iota < n_valid, pos, jnp.int32(span))
    pos = jnp.clip(pos, 0, span)          # sentinel slot = span
    table = jnp.full(span + 1, -1, dtype=jnp.int32)
    table = table.at[pos].set(iota)
    return table[:span]


@partial(jax.jit, static_argnames=("span",))
def _prep_dense_table(keys_sorted, n_valid, lo, span):
    """Dense inverse index as its own program — the runtime-range path,
    used when the key bounds only became host-known via the flag sync.
    One small scatter per query per build — prep-time only."""
    return _dense_table_arrays(keys_sorted, n_valid, lo, span)


def _ghost_of(col: Column) -> "_Ghost":
    return _Ghost(col.dtype,
                  col.dictionary if isinstance(col, StringColumn) else None,
                  getattr(col, "stats", None))


#: prep results keyed by broadcast exchange object — a side table (not
#: attributes) so the exchange stays picklable for cluster map tasks and
#: the device arrays die with the query's plan objects. The global lock
#: guards only cache BOOKKEEPING; build materialization (arbitrarily
#: expensive, and possibly recursing into prepare_build for a chain
#: nested inside the build subtree) runs outside it, coordinated by a
#: per-(exchange, key) event so concurrent consumers wait on their own
#: build, never on an unrelated one.
_PREP_CACHE: "weakref.WeakKeyDictionary" = None
_PREP_LOCK = lockorder.make_lock("execs.fused.prepCache")


def _finalize_entries_locked(entries) -> None:
    """Caller holds _PREP_LOCK. Fetch the dup/key-range flags for every
    launched-but-unfinished entry in ONE device_get and build their
    PreparedBuilds (dense tables launch async). Safe under the global
    lock: finalization never materializes a subtree, so it cannot
    recurse into the prep machinery."""
    todo = [e for e in entries
            if not e["done"].is_set() and e.get("pending") is not None]
    if not todo:
        return
    try:
        flags = jax.device_get(
            [(e["pending"][0][3], e["pending"][0][5],
              e["pending"][0][6]) for e in todo])
    except BaseException as exc:
        for e in todo:
            e["error"] = exc
            # drop the poisoned entry like the launch-failure path: a
            # transient tunnel error during the flag sync must not
            # permanently fail every later consumer of this exchange
            cache, key = e["slot"]
            if cache.get(key) is e:
                cache.pop(key, None)
            e["done"].set()
        raise
    for e, (dup_h, kmin_h, kmax_h) in zip(todo, flags):
        (sh, sdatas, svals, _d, n_valid, _kn, _kx, table, ptable), \
            ghosts, want_range, build_keys, span_max, dense_span, \
            dense_lo = e.pop("pending")
        if bool(dup_h):
            prep = PreparedBuild(ok=False)
        else:
            prep = PreparedBuild(
                ok=True, h_sorted=sh, datas=tuple(sdatas),
                vals=tuple(svals), n_valid=n_valid, ghosts=ghosts,
                ptable=ptable)
            if dense_span > 0:
                # stats-known range: the table came out of _prep_build
                prep.table = table
                prep.dense_lo = dense_lo
            elif want_range and int(kmin_h) <= int(kmax_h):
                from spark_rapids_tpu.ops.groupby import quantize_range

                qlo, qhi = quantize_range(int(kmin_h), int(kmax_h))
                span = qhi - qlo + 1
                if span <= span_max:
                    with TraceRange("FusedChain.denseTable"):
                        prep.table = _prep_dense_table(
                            sdatas[build_keys[0]], n_valid,
                            jnp.int64(qlo), span=span)
                    prep.dense_lo = qlo
        e["prep"] = prep
        e["done"].set()


def prepare_builds(specs) -> List[PreparedBuild]:
    """Materialize + hash-sort MANY broadcast build sides with (at
    most) ONE host sync. ``specs``: [(exchange, build_keys,
    build_types, hash_types, dense_span_max)].

    Per-build prep costs a dispatch (+1 for a dense table) but the dup/
    key-range flags need a blocking device_get; done per build that is
    4 round trips on a q9-class 4-dim join chain. Builds are claimed
    and LAUNCHED one at a time (a build's materialization can recurse
    into prepare_builds for a fused chain nested in its subtree — a
    sibling claimed later is then simply unowned and the nested call
    owns it; a sibling launched earlier is finalizable by ANY caller,
    so no claim is ever held un-launched while waiting). The flag sync
    itself batches over every still-pending launch. Cached per
    exchange object so every consumer partition and every chain
    sharing the broadcast pays its prep only once."""
    import weakref

    global _PREP_CACHE
    entries = []   # (cache, key, entry, owner) per spec
    for exch, build_keys, build_types, hash_types, span_max in specs:
        key = (tuple(build_keys), tuple(hash_types), span_max)
        with _PREP_LOCK:
            if _PREP_CACHE is None:
                _PREP_CACHE = weakref.WeakKeyDictionary()
            cache = _PREP_CACHE.get(exch)
            if cache is None:
                cache = _PREP_CACHE[exch] = {}
            entry = cache.get(key)
            if entry is None:
                entry = cache[key] = {"done": threading.Event(),
                                      "prep": None, "error": None,
                                      "pending": None,
                                      "slot": (cache, key)}
                owner = True
            else:
                owner = False
        entries.append((cache, key, entry, owner))
        if not owner:
            continue
        # launch this build's prep now (async, no sync); materialize
        # may recurse into prepare_builds for nested chains
        try:
            want_range = span_max > 0 and len(build_keys) == 1 and (
                hash_types[0].is_integral or
                hash_types[0] in (dt.DATE, dt.TIMESTAMP, dt.BOOLEAN))
            with exch._materialize().acquired() as b:
                # when footer/upload stats survived the build subtree
                # the key range is host-known NOW: fold the dense table
                # into the prep program and skip the runtime-range
                # machinery (stats are bounds, possibly loose — the
                # table just covers a wider span)
                dense_span = 0
                dense_lo = 0
                if want_range and b.columns:
                    st = getattr(b.columns[build_keys[0]], "stats",
                                 None)
                    if st is not None:
                        from spark_rapids_tpu.ops.groupby import \
                            quantize_range

                        qlo, qhi = quantize_range(int(st[0]),
                                                  int(st[1]))
                        if qhi - qlo + 1 <= span_max:
                            dense_span = qhi - qlo + 1
                            dense_lo = qlo
                from spark_rapids_tpu.native import kernels as nkr

                with TraceRange("FusedChain.prepareBuild"):
                    out = _prep_build(
                        [c.data for c in b.columns],
                        [c.validity for c in b.columns],
                        b.num_rows_device(), tuple(build_keys),
                        tuple(build_types), tuple(hash_types),
                        key_range=want_range and not dense_span,
                        dense_span=dense_span,
                        dense_lo=np.int64(dense_lo),
                        kernel_table=nkr.enabled("join"))
                ghosts = [_ghost_of(c) for c in b.columns]
            with _PREP_LOCK:
                entry["pending"] = (out, ghosts, want_range,
                                    tuple(build_keys), span_max,
                                    dense_span, dense_lo)
        except BaseException as e:
            entry["error"] = e
            with _PREP_LOCK:
                cache.pop(key, None)  # a later caller may retry
            entry["done"].set()
            raise

    # one sync finalizes every build this call launched
    with _PREP_LOCK:
        _finalize_entries_locked([e for _c, _k, e, own in entries
                                  if own])
    out: List[PreparedBuild] = []
    for _cache, _key, entry, _own in entries:
        if not entry["done"].is_set():
            # someone else launched it: finalize if launched, else wait
            # for their launch to post (short — the launcher is inside
            # materialize+dispatch, never inside a wait on us)
            with _PREP_LOCK:
                _finalize_entries_locked([entry])
            if not entry["done"].is_set():
                entry["done"].wait()
        if entry["error"] is not None:
            raise entry["error"]
        out.append(entry["prep"])
    return out


def prepare_build(exch: BroadcastExchangeExec, build_keys: Sequence[int],
                  build_types: Sequence[dt.DType],
                  hash_types: Sequence[dt.DType],
                  dense_span_max: int = _DENSE_SPAN_MAX
                  ) -> PreparedBuild:
    """Single-build convenience wrapper over prepare_builds."""
    return prepare_builds([(exch, build_keys, build_types,
                            hash_types, dense_span_max)])[0]


# ---------------------------------------------------------------------------
# the chain engine
# ---------------------------------------------------------------------------


def _batching_ctx():
    """The thread's micro-batching slice context, or None outside a
    query-service slice (the common library path: one sys.modules hit
    plus a thread-local read)."""
    try:
        from spark_rapids_tpu.service.batching import microbatch as _mb
    except Exception:  # pragma: no cover - service package unavailable
        return None
    return _mb.current()


@dataclasses.dataclass
class _Ghost:
    """Host mirror of one working column during the ghost walk: what the
    program can't carry through jit (dictionaries, footer stats)."""

    dtype: dt.DType
    dictionary: Optional[np.ndarray] = None
    stats: Optional[tuple] = None


class FusedChain:
    """Compiles a step list into one jitted program over raw arrays."""

    def __init__(self, steps: List, source_types: List[dt.DType],
                 n_builds: int):
        self.steps = list(steps)
        self.source_types = list(source_types)
        self.n_builds = n_builds
        self._number_aux_slots()
        self._programs: dict = {}

    def _number_aux_slots(self) -> None:
        # aux operand slots for string predicates: number sequentially
        # in (step, pred) order — run() collects per-batch values in
        # the same order
        slot = 0
        for s in self.steps:
            for p in getattr(s, "aux_preds", ()):
                p.base_slot = slot
                slot += p.n_slots()
        self.n_aux = slot

    # jit closures and compiled programs never ship to remote executors
    def __getstate__(self):
        return {"steps": self.steps, "source_types": self.source_types,
                "n_builds": self.n_builds}

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._number_aux_slots()
        self._programs = {}

    def chain_key(self, compact_out: bool, modes: tuple = (),
                  decode: tuple = (), inline: tuple = ()):
        ks = tuple(s.key() for s in self.steps)
        if any(k is None for k in ks):
            return None
        # the native-kernel gate state routes ops at TRACE time, so it
        # is part of the program's structural identity — a knob flip
        # must miss every cache, never serve the stale routing
        return ("fused_chain", ks, tuple(self.source_types), compact_out,
                modes, decode, inline, nkr.cache_token())

    def _program(self, compact_out: bool, modes: tuple = (),
                 decode: tuple = (), inline: tuple = ()):
        ckey = (compact_out, modes, decode, inline, nkr.cache_token())
        prog = self._programs.get(ckey)
        if prog is not None:
            return prog
        key = self.chain_key(compact_out, modes, decode, inline)
        # single-flight: concurrent same-template queries (different
        # tenants) racing a cold key trace it ONCE and share the
        # program — the cross-tenant compile fence
        prog = fused_cache_get_or_build(
            key, lambda: self._build_program(compact_out, modes,
                                             decode, inline))
        self._programs[ckey] = prog
        return prog

    def _build_program(self, compact_out: bool, modes: tuple = (),
                       decode: tuple = (), inline: tuple = ()):
        steps = self.steps
        sort_step = steps[-1] if steps and \
            isinstance(steps[-1], SortStep) else None

        def run_steps(cols, live, num_rows, builds, aux, capacity):
            for step in steps:
                if isinstance(step, FilterStep):
                    ctx = EvalContext(cols, capacity, num_rows,
                                      in_jit=True)
                    ctx.aux = aux
                    v = broadcast(step.condition.eval(ctx), ctx)
                    keep = v.data
                    if v.validity is not None:
                        keep = keep & v.validity
                    live = live & keep
                elif isinstance(step, ProjectStep):
                    ctx = EvalContext(cols, capacity, num_rows,
                                      in_jit=True)
                    ctx.aux = aux
                    cols = [broadcast(e.eval(ctx), ctx)
                            for e in step.exprs]
                elif isinstance(step, SortStep):
                    continue  # terminal; handled below
                else:
                    cols, live = _apply_join(step, cols, live,
                                             builds[step.build_index])
            if sort_step is not None:
                # ONE variadic sort carries every column; dead lanes
                # (padding + filtered rows) sink last via the live mask
                pairs = [(c.data, c.validity) for c in cols]
                dts = [c.dtype for c in cols]
                payloads = []
                layout = []
                for c in cols:
                    di = len(payloads)
                    payloads.append(c.data)
                    vi = -1
                    if c.validity is not None:
                        vi = len(payloads)
                        payloads.append(c.validity)
                    layout.append((di, vi))
                sorted_pl = sortkeys.sort_with_payloads(
                    pairs, dts, list(sort_step.specs), num_rows,
                    payloads, live_mask=live)
                outs = [(sorted_pl[di],
                         None if vi < 0 else sorted_pl[vi])
                        for di, vi in layout]
                return outs, jnp.sum(live).astype(jnp.int32)
            outs = [(c.data, c.validity) for c in cols]
            if not compact_out:
                return outs, live
            if nkr.enabled("sort"):
                # O(n) prefix-scan partition kernel: bit-equal to the
                # stable argsort but skips the O(n log n) sort network
                # — the measured end-of-chain cost at sf1 widths
                from spark_rapids_tpu.native.kernels import \
                    sort as nsort

                order = nsort.partition_order(live)
            else:
                order = jnp.argsort(~live, stable=True)
            n = jnp.sum(live).astype(jnp.int32)
            outs = [(jnp.take(d, order),
                     None if v is None else jnp.take(v, order))
                    for d, v in outs]
            return outs, n

        def inline_build_ops(raw_builds):
            # in-program build: trace the build-side prep (hash sort,
            # dup probe, stats-known dense table) INSIDE this program.
            # Per build, hand run_steps the probe-ready ops tuple and
            # hand the caller the prepared arrays + dup flag so later
            # batches reuse them via the probe-only variant — the
            # standalone _prep_build dispatch and its flag-sync
            # device_get both disappear from the stage.
            from spark_rapids_tpu.native import kernels as nkr

            ops, prepared = [], []
            for spec, (bdatas, bvals, bnum) in zip(inline, raw_builds):
                bkeys, btypes, htypes, dspan, dlo = spec
                (sh, sdatas, svals, dup, n_valid, _kn, _kx, table,
                 ptable) = _prep_build_arrays(
                    list(bdatas), list(bvals), bnum, bkeys, btypes,
                    htypes, dense_span=dspan, dense_lo=dlo,
                    kernel_table=nkr.enabled("join"))
                ops.append((sh, tuple(sdatas), tuple(svals), n_valid,
                            table if dspan > 0 else None,
                            dlo if dspan > 0 else None, ptable))
                prepared.append((sh, tuple(sdatas), tuple(svals), dup,
                                 n_valid, table, ptable))
            return ops, tuple(prepared)

        if decode:
            # scan-decode prelude: the chain starts from the PACKED
            # upload buffers and inlines the transfer decode, so the
            # scan->filter->join->project stage pays zero decode
            # dispatch (see interop.PackedBatch)
            from spark_rapids_tpu.execs import interop as _interop

            dec_specs, col_map, cap = decode

            if inline:
                def run(bufs, bases, num_rows, raw_builds, aux, types):
                    decoded = _interop.unpack_arrays(list(bufs), bases,
                                                     dec_specs, cap)
                    cols = [ColV(t, decoded[bi],
                                 None if vi < 0 else decoded[vi])
                            for t, (_k, bi, vi) in zip(types, col_map)]
                    live = jnp.arange(cap, dtype=jnp.int32) < num_rows
                    builds, prepared = inline_build_ops(raw_builds)
                    outs, live = run_steps(cols, live, num_rows,
                                           builds, aux, cap)
                    return outs, live, prepared
            else:
                def run(bufs, bases, num_rows, builds, aux, types):
                    decoded = _interop.unpack_arrays(list(bufs), bases,
                                                     dec_specs, cap)
                    cols = [ColV(t, decoded[bi],
                                 None if vi < 0 else decoded[vi])
                            for t, (_k, bi, vi) in zip(types, col_map)]
                    live = jnp.arange(cap, dtype=jnp.int32) < num_rows
                    return run_steps(cols, live, num_rows, builds, aux,
                                     cap)
        elif inline:
            def run(datas, vals, num_rows, raw_builds, aux, types):
                capacity = datas[0].shape[0] if datas else 128
                cols = [ColV(t, d, v)
                        for t, d, v in zip(types, datas, vals)]
                live = jnp.arange(capacity, dtype=jnp.int32) < num_rows
                builds, prepared = inline_build_ops(raw_builds)
                outs, live = run_steps(cols, live, num_rows, builds,
                                       aux, capacity)
                return outs, live, prepared
        else:
            def run(datas, vals, num_rows, builds, aux, types):
                capacity = datas[0].shape[0] if datas else 128
                cols = [ColV(t, d, v)
                        for t, d, v in zip(types, datas, vals)]
                live = jnp.arange(capacity, dtype=jnp.int32) < num_rows
                return run_steps(cols, live, num_rows, builds, aux,
                                 capacity)

        # distinct per-chain names so dispatch telemetry attributes each
        # chain program separately (every chain would otherwise report
        # as one 'run' bucket). The crc tag separates chains that share
        # a step-type shape but compile different expressions (q9's five
        # filter+project branches); it keys on the SAME (compact_out,
        # modes) tuple as the program cache so dense-probe and
        # hash-probe variants of one chain attribute separately
        import zlib

        key = self.chain_key(compact_out, modes, decode, inline)
        tag = zlib.crc32(repr(key if key is not None
                              else id(self)).encode()) & 0xFFFF
        label = "fused_chain[" + ("build+" if inline else "") + \
            ("decode+" if decode else "") + \
            "+".join(type(s).__name__.replace("Step", "").lower()
                     for s in steps) + f"]@{tag:04x}"
        run.__name__ = run.__qualname__ = label
        return partial(jax.jit, static_argnames=("types",))(run)

    def run(self, batch, preps: List[PreparedBuild],
            compact_out: bool):
        """-> (outs, live_mask | new_count, final output ghosts). The
        ghost walk runs ONCE per batch, serving both the aux operand
        collection and the caller's output wrapping. ``batch`` may be a
        still-packed upload (interop.PackedBatch): the program then
        inlines the transfer decode as its first traced steps.

        Under a query-service slice (service/batching context on this
        thread) the launch routes through the micro-batcher: same-key
        same-bucket dispatches from concurrent queries coalesce into
        one physical program launch, and the shape-bucket registry logs
        the (program, bucket) observation for warmup/stats."""
        from spark_rapids_tpu.execs import interop as _interop

        states, final_ghosts = self._ghost_states(batch, preps)
        build_ops = tuple(
            (p.h_sorted, p.datas, p.vals, p.n_valid, p.table,
             None if p.table is None else p.dense_lo, p.ptable)
            for p in preps)
        # dense/hash probe mode is per-build runtime information (key
        # stats), so it keys the compiled program separately
        modes = tuple(p.table is not None for p in preps)
        aux = self._aux_from_states(states)
        if isinstance(batch, _interop.PackedBatch):
            decode = batch.decode_key()
            prog = self._program(compact_out, modes, decode)
            args = (tuple(batch.bufs), tuple(batch.dec_bases),
                    batch.num_rows_device(), build_ops, aux)
        else:
            decode = ()
            prog = self._program(compact_out, modes)
            args = ([c.data for c in batch.columns],
                    [c.validity for c in batch.columns],
                    batch.num_rows_device(), build_ops, aux)
        statics = {"types": tuple(self.source_types)}
        ctx = _batching_ctx()
        key = None if ctx is None else \
            self.chain_key(compact_out, modes, decode)
        if ctx is None or key is None:
            # unkeyed chains (some step has no structural key) must NOT
            # coalesce: the only stable identity would be id(prog), and
            # a recycled object id after GC could hand another chain's
            # cached K-way program back — silently wrong results
            outs, live = prog(*args, **statics)
        else:
            reg = getattr(ctx.batcher, "registry", None)
            if reg is not None and not decode:
                # packed chains bake the decode capacity in as a
                # static, so their shapes are not ladder-replayable.
                # stream_args=2: leaves of (datas, vals) ride the
                # ladder; build_ops/aux keep their recorded shapes
                reg.record(key, prog, args, statics, stream_args=2)
            outs, live = ctx.batcher.call(key, prog, args, statics,
                                          ctx.query_id, ctx.multi)
        return outs, live, final_ghosts

    def run_inline(self, batch, descs: tuple, raw_builds: Sequence,
                   build_ghosts: Sequence, compact_out: bool):
        """First-batch launch of the build-inlined program variant:
        -> (outs, live | count, prepared build array tuples, output
        ghosts). ``descs`` is the static per-build descriptor
        ((build_keys, build_types, hash_types, dense_span, dense_lo),
        ...); ``raw_builds`` the matching raw (datas, vals, num_rows)
        triples. Deliberately bypasses the micro-batcher and the
        warmup-ladder registry: the variant runs ONCE per (chain,
        query) — its argument layout puts raw build arrays where
        probe-only launches put prepared ops, so a ladder replay would
        re-prepare builds for nothing, and a one-shot launch has no
        cross-tenant sharing to win."""
        from spark_rapids_tpu.execs import interop as _interop

        ghost_preps = [PreparedBuild(ok=True, ghosts=list(g))
                       for g in build_ghosts]
        states, final_ghosts = self._ghost_states(batch, ghost_preps)
        aux = self._aux_from_states(states)
        raw_ops = tuple((tuple(d), tuple(v), n)
                        for d, v, n in raw_builds)
        if isinstance(batch, _interop.PackedBatch):
            decode = batch.decode_key()
            prog = self._program(compact_out, (), decode, inline=descs)
            args = (tuple(batch.bufs), tuple(batch.dec_bases),
                    batch.num_rows_device(), raw_ops, aux)
        else:
            prog = self._program(compact_out, (), inline=descs)
            args = ([c.data for c in batch.columns],
                    [c.validity for c in batch.columns],
                    batch.num_rows_device(), raw_ops, aux)
        outs, live, prepared = prog(*args,
                                    types=tuple(self.source_types))
        return outs, live, prepared, final_ghosts

    # -- host mirror --------------------------------------------------------

    def _ghost_states(self, batch, preps: List[PreparedBuild]):
        """Per-step INPUT ghost lists, plus the final output ghosts."""
        from spark_rapids_tpu.execs import interop as _interop

        if isinstance(batch, _interop.PackedBatch):
            ghosts = [_Ghost(t, d, s) for t, d, s in batch.ghost_info()]
        else:
            ghosts = [_ghost_of(c) for c in batch.columns]
        states = []
        for step in self.steps:
            states.append(ghosts)
            if isinstance(step, (FilterStep, SortStep)):
                continue
            if isinstance(step, ProjectStep):
                ghosts = [self._project_ghost(e, ghosts)
                          for e in step.exprs]
                continue
            if step.kind in ("left_semi", "left_anti"):
                continue
            ghosts = ghosts + list(preps[step.build_index].ghosts)
        return states, ghosts

    def _aux_from_states(self, states) -> tuple:
        """Per-batch scalar operands for string predicates: dictionary
        searchsorted positions of each predicate's literals, in slot
        order (matching the numbering done at construction)."""
        if self.n_aux == 0:
            return ()
        aux: List[int] = []
        for step, ghosts in zip(self.steps, states):
            for p in getattr(step, "aux_preds", ()):
                g = ghosts[p.children[0].ordinal]
                aux.extend(p.aux_values(g.dictionary))
        assert len(aux) == self.n_aux, (len(aux), self.n_aux)
        # plain ints: jit traces them as scalar operands shipped with
        # the call (a jnp.int32() per value would be its own transfer)
        return tuple(aux)

    @staticmethod
    def _project_ghost(e: Expression, ghosts: List[_Ghost]) -> _Ghost:
        u = _unwrap_alias(e)
        if isinstance(u, BoundReference):
            g = ghosts[u.ordinal]
            return _Ghost(e.dtype, g.dictionary, g.stats)
        if e.dtype is dt.STRING:
            assert isinstance(u, Literal), \
                "device_only string expr must be a ref or literal"
            dictionary = np.array(
                [] if u.value is None else [u.value], dtype=object)
            return _Ghost(dt.STRING, dictionary, None)
        return _Ghost(e.dtype, None, derive_stats(e, ghosts))

    def wrap(self, outs, ghosts: List[_Ghost], num_rows) -> ColumnarBatch:
        cols: List[Column] = []
        for (data, validity), g in zip(outs, ghosts):
            if g.dtype is dt.STRING:
                cols.append(StringColumn(data, g.dictionary, validity))
            else:
                cols.append(Column(g.dtype, data, validity,
                                   stats=g.stats))
        return ColumnarBatch(cols, num_rows)


def _apply_join(step: JoinStep, cols: List[ColV], live,
                b: Tuple) -> Tuple[List[ColV], jax.Array]:
    """Unique-build probe. Dense mode (fact->dim surrogate keys): ONE
    gather into the prep-time inverse table — exact by construction, no
    hashing, no verification. Hash mode: searchsorted into the
    hash-sorted build + exact key verification. Either way each probe
    row has at most one candidate; matches fold into the live-mask
    (inner/semi/anti) or gathered validity (left). With the join kernel
    on, hash mode probes the prep-time bucket table (one short in-HBM
    scan) instead of the ~17-step searchsorted binary search — same
    leftmost-match contract, same exact-key verification."""
    sh, datas, vals, n_valid, table, dense_lo, ptable = b
    b_cap = sh.shape[0]
    if table is not None:
        span = table.shape[0]
        sc = cols[step.stream_keys[0]]
        pos = sc.data.astype(jnp.int64) - dense_lo
        inb = (pos >= 0) & (pos < span)
        idx = jnp.take(table,
                       jnp.clip(pos, 0, span - 1).astype(jnp.int32))
        found = inb & (idx >= 0)
        if sc.validity is not None:
            found = found & sc.validity
        lo_c = jnp.clip(idx, 0, b_cap - 1)
    else:
        key_cols = [cols[o] for o in step.stream_keys]
        h_p = _hash_keys(key_cols, [c.dtype for c in key_cols],
                         step.key_common, _PROBE_NULL)
        if ptable is not None:
            from spark_rapids_tpu.native.kernels import join as njoin

            lo, _cnt = njoin.probe(ptable, h_p)
        else:
            lo = jnp.searchsorted(sh, h_p,
                                  side="left").astype(jnp.int32)
        lo_c = jnp.clip(lo, 0, b_cap - 1)
        found = (jnp.take(sh, lo_c) == h_p) & (lo < n_valid)
        for so, bo, ct in zip(step.stream_keys, step.build_keys,
                              step.key_common):
            sc = cols[so]
            sd = sc.data if sc.dtype is ct else \
                sc.data.astype(ct.kernel_dtype)
            bd = jnp.take(datas[bo], lo_c)
            if step.build_types[bo] is not ct:
                bd = bd.astype(ct.kernel_dtype)
            bv = vals[bo]
            bv = None if bv is None else jnp.take(bv, lo_c)
            s_comps, s_valid = sortkeys.equality_parts(sd, sc.validity,
                                                       ct)
            b_comps, b_valid = sortkeys.equality_parts(bd, bv, ct)
            found = found & s_valid & b_valid
            for scp, bcp in zip(s_comps, b_comps):
                found = found & (scp == bcp)
    if step.kind == "left_semi":
        return cols, live & found
    if step.kind == "left_anti":
        return cols, live & ~found
    out = list(cols)
    for bd, bv, bt in zip(datas, vals, step.build_types):
        gd = jnp.take(bd, lo_c)
        gv = None if bv is None else jnp.take(bv, lo_c)
        if step.kind == "left":
            gv = found if gv is None else (gv & found)
        out.append(ColV(bt, gd, gv))
    return out, (live & found) if step.kind == "inner" else live


# ---------------------------------------------------------------------------
# execs
# ---------------------------------------------------------------------------


def _build_key_specs(steps) -> list:
    """(build_keys, build_types, key_common) per JoinStep, ordered by
    ``build_index`` — the inputs prepare_build needs, shared by both
    fused execs. ORDER MATTERS: the builds list is in extraction
    (reverse-execution) order while steps run in execution order;
    indexing by build_index keeps spec[i] paired with builds[i] (a
    mismatch cross-hashes the wrong key columns: loud IndexError when
    widths differ, silently empty probes when they coincide)."""
    joins = sorted((s for s in steps if isinstance(s, JoinStep)),
                   key=lambda s: s.build_index)
    return [(tuple(s.build_keys), tuple(s.build_types),
             tuple(s.key_common)) for s in joins]


class FusedChainExec(TpuExec):
    """Standalone fused segment: filters/projections/broadcast probes
    (and, for post-aggregate tails, the final ORDER BY) in one program
    per batch, compacted once at the end (lazy row count). Falls back
    to the preserved unfused subtree when a build side has duplicate
    key hashes."""

    #: planner-set: the packed scan feeding this chain (its decode runs
    #: inside the chain program); reset to eager decode on fallback
    _defer_scan = None

    def __init__(self, source: TpuExec, chain: FusedChain,
                 builds: List[BroadcastExchangeExec], schema: Schema,
                 fallback: TpuExec, conf=None):
        super().__init__([source], schema)
        self.chain = chain
        self.builds = builds
        self.fallback = fallback
        self.conf = conf
        self.build_key_specs = _build_key_specs(chain.steps)
        self._preps: Optional[List[PreparedBuild]] = None
        self._preps_ok: Optional[bool] = None
        self._inline_evt = None
        self._prep_lock = lockorder.make_lock("execs.fused.chainPrep")

    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_prep_lock", None)
        state.pop("_inline_evt", None)
        state["_preps"] = None
        state["_preps_ok"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._inline_evt = None
        self._prep_lock = lockorder.make_lock("execs.fused.chainPrep")

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    def _ensure_preps(self) -> bool:
        with self._prep_lock:
            if self._preps_ok is None:
                from spark_rapids_tpu import config as cfg

                conf = getattr(self, "conf", None)
                span_max = conf.get(cfg.FUSION_DENSE_PROBE_MAX_SPAN) \
                    if conf is not None else _DENSE_SPAN_MAX
                preps = prepare_builds(
                    [(exch, keys, types, commons, span_max)
                     for exch, (keys, types, commons) in zip(
                         self.builds, self.build_key_specs)])
                ok = all(p.ok for p in preps)
                if not ok and self._defer_scan is not None:
                    # the fallback subtree re-executes the scan and is
                    # not fusion-aware: restore eager decode first
                    self._defer_scan.defer_decode = False
                self._preps = preps if ok else None
                self._preps_ok = ok
            return self._preps_ok

    def _inline_enabled(self) -> bool:
        """In-program build applies when the chain HAS builds and the
        knob is on; chains without joins take the (free) host path."""
        if not self.builds:
            return False
        from spark_rapids_tpu import config as cfg

        conf = getattr(self, "conf", None)
        return bool(conf.get(cfg.FUSION_IN_PROGRAM_BUILD)
                    if conf is not None
                    else cfg.FUSION_IN_PROGRAM_BUILD.default)

    def _inline_first(self, batch, compact_out: bool):
        """Single-flight first-batch inline build. Returns the chain
        output triple when THIS thread ran the build-inlined launch, or
        None when the builds were resolved (or failed to duplicates) by
        another thread / the dup fallback engaged — the caller then
        consults ``_preps_ok``. A leader that errors leaves ``_preps_ok``
        None; the next waiter retries as the new leader (same contract
        as the _PREP_CACHE poisoned-entry drop)."""
        while True:
            leader = False
            with self._prep_lock:
                if self._preps_ok is not None:
                    return None
                evt = self._inline_evt
                if evt is None:
                    evt = self._inline_evt = threading.Event()
                    leader = True
            if leader:
                try:
                    return self._inline_launch(batch, compact_out)
                finally:
                    with self._prep_lock:
                        self._inline_evt = None
                    evt.set()
            evt.wait()
            if self._preps_ok is not None:
                return None

    def _inline_launch(self, batch, compact_out: bool):
        """Materialize the build sides RAW and run the chain's
        build-inlined program variant on the first stream batch: hash
        sort, duplicate probe and (stats-known) dense table trace
        INSIDE the chain program, so stage0 sheds the standalone
        _prep_build dispatch AND its flag-sync device_get. The launch
        is SPECULATIVE — probe results are garbage if a build has
        duplicate key hashes — so the dup flags ride back as program
        outputs and are read via np.asarray, a transfer that overlaps
        the (already in-flight) program instead of costing its own
        dispatch. Duplicates discard the output, restore eager scan
        decode, and fall back to the preserved unfused subtree, exactly
        like the host path. Returns (outs, live|count, ghosts) or None
        on fallback. Unlike the host path the runtime-key-range dense
        table is NOT built here (it needed the flag sync this variant
        exists to remove): builds without host-known stats probe in
        hash mode."""
        import contextlib

        from spark_rapids_tpu import config as cfg

        conf = getattr(self, "conf", None)
        span_max = conf.get(cfg.FUSION_DENSE_PROBE_MAX_SPAN) \
            if conf is not None else _DENSE_SPAN_MAX
        descs, raw, ghosts_l = [], [], []
        with contextlib.ExitStack() as stack:
            for exch, (bkeys, btypes, commons) in zip(
                    self.builds, self.build_key_specs):
                bb = stack.enter_context(exch._materialize().acquired())
                dense_span = 0
                dense_lo = 0
                want_range = span_max > 0 and len(bkeys) == 1 and (
                    commons[0].is_integral or
                    commons[0] in (dt.DATE, dt.TIMESTAMP, dt.BOOLEAN))
                if want_range and bb.columns:
                    st = getattr(bb.columns[bkeys[0]], "stats", None)
                    if st is not None:
                        from spark_rapids_tpu.ops.groupby import \
                            quantize_range

                        qlo, qhi = quantize_range(int(st[0]),
                                                  int(st[1]))
                        if qhi - qlo + 1 <= span_max:
                            dense_span = qhi - qlo + 1
                            dense_lo = qlo
                descs.append((tuple(bkeys), tuple(btypes),
                              tuple(commons), dense_span, dense_lo))
                raw.append(([c.data for c in bb.columns],
                            [c.validity for c in bb.columns],
                            bb.num_rows_device()))
                ghosts_l.append([_ghost_of(c) for c in bb.columns])
            with TraceRange("FusedChainExec.inlineBuild"):
                outs, live, prepared, ghosts = self.chain.run_inline(
                    batch, tuple(descs), raw, ghosts_l, compact_out)
        # np.asarray, not device_get: the flag rides home with the
        # in-flight program's results rather than as its own counted
        # round trip (the telemetry's device_get wrapper is the
        # dispatch boundary; __array__ coercion isn't)
        if any(bool(np.asarray(p[3])) for p in prepared):
            with self._prep_lock:
                self._preps = None
                self._preps_ok = False
            if self._defer_scan is not None:
                # the fallback subtree re-executes the scan and is
                # not fusion-aware: restore eager decode first
                self._defer_scan.defer_decode = False
            return None
        preps = []
        for (bkeys, _bt, _cm, dspan, dlo), p, g in zip(descs, prepared,
                                                       ghosts_l):
            sh, sdatas, svals, _dup, n_valid, table, ptable = p
            prep = PreparedBuild(ok=True, h_sorted=sh, datas=sdatas,
                                 vals=svals, n_valid=n_valid, ghosts=g,
                                 ptable=ptable)
            if dspan > 0:
                prep.table = table
                prep.dense_lo = dlo
            preps.append(prep)
        with self._prep_lock:
            self._preps = preps
            self._preps_ok = True
        return outs, live, ghosts

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        if self._preps_ok is None and self._inline_enabled():
            return timed(self, self._iter_inline(partition))
        if not self._ensure_preps():
            return self.fallback.execute(partition)
        return timed(self, self._iter_probe(partition))

    def _iter_probe(self, partition: int):
        saw = False
        has_sort = any(isinstance(s, SortStep)
                       for s in self.chain.steps)
        for b in self.children[0].execute(partition):
            # skip empties only when the count is ALREADY host-side:
            # forcing a lazy count here would cost the same round
            # trip the skip is trying to save
            n = b.num_rows
            if isinstance(n, int) and n == 0 and saw:
                continue
            if saw and has_sort:
                # not an assert: must survive python -O — a second
                # batch through a SortStep chain would silently
                # produce per-batch (non-global) order
                raise RuntimeError(
                    "SortStep chain fed more than one batch "
                    "(planner bug: source must be a single-batch "
                    "aggregate)")
            saw = True
            with TraceRange("FusedChainExec"):
                outs, n, ghosts = self.chain.run(b, self._preps,
                                                 compact_out=True)
            yield self.chain.wrap(outs, ghosts, n)

    def _iter_inline(self, partition: int):
        """First batch runs the build-inlined variant (or waits for a
        peer partition's); every later batch takes the probe-only path
        over the prepared arrays it produced."""
        saw = False
        has_sort = any(isinstance(s, SortStep)
                       for s in self.chain.steps)
        for b in self.children[0].execute(partition):
            n = b.num_rows
            if isinstance(n, int) and n == 0 and saw:
                continue
            if saw and has_sort:
                raise RuntimeError(
                    "SortStep chain fed more than one batch "
                    "(planner bug: source must be a single-batch "
                    "aggregate)")
            if self._preps_ok is None:
                res = self._inline_first(b, compact_out=True)
                if res is not None:
                    saw = True
                    outs, n2, ghosts = res
                    yield self.chain.wrap(outs, ghosts, n2)
                    continue
                # a peer thread may have prepared the builds; fall
                # through to the shared dup check / probe path
            if not self._preps_ok:
                # duplicate build-key hashes: the speculative output
                # is discarded, the preserved subtree runs. Checked
                # OUTSIDE the is-None branch: a peer partition's
                # leader can set the dup flag between our execute()
                # routing decision and this batch, in which case
                # _preps is None and the probe path must not run.
                yield from self.fallback.execute(partition)
                return
            saw = True
            with TraceRange("FusedChainExec"):
                outs, n2, ghosts = self.chain.run(b, self._preps,
                                                  compact_out=True)
            yield self.chain.wrap(outs, ghosts, n2)

    def tree_string(self, indent: int = 0) -> str:
        return _fused_tree_string(self, indent,
                                  f"[{len(self.chain.steps)} fused steps]")

    def all_metrics(self):
        return _fused_all_metrics(self)


def _fused_tree_string(exec_, indent: int, note: str) -> str:
    """Explain output for a fused exec — when the duplicate-build
    fallback ran, the UNfused subtree did the work and must be what
    explain shows (degradation is never silent, same rule as cluster
    local-placement)."""
    label = "  " * indent + exec_.name + " " + note
    if exec_._preps_ok is False:
        label += " [FELL BACK: duplicate build key hashes]"
        return "\n".join([label,
                          exec_.fallback.tree_string(indent + 1)])
    lines = [label]
    for c in exec_.children:
        lines.append(c.tree_string(indent + 1))
    return "\n".join(lines)


def _fused_all_metrics(exec_):
    out = {exec_.name: exec_.metrics}
    if exec_._preps_ok is False:
        out.update(exec_.fallback.all_metrics())
    else:
        for c in exec_.children:
            out.update(c.all_metrics())
    return out


class _InlineDupFallback(Exception):
    """Internal: the speculative build-inlined first launch found
    duplicate build-key hashes. Raised out of
    FusedAggregateExec._update_inputs — safe because the aggregate
    yields nothing before its first _update_inputs — and caught in
    execute(), which reruns the partition through the preserved
    unfused subtree."""


class FusedAggregateExec(agg_exec.HashAggregateExec):
    """Hash aggregate whose update side consumes a fused chain: per
    batch, ONE chain program produces the projected aggregate inputs
    plus a live-mask that rides into the groupby sort — the reference's
    per-batch update pipeline (aggregate.scala:420-478) as two compiled
    programs instead of a dispatch per operator."""

    _defer_scan = None  # see FusedChainExec

    def __init__(self, grouping, aggs, schema, mode, conf,
                 source: TpuExec, steps: List,
                 builds: List[BroadcastExchangeExec],
                 fallback: agg_exec.HashAggregateExec):
        super().__init__(grouping, aggs, source, schema, mode=mode,
                         conf=conf, fused_filter=None)
        steps = list(steps)
        if fallback.fused_filter is not None:
            steps.append(make_filter_step(
                fallback.fused_filter.condition))
        assert self.input_proj is not None
        # absorb the input projection only when it can trace (directly
        # or via the string-predicate transform); remaining dictionary-
        # dependent string expressions keep CompiledProjection's eager
        # path (it carries the source StringColumn; the chain's ColVs
        # don't)
        self._proj_in_chain = all(chain_traceable(e)
                                  for e in self.input_proj.exprs)
        if self._proj_in_chain:
            steps.append(make_project_step(self.input_proj.exprs))
        self.chain = FusedChain(steps, list(source.schema.types),
                                len(builds))
        self.builds = builds
        self.fallback = fallback
        self.build_key_specs = _build_key_specs(self.chain.steps)
        self._preps: Optional[List[PreparedBuild]] = None
        self._preps_ok: Optional[bool] = None
        self._inline_evt = None
        self._prep_lock = lockorder.make_lock("execs.fused.chainPrep")

    __getstate__ = FusedChainExec.__getstate__
    __setstate__ = FusedChainExec.__setstate__
    _ensure_preps = FusedChainExec._ensure_preps
    _inline_enabled = FusedChainExec._inline_enabled
    _inline_first = FusedChainExec._inline_first
    _inline_launch = FusedChainExec._inline_launch

    def _update_inputs(self, b: ColumnarBatch):
        if self._preps_ok is None and self._inline_enabled():
            res = self._inline_first(b, compact_out=False)
            if res is None:
                if not self._preps_ok:
                    raise _InlineDupFallback()
                # a peer thread prepared the builds: probe path below
            else:
                outs, live, ghosts = res
                out = self.chain.wrap(outs, ghosts, b.num_rows)
                if not self._proj_in_chain:
                    out = self.input_proj(out)
                return out, live
        with TraceRange("FusedAggregateExec.chain"):
            outs, live, ghosts = self.chain.run(b, self._preps,
                                                compact_out=False)
        out = self.chain.wrap(outs, ghosts, b.num_rows)
        if not self._proj_in_chain:
            # eager projection outside the chain (string dictionary
            # ops); row-aligned, so the live-mask stays valid
            out = self.input_proj(out)
        return out, live

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        if self._preps_ok is None and self._inline_enabled():
            # builds resolve lazily inside the first _update_inputs; a
            # duplicate-keyed build surfaces as _InlineDupFallback
            # BEFORE the aggregate yields anything, so the fallback
            # subtree can still own the whole partition
            def it():
                try:
                    yield from super(FusedAggregateExec,
                                     self).execute(partition)
                except _InlineDupFallback:
                    yield from self.fallback.execute(partition)
            return it()
        if not self._ensure_preps():
            return self.fallback.execute(partition)
        return super().execute(partition)

    def tree_string(self, indent: int = 0) -> str:
        return _fused_tree_string(
            self, indent,
            f"[{len(self.chain.steps)} fused steps, {self.mode}]")

    def all_metrics(self):
        return _fused_all_metrics(self)


# ---------------------------------------------------------------------------
# planner pass
# ---------------------------------------------------------------------------

_FUSABLE_JOIN_KINDS = ("inner", "left", "left_semi", "left_anti")


def _broadcast_of(j: joins.BroadcastHashJoinExec
                  ) -> Optional[BroadcastExchangeExec]:
    from spark_rapids_tpu.plan.overrides import _ReplayExec

    b = j.children[1]
    if isinstance(b, _ReplayExec):
        b = b.children[0]
    return b if isinstance(b, BroadcastExchangeExec) else None


def _fusable_join(node) -> bool:
    if type(node) is not joins.BroadcastHashJoinExec:
        return False
    if node.kind not in _FUSABLE_JOIN_KINDS:
        return False
    if node.condition is not None and not (
            node.kind == "inner" and node.condition.fused and
            node.condition.condition.deterministic):
        return False
    if _broadcast_of(node) is None:
        return False
    stream_types = node.children[0].schema.types
    build_types = node.children[1].schema.types
    for so, bo in zip(node.left_keys, node.right_keys):
        c = join_ops.common_key_type(stream_types[so], build_types[bo])
        if c is None or c is dt.STRING:
            return False
    return True


def _extract(node: TpuExec):
    """Walk down a maximal fusable chain; returns (steps bottom-up,
    source, build exchanges, walked exec nodes) or None. ``walked`` is
    every intermediate exec the chain absorbed — a stage-widening
    rewrite that MUTATES the source (defer_final) must verify none of
    them is shared, because a second parent of a shared intermediate
    reaches the source through it and still expects the unmutated
    output contract."""
    steps: List = []
    builds: List[BroadcastExchangeExec] = []
    walked: List[TpuExec] = []
    cur = node
    while True:
        if isinstance(cur, basic.FilterExec) and \
                chain_traceable(cur.filter.condition):
            steps.append(make_filter_step(cur.filter.condition))
            walked.append(cur)
            cur = cur.children[0]
        elif isinstance(cur, basic.ProjectExec) and \
                all(chain_traceable(e)
                    for e in cur.projection.exprs):
            steps.append(make_project_step(cur.projection.exprs))
            walked.append(cur)
            cur = cur.children[0]
        elif _fusable_join(cur):
            if cur.condition is not None:
                steps.append(make_filter_step(cur.condition.condition))
            stream_types = cur.children[0].schema.types
            build_types = list(cur.children[1].schema.types)
            commons = [join_ops.common_key_type(stream_types[so],
                                                build_types[bo])
                       for so, bo in zip(cur.left_keys, cur.right_keys)]
            steps.append(JoinStep(
                cur.kind, list(cur.left_keys), list(cur.right_keys),
                len(builds), build_types, commons))
            builds.append(_broadcast_of(cur))
            walked.append(cur)
            cur = cur.children[0]
        else:
            break
    if not steps:
        return None
    steps.reverse()
    return steps, cur, builds, walked


def _is_mesh(node: TpuExec) -> bool:
    """Chains must not absorb operators sitting directly on a mesh
    exec: the mesh layer runs filters between mesh execs SHARDED
    (parallel/filter_step.py) — wrapping them would gather the chain
    to one chip."""
    from spark_rapids_tpu.parallel import execs as pex

    return isinstance(node, (pex.MeshGroupByExec, pex.MeshShuffledJoinExec,
                             pex.MeshWindowExec, pex.MeshSortExec))


def _counts(steps) -> Tuple[int, int, int]:
    nf = sum(1 for s in steps if isinstance(s, FilterStep))
    np_ = sum(1 for s in steps if isinstance(s, ProjectStep))
    nj = sum(1 for s in steps if isinstance(s, JoinStep))
    return nf, np_, nj


def fuse_pipelines(root: TpuExec, conf=None) -> TpuExec:
    """Post-conversion pass (before coalesce insertion): absorb fusable
    chains into FusedAggregateExec / FusedChainExec, widen post-
    aggregate tails (final projection + HAVING + project + ORDER BY)
    into one chain program, and hand packed scan uploads straight to
    the chain that decodes them in-program. Memoized by node identity
    so shared (CTE) subtrees stay shared; stage-widening rewrites that
    MUTATE a source (defer_final, defer_decode) only apply to sources
    with a single parent."""
    from spark_rapids_tpu import config as cfg

    if conf is not None and not conf.get(cfg.FUSION_ENABLED):
        return root
    return _fuse_node(root, conf, {}, _multi_parent_ids(root))


def _multi_parent_ids(root: TpuExec) -> set:
    """ids of exec nodes referenced by MORE than one parent (shared CTE
    subtrees): stage-widening must not change their output contract."""
    counts: dict = {}
    seen: set = set()
    stack = [root]
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        for c in n.children:
            counts[id(c)] = counts.get(id(c), 0) + 1
            stack.append(c)
    return {i for i, c in counts.items() if c > 1}


def _absorb_final(steps, fused_src):
    """Pull an aggregate source's final projection into the consuming
    chain: the aggregate then emits raw (keys..., partials...) with a
    lazy count (defer_final) and the chain's program applies final-
    project + HAVING + compaction — removing the aggregate's own
    final-projection dispatch AND its rebucket host sync. Returns
    (steps, source_types) with source_types None when not absorbed.
    Only chains WITHOUT join steps qualify: a join chain can fall back
    to its preserved subtree, which must then see the aggregate's
    normal (finalized) output."""
    if any(isinstance(s, JoinStep) for s in steps):
        return steps, None
    if not isinstance(fused_src, agg_exec.HashAggregateExec):
        return steps, None
    if fused_src.mode not in ("complete", "final") or \
            fused_src.final_proj is None or fused_src.defer_final:
        return steps, None
    exprs = fused_src.final_proj.exprs
    if not all(chain_traceable(e) for e in exprs):
        return steps, None
    new_steps = [make_project_step(exprs)] + list(steps)
    src_types = [e.dtype for e in fused_src.grouping] + \
        list(fused_src.partial_types)
    fused_src.defer_final = True
    fb = getattr(fused_src, "fallback", None)
    if isinstance(fb, agg_exec.HashAggregateExec):
        # the prep-failure fallback aggregate feeds the SAME chain, so
        # it must emit the same deferred shape
        fb.defer_final = True
    return new_steps, src_types


def _maybe_defer_scan(out, new_source, shared, conf) -> None:
    """Hand a packed scan's upload buffers straight to the fused chain:
    the chain's program inlines the transfer decode (zero decode
    dispatch). Single-parent scans only — any other consumer would see
    PackedBatches it cannot read."""
    from spark_rapids_tpu import config as cfg

    if conf is not None and not conf.get(cfg.FUSION_DEFER_DECODE):
        return
    if isinstance(new_source, basic.ScanExec) and new_source.pack and \
            id(new_source) not in shared:
        new_source.defer_decode = True
        out._defer_scan = new_source


def _fuse_sort_tail(node, conf, memo: dict, shared: set):
    """Absorb a global ORDER BY into the post-aggregate chain below it:
    Sort(Project(Filter(Agg))) becomes ONE chain program (final-project
    + HAVING + project + in-program variadic sort) over the aggregate's
    raw partials. Valid only when the source emits exactly one batch on
    one partition — a hash aggregate — because a per-batch sort of a
    multi-batch stream is not a global sort."""
    ch = _extract(node.children[0])
    steps, source, builds, walked = ch if ch \
        else ([], node.children[0], [], [])
    if _is_mesh(source) or id(source) in shared:
        return None
    new_source = _fuse_node(source, conf, memo, shared)
    if not (isinstance(new_source, agg_exec.HashAggregateExec) and
            new_source.mode in ("complete", "final") and
            new_source.num_partitions == 1):
        return None
    src_types = None
    if not any(id(w) in shared for w in walked):
        # defer_final mutates the aggregate; a shared intermediate
        # (CTE-reused Project/Filter) would expose the mutated output
        # to a second consumer that expects finalized columns
        steps, src_types = _absorb_final(steps, new_source)
    steps = list(steps) + [SortStep(tuple(node.specs))]
    for bx in builds:
        bx.children = [_fuse_node(bx.children[0], conf, memo, shared)]
    chain = FusedChain(steps,
                       src_types or list(new_source.schema.types),
                       len(builds))
    return FusedChainExec(new_source, chain, builds, node.schema,
                          fallback=node, conf=conf)


def _fuse_node(node: TpuExec, conf, memo: dict, shared: set) -> TpuExec:
    hit = memo.get(id(node))
    if hit is not None:
        return hit[1]
    out = None
    if type(node) is agg_exec.HashAggregateExec and \
            node.mode in ("partial", "complete"):
        ch = _extract(node.children[0])
        steps, source, builds = ch[:3] if ch \
            else ([], node.children[0], [])
        # an empty chain still pays off when the agg carries a fused
        # filter: mask+project collapse into one program
        if _is_mesh(source):
            steps = None
        if steps or (steps is not None and node.fused_filter is not None):
            new_source = _fuse_node(source, conf, memo, shared)
            for bx in builds:
                bx.children = [_fuse_node(bx.children[0], conf, memo,
                                          shared)]
            out = FusedAggregateExec(
                node.grouping, node.aggs, node.schema, node.mode,
                node.conf, new_source, steps, builds, fallback=node)
            _maybe_defer_scan(out, new_source, shared, conf)
    if out is None:
        from spark_rapids_tpu.execs.sort import SortExec

        from spark_rapids_tpu import config as cfg

        sort_tail_on = conf is None or conf.get(cfg.FUSION_SORT_TAIL)
        if sort_tail_on and type(node) is SortExec and \
                node.global_sort and node.specs:
            out = _fuse_sort_tail(node, conf, memo, shared)
    if out is None:
        ch = _extract(node)
        if ch is not None and not _is_mesh(ch[1]):
            steps, source, builds, walked = ch
            nf, np_, nj = _counts(steps)
            # savings estimate: each filter ~2 dispatches, project 1,
            # join ~6; the chain costs 1. Skip a lone projection.
            if 2 * nf + np_ + 6 * nj - 1 >= 1:
                new_source = _fuse_node(source, conf, memo, shared)
                src_types = None
                if id(source) not in shared and not any(
                        id(w) in shared for w in walked):
                    # see _fuse_sort_tail: defer_final must not leak
                    # through a shared intermediate node
                    steps, src_types = _absorb_final(steps, new_source)
                for bx in builds:
                    bx.children = [_fuse_node(bx.children[0], conf,
                                              memo, shared)]
                chain = FusedChain(
                    steps, src_types or list(new_source.schema.types),
                    len(builds))
                out = FusedChainExec(new_source, chain, builds,
                                     node.schema, fallback=node,
                                     conf=conf)
                _maybe_defer_scan(out, new_source, shared, conf)
    if out is None:
        node.children = [_fuse_node(c, conf, memo, shared)
                         for c in node.children]
        out = node
    memo[id(node)] = (node, out)
    return out
