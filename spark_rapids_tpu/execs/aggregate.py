"""Hash-aggregate exec: streaming per-batch aggregation with a running
merge loop.

Reference flow (aggregate.scala:380-478): input-project each batch ->
per-batch aggregation -> concat with the running aggregate -> merge-
aggregate; after the last batch, final projection (:503-545) and the
empty-input default-values path (:488-501). On TPU the per-batch aggregate
is the sort-based segmented kernel (ops/groupby.py) and all halves run as
jit-compiled XLA programs.
"""
from __future__ import annotations

from typing import Iterator, List, Optional

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Expression)
from spark_rapids_tpu.expressions.compiler import CompiledProjection
from spark_rapids_tpu.ops.concat import concat_batches
from spark_rapids_tpu.ops.filter import rebucket
from spark_rapids_tpu.ops.groupby import AggSpec, groupby_aggregate, \
    reduce_aggregate
from spark_rapids_tpu.plan.nodes import AggCall
from spark_rapids_tpu.utils.tracing import TraceRange


class HashAggregateExec(TpuExec):
    """Modes (GpuHashAggregateExec / partial-final split):

    - complete: raw -> results in one exec
    - partial:  raw -> partial columns (update halves), feeds an exchange
    - final:    partials -> merged + evaluated results
    """

    #: planner-set (fused.py): yield raw (keys..., partials...) batches
    #: with a LAZY row count — the downstream fused chain absorbed the
    #: final projection, the HAVING filter and the compaction, so this
    #: exec's final-project dispatch and rebucket sync disappear
    defer_final = False
    #: deferred-final outputs above this capacity rebucket anyway: the
    #: consuming chain's in-program sort is a full-capacity variadic
    #: sort network, so the dispatch saving must not buy a multi-
    #: million-lane sort (group counts overwhelmingly fit far below)
    _DEFER_FINAL_MAX_CAP = 1 << 20

    def __init__(self, grouping: List[Expression], aggs: List[AggCall],
                 child: TpuExec, schema: Schema, mode: str = "complete",
                 conf=None, fused_filter=None):
        super().__init__([child], schema)
        assert mode in ("complete", "partial", "final")
        self.grouping = grouping
        self.aggs = aggs
        self.mode = mode
        self.conf = conf
        # a CompiledFilter whose keep-mask rides into the groupby sort as
        # a live_mask — the planner fuses Filter(child) pairs here, saving
        # the per-batch compaction pass (argsort + per-column gathers)
        self.fused_filter = fused_filter
        # resolve the grouping-sets dense guard NOW, while the full
        # in-process subtree is visible: a cluster rewrite may later
        # swap it for a shuffle-read stub (runtime/cluster.py), and the
        # pickled exec must carry the already-resolved flag
        self._dense_ok()
        self._single_pass()
        self._build()

    def _build(self):
        nkeys = len(self.grouping)
        if self.mode in ("complete", "partial"):
            # input projection: keys then each agg's input once per update op
            proj_exprs: List[Expression] = list(self.grouping)
            specs: List[AggSpec] = []
            for call in self.aggs:
                fn = call.fn
                if fn.input is not None:
                    ordinal = len(proj_exprs)
                    proj_exprs.append(fn.input)
                else:
                    ordinal = -1
                for op in fn.update_ops():
                    specs.append(AggSpec(op, ordinal
                                         if op != "count_star" else -1))
            self.input_proj: Optional[CompiledProjection] = \
                CompiledProjection(proj_exprs, self.conf)
            self.input_types = [e.dtype for e in proj_exprs]
            self.first_specs = specs
        else:
            # final mode: child emits keys then partial columns
            self.input_proj = None
            self.input_types = list(self.children[0].schema.types)
            specs = []
            p = nkeys
            for call in self.aggs:
                for op in call.fn.merge_ops():
                    specs.append(AggSpec(op, p))
                    p += 1
            self.first_specs = specs

        # merge specs re-aggregate this exec's own partial output (running
        # concat+merge loop): partial column i sits at nkeys+i.
        self.merge_specs: List[AggSpec] = []
        p = nkeys
        for call in self.aggs:
            for op in call.fn.merge_ops():
                self.merge_specs.append(AggSpec(op, p))
                p += 1
        self.partial_types: List[dt.DType] = []
        for call in self.aggs:
            self.partial_types.extend(call.fn.partial_types())

        # final projection over (keys..., partials...)
        if self.mode in ("complete", "final"):
            exprs: List[Expression] = [
                BoundReference(i, e.dtype) for i, e in
                enumerate(self.grouping)]
            base = nkeys
            for call in self.aggs:
                nparts = len(call.fn.partial_types())
                refs = [BoundReference(base + j, t) for j, t in
                        enumerate(call.fn.partial_types())]
                exprs.append(Alias(call.fn.evaluate(refs), call.name))
                base += nparts
            self.final_proj: Optional[CompiledProjection] = \
                CompiledProjection(exprs, self.conf)
        else:
            self.final_proj = None

    @property
    def coalesce_after(self):
        # the merge loop leaves exactly one batch per partition
        from spark_rapids_tpu.execs.batching import RequireSingleBatch

        return RequireSingleBatch

    @property
    def children_coalesce_goal(self):
        # final mode reads pre-reduced partials (often many tiny
        # shuffle blocks): coalescing them first turns N update+merge
        # kernel dispatches into one concat + one update, while the
        # TargetSize bound keeps memory behavior identical to the
        # streaming loop (which concats running+part at the same scale)
        if self.mode != "final":
            return [None]
        from spark_rapids_tpu import config as cfg
        from spark_rapids_tpu.execs.batching import TargetSize

        bb = self.conf.get(cfg.BATCH_SIZE_BYTES) if self.conf is not None \
            else cfg.BATCH_SIZE_BYTES.default
        return [TargetSize(bb)]

    # ------------------------------------------------------------------

    def _dense_ok(self) -> bool:
        """Grouping-set aggregates (an ExpandExec anywhere below) must
        not take the sort-free dense groupby for FLOAT sums: expand
        places each level's copy of the same rows at different
        positions, and the dense sweep's position-dependent reduction
        tree would break the cross-level bit-equality of float sums
        that rank()-over-sum ties rely on (TPC-DS q67). The kernel
        itself re-enables dense when no order-sensitive aggregate is
        present (ints/counts/min/max are order-invariant). Computed
        EAGERLY on first call in-process and cached on the exec, so a
        cluster rewrite that later replaces the subtree with a
        shuffle-read stub ships the already-resolved flag."""
        ok = getattr(self, "_dense_ok_cached", None)
        if ok is None:
            from spark_rapids_tpu.execs.basic import ExpandExec

            stack: list = [self]
            ok = True
            while stack:
                n = stack.pop()
                if isinstance(n, ExpandExec):
                    ok = False
                    break
                stack.extend(getattr(n, "children", ()))
            self._dense_ok_cached = ok
        return ok

    def _single_pass(self) -> bool:
        """Wide aggregates launch as ONE segmented pass (default) vs the
        chunked two-launch AOT workaround loop — see ops/groupby.py's
        _AOT_MAX_AGGS note. Resolved once and cached on the exec so a
        cluster-shipped pickle keeps the submitting session's choice."""
        sp = getattr(self, "_single_pass_cached", None)
        if sp is None:
            from spark_rapids_tpu import config as cfg

            sp = bool(self.conf.get(cfg.GROUPBY_SINGLE_PASS)
                      if self.conf is not None
                      else cfg.GROUPBY_SINGLE_PASS.default)
            self._single_pass_cached = sp
        return sp

    def _agg_batch(self, batch: ColumnarBatch, specs: List[AggSpec],
                   types: List[dt.DType], live_mask=None,
                   site: str = "aggregate.update") -> ColumnarBatch:
        """Aggregate one batch under the split-and-retry ladder: device
        OOM first spills the catalog and retries (the RMM event
        handler's spill-and-retry, DeviceMemoryEventHandler.scala:42),
        then HALVES the input and aggregates the halves — valid because
        partial aggregates re-merge with the merge ops, exactly what
        the streaming loop does between batches anyway."""
        from spark_rapids_tpu.memory import retry as _retry

        nkeys = len(self.grouping)

        def run(item):
            b, m = item
            if nkeys == 0:
                return reduce_aggregate(b, specs, types, m)[0]
            return groupby_aggregate(b, list(range(nkeys)), specs,
                                     types, m,
                                     dense_ok=self._dense_ok(),
                                     single_pass=self._single_pass())[0]

        def split(item):
            b, m = item
            if m is not None:
                # the live-mask is capacity-aligned to THIS batch; a
                # row-range half would need a matching mask slice at a
                # rebucketed capacity — compact the survivors instead
                # so the halves carry no mask at all
                from spark_rapids_tpu.ops import filter as filt

                b = rebucket(filt.compact_batch(b, m))
            halves = _retry.halve_batch(b)
            if halves is None:
                return None
            return [(h, None) for h in halves]

        parts = _retry.with_retry((batch, live_mask), run, split=split,
                                  tag=site)
        out = parts[0]
        for part in parts[1:]:
            # the re-merge runs at the memory level that just OOM'd, so
            # it goes through the ladder too: the concat under the
            # spill rungs, the merge aggregate recursively guarded
            # (splittable — merge ops are associative over partials)
            merged_in = _retry.with_retry_no_split(
                lambda o=out, p=part: concat_batches([o, p]),
                tag="aggregate.merge.concat")
            out = self._agg_batch(merged_in, self.merge_specs,
                                  self._merge_types(),
                                  site="aggregate.merge")
        return out

    def _merge_types(self) -> List[dt.DType]:
        return [e.dtype for e in self.grouping] + self.partial_types

    # -- the incremental-combine seam ----------------------------------
    # The update/merge split built for the retry ladder doubles as an
    # incremental operator: partials from disjoint row sets re-merge to
    # the partials of their union, so a consumer may hold ``running``
    # partials across calls and fold new input in O(new input). The
    # batch execute() loop below and the streaming subsystem
    # (service/streaming/state.py) both drive these three methods.

    def update_partials(self, batch: ColumnarBatch,
                        site: str = "aggregate.update") -> ColumnarBatch:
        """One update-program launch: a raw child batch ->
        (keys..., partials...) in the merge schema."""
        b, mask = self._update_inputs(batch)
        b, mask = self._maybe_compact_wide(b, mask)
        return self._agg_batch(b, self.first_specs, self.input_types,
                               mask, site=site)

    def merge_partials(self, running: ColumnarBatch,
                       part: ColumnarBatch,
                       site: str = "aggregate.merge") -> ColumnarBatch:
        """One merge launch: concat two partial batches and re-aggregate
        with the merge specs (associative — any fold order yields the
        same partials for integral aggregates)."""
        merged_in = concat_batches([running, part])
        return self._agg_batch(merged_in, self.merge_specs,
                               self._merge_types(), site=site)

    def finalize_partials(self, running: ColumnarBatch) -> ColumnarBatch:
        """Final projection + compaction over accumulated partials.
        Does NOT consume ``running`` — a streaming consumer can emit
        now and keep folding into the same partials."""
        if self.final_proj is not None:
            with TraceRange("HashAggregateExec.finalProject"):
                running = self.final_proj(running)
        return rebucket(running)

    def _update_inputs(self, b: ColumnarBatch):
        """Per-batch update-side inputs: (projected batch, live-mask).
        FusedAggregateExec overrides this with its one-program chain."""
        mask = None
        if self.fused_filter is not None:
            # keep-mask over the RAW batch (condition binds to
            # the child schema), row-aligned through projection
            mask = self.fused_filter.mask(b)
        if self.input_proj is not None:
            b = self.input_proj(b)
        return b, mask

    # above this capacity a WIDE (chunked) sort-path aggregate over a
    # filtered batch first compacts the survivors: the 2^23-capacity
    # 9-agg chunked groupby shape costs a multi-ten-minute remote XLA
    # compile (TPCx-BB q26 @ sf 1), while compact + count-sync +
    # re-bucket turns it into an already-cached small-capacity shape.
    # Dense-eligible aggregates skip this (no sort module to blow up).
    _COMPACT_WIDE_MIN_CAP = 1 << 22

    def _maybe_compact_wide(self, b: ColumnarBatch, mask):
        from spark_rapids_tpu.ops import filter as filt
        from spark_rapids_tpu.ops import groupby as gb

        if mask is None or b.capacity < self._COMPACT_WIDE_MIN_CAP or \
                len(self.first_specs) <= gb._AOT_MAX_AGGS or \
                not self.grouping:
            return b, mask
        key_ords = list(range(len(self.grouping)))
        kr = tuple(gb.key_range_of(b.columns[o], self.input_types[o])
                   for o in key_ords)
        khv = tuple(b.columns[o].validity is not None for o in key_ords)
        if self._dense_ok() and gb._dense_layout(
                list(self.input_types), key_ords, kr, khv) is not None:
            return b, mask   # dense path: no sort module to blow up
        with TraceRange("HashAggregateExec.compactWide"):
            small = rebucket(filt.compact_batch(b, mask))
        if small.capacity < b.capacity:
            return small, None
        return b, mask

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            running: Optional[ColumnarBatch] = None
            saw_input = False
            for b in self.children[0].execute(partition):
                if b.realized_num_rows() == 0:
                    continue
                saw_input = True
                with TraceRange("HashAggregateExec.updateAgg"):
                    part = self.update_partials(b)
                if running is None:
                    running = part
                else:
                    with TraceRange("HashAggregateExec.mergeAgg"):
                        running = self.merge_partials(running, part)
            if running is None:
                if self.grouping or (self.mode == "final" and not saw_input):
                    # grouped agg over empty input -> no rows (in the
                    # deferred-final shape the consumer chain expects
                    # the merge schema, not the final one)
                    yield ColumnarBatch.empty(
                        self._merge_schema() if self.defer_final
                        else self.schema)
                    return
                running = self._empty_global_partials()
            if self.defer_final:
                # the consuming fused chain applies the final
                # projection, HAVING and compaction in ITS program;
                # the count stays a lazy device scalar. Above the
                # capacity bound, rebucket anyway (one sync + shrink):
                # the chain's variadic SORT runs at this batch's
                # capacity, and a multi-million-lane sort network to
                # save two round trips is a net loss at large scale
                # factors
                if running.capacity > self._DEFER_FINAL_MAX_CAP:
                    running = rebucket(running)
                yield running
                return
            yield self.finalize_partials(running)
        return timed(self, it())

    def _merge_schema(self) -> Schema:
        types = self._merge_types()
        return Schema([f"_m{i}" for i in range(len(types))], types)

    def _empty_global_partials(self) -> ColumnarBatch:
        """Default partials for a global aggregate over zero rows: count=0,
        everything else null (aggregate.scala:488-501)."""
        import numpy as np

        from spark_rapids_tpu.ops.buckets import bucket_capacity

        cap = bucket_capacity(1)
        cols = []
        for call in self.aggs:
            for ptype, pop in zip(call.fn.partial_types(),
                                  call.fn.update_ops()):
                if pop in ("count", "count_star"):
                    cols.append(Column.from_numpy(
                        np.zeros(cap, dtype=np.int64), dtype=dt.INT64))
                else:
                    cols.append(Column.all_null(ptype, cap))
        return ColumnarBatch(cols, 1)
