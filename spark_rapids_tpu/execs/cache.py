"""Cached (persisted) datasets.

The reference routes ``.cache()`` through Spark's in-memory columnar
cache with host transitions (docs/FAQ.md:121); TPU-native caching is
strictly better-integrated: the materialized batches register with the
spill catalog as spillable buffers, so a cached DataFrame lives in HBM
while it fits and degrades through host/disk tiers under pressure —
identical machinery to shuffle blocks and broadcast tables."""
from __future__ import annotations

import threading
from spark_rapids_tpu.utils import lockorder
from typing import Dict, Iterator, List, Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.memory import priorities
from spark_rapids_tpu.memory.spillable import SpillableBatch
from spark_rapids_tpu.plan.nodes import PlanNode


class CacheNode(PlanNode):
    """Plan marker carrying a shared CacheHolder so repeated plans over
    the same cached DataFrame reuse one materialization."""

    def __init__(self, child: PlanNode):
        super().__init__([child])
        self.holder = CacheHolder()

    def output_schema(self) -> Schema:
        return self.children[0].output_schema()

    def describe(self) -> str:
        state = "materialized" if self.holder.is_materialized \
            else "lazy"
        return f"Cache[{state}]"


class CacheHolder:
    """Partition -> spillable batches, filled once."""

    def __init__(self):
        self._lock = lockorder.make_lock("execs.cache.materialize")
        self._parts: Optional[Dict[int, List[SpillableBatch]]] = None

    @property
    def is_materialized(self) -> bool:
        return self._parts is not None

    def materialize(self, child: TpuExec) -> None:
        with self._lock:
            if self._parts is not None:
                return
            parts: Dict[int, List[SpillableBatch]] = {}
            for p in range(child.num_partitions):
                handles = []
                for b in child.execute(p):
                    if b.realized_num_rows() == 0:
                        continue
                    handles.append(SpillableBatch(
                        b, priorities.INPUT_FROM_SHUFFLE_PRIORITY))
                parts[p] = handles
            self._parts = parts

    def num_partitions(self) -> int:
        assert self._parts is not None
        return max(len(self._parts), 1)

    def batches(self, partition: int):
        assert self._parts is not None
        return self._parts.get(partition, [])

    def unpersist(self) -> None:
        with self._lock:
            if self._parts is None:
                return
            for handles in self._parts.values():
                for h in handles:
                    h.close()
            self._parts = None


class CachedExec(TpuExec):
    def __init__(self, node: CacheNode, child: TpuExec):
        super().__init__([child], child.schema)
        self.node = node

    @property
    def num_partitions(self) -> int:
        if self.node.holder.is_materialized:
            return self.node.holder.num_partitions()
        return self.children[0].num_partitions

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            self.node.holder.materialize(self.children[0])
            handles = self.node.holder.batches(partition)
            if not handles:
                yield ColumnarBatch.empty(self.schema)
                return
            for h in handles:
                with h.acquired() as batch:
                    yield batch
        return timed(self, it())
