"""Join execs.

Reference: GpuHashJoin (shims/spark300/.../GpuHashJoin.scala:302-318) builds
one side, streams the other through cuDF join kernels; conditions are
post-join filters (:285-291); SMJ is replaced by shuffled hash join
(GpuSortMergeJoinExec.scala). TPU equivalents use the sort-probe equi-join
kernel (ops/join.py) — no device hash tables, XLA sorts instead.

- BroadcastHashJoinExec: build side fully materialized (whole child), probe
  side streamed per batch. Safe for inner/left/semi/anti with a right
  build; full joins need both sides whole.
- ShuffledHashJoinExec: same kernel after both sides were hash-partitioned
  by an exchange, per-partition build.
- Conditioned outer joins fall back at the planner (the kernel applies
  conditions post-join, valid only for inner/cross).
"""
from __future__ import annotations

from typing import Iterator, List, Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.execs.batching import RequireSingleBatch
from spark_rapids_tpu.expressions.base import Expression
from spark_rapids_tpu.expressions.compiler import CompiledFilter
from spark_rapids_tpu.ops.join import cross_join, equi_join, nested_loop_join
from spark_rapids_tpu.utils.tracing import TraceRange

_KIND_MAP = {"inner": "inner", "left": "left", "left_semi": "leftsemi",
             "left_anti": "leftanti", "full": "full"}


class HashJoinExec(TpuExec):
    """Build-side = children[1] (right); streams children[0] (left).
    ``right`` joins are planned as flipped ``left`` joins by the planner
    (Spark310-style buildSide handling lives there too)."""

    def __init__(self, kind: str, left: TpuExec, right: TpuExec,
                 left_keys: List[int], right_keys: List[int],
                 schema: Schema, condition: Optional[Expression] = None,
                 conf=None):
        super().__init__([left, right], schema)
        assert kind in _KIND_MAP, kind  # cross -> nested-loop/cartesian
        if condition is not None:
            assert kind == "inner", \
                "conditioned outer joins must fall back (planner bug)"
        self.kind = kind
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.condition = CompiledFilter(condition, conf) \
            if condition is not None else None

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    @property
    def children_coalesce_goal(self):
        # build side must arrive whole; full joins also need the stream
        # side whole (unmatched-build emission happens once)
        stream_goal = RequireSingleBatch if self.kind == "full" else None
        return [stream_goal, RequireSingleBatch]

    def _build_side(self, partition: int) -> ColumnarBatch:
        from spark_rapids_tpu.execs.batching import drain_to_single_batch

        return drain_to_single_batch(self.children[1].execute(partition),
                                     self.children[1].schema)

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        left_types = list(self.children[0].schema.types)
        right_types = list(self.children[1].schema.types)

        def it():
            build = self._build_side(partition)
            if self.kind == "full":
                # unmatched-build rows are emitted exactly once, so the
                # stream side must arrive as one batch
                from spark_rapids_tpu.execs.batching import \
                    drain_to_single_batch

                stream_batches = [drain_to_single_batch(
                    self.children[0].execute(partition),
                    self.children[0].schema)]
            else:
                stream_batches = self.children[0].execute(partition)
            saw = False
            for b in stream_batches:
                if b.realized_num_rows() == 0 and saw:
                    continue
                saw = True
                from spark_rapids_tpu.memory.oom import with_oom_retry

                with TraceRange(f"HashJoinExec.{self.kind}"):
                    out, _ = with_oom_retry(
                        lambda b=b: equi_join(
                            b, build, self.left_keys,
                            self.right_keys, left_types,
                            right_types,
                            join_type=_KIND_MAP[self.kind]))
                if self.condition is not None:
                    out = self.condition(out)
                yield out
        return timed(self, it())


class BroadcastHashJoinExec(HashJoinExec):
    """Identical kernel; the build child is a BroadcastExchangeExec that
    materializes once and replays per partition
    (GpuBroadcastHashJoinExec)."""


class ShuffledHashJoinExec(HashJoinExec):
    """Both children sit below hash ShuffleExchangeExecs on the same keys,
    so partition p of each side holds co-partitioned rows
    (GpuShuffledHashJoinExec)."""


class _NestedLoopJoinBase(TpuExec):
    """Shared body of the brute-force joins: stream the left child's
    batches against a whole right-side build batch, emitting the cross
    product with any residual condition fused into the pair expansion
    (nested_loop_join kernel). Both subclasses are disabled by default at
    the planner — same OOM-risk stance as the reference
    (GpuOverrides.scala:1837-1856)."""

    def __init__(self, left: TpuExec, right: TpuExec, schema: Schema,
                 condition: Optional[Expression] = None, conf=None):
        super().__init__([left, right], schema)
        self.condition = CompiledFilter(condition, conf) \
            if condition is not None else None

    @property
    def children_coalesce_goal(self):
        return [None, RequireSingleBatch]

    def _join_batches(self, stream_it, build: ColumnarBatch):
        left_types = list(self.children[0].schema.types)
        right_types = list(self.children[1].schema.types)
        from spark_rapids_tpu.memory.oom import with_oom_retry

        saw = False
        for b in stream_it:
            if b.realized_num_rows() == 0 and saw:
                continue
            saw = True
            with TraceRange(self.name):
                if self.condition is not None and self.condition.fused:
                    out, _ = with_oom_retry(
                        lambda b=b: nested_loop_join(
                            b, build, left_types, right_types,
                            self.condition.mask,
                            self.condition.condition.references()))
                else:
                    out, _ = with_oom_retry(
                        lambda b=b: cross_join(b, build, left_types,
                                               right_types))
                    if self.condition is not None:
                        out = self.condition(out)
            yield out


class BroadcastNestedLoopJoinExec(_NestedLoopJoinBase):
    """Streams the left child's partitions against a broadcast right side
    (GpuBroadcastNestedLoopJoinExec, sql-plugin/.../execution/
    GpuBroadcastNestedLoopJoinExec.scala). Inner-with-condition and cross
    only; left keeps its partitioning."""

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.execs.batching import drain_to_single_batch

        def it():
            build = drain_to_single_batch(
                self.children[1].execute(partition),
                self.children[1].schema)
            yield from self._join_batches(
                self.children[0].execute(partition), build)
        return timed(self, it())


class CartesianProductExec(_NestedLoopJoinBase):
    """Both sides stay partitioned; the output partition grid is
    left_partitions x right_partitions, partition p reading
    (p // right_n, p % right_n) — the RDD-cartesian shape of
    GpuCartesianProductExec (org/apache/spark/sql/rapids/
    GpuCartesianProductExec.scala)."""

    @property
    def num_partitions(self) -> int:
        return (self.children[0].num_partitions *
                self.children[1].num_partitions)

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.execs.batching import drain_to_single_batch

        rn = self.children[1].num_partitions
        lp, rp = divmod(partition, rn)

        def it():
            build = drain_to_single_batch(self.children[1].execute(rp),
                                          self.children[1].schema)
            yield from self._join_batches(
                self.children[0].execute(lp), build)
        return timed(self, it())
