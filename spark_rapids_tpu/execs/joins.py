"""Join execs.

Reference: GpuHashJoin (shims/spark300/.../GpuHashJoin.scala:302-318) builds
one side, streams the other through cuDF join kernels; conditions are
post-join filters (:285-291); SMJ is replaced by shuffled hash join
(GpuSortMergeJoinExec.scala). TPU equivalents use the sort-probe equi-join
kernel (ops/join.py) — no device hash tables, XLA sorts instead.

- BroadcastHashJoinExec: build side fully materialized (whole child), probe
  side streamed per batch. Safe for inner/left/semi/anti with a right
  build; full joins need both sides whole.
- ShuffledHashJoinExec: same kernel after both sides were hash-partitioned
  by an exchange, per-partition build.
- Conditioned outer joins fall back at the planner (the kernel applies
  conditions post-join, valid only for inner/cross).
"""
from __future__ import annotations

from typing import Iterator, List, Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.execs.batching import RequireSingleBatch
from spark_rapids_tpu.expressions.base import Expression
from spark_rapids_tpu.expressions.compiler import CompiledFilter
from spark_rapids_tpu.ops.join import (cross_join, equi_join,
                                       nested_loop_join, prepare_build)
from spark_rapids_tpu.utils.tracing import TraceRange

_KIND_MAP = {"inner": "inner", "left": "left", "left_semi": "leftsemi",
             "left_anti": "leftanti", "full": "full"}


class HashJoinExec(TpuExec):
    """Build-side = children[1] (right); streams children[0] (left).
    ``right`` joins are planned as flipped ``left`` joins by the planner
    (Spark310-style buildSide handling lives there too).

    Out-of-core (SURVEY §5.7): a build side that exceeds the batch
    budget is NOT funneled into one device batch (the reference's
    RequireSingleBatch cliff, GpuCoalesceBatches.scala:91-127). Both
    sides hash-bucket by join key into spillable slices (matching rows
    share a bucket by construction) and each bucket joins independently
    at a bounded size — the sort exec's range-bucket pattern applied to
    the join build."""

    def __init__(self, kind: str, left: TpuExec, right: TpuExec,
                 left_keys: List[int], right_keys: List[int],
                 schema: Schema, condition: Optional[Expression] = None,
                 conf=None, join_budget_rows: Optional[int] = None):
        super().__init__([left, right], schema)
        assert kind in _KIND_MAP, kind  # cross -> nested-loop/cartesian
        if condition is not None:
            assert kind == "inner", \
                "conditioned outer joins must fall back (planner bug)"
        self.kind = kind
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.condition = CompiledFilter(condition, conf) \
            if condition is not None else None
        self.join_budget_rows = join_budget_rows
        # (max_span, min_density, min_rows) — AdaptiveShuffledJoinExec
        # attaches this to arm the hash->dense probe upgrade; None (the
        # default everywhere else) keeps the probe strictly hash-based
        self._dense_spec = None
        self._batch_bytes = None
        if conf is not None:
            from spark_rapids_tpu import config as cfg

            self._batch_bytes = conf.get(cfg.BATCH_SIZE_BYTES)

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    @property
    def children_coalesce_goal(self):
        # neither side needs a single batch any more: the exec stages
        # incoming batches spillably and buckets them itself
        return [None, None]

    def _budget_rows(self) -> int:
        """Rows of ONE side the in-core path may hold resident (the
        sort exec's budget formula over the build schema)."""
        if self.join_budget_rows is not None:
            return max(self.join_budget_rows, 1)
        from spark_rapids_tpu import config as cfg

        bb = self._batch_bytes if self._batch_bytes is not None \
            else cfg.BATCH_SIZE_BYTES.default
        row_bytes = max(sum(t.byte_width
                            for t in self.children[1].schema.types), 1)
        return max(bb // row_bytes, 1 << 16)

    def _stage(self, child_index: int, partition: int):
        """Drain one child into spillable chunks (staged chunks can
        leave HBM while later child batches still compute)."""
        from spark_rapids_tpu.memory import priorities
        from spark_rapids_tpu.memory.spillable import SpillableBatch

        staged: List = []
        total = 0
        for b in self.children[child_index].execute(partition):
            n = b.realized_num_rows()
            if n == 0:
                continue
            total += n
            staged.append(SpillableBatch(
                b, priorities.INPUT_FROM_SHUFFLE_PRIORITY))
        return staged, total

    @staticmethod
    def _concat_staged(staged, schema) -> ColumnarBatch:
        from contextlib import ExitStack

        from spark_rapids_tpu.memory.retry import with_retry_no_split
        from spark_rapids_tpu.ops.concat import concat_batches

        if not staged:
            return ColumnarBatch.empty(schema)
        with ExitStack() as stack:
            parts = [stack.enter_context(sb.acquired()) for sb in staged]
            merged = parts[0] if len(parts) == 1 else \
                with_retry_no_split(lambda: concat_batches(parts),
                                    tag="join.build.concat")
        for sb in staged:
            sb.close()
        return merged

    def _probe_retry(self, b: ColumnarBatch, build: ColumnarBatch,
                     left_types, right_types, tag: str, prepared=None):
        """Probe one stream batch under split-and-retry: the stream
        side halves freely for every kind except full (a full join
        emits unmatched BUILD rows once per probe call, so its single
        stream batch must stay whole). Returns one output per final
        sub-batch. ``prepared`` is the build-once/probe-many state
        shared across stream batches (constant under stream splits)."""
        from spark_rapids_tpu.memory import retry as _retry

        split = _retry.halve_batch if self.kind != "full" else None
        outs = _retry.with_retry(
            b,
            lambda bb: equi_join(bb, build, self.left_keys,
                                 self.right_keys, left_types,
                                 right_types,
                                 join_type=_KIND_MAP[self.kind],
                                 prepared=prepared)[0],
            split=split, tag=tag)
        if self.condition is not None:
            outs = [self.condition(out) for out in outs]
        return outs

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        left_types = list(self.children[0].schema.types)
        right_types = list(self.children[1].schema.types)

        def it():
            build_staged, build_total = self._stage(1, partition)
            budget = self._budget_rows()
            if build_total > budget:
                yield from self._out_of_core(partition, build_staged,
                                             build_total, budget,
                                             left_types, right_types)
                return
            build = self._concat_staged(build_staged,
                                        self.children[1].schema)
            if self.kind == "full":
                # unmatched-build rows are emitted exactly once, so the
                # stream side must arrive as one batch
                stream_staged, _n = self._stage(0, partition)
                stream_batches = [self._concat_staged(
                    stream_staged, self.children[0].schema)]
            else:
                stream_batches = self.children[0].execute(partition)
            # build-once/probe-many: hash + sort (+ bucket table with
            # the join kernel on) a single time, reused by every stream
            # batch below (None when a join key is a string column).
            # With the AQE dense hint armed, a measured-narrow key range
            # upgrades the probe to a direct slot lookup instead.
            prepared = self._dense_prepared(build, left_types,
                                            right_types)
            if prepared is None:
                prepared = prepare_build(
                    build, self.right_keys, right_types,
                    [left_types[o] for o in self.left_keys])
            saw = False
            for b in stream_batches:
                if b.realized_num_rows() == 0 and saw:
                    continue
                saw = True
                with TraceRange(f"HashJoinExec.{self.kind}"):
                    outs = self._probe_retry(b, build, left_types,
                                             right_types,
                                             tag="join.probe",
                                             prepared=prepared)
                yield from outs
        return timed(self, it())

    def _dense_prepared(self, build: ColumnarBatch, left_types,
                        right_types):
        """AQE replan: measure the build key range and, when it is
        dense, slot-sort the build for direct-lookup probing
        (ops.join.DensePreparedBuild). None whenever the shape or the
        measurement disqualifies — the caller falls through to the hash
        prepare. ``full`` is excluded: its unmatched-BUILD emission
        order depends on the build sort (hash- vs slot-sorted), and
        replans must stay bit-identical to the static plan."""
        spec = self._dense_spec
        if spec is None or self.kind == "full" \
                or len(self.right_keys) != 1:
            return None
        from spark_rapids_tpu.columnar.column import StringColumn
        from spark_rapids_tpu.ops import join as join_ops

        max_span, min_density, min_rows = spec
        col = build.columns[self.right_keys[0]]
        if isinstance(col, StringColumn):
            return None
        common = join_ops.common_key_type(
            left_types[self.left_keys[0]],
            right_types[self.right_keys[0]])
        if common is None or not common.is_integral:
            return None
        if build.realized_num_rows() < min_rows:
            return None
        kmin, kmax, n_valid = join_ops.measure_key_range(
            col, build.num_rows_device())
        if n_valid <= 0:
            return None
        span = kmax - kmin + 1
        if not 0 < span <= max_span or n_valid / span < min_density:
            return None
        prepared = join_ops.prepare_build_dense(
            build, self.right_keys, right_types,
            [left_types[o] for o in self.left_keys], kmin, span)
        if prepared is not None:
            from spark_rapids_tpu.execs import adaptive

            adaptive.record_replan("strategy_switch",
                                   "hash->dense probe")
        return prepared

    def _bucket(self, staged, keys: List[int], types, n_buckets: int,
                trace: str):
        """Hash-partition each staged chunk by join key, regrouping
        slices per bucket (slices stay spillable until their bucket
        runs). The partitioner is the exchange's own hash kernel, so
        both sides agree on bucket placement."""
        from spark_rapids_tpu.memory import priorities
        from spark_rapids_tpu.memory.spillable import SpillableBatch
        from spark_rapids_tpu.ops import partition as part_ops

        per_bucket: List[List] = [[] for _ in range(n_buckets)]
        for sb in staged:
            with sb.acquired() as b:
                with TraceRange(trace):
                    sorted_b, counts = part_ops.hash_partition(
                        b, keys, types, n_buckets)
                    slices = part_ops.slice_partitions(sorted_b, counts)
                for p, sl in enumerate(slices):
                    if sl is not None:
                        per_bucket[p].append(SpillableBatch(
                            sl, priorities.OUTPUT_FOR_SHUFFLE_PRIORITY))
            sb.close()
        return per_bucket

    def _out_of_core(self, partition: int, build_staged,
                     build_total: int, budget: int, left_types,
                     right_types) -> Iterator[ColumnarBatch]:
        """Bucket-by-bucket join at bounded resident size. Hash
        co-bucketing keeps every join kind exact: matches share a
        bucket; left/full unmatched rows surface from their own bucket,
        each build row is in exactly one bucket so full-outer emits its
        unmatched rows exactly once."""
        # 2x headroom over the mean bucket absorbs hash skew
        n_buckets = max(-(-build_total // budget) * 2, 2)
        build_buckets = self._bucket(build_staged, self.right_keys,
                                     right_types, n_buckets,
                                     "HashJoinExec.oob.build")
        stream_staged, _n = self._stage(0, partition)
        stream_buckets = self._bucket(stream_staged, self.left_keys,
                                      left_types, n_buckets,
                                      "HashJoinExec.oob.stream")
        emitted = False
        for p in range(n_buckets):
            stream_b = self._concat_staged(stream_buckets[p],
                                           self.children[0].schema)
            if stream_b.realized_num_rows() == 0 and \
                    (self.kind != "full" or not build_buckets[p]):
                for h in build_buckets[p]:
                    h.close()
                continue
            build_b = self._concat_staged(build_buckets[p],
                                          self.children[1].schema)
            with TraceRange(f"HashJoinExec.oob.{self.kind}"):
                outs = self._probe_retry(stream_b, build_b, left_types,
                                         right_types,
                                         tag="join.oob.probe")
            emitted = True
            yield from outs
        if not emitted:
            yield ColumnarBatch.empty(self.schema)


class BroadcastHashJoinExec(HashJoinExec):
    """Identical kernel; the build child is a BroadcastExchangeExec that
    materializes once and replays per partition
    (GpuBroadcastHashJoinExec)."""


class ShuffledHashJoinExec(HashJoinExec):
    """Both children sit below hash ShuffleExchangeExecs on the same keys,
    so partition p of each side holds co-partitioned rows
    (GpuShuffledHashJoinExec)."""


class _NestedLoopJoinBase(TpuExec):
    """Shared body of the brute-force joins: stream the left child's
    batches against a whole right-side build batch, emitting the cross
    product with any residual condition fused into the pair expansion
    (nested_loop_join kernel). Both subclasses are disabled by default at
    the planner — same OOM-risk stance as the reference
    (GpuOverrides.scala:1837-1856)."""

    def __init__(self, left: TpuExec, right: TpuExec, schema: Schema,
                 condition: Optional[Expression] = None, conf=None):
        super().__init__([left, right], schema)
        self.condition = CompiledFilter(condition, conf) \
            if condition is not None else None

    @property
    def children_coalesce_goal(self):
        return [None, RequireSingleBatch]

    def _join_batches(self, stream_it, build: ColumnarBatch):
        left_types = list(self.children[0].schema.types)
        right_types = list(self.children[1].schema.types)
        from spark_rapids_tpu.memory import retry as _retry

        saw = False
        for b in stream_it:
            if b.realized_num_rows() == 0 and saw:
                continue
            saw = True
            with TraceRange(self.name):
                # the pair expansion is per-stream-row, so the stream
                # batch halves freely under the retry ladder (the build
                # side stays whole — it is the broadcast)
                if self.condition is not None and self.condition.fused:
                    outs = _retry.with_retry(
                        b,
                        lambda bb: nested_loop_join(
                            bb, build, left_types, right_types,
                            self.condition.mask,
                            self.condition.condition.references())[0],
                        split=_retry.halve_batch,
                        tag="join.nestedloop")
                else:
                    outs = _retry.with_retry(
                        b,
                        lambda bb: cross_join(bb, build, left_types,
                                              right_types)[0],
                        split=_retry.halve_batch,
                        tag="join.nestedloop")
                    if self.condition is not None:
                        outs = [self.condition(o) for o in outs]
            yield from outs


class BroadcastNestedLoopJoinExec(_NestedLoopJoinBase):
    """Streams the left child's partitions against a broadcast right side
    (GpuBroadcastNestedLoopJoinExec, sql-plugin/.../execution/
    GpuBroadcastNestedLoopJoinExec.scala). Inner-with-condition and cross
    only; left keeps its partitioning."""

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.execs.batching import drain_to_single_batch

        def it():
            build = drain_to_single_batch(
                self.children[1].execute(partition),
                self.children[1].schema)
            yield from self._join_batches(
                self.children[0].execute(partition), build)
        return timed(self, it())


class CartesianProductExec(_NestedLoopJoinBase):
    """Both sides stay partitioned; the output partition grid is
    left_partitions x right_partitions, partition p reading
    (p // right_n, p % right_n) — the RDD-cartesian shape of
    GpuCartesianProductExec (org/apache/spark/sql/rapids/
    GpuCartesianProductExec.scala)."""

    @property
    def num_partitions(self) -> int:
        return (self.children[0].num_partitions *
                self.children[1].num_partitions)

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.execs.batching import drain_to_single_batch

        rn = self.children[1].num_partitions
        lp, rp = divmod(partition, rn)

        def it():
            build = drain_to_single_batch(self.children[1].execute(rp),
                                          self.children[1].schema)
            yield from self._join_batches(
                self.children[0].execute(lp), build)
        return timed(self, it())
