"""Join execs.

Reference: GpuHashJoin (shims/spark300/.../GpuHashJoin.scala:302-318) builds
one side, streams the other through cuDF join kernels; conditions are
post-join filters (:285-291); SMJ is replaced by shuffled hash join
(GpuSortMergeJoinExec.scala). TPU equivalents use the sort-probe equi-join
kernel (ops/join.py) — no device hash tables, XLA sorts instead.

- BroadcastHashJoinExec: build side fully materialized (whole child), probe
  side streamed per batch. Safe for inner/left/semi/anti with a right
  build; full joins need both sides whole.
- ShuffledHashJoinExec: same kernel after both sides were hash-partitioned
  by an exchange, per-partition build.
- Conditioned outer joins fall back at the planner (the kernel applies
  conditions post-join, valid only for inner/cross).
"""
from __future__ import annotations

from typing import Iterator, List, Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.execs.batching import RequireSingleBatch
from spark_rapids_tpu.expressions.base import Expression
from spark_rapids_tpu.expressions.compiler import CompiledFilter
from spark_rapids_tpu.ops.join import cross_join, equi_join
from spark_rapids_tpu.utils.tracing import TraceRange

_KIND_MAP = {"inner": "inner", "left": "left", "left_semi": "leftsemi",
             "left_anti": "leftanti", "full": "full"}


class HashJoinExec(TpuExec):
    """Build-side = children[1] (right); streams children[0] (left).
    ``right`` joins are planned as flipped ``left`` joins by the planner
    (Spark310-style buildSide handling lives there too)."""

    def __init__(self, kind: str, left: TpuExec, right: TpuExec,
                 left_keys: List[int], right_keys: List[int],
                 schema: Schema, condition: Optional[Expression] = None,
                 conf=None):
        super().__init__([left, right], schema)
        assert kind in _KIND_MAP or kind == "cross", kind
        if condition is not None:
            assert kind in ("inner", "cross"), \
                "conditioned outer joins must fall back (planner bug)"
        self.kind = kind
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.condition = CompiledFilter(condition, conf) \
            if condition is not None else None

    @property
    def num_partitions(self) -> int:
        return self.children[0].num_partitions

    @property
    def children_coalesce_goal(self):
        # build side must arrive whole; full joins also need the stream
        # side whole (unmatched-build emission happens once)
        stream_goal = RequireSingleBatch if self.kind == "full" else None
        return [stream_goal, RequireSingleBatch]

    def _build_side(self, partition: int) -> ColumnarBatch:
        from spark_rapids_tpu.execs.batching import drain_to_single_batch

        return drain_to_single_batch(self.children[1].execute(partition),
                                     self.children[1].schema)

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        left_types = list(self.children[0].schema.types)
        right_types = list(self.children[1].schema.types)

        def it():
            build = self._build_side(partition)
            if self.kind == "full":
                # unmatched-build rows are emitted exactly once, so the
                # stream side must arrive as one batch
                from spark_rapids_tpu.execs.batching import \
                    drain_to_single_batch

                stream_batches = [drain_to_single_batch(
                    self.children[0].execute(partition),
                    self.children[0].schema)]
            else:
                stream_batches = self.children[0].execute(partition)
            saw = False
            for b in stream_batches:
                if b.realized_num_rows() == 0 and saw:
                    continue
                saw = True
                from spark_rapids_tpu.memory.oom import with_oom_retry

                with TraceRange(f"HashJoinExec.{self.kind}"):
                    if self.kind == "cross":
                        out, _ = with_oom_retry(
                            lambda b=b: cross_join(b, build, left_types,
                                                   right_types))
                    else:
                        out, _ = with_oom_retry(
                            lambda b=b: equi_join(
                                b, build, self.left_keys,
                                self.right_keys, left_types,
                                right_types,
                                join_type=_KIND_MAP[self.kind]))
                if self.condition is not None:
                    out = self.condition(out)
                yield out
        return timed(self, it())


class BroadcastHashJoinExec(HashJoinExec):
    """Identical kernel; the build child is a BroadcastExchangeExec that
    materializes once and replays per partition
    (GpuBroadcastHashJoinExec)."""


class ShuffledHashJoinExec(HashJoinExec):
    """Both children sit below hash ShuffleExchangeExecs on the same keys,
    so partition p of each side holds co-partitioned rows
    (GpuShuffledHashJoinExec)."""
