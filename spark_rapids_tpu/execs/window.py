"""Window exec: segmented-scan window functions on device.

Reference: GpuWindowExec.scala + GpuWindowExpression.scala:738-818 map window
specs onto cuDF rolling windows. The TPU formulation is better than a
rolling-window translation: sort rows by (partition keys, order keys) once,
derive segment ids from key-change boundaries, then every window function
is a segmented scan/reduction XLA fuses into one program:

- row_number/rank/dense_rank: index arithmetic against segment starts,
- running aggregates (unboundedPreceding..currentRow): prefix sums /
  ``lax.associative_scan`` with a segment-reset combiner,
- whole-partition aggregates: ``jax.ops.segment_*`` + gather,
- bounded row frames for sum/count/avg: prefix-sum differences,
- lead/lag: shifted gather with same-segment masking.

Partition-by requires the partition's rows in one batch (the reference has
the same constraint, GpuWindowExec.scala:92); the planner coalesces to
RequireSingleBatch below this exec.
"""
from __future__ import annotations

from typing import Iterator, List, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.execs.batching import RequireSingleBatch
from spark_rapids_tpu.expressions.aggregates import (AggregateFunction,
                                                     Average, Count, First,
                                                     Last, Max, Min, Sum)
from spark_rapids_tpu.expressions.base import BoundReference, Expression
from spark_rapids_tpu.expressions.compiler import CompiledProjection
from spark_rapids_tpu.ops import sortkeys
from spark_rapids_tpu.ops.sort import sort_batch
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.plan.nodes import WindowCall
from spark_rapids_tpu.utils.tracing import TraceRange


def _neq_prev(data: jax.Array, validity, dtype: dt.DType) -> jax.Array:
    """True where row i's key differs from row i-1's (null == null)."""
    if dtype.is_floating:
        d = sortkeys.canonicalize_floats(data)
        d = jnp.where(jnp.isnan(d), jnp.zeros((), d.dtype), d)
        nan = jnp.isnan(sortkeys.canonicalize_floats(data))
        neq = (d != jnp.roll(d, 1)) | (nan != jnp.roll(nan, 1))
    else:
        neq = data != jnp.roll(data, 1)
    if validity is not None:
        v = validity
        neq = jnp.where(v & jnp.roll(v, 1), neq, v != jnp.roll(v, 1))
    return neq.at[0].set(True)


class WindowKernel:
    """The post-sort window math over raw device columns: segment
    derivation + one output column per call. Pure function of traced
    arrays, so it runs identically under the single-device exec (below)
    and inside a per-chip ``shard_map`` body
    (parallel/window_step.py) — the mesh path is the same kernel after
    an all_to_all partition-key route."""

    def __init__(self, pre_types: List[dt.DType],
                 partition_ordinals: List[int],
                 order_specs: List[SortKeySpec], calls: List[WindowCall],
                 input_ordinals: List[int]):
        self.pre_types = list(pre_types)
        self.partition_ordinals = list(partition_ordinals)
        self.order_specs = list(order_specs)
        self.calls = list(calls)
        self._input_ordinal = list(input_ordinals)

    def __call__(self, cols: List[Column], num_rows) -> List[Column]:
        """``cols``: the pre-projected columns ALREADY sorted by
        (partition keys, order keys) with padding last; ``num_rows`` a
        device scalar. Returns one column per window call."""
        cap = cols[0].capacity
        live = jnp.arange(cap, dtype=jnp.int32) < num_rows

        part_b = self._boundary(cols, self.partition_ordinals, num_rows)
        order_cols = [spec.ordinal for spec in self.order_specs]
        order_b = part_b | self._boundary(cols, order_cols, num_rows) \
            if order_cols else part_b

        seg_id = jnp.cumsum(part_b.astype(jnp.int32)) - 1
        idx = jnp.arange(cap, dtype=jnp.int32)
        seg_start = jax.ops.segment_min(idx, seg_id, num_segments=cap,
                                        indices_are_sorted=True)
        start_of_row = jnp.take(seg_start, seg_id)
        # segment end (exclusive)
        seg_end = jax.ops.segment_max(idx, seg_id, num_segments=cap,
                                      indices_are_sorted=True) + 1
        end_of_row = jnp.take(seg_end, seg_id)

        out: List[Column] = []
        for c, inp_ord in zip(self.calls, self._input_ordinal):
            out.append(self._one_call(c, cols, inp_ord, seg_id, idx,
                                      start_of_row, end_of_row, order_b,
                                      live))
        return out

    def _boundary(self, cols: List[Column], ordinals: List[int],
                  num_rows) -> jax.Array:
        cap = cols[0].capacity
        boundary = jnp.zeros(cap, dtype=bool).at[0].set(True)
        for o in ordinals:
            c = cols[o]
            boundary = boundary | _neq_prev(c.data, c.validity,
                                            self.pre_types[o])
        # first padding row opens its own segment
        is_first_pad = jnp.arange(cap, dtype=jnp.int32) == num_rows
        return boundary | is_first_pad

    # ------------------------------------------------------------------

    def _one_call(self, c: WindowCall, cols: List[Column], inp_ord: int,
                  seg_id, idx, start_of_row, end_of_row, order_b,
                  live) -> Column:
        cap = cols[0].capacity
        if c.fn == "row_number":
            data = (idx - start_of_row + 1).astype(jnp.int32)
            return Column(dt.INT32, data, None)
        if c.fn in ("rank", "dense_rank"):
            tie_id = jnp.cumsum(order_b.astype(jnp.int32)) - 1
            tie_start = jax.ops.segment_min(idx, tie_id, num_segments=cap,
                                            indices_are_sorted=True)
            if c.fn == "rank":
                data = (jnp.take(tie_start, tie_id) - start_of_row + 1)
            else:
                cs = jnp.cumsum(order_b.astype(jnp.int32))
                data = cs - jnp.take(cs, start_of_row) + 1
            return Column(dt.INT32, data.astype(jnp.int32), None)
        if isinstance(c.fn, tuple):
            kind = c.fn[0]
            off = c.offset if kind == "lead" else -c.offset
            src = idx + off
            ok = (src >= 0) & (src < cap)
            src_c = jnp.clip(src, 0, cap - 1)
            same = jnp.take(seg_id, src_c) == seg_id
            ok = ok & same & jnp.take(live, src_c)
            inp = cols[inp_ord]
            data = jnp.take(inp.data, src_c)
            src_valid = jnp.take(inp.validity, src_c) \
                if inp.validity is not None else None
            if c.default is not None:
                fill = jnp.asarray(c.default, dtype=data.dtype)
                data = jnp.where(ok, data, fill)
                # out-of-frame slots take the (non-null) default
                valid = None if src_valid is None else \
                    jnp.where(ok, src_valid, True)
            else:
                valid = ok if src_valid is None else (ok & src_valid)
            return inp._like(data, valid)
        assert isinstance(c.fn, AggregateFunction)
        return self._window_agg(c, cols, inp_ord, seg_id, idx,
                                start_of_row, end_of_row, live)

    def _range_bounds(self, cols: List[Column], seg_id, start_of_row,
                      end_of_row, frame, live):
        """Per-row [lo, hi] row-index bounds of a RANGE frame over the
        single ascending order key. Null keys sort first and are all
        'equal': a null row's frame is exactly the null run."""
        okey_ord = self.order_specs[0].ordinal
        kcol = cols[okey_ord]
        cap = kcol.capacity
        key = kcol.data
        kvalid = (kcol.validity if kcol.validity is not None
                  else jnp.ones(cap, dtype=bool)) & live
        if self.pre_types[okey_ord].is_floating:
            key = sortkeys.canonicalize_floats(key)
        lo_arr = start_of_row if frame.lower is None else \
            _range_lower_upper_bound(seg_id, kvalid, key, seg_id,
                                     key + frame.lower, cap, upper=False)
        hi_arr = (end_of_row - 1) if frame.upper is None else \
            _range_lower_upper_bound(seg_id, kvalid, key, seg_id,
                                     key + frame.upper, cap,
                                     upper=True) - 1
        if frame.lower is not None:
            lo_arr = jnp.maximum(lo_arr, start_of_row)
        if frame.upper is not None:
            hi_arr = jnp.minimum(hi_arr, end_of_row - 1)
        # null-key rows: value offsets are undefined over null, so
        # BOUNDED sides clamp to the null run (null peers); UNBOUNDED
        # sides stay positional (partition start / end), like Spark
        invalid_live = (~kvalid) & live
        ps_null = jnp.cumsum(invalid_live.astype(jnp.int32))
        hi_null = jnp.take(ps_null, jnp.clip(end_of_row - 1, 0, cap - 1))
        lo_null = jnp.where(
            start_of_row > 0,
            jnp.take(ps_null, jnp.clip(start_of_row - 1, 0, cap - 1)), 0)
        nulls_in_seg = hi_null - lo_null
        # nulls-first: the null run always starts at the segment start,
        # so the lower bound is start_of_row for null rows either way
        lo_arr = jnp.where(kvalid, lo_arr, start_of_row)
        if frame.upper is not None:
            hi_arr = jnp.where(kvalid, hi_arr,
                               start_of_row + nulls_in_seg - 1)
        return lo_arr, hi_arr

    def _window_agg(self, c: WindowCall, cols: List[Column],
                    inp_ord: int, seg_id, idx, start_of_row, end_of_row,
                    live) -> Column:
        fn = c.fn
        cap = cols[0].capacity
        frame = c.frame
        if isinstance(fn, Count) and fn.input is None:
            vals = jnp.ones(cap, dtype=jnp.int64)
            valid_in = live
        else:
            inp = cols[inp_ord]
            vals = inp.data
            valid_in = live if inp.validity is None else \
                (live & inp.validity)

        if frame.kind == "range":
            lo_arr, hi_arr = self._range_bounds(cols, seg_id,
                                                start_of_row, end_of_row,
                                                frame, live)
        else:
            lo_arr = start_of_row if frame.lower is None else \
                jnp.maximum(idx + frame.lower, start_of_row)
            hi_arr = (end_of_row - 1) if frame.upper is None else \
                jnp.minimum(idx + frame.upper, end_of_row - 1)

        def prefix_range_sum(x):
            """sum over [frame_start, frame_end] rows per row."""
            ps = jnp.cumsum(x)
            empty = hi_arr < lo_arr  # e.g. rows (-2,-1) at segment start
            upper = jnp.take(ps, jnp.clip(hi_arr, 0, cap - 1))
            lower = jnp.where(
                lo_arr > 0,
                jnp.take(ps, jnp.clip(lo_arr - 1, 0, cap - 1)),
                jnp.zeros((), ps.dtype))
            return jnp.where(empty, jnp.zeros((), ps.dtype), upper - lower)

        if isinstance(fn, (First, Last)):
            # ignoreNulls=False: the boundary row's value as-is (its own
            # validity), NULL when the frame is empty
            pos = lo_arr if isinstance(fn, First) else hi_arr
            posc = jnp.clip(pos, 0, cap - 1)
            inp = cols[inp_ord]
            data = jnp.take(inp.data, posc)
            src_valid = jnp.take(inp.validity, posc) \
                if inp.validity is not None else jnp.ones(cap, dtype=bool)
            ok = (hi_arr >= lo_arr) & src_valid
            return inp._like(data, ok)

        if isinstance(fn, (Sum, Average, Count)):
            acc_t = jnp.int64 if fn.dtype.is_integral else jnp.float64
            x = jnp.where(valid_in, vals, 0).astype(acc_t)
            total = prefix_range_sum(x)
            cnt = prefix_range_sum(valid_in.astype(jnp.int64))
            if isinstance(fn, Count):
                return Column(dt.INT64, cnt, None)
            if isinstance(fn, Average):
                data = total.astype(jnp.float64) / \
                    jnp.maximum(cnt, 1).astype(jnp.float64)
                return Column(dt.FLOAT64, data, cnt > 0)
            return Column(fn.dtype, total.astype(fn.dtype.kernel_dtype),
                          cnt > 0)

        if isinstance(fn, (Min, Max)):
            is_min = isinstance(fn, Min)
            if frame.kind == "range":
                raise NotImplementedError(
                    "range-framed min/max windows fall back to CPU")
            if frame.lower is None and frame.upper == 0:
                data, cnt = _running_minmax(vals, valid_in, seg_id, is_min)
                return Column(fn.dtype, data.astype(fn.dtype.kernel_dtype),
                              cnt > 0)
            if frame.lower is None and frame.upper is None:
                seg_fn = jax.ops.segment_min if is_min else \
                    jax.ops.segment_max
                sentinel = _sentinel(vals.dtype, is_min)
                x = jnp.where(valid_in, vals, sentinel)
                per_seg = seg_fn(x, seg_id, num_segments=cap,
                                 indices_are_sorted=True)
                cnt = jax.ops.segment_sum(valid_in.astype(jnp.int32),
                                          seg_id, num_segments=cap,
                                          indices_are_sorted=True)
                data = jnp.take(per_seg, seg_id)
                return Column(fn.dtype, data.astype(fn.dtype.kernel_dtype),
                              jnp.take(cnt, seg_id) > 0)
            raise NotImplementedError(
                "bounded min/max window frames fall back to CPU")
        raise NotImplementedError(f"window aggregate {type(fn).__name__}")


def window_pre_projection(child_types: List[dt.DType],
                          calls: List[WindowCall], conf
                          ) -> Tuple[CompiledProjection, List[dt.DType],
                                     List[int]]:
    """Child columns + each call's input expression; returns the
    projection, its output types, and each call's input ordinal (-1 for
    input-free calls like row_number/count(*))."""
    exprs: List[Expression] = [
        BoundReference(i, t) for i, t in enumerate(child_types)]
    input_ordinals: List[int] = []
    for c in calls:
        if isinstance(c.fn, AggregateFunction):
            inp = c.fn.input
        elif isinstance(c.fn, tuple):
            inp = c.fn[1]
        else:
            inp = None
        if inp is None:
            input_ordinals.append(-1)
        else:
            input_ordinals.append(len(exprs))
            exprs.append(inp)
    return (CompiledProjection(exprs, conf), [e.dtype for e in exprs],
            input_ordinals)


class WindowExec(TpuExec):
    """Out-of-core (SURVEY §5.7): a partitioned-window input exceeding
    the batch budget hash-buckets by PARTITION BY keys (every window
    group lands wholly in one bucket by construction) and runs the
    kernel bucket-by-bucket at a bounded resident size — the join
    build's treatment applied to windows. Un-partitioned windows have
    no such split and keep the single-batch requirement (the reference
    has the same constraint, GpuWindowExec.scala:92)."""

    def __init__(self, partition_ordinals: List[int],
                 order_specs: List[SortKeySpec], calls: List[WindowCall],
                 child: TpuExec, schema: Schema, conf=None,
                 window_budget_rows=None):
        super().__init__([child], schema)
        self.partition_ordinals = partition_ordinals
        self.order_specs = order_specs
        self.calls = calls
        self.conf = conf
        self.window_budget_rows = window_budget_rows
        self.n_child = len(child.schema)
        self.pre_proj, self.pre_types, self._input_ordinal = \
            window_pre_projection(list(child.schema.types), calls, conf)
        self.kernel = WindowKernel(self.pre_types, partition_ordinals,
                                   order_specs, calls,
                                   self._input_ordinal)

    @property
    def children_coalesce_goal(self):
        return [None if self.partition_ordinals else RequireSingleBatch]

    def _budget_rows(self) -> int:
        if self.window_budget_rows is not None:
            return max(self.window_budget_rows, 1)
        from spark_rapids_tpu import config as cfg

        bb = cfg.BATCH_SIZE_BYTES.default if self.conf is None \
            else self.conf.get(cfg.BATCH_SIZE_BYTES)
        row_bytes = max(sum(t.byte_width for t in self.pre_types), 1)
        return max(bb // row_bytes, 1 << 16)

    # ------------------------------------------------------------------

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            from spark_rapids_tpu.memory import priorities
            from spark_rapids_tpu.memory.spillable import SpillableBatch

            staged: List[SpillableBatch] = []
            total = 0
            for b in self.children[0].execute(partition):
                n = b.realized_num_rows()
                if n == 0:
                    continue
                total += n
                staged.append(SpillableBatch(
                    b, priorities.INPUT_FROM_SHUFFLE_PRIORITY))
            if not staged:
                yield ColumnarBatch.empty(self.schema)
                return
            budget = self._budget_rows()
            if total > budget and self.partition_ordinals:
                yield from self._out_of_core(staged, total, budget)
                return
            b = self._concat_staged(staged)
            with TraceRange("WindowExec"):
                yield self._run(b)
        return timed(self, it())

    @staticmethod
    def _concat_staged(staged) -> ColumnarBatch:
        from contextlib import ExitStack

        from spark_rapids_tpu.memory.retry import with_retry_no_split
        from spark_rapids_tpu.ops.concat import concat_batches

        with ExitStack() as stack:
            parts = [stack.enter_context(sb.acquired()) for sb in staged]
            merged = parts[0] if len(parts) == 1 else \
                with_retry_no_split(lambda: concat_batches(parts),
                                    tag="window.concat")
        for sb in staged:
            sb.close()
        return merged

    def _out_of_core(self, staged, total: int,
                     budget: int) -> Iterator[ColumnarBatch]:
        """Hash-bucket by PARTITION BY keys, window each bucket
        independently (groups never span buckets, so results are
        exact; output order is per-bucket, same contract as the
        post-shuffle window)."""
        from spark_rapids_tpu.memory import priorities
        from spark_rapids_tpu.memory.retry import with_retry_no_split
        from spark_rapids_tpu.memory.spillable import SpillableBatch
        from spark_rapids_tpu.ops import partition as part_ops

        n_buckets = max(-(-total // budget) * 2, 2)
        child_types = list(self.children[0].schema.types)
        per_bucket: List[List[SpillableBatch]] = \
            [[] for _ in range(n_buckets)]
        for sb in staged:
            with sb.acquired() as b:
                with TraceRange("WindowExec.oob.partition"):
                    sorted_b, counts = part_ops.hash_partition(
                        b, list(self.partition_ordinals), child_types,
                        n_buckets)
                    slices = part_ops.slice_partitions(sorted_b, counts)
                for p, sl in enumerate(slices):
                    if sl is not None:
                        per_bucket[p].append(SpillableBatch(
                            sl, priorities.OUTPUT_FOR_SHUFFLE_PRIORITY))
            sb.close()
        emitted = False
        for p in range(n_buckets):
            if not per_bucket[p]:
                continue
            b = self._concat_staged(per_bucket[p])
            if b.realized_num_rows() == 0:
                continue
            with TraceRange("WindowExec.oob.bucket"):
                # a bucket holds whole PARTITION BY groups; halving by
                # rows would split a group, so no split rung here
                out = with_retry_no_split(lambda b=b: self._run(b),
                                          tag="window.bucket")
            emitted = True
            yield out
        if not emitted:
            yield ColumnarBatch.empty(self.schema)

    def _run(self, batch: ColumnarBatch) -> ColumnarBatch:
        ext = self.pre_proj(batch)
        sort_specs = [SortKeySpec(o, True, True)
                      for o in self.partition_ordinals] + self.order_specs
        s = sort_batch(ext, sort_specs, self.pre_types) if sort_specs \
            else ext
        call_cols = self.kernel(list(s.columns), s.num_rows_device())
        out_cols = list(s.columns[:self.n_child]) + call_cols
        return ColumnarBatch(out_cols, s.num_rows)


def _range_lower_upper_bound(seg_id, kvalid, key, tseg, tkey, cap: int,
                             upper: bool):
    """Vectorized binary search over rows ordered by (segment, nulls
    first, key): per row, the first index whose tuple is >= (>) the
    target. O(log n) unrolled steps of full-width gathers — range frames
    trade bandwidth for exactness (cuDF's range windows do a comparable
    per-row bounds search)."""
    import math

    lo = jnp.zeros(cap, dtype=jnp.int32)
    hi = jnp.full(cap, cap, dtype=jnp.int32)
    for _ in range(max(int(math.ceil(math.log2(max(cap, 2)))), 1) + 1):
        mid = (lo + hi) // 2
        midc = jnp.clip(mid, 0, cap - 1)
        sm = jnp.take(seg_id, midc)
        vm = jnp.take(kvalid, midc)
        km = jnp.take(key, midc)
        # tuple (sm, vm, km) vs (tseg, True, tkey); invalid (null) rows
        # sort first within a segment
        if upper:
            key_le = km <= tkey
        else:
            key_le = km < tkey
        less = (sm < tseg) | ((sm == tseg) & (~vm | (vm & key_le)))
        less = less & (mid < hi)  # converged lanes stay put
        lo = jnp.where(less, mid + 1, lo)
        hi = jnp.where(less, hi, mid)
    return lo


def _sentinel(dtype, is_min: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf if is_min else -jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.asarray(info.max if is_min else info.min, dtype)


def _running_minmax(vals, valid, seg_id, is_min: bool
                    ) -> Tuple[jax.Array, jax.Array]:
    """Segmented running min/max via associative scan: the combiner resets
    when the segment changes."""
    sentinel = _sentinel(vals.dtype, is_min)
    x = jnp.where(valid, vals, sentinel)

    def combine(a, b):
        a_seg, a_val, a_cnt = a
        b_seg, b_val, b_cnt = b
        best = jnp.minimum(a_val, b_val) if is_min \
            else jnp.maximum(a_val, b_val)
        same = a_seg == b_seg
        return (b_seg,
                jnp.where(same, best, b_val),
                jnp.where(same, a_cnt + b_cnt, b_cnt))

    seg, out, cnt = jax.lax.associative_scan(
        combine, (seg_id, x, valid.astype(jnp.int32)))
    return out, cnt
