"""Batch coalescing: the CoalesceGoal lattice and the coalesce exec.

Reference: GpuCoalesceBatches.scala — ``RequireSingleBatch`` vs
``TargetSize`` with max/satisfies lattice ops (:91-127) and an iterator that
concatenates input batches up to the goal (:129-490). TPU-specific twist:
concatenation lands on *bucketed* capacities (ops/buckets.py) so XLA
recompiles O(log n) distinct shapes, not one per batch size.
"""
from __future__ import annotations

from typing import Iterator, List, Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.ops.concat import concat_batches


class CoalesceGoal:
    def satisfies(self, other: "CoalesceGoal") -> bool:
        raise NotImplementedError


class _RequireSingleBatch(CoalesceGoal):
    """The whole partition must arrive as one batch (global sort, build
    side of a hash join...). GpuCoalesceBatches.scala:91-103."""

    def satisfies(self, other: CoalesceGoal) -> bool:
        return True  # single batch satisfies any size target

    def __repr__(self):
        return "RequireSingleBatch"


RequireSingleBatch = _RequireSingleBatch()


class TargetSize(CoalesceGoal):
    def __init__(self, target_bytes: int):
        self.target_bytes = target_bytes

    def satisfies(self, other: CoalesceGoal) -> bool:
        if other is RequireSingleBatch or isinstance(other,
                                                     _RequireSingleBatch):
            return False
        return self.target_bytes >= other.target_bytes  # type: ignore

    def __repr__(self):
        return f"TargetSize({self.target_bytes})"


def max_goal(a: Optional[CoalesceGoal],
             b: Optional[CoalesceGoal]) -> Optional[CoalesceGoal]:
    """Least upper bound (GpuCoalesceBatches.scala:105-127)."""
    if a is None:
        return b
    if b is None:
        return a
    if isinstance(a, _RequireSingleBatch) or isinstance(b,
                                                        _RequireSingleBatch):
        return RequireSingleBatch
    return a if a.target_bytes >= b.target_bytes else b


def drain_to_single_batch(it: Iterator[ColumnarBatch], schema
                          ) -> ColumnarBatch:
    """Drain a child iterator into exactly one batch (the in-place
    RequireSingleBatch: global sort, join build side, window input)."""
    batches = [b for b in it if b.realized_num_rows() > 0]
    if not batches:
        return ColumnarBatch.empty(schema)
    if len(batches) == 1:
        return batches[0]
    from spark_rapids_tpu.memory.retry import with_retry_no_split

    # single-batch contract: only the spill rungs apply (halving the
    # inputs cannot shrink the concatenated result)
    return with_retry_no_split(lambda: concat_batches(batches),
                               tag="coalesce.concat")


def coalesce_iterator(it: Iterator[ColumnarBatch], goal: CoalesceGoal
                      ) -> Iterator[ColumnarBatch]:
    """Concatenate incoming batches until the goal is met
    (AbstractGpuCoalesceIterator, GpuCoalesceBatches.scala:129)."""
    if isinstance(goal, _RequireSingleBatch):
        batches = [b for b in it]
        if batches:
            yield concat_batches(batches)
        return
    assert isinstance(goal, TargetSize)
    pending: List[ColumnarBatch] = []
    pending_bytes = 0
    for b in it:
        sz = b.device_memory_size()
        if pending and pending_bytes + sz > goal.target_bytes:
            yield concat_batches(pending)
            pending, pending_bytes = [], 0
        pending.append(b)
        pending_bytes += sz
    if pending:
        yield concat_batches(pending)


class CoalesceBatchesExec(TpuExec):
    def __init__(self, child: TpuExec, goal: CoalesceGoal):
        super().__init__([child], child.schema)
        self.goal = goal

    @property
    def coalesce_after(self):
        return self.goal

    def execute(self, partition: int = 0):
        return timed(self,
                     coalesce_iterator(self.children[0].execute(partition),
                                       self.goal))
