"""Sort exec (GpuSortExec.scala:50, GpuColumnarBatchSorter :104).

Local sort: per-batch device lexsort. Global sort within one partition:
coalesce-to-one + one device lexsort while the data fits the sort
budget; beyond it, a RANGE-BUCKETED OUT-OF-CORE path (SURVEY §5.7's
mandate not to replicate the RequireSingleBatch cliff):

  1. stage incoming batches as spillable chunks (catalog-managed, so
     they can leave HBM under pressure),
  2. sample range bounds across the staged chunks host-side (the
     reference's CPU-sampled-bounds design, GpuRangePartitioner.scala:
     42-95) with enough buckets that each fits the budget,
  3. range-partition each chunk on device, regrouping slices per bucket
     (slices stay spillable until their bucket runs),
  4. concat + device-sort one bucket at a time, yielding buckets in
     bound order — the output stream is globally ordered without any
     single resident batch exceeding the budget.

TPU note: buckets are sorted independently (one variadic-sort HLO per
bucket at a bounded shape) — there is no k-way merge kernel to keep
resident; order across buckets comes from the range partitioning.
"""
from __future__ import annotations

from typing import Iterator, List, Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.ops.sort import sort_batch
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.utils.tracing import TraceRange


class SortExec(TpuExec):
    def __init__(self, specs: List[SortKeySpec], child: TpuExec,
                 global_sort: bool = True,
                 batch_bytes: Optional[int] = None,
                 sort_budget_rows: Optional[int] = None):
        super().__init__([child], child.schema)
        self.specs = specs
        self.global_sort = global_sort
        self.batch_bytes = batch_bytes
        self.sort_budget_rows = sort_budget_rows

    def _budget_rows(self) -> int:
        """THE budget formula (planner passes only the configured batch
        bytes; tests may pin rows directly)."""
        if self.sort_budget_rows is not None:
            return max(self.sort_budget_rows, 1)
        from spark_rapids_tpu import config as cfg

        bb = self.batch_bytes if self.batch_bytes is not None \
            else cfg.BATCH_SIZE_BYTES.default
        row_bytes = max(sum(t.byte_width for t in self.schema.types), 1)
        return max(bb // row_bytes, 1 << 16)

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        types = list(self.schema.types)

        def it():
            if not self.global_sort:
                for b in self.children[0].execute(partition):
                    with TraceRange("SortExec.local"):
                        yield sort_batch(b, self.specs, types)
                return
            from spark_rapids_tpu.memory import priorities
            from spark_rapids_tpu.memory.retry import with_retry_no_split
            from spark_rapids_tpu.memory.spillable import SpillableBatch

            budget = self._budget_rows()
            # stage AS batches arrive: everything drained so far can
            # spill while later child batches still compute — the input
            # is never pinned whole in HBM. Counts stay LAZY while
            # staging (defer_count): when the whole input provably fits
            # the in-core budget by CAPACITY (capacity >= rows), the
            # single-batch fast path sorts without any host sync at
            # all, and the multi-batch path realizes every count in the
            # one batched get concat already pays — the per-batch
            # realize here used to cost one ~105 ms round trip each
            caps = 0
            staged: List[SpillableBatch] = []
            for b in self.children[0].execute(partition):
                caps += b.capacity
                staged.append(SpillableBatch(
                    b, priorities.INPUT_FROM_SHUFFLE_PRIORITY,
                    defer_count=True))
            if not staged:
                yield ColumnarBatch.empty(self.schema)
                return
            def sort_in_core(handles):
                from contextlib import ExitStack

                from spark_rapids_tpu.ops.concat import concat_batches

                with ExitStack() as stack:
                    parts = [stack.enter_context(sb.acquired())
                             for sb in handles]
                    with TraceRange("SortExec.global"):
                        # output contract is ONE globally sorted batch:
                        # spill rungs only (sorted halves would need a
                        # merge kernel the TPU path deliberately lacks)
                        merged = parts[0] if len(parts) == 1 else \
                            with_retry_no_split(
                                lambda: concat_batches(parts),
                                tag="sort.concat")
                        out = with_retry_no_split(
                            lambda: sort_batch(merged, self.specs,
                                               types),
                            tag="sort.sort")
                for sb in handles:
                    sb.close()
                return out

            if caps <= budget:
                yield sort_in_core(staged)
                return
            # above the capacity bound: realize every count in ONE
            # batched transfer, drop empties, and re-check the real
            # total (capacity over-estimates rows)
            SpillableBatch.realize_counts(staged)
            total = 0
            live: List[SpillableBatch] = []
            for sb in staged:
                n = sb.num_rows
                if n == 0:
                    sb.close()
                    continue
                total += n
                live.append(sb)
            staged = live
            if not staged:
                yield ColumnarBatch.empty(self.schema)
                return
            if total <= budget:
                yield sort_in_core(staged)
                return
            yield from self._out_of_core(staged, total, budget, types)

        return timed(self, it())

    def _out_of_core(self, staged, total: int, budget: int,
                     types) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.memory import priorities
        from spark_rapids_tpu.memory.retry import with_retry_no_split
        from spark_rapids_tpu.memory.spillable import SpillableBatch
        from spark_rapids_tpu.ops import partition as part_ops
        from spark_rapids_tpu.ops.concat import concat_batches

        # 2x margin absorbs sampling error; heavy key skew can still
        # overfill one bucket — the oom-retry spill path covers that
        n_buckets = max(-(-total // budget) * 2, 2)
        if len(self.specs) > 1:
            bounds = part_ops.sample_range_bounds_rows(
                staged, self.specs, types, n_buckets)
        else:
            bounds = part_ops.sample_range_bounds_multi(
                staged, self.specs, types, n_buckets)
        per_bucket: List[List[SpillableBatch]] = \
            [[] for _ in range(n_buckets)]
        for sb in staged:
            with sb.acquired() as b:
                with TraceRange("SortExec.oob.partition"):
                    if len(self.specs) > 1:
                        sorted_b, counts = part_ops.range_partition_multi(
                            b, self.specs, types, bounds, n_buckets)
                    else:
                        sorted_b, counts = part_ops.range_partition(
                            b, self.specs, types, bounds, n_buckets)
                    slices = part_ops.slice_partitions(sorted_b, counts)
                for p, sl in enumerate(slices):
                    if sl is not None:
                        per_bucket[p].append(SpillableBatch(
                            sl, priorities.OUTPUT_FOR_SHUFFLE_PRIORITY))
            sb.close()
        from contextlib import ExitStack

        for p in range(n_buckets):
            handles = per_bucket[p]
            if not handles:
                continue
            # handles stay ACQUIRED through concat+sort: releasing
            # early would let the oom-retry spill copy them to host
            # while `parts` still pins the device arrays (no memory
            # actually freed, catalog accounting corrupted)
            with ExitStack() as stack:
                parts = [stack.enter_context(h.acquired())
                         for h in handles]
                with TraceRange("SortExec.oob.bucket"):
                    merged = parts[0] if len(parts) == 1 else \
                        with_retry_no_split(
                            lambda: concat_batches(parts),
                            tag="sort.oob.concat")
                    out = with_retry_no_split(
                        lambda: sort_batch(merged, self.specs, types),
                        tag="sort.oob.sort")
            for h in handles:
                h.close()
            yield out
