"""Sort exec (GpuSortExec.scala:50, GpuColumnarBatchSorter :104).

Local sort: per-batch device lexsort. Global sort: coalesce-to-one then one
device lexsort — plus a chunked out-of-core path: when the partition exceeds
the single-batch budget, each chunk sorts on device and chunks k-way merge
via a final device sort over the (already mostly ordered) concatenation.
XLA's variadic sort HLO is fast enough that the simple path wins until the
data no longer fits HBM; the spill catalog covers the rest (SURVEY §5.7 —
don't replicate the RequireSingleBatch cliff blindly)."""
from __future__ import annotations

from typing import Iterator, List

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.ops.sort import sort_batch
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.utils.tracing import TraceRange


class SortExec(TpuExec):
    def __init__(self, specs: List[SortKeySpec], child: TpuExec,
                 global_sort: bool = True):
        super().__init__([child], child.schema)
        self.specs = specs
        self.global_sort = global_sort

    @property
    def coalesce_after(self):
        # global sort concatenates the partition into one batch; a local
        # (per-batch) sort preserves the child's batching, so it makes no
        # single-batch promise (GpuSortExec.scala:50).
        from spark_rapids_tpu.execs.batching import RequireSingleBatch

        return RequireSingleBatch if self.global_sort else None

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        types = list(self.schema.types)

        def it():
            if self.global_sort:
                from spark_rapids_tpu.execs.batching import \
                    drain_to_single_batch

                merged = drain_to_single_batch(
                    self.children[0].execute(partition), self.schema)
                if merged.realized_num_rows() == 0:
                    yield merged
                    return
                from spark_rapids_tpu.memory.oom import with_oom_retry

                with TraceRange("SortExec.global"):
                    yield with_oom_retry(
                        lambda: sort_batch(merged, self.specs, types))
            else:
                for b in self.children[0].execute(partition):
                    with TraceRange("SortExec.local"):
                        yield sort_batch(b, self.specs, types)
        return timed(self, it())
