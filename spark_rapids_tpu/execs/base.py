"""Exec base: streaming columnar operators.

The reference's GpuExec contract (GpuExec.scala:65-137):
``doExecuteColumnar(): RDD[ColumnarBatch]`` + metrics + batching goals.
Here: ``execute(partition) -> Iterator[ColumnarBatch]`` over
``num_partitions`` logical partitions (the single-process analogue of
Spark's task partitions; the distributed runtime maps partitions onto mesh
devices).
"""
from __future__ import annotations

import threading
from spark_rapids_tpu.utils import lockorder
import time
from typing import Dict, Iterator, List, Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema


class Metrics:
    """num_output_rows / num_output_batches / op_time_ns per exec
    (GpuMetricNames, GpuExec.scala:27-55). ``op_time_ns`` is self time —
    like the reference's totalTime it excludes time spent pulling child
    batches; ``pipeline_time_ns`` is inclusive.

    Row counts are recorded as DEVICE scalars and realized lazily when
    read: metric accounting must not inject a host sync per exec per
    batch into the pipeline (each sync is a full round trip behind a
    remote device attachment)."""

    def __init__(self):
        self._pending_rows = []
        self._rows = 0
        self.num_output_batches = 0
        self.op_time_ns = 0
        self.pipeline_time_ns = 0
        self._lock = lockorder.make_lock("execs.base.metrics")

    def record(self, batch: ColumnarBatch, elapsed_ns: int = 0,
               child_ns: int = 0):
        n = batch.num_rows
        with self._lock:  # partitions run on concurrent task threads
            self.num_output_batches += 1
            if isinstance(n, int):
                self._rows += n
            else:
                self._pending_rows.append(n)
            self.pipeline_time_ns += elapsed_ns
            self.op_time_ns += max(elapsed_ns - child_ns, 0)

    @property
    def num_output_rows(self) -> int:
        if self._pending_rows:
            import jax

            # ONE transfer for all pending scalars — per-batch
            # device_get here would re-serialize the round trips the
            # deferral exists to avoid
            realized = jax.device_get(self._pending_rows)
            self._rows += int(sum(int(n) for n in realized))
            self._pending_rows.clear()
        return self._rows

    # exec trees ship to remote executors as task closures (the cluster
    # runtime's map tasks, like Spark serializing RDD lineage); locks and
    # unrealized device scalars stay behind
    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_lock", None)
        state["_rows"] = self.num_output_rows  # realizes pending
        state["_pending_rows"] = []
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = lockorder.make_lock("execs.base.metrics")


class TpuExec:
    """Base physical operator."""

    def __init__(self, children: List["TpuExec"], schema: Schema):
        self.children = children
        self.schema = schema
        self.metrics = Metrics()

    @property
    def name(self) -> str:
        return type(self).__name__

    @property
    def num_partitions(self) -> int:
        if self.children:
            return self.children[0].num_partitions
        return 1

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        raise NotImplementedError

    # -- batching contract (GpuExec.scala:71-86) --------------------------

    @property
    def coalesce_after(self) -> Optional[object]:
        """Goal describing batches this exec OUTPUTS (None = don't care)."""
        return None

    @property
    def children_coalesce_goal(self) -> List[Optional[object]]:
        """Goal each child's input must satisfy."""
        return [None] * len(self.children)

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.name]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def all_metrics(self) -> Dict[str, Metrics]:
        out = {self.name: self.metrics}
        for c in self.children:
            out.update(c.all_metrics())
        return out


def timed(owner, it: Iterator[ColumnarBatch]
          ) -> Iterator[ColumnarBatch]:
    """Wrap an exec's output iterator with metric recording. ``owner`` is
    the TpuExec (self time = pull time minus children's pipeline time); a
    bare Metrics is accepted for exec-less iterators."""
    from spark_rapids_tpu.utils import dispatch as _disp

    if isinstance(owner, Metrics):
        metrics, children = owner, ()
        stage = None
    else:
        metrics, children = owner.metrics, owner.children
        # stage-cutting label (plan/optimizer.cut_stages): dispatches
        # issued while this exec's iterator advances attribute to its
        # pipeline stage in the telemetry
        stage = getattr(owner, "_stage_label", None)
    while True:
        child0 = sum(c.metrics.pipeline_time_ns for c in children)
        t0 = time.perf_counter_ns()
        tok = _disp.enter_stage(stage)
        try:
            batch = next(it)
        except StopIteration:
            return
        finally:
            _disp.exit_stage(tok)
        elapsed = time.perf_counter_ns() - t0
        child_ns = sum(c.metrics.pipeline_time_ns
                       for c in children) - child0
        metrics.record(batch, elapsed, child_ns)
        yield batch


def run_partitions(n_partitions: int, fn, task_threads: int = 4):
    """Drive ``fn(partition) -> result`` over all partitions on a worker
    pool, returning results in partition order. The reference's model:
    Spark schedules many concurrent tasks per executor while GpuSemaphore
    bounds how many touch the device (GpuSemaphore.scala:27-161,
    RapidsConf.scala:340) — here the pool is the task-slot analogue and
    execs acquire the shared TpuSemaphore at device entry, so host I/O of
    one partition overlaps device compute of another. ``task_threads<=1``
    or a single partition degrades to the serial loop (no thread hop)."""
    if n_partitions <= 1 or task_threads <= 1:
        return [fn(p) for p in range(n_partitions)]
    from concurrent.futures import ThreadPoolExecutor

    from spark_rapids_tpu.memory.catalog import (current_buffer_owner,
                                                 set_buffer_owner)
    from spark_rapids_tpu.service.batching import microbatch as _mb
    from spark_rapids_tpu.utils import dispatch as _disp

    # propagate the caller's buffer-owner tag, dispatch query tag and
    # micro-batching slice context (all thread-local) onto the pool
    # threads: a query-service slice that fans out here must have every
    # batch the tasks register and every dispatch they issue attributed
    # to its query — and its stage programs must stay coalescible — or
    # cancel/deadline cleanup, stalled-query spill demotion,
    # ServiceStats per-query dispatch counts and cross-query
    # micro-batching would all miss pool work
    owner = current_buffer_owner()
    qid = _disp.current_query()
    bctx = _mb.current()
    run = fn
    if owner is not None or qid is not None or bctx is not None:
        def run(p, _fn=fn, _owner=owner, _qid=qid, _bctx=bctx):
            prev = set_buffer_owner(_owner) if _owner is not None \
                else None
            qtok = _disp.enter_query(_qid)
            btok = None
            if _bctx is not None:
                btok = _mb.enter_slice(_bctx.batcher, _bctx.query_id,
                                       _bctx.multi)
            try:
                return _fn(p)
            finally:
                if _bctx is not None:
                    _mb.exit_slice(btok)
                _disp.exit_query(qtok)
                if _owner is not None:
                    set_buffer_owner(prev)

    with ThreadPoolExecutor(max_workers=min(task_threads, n_partitions),
                            thread_name_prefix="tpu-task") as pool:
        return list(pool.map(run, range(n_partitions)))


def collect(exec_: TpuExec, conf=None):
    """Run all partitions and return one pandas DataFrame — the
    GpuColumnarToRowExec boundary (GpuColumnarToRowExec.scala:111).
    Partitions run concurrently on the task pool (see run_partitions);
    output row order is by partition then batch, same as the serial
    loop."""
    import pandas as pd

    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.utils import dispatch as _disp

    threads = (conf.get(cfg.TASK_THREADS) if conf is not None
               else cfg.TASK_THREADS.default)

    def one(p: int):
        # to_pandas fetches data + (possibly lazy) row count in ONE
        # device_get; a realized_num_rows() pre-filter here would pay a
        # separate round trip per batch just to skip empties. The fetch
        # is bracketed as the "result_sync" stage: it is the documented
        # end-of-query device->host transfer, not an unattributed
        # mid-plan sync, and the telemetry should say so.
        frames = []
        for batch in exec_.execute(p):
            tok = _disp.enter_stage("result_sync")
            try:
                frames.append(batch.to_pandas(exec_.schema))
            finally:
                _disp.exit_stage(tok)
        return [f for f in frames if len(f)]

    frames = [f for fs in
              run_partitions(exec_.num_partitions, one, threads)
              for f in fs]
    if not frames:
        cols = {n: pd.Series([], dtype=object)
                for n in exec_.schema.names}
        return pd.DataFrame(cols)
    return pd.concat(frames, ignore_index=True)
